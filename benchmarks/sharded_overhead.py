#!/usr/bin/env python
"""Characterize the sharded engine's exchange overhead (VERDICT r1 weak #8).

Times the same streaming reduce on the single-device engine vs the sharded
all_to_all engine across shard counts and bucket_cap settings, on whatever
backend is available (the 8-virtual-device CPU mesh by default — absolute
numbers are CPU numbers, but the *ratios* expose the exchange/padding
overhead the bucket heuristic pays, which is the thing to re-measure when a
real multi-chip slice exists).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/sharded_overhead.py

Prints one JSON line per configuration.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from map_oxidize_tpu.api import MapOutput, SumReducer
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.ops.hashing import HashDictionary
from map_oxidize_tpu.runtime.engine import DeviceReduceEngine


def _rows(rng, n, key_space):
    keys = rng.integers(0, key_space, size=n, dtype=np.uint64)
    vals = rng.integers(1, 10, size=n, dtype=np.int32)
    hi = (keys >> np.uint64(32)).astype(np.uint32)
    lo = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return hi, lo, vals


def time_engine(make, batches, repeats=3):
    times = []
    for _ in range(repeats):
        eng = make()
        # warm-up feed+finalize OUTSIDE the timed region: each sharded
        # engine instance builds a fresh jit(shard_map) closure, so without
        # this every repeat would pay trace/compile inside the timer while
        # the single engine's module-level jits compile once process-wide
        hi, lo, vals = batches[0]
        eng.feed(MapOutput(hi=hi, lo=lo, values=vals,
                           dictionary=HashDictionary()))
        eng.finalize()
        t0 = time.perf_counter()
        for hi, lo, vals in batches:
            eng.feed(MapOutput(hi=hi, lo=lo, values=vals,
                               dictionary=HashDictionary()))
        eng.finalize()
        times.append(time.perf_counter() - t0)
    return min(times)


def main():
    from map_oxidize_tpu.parallel.engine import ShardedReduceEngine

    rng = np.random.default_rng(0)
    n_batches, batch_rows, key_space = 16, 1 << 16, 50_000
    batches = [_rows(rng, batch_rows, key_space) for _ in range(n_batches)]
    rows = n_batches * batch_rows

    cfg = JobConfig(batch_size=batch_rows, key_capacity=1 << 17,
                    initial_key_capacity=1 << 17, backend="cpu", metrics=False)
    base = time_engine(lambda: DeviceReduceEngine(cfg, SumReducer()), batches)
    print(json.dumps({"engine": "single", "shards": 1,
                      "rows_per_sec": round(rows / base, 1),
                      "best_s": round(base, 4)}))

    for S in (2, 4, 8):
        c = JobConfig(batch_size=batch_rows, key_capacity=(1 << 17) * S,
                      initial_key_capacity=(1 << 17) * S, backend="cpu",
                      num_shards=S, metrics=False)
        # expected per-bucket load is (local batch)/S = batch_rows/S^2;
        # auto is 2x that (+16).  tight probes BELOW auto, wide 2x above.
        per_bucket = batch_rows // (S * S)
        for cap_label, cap in (("auto(2x)", 0),
                               ("tight(1.1x)", int(1.1 * per_bucket) + 1),
                               ("wide(4x)", 4 * per_bucket + 16)):
            t = time_engine(
                lambda: ShardedReduceEngine(c, SumReducer(), bucket_cap=cap),
                batches)
            print(json.dumps({
                "engine": "sharded", "shards": S, "bucket_cap": cap_label,
                "rows_per_sec": round(rows / t, 1),
                "best_s": round(t, 4),
                "vs_single": round(base / t, 3),
            }))


if __name__ == "__main__":
    main()
