#!/usr/bin/env python
"""Characterize the sharded-collect exchange: bucket_cap cost and the
receive buffer's residency (the round-2 advisor's S x padded-block
retention, fixed in round 3 by compact-on-append).

Run on the virtual 8-device CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/sharded_collect_overhead.py
"""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from map_oxidize_tpu.api import MapOutput  # noqa: E402
from map_oxidize_tpu.config import JobConfig  # noqa: E402
from map_oxidize_tpu.ops.hashing import HashDictionary, split_u64  # noqa: E402
from map_oxidize_tpu.parallel.collect import ShardedCollectEngine  # noqa: E402


def run(S: int, cap_label: str, cap: int, n_rows: int, batch: int,
        n_terms: int, repeats: int = 3):
    rng = np.random.default_rng(7)
    terms = rng.integers(0, 2**62, size=n_terms, dtype=np.uint64)
    keys = terms[rng.integers(0, n_terms, size=n_rows)]
    docs = np.sort(rng.integers(0, 2**40, size=n_rows).astype(np.uint64))
    hi, lo = split_u64(keys)
    vals = np.empty((n_rows, 2), np.uint32)
    vals[:, 0] = (docs >> np.uint64(32)).astype(np.uint32)
    vals[:, 1] = (docs & np.uint64(0xFFFFFFFF)).astype(np.uint32)

    best = None
    resident = 0
    for _ in range(repeats):
        eng = ShardedCollectEngine(
            JobConfig(batch_size=batch, num_shards=S, backend="cpu"),
            bucket_cap=cap)
        t0 = time.perf_counter()
        for start in range(0, n_rows, batch):
            stop = min(start + batch, n_rows)
            eng.feed(MapOutput(hi=hi[start:stop], lo=lo[start:stop],
                               values=vals[start:stop],
                               dictionary=HashDictionary()))
        k, d = eng.finalize()
        dt = time.perf_counter() - t0
        assert k.shape[0] == n_rows
        resident = eng.S * eng.R
        best = dt if best is None else min(best, dt)
    return best, resident


def main():
    n_rows = 1 << 19
    batch = 1 << 15
    n_terms = 4096
    print(f"rows={n_rows}, batch={batch}, terms={n_terms} "
          f"(uniform hash -> flat buckets)")
    print(f"{'S':>2} {'bucket_cap':>12} {'secs':>7} {'rows/s':>9} "
          f"{'resident rows':>13} {'resident/fed':>12}")
    for S in (2, 4, 8):
        bps = batch // S
        for label, cap in (("safe (bps)", bps),
                           ("2x expected", max(1, 2 * batch // S // S)),
                           ("1.2x expected", max(1, batch * 6 // (5 * S * S)))):
            try:
                secs, resident = run(S, label, cap, n_rows, batch, n_terms)
                print(f"{S:>2} {label:>12} {secs:7.2f} {n_rows/secs:9.0f} "
                      f"{resident:>13} {resident/n_rows:12.2f}")
            except RuntimeError as e:
                print(f"{S:>2} {label:>12}  OVERFLOW ({e})")


if __name__ == "__main__":
    main()
