"""Golden-parity tests: full pipeline vs the pure-Python reference model
(SURVEY.md §4 test strategy — the reference itself ships no tests)."""

import numpy as np
import pytest

from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.io.splitter import iter_chunks, split_round_robin
from map_oxidize_tpu.runtime.driver import run_wordcount_job
from map_oxidize_tpu.workloads.reference_model import top_k_model, wordcount_model
from map_oxidize_tpu.workloads.wordcount import make_wordcount

CORPUS = b"""To be, or not to be, that is the question:
Whether 'tis nobler in the mind to suffer
The slings and arrows of outrageous fortune,
Or to take arms against a sea of troubles
And by opposing end them. To die-to sleep,
No more; and by a sleep to say we end
The heart-ache and the thousand natural shocks
That flesh is heir to: 'tis a consummation
Devoutly to be wish'd. To die, to sleep;
To sleep, perchance to dream-ay, there's the rub:
"""


@pytest.fixture
def corpus_file(tmp_path):
    # repeat so chunking actually kicks in
    p = tmp_path / "shakes.txt"
    p.write_bytes(CORPUS * 50)
    return str(p)


def _run(corpus_file, tmp_path, **overrides):
    cfg = JobConfig(
        input_path=corpus_file,
        output_path=str(tmp_path / "final_result.txt"),
        chunk_bytes=512,          # many small chunks
        batch_size=256,           # many small device batches
        key_capacity=4096,
        backend="cpu",
        use_native=False,
        **overrides,
    )
    mapper, reducer = make_wordcount(cfg.tokenizer, cfg.use_native)
    return cfg, run_wordcount_job(cfg, mapper, reducer)


def test_wordcount_matches_reference_model(corpus_file, tmp_path):
    cfg, result = _run(corpus_file, tmp_path)
    model = wordcount_model(iter_chunks(corpus_file, 512))
    assert result.counts == dict(model)
    assert result.top == top_k_model(model, 10)


def test_round_robin_compat_chunking_same_result(corpus_file, tmp_path):
    """Byte-range chunking and the reference's round-robin line chunking
    (main.rs:36-51) must produce identical global counts."""
    _, streamed = _run(corpus_file, tmp_path)
    _, rr = _run(corpus_file, tmp_path, num_chunks=8)
    assert streamed.counts == rr.counts
    chunks = split_round_robin(corpus_file, 8)
    assert wordcount_model(chunks) == streamed.counts


def test_final_result_file_deterministic_and_truncated(corpus_file, tmp_path):
    out = tmp_path / "final_result.txt"
    # pre-existing longer file would expose the reference's no-truncate bug
    # (main.rs:171-175): stale trailing bytes must NOT survive.
    out.write_bytes(b"x" * 1_000_000)
    _, result = _run(corpus_file, tmp_path)
    first = out.read_bytes()
    assert len(first) < 1_000_000
    _, result2 = _run(corpus_file, tmp_path)
    assert out.read_bytes() == first  # byte-identical across runs
    # file content round-trips to the counts dict
    parsed = {}
    for line in first.splitlines():
        w, c = line.rsplit(b" ", 1)
        parsed[w] = int(c)
    assert parsed == result.counts


def test_unicode_tokenizer_mode(tmp_path):
    p = tmp_path / "u.txt"
    p.write_bytes("Ärger straße Ärger ÉCLAIR\n".encode("utf-8"))
    cfg = JobConfig(input_path=str(p), output_path="", backend="cpu",
                    tokenizer="unicode", use_native=False,
                    batch_size=64, key_capacity=64)
    mapper, reducer = make_wordcount("unicode", use_native=False)
    result = run_wordcount_job(cfg, mapper, reducer)
    assert result.counts["ärger".encode()] == 2
    assert result.counts["éclair".encode()] == 1
    assert result.counts["straße".encode()] == 1


def test_conservation_metric(corpus_file, tmp_path):
    _, result = _run(corpus_file, tmp_path)
    assert result.metrics["records_in"] == sum(result.counts.values())
    assert result.metrics["distinct_keys"] == len(result.counts)


def test_cli_smoke(corpus_file, tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)
    from map_oxidize_tpu.cli import main

    rc = main(["wordcount", corpus_file, "--backend", "cpu", "--no-native",
               "--top-k", "5", "--output", str(tmp_path / "out.txt"), "-q"])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.startswith("Top 5 words:")
    assert len(out.strip().splitlines()) == 6
    model = wordcount_model([open(corpus_file, "rb").read()])
    for line, (w, c) in zip(out.strip().splitlines()[1:], top_k_model(model, 5)):
        assert line == f"{w.decode()}: {c}"
