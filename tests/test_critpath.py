"""Causal critical-path observatory (ISSUE-15 tentpole): the
happens-before DAG over the merged distributed trace, cross-process
blame/slack, the what-if replay, the degenerate single-chip form, and
the post-mortem merge semantics (torn shards, clock skew).

Two layers: synthetic shard documents with EXACT known timings pin the
model (blame shares, slack, what-if arithmetic, tiling identity,
refusals), and one real 2-process Gloo run with an injected straggler
pins the end-to-end wiring (round tags -> merge -> critpath section ->
ledger gate fields -> CLI render).
"""

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from map_oxidize_tpu.obs import critpath
from map_oxidize_tpu.obs import merge as obs_merge

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- synthetic shard builders ----------------------------------------------


def _X(name, ts_us, dur_us, tid=0, **args):
    return {"name": name, "ph": "X", "ts": ts_us, "dur": dur_us,
            "tid": tid, "args": args}


def _shard(proc, wall_start, events, n=2, attrib=None):
    return {"schema": obs_merge.SHARD_SCHEMA,
            "meta": {"process": proc, "n_processes": n,
                     "config_hash": "h", "workload": "wordcount",
                     "version": "x", "wall_start_unix_s": wall_start},
            "events": events,
            "metrics": ({"attrib": attrib} if attrib else {})}


def _lockstep_events(map_ms_per_round, rounds=3, coll_ms=10.0,
                     tail_ms=50.0, slowest_ms=None):
    """One process's lockstep event stream: map -> flag -> merge per
    round.  Every process's flag round exits at (global) last-arrival +
    coll_ms, so the caller passes ``slowest_ms`` = the per-round map
    wall of the SLOWEST process (the barrier schedule)."""
    slowest = slowest_ms if slowest_ms is not None else map_ms_per_round
    ev = []
    t = 0.0
    for r in range(rounds):
        ev.append(_X("dist/map_chunk", t, map_ms_per_round * 1e3))
        enter = t + map_ms_per_round * 1e3
        exit_t = ((r + 1) * slowest + r * (coll_ms + 10.0)
                  + coll_ms) * 1e3
        ev.append(_X("dist/lockstep_flag", enter, exit_t - enter,
                     round=r))
        ev.append(_X("dist/merge_local", exit_t, 10e3, round=r))
        t = exit_t + 10e3
    ev.append(_X("phase/finalize", t, tail_ms * 1e3))
    return ev


def _straggler_shards(slow_ms=300.0, fast_ms=100.0, rounds=3):
    return [
        _shard(0, 1000.0, _lockstep_events(fast_ms, rounds=rounds,
                                           slowest_ms=slow_ms)),
        _shard(1, 1000.0, _lockstep_events(slow_ms, rounds=rounds,
                                           slowest_ms=slow_ms,
                                           tail_ms=50.0)),
    ]


# --- the model -------------------------------------------------------------


def test_straggler_owns_blame_and_fast_proc_has_slack():
    doc = critpath.compute_from_shards(_straggler_shards())
    # proc 1 maps 300ms/round vs proc 0's 100ms: every round binds on
    # proc 1, so it owns (essentially all of) the on-path work
    blame = doc["blame"]
    assert blame["1"]["share_pct"] > 90.0
    assert blame["0"]["share_pct"] < 10.0
    assert abs(sum(r["share_pct"] for r in blame.values())
               - 100.0) < 0.1
    # the fast process could absorb its barrier waits for free: 200ms
    # of wait at each of the 3 rounds
    assert doc["slack"]["0"]["slack_ms"] == pytest.approx(600.0,
                                                          rel=0.05)
    assert doc["slack"]["1"]["slack_ms"] == 0.0
    # the path tiles the traced wall (the acceptance identity: >= 90%)
    assert doc["path_over_wall_pct"] >= 99.0
    # the replay model reproduces the measured schedule
    assert doc["model_error_pct"] < 1.0
    assert "proc 1" in doc["bound_by"]
    # DAG bookkeeping: program edges exist, barrier edges cover
    # rounds x procs in+out
    assert doc["dag"]["edges"]["barrier"] == 3 * 2 * 2
    assert doc["dag"]["nodes"] > 0


def test_whatif_matches_measured_delta_when_straggler_removed():
    """The acceptance bound: the 'slow proc at median speed' estimate
    must land within 20% of the wall delta actually measured when the
    slowdown is removed.  Synthetic timings make both sides exact."""
    slow = critpath.compute_from_shards(_straggler_shards())
    clean = critpath.compute_from_shards(
        _straggler_shards(slow_ms=100.0))
    measured_delta = slow["wall_ms"] - clean["wall_ms"]
    est = next(w for w in slow["what_if"]
               if w["name"] == critpath.WHATIF_PROC_MEDIAN.format(p=1))
    assert measured_delta > 0
    assert abs(est["est_delta_ms"] - measured_delta) \
        <= 0.2 * measured_delta
    # collectives-free removes exactly the per-round collective latency
    free = next(w for w in slow["what_if"]
                if w["name"] == critpath.WHATIF_FREE_COLLECTIVES)
    assert free["est_delta_ms"] == pytest.approx(3 * 10.0, rel=0.05)


def test_overlap_whatif_hides_exchange_behind_map():
    # make the exchange long enough to matter: merge_local 80ms vs
    # map 100ms -> full overlap hides min(80, 100) = 80ms per round
    shards = []
    for p in (0, 1):
        ev = []
        t = 0.0
        for r in range(2):
            ev.append(_X("dist/map_chunk", t, 100e3))
            ev.append(_X("dist/lockstep_flag", t + 100e3, 5e3, round=r))
            ev.append(_X("dist/merge_local", t + 105e3, 80e3, round=r))
            t += 185e3
        shards.append(_shard(p, 1000.0, ev))
    doc = critpath.compute_from_shards(shards)
    ov = next(w for w in doc["what_if"]
              if w["name"] == critpath.WHATIF_OVERLAP)
    # exchange rides the interval AFTER its round's flag: round 0's
    # merge_local lands in round 1's interval, round 1's in the tail —
    # one overlappable round -> ~80ms
    assert ov["est_delta_ms"] == pytest.approx(80.0, rel=0.1)


def test_path_segments_classified_onto_buckets():
    doc = critpath.compute_from_shards(_straggler_shards())
    kinds = {s["kind"] for s in doc["segments"]}
    assert "work" in kinds and "collective" in kinds
    work = [s for s in doc["segments"] if s["kind"] == "work"]
    # the straggler's intervals classify as host map production
    assert any(s["buckets"].get("host_produce", 0) > 0 for s in work)
    on_path_coll = doc["collective_wait"]["on_path_ms"]
    assert on_path_coll == pytest.approx(3 * 10.0, rel=0.2)


# --- refusals + post-mortem tolerance --------------------------------------


def test_clock_skew_refuses_with_named_error():
    shards = _straggler_shards()
    shards[1]["meta"]["wall_start_unix_s"] = 1000.0 + 30.0
    with pytest.raises(critpath.ClockSkewError) as ei:
        critpath.compute_from_shards(shards)
    assert "wall-clock skew" in str(ei.value)
    with pytest.raises(critpath.ClockSkewError):
        obs_merge.merge_shards(shards)
    # the forensics override still merges
    events, _skew = obs_merge.merge_shards(shards,
                                           allow_clock_skew=True)
    assert events


def test_mixed_identity_and_duplicate_slots_refuse():
    """Stale .proc<i> shards from an earlier run (different config
    hash) or duplicated slots must refuse — blending them would be a
    silently cross-job causal report."""
    shards = _straggler_shards()
    shards[1]["meta"]["config_hash"] = "other"
    with pytest.raises(ValueError, match="not shards of one job"):
        critpath.compute_from_shards(shards)
    dup = _straggler_shards()
    dup[1]["meta"]["process"] = 0
    with pytest.raises(ValueError, match="duplicate process slots"):
        critpath.compute_from_shards(dup)


def test_unanchorable_shard_refuses():
    shards = _straggler_shards()
    del shards[0]["meta"]["wall_start_unix_s"]
    with pytest.raises(ValueError, match="wall_start_unix_s"):
        critpath.compute_from_shards(shards)


def test_torn_and_missing_shards_yield_postmortem_with_coverage(
        tmp_path, capsys):
    """A killed process's torn shard must yield a post-mortem merge +
    critpath with a NAMED coverage gap, not an abort (satellite +
    regression test)."""
    base = str(tmp_path / "t.json")
    attrib = {"wall_ms": 1000.0, "unattributed_pct": 10.0,
              "buckets": {"host_produce": {"ms": 700.0},
                          "device_compute": {"ms": 200.0}}}
    good = _shard(0, 1000.0, _lockstep_events(100.0), attrib=attrib)
    with open(base + ".proc0", "w") as f:
        json.dump(good, f)
    with open(base + ".proc1", "w") as f:
        f.write('{"schema": "moxt-obs-shard-v1", "meta": {"proc')  # torn
    skew = obs_merge.merge_to_files(obs_merge.find_shards(base), base)
    cov = skew["coverage"]
    assert cov["missing_processes"] == [1]
    assert cov["torn_shards"] == ["t.json.proc1"]
    # one surviving shard: the path degenerates to its attrib timeline,
    # and the coverage gap rides the document
    cp = skew["critpath"]
    assert cp.get("degenerate") == "attrib-timeline"
    assert cp["coverage"]["missing_processes"] == [1]
    # the CLI path: rc 0, gap named on stdout
    from map_oxidize_tpu.cli import main

    rc = main(["obs", "merge", base])
    assert rc == 0
    out = capsys.readouterr().out
    assert "coverage gap" in out
    # ... and zero readable shards still aborts with a named error
    os.remove(base + ".proc0")
    with open(base + ".proc0", "w") as f:
        f.write("garbage")
    with pytest.raises(ValueError, match="no readable obs shards"):
        obs_merge.merge_to_files(obs_merge.find_shards(base), base)


def test_no_round_tags_is_named_not_fatal(tmp_path):
    """Pre-critpath traces (no round= args) merge fine; the critpath
    section carries a named error instead of data."""
    base = str(tmp_path / "t.json")
    for p in (0, 1):
        ev = [_X("dist/map_chunk", 0.0, 50e3),
              _X("dist/lockstep_flag", 50e3, 5e3)]  # no round tag
        with open(base + f".proc{p}", "w") as f:
            json.dump(_shard(p, 1000.0, ev), f)
    skew = obs_merge.merge_to_files(obs_merge.find_shards(base), base)
    assert "no common lockstep rounds" in skew["critpath"]["error"]


# --- degenerate single-process form ----------------------------------------


def _attrib_doc():
    return {"wall_ms": 1000.0, "attributed_ms": 950.0,
            "unattributed_pct": 5.0,
            "buckets": {"host_produce": {"ms": 600.0},
                        "device_compute": {"ms": 250.0},
                        "feed_wait": {"ms": 100.0}}}


def test_degenerate_reconciles_with_attrib():
    doc = critpath.degenerate_from_attrib(_attrib_doc())
    assert doc["degenerate"] == "attrib-timeline"
    assert doc["n_processes"] == 1
    # segments ARE the attrib timeline: their sum reconciles with the
    # attributed wall exactly
    assert sum(s["ms"] for s in doc["segments"]) \
        == pytest.approx(950.0)
    assert doc["blame"]["0"]["share_pct"] == 100.0
    assert doc["slack"] == {}
    assert "host_produce" in doc["bound_by"]
    ov = next(w for w in doc["what_if"]
              if w["name"] == critpath.WHATIF_OVERLAP)
    assert ov["est_delta_ms"] == pytest.approx(100.0)


def test_headline_gauges_and_blame_share_scoping():
    multi = critpath.compute_from_shards(_straggler_shards())
    g = critpath.headline(multi)
    assert g["critpath/bound_frac"] > 0.9
    assert g["critpath/top_blame_share"] > 0.9
    # the SLO-watched causal share: fixing the straggler saves most of
    # the wall here (3 rounds of 300ms vs 100ms)
    assert g["critpath/straggler_save_frac"] > 0.3
    assert g["critpath/top_process_slack_ms"] > 0
    assert isinstance(g["critpath/bound_by"], str)
    # the degenerate form must NOT publish the process-blame share (it
    # would read 1.0 and trip the SLO rule on every single-chip job);
    # its bound_frac is the dominant COST's share instead
    dg = critpath.headline(critpath.degenerate_from_attrib(_attrib_doc()))
    assert "critpath/top_blame_share" not in dg
    assert dg["critpath/bound_frac"] == pytest.approx(0.6)


def test_publish_lands_on_registry():
    from map_oxidize_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    critpath.publish(reg, critpath.compute_from_shards(
        _straggler_shards()))
    assert reg.gauges["critpath/top_blame_share"] > 0.9
    assert "critpath/bound_by" in reg.gauges
    # string gauges stay out of the numeric summary-derived series but
    # ride the summary for the ledger
    assert "critpath/bound_by" in reg.summary()


# --- gates + SLO -----------------------------------------------------------


def _entry(metrics):
    return {"workload": "wordcount", "config_hash": "h", "version": "x",
            "corpus_bytes": 10, "ts_unix_s": 1.0, "phases_s": {},
            "metrics": metrics}


def test_ledger_gate_flags_blame_concentration_and_coverage_loss():
    from map_oxidize_tpu.obs import ledger

    a = _entry({"critpath/top_blame_share": 0.55,
                "critpath/path_over_wall_pct": 99.0})
    b = _entry({"critpath/top_blame_share": 0.85,
                "critpath/path_over_wall_pct": 99.0})
    d = ledger.diff_entries(a, b, force=True)
    assert any("straggler concentration" in r for r in d["regressions"])
    # small drift stays silent
    c = _entry({"critpath/top_blame_share": 0.60,
                "critpath/path_over_wall_pct": 99.0})
    assert not ledger.diff_entries(a, c, force=True)["regressions"]
    # causal coverage loss flags
    e = _entry({"critpath/top_blame_share": 0.55,
                "critpath/path_over_wall_pct": 80.0})
    d = ledger.diff_entries(a, e, force=True)
    assert any("causal coverage" in r for r in d["regressions"])
    # a MISSING baseline (pre-critpath entry) is unknown, not 0.0: a
    # healthy 1/P share against it must NOT read as concentration
    old = _entry({})
    healthy = _entry({"critpath/top_blame_share": 0.55,
                      "critpath/path_over_wall_pct": 99.0})
    assert not ledger.diff_entries(old, healthy,
                                   force=True)["regressions"]


def test_slo_rule_fires_on_process_blame():
    from map_oxidize_tpu.obs import Obs, Tracer
    from map_oxidize_tpu.obs.metrics import MetricsRegistry
    from map_oxidize_tpu.obs.slo import SloEvaluator, load_rules
    from map_oxidize_tpu.obs.timeseries import TimeSeriesRecorder

    obs = Obs(registry=MetricsRegistry(), tracer=Tracer(enabled=False))
    obs.series = TimeSeriesRecorder(obs.registry, interval_s=1.0)
    ev = SloEvaluator(obs, load_rules(None), interval_s=1.0)
    # a healthy 2-proc run (near-tied arrivals: fixing any one process
    # saves ~nothing) stays silent even when raw path ownership is high
    obs.registry.set("critpath/top_blame_share", 0.99)
    obs.registry.set("critpath/straggler_save_frac", 0.02)
    obs.series.sample_once()
    assert ev.evaluate_once() == []
    # a genuine straggler — fixing one process saves >30% of wall —
    # fires the blame rule
    obs.registry.set("critpath/straggler_save_frac", 0.45)
    obs.series.sample_once()
    events = ev.evaluate_once()
    assert [e["rule"] for e in events
            if e["event"] == "fired"] == ["critpath-process-blame"]


# --- CLI -------------------------------------------------------------------


def test_cli_critpath_from_shards_merged_trace_and_metrics(tmp_path,
                                                           capsys):
    from map_oxidize_tpu.cli import main

    base = str(tmp_path / "t.json")
    for p, s in enumerate(_straggler_shards()):
        with open(base + f".proc{p}", "w") as f:
            json.dump(s, f)
    # from the trace base (shards found next to it)
    assert main(["obs", "critpath", base, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["blame"]["1"]["share_pct"] > 90
    # from the merged trace artifact
    obs_merge.merge_to_files(obs_merge.find_shards(base),
                             str(tmp_path / "merged.json"))
    assert main(["obs", "critpath", str(tmp_path / "merged.json"),
                 "--json"]) == 0
    doc2 = json.loads(capsys.readouterr().out)
    assert doc2["blame"]["1"]["share_pct"] == pytest.approx(
        doc["blame"]["1"]["share_pct"], abs=1.0)
    # from a metrics document (degenerate attrib path) + rendered form
    mpath = tmp_path / "m.json"
    mpath.write_text(json.dumps({"meta": {"workload": "wc"},
                                 "attrib": _attrib_doc()}))
    assert main(["obs", "critpath", str(mpath)]) == 0
    out = capsys.readouterr().out
    assert "bound by:" in out and "attrib timeline" in out
    # a clock-skewed base refuses with rc 3
    skewed = str(tmp_path / "s.json")
    shards = _straggler_shards()
    shards[1]["meta"]["wall_start_unix_s"] = 1030.0
    for p, s in enumerate(shards):
        with open(skewed + f".proc{p}", "w") as f:
            json.dump(s, f)
    assert main(["obs", "critpath", skewed]) == 3
    capsys.readouterr()


# --- queue-handoff spans ---------------------------------------------------


def test_prefetcher_records_handoff_spans_with_seq():
    from map_oxidize_tpu.obs import Obs, Tracer
    from map_oxidize_tpu.obs.metrics import MetricsRegistry
    from map_oxidize_tpu.runtime.pipeline import ChunkPrefetcher

    obs = Obs(registry=MetricsRegistry(), tracer=Tracer(enabled=True))
    items = list(ChunkPrefetcher(iter(range(4)), depth=2,
                                 name="pipeline", obs=obs))
    assert items == [0, 1, 2, 3]
    with obs.tracer._lock:
        events = list(obs.tracer._events)
    produced = sorted(e["args"]["seq"] for e in events
                      if e["name"] == "pipeline/produce"
                      and not e["args"].get("exhausted"))
    waited = sorted(e["args"]["seq"] for e in events
                    if e["name"] == "pipeline/feed_wait")
    assert produced == [0, 1, 2, 3]
    # no error-tagged spans on the healthy path (exhaustion is a flag,
    # not an exception crossing the span)
    assert not any("error" in e["args"] for e in events)
    # the consumer waits once per item (+ the _DONE sentinel)
    assert set(produced) <= set(waited)


# --- the real thing: 2-proc Gloo with an injected straggler ----------------


_CHILD = r"""
import json, logging, sys, time
pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
corpus = sys.argv[4]; art = sys.argv[5]; slow = float(sys.argv[6])
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.utils.logging import configure
from map_oxidize_tpu.parallel.distributed import (
    init_distributed, run_distributed_job)
configure(logging.INFO)
slept = [0.0]
if pid == 1 and slow > 0:
    import map_oxidize_tpu.workloads.wordcount as wc
    _orig = wc.make_wordcount
    def make_slow(*a, **k):
        m, r = _orig(*a, **k)
        om = m.map_chunk
        def slow_map(b):
            time.sleep(slow)
            slept[0] += slow
            return om(b)
        m.map_chunk = slow_map
        return m, r
    wc.make_wordcount = make_slow
init_distributed(f"127.0.0.1:{port}", num_processes=nproc, process_id=pid)
cfg = JobConfig(input_path=corpus, output_path="", chunk_bytes=4096,
                batch_size=1 << 12, key_capacity=1 << 12, top_k=5,
                metrics=False, obs_sample_s=0.2,
                dist_coordinator=f"127.0.0.1:{port}",
                dist_num_processes=nproc, dist_process_id=pid,
                trace_out=f"{art}/t.json", metrics_out=f"{art}/m.json",
                ledger_dir=f"{art}/ledger")
r = run_distributed_job(cfg, "wordcount")
print("RESULT", json.dumps({"records": r.records, "slept_s": slept[0]}))
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env():
    env = dict(os.environ)
    for k in ("PALLAS_AXON_POOL_IPS", "PJRT_LIBRARY_PATH",
              "TPU_LIBRARY_PATH", "PJRT_DEVICE", "TPU_ACCELERATOR_TYPE",
              "TPU_TOPOLOGY", "TPU_WORKER_HOSTNAMES"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.fixture(scope="module")
def straggler_run(tmp_path_factory):
    """One 2-proc Gloo wordcount with process 1 sleeping per chunk;
    returns (artifact dir, per-process stdout logs)."""
    tmp = tmp_path_factory.mktemp("critpath_dist")
    corpus = tmp / "c.txt"
    rng = np.random.default_rng(11)
    words = [b"Alpha", b"beta,", b"Gamma.", b"delta", b"eps;", b"zeta"]
    with open(corpus, "wb") as f:
        for _ in range(3000):
            f.write(b" ".join(words[int(i)]
                              for i in rng.integers(0, 6, 6)) + b"\n")
    env = _env()
    logs = None
    for attempt in range(2):  # free-port probe is inherently racy
        port = _free_port()
        procs = [subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(i), "2", str(port),
             str(corpus), str(tmp), "0.3"],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True) for i in range(2)]
        logs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=420)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                out = "(timeout)"
            logs.append(out)
        if all(p.returncode == 0 for p in procs):
            break
        if attempt == 1:
            for i, p in enumerate(procs):
                assert p.returncode == 0, f"process {i} failed:\n{logs[i]}"
    return tmp, logs


def test_real_straggler_blame_slack_and_whatif(straggler_run):
    tmp, logs = straggler_run
    skew = json.loads((tmp / "t.json.skew.json").read_text())
    cp = skew["critpath"]
    results = [json.loads(l.split("RESULT ", 1)[1].splitlines()[0])
               for l in logs]
    slept_ms = results[1]["slept_s"] * 1e3
    assert slept_ms > 0
    # the slowed process owns at least its injected share of the blame
    injected_share = 100.0 * slept_ms / cp["wall_ms"]
    assert cp["blame"]["1"]["share_pct"] >= injected_share * 0.9
    assert cp["blame"]["1"]["share_pct"] > cp["blame"]["0"]["share_pct"]
    assert abs(sum(r["share_pct"] for r in cp["blame"].values())
               - 100.0) < 0.5
    # the fast process has positive slack (it waited at the barriers)
    assert cp["slack"]["0"]["slack_ms"] > 0
    # path tiles >= 90% of the traced wall (acceptance identity)
    assert cp["path_over_wall_pct"] >= 90.0
    # the straggler-removed estimate is in the injected ballpark: the
    # model can't beat scheduling jitter on a busy CI box, so the bound
    # here is coarse — the EXACT 20% acceptance bound is pinned by the
    # synthetic twin (test_whatif_matches_measured_delta_...)
    est = next(w for w in cp["what_if"]
               if w["name"] == critpath.WHATIF_PROC_MEDIAN.format(p=1))
    assert est["est_delta_ms"] >= 0.5 * slept_ms
    assert est["est_delta_ms"] <= 1.6 * slept_ms


def test_real_run_ledger_and_metrics_doc_carry_critpath(straggler_run):
    tmp, _logs = straggler_run
    from map_oxidize_tpu.obs import ledger

    entries = ledger.read(str(tmp / "ledger"))
    assert len(entries) == 1
    e = entries[0]
    for key in ("critpath/bound_frac", "critpath/top_blame_share",
                "critpath/top_process_slack_ms",
                "critpath/collective_wait_share_pct",
                "critpath/path_over_wall_pct", "critpath/bound_by"):
        assert key in e["metrics"], key
    assert e["metrics"]["critpath/top_blame_share"] > 0.5
    # the straggler is causally on the path: the SLO rule fired at the
    # final post-merge evaluator tick and landed in the gate counter
    assert e["metrics"]["critpath/straggler_save_frac"] > 0.3
    assert e["metrics"].get("alerts/fired", 0) >= 1
    assert e["critpath"]["blame"]["1"]["share_pct"] > 50
    # process 0's metrics document gained the full section post-merge
    md = json.loads((tmp / "m.json.proc0").read_text())
    assert md["critpath"]["blame"]["1"]["share_pct"] > 50
    assert md["gauges"]["critpath/top_blame_share"] > 0.5


def test_real_run_cli_renders_from_trace_base(straggler_run, capsys):
    tmp, _logs = straggler_run
    from map_oxidize_tpu.cli import main

    assert main(["obs", "critpath", str(tmp / "t.json")]) == 0
    out = capsys.readouterr().out
    assert "bound by: proc 1" in out
    assert "slack" in out and "what-if" in out


# --- single-chip degenerate (in-process real job) --------------------------


def test_single_chip_degenerates_to_attrib_timeline(tmp_path):
    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.runtime.driver import run_wordcount_job
    from map_oxidize_tpu.workloads.wordcount import make_wordcount

    corpus = tmp_path / "c.txt"
    corpus.write_bytes(b"alpha beta gamma delta\n" * 400)
    mapper, reducer = make_wordcount("ascii", use_native=False)
    cfg = JobConfig(input_path=str(corpus), output_path="",
                    metrics=False, num_chunks=4, batch_size=1 << 12,
                    num_map_workers=1, mapper="python", use_native=False,
                    metrics_out=str(tmp_path / "m.json"))
    run_wordcount_job(cfg, mapper, reducer)
    doc = json.loads((tmp_path / "m.json").read_text())
    cp = doc["critpath"]
    assert cp["degenerate"] == "attrib-timeline"
    assert cp["n_processes"] == 1
    # the path IS the attrib timeline: segment sum == attributed wall
    attributed = doc["attrib"]["attributed_ms"]
    assert sum(s["ms"] for s in cp["segments"]) == pytest.approx(
        attributed, rel=0.01)
    assert cp["blame"]["0"]["share_pct"] == 100.0
    # headline gauges landed, WITHOUT the process-blame share
    assert "critpath/bound_frac" in doc["gauges"]
    assert "critpath/top_blame_share" not in doc["gauges"]
