"""Core reduce kernel tests against a pure-Python dict model — the same
semantics as the reference's merge loop (/root/reference/src/main.rs:131-134:
``*entry += count``), evaluated on hashed keys."""

import collections

import jax.numpy as jnp
import numpy as np

from map_oxidize_tpu.ops.hashing import SENTINEL, SENTINEL64, join_u64, split_u64
from map_oxidize_tpu.ops.segment_reduce import (
    make_accumulator,
    merge_into_accumulator,
    reduce_pairs,
)
from map_oxidize_tpu.ops.topk import top_k_pairs


def _model_reduce(keys64, vals, combine="sum"):
    """Reference semantics on the host: dict fold."""
    out = {}
    for k, v in zip(keys64.tolist(), np.asarray(vals).tolist()):
        if k == SENTINEL64:
            continue
        if k not in out:
            out[k] = v
        elif combine == "sum":
            out[k] = out[k] + v
        elif combine == "min":
            out[k] = min(out[k], v)
        elif combine == "max":
            out[k] = max(out[k], v)
    return out


def _device_result_to_dict(hi, lo, vals, n_unique):
    n = int(n_unique)
    k64 = join_u64(np.asarray(hi[:n]), np.asarray(lo[:n]))
    return dict(zip(k64.tolist(), np.asarray(vals[:n]).tolist()))


def _random_pairs(rng, n, n_keys, with_padding=False):
    keys64 = rng.integers(0, 2**63, size=n_keys, dtype=np.uint64)
    picks = keys64[rng.integers(0, n_keys, size=n)]
    vals = rng.integers(1, 100, size=n).astype(np.int32)
    if with_padding:
        pad = rng.random(n) < 0.2
        picks = np.where(pad, np.uint64(SENTINEL64), picks)
        vals = np.where(pad, 0, vals).astype(np.int32)
    hi, lo = split_u64(picks)
    return picks, hi, lo, vals


def test_reduce_pairs_sum_matches_dict_model(rng):
    keys64, hi, lo, vals = _random_pairs(rng, 5000, 300)
    o_hi, o_lo, o_vals, n_unique = reduce_pairs(jnp.array(hi), jnp.array(lo), jnp.array(vals))
    got = _device_result_to_dict(o_hi, o_lo, o_vals, n_unique)
    assert got == _model_reduce(keys64, vals)


def test_reduce_pairs_min_max(rng):
    for combine in ("min", "max"):
        keys64, hi, lo, vals = _random_pairs(rng, 2000, 100)
        o = reduce_pairs(jnp.array(hi), jnp.array(lo), jnp.array(vals), combine)
        got = _device_result_to_dict(*o)
        assert got == _model_reduce(keys64, vals, combine)


def test_reduce_pairs_with_sentinel_padding(rng):
    keys64, hi, lo, vals = _random_pairs(rng, 4096, 200, with_padding=True)
    o_hi, o_lo, o_vals, n_unique = reduce_pairs(jnp.array(hi), jnp.array(lo), jnp.array(vals))
    got = _device_result_to_dict(o_hi, o_lo, o_vals, n_unique)
    assert got == _model_reduce(keys64, vals)
    # rows past n_unique are sentinel/identity
    assert np.all(np.asarray(o_hi[int(n_unique):]) == SENTINEL)
    assert np.all(np.asarray(o_vals[int(n_unique):]) == 0)


def test_reduce_pairs_all_padding():
    n = 64
    hi = jnp.full((n,), SENTINEL, jnp.uint32)
    lo = jnp.full((n,), SENTINEL, jnp.uint32)
    vals = jnp.zeros((n,), jnp.int32)
    _, _, _, n_unique = reduce_pairs(hi, lo, vals)
    assert int(n_unique) == 0


def test_reduce_pairs_vector_values(rng):
    """k-means-style [n, d] values reduce per-dimension."""
    keys64 = rng.integers(0, 2**62, size=10, dtype=np.uint64)
    picks = keys64[rng.integers(0, 10, size=500)]
    vals = rng.normal(size=(500, 3)).astype(np.float32)
    hi, lo = split_u64(picks)
    o_hi, o_lo, o_vals, n_unique = reduce_pairs(jnp.array(hi), jnp.array(lo), jnp.array(vals))
    n = int(n_unique)
    got = {k: v for k, v in zip(join_u64(np.asarray(o_hi[:n]), np.asarray(o_lo[:n])).tolist(),
                                np.asarray(o_vals[:n]))}
    want = collections.defaultdict(lambda: np.zeros(3, np.float64))
    for k, v in zip(picks.tolist(), vals):
        want[k] += v
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-5)


def test_streaming_accumulator_equals_one_shot(rng):
    """Fold 10 batches through merge_into_accumulator; must equal a single
    global reduce (associativity of the monoid)."""
    cap, bs = 2048, 512
    acc = make_accumulator(cap)
    ovf = jnp.zeros((), jnp.int32)
    all_keys, all_vals = [], []
    for _ in range(10):
        keys64, hi, lo, vals = _random_pairs(rng, bs, 150, with_padding=True)
        all_keys.append(keys64)
        all_vals.append(vals)
        acc_hi, acc_lo, acc_vals, n_unique, ovf = merge_into_accumulator(
            *acc, ovf, jnp.array(hi), jnp.array(lo), jnp.array(vals)
        )
        acc = (acc_hi, acc_lo, acc_vals)
    assert int(n_unique) <= cap
    assert int(ovf) == 0
    got = _device_result_to_dict(acc_hi, acc_lo, acc_vals, n_unique)
    want = _model_reduce(np.concatenate(all_keys), np.concatenate(all_vals))
    assert got == want


def test_merge_overflow_counter(rng):
    """Truncation past capacity must count dropped keys; exact fill must not."""
    # exact fill: 64 distinct keys into capacity 64 -> no drop
    acc = make_accumulator(64)
    ovf = jnp.zeros((), jnp.int32)
    keys = np.arange(64, dtype=np.uint64)
    hi, lo = split_u64(keys)
    vals = np.ones(64, np.int32)
    *_, n, ovf = merge_into_accumulator(
        *acc, ovf, jnp.array(hi), jnp.array(lo), jnp.array(vals)
    )
    assert int(n) == 64 and int(ovf) == 0
    # 100 distinct into capacity 64 -> 36 dropped, and the counter is sticky
    acc = make_accumulator(64)
    ovf = jnp.zeros((), jnp.int32)
    keys = np.arange(100, dtype=np.uint64)
    hi, lo = split_u64(keys)
    vals = np.ones(100, np.int32)
    acc_hi, acc_lo, acc_vals, n, ovf = merge_into_accumulator(
        *acc, ovf, jnp.array(hi), jnp.array(lo), jnp.array(vals)
    )
    assert int(ovf) == 36
    # a subsequent clean merge must not reset it
    k2 = np.arange(8, dtype=np.uint64)
    h2, l2 = split_u64(k2)
    *_, n, ovf = merge_into_accumulator(
        acc_hi, acc_lo, acc_vals, ovf,
        jnp.array(h2), jnp.array(l2), jnp.ones(8, jnp.int32)
    )
    assert int(ovf) >= 36


def test_identity_extrema_for_all_int_widths():
    """min/max identities must be the true dtype extremum for EVERY integer
    width, not just the 32/64-bit ones (an inf fill would unsafe-cast to 0
    and a padding row could then outrank real all-negative maxima)."""
    from map_oxidize_tpu.ops.segment_reduce import _identity

    for dt in (np.int8, np.int16, np.int32, np.int64,
               np.uint8, np.uint16, np.uint32):
        info = np.iinfo(dt)
        assert _identity("max", dt) == info.min, dt
        assert _identity("min", dt) == info.max, dt
    assert _identity("max", np.float32) == -np.inf
    assert _identity("min", np.float32) == np.inf


def test_reduce_pairs_max_int8_all_negative(rng):
    """End-to-end guard for the int8 identity: all-negative maxima must
    survive padding rows."""
    keys64 = rng.integers(0, 2**62, size=20, dtype=np.uint64)
    picks = keys64[rng.integers(0, 20, size=200)]
    vals = rng.integers(-120, -1, size=200).astype(np.int8)
    hi, lo = split_u64(picks)
    o = reduce_pairs(jnp.array(hi), jnp.array(lo), jnp.array(vals), "max")
    got = _device_result_to_dict(*o)
    assert got == _model_reduce(picks, vals, "max")
    assert all(v < 0 for v in got.values())


def test_top_k_pairs(rng):
    keys64, hi, lo, vals = _random_pairs(rng, 3000, 50)
    o_hi, o_lo, o_vals, n_unique = reduce_pairs(jnp.array(hi), jnp.array(lo), jnp.array(vals))
    k = 7
    t_hi, t_lo, t_vals = top_k_pairs(o_hi, o_lo, o_vals, k)
    model = _model_reduce(keys64, vals)
    want_counts = sorted(model.values(), reverse=True)[:k]
    assert np.asarray(t_vals).tolist() == want_counts
    got = dict(zip(join_u64(np.asarray(t_hi), np.asarray(t_lo)).tolist(),
                   np.asarray(t_vals).tolist()))
    for k64, c in got.items():
        assert model[k64] == c
