"""Host map executor: the worker-pool phase engine.

The build machine has one core, so the pool short-circuits to inline
mapping at runtime (`executor.py` guard); these tests monkeypatch
``os.cpu_count`` to force the real ThreadPoolExecutor path — claim from a
lazy iterator, bounded in-flight backpressure, completion-order yields,
per-chunk retries (the reference aborts on first error, main.rs:88)."""

import os
import threading
import time

import numpy as np
import pytest

from map_oxidize_tpu.api import Mapper, MapOutput
from map_oxidize_tpu.runtime.executor import MapTaskError, run_map_phase


class CountingMapper(Mapper):
    def __init__(self, fail_plan=None, delay_chunk=None):
        self.calls = []
        self._lock = threading.Lock()
        self.fail_plan = dict(fail_plan or {})  # chunk payload -> fail count
        self.delay_chunk = delay_chunk

    def map_chunk(self, chunk) -> MapOutput:
        key = bytes(chunk)
        with self._lock:
            self.calls.append(key)
            remaining = self.fail_plan.get(key, 0)
            if remaining:
                self.fail_plan[key] = remaining - 1
        if remaining:
            raise RuntimeError(f"planned failure for {key!r}")
        if self.delay_chunk == key:
            time.sleep(0.2)
        return MapOutput(hi=np.zeros(1, np.uint32),
                         lo=np.frombuffer(key[:4].ljust(4, b"\0"),
                                          np.uint32).copy(),
                         values=np.ones(1, np.int32), records_in=1)


@pytest.fixture
def force_pool(monkeypatch):
    """Pretend the host has cores so the pool path actually runs."""
    monkeypatch.setattr(os, "cpu_count", lambda: 8)


def _chunks(n):
    return [b"c%03d" % i for i in range(n)]


def test_pool_maps_every_chunk_exactly_once(force_pool):
    mapper = CountingMapper()
    got = dict(run_map_phase(_chunks(20), mapper, num_workers=4))
    assert sorted(got) == list(range(20))
    assert sorted(mapper.calls) == sorted(_chunks(20))


def test_pool_yields_in_completion_order_with_indices(force_pool):
    # chunk 0 sleeps; later chunks must be allowed to finish first
    mapper = CountingMapper(delay_chunk=b"c000")
    order = [idx for idx, _ in
             run_map_phase(_chunks(10), mapper, num_workers=4)]
    assert sorted(order) == list(range(10))
    assert order[0] != 0  # the slow chunk did not serialize the pool


def test_pool_retries_then_succeeds(force_pool):
    mapper = CountingMapper(fail_plan={b"c003": 2})
    got = dict(run_map_phase(_chunks(8), mapper, num_workers=3,
                             max_retries=2))
    assert sorted(got) == list(range(8))
    assert mapper.calls.count(b"c003") == 3  # 2 failures + 1 success


def test_pool_raises_after_retry_budget(force_pool):
    mapper = CountingMapper(fail_plan={b"c002": 99})
    with pytest.raises(MapTaskError, match="chunk 2"):
        dict(run_map_phase(_chunks(6), mapper, num_workers=2, max_retries=1))
    assert mapper.calls.count(b"c002") == 2  # budget respected


def test_pool_backpressures_the_chunk_iterator(force_pool):
    """At most 2*num_workers chunks may be claimed before the consumer
    drains results — the reader must never race ahead unboundedly (the
    reference clones ALL chunks into every worker, main.rs:62)."""
    claimed = []

    def lazy_chunks():
        for i in range(50):
            claimed.append(i)
            yield b"c%03d" % i

    mapper = CountingMapper()
    gen = run_map_phase(lazy_chunks(), mapper, num_workers=2)
    next(gen)  # first result out
    # claimed so far: at most in-flight cap + the one consumed
    assert len(claimed) <= 2 * 2 + 1
    rest = dict(gen)
    assert len(rest) == 49


def test_single_worker_is_inline_and_ordered():
    # no monkeypatch: 1 worker short-circuits regardless of cores
    mapper = CountingMapper()
    out = list(run_map_phase(_chunks(5), mapper, num_workers=1))
    assert [i for i, _ in out] == list(range(5))
    assert mapper.calls == _chunks(5)
