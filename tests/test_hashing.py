import numpy as np
import pytest

from map_oxidize_tpu.ops.hashing import (
    HashDictionary,
    fnv1a64,
    hash_tokens,
    join_u64,
    split_u64,
)


def test_fnv1a64_known_vectors():
    # Published FNV-1a 64 test vectors.
    assert fnv1a64(b"") == 0xCBF29CE484222325
    assert fnv1a64(b"a") == 0xAF63DC4C8601EC8C
    assert fnv1a64(b"foobar") == 0x85944171F73967E8
    assert fnv1a64("foobar") == fnv1a64(b"foobar")


def test_split_join_roundtrip(rng):
    h = rng.integers(0, 2**64, size=1000, dtype=np.uint64)
    hi, lo = split_u64(h)
    assert hi.dtype == np.uint32 and lo.dtype == np.uint32
    np.testing.assert_array_equal(join_u64(hi, lo), h)


def test_hash_tokens_order_and_dtype():
    toks = [b"the", b"quick", b"the"]
    out = hash_tokens(toks)
    assert out.dtype == np.uint64
    assert out[0] == out[2] == fnv1a64(b"the")
    assert out[1] == fnv1a64(b"quick")


def test_dictionary_union_and_collision():
    d1, d2 = HashDictionary(), HashDictionary()
    d1.add(fnv1a64(b"the"), b"the")
    d2.add(fnv1a64(b"cat"), b"cat")
    d1.update(d2)
    assert d1.lookup(fnv1a64(b"cat")) == b"cat"
    assert len(d1) == 2
    # same-hash different-bytes must raise (collision detection)
    with pytest.raises(ValueError):
        d1.add(fnv1a64(b"the"), b"not-the")
