import numpy as np
import pytest

from map_oxidize_tpu.ops.hashing import (
    SENTINEL64,
    HashDictionary,
    fnv1a64,
    hash_tokens,
    join_u64,
    moxt64,
    moxt64_bytes,
    split_u64,
)


def test_fnv1a64_known_vectors():
    # Published FNV-1a 64 test vectors.
    assert fnv1a64(b"") == 0xCBF29CE484222325
    assert fnv1a64(b"a") == 0xAF63DC4C8601EC8C
    assert fnv1a64(b"foobar") == 0x85944171F73967E8
    assert fnv1a64("foobar") == fnv1a64(b"foobar")


def test_moxt64_basic_properties():
    # deterministic, length-sensitive, 64-bit range, never the sentinel
    assert moxt64(b"the") == moxt64(b"the")
    assert moxt64(b"the") != moxt64(b"The")
    assert moxt64(b"a") != moxt64(b"a\0")  # length is part of the key
    assert moxt64("foobar") == moxt64(b"foobar")
    for t in (b"", b"a", b"0123456789abcdef", b"0123456789abcdef0",
              b"x" * 1000):
        h = moxt64_bytes(t)
        assert 0 <= h < 2**64 and h != SENTINEL64


def test_moxt64_no_collisions_structured():
    # the weakness that sank the first moxt64 draft: same-length keys whose
    # differences sit in cancelling bit positions of w0/w1
    keys = [b"lurnq wzzbpd", b"lurnq mjzbas"]
    keys += [f"tok{i:04d} tok{j:04d}".encode()
             for i in range(100) for j in range(50)]
    hs = [moxt64_bytes(k) for k in keys]
    assert len(set(hs)) == len(keys)


def test_split_join_roundtrip(rng):
    h = rng.integers(0, 2**64, size=1000, dtype=np.uint64)
    hi, lo = split_u64(h)
    assert hi.dtype == np.uint32 and lo.dtype == np.uint32
    np.testing.assert_array_equal(join_u64(hi, lo), h)


def test_hash_tokens_order_and_dtype():
    toks = [b"the", b"quick", b"the"]
    out = hash_tokens(toks)
    assert out.dtype == np.uint64
    assert out[0] == out[2] == moxt64(b"the")
    assert out[1] == moxt64(b"quick")


def test_dictionary_union_and_collision():
    d1, d2 = HashDictionary(), HashDictionary()
    d1.add(moxt64(b"the"), b"the")
    d2.add(moxt64(b"cat"), b"cat")
    d1.update(d2)
    assert d1.lookup(moxt64(b"cat")) == b"cat"
    assert len(d1) == 2
    # same-hash different-bytes must raise (collision detection)
    with pytest.raises(ValueError):
        d1.add(moxt64(b"the"), b"not-the")
