"""Data-plane observatory (ISSUE-16 tentpole): row-conservation audits,
key-skew telemetry, and reduction-ratio gauges across the shuffle.

Layers covered:

* checksum algebra — order independence, sum-combine invariance, and
  single-row sensitivity of both digest families;
* partition parity — the audit's numpy partitioner vs the device
  shuffle's ``bucket_of`` routing;
* the audit object — skew figures against numpy oracles on an
  adversarial Zipf corpus, HLL tolerance, violation raising, and the
  simulated cross-process reduction;
* end-to-end — single-chip wordcount (gauges + metrics doc + ledger
  gauge), the spilled inverted index, and an injected single-row drop
  that must fail the run with the NAMED error;
* the ledger diff gates and the skew SLO rule's evidence field;
* 2-process Gloo — wordcount + forced-spill inverted index in ONE child
  pair: per-partition rows, checksums matching across the exchange,
  the imbalance factor, and process-identical audit documents.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from map_oxidize_tpu.obs import dataplane as dpm
from map_oxidize_tpu.obs.dataplane import (
    ConservationError,
    DataPlaneAudit,
    pair_digest,
    partition_of,
    weighted_checksum,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- checksum algebra -----------------------------------------------------


def test_weighted_checksum_order_independent():
    rng = np.random.default_rng(7)
    keys = rng.integers(0, 1 << 63, 500, dtype=np.uint64)
    vals = rng.integers(1, 100, 500, dtype=np.int64)
    perm = rng.permutation(500)
    assert (weighted_checksum(keys, vals)
            == weighted_checksum(keys[perm], vals[perm]))


def test_weighted_checksum_combine_invariant():
    # pre-combining rows of one key (summing values) must not change the
    # digest — the property that lets map-side pre-combined chunks match
    # the fully reduced readback
    keys = np.array([11, 11, 11, 42, 42], np.uint64)
    vals = np.array([1, 2, 3, 10, 20], np.int64)
    combined_k = np.array([11, 42], np.uint64)
    combined_v = np.array([6, 30], np.int64)
    assert (weighted_checksum(keys, vals)
            == weighted_checksum(combined_k, combined_v))


def test_weighted_checksum_single_drop_flips():
    keys = np.arange(1, 100, dtype=np.uint64)
    vals = np.ones(99, np.int64)
    assert (weighted_checksum(keys, vals)
            != weighted_checksum(keys[:-1], vals[:-1]))


def test_pair_digest_multiset_identity_and_sensitivity():
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 1 << 62, 300, dtype=np.uint64)
    docs = rng.integers(0, 1 << 40, 300).astype(np.int64)
    perm = rng.permutation(300)
    assert pair_digest(keys, docs) == pair_digest(keys[perm], docs[perm])
    assert pair_digest(keys, docs) != pair_digest(keys[:-1], docs[:-1])
    # corrupting ONE doc id flips it too
    docs2 = docs.copy()
    docs2[17] += 1
    assert pair_digest(keys, docs) != pair_digest(keys, docs2)


def test_partition_of_matches_device_bucket_of():
    from map_oxidize_tpu.parallel.shuffle import bucket_of

    rng = np.random.default_rng(5)
    keys = rng.integers(0, np.iinfo(np.uint64).max, 1000,
                        dtype=np.uint64)
    hi = (keys >> np.uint64(32)).astype(np.uint32)
    lo = keys.astype(np.uint32)
    for s in (2, 4, 8):
        dev = np.asarray(bucket_of(hi, lo, s))
        assert np.array_equal(partition_of(keys, s), dev.astype(np.int64))


# --- the audit object -----------------------------------------------------


def _zipf_corpus(n=20000, vocab=200, seed=2):
    """Adversarial skew: one key owns ~half the rows."""
    rng = np.random.default_rng(seed)
    body = rng.integers(1, vocab, n, dtype=np.uint64) * np.uint64(2654435761)
    hot = np.full(n, body[0], np.uint64)
    take_hot = rng.random(n) < 0.5
    return np.where(take_hot, hot, body)


def test_audit_skew_matches_numpy_oracle():
    keys = _zipf_corpus()
    vals = np.ones(keys.shape[0], np.int64)
    a = DataPlaneAudit(8)
    # feed in 3 chunks to exercise accumulation
    for blk in np.array_split(np.arange(keys.shape[0]), 3):
        a.record_fold_in(keys[blk], vals[blk])
    rows = np.bincount(partition_of(keys, 8), minlength=8)
    d = a.doc()
    assert d["skew"]["rows_per_partition"] == rows.tolist()
    oracle_imb = rows.max() / rows.mean()
    assert d["skew"]["imbalance_factor"] == pytest.approx(oracle_imb,
                                                          rel=1e-3)
    n_distinct = np.unique(keys).shape[0]
    assert d["skew"]["distinct_est"] == pytest.approx(n_distinct, rel=0.05)
    # the hot key (~half the rows) must top the hot-key table exactly
    uk, cnt = np.unique(keys, return_counts=True)
    top_hash, top_rows = int(uk[cnt.argmax()]), int(cnt.max())
    hot = d["skew"]["hot_keys"][0]
    assert hot["hash"] == f"{top_hash:#018x}"
    assert hot["rows"] == top_rows
    assert d["skew"]["top_share"] == pytest.approx(
        top_rows / keys.shape[0], abs=1e-3)


def test_audit_fold_conservation_and_violation():
    keys = _zipf_corpus(4000, 50, seed=9)
    vals = np.ones(keys.shape[0], np.int64)
    a = DataPlaneAudit(4)
    a.record_fold_in(keys, vals)
    uk, inv = np.unique(keys, return_inverse=True)
    reduced = np.bincount(inv).astype(np.int64)
    a.record_fold_out(uk, reduced)
    a.set_records_in(int(vals.sum()))
    a.check_fold()  # exact: combined readback balances the map side
    assert a.violations == []
    assert a.doc()["reduction"]["ratio"] == pytest.approx(
        keys.shape[0] / uk.shape[0], rel=1e-3)
    # drop one reduced row -> named error, violation recorded
    b = DataPlaneAudit(4)
    b.record_fold_in(keys, vals)
    b.record_fold_out(uk[:-1], reduced[:-1])
    b.set_records_in(int(vals.sum()))
    with pytest.raises(ConservationError, match="conservation violated"):
        b.check_fold()
    assert len(b.violations) == 1
    assert b.doc()["conservation"]["violations"]


def test_audit_pairs_violation_on_corruption():
    rng = np.random.default_rng(21)
    keys = rng.integers(0, 1 << 60, 1000, dtype=np.uint64)
    docs = np.arange(1000, dtype=np.int64)
    a = DataPlaneAudit(4)
    a.record_pairs_in(keys, docs)
    docs2 = docs.copy()
    docs2[500] ^= 1  # same rows, one corrupted doc id
    a.record_pairs_out(keys, docs2)
    with pytest.raises(ConservationError,
                       match="pair contents changed in flight"):
        a.check_pairs()


def test_audit_reduce_distributed_two_halves():
    """Two simulated processes: each audits half the rows; after the
    reduction the second holds the single-process oracle's global
    state and the replicated readback balances it."""
    keys = _zipf_corpus(6000, 80, seed=13)
    vals = np.ones(keys.shape[0], np.int64)
    uk, inv = np.unique(keys, return_inverse=True)
    reduced = np.bincount(inv).astype(np.int64)

    halves = np.array_split(np.arange(keys.shape[0]), 2)
    a0, a1 = DataPlaneAudit(4), DataPlaneAudit(4)
    a0.record_fold_in(keys[halves[0]], vals[halves[0]])
    a1.record_fold_in(keys[halves[1]], vals[halves[1]])
    a0.set_records_in(halves[0].shape[0])
    a1.set_records_in(halves[1].shape[0])

    # capture each side's flat vector, then hand both the same (2, k)
    flats = []
    a0.reduce_distributed(lambda v: (flats.append(v.copy()),
                                     np.stack([v, v * np.uint64(0)]))[1])
    a1.reduce_distributed(lambda v: np.stack([flats[0], v]))

    # a1 now holds the global audit; the replicated readback closes it
    a1.record_fold_out(uk, reduced)
    a1.check_fold()
    assert a1.records_in == keys.shape[0]
    oracle = np.bincount(partition_of(keys, 4), minlength=4)
    assert a1.doc()["skew"]["rows_per_partition"] == oracle.tolist()

    # a process that recorded NOTHING (it owned zero chunks) must still
    # ship the same payload shape — np.stack raises on divergence, the
    # host-side spelling of the allgather wedge this guards against
    empty = DataPlaneAudit(4)
    empty.reduce_distributed(lambda v: np.stack([flats[0], v]))
    assert empty.records_in == halves[0].shape[0]
    half_oracle = np.bincount(partition_of(keys[halves[0]], 4),
                              minlength=4)
    assert (empty.stages["map_out"].rows.astype(np.int64).tolist()
            == half_oracle.tolist())


# --- ledger gates + SLO rule ---------------------------------------------


def _entry(metrics):
    return {"workload": "wordcount", "config_hash": "h", "version": "v",
            "corpus_bytes": 1, "n_processes": 1, "phases_s": {},
            "metrics": metrics}


def test_ledger_gate_conservation_violations():
    from map_oxidize_tpu.obs.ledger import diff_entries

    d = diff_entries(_entry({"data/conservation_violations": 0}),
                     _entry({"data/conservation_violations": 1}),
                     force=True)
    assert any("row-conservation violations" in r for r in d["regressions"])
    ok = diff_entries(_entry({"data/conservation_violations": 0}),
                      _entry({"data/conservation_violations": 0}),
                      force=True)
    assert not any("conservation" in r for r in ok["regressions"])


def test_ledger_gate_imbalance_points():
    from map_oxidize_tpu.obs.ledger import (
        DATA_IMBALANCE_GATE_POINTS,
        diff_entries,
    )

    lo, hi = 1.2, 1.2 + DATA_IMBALANCE_GATE_POINTS + 0.5
    d = diff_entries(_entry({"data/imbalance_factor": lo}),
                     _entry({"data/imbalance_factor": hi}), force=True)
    assert any("key-skew regression" in r for r in d["regressions"])
    # a sub-threshold wiggle stays quiet
    ok = diff_entries(_entry({"data/imbalance_factor": lo}),
                      _entry({"data/imbalance_factor": lo + 0.3}),
                      force=True)
    assert not any("key-skew" in r for r in ok["regressions"])


def test_skew_slo_rule_has_evidence():
    from map_oxidize_tpu.obs.slo import DEFAULT_RULES, SloRule

    rules = [SloRule(**r) for r in DEFAULT_RULES]
    skew = [r for r in rules if r.name == "data-partition-skew"]
    assert len(skew) == 1
    skew[0].validate()
    assert skew[0].metric == "data/imbalance_factor"
    assert skew[0].evidence == "critpath/straggler_save_frac"


# --- end-to-end (single process) -----------------------------------------


def _write_corpus(path, lines=2000, vocab=17):
    with open(path, "w") as f:
        for i in range(lines):
            f.write(f"alpha beta gamma word{i % vocab}\n")


def test_wordcount_end_to_end_audit(tmp_path):
    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.runtime.driver import run_wordcount_job
    from map_oxidize_tpu.workloads.wordcount import make_wordcount

    inp = tmp_path / "c.txt"
    _write_corpus(inp)
    mout = tmp_path / "m.json"
    cfg = JobConfig(input_path=str(inp), output_path="",
                    metrics_out=str(mout),
                    ledger_dir=str(tmp_path / "ledger"))
    mapper, reducer = make_wordcount(cfg.tokenizer, cfg.use_native)
    run_wordcount_job(cfg, mapper, reducer)

    doc = json.loads(mout.read_text())
    d = doc["data"]
    assert d["schema"] == dpm.DATA_SCHEMA
    assert d["conservation"]["violations"] == []
    assert d["conservation"]["checks"] >= 2
    st = d["stages"]
    assert (st["map_out"]["weighted_checksum"]
            == st["reduce_out"]["weighted_checksum"])
    assert st["map_out"]["value_sum"] == d["records_in"]
    g = doc["gauges"]
    assert g["data/conservation_violations"] == 0
    assert g["data/reduction_ratio"] > 0
    assert g["data/imbalance_factor"] >= 1.0
    # the acceptance gauge rides the ledger entry's flat metrics AND the
    # compact data section rides the entry itself
    entry = json.loads((tmp_path / "ledger" / "ledger.jsonl")
                       .read_text().splitlines()[-1])
    assert entry["metrics"]["data/reduction_ratio"] == pytest.approx(
        g["data/reduction_ratio"])
    assert entry["data"]["imbalance_factor"] == pytest.approx(
        g["data/imbalance_factor"])
    assert entry["data"]["violations"] == []


def test_injected_row_drop_fails_named(tmp_path, monkeypatch):
    """A single pair record dropped inside the spill round-trip must
    fail the job with ConservationError — not silently shrink output."""
    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.runtime.driver import run_inverted_index_job
    from map_oxidize_tpu.runtime.spill import BucketFiles

    inp = tmp_path / "docs.txt"
    with open(inp, "w") as f:
        for i in range(300):
            f.write(f"doc{i} shared words here word{i % 11}\n")

    orig = BucketFiles.write_partitioned
    state = {"dropped": False}

    def drop_one(self, suffix, rows, counts, offs, *a, **kw):
        if not state["dropped"] and rows.shape[0] > 1:
            state["dropped"] = True
            rows = rows[:-1]
            offs = np.minimum(offs, rows.shape[0])
        return orig(self, suffix, rows, counts, offs, *a, **kw)

    monkeypatch.setattr(BucketFiles, "write_partitioned", drop_one)
    cfg = JobConfig(input_path=str(inp), output_path="",
                    collect_max_rows=400)
    with pytest.raises(ConservationError,
                       match="spill conservation violated"):
        run_inverted_index_job(cfg)
    assert state["dropped"]


def test_obs_data_cli_renders(tmp_path):
    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.runtime.driver import run_inverted_index_job

    inp = tmp_path / "docs.txt"
    with open(inp, "w") as f:
        for i in range(200):
            f.write(f"doc{i} common words word{i % 7}\n")
    mout = tmp_path / "m.json"
    cfg = JobConfig(input_path=str(inp), output_path="",
                    metrics_out=str(mout))
    run_inverted_index_job(cfg)

    from map_oxidize_tpu.obs.cli import obs_main

    rc = obs_main(["data", str(mout)])
    assert rc == 0
    doc = json.loads(mout.read_text())
    text = dpm.render(doc["data"])
    assert "conservation" in text and "[OK]" in text
    assert "imbalance factor" in text
    assert "reduction ratio" in text


def test_no_data_audit_flag_disables(tmp_path):
    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.runtime.driver import run_wordcount_job
    from map_oxidize_tpu.workloads.wordcount import make_wordcount

    inp = tmp_path / "c.txt"
    _write_corpus(inp, lines=200)
    mout = tmp_path / "m.json"
    cfg = JobConfig(input_path=str(inp), output_path="",
                    metrics_out=str(mout), data_audit=False)
    mapper, reducer = make_wordcount(cfg.tokenizer, cfg.use_native)
    run_wordcount_job(cfg, mapper, reducer)  # legacy check still passes
    doc = json.loads(mout.read_text())
    assert "data" not in doc
    assert not any(k.startswith("data/") for k in doc["gauges"])


# --- 2-process Gloo -------------------------------------------------------

_CHILD = r"""
import json, logging, sys
pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
corpus = sys.argv[4]; docs = sys.argv[5]; art = sys.argv[6]
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.utils.logging import configure
from map_oxidize_tpu.parallel.distributed import (
    init_distributed, run_distributed_job)
configure(logging.INFO)
init_distributed(f"127.0.0.1:{port}", num_processes=nproc, process_id=pid)
common = dict(output_path="", chunk_bytes=4096, batch_size=1 << 12,
              key_capacity=1 << 12, top_k=5, metrics=False,
              dist_coordinator=f"127.0.0.1:{port}",
              dist_num_processes=nproc, dist_process_id=pid)
cfg = JobConfig(input_path=corpus, metrics_out=f"{art}/wc.json",
                ledger_dir=f"{art}/ledger", **common)
r = run_distributed_job(cfg, "wordcount")
cfg2 = JobConfig(input_path=docs, metrics_out=f"{art}/ii.json",
                 collect_max_rows=512, **common)
r2 = run_distributed_job(cfg2, "invertedindex")
print("RESULT", json.dumps({"records": r.records, "n_keys": r.n_keys,
                            "pairs": r2.n_pairs}))
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env():
    env = dict(os.environ)
    for k in ("PALLAS_AXON_POOL_IPS", "PJRT_LIBRARY_PATH",
              "TPU_LIBRARY_PATH", "PJRT_DEVICE", "TPU_ACCELERATOR_TYPE",
              "TPU_TOPOLOGY", "TPU_WORKER_HOSTNAMES"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.fixture(scope="module")
def dist_dataplane_run(tmp_path_factory):
    """One 2-process pair running a SKEWED wordcount then a forced-spill
    inverted index; returns the artifact dir and stdout logs."""
    tmp = tmp_path_factory.mktemp("dist_data")
    corpus = tmp / "c.txt"
    rng = np.random.default_rng(4)
    with open(corpus, "wb") as f:
        for _ in range(2500):
            tail = b" ".join(b"w%d" % i for i in rng.integers(0, 40, 3))
            f.write(b"hot hot hot " + tail + b"\n")
    docs = tmp / "d.txt"
    with open(docs, "wb") as f:
        for i in range(600):
            f.write(b"doc%d shared words plus w%d\n" % (i, i % 19))
    env = _env()
    logs = None
    for attempt in range(2):  # free-port probe is inherently racy
        port = _free_port()
        procs = [subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(i), "2", str(port),
             str(corpus), str(docs), str(tmp)],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True) for i in range(2)]
        logs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=420)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                out = "(timeout)"
            logs.append(out)
        if all(p.returncode == 0 for p in procs):
            break
        if attempt == 1:
            for i, p in enumerate(procs):
                assert p.returncode == 0, f"process {i} failed:\n{logs[i]}"
    return tmp, logs


def test_distributed_fold_audit(dist_dataplane_run):
    tmp, _logs = dist_dataplane_run
    docs = [json.loads((tmp / f"wc.json.proc{p}").read_text())
            for p in (0, 1)]
    for m in docs:
        d = m["data"]
        assert d["conservation"]["violations"] == []
        st = m["data"]["stages"]
        # the checksum matches ACROSS the exchange: locally-recorded map
        # outputs, allgather-reduced, equal the replicated readback
        assert (st["map_out"]["weighted_checksum"]
                == st["reduce_out"]["weighted_checksum"])
        assert st["map_out"]["value_sum"] == st["reduce_out"]["value_sum"]
        assert d["skew"]["imbalance_factor"] >= 1.0
        assert d["reduction"]["ratio"] > 1.0  # 'hot' repeats per line
        assert m["gauges"]["data/conservation_violations"] == 0
    # the reduced audit is replicated: both processes publish the SAME
    # global figures (records_in, per-partition rows, checksums)
    assert docs[0]["data"]["records_in"] == docs[1]["data"]["records_in"]
    assert (docs[0]["data"]["skew"]["rows_per_partition"]
            == docs[1]["data"]["skew"]["rows_per_partition"])
    assert (docs[0]["data"]["stages"]["map_out"]["weighted_checksum"]
            == docs[1]["data"]["stages"]["map_out"]["weighted_checksum"])
    # the hot key dominates and resolves to its string on both processes
    for m in docs:
        hot = m["data"]["skew"]["hot_keys"][0]
        assert hot["key"] == "hot"
    # process 0's ledger entry carries the acceptance gauge + section
    entry = json.loads((tmp / "ledger" / "ledger.jsonl")
                       .read_text().splitlines()[-1])
    assert entry["metrics"]["data/reduction_ratio"] > 1.0
    assert entry["data"]["violations"] == []


def test_distributed_spilled_pairs_audit(dist_dataplane_run):
    tmp, logs = dist_dataplane_run
    docs = [json.loads((tmp / f"ii.json.proc{p}").read_text())
            for p in (0, 1)]
    for m in docs:
        d = m["data"]
        assert d["conservation"]["violations"] == []
        st = d["stages"]
        assert st["map_out"]["rows"] == st["reduce_out"]["rows"]
        assert st["map_out"]["pair_xor"] == st["reduce_out"]["pair_xor"]
        assert st["map_out"]["pair_sum"] == st["reduce_out"]["pair_sum"]
        # the forced spill actually happened, and its round-trip digests
        # balanced (a mismatch would have aborted the child)
        assert m["counters"].get("spill/rows", 0) > 0
    assert (docs[0]["data"]["stages"]["map_out"]["pair_xor"]
            == docs[1]["data"]["stages"]["map_out"]["pair_xor"])
