from collections import Counter

from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.io.splitter import iter_chunks
from map_oxidize_tpu.runtime.driver import run_wordcount_job
from map_oxidize_tpu.workloads.bigram import make_bigram
from map_oxidize_tpu.workloads.wordcount import tokenize


def _bigram_model(chunks):
    total = Counter()
    for chunk in chunks:
        toks = tokenize(chunk)
        total.update(toks[i] + b" " + toks[i + 1] for i in range(len(toks) - 1))
    return total


def test_bigram_matches_model(tmp_path):
    p = tmp_path / "c.txt"
    p.write_bytes(b"the cat sat on the mat\nthe cat ran\n" * 40)
    cfg = JobConfig(input_path=str(p), output_path="", backend="cpu",
                    chunk_bytes=128, batch_size=128, key_capacity=2048)
    mapper, reducer = make_bigram()
    result = run_wordcount_job(cfg, mapper, reducer)
    model = _bigram_model(iter_chunks(str(p), 128))
    assert result.counts == dict(model)
    assert result.metrics["records_in"] == sum(model.values())
