"""End-to-end device-map path vs the reference-semantics model."""

from collections import Counter

import numpy as np
import pytest

from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.runtime import resolve_mapper, run_job
from map_oxidize_tpu.runtime.device_map import run_device_wordcount_job
from map_oxidize_tpu.workloads.reference_model import top_k_model, wordcount_model


def _write_corpus(tmp_path, rng, lines=300):
    words = ["The", "the", "fox,", "dog", "a", "over", "Lazy", "THE.",
             "multi\tword", "end."]
    text = "\n".join(" ".join(rng.choice(words, size=11)) for _ in range(lines))
    p = tmp_path / "corpus.txt"
    p.write_text(text)
    return p, text.encode()


def test_device_job_matches_model(tmp_path, rng):
    corpus, raw = _write_corpus(tmp_path, rng)
    cfg = JobConfig(input_path=str(corpus), output_path=str(tmp_path / "o.txt"),
                    backend="cpu", mapper="device", chunk_bytes=4096,
                    device_chunk_keys=1024, initial_key_capacity=256)
    res = run_device_wordcount_job(cfg)
    want = wordcount_model([raw])
    assert res.counts == dict(want)
    assert res.top == top_k_model(want, 10)
    # output file written deterministically
    body = (tmp_path / "o.txt").read_bytes()
    assert body == b"".join(
        w + b" " + str(c).encode() + b"\n" for w, c in sorted(want.items())
    )


def test_device_job_multi_chunk_equals_single(tmp_path, rng):
    corpus, raw = _write_corpus(tmp_path, rng, lines=500)
    small = JobConfig(input_path=str(corpus), output_path="", backend="cpu",
                      mapper="device", chunk_bytes=2048,
                      device_chunk_keys=512, initial_key_capacity=128)
    big = JobConfig(input_path=str(corpus), output_path="", backend="cpu",
                    mapper="device", chunk_bytes=1 << 20,
                    device_chunk_keys=4096)
    assert run_device_wordcount_job(small).counts == \
        run_device_wordcount_job(big).counts


def test_run_job_dispatch_and_fallbacks(tmp_path, rng):
    corpus, raw = _write_corpus(tmp_path, rng, lines=50)
    # unicode tokenizer cannot run on device -> native fallback, same counts
    cfg_dev = JobConfig(input_path=str(corpus), output_path="", backend="cpu",
                        mapper="device", chunk_bytes=4096,
                        device_chunk_keys=1024)
    cfg_uni = JobConfig(input_path=str(corpus), output_path="", backend="cpu",
                        mapper="device", tokenizer="unicode")
    assert resolve_mapper(cfg_uni, "wordcount") == "native"
    assert resolve_mapper(cfg_dev, "bigram") == "device"
    assert resolve_mapper(cfg_dev, "invertedindex") == "native"
    got_dev = run_job(cfg_dev, "wordcount").counts
    got_py = run_job(
        JobConfig(input_path=str(corpus), output_path="", backend="cpu",
                  mapper="python"), "wordcount").counts
    assert got_dev == got_py == dict(wordcount_model([raw]))


def _bigram_model_for_chunks(path, chunk_bytes):
    """Per-chunk bigram counts with the device path's own chunking (bigram
    results are chunking-dependent by documented contract)."""
    from map_oxidize_tpu.io.splitter import iter_chunks_capped
    from map_oxidize_tpu.workloads.wordcount import tokenize

    want = Counter()
    for chunk in iter_chunks_capped(str(path), chunk_bytes):
        toks = tokenize(bytes(chunk))
        want.update(toks[i] + b" " + toks[i + 1] for i in range(len(toks) - 1))
    return dict(want)


def test_device_bigram_matches_host_model(tmp_path, rng):
    corpus, _ = _write_corpus(tmp_path, rng, lines=400)
    cfg = JobConfig(input_path=str(corpus), output_path="", backend="cpu",
                    mapper="device", chunk_bytes=4096,
                    device_chunk_keys=4096, initial_key_capacity=256)
    res = run_job(cfg, "bigram")
    assert res.counts == _bigram_model_for_chunks(corpus, 4096)


def test_device_out_keys_clamped_to_max_tokens(tmp_path, rng):
    """Regression: out_keys > max_tokens used to desync the host's packed
    slicing from the kernel's clamped output width (empty rep array)."""
    corpus, raw = _write_corpus(tmp_path, rng, lines=200)
    cfg = JobConfig(input_path=str(corpus), output_path="", backend="cpu",
                    mapper="device", chunk_bytes=2048,
                    device_chunk_keys=1 << 16)  # >> max_tokens = 1025
    assert run_job(cfg, "wordcount").counts == dict(wordcount_model([raw]))


def test_sharded_device_wordcount(tmp_path, rng):
    """Device map composed with the all_to_all sharded engine on the 8-device
    virtual mesh: tokenize under shard_map feeds the exchange directly."""
    corpus, raw = _write_corpus(tmp_path, rng, lines=600)
    cfg = JobConfig(input_path=str(corpus), output_path=str(tmp_path / "o.txt"),
                    backend="cpu", mapper="device", num_shards=8,
                    chunk_bytes=2048, device_chunk_keys=512,
                    key_capacity=1 << 16)
    res = run_job(cfg, "wordcount")
    want = wordcount_model([raw])
    assert res.counts == dict(want)
    assert res.top == top_k_model(want, 10)
    assert res.metrics["shards"] == 8


def test_sharded_device_bigram(tmp_path, rng):
    corpus, _ = _write_corpus(tmp_path, rng, lines=400)
    cfg = JobConfig(input_path=str(corpus), output_path="", backend="cpu",
                    mapper="device", num_shards=8, chunk_bytes=2048,
                    device_chunk_keys=1024, key_capacity=1 << 17)
    res = run_job(cfg, "bigram")
    assert res.counts == _bigram_model_for_chunks(corpus, 2048)
