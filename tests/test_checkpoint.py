"""Checkpoint/resume: a job killed mid-map resumes without re-mapping the
spilled prefix and produces byte-identical output.

The reference's intermediate files (main.rs:74-75) could have supported this
but nothing reads them across runs; here resume is a tested contract
(VERDICT round 1, item 7)."""

import os

import numpy as np
import pytest

from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.runtime import run_job
from map_oxidize_tpu.runtime.checkpoint import CheckpointStore
from map_oxidize_tpu.workloads.wordcount import WordCountMapper


def _make_corpus(path, n_lines=4000, seed=0):
    rng = np.random.default_rng(seed)
    words = [b"alpha", b"beta", b"Gamma,", b"delta.", b"epsilon", b"zeta"]
    with open(path, "wb") as f:
        for _ in range(n_lines):
            k = int(rng.integers(3, 9))
            f.write(b" ".join(words[int(i)] for i in rng.integers(0, 6, k)))
            f.write(b"\n")


class _DyingMapper(WordCountMapper):
    """Aborts the run after ``die_after`` chunks — the mid-run kill."""

    def __init__(self, die_after: int, **kw):
        super().__init__(**kw)
        self.mapped = 0
        self.die_after = die_after

    def map_chunk(self, chunk):
        if self.mapped >= self.die_after:
            raise KeyboardInterrupt("simulated kill")
        self.mapped += 1
        return super().map_chunk(chunk)


def _cfg(corpus, out, ckdir, **kw):
    base = dict(
        input_path=str(corpus), output_path=str(out), checkpoint_dir=ckdir,
        chunk_bytes=16 * 1024, backend="cpu", num_shards=1, metrics=False,
        num_map_workers=1, max_retries=0, use_native=False, mapper="python",
    )
    base.update(kw)
    return JobConfig(**base)


@pytest.mark.parametrize("use_native", [False, True])
def test_resume_after_kill_byte_identical(tmp_path, use_native):
    corpus = tmp_path / "corpus.txt"
    _make_corpus(corpus)
    ckdir = str(tmp_path / "ck")
    mapper_mode = "native" if use_native else "python"

    # reference run: no checkpointing at all
    want_out = tmp_path / "want.txt"
    run_job(_cfg(corpus, want_out, None, mapper=mapper_mode,
                 use_native=use_native), "wordcount")

    # run 1: dies mid-map.  The python mapper path is used for the kill run
    # (the native mmap path maps inline in C++ — a per-chunk kill hook needs
    # map_chunk), so spilled chunks come from the splitter path; the resume
    # run may then use either path, proving the two agree on chunk cuts.
    from map_oxidize_tpu.runtime.driver import run_wordcount_job
    from map_oxidize_tpu.api import SumReducer

    dying = _DyingMapper(die_after=3, use_native=False)
    got_out = tmp_path / "got.txt"
    with pytest.raises(KeyboardInterrupt):
        run_wordcount_job(_cfg(corpus, got_out, ckdir), dying, SumReducer())
    saved = [n for n in os.listdir(ckdir) if n.endswith(".npz")]
    assert len(saved) == 3, saved

    # run 2: resumes — must not re-map the spilled prefix
    counting = _DyingMapper(die_after=10**9, use_native=use_native)
    if use_native and counting._native is None:
        pytest.skip("native build unavailable")
    res = run_wordcount_job(
        _cfg(corpus, got_out, ckdir, mapper=mapper_mode,
             use_native=use_native), counting, SumReducer())
    total_chunks = res.metrics["chunks"]
    if not use_native:
        assert counting.mapped == total_chunks - 3  # prefix was replayed

    assert got_out.read_bytes() == want_out.read_bytes()
    # success removes the spill by default (reference cleanup semantics)
    assert not os.path.isdir(ckdir)


def test_resume_preserves_prefix_only_words(tmp_path):
    """Regression: update() must not strip a chunk's pending dictionary
    delta before the checkpoint spill serializes it.  Words whose ONLY
    occurrences sit in the replayed prefix can never be re-drained on
    resume — if the spill lost them, finalize dies on a KeyError."""
    corpus = tmp_path / "c.txt"
    with open(corpus, "wb") as f:
        # unique early vocabulary (first ~3 chunks), disjoint tail vocab
        for i in range(600):
            f.write(b"early%04d " % i)
            if i % 8 == 7:
                f.write(b"\n")
        f.write(b"\n")
        for i in range(600):
            f.write(b"late%04d " % i)
            if i % 8 == 7:
                f.write(b"\n")
    ckdir = str(tmp_path / "ck")
    from map_oxidize_tpu.workloads.wordcount import WordCountMapper

    if WordCountMapper("ascii", use_native=True)._native is None:
        pytest.skip("native build unavailable; the pending-delta spill "
                    "path under test only exists on the native mapper")
    want = run_job(_cfg(corpus, tmp_path / "w.txt", None, use_native=True,
                        mapper="native", chunk_bytes=2048), "wordcount")

    # native-path run spills every chunk; keep the spill.  With the stolen-
    # delta bug, every spilled chunk carried an EMPTY dictionary here.
    run_job(_cfg(corpus, tmp_path / "g.txt", ckdir, use_native=True,
                 mapper="native", chunk_bytes=2048, keep_intermediates=True),
            "wordcount")
    # pure-replay run: every chunk comes from the spill, nothing is
    # re-mapped, so lost dictionary deltas cannot be re-drained -> KeyError
    res = run_job(_cfg(corpus, tmp_path / "g2.txt", ckdir, use_native=True,
                       mapper="native", chunk_bytes=2048,
                       keep_intermediates=True), "wordcount")
    assert res.counts == want.counts
    assert (tmp_path / "g2.txt").read_bytes() == (tmp_path / "w.txt").read_bytes()


def test_keep_intermediates_preserves_spill(tmp_path):
    corpus = tmp_path / "corpus.txt"
    _make_corpus(corpus, n_lines=500)
    ckdir = str(tmp_path / "ck")
    run_job(_cfg(corpus, tmp_path / "o.txt", ckdir, keep_intermediates=True),
            "wordcount")
    names = os.listdir(ckdir)
    assert "meta.json" in names
    assert any(n.endswith(".npz") for n in names)

    # a second identical run replays everything and still matches
    res = run_job(_cfg(corpus, tmp_path / "o2.txt", ckdir,
                       keep_intermediates=True), "wordcount")
    assert (tmp_path / "o.txt").read_bytes() == (tmp_path / "o2.txt").read_bytes()
    assert res.metrics["chunks"] > 0


def test_checkpoint_invalidated_on_different_job(tmp_path):
    corpus = tmp_path / "corpus.txt"
    _make_corpus(corpus, n_lines=500)
    other = tmp_path / "other.txt"
    _make_corpus(other, n_lines=700, seed=1)
    ckdir = str(tmp_path / "ck")

    run_job(_cfg(corpus, tmp_path / "o.txt", ckdir, keep_intermediates=True),
            "wordcount")
    # same dir, different input: stale spill must be discarded, not replayed
    res = run_job(_cfg(other, tmp_path / "o2.txt", ckdir), "wordcount")
    want = run_job(_cfg(other, tmp_path / "o3.txt", None), "wordcount")
    assert res.counts == want.counts


def test_round_robin_mode_resumes_by_index(tmp_path):
    corpus = tmp_path / "corpus.txt"
    _make_corpus(corpus, n_lines=800)
    ckdir = str(tmp_path / "ck")
    from map_oxidize_tpu.runtime.driver import run_wordcount_job
    from map_oxidize_tpu.api import SumReducer

    want = run_job(_cfg(corpus, tmp_path / "w.txt", None, num_chunks=6),
                   "wordcount")
    dying = _DyingMapper(die_after=2, use_native=False)
    with pytest.raises(KeyboardInterrupt):
        run_wordcount_job(_cfg(corpus, tmp_path / "g.txt", ckdir,
                               num_chunks=6), dying, SumReducer())
    counting = _DyingMapper(die_after=10**9, use_native=False)
    res = run_wordcount_job(_cfg(corpus, tmp_path / "g.txt", ckdir,
                                 num_chunks=6), counting, SumReducer())
    assert counting.mapped == 4  # 6 chunks, 2 replayed
    assert res.counts == want.counts


def test_meta_mismatch_detection(tmp_path):
    corpus = tmp_path / "c.txt"
    _make_corpus(corpus, n_lines=100)
    cfg = _cfg(corpus, "", str(tmp_path / "ck"))
    m1 = CheckpointStore.job_meta(cfg, "wordcount")
    m2 = CheckpointStore.job_meta(cfg, "bigram")
    assert m1 != m2
    m3 = CheckpointStore.job_meta(
        _cfg(corpus, "", None, chunk_bytes=8 * 1024), "wordcount")
    assert m1 != m3
