"""Collective calibration observatory: the probe harness, the coverage
plane, and the store-driven exchange-collective chooser.

The chooser units run against DOCTORED stores (hand-built curve rows) so
every provenance path is pinned without timing anything: a real curve
steers, a cold store falls back with a named reason, an out-of-range
bucket is extrapolation-not-evidence, and thin cells stay below the
min-samples floor.  The probe round-trip actually times the mesh
programs (in-process 8-virtual-device CPU mesh) and checks the rows land
through the normal merge machinery tagged ``source="probe"`` — and that
one probe is enough to flip a cold chooser to ``provenance: curve``.
The 2-process Gloo probe (slow) checks the lockstep-sweep determinism
promise: both processes merge IDENTICAL row sets into their own stores.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from map_oxidize_tpu.obs import calib
from map_oxidize_tpu.parallel.shuffle import (
    EXCHANGE_COLLECTIVES,
    choose_collective,
    exchange_payload_bytes,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

IDENT = {"platform": "cpu", "device_count": 8, "topology": "1x8"}
# S=8, cap=100, int32 values: 8*8*100*(8+4) = 76800 bytes -> bucket 64KB
S, CAP, ROW_BYTES = 8, 100, 4
PAYLOAD = exchange_payload_bytes(S, CAP, ROW_BYTES)
BUCKET = calib.shape_bucket(PAYLOAD)


def _doctored_store(rows, ident=IDENT):
    """A store holding hand-built curve rows: (collective, bucket,
    per_call_bytes, mean_ms, samples, source) tuples."""
    store = calib.CalibStore()
    for collective, bucket, per_call, mean_ms, samples, source in rows:
        key = calib._comm_key(ident, collective, "shuffle/merge", bucket,
                              source)
        store.doc["comms"][key] = dict(
            ident, collective=collective, program="shuffle/merge",
            shape_bucket=bucket, source=source, calls=samples,
            bytes=float(per_call) * samples,
            latency_ms=float(mean_ms) * samples,
            latency_samples=samples, runs=1)
    return store


# --- the chooser: every provenance path against doctored stores ----------


def test_chooser_curve_selects_cheaper_collective():
    # all_gather measured 3x cheaper at the exact bucket -> selected
    store = _doctored_store([
        ("all_to_all", BUCKET, PAYLOAD, 9.0, 5, "probe"),
        ("all_gather", BUCKET, PAYLOAD, 3.0, 5, "probe"),
    ])
    d = choose_collective(store, IDENT, S, CAP, ROW_BYTES)
    assert d["method"] == "all_gather"
    assert d["provenance"] == "curve"
    assert d["bucket"] == BUCKET
    assert d["payload_bytes"] == PAYLOAD
    ev = d["evidence"]
    assert ev["all_gather"]["predicted_ms"] == pytest.approx(3.0)
    assert ev["all_to_all"]["predicted_ms"] == pytest.approx(9.0)
    assert ev["all_gather"]["by_source"] == {"probe": 5}
    assert ev["all_gather"]["bucket_distance"] == 0
    # the flipped comparison picks the monolith
    d2 = choose_collective(_doctored_store([
        ("all_to_all", BUCKET, PAYLOAD, 2.0, 5, "job"),
        ("all_gather", BUCKET, PAYLOAD, 8.0, 5, "job"),
    ]), IDENT, S, CAP, ROW_BYTES)
    assert d2["method"] == "all_to_all"
    assert d2["provenance"] == "curve"


def test_chooser_cold_store_falls_back_with_named_reason():
    for store in (None, calib.CalibStore()):
        d = choose_collective(store, IDENT, S, CAP, ROW_BYTES)
        assert d["method"] == EXCHANGE_COLLECTIVES[0]  # the default
        assert d["provenance"] == "default"
        assert "cold store" in d["reason"]
        assert d["evidence"]["all_to_all"]["bucket_distance"] is None


def test_chooser_wrong_identity_is_cold():
    # same rows under a different mesh identity must not steer this one
    store = _doctored_store([
        ("all_to_all", BUCKET, PAYLOAD, 9.0, 5, "probe"),
        ("all_gather", BUCKET, PAYLOAD, 3.0, 5, "probe"),
    ], ident={"platform": "tpu", "device_count": 4, "topology": "1x4"})
    d = choose_collective(store, IDENT, S, CAP, ROW_BYTES)
    assert d["provenance"] == "default"
    assert "cold store" in d["reason"]


def test_chooser_out_of_range_is_extrapolation_not_evidence():
    # curves sampled only at 4MB; the job lands at 64KB -> 6 pow2 steps
    far = 4 << 20
    store = _doctored_store([
        ("all_to_all", "4MB", far, 9.0, 5, "probe"),
        ("all_gather", "4MB", far, 3.0, 5, "probe"),
    ])
    d = choose_collective(store, IDENT, S, CAP, ROW_BYTES)
    assert d["method"] == EXCHANGE_COLLECTIVES[0]
    assert d["provenance"] == "default"
    assert "out of bucket range" in d["reason"]
    assert "extrapolation" in d["reason"]
    assert d["evidence"]["all_to_all"]["bucket_distance"] == 6
    # the coverage plane reports the same distance for the gauges
    ev = calib.collective_evidence(store, IDENT, "all_gather", BUCKET)
    assert ev["bucket_distance"] == 6
    assert ev["samples"] == 0


def test_chooser_min_samples_floor():
    store = _doctored_store([
        ("all_to_all", BUCKET, PAYLOAD, 9.0, 2, "probe"),
        ("all_gather", BUCKET, PAYLOAD, 3.0, 2, "probe"),
    ])
    d = choose_collective(store, IDENT, S, CAP, ROW_BYTES)  # default floor 3
    assert d["provenance"] == "default"
    assert "below min-samples floor" in d["reason"]
    # lowering the floor to the evidence level unlocks the curve
    d2 = choose_collective(store, IDENT, S, CAP, ROW_BYTES, min_samples=2)
    assert d2["provenance"] == "curve"
    assert d2["method"] == "all_gather"


def test_chooser_requires_evidence_for_both_methods():
    # one strong curve is not enough: the comparison needs both
    store = _doctored_store([
        ("all_gather", BUCKET, PAYLOAD, 3.0, 5, "probe"),
    ])
    d = choose_collective(store, IDENT, S, CAP, ROW_BYTES)
    assert d["provenance"] == "default"
    assert d["method"] == EXCHANGE_COLLECTIVES[0]


def test_chooser_pooled_sources_stay_attributable():
    # probe + job rows pool for density but by_source keeps them split
    store = _doctored_store([
        ("all_to_all", BUCKET, PAYLOAD, 9.0, 2, "probe"),
        ("all_to_all", BUCKET, PAYLOAD, 9.0, 2, "job"),
        ("all_gather", BUCKET, PAYLOAD, 3.0, 4, "probe"),
    ])
    d = choose_collective(store, IDENT, S, CAP, ROW_BYTES)
    assert d["provenance"] == "curve"
    assert d["evidence"]["all_to_all"]["samples"] == 4
    assert d["evidence"]["all_to_all"]["by_source"] == {
        "probe": 2, "job": 2}


def test_chooser_user_pin_short_circuits():
    d = choose_collective(None, IDENT, S, CAP, ROW_BYTES,
                          requested="all_gather")
    assert d["method"] == "all_gather"
    assert d["provenance"] == "pinned"


# --- parity pins: the jax-free mirrors must track the source tuples ------


def test_collective_name_mirrors_stay_in_sync():
    # calib's jax-free mirror of the shuffle tuple
    assert calib.EXCHANGE_COLLECTIVE_NAMES == EXCHANGE_COLLECTIVES
    # config.validate's hardcoded literal (jax-free CLI path)
    from map_oxidize_tpu.config import JobConfig

    for name in ("auto", *EXCHANGE_COLLECTIVES):
        JobConfig(input_path="x", exchange_collective=name).validate()
    with pytest.raises(ValueError, match="exchange_collective"):
        JobConfig(input_path="x",
                  exchange_collective="ring_reduce").validate()


def test_exchange_shape_matches_engine_derivation():
    # fold engines: cap = min(bps, 2*ceil(bps/S)+16), int32 value rows
    cap, row = calib.exchange_shape(8, 1 << 16)
    bps = (1 << 16) // 8
    assert row == 4
    assert cap == min(bps, 2 * (-(-bps // 8)) + 16)
    # collect engines keep the full per-shard batch, u64 row tax
    cap_c, row_c = calib.exchange_shape(8, 1 << 16, collect=True)
    assert (cap_c, row_c) == (bps, 8)


# --- coverage plane ------------------------------------------------------


def test_coverage_report_needs_vs_has():
    store = _doctored_store([
        ("all_to_all", BUCKET, PAYLOAD, 9.0, 5, "probe"),
        ("all_gather", "4MB", 4 << 20, 3.0, 5, "probe"),
    ])
    cells = [{"collective": c, "bucket": BUCKET}
             for c in EXCHANGE_COLLECTIVES]
    rep = calib.coverage_report(store, IDENT, cells)
    assert rep["schema"] == "moxt-calib-coverage-v1"
    assert rep["needed"] == 2
    assert rep["covered"] == 1  # all_gather only sampled 6 buckets away
    assert rep["coverage_pct"] == pytest.approx(50.0)
    assert rep["extrapolation_bucket_distance"] == 6
    text = calib.render_coverage(rep)
    assert "50.0%" in text


def test_coverage_vacuous_is_fully_covered():
    # a single-shard job needs no collective cells: 100%, never a gate
    # flag (0.0 here would false-fire the coverage-drop gate)
    rep = calib.coverage_report(calib.CalibStore(), IDENT, [])
    assert rep["needed"] == 0
    assert rep["coverage_pct"] == 100.0
    assert rep["extrapolation_bucket_distance"] == 0


def test_bucket_index_parses_labels():
    assert calib.bucket_index("64KB") == 16
    assert calib.bucket_index("1MB") == 20
    assert calib.bucket_index("512B") == 9
    assert calib.bucket_index("0B") is None
    assert calib.bucket_index("weird") is None


# --- store mechanics: source tagging, legacy keys, concurrent merge ------


def test_legacy_six_part_keys_normalize_to_job_source(tmp_path):
    path = tmp_path / calib.CALIB_FILE
    legacy_key = "|".join(["cpu", "8", "1x8", "all_to_all",
                           "shuffle/merge", "64KB"])
    doc = {"schema": calib.CALIB_SCHEMA, "version": calib.CALIB_VERSION,
           "comms": {legacy_key: {
               "platform": "cpu", "device_count": 8, "topology": "1x8",
               "collective": "all_to_all", "program": "shuffle/merge",
               "shape_bucket": "64KB", "calls": 4, "bytes": 4.0 * PAYLOAD,
               "latency_ms": 20.0, "latency_samples": 4, "runs": 1}},
           "programs": {}, "runs": 1}
    path.write_text(json.dumps(doc))
    store = calib.CalibStore.load(str(path))
    assert legacy_key + "|job" in store.doc["comms"]
    assert legacy_key not in store.doc["comms"]
    row = store.doc["comms"][legacy_key + "|job"]
    assert row["source"] == "job"
    # and the normalized row feeds the evidence plane as job evidence
    ev = calib.collective_evidence(store, IDENT, "all_to_all", "64KB")
    assert ev["by_source"] == {"job": 4}


def test_accumulate_rejects_unknown_source():
    store = calib.CalibStore()
    with pytest.raises(ValueError, match="source"):
        store.accumulate_run(IDENT, [{"collective": "psum",
                                      "program": "shuffle/merge",
                                      "count": 1, "bytes": 64.0}], None,
                             source="vibes")


def test_probe_and_job_rows_never_collide(tmp_path):
    # same (collective, program, bucket) cell, different sources ->
    # distinct store rows, both visible and attributable after reload
    path = str(tmp_path / calib.CALIB_FILE)
    comms = [{"collective": "all_to_all", "program": "shuffle/merge",
              "count": 2, "bytes": 2.0 * PAYLOAD,
              "latency_ms": {"count": 2, "mean": 5.0}}]
    a = calib.CalibStore(path=path)
    a.accumulate_run(IDENT, comms, None, source="probe")
    a.save_merged()
    b = calib.CalibStore(path=path)  # fresh accumulation object, same file
    b.accumulate_run(IDENT, comms, None, source="job")
    b.save_merged()
    merged = calib.CalibStore.load(path)
    sources = {r["source"] for r in merged.doc["comms"].values()}
    assert sources == {"probe", "job"}
    assert merged.doc["runs"] == 2
    ev = calib.collective_evidence(merged, IDENT, "all_to_all", BUCKET)
    assert ev["by_source"] == {"probe": 2, "job": 2}


# --- the probe harness: real mesh programs, real rows --------------------


def test_probe_round_trip_fills_a_selectable_curve(tmp_path):
    from map_oxidize_tpu.obs.probe import render_probe, run_probe

    summary = run_probe(str(tmp_path), buckets=("16KB", "32KB"), reps=3)
    assert summary["schema"] == "moxt-calib-probe-v1"
    assert summary["num_shards"] == 8
    assert summary["rows_merged"] > 0
    # both exchange wire programs, the psum reduction, and the top-k
    # all_gather all probed
    progs = {(c["collective"], c["program"]) for c in summary["cells"]}
    for coll in EXCHANGE_COLLECTIVES:
        assert (coll, "shuffle/merge") in progs
    assert ("psum", "shuffle/merge") in progs
    assert ("all_gather", "shuffle/top_k") in progs
    render_probe(summary)  # renderer must hold on a real summary

    store = calib.CalibStore.load(str(tmp_path))
    assert store.doc["runs"] == 1
    assert all(r["source"] == "probe"
               for r in store.doc["comms"].values())
    # one probe on a cold store is enough evidence for the chooser: pick
    # a cap whose payload lands in a probed bucket
    ident = calib.run_identity()
    cell = next(c for c in summary["cells"]
                if c["program"] == "shuffle/merge")
    cap = cell["payload_bytes"] // (8 * 8 * (8 + 4))
    d = choose_collective(store, ident, 8, cap, 4)
    assert d["provenance"] == "curve", d["reason"]
    assert d["method"] in EXCHANGE_COLLECTIVES
    assert d["evidence"][d["method"]]["by_source"].get("probe", 0) >= 3
    # and the coverage gauges read nonzero for the probed cells
    rep = calib.coverage_report(
        store, ident, [{"collective": c, "bucket": d["bucket"]}
                       for c in EXCHANGE_COLLECTIVES])
    assert rep["coverage_pct"] == 100.0
    assert rep["extrapolation_bucket_distance"] == 0


def test_probe_merges_concurrently_with_job_evidence(tmp_path):
    # a job finishing mid-probe: save_merged's read-merge-write keeps
    # both (the probe holds ONLY its own rows, so no double count)
    from map_oxidize_tpu.obs.probe import run_probe

    run_probe(str(tmp_path), buckets=("16KB",), reps=3)
    job = calib.CalibStore(path=str(tmp_path / calib.CALIB_FILE))
    job.accumulate_run(calib.run_identity(), [
        {"collective": "all_to_all", "program": "shuffle/merge",
         "count": 3, "bytes": 3.0 * 20000,
         "latency_ms": {"count": 3, "mean": 4.0}}], None, source="job")
    job.save_merged()
    merged = calib.CalibStore.load(str(tmp_path))
    assert merged.doc["runs"] == 2
    by_source = {}
    for r in merged.doc["comms"].values():
        by_source[r["source"]] = by_source.get(r["source"], 0) + 1
    assert by_source["probe"] >= 4 and by_source["job"] == 1


# --- exchange-method parity: the chooser may never change results --------


def test_all_gather_exchange_is_byte_identical(rng):
    import jax
    from jax.sharding import PartitionSpec as P

    from map_oxidize_tpu.parallel.mesh import SHARD_AXIS, make_mesh
    from map_oxidize_tpu.parallel.shuffle import _exchange
    from map_oxidize_tpu.utils.jax_compat import shard_map

    mesh = make_mesh(8)
    cap = 16
    n = 8 * 32  # 32 rows/shard -> mean 4 per bucket, far under cap
    hi = rng.integers(0, 1 << 32, size=n, dtype=np.uint32)
    lo = rng.integers(0, 1 << 32, size=n, dtype=np.uint32)
    vals = np.ones(n, dtype=np.int32)
    outs = {}
    for method in EXCHANGE_COLLECTIVES:
        def body(h, l, v, _m=method):
            return _exchange(h, l, v, 8, cap, method=_m)

        fn = jax.jit(shard_map(
            body, mesh=mesh,
            in_specs=(P(SHARD_AXIS),) * 3,
            out_specs=(P(SHARD_AXIS),) * 3 + (P(),)))
        r_hi, r_lo, r_vals, ovf = fn(hi, lo, vals)
        assert int(np.asarray(ovf).reshape(-1)[0]) == 0
        outs[method] = (np.asarray(r_hi), np.asarray(r_lo),
                        np.asarray(r_vals))
    a, b = outs[EXCHANGE_COLLECTIVES[0]], outs[EXCHANGE_COLLECTIVES[1]]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


# --- 2-process Gloo probe: lockstep sweep, identical stores --------------

_PROBE_CHILD = r"""
import json, sys
pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
store_dir = sys.argv[4]
from map_oxidize_tpu.parallel.distributed import init_distributed
init_distributed(f"127.0.0.1:{port}", num_processes=nproc, process_id=pid)
from map_oxidize_tpu.obs.probe import run_probe
s = run_probe(store_dir, buckets=("16KB", "64KB"), reps=2,
              n_processes=nproc)
print("probe child", pid, "merged", s["rows_merged"])
"""


@pytest.mark.slow
def test_probe_two_process_gloo_identical_stores(tmp_path):
    from tests.test_distributed import _env, _free_port

    nproc = 2
    dirs = [str(tmp_path / f"p{i}") for i in range(nproc)]
    env = _env(devices=4)  # 2 procs x 4 local = 8-device global mesh
    for attempt in range(2):
        port = _free_port()
        procs = [subprocess.Popen(
            [sys.executable, "-c", _PROBE_CHILD, str(i), str(nproc),
             str(port), dirs[i]],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True) for i in range(nproc)]
        logs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=420)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                out = "(timeout)"
            logs.append(out)
        if all(p.returncode == 0 for p in procs):
            break
        if attempt == 1:
            for i, p in enumerate(procs):
                assert p.returncode == 0, f"process {i} failed:\n{logs[i]}"
    stores = [calib.CalibStore.load(d) for d in dirs]
    keys = [sorted(s.doc["comms"]) for s in stores]
    assert keys[0] == keys[1] and keys[0], logs
    for key in keys[0]:
        a, b = stores[0].doc["comms"][key], stores[1].doc["comms"][key]
        # deterministic sweep: identical shapes/payloads/counts (walls
        # differ — they are measurements)
        for field in ("calls", "bytes", "latency_samples", "runs",
                      "source", "collective", "program", "shape_bucket",
                      "topology", "device_count"):
            assert a[field] == b[field], (key, field)
        assert a["source"] == "probe"
    # the distributed identity rode in: 2-process topology, 8 devices
    row = stores[0].doc["comms"][keys[0][0]]
    assert row["topology"] == "2x8"
    assert row["device_count"] == 8
