"""SLO & alerting plane + cross-run forensics (ISSUE-9 tentpole).

Unit layer: rule parsing/validation, the value/delta/rate observation
kinds, the for_s debounce and after_s arming, ring-wraparound
correctness for windowed rules, incident bundles, the Prometheus
cumulative-bucket histogram export and the sanitized-name collision
guard, and the ``obs trend`` movers/step analysis.

Integration layer: an injected rule firing and resolving on a live
``/alerts`` endpoint (with the heartbeat line and the ``obs top``
panel), default rules staying silent on a healthy run, and the serve
scheduler's per-job latency histograms.
"""

import json
import os
import re
import threading
import time
import urllib.request

import pytest

from map_oxidize_tpu.config import JobConfig, ServeConfig
from map_oxidize_tpu.obs import Heartbeat, MetricsRegistry, Obs
from map_oxidize_tpu.obs.metrics import LATENCY_BUCKETS_MS
from map_oxidize_tpu.obs.serve import (
    prometheus_text,
    sanitized_export_names,
)
from map_oxidize_tpu.obs.slo import (
    DEFAULT_RULES,
    MAX_INCIDENTS,
    SloEvaluator,
    SloRule,
    load_rules,
)
from map_oxidize_tpu.obs.timeseries import TimeSeriesRecorder
from map_oxidize_tpu.obs.trace import Tracer


def _write_corpus(path, lines=300):
    with open(path, "wb") as f:
        f.write(b"the quick brown fox jumps over the lazy dog\n" * lines)
    return str(path)


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _bundle(clock, capacity=64, interval_s=1.0):
    """A minimal Obs bundle with a fake-clock series recorder attached —
    the deterministic substrate every evaluator unit test drives by
    hand (no threads)."""
    obs = Obs(registry=MetricsRegistry(), tracer=Tracer(enabled=False))
    obs.tracer.wall_start = clock()
    obs.series = TimeSeriesRecorder(obs.registry, interval_s=interval_s,
                                    capacity=capacity, clock=clock)
    return obs


def _evaluator(obs, rules, clock, **kw):
    return SloEvaluator(obs, rules, clock=clock, **kw)


# --- rules ------------------------------------------------------------------


def test_rule_validation_rejects_bad_specs():
    with pytest.raises(ValueError):
        SloRule(name="x", metric="m", kind="bogus").validate()
    with pytest.raises(ValueError):
        SloRule(name="x", metric="m", op="==").validate()
    with pytest.raises(ValueError):
        SloRule(name="x", metric="m", scope="cluster").validate()
    with pytest.raises(ValueError):
        SloRule(name="x", metric="m", window_s=0).validate()
    with pytest.raises(ValueError):   # denominator is value-rule-only
        SloRule(name="x", metric="m", kind="delta",
                denominator="d").validate()
    with pytest.raises(ValueError):   # unknown field = a typo, not noise
        load_rules('[{"name": "x", "metric": "m", "treshold": 3}]')


def test_load_rules_extend_replace_override():
    assert [r.name for r in load_rules(None)] == \
        [d["name"] for d in DEFAULT_RULES]
    # a list EXTENDS the defaults
    got = load_rules('[{"name": "mine", "metric": "m"}]')
    assert "mine" in {r.name for r in got}
    assert len(got) == len(DEFAULT_RULES) + 1
    # an object with defaults:false REPLACES them
    got = load_rules('{"defaults": false, '
                     '"rules": [{"name": "only", "metric": "m"}]}')
    assert [r.name for r in got] == ["only"]
    # same-name rule OVERRIDES the default (tunable thresholds)
    got = load_rules('[{"name": "mfu-floor", "metric": "xprof/*/mfu_pct",'
                     ' "op": "<", "threshold": 40}]')
    floor = next(r for r in got if r.name == "mfu-floor")
    assert floor.threshold == 40
    assert len(got) == len(DEFAULT_RULES)


def test_load_rules_from_file(tmp_path):
    p = tmp_path / "rules.json"
    p.write_text(json.dumps([{"name": "f", "metric": "m"}]))
    assert "f" in {r.name for r in load_rules(str(p))}
    with pytest.raises(OSError):
        load_rules(str(tmp_path / "missing.json"))
    with pytest.raises(ValueError):
        JobConfig(input_path="x",
                  slo_rules=str(tmp_path / "missing.json")).validate()


# --- evaluation: kinds, debounce, arming, wraparound ------------------------


def test_value_rule_fires_and_resolves():
    clock = _Clock()
    obs = _bundle(clock)
    rule = SloRule(name="low", metric="work/level", op="<",
                   threshold=100).validate()
    ev = _evaluator(obs, [rule], clock)
    obs.registry.set("work/level", 5)
    obs.series.sample_once()
    events = ev.evaluate_once()
    assert [e["event"] for e in events] == ["fired"]
    assert events[0]["rule"] == "low" and events[0]["value"] == 5
    assert obs.registry.counters["alerts/fired"] == 1
    assert obs.registry.gauges["alerts/firing"] == 1
    # still firing: no duplicate event
    clock.t += 1
    obs.series.sample_once()
    assert ev.evaluate_once() == []
    # condition clears -> resolved
    obs.registry.set("work/level", 500)
    clock.t += 1
    obs.series.sample_once()
    events = ev.evaluate_once()
    assert [e["event"] for e in events] == ["resolved"]
    assert obs.registry.counters["alerts/resolved"] == 1
    assert obs.registry.gauges["alerts/firing"] == 0
    assert [e["event"] for e in ev.timeline] == ["fired", "resolved"]


def test_for_s_debounce_requires_sustained_condition():
    clock = _Clock()
    obs = _bundle(clock)
    rule = SloRule(name="slow", metric="g", op=">", threshold=10,
                   for_s=5.0).validate()
    ev = _evaluator(obs, [rule], clock)
    obs.registry.set("g", 50)
    obs.series.sample_once()
    assert ev.evaluate_once() == []          # pending, not firing
    clock.t += 2
    obs.series.sample_once()
    assert ev.evaluate_once() == []          # still inside for_s
    # a dip resets the debounce
    obs.registry.set("g", 1)
    clock.t += 1
    obs.series.sample_once()
    assert ev.evaluate_once() == []
    obs.registry.set("g", 50)
    clock.t += 1
    obs.series.sample_once()
    assert ev.evaluate_once() == []          # pending restarted
    clock.t += 6
    obs.series.sample_once()
    events = ev.evaluate_once()
    assert [e["event"] for e in events] == ["fired"]


def test_after_s_excludes_cold_start():
    clock = _Clock()
    obs = _bundle(clock)
    rule = SloRule(name="warmed", metric="g", op=">", threshold=0,
                   after_s=300).validate()
    ev = _evaluator(obs, [rule], clock)
    obs.registry.set("g", 5)
    obs.series.sample_once()
    assert ev.evaluate_once() == []          # job too young
    clock.t += 301
    obs.series.sample_once()
    assert [e["event"] for e in ev.evaluate_once()] == ["fired"]


def test_delta_rule_fires_then_resolves_as_window_passes():
    clock = _Clock()
    obs = _bundle(clock)
    rule = SloRule(name="grew", metric="c", kind="delta", op=">",
                   threshold=0, window_s=10).validate()
    ev = _evaluator(obs, [rule], clock)
    obs.registry.count("c", 1)
    obs.series.sample_once()
    clock.t += 5
    obs.registry.count("c", 3)
    obs.series.sample_once()
    # delta clamps to the oldest sample when the window reaches past it
    assert [e["event"] for e in ev.evaluate_once()] == ["fired"]
    # 20s later with no increments, the window holds no growth
    clock.t += 20
    obs.series.sample_once()
    assert [e["event"] for e in ev.evaluate_once()] == ["resolved"]


def test_delta_rule_fires_on_first_increment_of_lazy_counter():
    """Counters are created lazily on their first increment — and that
    FIRST increment is the whole signal for stall/warm-recompile rules:
    the tick before the series' first sample proves it was absent, so
    the baseline is 0 there, not the post-increment value."""
    clock = _Clock()
    obs = _bundle(clock)
    rule = SloRule(name="stall", metric="heartbeat/stalls",
                   kind="delta", op=">", threshold=0,
                   window_s=120).validate()
    ev = _evaluator(obs, [rule], clock)
    for _ in range(3):                       # ring has pre-stall history
        obs.series.sample_once()
        clock.t += 1
    assert ev.evaluate_once() == []          # series absent: nothing
    obs.registry.count("heartbeat/stalls", 1)   # THE first episode
    obs.series.sample_once()
    events = ev.evaluate_once()
    assert [e["event"] for e in events] == ["fired"]
    assert events[0]["value"] == 1.0


def test_rule_numeric_fields_type_checked_at_config_time():
    """The config-time validation promise: a string threshold must fail
    at load, not TypeError out of every evaluator tick."""
    with pytest.raises(ValueError):
        load_rules('[{"name": "x", "metric": "m", "threshold": "5000"}]')
    with pytest.raises(ValueError):
        load_rules('[{"name": "x", "metric": "m", "window_s": "60"}]')
    with pytest.raises(ValueError):
        JobConfig(input_path="x", slo_rules='[{"name": "x", "metric": '
                  '"m", "threshold": "5000"}]').validate()


def test_scope_filters_serve_rules_off_jobs():
    clock = _Clock()
    obs = _bundle(clock)
    rule = SloRule(name="s", metric="g", op=">", threshold=0,
                   scope="serve").validate()
    ev = _evaluator(obs, [rule], clock)
    obs.registry.set("g", 5)
    obs.series.sample_once()
    assert ev.evaluate_once() == []          # job scope: serve rule off
    obs.workload = "serve"
    assert [e["event"] for e in ev.evaluate_once()] == ["fired"]


def test_denominator_rule_dormant_until_budget_exists():
    clock = _Clock()
    obs = _bundle(clock)
    rule = SloRule(name="hbm", metric="hbm/live_bytes_*", op=">",
                   threshold=0.95, denominator="hbm/budget_bytes"
                   ).validate()
    ev = _evaluator(obs, [rule], clock)
    obs.registry.set("hbm/live_bytes_device0", 96)
    obs.series.sample_once()
    assert ev.evaluate_once() == []          # no budget gauge yet
    obs.registry.set("hbm/budget_bytes", 100)
    clock.t += 1
    obs.series.sample_once()
    events = ev.evaluate_once()
    assert [e["event"] for e in events] == ["fired"]
    assert events[0]["value"] == pytest.approx(0.96)


def test_rate_rule_correct_across_ring_wraparound():
    """A 4-slot ring wraps long before the window: the rate must clamp
    to the oldest SURVIVING sample and divide by the actual span — a
    wrapped ring must never fabricate a burst (or lose the signal)."""
    clock = _Clock()
    obs = _bundle(clock, capacity=4)
    rule = SloRule(name="rate", metric="c", kind="rate", op=">",
                   threshold=4.9, window_s=1000).validate()
    ev = _evaluator(obs, [rule], clock)
    for _i in range(10):                     # 5 units/s for 10s
        obs.registry.count("c", 5)
        obs.series.sample_once()
        clock.t += 1
    assert obs.series.samples_taken == 10    # ring wrapped (cap 4)
    export = obs.series.export()
    assert len(export["t_unix_s"]) == 4
    assert export["t_unix_s"] == sorted(export["t_unix_s"])
    events = ev.evaluate_once(now=clock.t)
    assert [e["event"] for e in events] == ["fired"]
    # observed rate ~5/s over the 3s surviving span, not an artifact of
    # the nominal 1000s window
    assert events[0]["value"] == pytest.approx(5.0)


def test_series_capacity_env_hook(tmp_path, monkeypatch):
    """MOXT_SERIES_CAPACITY shrinks the ring for long-serve wraparound
    simulation without a 17-minute soak."""
    monkeypatch.setenv("MOXT_SERIES_CAPACITY", "8")
    corpus = _write_corpus(tmp_path / "c.txt", lines=5)
    cfg = JobConfig(input_path=corpus, output_path="",
                    obs_sample_s=0.01).validate()
    obs = Obs.from_config(cfg)
    try:
        assert obs.series.capacity == 8
        for _ in range(20):
            obs.series.sample_once()
        assert len(obs.series.export()["t_unix_s"]) == 8
    finally:
        obs.finish(cfg, "wordcount")


# --- incidents --------------------------------------------------------------


def test_incident_bundle_and_cap(tmp_path):
    clock = _Clock()
    obs = _bundle(clock)
    corpus = _write_corpus(tmp_path / "c.txt", lines=3)
    cfg = JobConfig(input_path=corpus, output_path="").validate()
    rule = SloRule(name="inc/rule", metric="g", op=">",
                   threshold=0).validate()
    ev = _evaluator(obs, [rule], clock, config=cfg,
                    incident_dir=str(tmp_path / "incidents"))
    obs.registry.set("g", 7)
    obs.series.sample_once()
    assert [e["event"] for e in ev.evaluate_once()] == ["fired"]
    bundles = os.listdir(tmp_path / "incidents")
    assert len(bundles) == 1 and bundles[0].startswith("incident_")
    assert "inc_rule" in bundles[0]          # rule name path-sanitized
    doc = json.load(open(tmp_path / "incidents" / bundles[0]
                         / "incident.json"))
    assert doc["schema"] == "moxt-incident-v1"
    assert doc["rule"]["name"] == "inc/rule" and doc["value"] == 7
    assert doc["window"]["values"][-1] == 7
    assert doc["status"]["schema"] == "moxt-status-v1"
    # the cap: an alert storm stops writing bundles, keeps counting
    ev.incidents_written = MAX_INCIDENTS
    obs.registry.set("g", 0)
    clock.t += 1
    obs.series.sample_once()
    ev.evaluate_once()                       # resolved
    obs.registry.set("g", 9)
    clock.t += 1
    obs.series.sample_once()
    assert [e["event"] for e in ev.evaluate_once()] == ["fired"]
    assert ev.fired_total == 2
    assert len(os.listdir(tmp_path / "incidents")) == 1


# --- announcement + export --------------------------------------------------


def test_alert_lines_ride_the_heartbeat():
    clock = _Clock()
    obs = _bundle(clock)
    lines = []
    obs.heartbeat = Heartbeat(interval_s=10.0, clock=lambda: clock.t,
                              emit=lines.append)
    rule = SloRule(name="loud", metric="g", op=">", threshold=0).validate()
    ev = _evaluator(obs, [rule], clock)
    obs.registry.set("g", 3)
    obs.series.sample_once()
    ev.evaluate_once()
    obs.registry.set("g", 0)
    clock.t += 1
    obs.series.sample_once()
    ev.evaluate_once()
    assert any("[alert] FIRING loud" in line for line in lines)
    assert any("[alert] resolved loud" in line for line in lines)


def test_alerts_export_and_top_panel():
    from map_oxidize_tpu.obs.cli import render_alerts

    clock = _Clock()
    obs = _bundle(clock)
    rules = [SloRule(name="a", metric="g", op=">", threshold=1).validate(),
             SloRule(name="b", metric="h", op=">", threshold=1,
                     severity="critical").validate()]
    ev = _evaluator(obs, rules, clock)
    obs.registry.set("g", 5)
    obs.registry.set("h", 5)
    obs.series.sample_once()
    ev.evaluate_once()
    obs.registry.set("h", 0)
    clock.t += 1
    obs.series.sample_once()
    ev.evaluate_once()
    doc = ev.export()
    assert doc["schema"] == "moxt-alerts-v1"
    assert doc["counts"] == {"fired": 2, "resolved": 1, "incidents": 0}
    assert [f["rule"] for f in doc["firing"]] == ["a"]
    assert [r["rule"] for r in doc["resolved"]] == ["b"]
    assert len(doc["rules"]) == 2 and doc["rules"][0]["states"]
    frame = render_alerts(doc)
    assert "1 firing" in frame
    assert "!! WARNING  a: g=5" in frame
    assert "ok resolved b: h" in frame


# --- ledger gate + trend forensics ------------------------------------------


def _entry(ts, metrics, workload="wc", phases=None):
    return {"ts_unix_s": ts, "version": "1", "config_hash": "cfg",
            "workload": workload, "corpus_bytes": 1000, "n_processes": 1,
            "phases_s": dict(phases or {"map+reduce": 1.0}),
            "metrics": dict(metrics)}


def test_ledger_diff_flags_alert_firing():
    from map_oxidize_tpu.obs import ledger

    a = _entry(1, {"alerts/fired": 0})
    b = _entry(2, {"alerts/fired": 2})
    diff = ledger.diff_entries(a, b)
    assert any("SLO alerts fired" in r for r in diff["regressions"])
    # equal counts: no flag
    diff = ledger.diff_entries(b, _entry(3, {"alerts/fired": 2}))
    assert not diff["regressions"]


def test_trend_movers_rank_injected_regression_first():
    from map_oxidize_tpu.obs import trend

    base = {"rate": 1000.0, "comms/psum/fit/bytes": 1_000_000,
            "records_in": 5000}
    entries = [_entry(i, base, phases={"map+reduce": 1.0})
               for i in range(1, 4)]
    bad = dict(base, **{"comms/psum/fit/bytes": 10_000_000})
    entries.append(_entry(4, bad, phases={"map+reduce": 1.05}))
    mv = trend.movers(entries)
    assert mv[0]["name"] == "comms/psum/fit/bytes"
    assert mv[0]["rank"] == 1 and mv[0]["pct"] == pytest.approx(900.0)
    assert mv[0]["direction"] == "moved"
    steps = trend.detect_steps(trend.trajectories(entries))
    assert steps and steps[0]["name"] == "comms/psum/fit/bytes"
    assert steps[0]["index"] == 3
    # a rate DROP is annotated as the regression direction
    slow = [_entry(i, {"rate": 1000.0}) for i in range(1, 4)]
    slow.append(_entry(4, {"rate": 500.0}))
    mv = trend.movers(slow)
    assert mv[0]["name"] == "rate" and mv[0]["direction"] == "regressed"


def test_trend_cli_json_roundtrip(tmp_path, capsys):
    from map_oxidize_tpu.obs import ledger
    from map_oxidize_tpu.obs.cli import obs_main

    ldir = tmp_path / "ledger"
    base = {"rate": 100.0, "spill/rows": 10}
    for i in range(1, 4):
        ledger.append(str(ldir), _entry(i, base))
    ledger.append(str(ldir), _entry(4, dict(base, **{"spill/rows": 900})))
    rc = obs_main(["trend", "--ledger-dir", str(ldir), "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["n_entries"] == 4 and doc["workload"] == "wc"
    assert doc["movers"][0]["name"] == "spill/rows"
    assert doc["movers"][0]["direction"] == "regressed"
    # human-readable form names the mover too
    rc = obs_main(["trend", "--ledger-dir", str(ldir)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "spill/rows" in out and "movers" in out
    # too little history is a named refusal, not a crash
    rc = obs_main(["trend", "--ledger-dir", str(tmp_path / "empty")])
    assert rc == 2


def test_trend_bench_rounds(tmp_path, capsys):
    from map_oxidize_tpu.obs import trend
    from map_oxidize_tpu.obs.cli import obs_main

    for i, kv in enumerate([(10.0, 1.0), (11.0, 1.1), (11.5, 0.4)], 1):
        (tmp_path / f"BENCH_r{i:02d}.json").write_text(json.dumps(
            {"parsed": {"value": kv[0],
                        "workloads": {"distinct_256mb": kv[1]}}}))
    entries = trend.bench_rounds(
        sorted(str(p) for p in tmp_path.glob("BENCH_r*.json")))
    assert len(entries) == 3
    mv = trend.movers(entries)
    assert mv[0]["name"] == "workloads/distinct_256mb/vs_baseline"
    assert mv[0]["direction"] == "regressed"
    rc = obs_main(["trend", "--bench",
                   str(tmp_path / "BENCH_r*.json")])
    assert rc == 0
    assert "distinct_256mb" in capsys.readouterr().out


# --- prometheus export ------------------------------------------------------


def test_sanitized_name_collision_guard():
    entries = [("counter", "comms/a/b/bytes"), ("gauge", "comms/a_b/bytes"),
               ("counter", "x+y"), ("counter", "x-y")]
    names = sanitized_export_names(entries)
    assert len(set(names.values())) == len(entries)
    # deterministic: same input, same mapping
    assert names == sanitized_export_names(list(reversed(entries)))
    # the first taker (sorted) keeps the clean spelling
    assert names[("counter", "comms/a/b/bytes")] == "moxt_comms_a_b_bytes"
    assert names[("gauge", "comms/a_b/bytes")].startswith(
        "moxt_comms_a_b_bytes_x")


def test_prometheus_names_sticky_across_scrapes():
    """A colliding key created AFTER a series was first exported must
    not steal (or rename) the existing series — the mapping is sticky
    for the registry's lifetime."""
    reg = MetricsRegistry()
    reg.count("comms/a_b/bytes", 5)          # sorts AFTER comms/a/b
    first = prometheus_text(reg)
    assert "moxt_comms_a_b_bytes 5" in first
    reg.count("comms/a/b/bytes", 7)          # the would-be name thief
    second = prometheus_text(reg)
    assert "moxt_comms_a_b_bytes 5" in second     # original keeps it
    assert "moxt_comms_a_b_bytes_x" in second     # newcomer suffixed
    # and stays stable on every later scrape
    assert prometheus_text(reg) == second


_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.eE+inf-]+$")


def _parse_prom(text: str) -> dict:
    """Minimal Prometheus text-format parse check: every non-comment
    line matches the exposition grammar; returns {series_name_with_
    labels: value}."""
    out = {}
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        assert _PROM_LINE.match(line), f"bad exposition line: {line!r}"
        key, val = line.rsplit(" ", 1)
        out[key] = float(val) if val != "+Inf" else float("inf")
    return out


def test_prometheus_histogram_buckets_parse_and_cumulate():
    reg = MetricsRegistry()
    for v in (3.0, 30.0, 300.0, 3000.0, 10_000_000.0):
        reg.observe("serve/queue_wait_ms", v, buckets=LATENCY_BUCKETS_MS)
    text = prometheus_text(reg)
    series = _parse_prom(text)
    bucket_re = re.compile(
        r'^moxt_serve_queue_wait_ms_hist_bucket\{le="([^"]+)"\}$')
    buckets = []
    for key, val in series.items():
        m = bucket_re.match(key)
        if m:
            le = float("inf") if m.group(1) == "+Inf" else float(m.group(1))
            buckets.append((le, val))
    buckets.sort()
    assert len(buckets) == len(LATENCY_BUCKETS_MS) + 1
    # cumulative + monotone, +Inf == count, sum exact
    counts = [c for _le, c in buckets]
    assert counts == sorted(counts)
    assert buckets[-1] == (float("inf"), 5.0)
    assert buckets[0] == (5.0, 1.0)          # the 3ms observation
    assert series["moxt_serve_queue_wait_ms_hist_count"] == 5.0
    assert series["moxt_serve_queue_wait_ms_hist_sum"] == pytest.approx(
        10_003_333.0)
    # the summary quantiles still export beside the histogram
    assert 'moxt_serve_queue_wait_ms{quantile="0.5"}' in series
    # per-process labels compose with the le label
    labeled = prometheus_text(reg, {"process": "1"})
    assert 'le="+Inf",process="1"' in labeled


# --- serve: per-job latency histograms --------------------------------------


def _instant_runner(compiles=0):
    def run(config, workload, on_obs):
        obs = Obs.from_config(config)
        on_obs(obs)
        with obs.recording(config, workload):
            pass
        obs.finish(config, workload)

        class _R:
            metrics = {"records_in": 1,
                       "compile/total_compiles": compiles}

        return _R()

    return run


def test_scheduler_records_latency_histograms_and_warm_compiles(tmp_path):
    from map_oxidize_tpu.serve.scheduler import Scheduler

    corpus = _write_corpus(tmp_path / "c.txt", lines=5)
    cfg = ServeConfig(spool_dir=str(tmp_path / "spool"), workers=1,
                      job_sample_s=0.0, drain_timeout_s=5.0).validate()
    sched = Scheduler(cfg, runner=_instant_runner(compiles=2))
    reg = MetricsRegistry()
    sched.server_registry = reg
    sched.start()
    try:
        jobs = [sched.submit("wordcount", corpus) for _ in range(3)]
        for j in jobs:
            assert sched.wait(j.id, timeout=30).state == "done"
    finally:
        sched.shutdown()
    with reg._lock:
        hq = reg.histograms["serve/queue_wait_ms"]
        ha = reg.histograms["serve/admission_wait_ms"]
        hr = reg.histograms["serve/run_wall_ms"]
    assert hq.count == ha.count == hr.count == 3
    assert hq.buckets == tuple(LATENCY_BUCKETS_MS)
    assert hq.cumulative_buckets()[-1] == (float("inf"), 3)
    assert reg.counters["serve/jobs_total"] == 3
    assert reg.counters["serve/jobs_done"] == 3
    # warm-compile counter: job 1 is the cold compile (not counted);
    # jobs 2-3 "recompiled" 2 programs each in this injected runner
    assert reg.counters["serve/warm_compiles"] == 4
    # the bucketed export parses as a real Prometheus histogram
    series = _parse_prom(prometheus_text(reg))
    assert series["moxt_serve_run_wall_ms_hist_count"] == 3.0
    # /jobs rows carry the queue-wait evidence
    row = sched.job_doc(jobs[0].id)
    assert row["queue_wait_s"] >= 0


# --- end-to-end: injected rule on a live job --------------------------------


def test_injected_rule_fires_and_resolves_live(tmp_path):
    """The acceptance path: an injected rule fires mid-run — visible at
    /alerts, in the heartbeat output, in the obs top panel, and as an
    incident bundle — then RESOLVES when the condition clears, and the
    exported timeline carries both transitions."""
    from map_oxidize_tpu.obs.cli import render_alerts

    corpus = _write_corpus(tmp_path / "c.txt", lines=50)
    rule = json.dumps({"defaults": False, "rules": [
        {"name": "rows-floor", "metric": "progress/rows", "op": "<",
         "threshold": 50, "kind": "value"}]})
    cfg = JobConfig(input_path=corpus, output_path="",
                    obs_port=0, obs_sample_s=0.02, slo_rules=rule,
                    metrics_out=str(tmp_path / "metrics.json"),
                    crash_dir=str(tmp_path / "crash")).validate()
    obs = Obs.from_config(cfg)

    def _get(ep):
        return json.loads(urllib.request.urlopen(
            f"{obs.server.url}{ep}", timeout=5).read())

    deadline = time.monotonic() + 30
    with obs.recording(cfg, "wordcount"):
        doc = None
        while time.monotonic() < deadline:   # rows=0 < 50: must fire
            doc = _get("/alerts")
            if doc["firing"]:
                break
            time.sleep(0.01)
        assert doc["firing"] and doc["firing"][0]["rule"] == "rows-floor"
        assert "rows-floor" in render_alerts(doc)
        assert "/alerts" in _get("/")["endpoints"]
        obs.heartbeat.update(rows=500)       # condition clears
        while time.monotonic() < deadline:
            doc = _get("/alerts")
            if not doc["firing"] and doc["counts"]["resolved"]:
                break
            time.sleep(0.01)
        assert not doc["firing"] and doc["counts"]["resolved"] == 1
    obs.finish(cfg, "wordcount")
    out = json.load(open(tmp_path / "metrics.json"))
    events = [e["event"] for e in out["alerts"]["timeline"]]
    assert events == ["fired", "resolved"]
    assert out["counters"]["alerts/fired"] == 1
    # incident bundle defaulted into the crash dir
    assert any(d.startswith("incident_")
               for d in os.listdir(tmp_path / "crash"))


def test_default_rules_silent_on_healthy_run(tmp_path):
    from map_oxidize_tpu.runtime import run_job

    corpus = _write_corpus(tmp_path / "c.txt", lines=200)
    cfg = JobConfig(input_path=corpus,
                    output_path=str(tmp_path / "out.txt"),
                    num_shards=1, num_chunks=4, obs_sample_s=0.01,
                    metrics_out=str(tmp_path / "m.json")).validate()
    run_job(cfg, "wordcount")
    doc = json.load(open(tmp_path / "m.json"))
    assert doc["alerts"]["counts"]["fired"] == 0
    assert doc["alerts"]["timeline"] == []
    assert "alerts/fired" not in doc["counters"]


def test_crash_bundle_carries_alert_timeline(tmp_path):
    """An abort mid-alert lands the firing state in the flight-recorder
    bundle — which SLOs were red when the job died."""
    corpus = _write_corpus(tmp_path / "c.txt", lines=5)
    rule = json.dumps({"defaults": False, "rules": [
        {"name": "always", "metric": "boom/level", "op": ">",
         "threshold": 0}]})
    cfg = JobConfig(input_path=corpus, output_path="",
                    obs_sample_s=0.02, slo_rules=rule,
                    crash_dir=str(tmp_path / "crash")).validate()
    obs = Obs.from_config(cfg)
    with pytest.raises(RuntimeError):
        with obs.recording(cfg, "wordcount"):
            obs.registry.set("boom/level", 9)
            deadline = time.monotonic() + 20
            while obs.alerts.fired_total == 0 \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            assert obs.alerts.fired_total == 1
            raise RuntimeError("abort with an alert firing")
    bundles = [d for d in os.listdir(tmp_path / "crash")
               if d.startswith("crash_")]
    assert len(bundles) == 1
    doc = json.load(open(tmp_path / "crash" / bundles[0]
                         / "metrics.json"))
    assert doc["alerts"]["counts"]["fired"] == 1
    assert doc["alerts"]["firing"][0]["rule"] == "always"
