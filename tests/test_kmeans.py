"""k-means workload (BASELINE config #5): streamed engine result == NumPy
oracle, device-resident path == oracle, conservation, empty clusters."""

import numpy as np
import pytest

from map_oxidize_tpu.api import SumReducer
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.runtime import run_job
from map_oxidize_tpu.runtime.driver import make_engine, run_kmeans_job
from map_oxidize_tpu.workloads.kmeans import (
    KMeansMapper,
    assign_points,
    iter_point_chunks,
    kmeans_fit_device,
    kmeans_iteration,
    kmeans_model,
)


def _blobs(rng, n=4000, d=8, k=5):
    centers = rng.normal(0, 10, size=(k, d)).astype(np.float32)
    pts = (centers[rng.integers(0, k, size=n)]
           + rng.normal(0, 0.5, size=(n, d))).astype(np.float32)
    return pts, centers


def test_streamed_iteration_matches_oracle(rng):
    pts, init = _blobs(rng)
    cfg = JobConfig(input_path="unused", output_path="", backend="cpu",
                    batch_size=512, metrics=False)
    engine = make_engine(cfg, SumReducer(), value_shape=(pts.shape[1] + 1,),
                         value_dtype=np.float32)
    chunks = [pts[i:i + 700] for i in range(0, pts.shape[0], 700)]
    ours = kmeans_iteration(engine, init, chunks)
    want = kmeans_model(pts, init)
    np.testing.assert_allclose(ours, want, rtol=1e-4, atol=1e-4)


def test_device_fit_matches_oracle(rng):
    pts, init = _blobs(rng, n=2000, d=4, k=3)
    got = kmeans_fit_device(pts, init, iters=1)
    want = kmeans_model(pts, init)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_device_fit_multi_iter_matches_repeated_oracle(rng):
    pts, init = _blobs(rng, n=1500, d=4, k=4)
    got = kmeans_fit_device(pts, init, iters=3)
    want = init
    for _ in range(3):
        want = kmeans_model(pts, want)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_empty_centroid_keeps_position(rng):
    pts = np.ones((50, 2), np.float32)          # all points at (1, 1)
    init = np.array([[1.0, 1.0], [99.0, 99.0]], np.float32)
    cfg = JobConfig(input_path="unused", output_path="", backend="cpu",
                    metrics=False)
    engine = make_engine(cfg, SumReducer(), value_shape=(3,),
                         value_dtype=np.float32)
    new = kmeans_iteration(engine, init, [pts])
    np.testing.assert_allclose(new[0], [1.0, 1.0])
    np.testing.assert_allclose(new[1], [99.0, 99.0])  # empty: unchanged


def test_mapper_emits_partial_sums(rng):
    pts, init = _blobs(rng, n=300, d=3, k=4)
    out = KMeansMapper(init).map_chunk(pts)
    assert out.records_in == 300
    # counts column conserves points
    assert int(round(float(out.values[:, -1].sum()))) == 300
    # each emitted row matches a direct per-centroid sum
    cid = assign_points(pts, init)
    for hi, lo, row in zip(out.hi, out.lo, out.values):
        assert hi == 0
        m = cid == int(lo)
        np.testing.assert_allclose(row[:-1], pts[m].sum(0), rtol=1e-4)
        assert int(round(float(row[-1]))) == int(m.sum())


def test_run_kmeans_job_end_to_end(tmp_path, rng):
    pts, _ = _blobs(rng, n=3000, d=6, k=4)
    inp = tmp_path / "points.npy"
    np.save(inp, pts)
    outp = tmp_path / "centroids.npy"
    cfg = JobConfig(input_path=str(inp), output_path=str(outp),
                    backend="cpu", kmeans_k=4, kmeans_iters=2,
                    chunk_bytes=4096, metrics=False)
    res = run_job(cfg, "kmeans")
    want = np.asarray(pts[:4], np.float32)
    for _ in range(2):
        want = kmeans_model(pts, want)
    np.testing.assert_allclose(res.centroids, want, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.load(outp), res.centroids)


def test_conservation_violation_raises(rng):
    # a mapper bug that miscounts points must be caught by the count check
    pts, init = _blobs(rng, n=100, d=2, k=2)
    cfg = JobConfig(input_path="unused", output_path="", backend="cpu",
                    metrics=False)

    class Lossy(KMeansMapper):
        def map_chunk(self, points):
            out = super().map_chunk(points)
            out.records_in += 7  # claim more points than were summed
            return out

    engine = make_engine(cfg, SumReducer(), value_shape=(3,),
                         value_dtype=np.float32)
    with pytest.raises(RuntimeError, match="conservation"):
        kmeans_iteration(engine, init, [pts], mapper=Lossy(init))


def test_sharded_fit_matches_oracle(rng):
    """Multi-chip HBM-resident k-means on the 8-device virtual mesh: one
    psum per iteration, padding rows carry zero weight."""
    from map_oxidize_tpu.parallel.kmeans import kmeans_fit_sharded

    pts, init = _blobs(rng, n=2005, d=4, k=3)  # 2005 % 8 != 0: pad rows live
    got = kmeans_fit_sharded(pts, init, iters=2, num_shards=8, backend="cpu")
    want = init
    for _ in range(2):
        want = kmeans_model(pts, want)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_run_kmeans_job_device_paths(tmp_path, rng):
    """mapper='device' routes to the HBM-resident fit (single) and the
    sharded psum fit (mesh); both match the streamed default."""
    pts, _ = _blobs(rng, n=1600, d=5, k=4)
    inp = tmp_path / "points.npy"
    np.save(inp, pts)

    def run(mapper, shards):
        cfg = JobConfig(input_path=str(inp), output_path="", backend="cpu",
                        kmeans_k=4, kmeans_iters=2, chunk_bytes=4096,
                        mapper=mapper, num_shards=shards, metrics=False)
        return run_job(cfg, "kmeans").centroids

    streamed = run("native", 1)  # 'native' pins the streaming path
    dev1 = run("device", 1)
    dev8 = run("device", 8)
    np.testing.assert_allclose(dev1, streamed, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(dev8, streamed, rtol=1e-3, atol=1e-3)
    # 'auto' resolves to the HBM-resident fit for in-memory points — the
    # measured winner (benchmarks/RESULTS.md) — bit-identically
    assert run("auto", 1).tobytes() == dev1.tobytes()


# --- checkpoint/resume (round-3: closes the last warn-and-run hole) -------

def _ck_cfg(inp, iters, ckdir, **kw):
    # mapper='native' pins the streaming path (the checkpoint tests below
    # that target the device paths override it); 'auto' would resolve to
    # the device fit for these in-memory point sets
    base = dict(input_path=str(inp), output_path="", backend="cpu",
                kmeans_k=3, kmeans_iters=iters, chunk_bytes=4096,
                checkpoint_dir=ckdir, metrics=False, mapper="native")
    base.update(kw)
    return JobConfig(**base)


def test_kmeans_checkpoint_resume_streamed(tmp_path, rng, monkeypatch):
    """A 2-iteration run's snapshot resumes a 5-iteration job at iteration
    2 (only 3 more run) and the result is byte-identical to an
    uninterrupted checkpointed 5-iteration run."""
    import os

    pts, _ = _blobs(rng, n=1200, d=4, k=3)
    inp = tmp_path / "p.npy"
    np.save(inp, pts)

    want = run_job(_ck_cfg(inp, 5, str(tmp_path / "ck_ref")),
                   "kmeans").centroids

    ck = str(tmp_path / "ck")
    run_job(_ck_cfg(inp, 2, ck, keep_intermediates=True), "kmeans")
    assert os.path.isfile(os.path.join(ck, "snapshot.npz"))

    import map_oxidize_tpu.workloads.kmeans as wk

    calls = {"n": 0}
    orig = wk.kmeans_iteration

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(wk, "kmeans_iteration", counting)
    got = run_job(_ck_cfg(inp, 5, ck), "kmeans").centroids
    assert calls["n"] == 3, "resume must skip the 2 snapshotted iterations"
    assert got.tobytes() == want.tobytes()
    assert not os.path.isdir(ck)  # success removes the spill by default


def test_kmeans_checkpoint_resume_device(tmp_path, rng):
    """Device (HBM-resident) path: per-iteration snapshots via on_iter;
    interrupted-at-2 then resumed-to-4 equals uninterrupted checkpointed 4."""
    import os

    pts, _ = _blobs(rng, n=900, d=4, k=3)
    inp = tmp_path / "p.npy"
    np.save(inp, pts)

    want = run_job(_ck_cfg(inp, 4, str(tmp_path / "ck_ref"),
                           mapper="device", num_shards=1),
                   "kmeans").centroids
    ck = str(tmp_path / "ck")
    run_job(_ck_cfg(inp, 2, ck, mapper="device", num_shards=1,
                    keep_intermediates=True), "kmeans")
    got = run_job(_ck_cfg(inp, 4, ck, mapper="device", num_shards=1),
                  "kmeans").centroids
    assert got.tobytes() == want.tobytes()
    assert not os.path.isdir(ck)


def test_kmeans_checkpoint_identity_mismatch_discards(tmp_path, rng):
    """A snapshot from a different k (or mode) must be discarded, not
    resumed: the k=4 run starts fresh and matches a no-checkpoint run."""
    pts, _ = _blobs(rng, n=800, d=4, k=4)
    inp = tmp_path / "p.npy"
    np.save(inp, pts)
    ck = str(tmp_path / "ck")

    run_job(_ck_cfg(inp, 2, ck, keep_intermediates=True), "kmeans")  # k=3
    got = run_job(_ck_cfg(inp, 2, ck, kmeans_k=4), "kmeans").centroids
    want = run_job(_ck_cfg(inp, 2, None, kmeans_k=4), "kmeans").centroids
    assert got.tobytes() == want.tobytes()


def test_kmeans_snapshot_covers_all_requested_iters(tmp_path, rng):
    """Resume where the snapshot already has >= kmeans_iters iterations:
    no iteration runs, the snapshot centroids are the result, and the
    zero-work run must NOT delete the training state it merely read
    (code-review finding, round 3)."""
    import os

    pts, _ = _blobs(rng, n=600, d=3, k=3)
    inp = tmp_path / "p.npy"
    np.save(inp, pts)
    ck = str(tmp_path / "ck")
    want = run_job(_ck_cfg(inp, 3, ck, keep_intermediates=True),
                   "kmeans").centroids
    got = run_job(_ck_cfg(inp, 2, ck), "kmeans").centroids  # 2 < 3 done
    assert got.tobytes() == want.tobytes()
    assert os.path.isfile(os.path.join(ck, "snapshot.npz")), \
        "a zero-work read must preserve the continue-training snapshot"
    # ...and the preserved state still resumes a longer job, then cleans up
    run_job(_ck_cfg(inp, 5, ck), "kmeans")
    assert not os.path.isdir(ck)


def test_kmeans_explicit_init_invalidates_foreign_snapshot(tmp_path, rng):
    """A snapshot from a different initial-centroid trajectory must be
    discarded, not silently resumed over the caller's init (code-review
    finding, round 3)."""
    pts, _ = _blobs(rng, n=500, d=3, k=3)
    inp = tmp_path / "p.npy"
    np.save(inp, pts)
    ck = str(tmp_path / "ck")
    init_a = np.asarray(pts[:3], np.float32)
    init_b = np.asarray(pts[10:13], np.float32) + 1.0

    run_kmeans_job(_ck_cfg(inp, 2, ck, keep_intermediates=True),
                   centroids=init_a)
    got = run_kmeans_job(_ck_cfg(inp, 2, ck), centroids=init_b).centroids
    want = run_kmeans_job(_ck_cfg(inp, 2, None), centroids=init_b).centroids
    assert got.tobytes() == want.tobytes()


def test_kmeans_checkpoint_resume_sharded(tmp_path, rng):
    """Sharded HBM-resident path (kmeans_fit_sharded + on_iter): resume on
    the 8-device virtual mesh is byte-identical to an uninterrupted
    checkpointed run, and metrics count only the iterations actually run."""
    import os

    pts, _ = _blobs(rng, n=1001, d=4, k=3)  # odd n: pad rows live
    inp = tmp_path / "p.npy"
    np.save(inp, pts)

    kw = dict(mapper="device", num_shards=8)
    want = run_job(_ck_cfg(inp, 4, str(tmp_path / "ck_ref"), **kw),
                   "kmeans").centroids
    ck = str(tmp_path / "ck")
    run_job(_ck_cfg(inp, 2, ck, keep_intermediates=True, **kw), "kmeans")
    res = run_job(_ck_cfg(inp, 4, ck, **kw), "kmeans")
    assert res.centroids.tobytes() == want.tobytes()
    assert not os.path.isdir(ck)


def test_kmeans_resume_metrics_count_only_ran_iters(tmp_path, rng):
    """records_in/iters after a resume: throughput numerators must not be
    inflated by snapshotted iterations (code-review finding, round 3)."""
    pts, _ = _blobs(rng, n=500, d=3, k=3)
    inp = tmp_path / "p.npy"
    np.save(inp, pts)
    ck = str(tmp_path / "ck")

    run_job(_ck_cfg(inp, 2, ck, keep_intermediates=True), "kmeans")
    cfg = _ck_cfg(inp, 5, ck)
    cfg.metrics = True
    res = run_job(cfg, "kmeans")
    assert res.metrics["records_in"] == 500 * 3   # only 3 iterations ran
    assert res.metrics["iters"] == 5              # result represents 5
    assert res.metrics["resumed_iters"] == 2


def test_auto_mapper_fit_cap(tmp_path, rng, monkeypatch):
    """'auto' resolves by the device-fit cap: under it -> HBM-resident fit,
    over it -> streamed (the only option at beyond-memory scale)."""
    import map_oxidize_tpu.runtime.driver as drv

    pts, _ = _blobs(rng, n=500, d=4, k=3)
    inp = tmp_path / "p.npy"
    np.save(inp, pts)
    cfg = JobConfig(input_path=str(inp), output_path="", backend="cpu",
                    kmeans_k=3, kmeans_iters=1, metrics=True)
    dev = run_job(cfg, "kmeans")
    monkeypatch.setattr(drv, "_KMEANS_DEVICE_FIT_BYTES", 100)  # force stream
    streamed = run_job(cfg, "kmeans")
    np.testing.assert_allclose(streamed.centroids, dev.centroids,
                               rtol=1e-3, atol=1e-3)


def test_auto_resume_adopts_snapshot_mode(tmp_path, rng):
    """A snapshot cut from the STREAMED path must resume streamed even
    when mapper='auto' would heuristically pick the device fit — resume
    continues the trajectory it was cut from instead of discarding it."""
    import os

    pts, _ = _blobs(rng, n=900, d=4, k=3)
    inp = tmp_path / "p.npy"
    np.save(inp, pts)
    want = run_job(_ck_cfg(inp, 4, str(tmp_path / "ck_ref")),
                   "kmeans").centroids  # streamed, checkpointed, 4 iters

    ck = str(tmp_path / "ck")
    run_job(_ck_cfg(inp, 2, ck, keep_intermediates=True), "kmeans")
    res = run_job(_ck_cfg(inp, 4, ck, mapper="auto"), "kmeans")
    assert res.metrics.get("resumed_iters") == 2, \
        "auto must adopt the snapshot's stream mode, not invalidate it"
    assert res.centroids.tobytes() == want.tobytes()
    assert not os.path.isdir(ck)


def test_bf16_precision_convergence_parity(tmp_path, rng):
    """--kmeans-precision bf16 (VERDICT r4 #6): native single-pass bf16
    matmuls must (a) actually change the numerics (the knob is real — on
    CPU XLA emulates the bf16 operand rounding), (b) stay within bf16
    rounding of the f32-HIGHEST trajectory over 24 iterations on
    clustered data (drift bound ~bf16 epsilon relative to the data
    scale), and (c) land on the same cluster structure as the NumPy
    oracle.  Sharded and single-device bf16 share assign_and_sum, so one
    drift gate covers both formulations."""
    pts, centers = _blobs(rng, n=3000, d=16, k=8)
    # true centers as the first k rows (= the driver's init): arbitrary-
    # point init creates sliver Voronoi cells whose near-tie assignment
    # flips compound chaotically across iterations — the same reason the
    # round-4 bench parity gate seeds this way (bench.py kmeans section).
    # The knob's drift bound is about ROUNDING, not k-means instability.
    pts[:8] = centers
    inp = tmp_path / "p.npy"
    np.save(inp, pts)

    def run(precision, shards=1):
        cfg = JobConfig(input_path=str(inp), output_path="", backend="cpu",
                        kmeans_k=8, kmeans_iters=24, mapper="device",
                        num_shards=shards, metrics=False,
                        kmeans_precision=precision)
        return run_kmeans_job(cfg).centroids

    f32 = run("highest")
    b16 = run("bf16")
    assert b16.tobytes() != f32.tobytes(), \
        "bf16 mode produced bit-identical results; the knob is a no-op"
    scale = float(np.abs(pts).max())
    # bf16 has ~8 mantissa bits (eps = 2^-8); converged centroids are
    # cluster means, so per-coordinate drift stays within a few eps of
    # the data scale
    drift = float(np.abs(b16 - f32).max())
    assert drift <= 4 * 2.0**-8 * scale, \
        f"bf16 drift {drift} vs f32 exceeds the rounding bound"
    want = pts[:8].copy()
    for _ in range(24):
        want = kmeans_model(pts, want)
    np.testing.assert_allclose(b16, want, rtol=0.05, atol=0.05 * scale)

    b16_sharded = run("bf16", shards=8)
    np.testing.assert_allclose(b16_sharded, b16, rtol=1e-4, atol=1e-4)

    with pytest.raises(ValueError, match="kmeans_precision"):
        JobConfig(input_path=str(inp), output_path="",
                  kmeans_precision="f64").validate()


def test_pallas_fused_kernel_parity(rng):
    """The fused Pallas assignment+partial-sum kernel (interpret mode on
    CPU) must reproduce assign_and_sum exactly in structure: equal
    counts, close sums, both precisions, with and without weights, and
    with tail padding exercised (n not a TILE_N multiple)."""
    import jax.numpy as jnp

    from map_oxidize_tpu.ops.kmeans_kernel import TILE_N, fused_assign_sum
    from map_oxidize_tpu.workloads.kmeans import assign_and_sum

    n, d, k = TILE_N + 777, 16, 32  # forces the padding mask path
    p = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    c = jnp.asarray(rng.normal(size=(k, d)).astype(np.float32))
    w = jnp.asarray((rng.random(n) > 0.3).astype(np.float32))
    for prec in ("highest", "bf16"):
        for weights in (None, w):
            s1, c1 = fused_assign_sum(p, c, k, prec, w=weights,
                                      interpret=True)
            s2, c2 = assign_and_sum(p, c, k, prec, w=weights)
            np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))
            np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                                       rtol=1e-5, atol=1e-4)


def test_streamed_device_fit_matches_oracle(tmp_path, rng):
    """Round-5 (verdict r4 #5): the device-streamed fit (chunks through
    the chip, one dispatch per chunk, update folded into the last) must
    match the repeated NumPy oracle across chunking shapes — multi-chunk
    with a padded tail, and the single-chunk first==last fusion."""
    from map_oxidize_tpu.workloads.kmeans import kmeans_fit_streamed_device

    pts, centers = _blobs(rng, n=5000, d=8, k=5)
    pts[:5] = centers
    path = tmp_path / "p.npy"
    np.save(path, pts)
    init = pts[:5].copy()
    want = init
    for _ in range(3):
        want = kmeans_model(pts, want)
    for chunk_rows in (1024, 8192):
        got = kmeans_fit_streamed_device(str(path), init, iters=3,
                                         chunk_rows=chunk_rows)
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)
    gb = kmeans_fit_streamed_device(str(path), init, iters=3,
                                    chunk_rows=1024, precision="bf16")
    scale = float(np.abs(pts).max())
    assert float(np.abs(gb - want).max()) <= 4 * 2.0**-8 * scale


def test_auto_routes_beyond_fit_to_streamed_device(tmp_path, rng,
                                                   monkeypatch):
    """mapper='auto' with points past the device-fit budget must take the
    device-streamed route (r4 the streamed fallback was host-assign),
    produce oracle-correct centroids, record feed_s, and resume from its
    own checkpoints under the 'stream_device' mode identity."""
    import map_oxidize_tpu.runtime.driver as drv

    pts, centers = _blobs(rng, n=4000, d=6, k=3)
    pts[:3] = centers
    inp = tmp_path / "p.npy"
    np.save(inp, pts)
    monkeypatch.setattr(drv, "_kmeans_device_fit_bytes", lambda b: 1)

    cfg = JobConfig(input_path=str(inp), output_path="", backend="cpu",
                    kmeans_k=3, kmeans_iters=2, mapper="auto",
                    metrics=False)
    res = run_kmeans_job(cfg)
    want = pts[:3].copy()
    for _ in range(2):
        want = kmeans_model(pts, want)
    np.testing.assert_allclose(res.centroids, want, rtol=1e-3, atol=1e-3)
    assert "time/feed_s" in res.metrics

    # checkpointed: 1-iter run, then resume to 3 — identical to a fresh
    # 3-iter run (the snapshot's stream_device mode is adopted)
    import dataclasses

    ck = str(tmp_path / "ck")
    run_kmeans_job(dataclasses.replace(cfg, kmeans_iters=1,
                                       checkpoint_dir=ck,
                                       keep_intermediates=True))
    resumed = run_kmeans_job(dataclasses.replace(cfg, kmeans_iters=3,
                                                 checkpoint_dir=ck))
    fresh = run_kmeans_job(dataclasses.replace(cfg, kmeans_iters=3))
    np.testing.assert_array_equal(resumed.centroids, fresh.centroids)
    assert resumed.metrics.get("resumed_iters") == 1


def test_streamed_sharded_matches_oracle(tmp_path, rng):
    """Streaming x sharding composed (VERDICT r5 #2): fixed-row chunks
    stream as per-shard blocks through make_stream_step_fn's one-psum
    program over the 8-virtual-device CPU mesh, and the result matches
    the NumPy oracle — across chunking shapes (multi-chunk with a
    padded, zero-weighted tail; the single-chunk first==last fusion)
    and with a row count not divisible by the mesh."""
    from map_oxidize_tpu.parallel.kmeans import kmeans_fit_streamed

    pts, centers = _blobs(rng, n=5003, d=8, k=5)
    pts[:5] = centers
    path = tmp_path / "p.npy"
    np.save(path, pts)
    init = pts[:5].copy()
    want = init
    for _ in range(3):
        want = kmeans_model(pts, want)
    for chunk_rows in (1000, 1 << 20):  # multi-chunk+tail / single fused
        got = kmeans_fit_streamed(str(path), init, iters=3,
                                  chunk_rows=chunk_rows, num_shards=8,
                                  backend="cpu")
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)
    # shards=1 runs the SAME program (psum over a singleton axis) and
    # must agree with the mesh run within float-reassociation tolerance
    got1 = kmeans_fit_streamed(str(path), init, iters=3, chunk_rows=1000,
                               num_shards=1, backend="cpu")
    np.testing.assert_allclose(got1, want, rtol=1e-3, atol=1e-3)
    # bf16 chunk storage stays within rounding of the f32 oracle
    gb = kmeans_fit_streamed(str(path), init, iters=3, chunk_rows=1000,
                             num_shards=8, backend="cpu",
                             precision="bf16")
    scale = float(np.abs(pts).max())
    assert float(np.abs(gb - want).max()) <= 4 * 2.0**-8 * scale


def test_fit_budget_config_routes_stream_device(tmp_path, rng):
    """VERDICT r5 #5: the device-fit budget is a CONFIG field now —
    forcing it tiny must route mapper='auto' to stream_device (recorded
    in metrics, no monkeypatching) and still match the NumPy oracle;
    a generous budget routes the same job to the resident fit."""
    pts, centers = _blobs(rng, n=1200, d=5, k=3)
    pts[:3] = centers
    inp = tmp_path / "p.npy"
    np.save(inp, pts)

    def run(budget):
        cfg = JobConfig(input_path=str(inp), output_path="", backend="cpu",
                        kmeans_k=3, kmeans_iters=2, mapper="auto",
                        metrics=True, kmeans_device_fit_bytes=budget)
        return run_job(cfg, "kmeans")

    want = pts[:3].copy()
    for _ in range(2):
        want = kmeans_model(pts, want)

    streamed = run(budget=64)  # working set >> 64 bytes -> must stream
    assert streamed.metrics["kmeans_mode"] == "stream_device"
    np.testing.assert_allclose(streamed.centroids, want,
                               rtol=1e-3, atol=1e-3)
    assert "time/feed_s" in streamed.metrics

    resident = run(budget=1 << 40)  # everything fits -> resident
    assert resident.metrics["kmeans_mode"] == "device"
    np.testing.assert_allclose(resident.centroids, want,
                               rtol=1e-3, atol=1e-3)
