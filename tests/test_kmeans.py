"""k-means workload (BASELINE config #5): streamed engine result == NumPy
oracle, device-resident path == oracle, conservation, empty clusters."""

import numpy as np
import pytest

from map_oxidize_tpu.api import SumReducer
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.runtime import run_job
from map_oxidize_tpu.runtime.driver import make_engine, run_kmeans_job
from map_oxidize_tpu.workloads.kmeans import (
    KMeansMapper,
    assign_points,
    iter_point_chunks,
    kmeans_fit_device,
    kmeans_iteration,
    kmeans_model,
)


def _blobs(rng, n=4000, d=8, k=5):
    centers = rng.normal(0, 10, size=(k, d)).astype(np.float32)
    pts = (centers[rng.integers(0, k, size=n)]
           + rng.normal(0, 0.5, size=(n, d))).astype(np.float32)
    return pts, centers


def test_streamed_iteration_matches_oracle(rng):
    pts, init = _blobs(rng)
    cfg = JobConfig(input_path="unused", output_path="", backend="cpu",
                    batch_size=512, metrics=False)
    engine = make_engine(cfg, SumReducer(), value_shape=(pts.shape[1] + 1,),
                         value_dtype=np.float32)
    chunks = [pts[i:i + 700] for i in range(0, pts.shape[0], 700)]
    ours = kmeans_iteration(engine, init, chunks)
    want = kmeans_model(pts, init)
    np.testing.assert_allclose(ours, want, rtol=1e-4, atol=1e-4)


def test_device_fit_matches_oracle(rng):
    pts, init = _blobs(rng, n=2000, d=4, k=3)
    got = kmeans_fit_device(pts, init, iters=1)
    want = kmeans_model(pts, init)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_device_fit_multi_iter_matches_repeated_oracle(rng):
    pts, init = _blobs(rng, n=1500, d=4, k=4)
    got = kmeans_fit_device(pts, init, iters=3)
    want = init
    for _ in range(3):
        want = kmeans_model(pts, want)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_empty_centroid_keeps_position(rng):
    pts = np.ones((50, 2), np.float32)          # all points at (1, 1)
    init = np.array([[1.0, 1.0], [99.0, 99.0]], np.float32)
    cfg = JobConfig(input_path="unused", output_path="", backend="cpu",
                    metrics=False)
    engine = make_engine(cfg, SumReducer(), value_shape=(3,),
                         value_dtype=np.float32)
    new = kmeans_iteration(engine, init, [pts])
    np.testing.assert_allclose(new[0], [1.0, 1.0])
    np.testing.assert_allclose(new[1], [99.0, 99.0])  # empty: unchanged


def test_mapper_emits_partial_sums(rng):
    pts, init = _blobs(rng, n=300, d=3, k=4)
    out = KMeansMapper(init).map_chunk(pts)
    assert out.records_in == 300
    # counts column conserves points
    assert int(round(float(out.values[:, -1].sum()))) == 300
    # each emitted row matches a direct per-centroid sum
    cid = assign_points(pts, init)
    for hi, lo, row in zip(out.hi, out.lo, out.values):
        assert hi == 0
        m = cid == int(lo)
        np.testing.assert_allclose(row[:-1], pts[m].sum(0), rtol=1e-4)
        assert int(round(float(row[-1]))) == int(m.sum())


def test_run_kmeans_job_end_to_end(tmp_path, rng):
    pts, _ = _blobs(rng, n=3000, d=6, k=4)
    inp = tmp_path / "points.npy"
    np.save(inp, pts)
    outp = tmp_path / "centroids.npy"
    cfg = JobConfig(input_path=str(inp), output_path=str(outp),
                    backend="cpu", kmeans_k=4, kmeans_iters=2,
                    chunk_bytes=4096, metrics=False)
    res = run_job(cfg, "kmeans")
    want = np.asarray(pts[:4], np.float32)
    for _ in range(2):
        want = kmeans_model(pts, want)
    np.testing.assert_allclose(res.centroids, want, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.load(outp), res.centroids)


def test_conservation_violation_raises(rng):
    # a mapper bug that miscounts points must be caught by the count check
    pts, init = _blobs(rng, n=100, d=2, k=2)
    cfg = JobConfig(input_path="unused", output_path="", backend="cpu",
                    metrics=False)

    class Lossy(KMeansMapper):
        def map_chunk(self, points):
            out = super().map_chunk(points)
            out.records_in += 7  # claim more points than were summed
            return out

    engine = make_engine(cfg, SumReducer(), value_shape=(3,),
                         value_dtype=np.float32)
    with pytest.raises(RuntimeError, match="conservation"):
        kmeans_iteration(engine, init, [pts], mapper=Lossy(init))


def test_sharded_fit_matches_oracle(rng):
    """Multi-chip HBM-resident k-means on the 8-device virtual mesh: one
    psum per iteration, padding rows carry zero weight."""
    from map_oxidize_tpu.parallel.kmeans import kmeans_fit_sharded

    pts, init = _blobs(rng, n=2005, d=4, k=3)  # 2005 % 8 != 0: pad rows live
    got = kmeans_fit_sharded(pts, init, iters=2, num_shards=8, backend="cpu")
    want = init
    for _ in range(2):
        want = kmeans_model(pts, want)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_run_kmeans_job_device_paths(tmp_path, rng):
    """mapper='device' routes to the HBM-resident fit (single) and the
    sharded psum fit (mesh); both match the streamed default."""
    pts, _ = _blobs(rng, n=1600, d=5, k=4)
    inp = tmp_path / "points.npy"
    np.save(inp, pts)

    def run(mapper, shards):
        cfg = JobConfig(input_path=str(inp), output_path="", backend="cpu",
                        kmeans_k=4, kmeans_iters=2, chunk_bytes=4096,
                        mapper=mapper, num_shards=shards, metrics=False)
        return run_job(cfg, "kmeans").centroids

    streamed = run("auto", 1)
    dev1 = run("device", 1)
    dev8 = run("device", 8)
    np.testing.assert_allclose(dev1, streamed, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(dev8, streamed, rtol=1e-3, atol=1e-3)
