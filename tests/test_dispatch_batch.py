"""Scan-batched multi-chunk dispatch (ISSUE 8): B logical chunks per
device launch on every streamed path.

The contract under test, at every B: outputs are BIT-IDENTICAL to the
unbatched schedule (the scan carries the accumulator left-fold, padded
tail chunks are zero-weight-masked), compile counts stay flat once the
known (B, first, last) variants are warm, comms accounting totals are
B-invariant (the ledger gate must compare identically across B), and
checkpoint/resume works across any (B_write, B_resume) pair because B is
deliberately NOT checkpoint identity.
"""

import dataclasses

import numpy as np
import pytest

from map_oxidize_tpu.api import MapOutput, SumReducer
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.ops.hashing import SENTINEL, HashDictionary, join_u64
from map_oxidize_tpu.runtime import run_job
from map_oxidize_tpu.runtime.dispatch import (
    DEFAULT_AUTO_B,
    record_dispatch_batch,
    resolve_dispatch_batch,
)
from map_oxidize_tpu.runtime.engine import DeviceReduceEngine
from map_oxidize_tpu.workloads.kmeans import kmeans_model


def _blobs(rng, n=4000, d=8, k=5):
    centers = rng.normal(0, 10, size=(k, d)).astype(np.float32)
    pts = (centers[rng.integers(0, k, size=n)]
           + rng.normal(0, 0.5, size=(n, d))).astype(np.float32)
    return pts, centers


# --- streamed k-means: scan-batched step parity ---------------------------


@pytest.mark.parametrize("num_shards", [1, 8])
def test_stream_kmeans_parity_across_B(tmp_path, rng, num_shards):
    """Oracle-exact at B in {1, 2, 7} (7 does not divide the chunk count,
    so the tail block pads with zero-weight dead chunks), on a 1-device
    mesh and the 8-virtual-device CPU mesh — and bit-identical across B
    (the scan preserves the per-chunk left-fold accumulation order)."""
    from map_oxidize_tpu.parallel.kmeans import kmeans_fit_streamed

    pts, centers = _blobs(rng, n=5003, d=8, k=5)
    pts[:5] = centers
    path = tmp_path / "p.npy"
    np.save(path, pts)
    init = pts[:5].copy()
    want = init
    for _ in range(3):
        want = kmeans_model(pts, want)

    outs = {}
    for b in (1, 2, 7):
        outs[b] = kmeans_fit_streamed(str(path), init, iters=3,
                                      chunk_rows=1000,
                                      num_shards=num_shards, backend="cpu",
                                      dispatch_batch=b)
        np.testing.assert_allclose(outs[b], want, rtol=1e-3, atol=1e-3)
    for b in (2, 7):
        assert outs[b].tobytes() == outs[1].tobytes(), (
            f"B={b} must be bit-identical to the unbatched schedule")


def test_stream_kmeans_zero_compile_delta_sweeping_B(tmp_path, rng):
    """After one warm pass per B, re-sweeping every B must add ZERO
    compiles of kmeans/stream_step: each (B, first, last) variant is a
    known program, and the padded tail block reuses the mid-stream shape
    (the DrJAX flat-program-count invariant the ledger gate enforces)."""
    from map_oxidize_tpu.obs.compile import LEDGER
    from map_oxidize_tpu.parallel.kmeans import kmeans_fit_streamed

    pts, centers = _blobs(rng, n=3000, d=6, k=4)
    pts[:4] = centers
    path = tmp_path / "p.npy"
    np.save(path, pts)
    init = pts[:4].copy()

    def sweep():
        for b in (1, 2, 7):
            kmeans_fit_streamed(str(path), init, iters=2, chunk_rows=600,
                                num_shards=8, backend="cpu",
                                dispatch_batch=b)

    sweep()  # warm: compiles the (B, first, last) variants once
    before = LEDGER.programs["kmeans/stream_step"].compiles
    sweep()
    after = LEDGER.programs["kmeans/stream_step"].compiles
    assert after == before, (
        f"re-sweeping warm B values recompiled kmeans/stream_step "
        f"({before} -> {after})")


# --- comms accounting: B-invariant totals ---------------------------------


def _stream_cfg(inp, b, **kw):
    return JobConfig(input_path=str(inp), output_path="", backend="cpu",
                     num_shards=8, mapper="auto", metrics=True,
                     kmeans_k=3, kmeans_iters=2,
                     kmeans_device_fit_bytes=64,  # force stream_device
                     chunk_bytes=256 * 4 * (6 + 2 * 3),  # ~256-row chunks
                     dispatch_batch=b, **kw)


def test_comms_bytes_invariant_across_B(tmp_path, rng):
    """The one (k, d+1) psum per LOGICAL chunk is recorded per real chunk
    (padded dead chunks excluded), so comms/*/bytes and /calls totals —
    the accounting identity the ledger gate compares — are identical at
    any B."""
    pts, centers = _blobs(rng, n=1000, d=6, k=3)
    pts[:3] = centers
    inp = tmp_path / "p.npy"
    np.save(inp, pts)

    got = {}
    for b in (1, 4):
        m = run_job(_stream_cfg(inp, b), "kmeans").metrics
        assert m["dispatch/batch"] == b
        got[b] = {k: v for k, v in m.items() if k.startswith("comms/")}
    key = "comms/psum/kmeans/stream_step/bytes"
    assert got[1][key] > 0
    assert got[1] == got[4], (
        "comms accounting must be invariant across dispatch batch")


def test_comms_gate_catches_per_dispatch_accounting(tmp_path, rng):
    """Injected regression: if the psum were recorded per DISPATCH
    instead of per logical chunk, a B=4 run would book ~1/4 the bytes —
    and the ledger gate comparing it against the correct entry must flag
    unexplained comms growth in the B-dependent direction."""
    from map_oxidize_tpu.obs.ledger import diff_entries

    pts, centers = _blobs(rng, n=1000, d=6, k=3)
    pts[:3] = centers
    inp = tmp_path / "p.npy"
    np.save(inp, pts)
    m = run_job(_stream_cfg(inp, 1), "kmeans").metrics
    key = "comms/psum/kmeans/stream_step/bytes"

    def entry(metrics):
        return {"version": "v", "workload": "kmeans", "config_hash": "h",
                "phases_s": {}, "metrics": metrics}

    correct = {key: m[key]}
    buggy_b4 = {key: m[key] / 4}  # per-dispatch accounting at B=4
    d = diff_entries(entry(buggy_b4), entry(correct))
    assert any(key in r for r in d["regressions"]), (
        "the comms gate must flag B-dependent accounting drift")
    # and the CORRECT accounting diffs clean against itself across B
    d = diff_entries(entry(correct), entry(correct))
    assert not d["regressions"]


# --- checkpoint identity: B is not part of it ------------------------------


@pytest.mark.parametrize("b_write,b_resume", [(1, 4), (4, 1)])
def test_checkpoint_resume_parity_across_B(tmp_path, rng, b_write,
                                           b_resume):
    """A streamed snapshot written at one B resumes under any other and
    lands bit-identical to an uninterrupted run: B is stamped OUT of
    checkpoint identity because outputs are B-invariant."""
    pts, centers = _blobs(rng, n=1000, d=6, k=3)
    pts[:3] = centers
    inp = tmp_path / "p.npy"
    np.save(inp, pts)

    want = run_job(
        dataclasses.replace(_stream_cfg(inp, 1), kmeans_iters=4),
        "kmeans").centroids

    ck = str(tmp_path / "ck")
    run_job(dataclasses.replace(_stream_cfg(inp, b_write),
                                checkpoint_dir=ck,
                                keep_intermediates=True), "kmeans")
    resumed = run_job(
        dataclasses.replace(_stream_cfg(inp, b_resume), kmeans_iters=4,
                            checkpoint_dir=ck), "kmeans")
    assert resumed.metrics.get("resumed_iters") == 2, (
        "a B mismatch must not invalidate the snapshot")
    assert resumed.centroids.tobytes() == want.tobytes()


# --- fold engine: scan-batched packed merges -------------------------------


def _out(keys, vals=None):
    keys = np.asarray(keys, np.uint64)
    if vals is None:
        vals = np.ones(len(keys), np.int32)
    hi = (keys >> np.uint64(32)).astype(np.uint32)
    lo = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return MapOutput(hi=hi, lo=lo, values=np.asarray(vals, np.int32),
                     dictionary=HashDictionary())


def _live(engine):
    hi, lo, vals, n = engine.finalize()
    hi, lo, vals = np.asarray(hi), np.asarray(lo), np.asarray(vals)
    m = ~((hi == np.uint32(SENTINEL)) & (lo == np.uint32(SENTINEL)))
    return dict(zip(join_u64(hi[m], lo[m]).tolist(), vals[m].tolist())), n


def _feed_all(engine, rng):
    """4 full feed-batch-sized slices plus a short tail (padded to full
    size under batching, so it queues too), overlapping keys, varied
    values.  Returns the oracle dict."""
    oracle: dict = {}
    for i in range(4):
        keys = rng.integers(0, 900, size=512).astype(np.uint64)
        vals = rng.integers(1, 100, size=512).astype(np.int32)
        for kk, vv in zip(keys.tolist(), vals.tolist()):
            oracle[kk] = oracle.get(kk, 0) + vv
        engine.feed(_out(keys, vals))
    keys = rng.integers(0, 900, size=77).astype(np.uint64)
    for kk in keys.tolist():
        oracle[kk] = oracle.get(kk, 0) + 1
    engine.feed(_out(keys))
    return oracle


@pytest.mark.parametrize("b", [1, 2, 7])
def test_engine_packed_batch_parity(b):
    """DeviceReduceEngine at dispatch_batch B: packable slices (short
    ones padded to full feed-batch size) queue and ship B per scanned
    launch, a partial queue pads with dead SENTINEL batches at forced
    drains — and the result equals the host oracle exactly at every B
    (7 never divides the 5 queued slices, so the finalize drain pads)."""
    rng = np.random.default_rng(5)
    cfg = JobConfig(backend="cpu", batch_size=512, key_capacity=2048,
                    initial_key_capacity=2048, dispatch_batch=b)
    eng = DeviceReduceEngine(cfg, SumReducer())
    oracle = _feed_all(eng, rng)
    got, n = _live(eng)
    assert n == len(oracle)
    assert got == oracle


def test_engine_tail_slices_queue_instead_of_draining():
    """The common flush shape is full slices plus a short tail; a tail
    that force-drained would pad the partial queue with up to B-1 dead
    batches per flush, shipping MORE transfer at B>1 than at B=1 — the
    opposite of the feature.  Under batching, short packable slices pad
    to full feed-batch size and queue; the single-batch program never
    runs, and dead padding happens only at the one finalize drain."""
    from map_oxidize_tpu.obs.compile import LEDGER

    rng = np.random.default_rng(5)
    cfg = JobConfig(backend="cpu", batch_size=512, key_capacity=4096,
                    initial_key_capacity=4096, dispatch_batch=4)
    eng = DeviceReduceEngine(cfg, SumReducer())
    single_before = (LEDGER.programs["engine/merge_packed"].dispatches
                     if "engine/merge_packed" in LEDGER.programs else 0)
    _feed_all(eng, rng)  # 4 full slices (one drained launch) + staged tail
    assert not eng._pack_queue and eng._staged == 77
    eng.flush()  # the short tail pads to full size and QUEUES
    assert len(eng._pack_queue) == 1, (
        "a tail slice must join the queue, not force-drain it")
    p = LEDGER.programs["engine/merge_packed_batch"]
    before = (p.dispatches, p.chunks)
    eng.finalize()
    assert p.dispatches - before[0] == 1, "finalize drains the queue once"
    # per-merge attribution counts the 1 REAL queued slice, not the 3
    # dead pads (the (4, 3, 512) shape compiled at the mid-feed drain,
    # so this dispatch is warm and lands in the chunks accounting)
    assert p.chunks - before[1] == 1
    single_after = (LEDGER.programs["engine/merge_packed"].dispatches
                    if "engine/merge_packed" in LEDGER.programs else 0)
    assert single_after == single_before, (
        "no slice fell back to the single-batch program")


def test_engine_state_dict_drains_queue():
    """export_state (the device-map checkpoint unit) must reflect queued
    packed batches: the drain pads the partial queue and merges before
    snapshotting."""
    cfg = JobConfig(backend="cpu", batch_size=512, key_capacity=2048,
                    initial_key_capacity=2048, dispatch_batch=4)
    eng = DeviceReduceEngine(cfg, SumReducer())
    eng.feed(_out(np.arange(512)))  # 1 of 4: sits in the queue
    state = eng.export_state()
    assert int(state["n_unique"]) == 512
    hi, lo = state["acc_hi"], state["acc_lo"]
    live = int(np.sum(~((hi == np.uint32(SENTINEL))
                        & (lo == np.uint32(SENTINEL)))))
    assert live == 512


def test_engine_zero_compile_delta_sweeping_B():
    """Re-sweeping warm engine B values must not recompile the batched
    merge: one (B, 3, feed_batch) shape per B, dead-batch padding keeps
    the tail on it."""
    from map_oxidize_tpu.obs.compile import LEDGER

    def sweep():
        for b in (1, 2, 7):
            rng = np.random.default_rng(5)
            cfg = JobConfig(backend="cpu", batch_size=512,
                            key_capacity=2048, initial_key_capacity=2048,
                            dispatch_batch=b)
            eng = DeviceReduceEngine(cfg, SumReducer())
            _feed_all(eng, rng)
            eng.finalize()

    sweep()
    progs = ("engine/merge_packed", "engine/merge_packed_batch")
    before = {p: LEDGER.programs[p].compiles for p in progs
              if p in LEDGER.programs}
    sweep()
    after = {p: LEDGER.programs[p].compiles for p in progs
             if p in LEDGER.programs}
    assert after == before


# --- the B decision + its evidence -----------------------------------------


def test_resolve_fixed_and_chunk_cap():
    b, info = resolve_dispatch_batch(5, n_chunks=100)
    assert b == 5 and info["mode"] == "fixed"
    b, info = resolve_dispatch_batch(16, n_chunks=3)
    assert b == 3 and info["capped_by_chunks"] == 3


def test_resolve_auto_records_inputs(monkeypatch):
    """auto with no measurements lands on the default and says so; the
    HBM admission estimate caps the block; the decision is memoized per
    (program, shape, platform) so a warm process can never flip B."""
    import map_oxidize_tpu.runtime.dispatch as dsp

    monkeypatch.setattr(dsp, "hbm_budget_bytes", lambda: 0)
    b, info = resolve_dispatch_batch(0, n_chunks=1000,
                                     program="test/no_measurements")
    assert b == DEFAULT_AUTO_B
    assert info["mode"] == "auto"
    assert info["rule"] == "default_no_measurements"
    assert info["floor_ms"] > 0

    monkeypatch.setattr(dsp, "hbm_budget_bytes", lambda: 1 << 20)
    b, info = resolve_dispatch_batch(0, n_chunks=1000,
                                     chunk_device_bytes=1 << 18,
                                     program="test/hbm_capped")
    assert b == 1 and info["hbm_cap"] == 1  # budget / (4 * chunk_bytes)

    b2, _ = resolve_dispatch_batch(0, n_chunks=1000,
                                   chunk_device_bytes=1 << 18,
                                   program="test/hbm_capped")
    assert b2 == b, "auto resolution must be memoized (stable warm B)"
    # callers read the memo state to skip the paid produce probe whose
    # result a cached resolution would discard (warm-server economy)
    from map_oxidize_tpu.runtime.dispatch import has_cached_auto

    assert has_cached_auto("test/hbm_capped", 1 << 18)
    assert not has_cached_auto("test/never_resolved", 1 << 18)


def test_measured_floor_snapshot_window():
    """dispatch_floor_snapshot scopes the floor to one measurement
    window: the ledger is process-global, so two bench entries sharing
    a program would otherwise contaminate each other's trajectory
    record."""
    from map_oxidize_tpu.obs.compile import LEDGER
    from map_oxidize_tpu.runtime.dispatch import (
        dispatch_floor_snapshot,
        measured_dispatch_floor_ms,
    )

    name = "test/floor_window"
    stats = LEDGER._stats(name)
    LEDGER.record_dispatch(stats, 100.0, None, compiled=False)
    snap = dispatch_floor_snapshot(name)
    LEDGER.record_dispatch(stats, 2.0, None, compiled=False)
    LEDGER.record_dispatch(stats, 4.0, None, compiled=False)
    assert measured_dispatch_floor_ms(name, since=snap) == 3.0
    assert measured_dispatch_floor_ms(name) == pytest.approx(106.0 / 3)
    # an empty window (no steady-state dispatches since) is None
    assert measured_dispatch_floor_ms(
        name, since=dispatch_floor_snapshot(name)) is None


def test_record_dispatch_batch_gauges():
    from map_oxidize_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    record_dispatch_batch(reg, 4, {"mode": "auto", "batch": 4,
                                   "floor_ms": 3.7, "rule": "x"})
    s = reg.summary()
    assert s["dispatch/batch"] == 4
    assert s["dispatch/batch_mode"] == "auto"
    assert s["dispatch/floor_ms"] == 3.7


def test_dispatch_gauges_ride_job_metrics(tmp_path, rng):
    """The chosen B and its evidence land in JobResult.metrics (and so
    the metrics doc and run-ledger entry): the 'auto resolving to a
    logged B' record the check.sh smoke reads."""
    pts, centers = _blobs(rng, n=600, d=6, k=3)
    pts[:3] = centers
    inp = tmp_path / "p.npy"
    np.save(inp, pts)
    m = run_job(_stream_cfg(inp, 0), "kmeans").metrics
    assert m["dispatch/batch_mode"] == "auto"
    assert m["dispatch/batch"] >= 1
    assert "dispatch/rule" in m or "dispatch/floor_ms" in m


# --- per-logical-chunk dispatch attribution --------------------------------


def test_observed_jit_chunk_attribution():
    """A scan-batched program declares chunks_of: non-compiling
    dispatches accumulate logical chunks next to the dispatch wall, so
    per-chunk gap (the dispatch-floor trajectory number) divides out B."""
    import jax
    import jax.numpy as jnp

    from map_oxidize_tpu.obs.compile import LEDGER, observed_jit

    name = "test/chunked_prog"
    fn = observed_jit(name, jax.jit(lambda x: jnp.sum(x, axis=1)),
                      chunks_of=lambda *a, **kw: a[0].shape[0])
    x = np.ones((4, 8), np.float32)
    fn(x)  # compiling call: excluded from the steady-state populations
    fn(x)
    fn(x)
    p = LEDGER.programs[name]
    assert p.chunks == 8  # 2 non-compiling dispatches x 4 chunks


# --- CLI / serve spelling ---------------------------------------------------


def test_cli_dispatch_batch_arg():
    import argparse

    from map_oxidize_tpu.cli import _dispatch_batch_arg, build_parser

    assert _dispatch_batch_arg("auto") == 0
    assert _dispatch_batch_arg("8") == 8
    for bad in ("0", "-2", "many"):
        with pytest.raises(argparse.ArgumentTypeError):
            _dispatch_batch_arg(bad)
    args = build_parser().parse_args(
        ["wordcount", "in", "--dispatch-batch", "auto"])
    assert args.dispatch_batch == 0


def test_serve_override_accepts_auto():
    from map_oxidize_tpu.serve.client import coerce_overrides

    assert coerce_overrides(["dispatch_batch=auto"]) == {"dispatch_batch": 0}
    assert coerce_overrides(["dispatch_batch=4"]) == {"dispatch_batch": 4}


def test_config_validates_dispatch_batch():
    with pytest.raises(ValueError):
        JobConfig(input_path="x", dispatch_batch=-1).validate()
    with pytest.raises(ValueError):
        JobConfig(input_path="x", dispatch_batch=4096).validate()
