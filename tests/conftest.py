"""Test harness: force an 8-virtual-device CPU mesh.

No TPU is required to run the suite — multi-device code paths are validated on
a fake mesh via ``--xla_force_host_platform_device_count=8`` (SURVEY.md §4).
The env vars must be set before jax initializes its backends, hence here.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
