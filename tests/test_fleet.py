"""Fleet observatory (ISSUE-13 tentpole): the multi-endpoint collector,
per-target staleness tracking, fleet SLOs + cross-target incident
correlation, the labeled fleet /metrics plane, target discovery
(explicit / port file / serve spool / well-known spool), and the
persistent series archive with its post-mortem readers.

Collector sweeps are driven through ``poll_once`` with an injected
clock, so staleness windows and alert transitions are deterministic —
no wall-clock sleeps on the model paths.  The endpoints scraped are
REAL ``ObsServer``/stub HTTP servers on ephemeral localhost ports.
"""

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from map_oxidize_tpu.config import FleetConfig, JobConfig
from map_oxidize_tpu.obs import Obs
from map_oxidize_tpu.obs import fleet as fleet_mod
from map_oxidize_tpu.obs.fleet import (
    ArchiveMismatch,
    FleetCollector,
    FleetServer,
    SeriesArchive,
    correlate_alerts,
    discover_targets,
)


class _Clock:
    """Injectable fleet time: staleness windows advance by assignment,
    never by sleeping."""

    def __init__(self, t0: float = 1_000.0):
        self.t = t0

    def __call__(self) -> float:
        return self.t


def _fleet_cfg(**kw) -> FleetConfig:
    kw.setdefault("discover_dir", "none")
    kw.setdefault("poll_interval_s", 0.5)
    kw.setdefault("stale_after_s", 5.0)
    return FleetConfig(**kw).validate()


@pytest.fixture()
def job_server(tmp_path, monkeypatch):
    """One real obs server over a live job-shaped bundle (ephemeral
    port), spool publishing routed into the test's tmpdir."""
    monkeypatch.setenv("MOXT_OBS_SPOOL", str(tmp_path / "wkspool"))
    cfg = JobConfig(input_path=str(tmp_path / "x"), obs_port=0,
                    obs_sample_s=0.05).validate()
    obs = Obs.from_config(cfg)
    obs.workload = "wordcount"
    yield obs
    obs.stop_live()
    obs.finish_xprof()


# --- config -----------------------------------------------------------------


def test_fleet_config_validates():
    with pytest.raises(ValueError):
        FleetConfig(port=70000).validate()
    with pytest.raises(ValueError):
        FleetConfig(poll_interval_s=0).validate()
    with pytest.raises(ValueError):
        FleetConfig(stale_after_s=0).validate()
    with pytest.raises(ValueError):
        FleetConfig(archive_max_segments=1).validate()
    with pytest.raises(ValueError, match="invalid fleet slo_rules"):
        FleetConfig(slo_rules='[{"metric": "x"}]').validate()
    # fleet defaults are tunable by name, like any rule set
    cfg = FleetConfig(slo_rules='[{"name": "fleet-target-stale", '
                                '"metric": "fleet/target/*/stale", '
                                '"threshold": 2}]').validate()
    from map_oxidize_tpu.obs.fleet import FLEET_RULES
    from map_oxidize_tpu.obs.slo import load_rules

    rules = {r.name: r for r in load_rules(cfg.slo_rules,
                                           defaults=FLEET_RULES)}
    assert rules["fleet-target-stale"].threshold == 2
    assert "fleet-hbm-watermark" in rules


# --- the series archive -----------------------------------------------------


def test_archive_ring_bounds_and_export(tmp_path):
    root = str(tmp_path / "arch")
    arch = SeriesArchive(root, segment_records=4, max_segments=2)
    for i in range(20):
        arch.append(100.0 + i, {"fleet/rows_per_sec": float(i)})
    arch.close()
    samples = SeriesArchive.samples(root)
    # bounded: at most segment_records * max_segments survive, and the
    # survivors are the NEWEST samples in order
    assert len(samples) <= 8
    vals = [v["fleet/rows_per_sec"] for _t, v in samples]
    assert vals == sorted(vals)
    assert vals[-1] == 19.0
    export = SeriesArchive.export(root)
    assert export["schema"] == "moxt-archive-v1"
    assert len(export["t_unix_s"]) == len(samples)
    assert export["series"]["fleet/rows_per_sec"][-1] == 19.0


def test_archive_resume_and_latest(tmp_path):
    root = str(tmp_path / "arch")
    arch = SeriesArchive(root, segment_records=4, max_segments=3)
    for i in range(3):
        arch.append(float(i), {"c": i})
    arch.write_latest("status", {"schema": "moxt-fleet-status-v1",
                                 "counts": {"targets": 2}})
    arch.close()
    # a second collector resumes the ring instead of refusing/clobbering
    arch2 = SeriesArchive(root, segment_records=4, max_segments=3)
    arch2.append(3.0, {"c": 3})
    arch2.close()
    assert [v["c"] for _t, v in SeriesArchive.samples(root)] == [0, 1, 2, 3]
    assert SeriesArchive.latest(root, "status")["counts"]["targets"] == 2
    assert SeriesArchive.latest(root, "alerts") is None


def test_archive_schema_refusal(tmp_path):
    from map_oxidize_tpu.cli import main

    root = str(tmp_path / "arch")
    arch = SeriesArchive(root)
    arch.append(1.0, {"c": 1})
    arch.close()
    meta = json.loads((tmp_path / "arch" / "archive.json").read_text())
    meta["schema"] = "moxt-archive-v99"
    (tmp_path / "arch" / "archive.json").write_text(json.dumps(meta))
    with pytest.raises(ArchiveMismatch, match="moxt-archive-v99"):
        SeriesArchive.samples(root)
    with pytest.raises(ArchiveMismatch):
        SeriesArchive(root)              # a writer refuses it too
    assert main(["obs", "trend", "--archive", root]) == 2
    assert main(["obs", "top", "--archive", root]) == 2


# --- discovery --------------------------------------------------------------


def test_discovery_sources(tmp_path):
    portfile = tmp_path / "ports.txt"
    portfile.write_text("0 8101\n1 8102\nnot a line\n")
    spool = tmp_path / "serve_spool"
    spool.mkdir()
    (spool / "obs_port.json").write_text(json.dumps({
        "schema": "moxt-obs-port-v1", "pid": os.getpid(),
        "url": "http://127.0.0.1:8203"}))
    cfg = _fleet_cfg(targets=["127.0.0.1:8001", "http://127.0.0.1:8002/"],
                     port_file=str(portfile), spool_dirs=[str(spool)])
    found = discover_targets(cfg)
    assert found["127.0.0.1:8001"]["explicit"]
    assert found["127.0.0.1:8002"]["url"] == "http://127.0.0.1:8002"
    assert found["127.0.0.1:8101"]["source"] == "portfile"
    assert found["127.0.0.1:8102"]["source"] == "portfile"
    assert found["127.0.0.1:8203"]["source"] == "spool"
    # a malformed spool record is skipped, never fatal
    (spool / "obs_port.json").write_text("{broken")
    assert "127.0.0.1:8203" not in discover_targets(cfg)


def test_discovery_well_known_spool_gc(tmp_path):
    """Dead-pid records: never a target when unwatched, KEPT on disk
    while fresh (another collector sharing the spool may be watching
    that target — a kill must read as stale, not as a clean departure),
    garbage-collected only once genuinely old, and always kept when
    THIS collector watches the label."""
    import time as _time

    from map_oxidize_tpu.obs.fleet import GC_GRACE_S

    spool = tmp_path / "spool"
    spool.mkdir()

    def _record(name, pid, port):
        (spool / name).write_text(json.dumps({
            "schema": "moxt-obs-port-v1", "pid": pid,
            "url": f"http://127.0.0.1:{port}"}))

    _record("moxt-obs-1-p0.json", 2 ** 22 + 1234567, 8301)  # dead pid
    _record(f"moxt-obs-{os.getpid()}-p0.json", os.getpid(), 8302)
    cfg = _fleet_cfg(discover_dir=str(spool))
    found = discover_targets(cfg)
    assert "127.0.0.1:8301" not in found          # dead: not a target
    assert (spool / "moxt-obs-1-p0.json").exists()  # fresh: kept
    assert found["127.0.0.1:8302"]["source"] == "discovered"
    # past the grace age the unwatched dead record is collected
    old = _time.time() - GC_GRACE_S - 60
    os.utime(spool / "moxt-obs-1-p0.json", (old, old))
    found = discover_targets(cfg)
    assert "127.0.0.1:8301" not in found
    assert not (spool / "moxt-obs-1-p0.json").exists()
    # the same old dead record, for a label the collector DOES watch,
    # stays listed AND on disk
    _record("moxt-obs-1-p0.json", 2 ** 22 + 1234567, 8301)
    os.utime(spool / "moxt-obs-1-p0.json", (old, old))
    found = discover_targets(cfg, known={"127.0.0.1:8301"})
    assert "127.0.0.1:8301" in found
    assert (spool / "moxt-obs-1-p0.json").exists()


def test_discovery_skips_collector_port_lines(tmp_path):
    """A collector's own 'fleet <port>' MOXT_OBS_PORT_FILE line is not a
    target — a collector sharing a run's port file must not discover
    itself and refuse its own fleet-schema payload every sweep."""
    portfile = tmp_path / "ports.txt"
    portfile.write_text("0 8101\nfleet 8999\n")
    found = discover_targets(_fleet_cfg(port_file=str(portfile)))
    assert "127.0.0.1:8101" in found
    assert "127.0.0.1:8999" not in found


def test_obs_server_publishes_and_departs(tmp_path, monkeypatch):
    """Satellite: every serving process publishes its port record at the
    well-known spool — a 2-process run appears as two targets with no
    flags — and a CLEAN stop removes the record, which the collector
    models as departure (not staleness)."""
    spool = tmp_path / "spool"
    monkeypatch.setenv("MOXT_OBS_SPOOL", str(spool))
    cfg = JobConfig(input_path=str(tmp_path / "x"), obs_port=0,
                    obs_sample_s=0.05).validate()
    bundles = [Obs.from_config(cfg, process=i, n_processes=2)
               for i in range(2)]
    for b in bundles:
        b.workload = "wordcount"
    records = sorted(spool.glob("moxt-obs-*.json"))
    assert len(records) == 2
    recs = [json.loads(p.read_text()) for p in records]
    assert {r["process"] for r in recs} == {0, 1}
    assert all(r["schema"] == "moxt-obs-port-v1" for r in recs)

    clock = _Clock()
    col = FleetCollector(_fleet_cfg(discover_dir=str(spool)),
                         clock=clock)
    doc = col.poll_once(now=clock.t)
    assert doc["counts"] == {"targets": 2, "up": 2, "stale": 0,
                             "departed": 0}
    # clean stop removes the record -> departed, NOT stale (no alert)
    bundles[0].stop_live()
    clock.t += 60
    doc = col.poll_once(now=clock.t)
    states = {t["target"]: t["state"] for t in doc["targets"]}
    assert sorted(states.values()) == ["departed", "up"]
    assert col.alerts.fired_total == 0
    for b in bundles:
        b.stop_live()
        b.finish_xprof()


# --- live merge + the fleet plane -------------------------------------------


def _get_json(url: str) -> dict:
    import urllib.request

    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())


def test_collector_merges_live_target(job_server, tmp_path):
    obs = job_server
    obs.registry.set("hbm/live_bytes_device0", 1 << 20)
    obs.registry.set("hbm/budget_bytes", 1 << 21)
    clock = _Clock()
    col = FleetCollector(
        _fleet_cfg(targets=[obs.server.url],
                   archive_dir=str(tmp_path / "arch")), clock=clock)
    doc = col.poll_once(now=clock.t)
    (row,) = doc["targets"]
    assert row["state"] == "up" and row["kind"] == "job"
    assert row["workload"] == "wordcount"
    assert row["version"] == doc["version"]  # same package, no refusal
    assert row["hbm_bytes"] == 1 << 20
    assert row["hbm_frac"] == 0.5
    assert doc["aggregates"]["hbm_max_bytes"] == 1 << 20
    assert doc["aggregates"]["targets_up"] == 1
    # the flat spellings ride the registry -> the series ring the SLO
    # evaluator globs
    assert col.series.latest_names()
    label = row["target"]
    assert f"fleet/target/{label}/up" in col.series.latest_names()
    # the fleet plane serves it all
    srv = FleetServer(col, 0).start()
    try:
        status = _get_json(srv.url + "/status")
        assert status["schema"] == "moxt-fleet-status-v1"
        hz = _get_json(srv.url + "/healthz")
        assert hz["schema"] == "moxt-healthz-v1"
        assert hz["workload"] == "fleet" and hz["targets"] == 1
        alerts = _get_json(srv.url + "/alerts")
        assert alerts["schema"] == "moxt-fleet-alerts-v1"
        assert alerts["incidents"] == []
        series = _get_json(srv.url + "/series")
        assert series["schema"] == "moxt-series-v1"
        import urllib.request

        text = urllib.request.urlopen(srv.url + "/metrics",
                                      timeout=10).read().decode()
        assert f'moxt_fleet_target_up{{target="{label}"}} 1' in text
        assert f'moxt_fleet_target_hbm_bytes{{target="{label}"}}' in text
        # fleet aggregates export flat beside the labeled series
        assert "moxt_fleet_rows_per_sec" in text
        assert "moxt_fleet_hbm_max_bytes" in text
    finally:
        srv.stop()


def test_target_death_fires_and_resolves(job_server, tmp_path):
    """The resilience contract: a target dying mid-watch becomes a stale
    row + a fleet alert + ONE correlated incident — and the alert
    resolves when the target returns on the same port."""
    from map_oxidize_tpu.obs.serve import ObsServer

    obs = job_server
    port = obs.server.port
    clock = _Clock()
    col = FleetCollector(
        _fleet_cfg(targets=[obs.server.url], stale_after_s=5.0,
                   archive_dir=str(tmp_path / "arch")), clock=clock)
    doc = col.poll_once(now=clock.t)
    assert doc["targets"][0]["state"] == "up"
    # kill the endpoint (the discovery record is irrelevant: the target
    # is explicit, so it can never depart)
    obs.server.stop()
    clock.t += 2
    doc = col.poll_once(now=clock.t)
    assert doc["targets"][0]["state"] == "down"   # inside the window
    assert col.alerts.fired_total == 0
    clock.t += 10                                 # past stale_after_s
    doc = col.poll_once(now=clock.t)
    assert doc["targets"][0]["state"] == "stale"
    assert doc["targets"][0]["staleness_s"] > 5
    assert col.registry.counters["fleet/scrape_errors"] >= 2
    alerts = col.alerts_doc(now=clock.t)
    (inc,) = [i for i in alerts["incidents"]
              if i["rule"] == "fleet-target-stale"]
    assert inc["active"] and inc["k"] == 1
    assert inc["targets"] == [doc["targets"][0]["target"]]
    assert col.alerts.fired_total == 1
    # an incident bundle landed under the archive
    import glob as _glob

    assert _glob.glob(str(tmp_path / "arch" / "incidents" /
                          "incident_*" / "incident.json"))
    # the target returns on the SAME port -> resolves next sweep
    revived = ObsServer(obs, JobConfig(
        input_path=str(tmp_path / "x"), obs_spool="none").validate(),
        port)
    revived.start()
    try:
        clock.t += 2
        doc = col.poll_once(now=clock.t)
        assert doc["targets"][0]["state"] == "up"
        assert col.alerts.resolved_total == 1
        events = [e["event"] for e in col.alerts.timeline]
        assert events == ["fired", "resolved"]
    finally:
        revived.stop()


# --- refusal ----------------------------------------------------------------


class _StubHandler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        body = self.server.payload
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):
        pass


def _stub_server(payload: bytes):
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _StubHandler)
    httpd.daemon_threads = True
    httpd.payload = payload
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def test_malformed_and_mismatched_payloads_refused(job_server):
    """A version-mismatched or garbage payload is counted
    (``fleet/scrape_refused``) and NEVER merged: the model keeps the
    last good document, and persistent refusal runs out the staleness
    clock exactly like unreachability."""
    obs = job_server
    good = json.dumps(_get_json(obs.server.url + "/status")).encode()
    httpd, url = _stub_server(good)
    try:
        clock = _Clock()
        col = FleetCollector(_fleet_cfg(targets=[url], stale_after_s=5.0),
                             clock=clock)
        doc = col.poll_once(now=clock.t)
        assert doc["targets"][0]["state"] == "up"
        good_phase = doc["targets"][0]["phase"]
        # flip to a version-mismatched schema: refused, model untouched
        httpd.payload = json.dumps(
            {"schema": "moxt-status-v99", "phase": "evil"}).encode()
        clock.t += 1
        doc = col.poll_once(now=clock.t)
        row = doc["targets"][0]
        assert row["state"] == "down"
        assert row["scrape_refused"] == 1
        assert "moxt-status-v99" in row["last_error"]
        assert row["phase"] == good_phase          # never merged
        assert col.registry.counters["fleet/scrape_refused"] == 1
        # raw garbage refuses too (malformed, not a transport error)
        httpd.payload = b"<html>not json</html>"
        clock.t += 1
        col.poll_once(now=clock.t)
        assert col.registry.counters["fleet/scrape_refused"] == 2
        assert col.registry.counters.get("fleet/scrape_errors") is None
        # persistent refusal -> stale, and the refusal delta rule fired
        clock.t += 10
        doc = col.poll_once(now=clock.t)
        assert doc["targets"][0]["state"] == "stale"
        fired = {e["rule"] for e in col.alerts.timeline
                 if e["event"] == "fired"}
        assert fired == {"fleet-target-stale", "fleet-scrape-refused"}
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_non_http_garbage_target_never_aborts_sweep():
    """A reclaimed port speaking non-HTTP (BadStatusLine territory) is
    an unreachable-target model state, never an escaped exception that
    would abort every sweep and blind the whole fleet."""
    import socket

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    port = srv.getsockname()[1]

    def _garbage():
        try:
            conn, _addr = srv.accept()
            conn.sendall(b"I AM NOT HTTP\r\n\r\n")
            conn.close()
        except OSError:
            pass

    t = threading.Thread(target=_garbage, daemon=True)
    t.start()
    try:
        clock = _Clock()
        col = FleetCollector(_fleet_cfg(targets=[f"127.0.0.1:{port}"]),
                             clock=clock)
        doc = col.poll_once(now=clock.t)      # must not raise
        assert doc["targets"][0]["state"] == "down"
        assert col.registry.counters["fleet/scrape_errors"] >= 1
    finally:
        srv.close()


def test_hbm_frac_zeroes_when_target_dies(job_server):
    """The per-target HBM fraction is refreshed from CURRENT evidence:
    a target that dies with a high watermark must not leave the gauge
    frozen where the fleet-hbm-watermark rule fires forever (the
    staleness rule owns dead targets)."""
    obs = job_server
    obs.registry.set("hbm/live_bytes_device0", 96)
    obs.registry.set("hbm/budget_bytes", 100)
    clock = _Clock()
    col = FleetCollector(_fleet_cfg(targets=[obs.server.url]),
                         clock=clock)
    doc = col.poll_once(now=clock.t)
    (row,) = doc["targets"]
    label = row["target"]
    assert row["hbm_frac"] == 0.96
    obs.server.stop()
    clock.t += 1
    doc = col.poll_once(now=clock.t)
    assert doc["targets"][0]["hbm_frac"] == 0.0
    assert col.registry.gauges[f"fleet/target/{label}/hbm_frac"] == 0.0


# --- correlation ------------------------------------------------------------


def test_correlate_alerts_collapses_rule_across_targets():
    """The same rule firing on k targets within the window is ONE fleet
    incident naming all k — firing states and recent 'fired' timeline
    events both join; stale events outside the window do not."""
    now = 10_000.0
    a = {"firing": [{"rule": "stall-episodes", "series": "heartbeat/stalls",
                     "severity": "critical", "since_unix_s": now - 30}],
         "timeline": []}
    b = {"firing": [{"rule": "stall-episodes", "series": "heartbeat/stalls",
                     "severity": "critical", "since_unix_s": now - 10}],
         "timeline": []}
    c = {"firing": [],
         "timeline": [
             {"event": "fired", "rule": "stall-episodes",
              "severity": "critical", "t_unix_s": now - 100},
             {"event": "fired", "rule": "ancient-rule",
              "severity": "warning", "t_unix_s": now - 9_000}]}
    fleet_export = {"firing": [
        {"rule": "fleet-target-stale",
         "series": "fleet/target/10.0.0.1:8300/stale",
         "severity": "critical", "since_unix_s": now - 5}], "timeline": []}
    incidents = correlate_alerts({"t0": a, "t1": b, "t2": c},
                                 fleet_export, window_s=300, now=now)
    by_rule = {i["rule"]: i for i in incidents}
    stall = by_rule["stall-episodes"]
    assert stall["k"] == 3 and stall["targets"] == ["t0", "t1", "t2"]
    assert stall["firing"] == ["t0", "t1"]         # t2 already resolved
    assert stall["active"] and stall["severity"] == "critical"
    assert stall["first_t_unix_s"] == now - 100
    # the fleet evaluator's own staleness firing names the target from
    # its series spelling
    assert by_rule["fleet-target-stale"]["targets"] == ["10.0.0.1:8300"]
    # outside the window: no incident
    assert "ancient-rule" not in by_rule
    # widest incident ranks first
    assert incidents[0]["rule"] == "stall-episodes"


# --- healthz + serve spool record (satellites) ------------------------------


def test_healthz_is_cheap_and_complete(job_server):
    """GET /healthz: version/uptime/phase/process — none of the /status
    render — and the job counts when a scheduler is attached."""
    obs = job_server
    hz = _get_json(obs.server.url + "/healthz")
    assert hz["schema"] == "moxt-healthz-v1"
    from map_oxidize_tpu import __version__

    assert hz["version"] == __version__
    assert hz["uptime_s"] >= 0
    assert hz["workload"] == "wordcount"
    assert hz["process"] == 0 and hz["n_processes"] == 1
    assert "jobs" not in hz                       # no scheduler attached
    assert "xprof" not in hz and "comms" not in hz  # cheap: no render
    # the index names it
    assert "/healthz" in _get_json(obs.server.url + "/")["endpoints"]


def test_healthz_scheduler_counts(tmp_path, monkeypatch):
    from map_oxidize_tpu.obs.serve import ObsServer

    monkeypatch.setenv("MOXT_OBS_SPOOL", "none")

    class _FakeSched:
        def health_doc(self):
            return {"running": 2, "queued": 3, "queue_depth": 3,
                    "max_queue": 16, "workers": 2, "draining": False}

    cfg = JobConfig(input_path=str(tmp_path / "x")).validate()
    obs = Obs.from_config(cfg)
    srv = ObsServer(obs, cfg, 0, scheduler=_FakeSched())
    srv.start()
    try:
        hz = _get_json(srv.url + "/healthz")
        assert hz["jobs"] == {"running": 2, "queued": 3, "queue_depth": 3,
                              "max_queue": 16, "workers": 2,
                              "draining": False}
    finally:
        srv.stop()
        obs.finish_xprof()


def test_resident_server_publishes_spool_record(tmp_path, monkeypatch):
    """Satellite: the resident server drops <spool>/obs_port.json at
    start (fleet --spool discovery) and removes it on clean shutdown."""
    import threading as _threading

    from map_oxidize_tpu.config import ServeConfig
    from map_oxidize_tpu.serve.server import ResidentServer

    monkeypatch.setenv("MOXT_OBS_SPOOL", "none")
    spool = tmp_path / "spool"
    cfg = ServeConfig(port=0, spool_dir=str(spool),
                      drain_timeout_s=5.0).validate()

    def _runner(config, workload, on_obs):  # pragma: no cover - unused
        raise AssertionError("no jobs submitted")

    srv = ResidentServer(cfg, runner=_runner).start()
    try:
        rec = json.loads((spool / "obs_port.json").read_text())
        assert rec["schema"] == "moxt-obs-port-v1"
        assert rec["kind"] == "serve"
        assert rec["url"] == srv.url and rec["pid"] == os.getpid()
        # fleet --spool discovery resolves it
        found = discover_targets(_fleet_cfg(spool_dirs=[str(spool)]))
        assert list(found.values())[0]["url"] == srv.url
        # the collector sees the serve-plane healthz counts
        clock = _Clock()
        col = FleetCollector(_fleet_cfg(spool_dirs=[str(spool)]),
                             clock=clock)
        doc = col.poll_once(now=clock.t)
        (row,) = doc["targets"]
        assert row["kind"] == "serve" and row["state"] == "up"
        assert row["jobs_running"] == 0
    finally:
        srv.shutdown(drain=True)
    assert not (spool / "obs_port.json").exists()
    # and the departed target resolves, never going stale
    clock.t += 120
    doc = col.poll_once(now=clock.t)
    assert doc["targets"][0]["state"] == "departed"
    assert col.alerts.fired_total == 0


# --- renderers + CLI --------------------------------------------------------


def test_obs_top_renders_fleet_live_and_archive(job_server, tmp_path,
                                                capsys):
    from map_oxidize_tpu.cli import main

    obs = job_server
    clock = _Clock()
    col = FleetCollector(
        _fleet_cfg(targets=[obs.server.url],
                   archive_dir=str(tmp_path / "arch")), clock=clock)
    col.poll_once(now=clock.t)
    srv = FleetServer(col, 0).start()
    try:
        rc = main(["obs", "top", "--url", srv.url, "--iterations", "1",
                   "--no-clear"])
    finally:
        srv.stop()
    out = capsys.readouterr().out
    assert rc == 0
    assert "moxt obs fleet — 1 targets (1 up" in out
    assert "fleet alerts: 0 active incidents" in out
    label = col.status_doc(clock.t)["targets"][0]["target"]
    assert label in out
    # post-mortem: the archived frame renders after the collector dies
    col.stop()
    rc = main(["obs", "top", "--archive", str(tmp_path / "arch")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "moxt obs fleet — 1 targets" in out
    assert "(archived frame as of" in out


def test_obs_where_reads_archive(job_server, tmp_path, capsys):
    """Post-mortem attribution: the archived per-target /status
    snapshots carry each target's last live attribution, renderable
    after every producer process exited."""
    from map_oxidize_tpu.cli import main

    obs = job_server
    clock = _Clock()
    col = FleetCollector(
        _fleet_cfg(targets=[obs.server.url],
                   archive_dir=str(tmp_path / "arch")), clock=clock)
    col.poll_once(now=clock.t)
    label = col.status_doc(clock.t)["targets"][0]["target"]
    col.stop()
    obs.stop_live()                      # every producer gone
    rc = main(["obs", "where", "--archive", str(tmp_path / "arch")])
    out = capsys.readouterr().out
    assert rc == 0
    assert f"where did the time go — {label} (wordcount, archived)" in out
    assert "unattributed" in out
    # --target filters; an unknown label errors cleanly
    assert main(["obs", "where", "--archive", str(tmp_path / "arch"),
                 "--target", label]) == 0
    capsys.readouterr()
    assert main(["obs", "where", "--archive", str(tmp_path / "arch"),
                 "--target", "nope:1"]) == 2


def test_obs_trend_reads_archive(job_server, tmp_path, capsys):
    from map_oxidize_tpu.cli import main

    obs = job_server
    clock = _Clock()
    col = FleetCollector(
        _fleet_cfg(targets=[obs.server.url],
                   archive_dir=str(tmp_path / "arch")), clock=clock)
    for _ in range(4):
        col.poll_once(now=clock.t)
        clock.t += 1
    col.stop()
    rc = main(["obs", "trend", "--archive", str(tmp_path / "arch")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "trend: fleet-archive — 4 entries" in out
    # --last bounds the sample window
    assert main(["obs", "trend", "--archive", str(tmp_path / "arch"),
                 "--last", "2", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["n_entries"] == 2


def test_fleet_cli_end_to_end(job_server, capsys):
    """The obs fleet subcommand itself: bounded iterations against a
    real endpoint, clean exit."""
    from map_oxidize_tpu.cli import main

    obs = job_server
    rc = main(["obs", "fleet", "--targets", obs.server.url,
               "--discover-dir", "none", "--interval", "0.05",
               "--iterations", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "[fleet] collector on http://127.0.0.1:" in out
