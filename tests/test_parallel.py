"""Sharded engine tests on the fake 8-device CPU mesh (SURVEY.md §4).

Ground truth is always plain Python dict-merge over the same hashed rows —
the sharded path must agree exactly with both it and the single-device
engine, for any shard count that divides the mesh.
"""

import numpy as np
import pytest

from map_oxidize_tpu.api import MapOutput, SumReducer, MinReducer
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.ops.hashing import HashDictionary, join_u64, SENTINEL, SENTINEL64
from map_oxidize_tpu.parallel import ShardedReduceEngine, make_mesh
from map_oxidize_tpu.runtime.engine import DeviceReduceEngine


def _rows(rng, n, key_space):
    keys = rng.integers(0, key_space, size=n, dtype=np.uint64)
    # avoid the (astronomically unlikely in practice) sentinel key
    keys = np.where(keys == np.uint64(SENTINEL64), np.uint64(0), keys)
    vals = rng.integers(1, 10, size=n, dtype=np.int32)
    hi = (keys >> np.uint64(32)).astype(np.uint32)
    lo = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return hi, lo, vals, keys


def _truth(keys, vals, combine="sum"):
    out = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        if combine == "sum":
            out[k] = out.get(k, 0) + v
        elif combine == "min":
            out[k] = min(out.get(k, 1 << 62), v)
    return out


def _readback(engine):
    hi, lo, vals, n = engine.finalize()
    hi = np.asarray(hi)
    lo = np.asarray(lo)
    vals = np.asarray(vals)
    live = ~((hi == np.uint32(SENTINEL)) & (lo == np.uint32(SENTINEL)))
    k64 = join_u64(hi[live], lo[live])
    return dict(zip(k64.tolist(), vals[live].tolist())), n


@pytest.mark.parametrize("num_shards", [1, 2, 8])
def test_sharded_matches_truth(rng, num_shards):
    cfg = JobConfig(batch_size=512, key_capacity=4096, backend="cpu",
                    num_shards=num_shards)
    eng = ShardedReduceEngine(cfg, SumReducer())
    hi, lo, vals, keys = _rows(rng, 3000, key_space=500)
    d = HashDictionary()
    # feed in 3 uneven chunks to exercise padding + multiple merges
    for sl in (slice(0, 1000), slice(1000, 1700), slice(1700, 3000)):
        eng.feed(MapOutput(hi=hi[sl], lo=lo[sl], values=vals[sl], dictionary=d))
    got, n = _readback(eng)
    want = _truth(keys, vals)
    assert got == want
    assert n == len(want)


def test_sharded_matches_single_device(rng):
    cfg = JobConfig(batch_size=256, key_capacity=2048, backend="cpu",
                    num_shards=8)
    hi, lo, vals, keys = _rows(rng, 2000, key_space=300)
    d = HashDictionary()
    out = MapOutput(hi=hi, lo=lo, values=vals, dictionary=d)

    sharded = ShardedReduceEngine(cfg, SumReducer())
    sharded.feed(out)
    single = DeviceReduceEngine(cfg, SumReducer())
    single.feed(out)

    got_s, n_s = _readback(sharded)
    hi1, lo1, vals1, n1 = single.finalize()
    hi1, lo1, vals1 = np.asarray(hi1)[:n1], np.asarray(lo1)[:n1], np.asarray(vals1)[:n1]
    got_1 = dict(zip(join_u64(hi1, lo1).tolist(), vals1.tolist()))
    assert got_s == got_1
    assert n_s == n1


def test_sharded_topk(rng):
    cfg = JobConfig(batch_size=512, key_capacity=4096, backend="cpu",
                    num_shards=8)
    eng = ShardedReduceEngine(cfg, SumReducer())
    hi, lo, vals, keys = _rows(rng, 4000, key_space=200)
    eng.feed(MapOutput(hi=hi, lo=lo, values=vals, dictionary=HashDictionary()))
    t_hi, t_lo, t_vals, n = eng.top_k(10)
    want = sorted(_truth(keys, vals).items(), key=lambda kv: -kv[1])[:10]
    got_counts = sorted(t_vals.tolist(), reverse=True)
    assert got_counts == [c for _, c in want]
    # every returned key's count matches the truth
    truth = _truth(keys, vals)
    for h, v in zip(join_u64(t_hi, t_lo).tolist(), t_vals.tolist()):
        assert truth[h] == v


def test_sharded_min_monoid(rng):
    cfg = JobConfig(batch_size=256, key_capacity=2048, backend="cpu",
                    num_shards=4)
    eng = ShardedReduceEngine(cfg, MinReducer())
    hi, lo, vals, keys = _rows(rng, 1500, key_space=100)
    eng.feed(MapOutput(hi=hi, lo=lo, values=vals, dictionary=HashDictionary()))
    got, n = _readback(eng)
    want = _truth(keys, vals, "min")
    assert got == want


def test_skewed_batch_no_overflow(rng):
    """A Zipf-hot key must not overflow the exchange: the local pre-combine
    collapses duplicates before routing, so bucket load tracks distinct keys."""
    cfg = JobConfig(batch_size=512, key_capacity=4096, backend="cpu",
                    num_shards=8)
    eng = ShardedReduceEngine(cfg, SumReducer())
    n = 512
    keys = rng.integers(0, 260, size=n, dtype=np.uint64)
    keys[: n // 2] = 7  # one key is half the batch
    vals = np.ones(n, np.int32)
    hi = (keys >> np.uint64(32)).astype(np.uint32)
    lo = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    eng.feed(MapOutput(hi=hi, lo=lo, values=vals, dictionary=HashDictionary()))
    got, _ = _readback(eng)
    assert got == _truth(keys, vals)


def test_padding_does_not_count_toward_bucket_overflow():
    """Regression (ADVICE r1): a mostly-padding batch must not trip
    ShuffleOverflowError.  8 distinct keys land one per bucket, but the
    512-row padded batch spreads ~8 round-robin pads into each 3-slot
    bucket; only REAL rows may count against cap — the dropped tail here is
    padding only, and no data is lost."""
    cfg = JobConfig(batch_size=512, key_capacity=4096, backend="cpu",
                    num_shards=8)
    eng = ShardedReduceEngine(cfg, SumReducer(), bucket_cap=3)
    keys = np.arange(8, dtype=np.uint64)  # bucket_of = (hi^lo)%8 -> 1 each
    vals = np.full(8, 5, np.int32)
    hi = (keys >> np.uint64(32)).astype(np.uint32)
    lo = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    eng.feed(MapOutput(hi=hi, lo=lo, values=vals, dictionary=HashDictionary()))
    got, n = _readback(eng)   # finalize health-checks: old code raised here
    assert got == _truth(keys, vals)
    assert n == 8


def test_real_bucket_overflow_still_raises():
    """The counter must still catch real drops: 8 distinct keys forced into
    ONE bucket with cap=3 loses rows, which must raise, not silently drop."""
    from map_oxidize_tpu.parallel.engine import ShuffleOverflowError

    cfg = JobConfig(batch_size=512, key_capacity=4096, backend="cpu",
                    num_shards=8)
    eng = ShardedReduceEngine(cfg, SumReducer(), bucket_cap=3)
    keys = (np.arange(8, dtype=np.uint64) << np.uint64(3))  # (hi^lo)%8 == 0
    vals = np.ones(8, np.int32)
    hi = (keys >> np.uint64(32)).astype(np.uint32)
    lo = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    eng.feed(MapOutput(hi=hi, lo=lo, values=vals, dictionary=HashDictionary()))
    with pytest.raises(ShuffleOverflowError):
        eng.finalize()


def test_topk_wider_than_shard_capacity(rng):
    """k > per-shard capacity must not silently truncate: each shard's whole
    accumulator is gathered, so up to min(k, S*cap) rows come back."""
    cfg = JobConfig(batch_size=512, key_capacity=64, backend="cpu",
                    num_shards=8)  # cap_per_shard = 8
    eng = ShardedReduceEngine(cfg, SumReducer())
    n = 400
    keys = rng.permutation(40).astype(np.uint64)  # 40 distinct keys
    keys = np.concatenate([keys] * 10)[:n]
    vals = np.ones(n, np.int32)
    hi = (keys >> np.uint64(32)).astype(np.uint32)
    lo = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    eng.feed(MapOutput(hi=hi, lo=lo, values=vals, dictionary=HashDictionary()))
    t_hi, t_lo, t_vals, cnt = eng.top_k(30)  # 30 > cap_per_shard=8
    truth = _truth(keys, vals)
    assert cnt == len(truth) == 40
    got = dict(zip(join_u64(t_hi, t_lo).tolist(), t_vals.tolist()))
    live = {h: v for h, v in got.items() if v > 0}
    assert len(live) == 30
    for h, v in live.items():
        assert truth[h] == v


def test_driver_e2e_sharded(tmp_path, rng):
    """Full driver run through the sharded engine (8 fake devices)."""
    from map_oxidize_tpu.runtime.driver import run_wordcount_job
    from map_oxidize_tpu.workloads.wordcount import make_wordcount
    from map_oxidize_tpu.workloads.reference_model import wordcount_model

    corpus = tmp_path / "c.txt"
    words = ["The", "the", "fox,", "dog", "a", "over", "Lazy"]
    text = "\n".join(" ".join(rng.choice(words, size=9)) for _ in range(200))
    corpus.write_text(text)
    cfg = JobConfig(input_path=str(corpus), output_path=str(tmp_path / "o.txt"),
                    backend="cpu", num_shards=8, batch_size=256,
                    key_capacity=1024, use_native=False)
    mapper, reducer = make_wordcount("ascii", use_native=False)
    res = run_wordcount_job(cfg, mapper, reducer)
    want = wordcount_model([text.encode()])
    assert res.counts == dict(want)


def test_sharded_vector_values(rng):
    """k-means-shaped payloads: [n, d] rows reduce per-dimension."""
    cfg = JobConfig(batch_size=256, key_capacity=1024, backend="cpu",
                    num_shards=4)
    eng = ShardedReduceEngine(cfg, SumReducer(), value_shape=(3,),
                              value_dtype=np.float32)
    n = 1000
    keys = rng.integers(0, 50, size=n, dtype=np.uint64)
    vecs = rng.normal(size=(n, 3)).astype(np.float32)
    hi = (keys >> np.uint64(32)).astype(np.uint32)
    lo = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    eng.feed(MapOutput(hi=hi, lo=lo, values=vecs, dictionary=HashDictionary()))
    hi_a, lo_a, vals_a, cnt = eng.finalize()
    hi_a, lo_a, vals_a = np.asarray(hi_a), np.asarray(lo_a), np.asarray(vals_a)
    live = ~((hi_a == np.uint32(SENTINEL)) & (lo_a == np.uint32(SENTINEL)))
    got = dict(zip(join_u64(hi_a[live], lo_a[live]).tolist(),
                   [tuple(r) for r in vals_a[live]]))
    for k in np.unique(keys):
        want = vecs[keys == k].sum(axis=0)
        # float32 sums are fold-order-dependent (pre-combine reorders them);
        # tolerance covers the non-associativity, not a correctness slack
        np.testing.assert_allclose(np.asarray(got[int(k)]), want,
                                   rtol=1e-4, atol=1e-5)
    assert cnt == len(np.unique(keys))
