"""Multi-host execution: real OS processes, one global 8-device CPU mesh,
Gloo collectives over the coordination service — the DCN path SURVEY §2
promises, without pod hardware.

Each process maps its chunk subset, the lockstep feed assembles global
batches with make_array_from_process_local_data, the all_to_all exchange
routes keys across the process boundary, and every process must read back
identical, oracle-exact results — including winner STRINGS, gathered
through the mesh (no shared state outside the collectives).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, os, sys
pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
corpus = sys.argv[4]; out_path = sys.argv[5]; workload = sys.argv[6]
ckpt = sys.argv[7] if len(sys.argv) > 7 and sys.argv[7] != "-" else None
final = sys.argv[8] if len(sys.argv) > 8 and sys.argv[8] != "-" else ""
precision = "highest"
if workload == "kmeans_bf16":  # kmeans with the bf16 storage/matmul mode
    workload, precision = "kmeans", "bf16"
kmeans_resume = workload == "kmeans_resume"
if kmeans_resume:
    workload = "kmeans"
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.parallel.distributed import (
    init_distributed, run_distributed_job)
init_distributed(f"127.0.0.1:{port}", num_processes=nproc, process_id=pid)

die_after = int(os.environ.get("_MOXT_TEST_DIE_AFTER_CHUNKS", "0"))
if die_after and pid == 1:
    # deterministic mid-run failure: die after N checkpoint saves (the
    # spilled prefix must survive and resume)
    from map_oxidize_tpu.runtime.checkpoint import CheckpointStore
    orig = CheckpointStore.save
    state = {"n": 0}
    def dying_save(self, idx, out, next_offset):
        orig(self, idx, out, next_offset)
        state["n"] += 1
        if state["n"] >= die_after:
            os._exit(3)
    CheckpointStore.save = dying_save

cfg = JobConfig(input_path=corpus, output_path=final, chunk_bytes=4096,
                batch_size=1 << 12, key_capacity=1 << 12, top_k=5,
                metrics=False, checkpoint_dir=ckpt,
                keep_intermediates=bool(ckpt),
                kmeans_k=4, kmeans_iters=3, kmeans_precision=precision)
if kmeans_resume:
    # interrupted-training shape: 2 iterations snapshot (kept), then a
    # 3-iteration run resumes the snapshot and runs only the last one
    import dataclasses
    run_distributed_job(dataclasses.replace(
        cfg, kmeans_iters=2, keep_intermediates=True), "kmeans")
    cfg = dataclasses.replace(cfg, keep_intermediates=False)
r = run_distributed_job(cfg, workload)
payload = {
    "n_keys": r.n_keys, "n_pairs": r.n_pairs, "records": r.records,
    "estimate": r.estimate, "flag_rounds": r.flag_rounds,
    "resumed": r.resumed_chunks,
    "top": [[f"{h:#018x}",
             None if w is None else w.decode("utf-8"), c]
            for h, w, c in r.top],
    "counts": {str(k): v for k, v in (r.counts or {}).items()},
    "centroids": None if r.centroids is None else r.centroids.tolist(),
    "resumed_iters": (r.metrics or {}).get("resumed_iters", 0),
}
with open(out_path, "w") as f:
    json.dump(payload, f, sort_keys=True)
print("child", pid, "ok")
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _write_corpus(path, lines=3000, seed=11):
    rng = np.random.default_rng(seed)
    words = [b"Alpha", b"beta,", b"Gamma.", b"delta", b"eps;", b"zeta"]
    with open(path, "wb") as f:
        for _ in range(lines):
            f.write(b" ".join(words[int(i)]
                              for i in rng.integers(0, 6, 6)) + b"\n")


def _env(devices: int):
    env = dict(os.environ)
    for k in ("PALLAS_AXON_POOL_IPS", "PJRT_LIBRARY_PATH",
              "TPU_LIBRARY_PATH", "PJRT_DEVICE", "TPU_ACCELERATOR_TYPE",
              "TPU_TOPOLOGY", "TPU_WORKER_HOSTNAMES", "_MOXT_DRYRUN_CHILD"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devices}"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _launch(tmp_path, corpus, nproc, workload, devices=None, ckpt=None,
            extra_env=None, expect_fail=False, timeout=420, final=None):
    """Run ``nproc`` child processes; returns (payload list, logs).  The
    free-port probe is inherently racy (bind/close/reuse), so the whole
    launch retries once on a fresh port.  ``devices`` is the PER-PROCESS
    local device count; the global mesh is nproc times that (default: an
    8-device global mesh regardless of process count)."""
    env = _env(devices if devices is not None else 8 // nproc)
    if extra_env:
        env.update(extra_env)
    outs = [str(tmp_path / f"out_{workload}_{i}.json") for i in range(nproc)]
    for attempt in range(2):
        port = _free_port()
        procs = [subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(i), str(nproc), str(port),
             str(corpus), outs[i], workload, ckpt or "-", final or "-"],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True) for i in range(nproc)]
        logs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                out = "(timeout)"
            logs.append(out)
        if expect_fail:
            return [p.returncode for p in procs], logs
        if all(p.returncode == 0 for p in procs):
            break
        if attempt == 1:
            for i, p in enumerate(procs):
                assert p.returncode == 0, f"process {i} failed:\n{logs[i]}"
    results = []
    for path in outs:
        with open(path) as f:
            results.append(json.load(f))
    return results, logs


def _wordcount_oracle(corpus):
    from map_oxidize_tpu.ops.hashing import moxt64_bytes
    from map_oxidize_tpu.workloads.reference_model import wordcount_model

    with open(corpus, "rb") as f:
        model = wordcount_model([f.read()])
    return model, {moxt64_bytes(w): c for w, c in model.items()}


@pytest.mark.parametrize("nproc,devices", [(2, 4), (4, 2)])
def test_multiprocess_wordcount_matches_oracle(tmp_path, nproc, devices):
    corpus = tmp_path / "c.txt"
    _write_corpus(corpus)
    results, _ = _launch(tmp_path, corpus, nproc, "wordcount",
                         devices=devices)
    model, want = _wordcount_oracle(corpus)

    # every process sees the SAME replicated result; `records` is local
    # (this process's mapped share) and must SUM to the corpus total
    local = [r.pop("records") for r in results]
    assert sum(local) == sum(model.values())
    for r in results[1:]:
        assert r == results[0]
    got = {int(k): v for k, v in results[0]["counts"].items()}
    assert got == want
    # top-k: counts match the oracle head AND the winner STRINGS are
    # resolved across processes (each word's bytes live in only some
    # processes' dictionaries)
    want_top = sorted(model.items(), key=lambda kv: (-kv[1], kv[0]))[:5]
    got_counts = [c for _h, _w, c in results[0]["top"]]
    assert got_counts == [c for _w, c in want_top]
    got_words = {w for _h, w, _c in results[0]["top"]}
    assert got_words == {w.decode() for w, _c in want_top}
    assert results[0]["flag_rounds"] >= 1


def test_two_process_invertedindex_matches_oracle(tmp_path):
    corpus = tmp_path / "ii.txt"
    _write_corpus(corpus, lines=1500)
    results, _ = _launch(tmp_path, corpus, 2, "invertedindex")
    from map_oxidize_tpu.workloads.inverted_index import inverted_index_model

    model = inverted_index_model(str(corpus))
    for r in results:
        r.pop("records")
    assert results[0] == results[1]
    assert results[0]["n_keys"] == len(model)
    assert results[0]["n_pairs"] == sum(len(d) for d in model.values())
    # tie-break is hash-ascending (the engine convention), so compare the
    # df sequence and each winner's correctness rather than exact order
    want_dfs = sorted((len(d) for d in model.values()), reverse=True)[:5]
    assert [c for _h, w, c in results[0]["top"]] == want_dfs
    for _h, w, c in results[0]["top"]:
        assert w is not None and len(model[w.encode()]) == c


def test_two_process_distinct_estimate(tmp_path):
    corpus = tmp_path / "d.txt"
    _write_corpus(corpus, lines=800)
    results, _ = _launch(tmp_path, corpus, 2, "distinct")
    for r in results:
        r.pop("records")
    assert results[0] == results[1]
    # 6-word vocab: HLL's linear-counting regime is near-exact
    assert abs(results[0]["estimate"] - 6) < 0.5


def test_two_process_checkpoint_resume(tmp_path):
    """Process 1 dies after spilling 2 chunks; the re-run resumes its
    spilled prefix (resumed > 0 on process 1) and the result is still
    oracle-exact."""
    corpus = tmp_path / "ck.txt"
    _write_corpus(corpus)
    ckpt = str(tmp_path / "ckpt")

    rcs, logs = _launch(tmp_path, corpus, 2, "wordcount", ckpt=ckpt,
                        extra_env={"_MOXT_TEST_DIE_AFTER_CHUNKS": "2"},
                        expect_fail=True, timeout=180)
    assert any(rc != 0 for rc in rcs), f"expected a failed first run: {logs}"
    # the dead process's spill survived
    assert os.path.isdir(os.path.join(ckpt, "proc_1"))

    results, _ = _launch(tmp_path, corpus, 2, "wordcount", ckpt=ckpt)
    _model, want = _wordcount_oracle(corpus)
    resumed = [r.pop("resumed") for r in results]
    for r in results:
        r.pop("records")
    assert results[0] == results[1]
    got = {int(k): v for k, v in results[0]["counts"].items()}
    assert got == want
    assert resumed[1] >= 2  # process 1 replayed its spilled prefix


def test_process_death_aborts_cleanly(tmp_path):
    """A process dying mid-run must produce a clean nonzero abort on the
    survivor (coordination-service heartbeat / collective failure), not a
    hang past the test timeout."""
    corpus = tmp_path / "dd.txt"
    _write_corpus(corpus)
    # no checkpoint dir: _MOXT_TEST_DIE_AFTER_CHUNKS needs one to count
    # saves, so use it WITH a ckpt dir but assert on process 0's fate
    ckpt = str(tmp_path / "ck2")
    rcs, logs = _launch(tmp_path, corpus, 2, "wordcount", ckpt=ckpt,
                        extra_env={"_MOXT_TEST_DIE_AFTER_CHUNKS": "1"},
                        expect_fail=True, timeout=240)
    assert rcs[1] != 0  # the deliberate death
    # the survivor must EXIT (nonzero), not hang: a timeout above would
    # have killed it and left "(timeout)" in its log
    assert rcs[0] is not None and rcs[0] != 0, f"survivor: {logs[0]}"
    assert "(timeout)" not in logs[0], (
        "survivor hung past the collective timeout instead of aborting:\n"
        + logs[0][-2000:])


def test_gather_strings_single_process():
    """Collective semantics degrade to identity in a single process:
    known hashes resolve, unknown hashes are absent, empty input is
    empty.  (Cross-process resolution is covered by the word-top tests
    above — each word's bytes live in only some processes.)"""
    from map_oxidize_tpu.ops.hashing import HashDictionary, moxt64_bytes
    from map_oxidize_tpu.parallel.distributed import gather_strings

    d = HashDictionary()
    h1, h2 = moxt64_bytes(b"alpha"), moxt64_bytes(b"beta")
    d.add(h1, b"alpha")
    got = gather_strings([h1, h2], d)
    assert got == {h1: b"alpha"}
    assert gather_strings([], d) == {}


def test_two_process_output_byte_identical_to_single(tmp_path):
    """--output parity (the reference's primary artifact,
    main.rs:170-182): a 2-process run writes per-partition shard files
    whose concatenated, sorted rows are byte-identical to the
    single-process final_result.txt — for wordcount AND invertedindex;
    the distributed distinct file (written once, registers replicated)
    must equal the single-process file outright."""
    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.runtime import run_job

    corpus = tmp_path / "po.txt"
    _write_corpus(corpus, lines=1200)

    def single(workload, out):
        run_job(JobConfig(input_path=str(corpus), output_path=str(out),
                          backend="cpu", num_shards=1, metrics=False,
                          chunk_bytes=4096), workload)
        return out.read_bytes()

    def parts(workload, out):
        _launch(tmp_path, corpus, 2, workload, final=str(out))
        shard_files = sorted(tmp_path.glob(out.name + ".part*"))
        assert [p.name for p in shard_files] == [
            out.name + ".part0of2", out.name + ".part1of2"]
        rows = []
        for p in shard_files:
            rows.extend(p.read_bytes().splitlines(keepends=True))
        return b"".join(sorted(rows))

    assert (parts("wordcount", tmp_path / "wc.txt")
            == single("wordcount", tmp_path / "wc_single.txt"))
    assert (parts("invertedindex", tmp_path / "ii.txt")
            == single("invertedindex", tmp_path / "ii_single.txt"))

    # wide-vocab corpus: most words live in only ONE process's chunks, so
    # partition resolution MUST go through the cross-process miss gather
    # (the 6-word corpus above resolves everything locally and would hide
    # a broken gather — it did in round 5: 64-bit hashes shipped as int64
    # were silently truncated to int32 by process_allgather)
    wide = tmp_path / "wide.txt"
    with open(wide, "wb") as f:
        for i in range(3000):
            f.write(b"unique%05d shared\n" % i)
    corpus = wide
    assert (parts("wordcount", tmp_path / "ww.txt")
            == single("wordcount", tmp_path / "ww_single.txt"))

    _launch(tmp_path, corpus, 2, "distinct", final=str(tmp_path / "d.txt"))
    assert ((tmp_path / "d.txt").read_bytes()
            == single("distinct", tmp_path / "d_single.txt"))


def test_two_process_kmeans_matches_single_controller(tmp_path):
    """Distributed k-means (the last multi-process carve-out, removed
    round 5): 2 Gloo processes × 4 local devices run the SAME jitted psum
    iteration as the single-controller 8-shard fit.  The two processes
    must agree BITWISE (one replicated result); against the
    single-controller run the Gloo allreduce sums shards in a different
    order, so the comparison is ulp-tight (measured: 1 ulp, ~1.2e-7) but
    not exact — float addition is not associative across collective
    topologies.  The oracle comparison uses the usual float tolerance,
    and process 0's --output file carries the replicated result."""
    rng = np.random.default_rng(5)
    pts = rng.normal(size=(1000, 8)).astype(np.float32)
    path = tmp_path / "pts.npy"
    np.save(path, pts)
    out = tmp_path / "cent.npy"
    results, _ = _launch(tmp_path, path, 2, "kmeans", final=str(out))
    got = [np.array(r["centroids"], np.float32) for r in results]
    np.testing.assert_array_equal(got[0], got[1])

    from map_oxidize_tpu.parallel.kmeans import kmeans_fit_sharded
    from map_oxidize_tpu.workloads.kmeans import kmeans_model

    single = kmeans_fit_sharded(pts, pts[:4].copy(), iters=3,
                                num_shards=8, backend="cpu")
    np.testing.assert_allclose(got[0], single, rtol=2e-6, atol=2e-7)
    want = pts[:4].copy()
    for _ in range(3):
        want = kmeans_model(pts, want)
    np.testing.assert_allclose(got[0], want, rtol=1e-3, atol=1e-3)
    np.testing.assert_array_equal(np.load(out), got[0])


def test_two_process_kmeans_checkpoint_resume(tmp_path):
    """Distributed k-means checkpoint/resume (the last 'no effect on
    distributed kmeans' carve-out, removed this round): a 2-iteration run
    snapshots per iteration through process 0 (kept), then a 3-iteration
    run resumes the snapshot on BOTH processes and runs only the final
    iteration.  The resumed trajectory must match the straight 3-iteration
    single-controller fit within collective-order tolerance, both
    processes must agree bitwise, and the metrics must record the resume
    (resumed_iters == 2)."""
    rng = np.random.default_rng(7)
    pts = rng.normal(size=(1000, 8)).astype(np.float32)
    path = tmp_path / "pr.npy"
    np.save(path, pts)
    ckpt = str(tmp_path / "kckpt")
    results, _ = _launch(tmp_path, path, 2, "kmeans_resume", ckpt=ckpt)
    got = [np.array(r["centroids"], np.float32) for r in results]
    np.testing.assert_array_equal(got[0], got[1])
    assert [r["resumed_iters"] for r in results] == [2, 2]

    from map_oxidize_tpu.parallel.kmeans import kmeans_fit_sharded

    single = kmeans_fit_sharded(pts, pts[:4].copy(), iters=3,
                                num_shards=8, backend="cpu")
    np.testing.assert_allclose(got[0], single, rtol=2e-6, atol=2e-7)


def test_two_process_kmeans_bf16_matches_sharded(tmp_path):
    """The bf16 storage/matmul mode through the multi-process path: local
    row blocks cast to ml_dtypes.bfloat16 before assembly must produce
    the same (replicated, bitwise-identical across processes) centroids
    as the single-controller sharded bf16 fit within collective-order
    tolerance — the same numerics family, so drift stays at the ulp
    level, NOT the bf16 rounding bound."""
    rng = np.random.default_rng(6)
    centers = rng.normal(0, 10, size=(4, 8)).astype(np.float32)
    pts = (centers[rng.integers(0, 4, 900)]
           + rng.normal(0, 0.5, size=(900, 8))).astype(np.float32)
    pts[:4] = centers
    path = tmp_path / "pb.npy"
    np.save(path, pts)
    results, _ = _launch(tmp_path, path, 2, "kmeans_bf16")
    got = [np.array(r["centroids"], np.float32) for r in results]
    np.testing.assert_array_equal(got[0], got[1])

    from map_oxidize_tpu.parallel.kmeans import kmeans_fit_sharded

    single = kmeans_fit_sharded(pts, pts[:4].copy(), iters=3,
                                num_shards=8, backend="cpu",
                                precision="bf16")
    np.testing.assert_allclose(got[0], single, rtol=2e-5, atol=2e-5)
