"""Multi-host execution: two real OS processes, one global 8-device CPU
mesh (4 local devices each), Gloo collectives over the coordination
service — the DCN path SURVEY §2 promises, without pod hardware.

Each process maps its chunk subset, the lockstep feed assembles global
batches with make_array_from_process_local_data, the all_to_all exchange
routes keys across the process boundary, and both processes must read back
identical, oracle-exact counts."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, sys
pid = int(sys.argv[1]); port = sys.argv[2]; corpus = sys.argv[3]
out_path = sys.argv[4]
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.parallel.distributed import (
    init_distributed, run_distributed_wordcount)
init_distributed(f"127.0.0.1:{port}", num_processes=2, process_id=pid)
cfg = JobConfig(input_path=corpus, output_path="", chunk_bytes=4096,
                batch_size=1 << 12, key_capacity=1 << 12, top_k=5,
                metrics=False)
counts, top = run_distributed_wordcount(cfg, "wordcount")
with open(out_path, "w") as f:
    json.dump({"counts": {str(k): v for k, v in counts.items()},
               "top": top}, f, sort_keys=True)
print("child", pid, "ok", len(counts))
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_wordcount_matches_oracle(tmp_path):
    rng = np.random.default_rng(11)
    words = [b"Alpha", b"beta,", b"Gamma.", b"delta", b"eps;", b"zeta"]
    corpus = tmp_path / "c.txt"
    with open(corpus, "wb") as f:
        for _ in range(3000):
            f.write(b" ".join(words[int(i)]
                              for i in rng.integers(0, 6, 6)) + b"\n")

    env = dict(os.environ)
    for k in ("PALLAS_AXON_POOL_IPS", "PJRT_LIBRARY_PATH",
              "TPU_LIBRARY_PATH", "PJRT_DEVICE", "TPU_ACCELERATOR_TYPE",
              "TPU_TOPOLOGY", "TPU_WORKER_HOSTNAMES", "_MOXT_DRYRUN_CHILD"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    outs = [str(tmp_path / f"out_{i}.json") for i in range(2)]
    # the free-port probe is inherently racy (bind/close/reuse); retry the
    # whole launch once on a fresh port before declaring failure
    for attempt in range(2):
        port = _free_port()
        procs = [subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(i), str(port), str(corpus),
             outs[i]],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True) for i in range(2)]
        logs = []
        for p in procs:
            out, _ = p.communicate(timeout=420)
            logs.append(out)
        if all(p.returncode == 0 for p in procs):
            break
        if attempt == 1:
            for i, p in enumerate(procs):
                assert p.returncode == 0, f"process {i} failed:\n{logs[i]}"

    # oracle: hash-keyed reference-semantics counts
    from map_oxidize_tpu.ops.hashing import moxt64_bytes
    from map_oxidize_tpu.workloads.reference_model import wordcount_model

    with open(corpus, "rb") as f:
        model = wordcount_model([f.read()])
    want = {moxt64_bytes(w): c for w, c in model.items()}

    results = []
    for path in outs:
        with open(path) as f:
            d = json.load(f)
        results.append(d)
    # both processes see the SAME replicated result
    assert results[0] == results[1]
    got = {int(k): v for k, v in results[0]["counts"].items()}
    assert got == want
    # device top-k matches the oracle's count-descending head
    want_top = sorted(want.values(), reverse=True)[:5]
    assert [c for _, c in results[0]["top"]] == want_top
