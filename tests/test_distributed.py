"""Multi-host execution: real OS processes, one global 8-device CPU mesh,
Gloo collectives over the coordination service — the DCN path SURVEY §2
promises, without pod hardware.

Each process maps its chunk subset, the lockstep feed assembles global
batches with make_array_from_process_local_data, the all_to_all exchange
routes keys across the process boundary, and every process must read back
identical, oracle-exact results — including winner STRINGS, gathered
through the mesh (no shared state outside the collectives).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, os, sys
pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
corpus = sys.argv[4]; out_path = sys.argv[5]; workload = sys.argv[6]
ckpt = sys.argv[7] if len(sys.argv) > 7 and sys.argv[7] != "-" else None
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.parallel.distributed import (
    init_distributed, run_distributed_job)
init_distributed(f"127.0.0.1:{port}", num_processes=nproc, process_id=pid)

die_after = int(os.environ.get("_MOXT_TEST_DIE_AFTER_CHUNKS", "0"))
if die_after and pid == 1:
    # deterministic mid-run failure: die after N checkpoint saves (the
    # spilled prefix must survive and resume)
    from map_oxidize_tpu.runtime.checkpoint import CheckpointStore
    orig = CheckpointStore.save
    state = {"n": 0}
    def dying_save(self, idx, out, next_offset):
        orig(self, idx, out, next_offset)
        state["n"] += 1
        if state["n"] >= die_after:
            os._exit(3)
    CheckpointStore.save = dying_save

cfg = JobConfig(input_path=corpus, output_path="", chunk_bytes=4096,
                batch_size=1 << 12, key_capacity=1 << 12, top_k=5,
                metrics=False, checkpoint_dir=ckpt,
                keep_intermediates=bool(ckpt))
r = run_distributed_job(cfg, workload)
payload = {
    "n_keys": r.n_keys, "n_pairs": r.n_pairs, "records": r.records,
    "estimate": r.estimate, "flag_rounds": r.flag_rounds,
    "resumed": r.resumed_chunks,
    "top": [[f"{h:#018x}",
             None if w is None else w.decode("utf-8"), c]
            for h, w, c in r.top],
    "counts": {str(k): v for k, v in (r.counts or {}).items()},
}
with open(out_path, "w") as f:
    json.dump(payload, f, sort_keys=True)
print("child", pid, "ok")
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _write_corpus(path, lines=3000, seed=11):
    rng = np.random.default_rng(seed)
    words = [b"Alpha", b"beta,", b"Gamma.", b"delta", b"eps;", b"zeta"]
    with open(path, "wb") as f:
        for _ in range(lines):
            f.write(b" ".join(words[int(i)]
                              for i in rng.integers(0, 6, 6)) + b"\n")


def _env(devices: int):
    env = dict(os.environ)
    for k in ("PALLAS_AXON_POOL_IPS", "PJRT_LIBRARY_PATH",
              "TPU_LIBRARY_PATH", "PJRT_DEVICE", "TPU_ACCELERATOR_TYPE",
              "TPU_TOPOLOGY", "TPU_WORKER_HOSTNAMES", "_MOXT_DRYRUN_CHILD"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devices}"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _launch(tmp_path, corpus, nproc, workload, devices=None, ckpt=None,
            extra_env=None, expect_fail=False, timeout=420):
    """Run ``nproc`` child processes; returns (payload list, logs).  The
    free-port probe is inherently racy (bind/close/reuse), so the whole
    launch retries once on a fresh port.  ``devices`` is the PER-PROCESS
    local device count; the global mesh is nproc times that (default: an
    8-device global mesh regardless of process count)."""
    env = _env(devices if devices is not None else 8 // nproc)
    if extra_env:
        env.update(extra_env)
    outs = [str(tmp_path / f"out_{workload}_{i}.json") for i in range(nproc)]
    for attempt in range(2):
        port = _free_port()
        procs = [subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(i), str(nproc), str(port),
             str(corpus), outs[i], workload, ckpt or "-"],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True) for i in range(nproc)]
        logs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                out = "(timeout)"
            logs.append(out)
        if expect_fail:
            return [p.returncode for p in procs], logs
        if all(p.returncode == 0 for p in procs):
            break
        if attempt == 1:
            for i, p in enumerate(procs):
                assert p.returncode == 0, f"process {i} failed:\n{logs[i]}"
    results = []
    for path in outs:
        with open(path) as f:
            results.append(json.load(f))
    return results, logs


def _wordcount_oracle(corpus):
    from map_oxidize_tpu.ops.hashing import moxt64_bytes
    from map_oxidize_tpu.workloads.reference_model import wordcount_model

    with open(corpus, "rb") as f:
        model = wordcount_model([f.read()])
    return model, {moxt64_bytes(w): c for w, c in model.items()}


@pytest.mark.parametrize("nproc,devices", [(2, 4), (4, 2)])
def test_multiprocess_wordcount_matches_oracle(tmp_path, nproc, devices):
    corpus = tmp_path / "c.txt"
    _write_corpus(corpus)
    results, _ = _launch(tmp_path, corpus, nproc, "wordcount",
                         devices=devices)
    model, want = _wordcount_oracle(corpus)

    # every process sees the SAME replicated result; `records` is local
    # (this process's mapped share) and must SUM to the corpus total
    local = [r.pop("records") for r in results]
    assert sum(local) == sum(model.values())
    for r in results[1:]:
        assert r == results[0]
    got = {int(k): v for k, v in results[0]["counts"].items()}
    assert got == want
    # top-k: counts match the oracle head AND the winner STRINGS are
    # resolved across processes (each word's bytes live in only some
    # processes' dictionaries)
    want_top = sorted(model.items(), key=lambda kv: (-kv[1], kv[0]))[:5]
    got_counts = [c for _h, _w, c in results[0]["top"]]
    assert got_counts == [c for _w, c in want_top]
    got_words = {w for _h, w, _c in results[0]["top"]}
    assert got_words == {w.decode() for w, _c in want_top}
    assert results[0]["flag_rounds"] >= 1


def test_two_process_invertedindex_matches_oracle(tmp_path):
    corpus = tmp_path / "ii.txt"
    _write_corpus(corpus, lines=1500)
    results, _ = _launch(tmp_path, corpus, 2, "invertedindex")
    from map_oxidize_tpu.workloads.inverted_index import inverted_index_model

    model = inverted_index_model(str(corpus))
    for r in results:
        r.pop("records")
    assert results[0] == results[1]
    assert results[0]["n_keys"] == len(model)
    assert results[0]["n_pairs"] == sum(len(d) for d in model.values())
    # tie-break is hash-ascending (the engine convention), so compare the
    # df sequence and each winner's correctness rather than exact order
    want_dfs = sorted((len(d) for d in model.values()), reverse=True)[:5]
    assert [c for _h, w, c in results[0]["top"]] == want_dfs
    for _h, w, c in results[0]["top"]:
        assert w is not None and len(model[w.encode()]) == c


def test_two_process_distinct_estimate(tmp_path):
    corpus = tmp_path / "d.txt"
    _write_corpus(corpus, lines=800)
    results, _ = _launch(tmp_path, corpus, 2, "distinct")
    for r in results:
        r.pop("records")
    assert results[0] == results[1]
    # 6-word vocab: HLL's linear-counting regime is near-exact
    assert abs(results[0]["estimate"] - 6) < 0.5


def test_two_process_checkpoint_resume(tmp_path):
    """Process 1 dies after spilling 2 chunks; the re-run resumes its
    spilled prefix (resumed > 0 on process 1) and the result is still
    oracle-exact."""
    corpus = tmp_path / "ck.txt"
    _write_corpus(corpus)
    ckpt = str(tmp_path / "ckpt")

    rcs, logs = _launch(tmp_path, corpus, 2, "wordcount", ckpt=ckpt,
                        extra_env={"_MOXT_TEST_DIE_AFTER_CHUNKS": "2"},
                        expect_fail=True, timeout=180)
    assert any(rc != 0 for rc in rcs), f"expected a failed first run: {logs}"
    # the dead process's spill survived
    assert os.path.isdir(os.path.join(ckpt, "proc_1"))

    results, _ = _launch(tmp_path, corpus, 2, "wordcount", ckpt=ckpt)
    _model, want = _wordcount_oracle(corpus)
    resumed = [r.pop("resumed") for r in results]
    for r in results:
        r.pop("records")
    assert results[0] == results[1]
    got = {int(k): v for k, v in results[0]["counts"].items()}
    assert got == want
    assert resumed[1] >= 2  # process 1 replayed its spilled prefix


def test_process_death_aborts_cleanly(tmp_path):
    """A process dying mid-run must produce a clean nonzero abort on the
    survivor (coordination-service heartbeat / collective failure), not a
    hang past the test timeout."""
    corpus = tmp_path / "dd.txt"
    _write_corpus(corpus)
    # no checkpoint dir: _MOXT_TEST_DIE_AFTER_CHUNKS needs one to count
    # saves, so use it WITH a ckpt dir but assert on process 0's fate
    ckpt = str(tmp_path / "ck2")
    rcs, logs = _launch(tmp_path, corpus, 2, "wordcount", ckpt=ckpt,
                        extra_env={"_MOXT_TEST_DIE_AFTER_CHUNKS": "1"},
                        expect_fail=True, timeout=240)
    assert rcs[1] != 0  # the deliberate death
    # the survivor must EXIT (nonzero), not hang: a timeout above would
    # have killed it and left "(timeout)" in its log
    assert rcs[0] is not None and rcs[0] != 0, f"survivor: {logs[0]}"
    assert "(timeout)" not in logs[0], (
        "survivor hung past the collective timeout instead of aborting:\n"
        + logs[0][-2000:])


def test_gather_strings_single_process():
    """Collective semantics degrade to identity in a single process:
    known hashes resolve, unknown hashes are absent, empty input is
    empty.  (Cross-process resolution is covered by the word-top tests
    above — each word's bytes live in only some processes.)"""
    from map_oxidize_tpu.ops.hashing import HashDictionary, moxt64_bytes
    from map_oxidize_tpu.parallel.distributed import gather_strings

    d = HashDictionary()
    h1, h2 = moxt64_bytes(b"alpha"), moxt64_bytes(b"beta")
    d.add(h1, b"alpha")
    got = gather_strings([h1, h2], d)
    assert got == {h1: b"alpha"}
    assert gather_strings([], d) == {}
