"""The driver-contract entry points must be hermetic.

``dryrun_multichip`` validates sharding semantics, which are
platform-independent — so it must pass even when the environment says a TPU
exists but the TPU is unusable (the MULTICHIP_r01/r02 failure mode: a
libtpu version mismatch killed a CPU-only correctness check).  These tests
poison the TPU-related environment and require the dryrun to still go green.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_dryrun(n: int, poison: dict) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    # start from a clean slate: drop the conftest's CPU pinning so the
    # subprocess sees what a driver invocation on a TPU host would see
    env.pop("JAX_PLATFORMS", None)
    env.pop("_MOXT_DRYRUN_CHILD", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(poison)
    code = f"import __graft_entry__ as g; g.dryrun_multichip({n})"
    return subprocess.run(
        [sys.executable, "-c", code], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=600,
    )


@pytest.mark.parametrize("poison", [
    # driver asks for the TPU platform but no TPU exists on this host
    {"JAX_PLATFORMS": "tpu"},
    # libtpu points at garbage — the r02 failure shape
    {"TPU_LIBRARY_PATH": "/nonexistent/libtpu.so",
     "PJRT_DEVICE": "TPU"},
    # axon-style site hook trigger: when its sitecustomize is importable it
    # re-registers a TPU plugin and overrides jax_platforms; the respawn
    # must strip the trigger so the child stays CPU-only
    {"PALLAS_AXON_POOL_IPS": "203.0.113.1"},
])
def test_dryrun_survives_sick_tpu_env(poison):
    res = _run_dryrun(4, poison)
    assert res.returncode == 0, (
        f"dryrun died under poisoned env {poison}:\n"
        f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    )
    assert "dryrun_multichip(4): ok" in res.stdout
    assert "device-map ok" in res.stdout


def test_dryrun_respawn_replaces_inherited_device_count():
    # an inherited force-flag for the WRONG pool size must be replaced,
    # not duplicated (XLA takes the first occurrence)
    res = _run_dryrun(2, {
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    assert res.returncode == 0, res.stderr
    assert "dryrun_multichip(2): ok" in res.stdout
