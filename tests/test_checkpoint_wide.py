"""Checkpoint/resume for the paths wired in round 3: the inverted-index
driver (per-chunk spill + replay, like wordcount) and the device-map
drivers (engine-state snapshots — map outputs never exist on the host
there).  Each test proves byte-identical output to an uncheckpointed run
after a mid-run kill."""

import os

import numpy as np
import pytest

from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.runtime import run_job


def _make_corpus(path, n_lines=3000, seed=3):
    rng = np.random.default_rng(seed)
    words = [b"alpha", b"beta", b"Gamma,", b"delta.", b"eps", b"zeta"]
    with open(path, "wb") as f:
        for _ in range(n_lines):
            k = int(rng.integers(3, 9))
            f.write(b" ".join(words[int(i)] for i in rng.integers(0, 6, k)))
            f.write(b"\n")


def _cfg(corpus, out, ckdir, **kw):
    base = dict(input_path=str(corpus), output_path=str(out),
                checkpoint_dir=ckdir, chunk_bytes=8 * 1024, backend="cpu",
                metrics=False, num_map_workers=1, max_retries=0)
    base.update(kw)
    return JobConfig(**base)


@pytest.mark.parametrize("use_native", [False, True])
def test_invertedindex_resume_from_partial_prefix(tmp_path, use_native):
    """Kill-equivalent: spill everything, truncate the spill to a prefix,
    resume — output must be byte-identical and only the tail re-mapped."""
    corpus = tmp_path / "c.txt"
    _make_corpus(corpus)
    if use_native:
        from map_oxidize_tpu.native.bindings import load_or_none

        if load_or_none() is None:
            pytest.skip("native build unavailable")
    ckdir = str(tmp_path / "ck")

    want = tmp_path / "want.txt"
    run_job(_cfg(corpus, want, None, num_shards=1, use_native=use_native),
            "invertedindex")

    got = tmp_path / "got.txt"
    run_job(_cfg(corpus, got, ckdir, num_shards=1, use_native=use_native,
                 keep_intermediates=True), "invertedindex")
    chunks = sorted(n for n in os.listdir(ckdir) if n.endswith(".npz"))
    assert len(chunks) >= 4, chunks
    # simulate the kill: only the first 2 chunks survived
    for name in chunks[2:]:
        os.unlink(os.path.join(ckdir, name))

    got2 = tmp_path / "got2.txt"
    res = run_job(_cfg(corpus, got2, ckdir, num_shards=1,
                       use_native=use_native), "invertedindex")
    assert got2.read_bytes() == want.read_bytes()
    assert res.metrics["chunks"] == len(chunks)  # 2 replayed + tail remapped
    assert not os.path.isdir(ckdir)  # success cleans up


def _dying_capped(monkeypatch, die_after):
    """Patch the device-map chunk iterator to raise after N chunks — the
    mid-run kill for a path whose map happens inline on device."""
    from map_oxidize_tpu.io import splitter
    from map_oxidize_tpu.runtime import device_map

    real = splitter.iter_chunks_capped

    def dying(path, chunk_bytes, start_offset=0):
        for i, c in enumerate(real(path, chunk_bytes, start_offset)):
            if i >= die_after:
                raise KeyboardInterrupt("simulated kill")
            yield c

    monkeypatch.setattr(device_map, "iter_chunks_capped", dying)


@pytest.mark.parametrize("num_shards", [1, 4])
def test_device_map_snapshot_resume(tmp_path, monkeypatch, num_shards):
    corpus = tmp_path / "c.txt"
    _make_corpus(corpus, n_lines=6000)
    ckdir = str(tmp_path / "ck")
    kw = dict(mapper="device", num_shards=num_shards, chunk_bytes=2048,
              device_chunk_keys=1 << 12)

    want = tmp_path / "want.txt"
    run_job(_cfg(corpus, want, None, **kw), "wordcount")

    # die after enough chunks that at least one snapshot was taken
    # (_SNAP_EVERY chunks single / _SNAP_EVERY groups sharded)
    from map_oxidize_tpu.runtime.device_map import _SNAP_EVERY

    die_after = _SNAP_EVERY * num_shards + 2
    _dying_capped(monkeypatch, die_after)
    got = tmp_path / "got.txt"
    with pytest.raises(KeyboardInterrupt):
        run_job(_cfg(corpus, got, ckdir, **kw), "wordcount")
    assert os.path.isfile(os.path.join(ckdir, "snapshot.npz"))

    monkeypatch.undo()  # resume runs unkilled
    res = run_job(_cfg(corpus, got, ckdir, **kw), "wordcount")
    assert got.read_bytes() == want.read_bytes()
    # the resumed run mapped fewer chunks than the total (prefix skipped)
    full = run_job(_cfg(corpus, tmp_path / "x.txt", None, **kw), "wordcount")
    assert res.metrics["chunks"] == full.metrics["chunks"]
    assert not os.path.isdir(ckdir)
