"""Native (C++) map path: bit-identity with the Python fallback.

The contract (native/csrc/moxt_native.cpp header comment): same token
boundaries as bytes.split(), same lowercasing as bytes.lower(), same FNV-1a64
as ops/hashing.py, same n-gram join as workloads/bigram.py.  Every test
compares full (hash -> count) dicts and dictionaries, not just top-k.
"""

import numpy as np
import pytest

from map_oxidize_tpu.native.bindings import load_or_none
from map_oxidize_tpu.ops.hashing import join_u64
from map_oxidize_tpu.workloads.bigram import BigramMapper
from map_oxidize_tpu.workloads.wordcount import WordCountMapper

native = load_or_none()
pytestmark = pytest.mark.skipif(native is None, reason="native build unavailable")


def _as_dict(out):
    k = join_u64(out.hi, out.lo)
    return dict(zip(k.tolist(), out.values.tolist()))


def _dict_bytes(out):
    return dict(out.dictionary.items())


CASES = [
    b"",
    b"   \t\n  ",
    b"hello",
    b"The quick Brown fox JUMPS over the lazy dog, the the THE",
    b"a b c d e f g h a b c a b a",
    b"tabs\tand\nnewlines\rand\x0bvertical\x0cfeeds mixed  double  spaces",
    b"punct, stays! attached. to? words; always: (parens) [too]",
    b"x" * 10000 + b" " + b"y" * 3 + b" end",
    "unicode café naïve 中文 words".encode("utf-8"),
    b"trailing space ",
    b" leading",
    b"A" * 4096,
]


@pytest.mark.parametrize("case", CASES, ids=range(len(CASES)))
def test_wordcount_native_matches_python(case):
    py = WordCountMapper("ascii", use_native=False).map_chunk(case)
    nat = native.map_wordcount(case)
    assert _as_dict(nat) == _as_dict(py)
    assert _dict_bytes(nat) == _dict_bytes(py)
    assert nat.records_in == py.records_in


@pytest.mark.parametrize("case", CASES, ids=range(len(CASES)))
def test_bigram_native_matches_python(case):
    py = BigramMapper("ascii", use_native=False).map_chunk(case)
    nat = native.map_bigram(case)
    assert _as_dict(nat) == _as_dict(py)
    assert _dict_bytes(nat) == _dict_bytes(py)
    assert nat.records_in == py.records_in


def test_large_random_corpus_identical(rng):
    words = [bytes(rng.choice(list(b"abcXYZ,."), size=rng.integers(1, 12)))
             for _ in range(500)]
    chunk = b" ".join(words[i] for i in rng.integers(0, 500, size=50_000))
    py = WordCountMapper("ascii", use_native=False).map_chunk(chunk)
    nat = native.map_wordcount(chunk)
    assert _as_dict(nat) == _as_dict(py)
    assert _dict_bytes(nat) == _dict_bytes(py)
    assert nat.records_in == py.records_in == 50_000
    # many uniques -> exercises table growth
    assert len(_as_dict(nat)) > 400


def test_trigram_sanity():
    out = native.map_ngram(b"a b c d", 3)
    k = join_u64(out.hi, out.lo).tolist()
    dd = dict(out.dictionary.items())
    got = {dd[h]: v for h, v in zip(k, out.values.tolist())}
    assert got == {b"a b c": 1, b"b c d": 1}
    assert out.records_in == 2


def test_trigram_mixed_separators_hash_identically():
    """Single-space windows take the zero-copy contiguous path; tab /
    multi-space windows take the scratch join.  Both must emit the SAME
    joined-bytes keys ("a b c") for the same token sequence."""
    a = native.map_ngram(b"a b c d", 3)
    b = native.map_ngram(b"a\tb  c \t d", 3)
    for out in (a, b):
        k = join_u64(out.hi, out.lo).tolist()
        dd = dict(out.dictionary.items())
        got = {dd[h]: v for h, v in zip(k, out.values.tolist())}
        assert got == {b"a b c": 1, b"b c d": 1}, got


def test_count_u64_matches_numpy_unique():
    """Fused MSD+LSD unique+count == np.unique across shapes that stress
    it: uniform hashes, heavy Zipf duplicates (one bucket >> cache), all
    same key, single key, and empty."""
    from map_oxidize_tpu.native.build import count_u64_or_none

    rng = np.random.default_rng(17)
    cases = [
        rng.integers(0, 2**64, size=100_000, dtype=np.uint64),        # uniform
        rng.choice(rng.integers(0, 2**64, size=50, dtype=np.uint64),
                   size=200_000).astype(np.uint64),                   # hot keys
        np.full(10_000, 0xDEADBEEFCAFEBABE, np.uint64),               # one key
        np.array([7], np.uint64),
        np.empty(0, np.uint64),
    ]
    for keys in cases:
        want_u, want_c = np.unique(keys, return_counts=True)
        got = count_u64_or_none(keys.copy())
        if got is None:
            pytest.skip("native library unavailable")
        got_u, got_c = got
        np.testing.assert_array_equal(got_u, want_u)
        np.testing.assert_array_equal(got_c.astype(np.int64), want_c)


def test_group_by_key_matches_sort_path():
    """Native hash->dense-id group-by == stable-sort + boundary-scan CSR,
    including duplicate-heavy Zipf keys, feed-order (doc) stability, a
    single key, and the contract rejections (missing key, duplicate uniq)."""
    from map_oxidize_tpu.native.build import group_by_key_or_none

    rng = np.random.default_rng(23)
    vocab = rng.integers(0, 2**64, size=300, dtype=np.uint64)
    keys = vocab[rng.integers(0, 300, size=50_000)]
    docs = np.arange(50_000, dtype=np.int64)  # feed order = doc order
    uniq = np.unique(keys)

    got = group_by_key_or_none(keys, docs, uniq)
    if got is None:
        pytest.skip("native library unavailable")
    offsets, grouped = got
    order = np.argsort(keys, kind="stable")
    ks, ds = keys[order], docs[order]
    bounds = np.flatnonzero(np.concatenate([[True], ks[1:] != ks[:-1]]))
    np.testing.assert_array_equal(offsets,
                                  np.append(bounds, ks.shape[0]))
    np.testing.assert_array_equal(grouped, ds)

    one = group_by_key_or_none(np.full(5, 7, np.uint64),
                               np.arange(5, dtype=np.int64),
                               np.array([7], np.uint64))
    np.testing.assert_array_equal(one[0], [0, 5])
    np.testing.assert_array_equal(one[1], np.arange(5))

    # a fed key absent from uniq -> contract violation -> None (fallback)
    assert group_by_key_or_none(keys, docs, uniq[:-1]) is None
    # duplicate uniq entry -> ambiguous ids -> None
    dup = np.sort(np.concatenate([uniq, uniq[:1]]))
    assert group_by_key_or_none(keys, docs, dup) is None


def test_sort_u64_blocks_matches_numpy():
    """Blocks radix (16-bit digits, first pass reads blocks in place) vs
    np.sort across edge shapes: many uneven blocks, empty blocks mixed
    in, all-equal keys (every pass skipped -> copy-through), single
    block, duplicate-heavy keys, and n==0."""
    from map_oxidize_tpu.native.build import sort_u64_blocks_or_none

    rng = np.random.default_rng(23)
    cases = [
        [rng.integers(0, 2**64, size=int(n), dtype=np.uint64)
         for n in (1000, 1, 0, 37, 4096)],
        [np.full(500, 0xABCDEF, np.uint64), np.full(300, 0xABCDEF, np.uint64)],
        [rng.choice(rng.integers(0, 2**64, 20, dtype=np.uint64),
                    1000).astype(np.uint64)],
        [np.empty(0, np.uint64)],
        [],
    ]
    for blocks in cases:
        got = sort_u64_blocks_or_none(list(blocks))
        assert got is not None
        want = np.sort(np.concatenate(blocks)
                       if blocks else np.empty(0, np.uint64))
        np.testing.assert_array_equal(got, want)
    # unsuitable input (wrong dtype) declines rather than mis-sorting
    assert sort_u64_blocks_or_none([np.arange(4, dtype=np.int64)]) is None
