"""Accumulator capacity growth + overflow semantics (both engines).

Review-derived regressions: an exactly-full accumulator must NOT raise;
growth must kick in below key_capacity; actual drops past key_capacity must
raise; initial_key_capacity=0 must be rejected at config validation.
"""

import numpy as np
import pytest

from map_oxidize_tpu.api import MapOutput, SumReducer
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.ops.hashing import HashDictionary, join_u64, SENTINEL
from map_oxidize_tpu.parallel import ShardedReduceEngine, ShuffleOverflowError
from map_oxidize_tpu.runtime.engine import CapacityError, DeviceReduceEngine


def _out(keys, vals=None):
    keys = np.asarray(keys, np.uint64)
    if vals is None:
        vals = np.ones(len(keys), np.int32)
    hi = (keys >> np.uint64(32)).astype(np.uint32)
    lo = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return MapOutput(hi=hi, lo=lo, values=vals, dictionary=HashDictionary())


def _live(engine):
    hi, lo, vals, n = engine.finalize()
    hi, lo, vals = np.asarray(hi), np.asarray(lo), np.asarray(vals)
    m = ~((hi == np.uint32(SENTINEL)) & (lo == np.uint32(SENTINEL)))
    return dict(zip(join_u64(hi[m], lo[m]).tolist(), vals[m].tolist())), n


def test_exact_fill_is_not_an_error():
    """512 distinct keys into capacity exactly 512 must succeed."""
    cfg = JobConfig(backend="cpu", batch_size=512, key_capacity=512,
                    initial_key_capacity=512)
    eng = DeviceReduceEngine(cfg, SumReducer())
    eng.feed(_out(np.arange(512)))
    got, n = _live(eng)
    assert n == 512 and len(got) == 512


def test_growth_below_max():
    """Distinct keys 16x the initial capacity must grow, not raise."""
    cfg = JobConfig(backend="cpu", batch_size=512, key_capacity=8192,
                    initial_key_capacity=512)
    eng = DeviceReduceEngine(cfg, SumReducer())
    for start in range(0, 8192, 512):
        eng.feed(_out(np.arange(start, start + 512)))
    got, n = _live(eng)
    assert n == 8192
    assert eng.capacity >= 8192
    assert all(v == 1 for v in got.values())


def test_drop_past_max_raises():
    cfg = JobConfig(backend="cpu", batch_size=512, key_capacity=256,
                    initial_key_capacity=256)
    eng = DeviceReduceEngine(cfg, SumReducer())
    eng.feed(_out(np.arange(512)))
    with pytest.raises(CapacityError):
        eng.finalize()


def test_sharded_growth_below_max(rng):
    cfg = JobConfig(backend="cpu", batch_size=512, key_capacity=1 << 14,
                    initial_key_capacity=64, num_shards=8)
    eng = ShardedReduceEngine(cfg, SumReducer())
    keys = rng.permutation(6000).astype(np.uint64)
    for s in range(0, 6000, 500):
        eng.feed(_out(keys[s:s + 500]))
    got, n = _live(eng)
    assert n == 6000
    assert all(v == 1 for v in got.values())


def test_sharded_drop_past_max_raises(rng):
    cfg = JobConfig(backend="cpu", batch_size=512, key_capacity=64,
                    initial_key_capacity=64, num_shards=8)
    eng = ShardedReduceEngine(cfg, SumReducer())
    eng.feed(_out(np.arange(2000)))
    with pytest.raises(ShuffleOverflowError):
        eng.finalize()


def test_zero_initial_capacity_rejected():
    with pytest.raises(ValueError):
        JobConfig(initial_key_capacity=0).validate()
