"""Packaging: the framework must install and run as a package outside this
checkout (the reference at least ships a Cargo manifest,
/root/reference/Cargo.toml:1-6).  Builds the wheel with the image's
setuptools (no network: --no-build-isolation), installs it into a temp
--target, and drives the console entry point from a foreign cwd with ONLY
the install dir on PYTHONPATH."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_wheel_installs_and_cli_runs_outside_checkout(tmp_path):
    wheel_dir = tmp_path / "wheels"
    r = subprocess.run(
        [sys.executable, "-m", "pip", "wheel", "--no-build-isolation",
         "--no-deps", "-w", str(wheel_dir), REPO],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"wheel build failed:\n{r.stdout}\n{r.stderr}"
    wheels = list(wheel_dir.glob("map_oxidize_tpu-*.whl"))
    assert len(wheels) == 1, f"expected one wheel, got {wheels}"

    target = tmp_path / "site"
    r = subprocess.run(
        [sys.executable, "-m", "pip", "install", "--no-deps", "--target",
         str(target), str(wheels[0])],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"install failed:\n{r.stdout}\n{r.stderr}"
    # the C++ source ships in the wheel (lazy build at first use)
    assert (target / "map_oxidize_tpu" / "native" / "csrc"
            / "moxt_native.cpp").is_file()

    corpus = tmp_path / "c.txt"
    corpus.write_bytes(b"b a\na b a\n")
    out = tmp_path / "final_result.txt"
    env = dict(os.environ)
    env["PYTHONPATH"] = str(target)  # ONLY the installed package
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-m", "map_oxidize_tpu", "wordcount", str(corpus),
         "--backend", "cpu", "--no-native", "--top-k", "2",
         "--output", str(out)],
        capture_output=True, text=True, timeout=300, cwd=str(tmp_path),
        env=env)
    assert r.returncode == 0, f"CLI failed:\n{r.stdout}\n{r.stderr}"
    assert "a: 3" in r.stdout
    assert out.read_bytes() == b"a 3\nb 2\n"

    # console-script metadata points at the CLI main (the script shim
    # itself lands in --target/bin, which a real install puts on PATH)
    import zipfile

    with zipfile.ZipFile(wheels[0]) as z:
        meta = next(n for n in z.namelist()
                    if n.endswith("entry_points.txt"))
        text = z.read(meta).decode()
    assert "map-oxidize-tpu = map_oxidize_tpu.cli:main" in text
