"""Device tokenizer vs the Python reference semantics.

The device path uses a different hash family (prefix-summable polynomial pair)
than the host mappers (FNV-1a64) — parity is on the (token -> count) mapping
reconstructed through representative offsets, exactly how the real driver
builds its dictionary.
"""

from collections import Counter

import numpy as np
import pytest

from map_oxidize_tpu.ops.device_tokenize import (
    DeviceTokenizer,
    token_at,
)
from map_oxidize_tpu.ops.hashing import join_u64


def _device_counts(chunk: bytes, chunk_bytes: int = 4096, out_keys: int = 1024):
    tok = DeviceTokenizer(chunk_bytes, out_keys)
    u_hi, u_lo, counts, reps, packed = [
        np.asarray(x) for x in tok.map_chunk_device(chunk)
    ]
    nu, n_dropped, n_tokens = packed[:3].astype(np.int64).tolist()
    assert int(n_dropped) == 0
    nu = int(nu)
    got = {}
    seen_hashes = set()
    for h, c, r in zip(join_u64(u_hi[:nu], u_lo[:nu]).tolist(),
                       counts[:nu].tolist(), reps[:nu].tolist()):
        word = token_at(chunk, r)
        assert h not in seen_hashes
        seen_hashes.add(h)
        assert word not in got, f"two hashes for {word!r}"
        got[word] = c
    return got, int(n_tokens)


CASES = [
    b"",
    b"   \t\n  ",
    b"hello",
    b"The quick Brown fox JUMPS over the lazy dog, the the THE",
    b"a b c d e f g h a b c a b a",
    b"tabs\tand\nnewlines\rand\x0bvertical\x0cfeeds mixed  double  spaces",
    b"punct, stays! attached. to? words; always: (parens) [too]",
    b"x" * 1000 + b" " + b"y" * 3 + b" end",
    "unicode café naïve 中文 words".encode("utf-8"),
    b"trailing space ",
    b" leading",
    b"A" * 512,
    b"a \x00b \x00ab ab b",  # NUL bytes are token bytes, not separators
]


@pytest.mark.parametrize("case", CASES, ids=range(len(CASES)))
def test_device_matches_python(case):
    got, n_tokens = _device_counts(case)
    want = Counter(case.lower().split())
    assert got == dict(want)
    assert n_tokens == sum(want.values())


def test_chunk_boundary_padding(rng):
    """A chunk that exactly fills chunk_bytes (no pad) and one that ends
    mid-token must both count correctly."""
    text = b"alpha beta gamma " * 16
    got, _ = _device_counts(text[:256], chunk_bytes=256)
    assert got == dict(Counter(text[:256].lower().split()))


def test_random_corpus_with_duplicates(rng):
    words = [bytes(rng.choice(list(b"abcdeXYZ,."),
                              size=rng.integers(1, 10)))
             for _ in range(300)]
    chunk = b" ".join(words[i] for i in rng.integers(0, 300, size=20_000))
    got, n_tokens = _device_counts(chunk, chunk_bytes=1 << 20,
                                   out_keys=4096)
    want = Counter(chunk.lower().split())
    assert got == dict(want)
    assert n_tokens == 20_000


def test_out_keys_overflow_detected():
    chunk = b" ".join(b"w%d" % i for i in range(200))
    tok = DeviceTokenizer(4096, out_keys=64)
    *_, packed = [np.asarray(x) for x in tok.map_chunk_device(chunk)]
    n_unique, n_dropped, _ = packed[:3].astype(np.int64).tolist()
    assert n_unique == 200
    assert n_dropped == 136
