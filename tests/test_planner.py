"""Plan observatory (ISSUE-18 tentpole): the predicted-vs-actual
planning loop and its prediction-error gate.

Layers covered:

* the shared auto-B roofline (``planner.solve_batch``) — rule selection
  and clamping, exactly the math the dispatch resolver applies;
* shape estimation and pin detection from the config object;
* ``build_plan`` provenance — a cold plan records ``platform_default``
  and NO prediction, a pinned override records ``pinned``, a fabricated
  calibration curve yields ``curve`` provenance with a per-MB-scaled
  predicted wall and the feed-wait deepen rule (capped);
* ``obs.plan`` publish/finalize/render — gauges, the error math, and
  the report text;
* the calibration store's workload rows — accumulate/curve round-trip,
  numeric merge, and the doctored-key refusal;
* the read-side curve APIs (``program_curve``,
  ``interpolate_latency_ms``);
* the ledger gate (points, not relative percent; missing baseline is
  unknown, not zero), the trend direction, the critpath headline's
  guarded fidelity gauge, the ``plan-model-drift`` default SLO rule,
  and the ``plan/dispatch_*`` gauge aliases.
"""

import dataclasses
import math

import pytest

from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.obs import calib as calib_mod
from map_oxidize_tpu.obs import plan as plan_mod
from map_oxidize_tpu.obs.calib import CalibMismatch, CalibStore
from map_oxidize_tpu.obs.metrics import MetricsRegistry
from map_oxidize_tpu.runtime import planner

IDENT = {"platform": "host", "device_count": 0, "topology": "1x0"}


def _attrib(wall_ms, buckets):
    attributed = sum(buckets.values())
    return {
        "schema": "moxt-attrib-v1",
        "wall_ms": wall_ms,
        "attributed_ms": attributed,
        "unattributed_ms": wall_ms - attributed,
        "unattributed_pct": 100.0 * (wall_ms - attributed) / wall_ms,
        "buckets": {name: {"ms": ms, "pct": 100.0 * ms / wall_ms}
                    for name, ms in buckets.items()},
    }


def _store_with_workload(workload="wordcount", corpus_bytes=float(1 << 20),
                         wall_ms=1000.0, buckets=None, ident=None):
    store = CalibStore()
    n = store.accumulate_workload(
        ident or IDENT, workload, corpus_bytes,
        _attrib(wall_ms, buckets if buckets is not None
                else {"device_compute": 600.0, "feed_wait": 100.0}))
    assert n == 1
    return store


# --- solve_batch (the shared roofline) -------------------------------------


def test_solve_batch_no_measurements_uses_default():
    b, rule = planner.solve_batch(150.0)
    assert (b, rule) == (4, "default_no_measurements")
    b, _ = planner.solve_batch(150.0, default_auto=100, max_b=64)
    assert b == 64


def test_solve_batch_overlap_host_produce():
    # produce 20ms, compute 5ms: headroom 15ms -> B = ceil(150/15) = 10
    b, rule = planner.solve_batch(150.0, compute_ms=5.0, produce_ms=20.0)
    assert (b, rule) == (10, "overlap_host_produce")


def test_solve_batch_amortize_vs_compute():
    # no produce measurement: amortize the floor against compute alone
    b, rule = planner.solve_batch(150.0, compute_ms=40.0)
    assert (b, rule) == (math.ceil(150.0 / 40.0), "amortize_vs_compute")
    # device-bound (produce < compute) takes the same rule
    b, rule = planner.solve_batch(150.0, compute_ms=40.0, produce_ms=10.0)
    assert rule == "amortize_vs_compute"


def test_solve_batch_clamps():
    b, _ = planner.solve_batch(1e6, compute_ms=0.1, max_b=64)
    assert b == 64
    b, _ = planner.solve_batch(0.0, compute_ms=1e9)
    assert b == 1


# --- shape + pins -----------------------------------------------------------


def test_estimate_shape(tmp_path):
    corpus = tmp_path / "c.txt"
    corpus.write_bytes(b"x" * 4096)
    cfg = JobConfig(input_path=str(corpus))
    shape = planner.estimate_shape(cfg, "wordcount")
    assert shape["corpus_bytes"] == 4096
    assert shape["est_rows"] == 4096 // 16
    assert shape["n_chunks"] == 1
    assert shape["record_model"] is False
    assert planner.estimate_shape(cfg, "sort")["record_model"] is True
    # unreadable input: zeros, never a raise
    missing = planner.estimate_shape(
        JobConfig(input_path=str(tmp_path / "nope")), "wordcount")
    assert missing["corpus_bytes"] == 0 and missing["est_rows"] == 0


def test_pinned_knobs_from_config_defaults():
    assert planner._pinned_knobs(JobConfig()) == set()
    assert planner._pinned_knobs(
        JobConfig(pipeline_depth=3)) == {"pipeline_depth"}
    assert planner._pinned_knobs(
        JobConfig(sort_sample=128, shuffle_transport="disk")) == {
            "sort_sample", "shuffle_transport"}


# --- build_plan provenance --------------------------------------------------


def test_cold_plan_is_platform_default(tmp_path):
    corpus = tmp_path / "c.txt"
    corpus.write_bytes(b"x" * 8192)
    doc = planner.build_plan(JobConfig(input_path=str(corpus)),
                             "wordcount", calib_prior=None)
    assert doc["schema"] == plan_mod.PLAN_SCHEMA
    assert doc["provenance"] == "platform_default"
    assert "predicted" not in doc
    assert doc["pins"] == []
    assert set(doc["knobs"]) == set(planner.PLAN_KNOBS)
    for row in doc["knobs"].values():
        assert row["provenance"] in plan_mod.PROVENANCES


def test_pinned_override_recorded_as_pin(tmp_path):
    corpus = tmp_path / "c.txt"
    corpus.write_bytes(b"x" * 8192)
    doc = planner.build_plan(
        JobConfig(input_path=str(corpus), pipeline_depth=3),
        "wordcount", calib_prior=None)
    assert doc["pins"] == ["pipeline_depth"]
    row = doc["knobs"]["pipeline_depth"]
    assert row["value"] == 3
    assert row["provenance"] == "pinned"
    assert row["evidence"] == {"requested": 3}


def test_warm_plan_predicts_and_scales(tmp_path):
    corpus = tmp_path / "c.txt"
    corpus.write_bytes(b"x" * (2 << 20))  # 2 MB vs a 1 MB curve
    ident = calib_mod.run_identity()
    store = _store_with_workload(wall_ms=1000.0, ident=ident)
    doc = planner.build_plan(JobConfig(input_path=str(corpus)),
                             "wordcount", calib_prior=store)
    assert doc["provenance"] == "curve"
    pred = doc["predicted"]
    # per-MB rate 1000ms/MB x 2MB corpus
    assert pred["wall_ms"] == pytest.approx(2000.0)
    assert pred["buckets"]["device_compute"] == pytest.approx(1200.0)
    assert pred["curve_runs"] == 1
    # low feed-wait share (10%): the curve CONFIRMS the default depth
    row = doc["knobs"]["pipeline_depth"]
    assert row["provenance"] == "curve"
    assert row["value"] == JobConfig().pipeline_depth
    assert row["evidence"]["feed_wait_share_pct"] == pytest.approx(10.0)


def test_warm_plan_deepens_on_feed_wait_and_caps(tmp_path):
    corpus = tmp_path / "c.txt"
    corpus.write_bytes(b"x" * (1 << 20))
    ident = calib_mod.run_identity()
    starved = {"device_compute": 300.0, "feed_wait": 400.0}  # 40% share
    store = _store_with_workload(wall_ms=1000.0, buckets=starved,
                                 ident=ident)
    doc = planner.build_plan(JobConfig(input_path=str(corpus)),
                             "wordcount", calib_prior=store)
    row = doc["knobs"]["pipeline_depth"]
    assert row["value"] == JobConfig().pipeline_depth + 1
    assert row["provenance"] == "curve"
    assert row["evidence"]["deepened_from"] == JobConfig().pipeline_depth
    # at the ceiling the curve stops deepening (depth 4 is a PIN here,
    # so provenance flips to pinned and the value holds)
    doc = planner.build_plan(
        JobConfig(input_path=str(corpus),
                  pipeline_depth=planner.MAX_PLANNED_DEPTH),
        "wordcount", calib_prior=store)
    assert (doc["knobs"]["pipeline_depth"]["value"]
            == planner.MAX_PLANNED_DEPTH)


# --- obs.plan publish / finalize / render -----------------------------------


class _FakeObs:
    def __init__(self):
        self.registry = MetricsRegistry()


def test_publish_flattens_plan_gauges(tmp_path):
    corpus = tmp_path / "c.txt"
    corpus.write_bytes(b"x" * 8192)
    doc = planner.build_plan(JobConfig(input_path=str(corpus)),
                             "wordcount", calib_prior=None)
    reg = MetricsRegistry()
    plan_mod.publish(reg, doc)
    assert reg.gauges["plan/mode"] == "auto"
    assert reg.gauges["plan/provenance"] == "platform_default"
    assert reg.gauges["plan/pipeline_depth"] == 2
    assert reg.gauges["plan/pipeline_depth_provenance"] == "default"
    assert "plan/predicted_wall_ms" not in reg.gauges
    plan_mod.publish(None, doc)  # bare-registry callers never raise


def test_finalize_scores_prediction():
    doc = {"predicted": {"wall_ms": 1500.0, "buckets": {}},
           "provenance": "curve"}
    obs = _FakeObs()
    out = plan_mod.finalize(obs, doc, _attrib(1000.0,
                                              {"device_compute": 700.0}))
    assert out["actual"]["wall_ms"] == 1000.0
    assert out["actual"]["buckets"]["device_compute"] == 700.0
    assert out["model_error_pct"] == pytest.approx(50.0)
    assert obs.registry.gauges["plan/model_error_pct"] == 50.0
    assert obs.registry.gauges["plan/actual_wall_ms"] == 1000.0


def test_finalize_cold_plan_attaches_actual_without_error():
    doc = {"provenance": "platform_default"}
    obs = _FakeObs()
    out = plan_mod.finalize(obs, doc, _attrib(800.0, {"compile": 500.0}))
    assert out["actual"]["wall_ms"] == 800.0
    assert "model_error_pct" not in out
    assert "plan/model_error_pct" not in obs.registry.gauges
    # no attribution (crashed before finalize): doc passes through
    assert plan_mod.finalize(obs, {"x": 1}, None) == {"x": 1}


def test_render_warm_and_cold(tmp_path):
    corpus = tmp_path / "c.txt"
    corpus.write_bytes(b"x" * (1 << 20))
    ident = calib_mod.run_identity()
    store = _store_with_workload(ident=ident)
    doc = planner.build_plan(JobConfig(input_path=str(corpus)),
                             "wordcount", calib_prior=store)
    plan_mod.finalize(_FakeObs(), doc,
                      _attrib(900.0, {"device_compute": 500.0}))
    text = plan_mod.render(doc)
    assert "plan vs actual: wordcount" in text
    assert "model error" in text
    assert "[curve  ]" in text
    assert "predicted" in text and "actual" in text
    cold = planner.build_plan(JobConfig(input_path=str(corpus)),
                              "wordcount", calib_prior=None)
    plan_mod.finalize(_FakeObs(), cold,
                      _attrib(900.0, {"device_compute": 500.0}))
    assert "no prediction (platform_default)" in plan_mod.render(cold)


# --- calibration store: workload rows ---------------------------------------


def test_accumulate_workload_and_curve_roundtrip():
    store = _store_with_workload(corpus_bytes=float(2 << 20),
                                 wall_ms=500.0,
                                 buckets={"host_sort": 200.0})
    curve = calib_mod.workload_curve(store, IDENT, "wordcount")
    assert curve["runs"] == 1
    assert curve["wall_ms_per_mb"] == pytest.approx(250.0)
    assert curve["buckets_ms_per_mb"]["host_sort"] == pytest.approx(100.0)
    assert curve["mean_corpus_bytes"] == pytest.approx(float(2 << 20))
    assert calib_mod.workload_curve(store, IDENT, "sort") is None
    assert calib_mod.workload_curve(None, IDENT, "wordcount") is None


def test_accumulate_workload_refuses_unusable_runs():
    store = CalibStore()
    ok = _attrib(100.0, {"compile": 50.0})
    assert store.accumulate_workload(IDENT, "", 1024.0, ok) == 0
    assert store.accumulate_workload(IDENT, "wc", 1024.0, None) == 0
    assert store.accumulate_workload(IDENT, "wc", 0.0, ok) == 0
    assert store.accumulate_workload(
        IDENT, "wc", 1024.0, {"wall_ms": 0.0}) == 0
    assert "workloads" not in store.doc


def test_workload_rows_merge_numerically():
    a = _store_with_workload(corpus_bytes=float(1 << 20), wall_ms=100.0)
    b = _store_with_workload(corpus_bytes=float(1 << 20), wall_ms=300.0)
    a.merge_from(b.doc)
    row = next(iter(a.doc["workloads"].values()))
    assert row["runs"] == 2
    assert row["wall_ms"] == pytest.approx(400.0)
    assert row["corpus_bytes"] == pytest.approx(float(2 << 20))
    # identity fields survived the numeric merge untouched
    assert row["workload"] == "wordcount"
    assert row["device_count"] == IDENT["device_count"]
    calib_mod.validate_doc(a.doc)


def test_doctored_workload_key_refuses():
    store = _store_with_workload()
    key = next(iter(store.doc["workloads"]))
    row = store.doc["workloads"].pop(key)
    store.doc["workloads"][key.replace("wordcount", "sort")] = row
    with pytest.raises(CalibMismatch, match="torn/doctored"):
        calib_mod.validate_doc(store.doc)
    clean = CalibStore()
    with pytest.raises(CalibMismatch):
        clean.merge_from(store.doc)


# --- read-side curves -------------------------------------------------------


def test_program_curve_reads_per_call_rates():
    store = CalibStore()
    key = calib_mod._prog_key(IDENT, "kmeans/stream_step")
    store.doc["programs"][key] = dict(
        IDENT, program="kmeans/stream_step", dispatches=10,
        dispatch_ms=80.0, compute_ms=30.0, compute_samples=10,
        compiles=1, compile_ms=100.0, runs=2)
    curve = calib_mod.program_curve(store, IDENT, "kmeans/stream_step")
    assert curve["dispatch_ms_per_call"] == pytest.approx(8.0)
    assert curve["compute_ms_per_sample"] == pytest.approx(3.0)
    assert curve["runs"] == 2
    assert calib_mod.program_curve(store, IDENT, "other") is None
    assert calib_mod.program_curve(None, IDENT, "x") is None


def test_interpolate_latency_log_linear_and_clamped():
    store = CalibStore()
    for nbytes, lat, bucket in ((1024.0, 1.0, "1KB"),
                                (1024.0 * 1024, 3.0, "1MB")):
        key = calib_mod._comm_key(IDENT, "psum", "p", bucket)
        store.doc["comms"][key] = dict(
            IDENT, collective="psum", program="p", shape_bucket=bucket,
            calls=4, bytes=nbytes * 4, latency_ms=lat * 4,
            latency_samples=4, runs=1)
    f = calib_mod.interpolate_latency_ms
    assert f(store, IDENT, "psum", 1024.0) == pytest.approx(1.0)
    assert f(store, IDENT, "psum", 1.0) == pytest.approx(1.0)  # clamp lo
    assert f(store, IDENT, "psum", 1e9) == pytest.approx(3.0)  # clamp hi
    # geometric midpoint of a log-linear curve: halfway latency
    assert f(store, IDENT, "psum", 32768.0) == pytest.approx(2.0)
    assert f(store, IDENT, "other", 1024.0) is None
    assert f(store, IDENT, "psum", 1024.0, program="q") is None


# --- ledger gate, trend, critpath, SLO rule ---------------------------------


def _entry(metrics):
    return {"workload": "wordcount", "config_hash": "h", "version": "v",
            "corpus_bytes": 1, "n_processes": 1, "phases_s": {},
            "metrics": metrics}


def test_ledger_gate_plan_model_error_points():
    from map_oxidize_tpu.obs.ledger import diff_entries

    lo = 5.0
    hi = lo + plan_mod.PLAN_ERROR_GATE_POINTS + 25.0
    d = diff_entries(_entry({"plan/model_error_pct": lo}),
                     _entry({"plan/model_error_pct": hi}), force=True)
    assert any("plan model drift" in r for r in d["regressions"])
    ok = diff_entries(_entry({"plan/model_error_pct": lo}),
                      _entry({"plan/model_error_pct": lo + 25.0}),
                      force=True)
    assert not any("plan model" in r for r in ok["regressions"])
    # no baseline (first warm run after a cold one) is unknown, not 0
    fresh = diff_entries(_entry({}), _entry({"plan/model_error_pct": hi}),
                         force=True)
    assert not any("plan model" in r for r in fresh["regressions"])
    # improving error never flags
    better = diff_entries(_entry({"plan/model_error_pct": hi}),
                          _entry({"plan/model_error_pct": lo}), force=True)
    assert not any("plan model" in r for r in better["regressions"])


def test_trend_ranks_model_error_up_is_bad():
    from map_oxidize_tpu.obs.trend import _direction

    assert _direction("plan/model_error_pct", 40.0) == "regressed"
    assert _direction("plan/model_error_pct", -40.0) == "improved"
    assert _direction("critpath/model_error_pct", 40.0) == "regressed"


def test_critpath_headline_model_error_guarded():
    from map_oxidize_tpu.obs.critpath import headline

    doc = {"blame": {}, "slack": {}, "degenerate": True, "wall_ms": 100.0,
           "segments": [{"ms": 60.0}], "bound_by": "x",
           "path_over_wall_pct": 100.0, "model_error_pct": 7.5}
    assert headline(doc)["critpath/model_error_pct"] == 7.5
    del doc["model_error_pct"]
    assert "critpath/model_error_pct" not in headline(doc)


def test_scheduler_publishes_median_plan_error(tmp_path):
    # the plan-model-drift rule watches the MEDIAN of recently finished
    # jobs, so one noisy micro-job cannot trip it; a server that never
    # saw a warm prediction publishes nothing (silent by construction)
    from map_oxidize_tpu.config import ServeConfig
    from map_oxidize_tpu.serve.scheduler import Scheduler

    class _Job:
        started_unix_s = None
        finished_unix_s = None
        submitted_unix_s = 0.0
        first_deferred_unix_s = None

        def __init__(self, summary):
            self.summary = summary

    sch = Scheduler(ServeConfig(spool_dir=str(tmp_path)))
    sch.server_registry = MetricsRegistry()
    for err in (10.0, 12.0, 900.0):
        sch._record_slo_metrics(_Job({"plan/model_error_pct": err}),
                                "done", 1)
    assert sch.server_registry.gauges["plan/model_error_pct"] == 12.0
    # a cold job (no prediction) neither publishes nor clears
    sch._record_slo_metrics(_Job({}), "done", 1)
    assert sch.server_registry.gauges["plan/model_error_pct"] == 12.0
    cold = Scheduler(ServeConfig(spool_dir=str(tmp_path / "cold")))
    cold.server_registry = MetricsRegistry()
    cold._record_slo_metrics(_Job({}), "done", 0)
    assert "plan/model_error_pct" not in cold.server_registry.gauges


def test_plan_model_drift_slo_rule():
    from map_oxidize_tpu.obs.slo import DEFAULT_RULES, SloRule

    rules = [SloRule(**r) for r in DEFAULT_RULES]
    drift = [r for r in rules if r.name == "plan-model-drift"]
    assert len(drift) == 1
    drift[0].validate()
    assert drift[0].metric == "plan/model_error_pct"
    assert drift[0].scope == "serve"
    assert drift[0].evidence == "plan/predicted_wall_ms"


# --- gauge namespaces + knob application ------------------------------------


def test_record_dispatch_batch_writes_plan_aliases():
    from map_oxidize_tpu.runtime.dispatch import record_dispatch_batch

    reg = MetricsRegistry()
    record_dispatch_batch(reg, 8, {"mode": "auto", "rule": "r",
                                   "floor_ms": 2.5})
    # primary planner namespace and the historical alias agree
    assert reg.gauges["plan/dispatch_batch"] == 8
    assert reg.gauges["dispatch/batch"] == 8
    assert reg.gauges["plan/dispatch_batch_mode"] == "auto"
    assert reg.gauges["dispatch/batch_mode"] == "auto"
    assert reg.gauges["plan/dispatch_floor_ms"] == 2.5
    assert reg.gauges["dispatch/floor_ms"] == 2.5


def test_obs_knob_prefers_plan_value():
    from map_oxidize_tpu.obs import Obs, Tracer

    obs = Obs(registry=MetricsRegistry(), tracer=Tracer(enabled=False))
    assert obs.knob("pipeline_depth", 2) == 2
    obs.plan = {"knobs": {"pipeline_depth": {"value": 3,
                                             "provenance": "curve"}}}
    assert obs.knob("pipeline_depth", 2) == 3
    assert obs.knob("chunk_bytes", 7) == 7  # absent knob: fallback


def test_config_validates_plan_mode():
    JobConfig(plan="off").validate()
    with pytest.raises(ValueError, match="plan must be"):
        JobConfig(plan="maybe").validate()


def test_plan_field_is_dataclass_default_auto():
    # _pinned_knobs depends on dataclass defaults staying the source of
    # truth; guard the knob surface against silent renames
    names = {f.name for f in dataclasses.fields(JobConfig)}
    assert set(planner.PLAN_KNOBS) <= names
    assert "plan" in names
