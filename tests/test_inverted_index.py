"""Inverted-index workload (BASELINE config #4): native and Python mappers
vs the pure-host oracle, end-to-end job parity, postings file format."""

import numpy as np
import pytest

from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.native.bindings import load_or_none
from map_oxidize_tpu.runtime import run_job
from map_oxidize_tpu.runtime.driver import run_inverted_index_job
from map_oxidize_tpu.workloads.inverted_index import (
    InvertedIndexMapper,
    inverted_index_model,
)

native = load_or_none()

CORPUS = (b"the cat sat on the mat\n"
          b"the DOG ran\n"
          b"\n"
          b"cat cat cat dog\n"
          b"punct, stays! the cat.\n"
          b"tabs\tand spaces  mixed\n")


def _write(tmp_path, data=CORPUS):
    p = tmp_path / "docs.txt"
    p.write_bytes(data)
    return str(p)


def _job_postings(path, **kw):
    cfg = JobConfig(input_path=path, output_path="", backend="cpu",
                    metrics=False, **kw)
    return run_inverted_index_job(cfg).postings


@pytest.mark.parametrize("sort_mode", ["host", "device"])
def test_collect_sort_modes_match_oracle(tmp_path, sort_mode):
    """Both sort placements (host lexsort / device lax.sort) must produce
    the oracle postings through the single-chip engine."""
    p = _write(tmp_path)
    got = _job_postings(p, num_shards=1, collect_sort=sort_mode)
    assert got == inverted_index_model(p)


def test_job_matches_oracle(tmp_path):
    p = _write(tmp_path)
    assert _job_postings(p) == inverted_index_model(p)


def test_multi_chunk_matches_single(tmp_path):
    p = _write(tmp_path)
    whole = _job_postings(p)
    chunked = _job_postings(p, chunk_bytes=32)
    assert whole == chunked == inverted_index_model(p)


@pytest.mark.skipif(native is None, reason="native build unavailable")
def test_python_mapper_matches_native(tmp_path):
    p = _write(tmp_path)
    py = InvertedIndexMapper(use_native=False).map_docs(CORPUS, 0)
    nat = InvertedIndexMapper(use_native=True).map_docs(CORPUS, 0)

    def rows(out):
        out.ensure_planes()  # native emits the compact (keys64, docs64) form
        k = (out.hi.astype(np.uint64) << np.uint64(32)) | out.lo
        d = (out.values[:, 0].astype(np.uint64) << np.uint64(32)) \
            | out.values[:, 1]
        return sorted(zip(k.tolist(), d.tolist()))

    assert rows(py) == rows(nat)
    assert dict(py.dictionary.items()) == dict(nat.dictionary.items())
    assert py.records_in == nat.records_in


def test_base_doc_offsets(tmp_path):
    # doc ids are absolute byte offsets: shifting base shifts every id
    out0 = InvertedIndexMapper(use_native=False).map_docs(b"a b\nc a\n", 0)
    out9 = InvertedIndexMapper(use_native=False).map_docs(b"a b\nc a\n", 9)
    d0 = sorted((out0.values[:, 1]).tolist())
    d9 = sorted((out9.values[:, 1]).tolist())
    assert [x + 9 for x in d0] == d9


def test_empty_and_blank_docs(tmp_path):
    p = _write(tmp_path, b"\n\n\nword\n\n")
    post = _job_postings(p)
    assert post == {b"word": [3]}
    empty = _write(tmp_path, b"")
    assert _job_postings(empty) == {}


def test_postings_file_roundtrip(tmp_path):
    p = _write(tmp_path)
    outp = tmp_path / "postings.txt"
    cfg = JobConfig(input_path=p, output_path=str(outp), backend="cpu",
                    metrics=False)
    res = run_job(cfg, "invertedindex")
    lines = outp.read_bytes().decode().strip().split("\n")
    assert len(lines) == len(res.postings)
    got = {}
    for ln in lines:
        term, docs = ln.split("\t")
        got[term.encode()] = [int(x) for x in docs.split()]
    assert got == res.postings
    # deterministic: re-run byte-identical
    before = outp.read_bytes()
    run_job(cfg, "invertedindex")
    assert outp.read_bytes() == before


def test_larger_random_corpus(tmp_path, rng):
    words = [bytes(rng.choice(list(b"abcdeXY,."),
                              size=rng.integers(1, 9))) for _ in range(80)]
    lines = []
    for _ in range(400):
        k = rng.integers(0, 12)
        lines.append(b" ".join(words[i] for i in rng.integers(0, 80, size=k)))
    p = _write(tmp_path, b"\n".join(lines) + b"\n")
    assert _job_postings(p, chunk_bytes=257) == inverted_index_model(p)


def test_sharded_collect_matches_single_device(tmp_path, rng):
    """Inverted index over the 8-device mesh: hash-routed all_to_all collect
    + per-shard sort must produce exactly the single-device postings (term
    segments are disjoint across shards by routing)."""
    words = ["the", "Fox,", "dog", "jumps", "over", "LAZY", "a", "end."]
    corpus = tmp_path / "docs.txt"
    corpus.write_text("\n".join(
        " ".join(rng.choice(words, size=int(rng.integers(2, 8))))
        for _ in range(300)))

    def run(shards):
        cfg = JobConfig(input_path=str(corpus), output_path="",
                        backend="cpu", num_shards=shards, batch_size=1024,
                        chunk_bytes=2048, metrics=False)
        return run_job(cfg, "invertedindex").postings

    single = run(1)
    sharded = run(8)
    assert sharded == single
    assert sharded == inverted_index_model(str(corpus))


def test_sharded_collect_skewed_single_term(tmp_path):
    """Every row routes to ONE bucket (a single hot term): the safe default
    bucket_cap must absorb it without overflow or loss."""
    corpus = tmp_path / "hot.txt"
    corpus.write_bytes(b"hot\n" * 2000)
    cfg = JobConfig(input_path=str(corpus), output_path="", backend="cpu",
                    num_shards=8, batch_size=512, chunk_bytes=1024,
                    metrics=False)
    res = run_job(cfg, "invertedindex")
    assert list(res.postings) == [b"hot"]
    assert res.postings[b"hot"] == sorted(res.postings[b"hot"])
    assert len(res.postings[b"hot"]) == 2000


def test_group_by_finalize_used_and_matches_model(tmp_path):
    """A small-vocab / many-pairs corpus passes the group-by gate (vocab <=
    pairs/8): assert the GROUP path actually ran (grouped_finalize metric)
    and its postings equal the independent model — the production wiring of
    moxt_group_by_key, not just its unit test."""
    rng = np.random.default_rng(9)
    words = [b"t%02d" % i for i in range(40)]
    p = tmp_path / "c.txt"
    with open(p, "wb") as f:
        for _ in range(800):
            f.write(b" ".join(words[int(i)]
                              for i in rng.integers(0, 40, 6)) + b"\n")
    cfg = JobConfig(input_path=str(p), output_path="", backend="cpu",
                    num_shards=1, metrics=True, chunk_bytes=4096)
    res = run_job(cfg, "invertedindex")
    if native is None:
        assert res.metrics["grouped_finalize"] is False
    else:
        assert res.metrics["grouped_finalize"] is True
    assert res.postings == inverted_index_model(str(p))


@pytest.mark.parametrize("shards", [1, 0])
def test_beyond_ram_pair_spill_matches_unspilled(tmp_path, rng, shards):
    """Round-5 (verdict r4 #4): pair collect past max_rows spills 16B
    (key, doc) records to top-bit disk buckets and finalizes bucket by
    bucket into a CSR whose doc column is an on-disk memmap — the job
    completes with bounded staging, identical postings, and a
    byte-identical output file.  shards=1 exercises the host engine's
    direct spill; shards=0 (auto: the 8-device test mesh) exercises the
    sharded engine DEMOTING its device buffers to the host engine when
    HBM residency crosses the cap."""
    words = [b"w%04d" % i for i in range(900)]
    lines = []
    for _ in range(1500):
        lines.append(b" ".join(
            words[int(i)] for i in rng.integers(0, 900, 10)))
    path = tmp_path / "big.txt"
    path.write_bytes(b"\n".join(lines) + b"\n")

    def run(cap, out_name):
        cfg = JobConfig(input_path=str(path),
                        output_path=str(tmp_path / out_name),
                        backend="cpu", metrics=True, chunk_bytes=4096,
                        num_shards=shards, collect_max_rows=cap)
        return run_inverted_index_job(cfg)

    plain = run(0, "plain.txt")          # engine default cap: in-RAM
    cap = 2048                           # ~1/6 of the fed pairs
    spilled = run(cap, "spilled.txt")
    assert spilled.metrics.get("spilled_pairs", 0) > 0
    assert plain.metrics.get("spilled_pairs") is None
    assert spilled.metrics["pairs"] == plain.metrics["pairs"]
    assert spilled.metrics["distinct_terms"] == plain.metrics["distinct_terms"]
    assert ((tmp_path / "spilled.txt").read_bytes()
            == (tmp_path / "plain.txt").read_bytes())
    assert spilled.postings == plain.postings
    model = inverted_index_model(str(path))
    assert dict(plain.postings.items()) == {
        t: d for t, d in model.items()}
