"""XLA program observatory (ISSUE-5 tentpole): the compile ledger counts
compiles exactly and names recompile causes, the cost-analysis join has
the documented schema, dispatch-gap histograms populate on the CPU mesh,
an injected recompile fails the ledger gate, and the ``obs xprof`` CLI
round-trips a real run's metrics document.
"""

import json

import numpy as np
import pytest

from map_oxidize_tpu.obs.compile import CompileLedger, ObservedJit
from map_oxidize_tpu.obs.xprof import (
    DeviceSampler,
    flatten_report,
    job_report,
    render_report,
)


def _observed(name, fn, ledger, **kw):
    import jax

    return ObservedJit(name, jax.jit(fn), ledger=ledger, **kw)


# --- compile ledger --------------------------------------------------------


def test_compile_counts_on_twice_shaped_program():
    """A program fed two input shapes compiles exactly twice, with the
    second compile named new_input_shape; re-calling either shape adds
    dispatches but no compiles."""
    led = CompileLedger()
    f = _observed("t/add", lambda x: x + 1, led)
    a = np.zeros(8, np.float32)
    b = np.zeros(16, np.float32)
    f(a)
    f(a)
    f(b)
    f(b)
    f(a)
    s = led.programs["t/add"]
    assert s.compiles == 2
    assert s.dispatches == 5
    assert s.causes == ["new_input_shape"]
    assert len(s.sigs) == 2


def test_recompile_cause_new_dtype_and_static():
    led = CompileLedger()
    f = _observed("t/dt", lambda x: x * 2, led)
    f(np.zeros(4, np.float32))
    f(np.zeros(4, np.int32))
    assert led.programs["t/dt"].causes == ["new_dtype"]

    import jax

    g = ObservedJit("t/st", jax.jit(lambda x, k: x[:k], static_argnums=1),
                    ledger=led)
    g(np.zeros(8, np.float32), 2)
    g(np.zeros(8, np.float32), 3)
    assert led.programs["t/st"].causes == ["new_static_config"]


def test_tag_distinguishes_closure_variants():
    """Two jits sharing one program name but differing in closed-over
    statics (the stream step's first/last flags) are told apart by the
    tag, not conflated into a phantom retrace."""
    led = CompileLedger()
    f1 = _observed("t/tag", lambda x: x + 1, led, tag=("first",))
    f2 = _observed("t/tag", lambda x: x + 2, led, tag=("last",))
    x = np.zeros(4, np.float32)
    f1(x)
    f2(x)
    s = led.programs["t/tag"]
    assert s.compiles == 2
    assert s.causes == ["new_static_config"]


def test_cost_analysis_join_schema(monkeypatch):
    """The job report carries FLOPs/bytes from cost_analysis per program,
    achieved rates over the estimated device time, MFU against the env
    peaks, and a memory/compute bound classification."""
    monkeypatch.setenv("MOXT_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("MOXT_PEAK_MEMBW", "1e11")
    led = CompileLedger()
    f = _observed("t/mm", lambda a, b: a @ b, led)
    a = np.ones((64, 64), np.float32)
    for _ in range(3):
        f(a, a)
    report = job_report(led.job_delta({}))
    row = report["programs"]["t/mm"]
    assert row["compiles"] == 1
    assert row["dispatches"] == 3
    assert row["flops_per_dispatch"] and row["flops_per_dispatch"] > 0
    assert row["bytes_per_dispatch"] and row["bytes_per_dispatch"] > 0
    assert row["device_s_est"] and row["device_s_est"] > 0
    assert row["achieved_flops_per_s"] > 0
    assert "mfu_pct" in row and row["mfu_pct"] >= 0
    assert row["bound"] in ("memory", "compute")
    assert report["peaks"]["source"] == "env"
    # the flat projection (what the run ledger gates on)
    flat = flatten_report(report)
    assert flat["compile/t/mm/compiles"] == 1
    assert flat["compile/total_compiles"] == 1
    assert flat["xprof/t/mm/dispatches"] == 3
    # and the rendered table mentions the program
    assert "t/mm" in render_report(report)


def test_job_delta_baseline_windows():
    """Per-job numbers are deltas against the activation snapshot: a
    second job over warm programs sees zero compiles, correct dispatch
    counts, and keeps the cost join."""
    led = CompileLedger()
    f = _observed("t/win", lambda x: x - 1, led)
    x = np.zeros(4, np.float32)
    f(x)                       # job 1: compile + dispatch
    base = {n: p.snapshot() for n, p in led.programs.items()}
    f(x)
    f(x)                       # job 2: two warm dispatches
    d = led.job_delta(base)
    assert d["t/win"]["compiles"] == 0
    assert d["t/win"]["dispatches"] == 2
    assert d["t/win"]["bytes_per_dispatch"] is not None


# --- dispatch-gap histograms on the CPU mesh -------------------------------


@pytest.fixture(scope="module")
def sharded_wordcount(tmp_path_factory):
    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.runtime.driver import run_wordcount_job
    from map_oxidize_tpu.workloads.wordcount import make_wordcount

    tmp = tmp_path_factory.mktemp("xprof")
    corpus = tmp / "c.txt"
    # the mapper combines per chunk (6 distinct words -> 6 rows/chunk), so
    # many small chunks against a 64-row feed batch produce several
    # SAME-SHAPE merges: beyond the compiling first dispatch the job has
    # steady-state dispatches for the gap histogram
    corpus.write_bytes(b"alpha beta gamma delta epsilon zeta\n" * 2000)
    metrics_out = tmp / "m.json"
    mapper, reducer = make_wordcount("ascii", use_native=False)
    cfg = JobConfig(input_path=str(corpus), output_path="", backend="cpu",
                    num_shards=8, mapper="python", batch_size=64,
                    chunk_bytes=4096, key_capacity=1 << 12, metrics=False,
                    metrics_out=str(metrics_out))
    result = run_wordcount_job(cfg, mapper, reducer)
    return result, json.loads(metrics_out.read_text())


def test_dispatch_gap_histogram_on_cpu_mesh(sharded_wordcount):
    """A real sharded job populates the dispatch-gap histogram (at least
    one steady-state dispatch beyond the compiling ones) and the shuffle
    merge program appears in the observatory with exact compile counts."""
    result, doc = sharded_wordcount
    m = result.metrics
    assert m.get("device/compute_ms/count", 0) >= 1
    assert m.get("compile/shuffle/merge/compiles") == 1
    assert m.get("compile/total_compiles", 0) >= 2
    progs = doc["xprof"]["programs"]
    assert progs["shuffle/merge"]["dispatches"] >= 1
    assert "device/dispatch_gap_ms" in doc["histograms"]


def test_xprof_cli_roundtrip(sharded_wordcount, capsys):
    """``obs xprof`` renders the report from the metrics document the
    job wrote (and --json re-emits the structured form)."""
    import os

    from map_oxidize_tpu.obs.cli import obs_main

    _result, doc = sharded_wordcount
    # re-materialize the document for the CLI (the fixture parsed it)
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json.dump(doc, f)
        path = f.name
    try:
        assert obs_main(["xprof", path]) == 0
        out = capsys.readouterr().out
        assert "XLA program observatory" in out
        assert "shuffle/merge" in out
        assert obs_main(["xprof", path, "--json"]) == 0
        parsed = json.loads(capsys.readouterr().out)
        assert parsed["programs"]["shuffle/merge"]["compiles"] == 1
    finally:
        os.unlink(path)


# --- ledger gate on injected recompiles ------------------------------------


def _entry(ts, compiles, mfu=50.0):
    from map_oxidize_tpu.obs import ledger

    summary = {"time/map+reduce_s": 1.0, "records_in": 100,
               "compile/engine/merge_packed/compiles": compiles,
               "compile/total_compiles": compiles,
               "xprof/engine/merge_packed/mfu_pct": mfu}
    e = {"ts_unix_s": ts, "version": "x", "config_hash": "deadbeef",
         "workload": "wordcount", "corpus_bytes": 1000, "n_processes": 1,
         "phases_s": {"map+reduce": 1.0}, "metrics": summary}
    return e


def test_gate_trips_on_injected_recompile(tmp_path, capsys):
    from map_oxidize_tpu.obs import ledger
    from map_oxidize_tpu.obs.cli import obs_main

    a = _entry(1.0, compiles=1)
    b = _entry(2.0, compiles=2)
    diff = ledger.diff_entries(a, b)
    assert any("recompile regression" in r for r in diff["regressions"])
    # gate_against_previous (the bench.py --gate primitive) flags it too
    ldir = tmp_path / "ledger"
    ledger.append(str(ldir), a)
    ledger.append(str(ldir), b)
    regs = ledger.gate_against_previous(str(ldir), b)
    assert any("recompile" in r for r in regs)
    # and the CLI exits 3 under --gate
    rc = obs_main(["diff", "--ledger-dir", str(ldir), "--gate"])
    capsys.readouterr()
    assert rc == 3
    # identical compile counts do NOT trip (zero-delta self gate)
    assert obs_main(["diff", "--ledger-dir", str(ldir), "--gate",
                     "--", "-1", "-1"]) == 0
    capsys.readouterr()


def test_gate_trips_on_mfu_drop():
    from map_oxidize_tpu.obs import ledger

    a = _entry(1.0, compiles=1, mfu=50.0)
    b = _entry(2.0, compiles=1, mfu=30.0)
    diff = ledger.diff_entries(a, b, threshold_pct=10.0)
    assert any("mfu_pct" in r for r in diff["regressions"])
    # a small wobble under the threshold passes
    c = _entry(3.0, compiles=1, mfu=48.0)
    diff = ledger.diff_entries(a, c, threshold_pct=10.0)
    assert not diff["regressions"]


# --- stall detector --------------------------------------------------------


class _FakeObs:
    def __init__(self):
        from map_oxidize_tpu.obs import MetricsRegistry, Tracer

        self.registry = MetricsRegistry()
        self.tracer = Tracer(enabled=True)
        self.heartbeat = None


def test_stall_detector_fires_once_and_rearms():
    """Chunks at ~1s cadence, then silence: one [stalled] warning naming
    the open spans, no repeat while still stalled, re-armed by the next
    completing chunk."""
    obs = _FakeObs()
    lines = []

    sampler = DeviceSampler(obs, interval_s=0.0, stall_factor=5.0)
    import map_oxidize_tpu.obs.xprof as xprof_mod

    orig_warn = xprof_mod._log.warning
    xprof_mod._log.warning = lambda fmt, *a: lines.append(fmt % a)
    try:
        t = 0.0
        span = obs.tracer.span("phase/map+reduce")
        span.__enter__()
        for i in range(5):
            obs.registry.observe("feed_block_ms", 1.0)
            assert sampler.check_stall(now=t) is False
            t += 1.0
        # silence: below the factor*median threshold -> quiet
        assert sampler.check_stall(now=t + 3.0) is False
        # past it -> exactly one warning, with the open span named
        assert sampler.check_stall(now=t + 6.0) is True
        assert sampler.check_stall(now=t + 7.0) is False
        assert len(lines) == 1
        assert "[stalled]" in lines[0]
        assert "phase/map+reduce" in lines[0]
        assert obs.registry.counters.get("heartbeat/stalls") == 1
        # a completing chunk re-arms the detector
        obs.registry.observe("feed_block_ms", 1.0)
        assert sampler.check_stall(now=t + 8.0) is False
        assert sampler.check_stall(now=t + 20.0) is True
        span.__exit__(None, None, None)
    finally:
        xprof_mod._log.warning = orig_warn


def test_hbm_sampler_noop_on_cpu():
    """CPU devices expose no memory_stats: the sampler must be silent,
    not crash, and record nothing."""
    obs = _FakeObs()
    sampler = DeviceSampler(obs, interval_s=0.1, stall_factor=0.0)
    sampler.sample_once()
    assert not any(k.startswith("hbm/") for k in obs.registry.gauges)
