"""Host collect-reduce engine (wide-key-space path) vs the dict model, and
the reduce_mode routing that selects it."""

import numpy as np
import pytest

from map_oxidize_tpu.api import MapOutput, MaxReducer, MinReducer, SumReducer
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.ops.hashing import HashDictionary, join_u64, split_u64
from map_oxidize_tpu.runtime.driver import make_engine, run_wordcount_job
from map_oxidize_tpu.runtime.host_reduce import HostCollectReduceEngine
from map_oxidize_tpu.workloads.bigram import make_bigram


def _feed(engine, keys64, vals):
    hi, lo = split_u64(keys64)
    engine.feed(MapOutput(hi=hi, lo=lo, values=vals,
                          dictionary=HashDictionary()))


def _model(keys64, vals, combine):
    out = {}
    f = {"sum": lambda a, b: a + b, "min": min, "max": max}[combine]
    for k, v in zip(keys64.tolist(), vals.tolist()):
        out[k] = f(out[k], v) if k in out else v
    return out


@pytest.mark.parametrize("reducer", [SumReducer(), MinReducer(), MaxReducer()])
def test_host_reduce_matches_model(rng, reducer):
    cfg = JobConfig(num_shards=1, backend="cpu")
    engine = HostCollectReduceEngine(cfg, reducer)
    all_k, all_v = [], []
    for _ in range(5):
        keys = rng.integers(0, 2**62, size=300, dtype=np.uint64)
        picks = keys[rng.integers(0, 300, size=2000)]
        vals = rng.integers(-50, 50, size=2000).astype(np.int32)
        all_k.append(picks)
        all_v.append(vals)
        _feed(engine, picks, vals)
    hi, lo, vals, n = engine.finalize()
    got = dict(zip(join_u64(hi, lo).tolist(), vals.tolist()))
    want = _model(np.concatenate(all_k), np.concatenate(all_v),
                  reducer.combine)
    assert got == want and n == len(want)


def test_host_reduce_top_k(rng):
    cfg = JobConfig(num_shards=1, backend="cpu")
    engine = HostCollectReduceEngine(cfg, SumReducer())
    keys = rng.integers(0, 2**62, size=40, dtype=np.uint64)
    picks = keys[rng.integers(0, 40, size=5000)]
    vals = np.ones(5000, np.int32)
    _feed(engine, picks, vals)
    hi, lo, topv, n = engine.top_k(7)
    model = _model(picks, vals, "sum")
    want = sorted(model.items(), key=lambda kv: (-kv[1], kv[0]))[:7]
    got = list(zip(join_u64(hi, lo).tolist(), topv.tolist()))
    assert got == want and n == len(model)


def test_host_reduce_empty():
    engine = HostCollectReduceEngine(JobConfig(num_shards=1), SumReducer())
    hi, lo, vals, n = engine.finalize()
    assert n == 0 and hi.shape == (0,)
    assert engine.top_k(5)[3] == 0


def test_reduce_mode_routing():
    cfg1 = JobConfig(num_shards=1, backend="cpu")
    assert isinstance(make_engine(cfg1, SumReducer(), wide_keys=True),
                      HostCollectReduceEngine)
    assert not isinstance(make_engine(cfg1, SumReducer(), wide_keys=False),
                          HostCollectReduceEngine)
    forced = JobConfig(num_shards=1, backend="cpu", reduce_mode="fold")
    assert not isinstance(make_engine(forced, SumReducer(), wide_keys=True),
                          HostCollectReduceEngine)


def test_lazycounts_top_k_tie_flood():
    """A heavily tied k-th value (Zipf tail) must take the capped-candidates
    branch and still match the full-sort semantics exactly."""
    from map_oxidize_tpu.ops.hashing import moxt64_bytes
    from map_oxidize_tpu.runtime.driver import LazyCounts

    d = HashDictionary()
    words, vals = [], []
    for i in range(5000):
        w = b"w%05d" % i
        h = moxt64_bytes(w)
        d.add(h, w)
        words.append(h)
        vals.append(3 if i in (17, 4321) else 1)  # 2 strict winners, k=5
    lc = LazyCounts(np.array(words, np.uint64), np.array(vals, np.int32), d)
    got = lc.top_k(5)
    want = sorted(((w, v) for w, v in zip(
        (b"w%05d" % i for i in range(5000)), vals)),
        key=lambda kv: (-kv[1], kv[0]))[:5]
    assert got == want


@pytest.mark.parametrize("reduce_mode", ["fold", "collect"])
def test_bigram_job_both_engines_agree(tmp_path, reduce_mode):
    """End-to-end bigram through each engine must give identical counts."""
    p = tmp_path / "c.txt"
    p.write_bytes(b"a b c a b\nb c d\n" * 50)
    cfg = JobConfig(input_path=str(p), output_path="", backend="cpu",
                    num_shards=1, reduce_mode=reduce_mode, metrics=False)
    mapper, reducer = make_bigram()
    res = run_wordcount_job(cfg, mapper, reducer, workload="bigram")
    from collections import Counter

    from map_oxidize_tpu.io.splitter import iter_chunks
    from map_oxidize_tpu.workloads.wordcount import tokenize

    model = Counter()
    for chunk in iter_chunks(str(p), cfg.chunk_bytes):
        toks = tokenize(bytes(chunk))
        model.update(toks[i] + b" " + toks[i + 1]
                     for i in range(len(toks) - 1))
    assert res.counts == dict(model)


class TestBeyondRamSpill:
    """Hash-only count jobs past max_rows switch to the disk-bucket
    partition instead of aborting (round-3 verdict missing #4)."""

    def _mk(self, max_rows):
        from map_oxidize_tpu.api import SumReducer
        from map_oxidize_tpu.config import JobConfig
        from map_oxidize_tpu.runtime.host_reduce import (
            HostCollectReduceEngine,
        )

        cfg = JobConfig(input_path="/dev/null", output_path="")
        return HostCollectReduceEngine(cfg, SumReducer(), max_rows=max_rows)

    def test_spill_matches_oracle_with_bounded_staging(self):
        from map_oxidize_tpu.api import MapOutput

        rng = np.random.default_rng(5)
        cap = 1 << 14
        eng = self._mk(cap)
        all_keys = []
        # 20 blocks x 8k rows = 10x the cap; keys duplicate-heavy
        pool = rng.integers(0, 1 << 48, 40_000, dtype=np.uint64)
        for _ in range(20):
            k = pool[rng.integers(0, pool.shape[0], 8192)]
            all_keys.append(k.copy())
            eng.feed(MapOutput(hi=None, lo=None, values=None,
                               records_in=k.shape[0], keys64=k))
        assert eng.spilled
        assert eng.peak_staged_rows <= cap + 8192  # one block of slack
        hi, lo, vals, n = eng.finalize()
        keys = (hi.astype(np.uint64) << np.uint64(32)) | lo
        want_u, want_c = np.unique(np.concatenate(all_keys),
                                   return_counts=True)
        assert n == want_u.shape[0]
        np.testing.assert_array_equal(keys, want_u)
        np.testing.assert_array_equal(vals, want_c.astype(np.int64))

    def test_spill_top_k_and_order(self):
        from map_oxidize_tpu.api import MapOutput

        rng = np.random.default_rng(7)
        eng = self._mk(1 << 12)
        # skewed: key 42 dominates
        blocks = []
        for _ in range(8):
            k = rng.integers(0, 1 << 60, 2048, dtype=np.uint64)
            k[: 512] = np.uint64(42)
            blocks.append(k)
            eng.feed(MapOutput(hi=None, lo=None, values=None,
                               records_in=k.shape[0], keys64=k))
        assert eng.spilled
        t_hi, t_lo, t_vals, n = eng.top_k(3)
        top_key = (int(t_hi[0]) << 32) | int(t_lo[0])
        assert top_key == 42
        assert t_vals[0] == 8 * 512
        want = np.unique(np.concatenate(blocks))
        assert n == want.shape[0]

    def test_explicit_values_spill_too(self):
        """Round 5: explicit-value rows no longer abort at the cap — they
        spill as (key, value) records (the r3-r4 behavior raised here)."""
        from map_oxidize_tpu.api import MapOutput

        eng = self._mk(256)
        k = np.arange(512, dtype=np.uint64)
        eng.feed(MapOutput(hi=None, lo=None,
                           values=np.full(512, 2, np.int32),
                           records_in=512, keys64=k))
        assert eng.spilled
        _hi, lo, vals, n = eng.finalize()
        assert n == 512
        np.testing.assert_array_equal(np.asarray(vals),
                                      np.full(512, 2, np.int32))


class TestBeyondRamSpillValues:
    """Round-5 (verdict r4 #4): the disk-bucket spill now covers
    explicit-value rows too — (key, value) records, any combine, mixed
    with hash-only blocks — so no host-reduce job hard-aborts at
    max_rows."""

    def _mk(self, max_rows, reducer=None):
        from map_oxidize_tpu.api import SumReducer
        from map_oxidize_tpu.config import JobConfig
        from map_oxidize_tpu.runtime.host_reduce import (
            HostCollectReduceEngine,
        )

        cfg = JobConfig(input_path="/dev/null", output_path="")
        return HostCollectReduceEngine(
            cfg, reducer if reducer is not None else SumReducer(),
            max_rows=max_rows)

    def test_mixed_ones_and_explicit_values_sum(self):
        from map_oxidize_tpu.api import MapOutput

        rng = np.random.default_rng(11)
        cap = 1 << 13
        eng = self._mk(cap)
        pool = rng.integers(0, 1 << 40, 5_000, dtype=np.uint64)
        want: dict = {}
        for j in range(16):
            k = pool[rng.integers(0, pool.shape[0], 4096)]
            if j % 2:  # explicit pre-combined counts
                v = rng.integers(1, 9, k.shape[0]).astype(np.int32)
                eng.feed(MapOutput(hi=None, lo=None, values=v,
                                   records_in=int(v.sum()), keys64=k))
            else:      # implicit ones (hash-only flavour)
                v = np.ones(k.shape[0], np.int64)
                eng.feed(MapOutput(hi=None, lo=None, values=None,
                                   records_in=k.shape[0], keys64=k))
            for kk, vv in zip(k.tolist(), v.tolist()):
                want[kk] = want.get(kk, 0) + int(vv)
        assert eng.spilled
        assert eng.peak_staged_rows <= cap + 4096
        hi, lo, vals, n = eng.finalize()
        keys = (hi.astype(np.uint64) << np.uint64(32)) | lo
        assert n == len(want)
        assert bool(np.all(keys[1:] > keys[:-1]))  # globally ascending
        got = dict(zip(keys.tolist(), vals.tolist()))
        assert got == want

    def test_max_combine_spills(self):
        from map_oxidize_tpu.api import MapOutput, MaxReducer

        rng = np.random.default_rng(13)
        eng = self._mk(1 << 12, MaxReducer())
        want: dict = {}
        for _ in range(8):
            k = rng.integers(0, 1 << 20, 2048, dtype=np.uint64)
            v = rng.integers(0, 1 << 20, k.shape[0]).astype(np.int32)
            eng.feed(MapOutput(hi=None, lo=None, values=v,
                               records_in=k.shape[0], keys64=k))
            for kk, vv in zip(k.tolist(), v.tolist()):
                want[kk] = max(want.get(kk, -1), int(vv))
        assert eng.spilled
        hi, lo, vals, _n = eng.finalize()
        keys = (hi.astype(np.uint64) << np.uint64(32)) | lo
        assert dict(zip(keys.tolist(), vals.tolist())) == want
        assert vals.dtype == np.int32  # no widening for max

    def test_hot_key_past_int32_widens(self):
        from map_oxidize_tpu.api import MapOutput

        eng = self._mk(1 << 10)
        k = np.full(1024, 7, np.uint64)
        big = np.full(1024, (1 << 30), np.int32)
        for _ in range(4):  # 4 * 1024 * 2^30 > int32 max
            eng.feed(MapOutput(hi=None, lo=None, values=big.copy(),
                               records_in=1024, keys64=k.copy()))
        assert eng.spilled
        _hi, _lo, vals, n = eng.finalize()
        assert n == 1
        assert vals.dtype == np.int64
        assert int(vals[0]) == 4 * 1024 * (1 << 30)
