"""The chunk-cut contract: io.splitter.iter_chunks and the native mmap path
(moxt_map_range) must produce IDENTICAL chunk sequences — bigram semantics
(pairs never straddle chunks) depend on it, so a divergence would silently
change counts between the Python and native drivers.

Also pins the SENTINEL64 guard: a token whose hash would equal the device
padding key must survive every path (VERDICT round 1, weak #5 — the tests
used to dodge this).
"""

import numpy as np
import pytest

from map_oxidize_tpu.io.splitter import iter_chunks
from map_oxidize_tpu.native.bindings import load_or_none, stream_or_none
from map_oxidize_tpu.ops.hashing import (
    SENTINEL64,
    HashDictionary,
    moxt64_bytes,
)

native = load_or_none()


CORPORA = [
    b"",
    b"one line\n",
    b"the cat sat on the mat\nthe cat ran\n" * 40,
    b"no trailing newline at all",
    b"x" * 300,                      # one giant token, hard split
    b"word " * 100,                  # whitespace cuts, no newlines
    (b"a" * 127 + b"\n") * 4,        # newline exactly at window edges
    b"\n" * 50,
    b"mixed \t tabs\nand spaces  \n" * 13,
]


def _native_chunks(path, chunk_bytes):
    """Chunk cuts as the C++ mmap path makes them, via moxt_map_range's
    consumed-bytes return (the map output itself is irrelevant here)."""
    from map_oxidize_tpu.native.build import NativeStream, _load_lib

    lib = _load_lib()
    data = open(path, "rb").read()
    st = NativeStream(1)
    f = lib.moxt_file_open(str(path).encode())
    assert f, "mmap open failed"
    try:
        out, off = [], 0
        while off < len(data):
            consumed = int(lib.moxt_map_range(st._st, f, off, chunk_bytes))
            assert consumed > 0
            out.append(data[off:off + consumed])
            off += consumed
        return out
    finally:
        lib.moxt_file_close(f)
        st.close()


@pytest.mark.skipif(native is None, reason="native build unavailable")
@pytest.mark.parametrize("corpus", CORPORA, ids=range(len(CORPORA)))
@pytest.mark.parametrize("chunk_bytes", [64, 128, 1 << 20])
def test_python_and_native_cut_identically(tmp_path, corpus, chunk_bytes):
    p = tmp_path / "c.txt"
    p.write_bytes(corpus)
    py = [bytes(c) for c in iter_chunks(str(p), chunk_bytes)]
    nat = _native_chunks(str(p), chunk_bytes)
    assert py == nat
    assert b"".join(py) == corpus  # no bytes lost or duplicated


@pytest.mark.parametrize("chunk_bytes", [7, 64, 1000])
def test_iter_chunks_reassembles(tmp_path, chunk_bytes, rng):
    blob = bytes(rng.integers(32, 127, size=5000, dtype=np.uint8))
    p = tmp_path / "r.txt"
    p.write_bytes(blob)
    chunks = [bytes(c) for c in iter_chunks(str(p), chunk_bytes)]
    assert b"".join(chunks) == blob
    assert all(len(c) <= chunk_bytes for c in chunks)


def test_sentinel_hash_token_survives():
    # No token can hash to SENTINEL64: the remap is part of the hash spec.
    # Verify the guard in the Python implementation and that the dictionary
    # round-trips a token through the full mapper path.
    assert moxt64_bytes(b"any token") != SENTINEL64
    d = HashDictionary()
    d.add(moxt64_bytes(b"tok"), b"tok")
    assert d.lookup(moxt64_bytes(b"tok")) == b"tok"


@pytest.mark.skipif(native is None, reason="native build unavailable")
def test_native_never_emits_sentinel_key(rng):
    # brute confidence: no emitted (hi, lo) pair equals the padding sentinel
    words = [bytes(rng.integers(97, 123, size=rng.integers(1, 20),
                                dtype=np.uint8)) for _ in range(2000)]
    chunk = b" ".join(words)
    s = stream_or_none(1)
    out = s.map_chunk(chunk)
    k64 = (out.hi.astype(np.uint64) << np.uint64(32)) | out.lo.astype(np.uint64)
    assert not np.any(k64 == np.uint64(SENTINEL64))
    s.close()
