"""The examples must actually run — a user-defined workload plugged into the
framework engines (the pluggable boundary the north star names)."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))


def _write_readings(path, rng, n=2000):
    cities = [b"Oslo", b"Nairobi", b"Quito", b"Perth", b"Ulan-Bator"]
    truth = {}
    with open(path, "wb") as f:
        for _ in range(n):
            c = cities[int(rng.integers(0, len(cities)))]
            t = int(rng.integers(-40, 45))
            f.write(c + b"," + str(t).encode() + b"\n")
            truth[c] = min(truth.get(c, 99), t)
        f.write(b"malformed line no comma\n")   # skipped, like main.rs:160
        f.write(b"Oslo,notanumber\n")
    return truth


@pytest.mark.parametrize("num_shards", [1, 8])
def test_min_temperature_by_city(tmp_path, rng, num_shards):
    from custom_workload import run

    path = tmp_path / "readings.txt"
    truth = _write_readings(path, rng)
    got = run(str(path), num_shards=num_shards)
    assert got == truth


@pytest.mark.parametrize("num_shards", [1, 8])
def test_device_top_k_on_min_monoid(tmp_path, rng, num_shards):
    """The DEVICE top-k path must work for a non-sum monoid (round-2 weak
    #8): padding rows carry the min identity (dtype MAX) and must be masked,
    not trusted to lose."""
    from custom_workload import run_device_topk

    path = tmp_path / "readings.txt"
    truth = _write_readings(path, rng)
    top, n = run_device_topk(str(path), k=3, num_shards=num_shards)
    assert n == len(truth)
    want = sorted(truth.values(), reverse=True)[:3]
    assert [v for _, v in top] == want
    for city, v in top:
        assert truth[city] == v


@pytest.mark.parametrize("num_shards", [1, 8])
def test_device_top_k_min_monoid_k_exceeds_live(tmp_path, num_shards):
    """k > live keys: the tail must be SENTINEL-keyed padding, never a
    padding row promoted above a real key."""
    from map_oxidize_tpu.api import MapOutput, MinReducer
    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.ops.hashing import (
        SENTINEL64,
        HashDictionary,
        join_u64,
        split_u64,
    )
    from map_oxidize_tpu.runtime.driver import make_engine

    cfg = JobConfig(num_shards=num_shards, backend="cpu", metrics=False)
    engine = make_engine(cfg, MinReducer())
    keys = np.array([11, 22, 33], np.uint64)
    vals = np.array([-5, 7, -9], np.int32)
    hi, lo = split_u64(keys)
    engine.feed(MapOutput(hi=hi, lo=lo, values=vals,
                          dictionary=HashDictionary()))
    t_hi, t_lo, t_vals, n = engine.top_k(10)
    assert n == 3
    k64 = join_u64(t_hi, t_lo)
    live = k64 != np.uint64(SENTINEL64)
    got = dict(zip(k64[live].tolist(), np.asarray(t_vals)[live].tolist()))
    assert got == {22: 7, 11: -5, 33: -9}
    # the three live rows outrank every padding row
    assert list(np.nonzero(live)[0]) == [0, 1, 2]


@pytest.mark.parametrize("num_shards", [1, 8])
def test_mean_temperature_vector_values(tmp_path, rng, num_shards):
    """Vector-valued user workload: mean via a (sum, count) value row —
    the non-monoid-through-a-monoid pattern, on both engines."""
    from vector_values import run

    path = tmp_path / "readings.txt"
    cities = [b"Oslo", b"Nairobi", b"Quito"]
    sums: dict[bytes, float] = {}
    counts: dict[bytes, int] = {}
    with open(path, "wb") as f:
        for _ in range(1500):
            c = cities[int(rng.integers(0, len(cities)))]
            t = int(rng.integers(-40, 45))
            f.write(c + b"," + str(t).encode() + b"\n")
            sums[c] = sums.get(c, 0.0) + t
            counts[c] = counts.get(c, 0) + 1
    got = run(str(path), num_shards=num_shards)
    assert set(got) == set(sums)
    for c in sums:
        assert abs(got[c] - sums[c] / counts[c]) < 1e-3


def test_sharded_top_k_floor_value_beats_cross_shard_padding():
    """A real key whose reduced value IS the dtype floor must not lose to
    another shard's floor-masked padding that precedes it in the gather
    (the final stage re-selects live-preferred, not index-preferred)."""
    from map_oxidize_tpu.api import MapOutput, MinReducer
    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.ops.hashing import (
        SENTINEL64,
        HashDictionary,
        join_u64,
        split_u64,
    )
    from map_oxidize_tpu.runtime.driver import make_engine

    cfg = JobConfig(num_shards=8, backend="cpu", metrics=False)
    engine = make_engine(cfg, MinReducer())
    keys = np.array([777], np.uint64)   # one real key, whichever shard owns it
    vals = np.array([np.iinfo(np.int32).min], np.int32)
    hi, lo = split_u64(keys)
    engine.feed(MapOutput(hi=hi, lo=lo, values=vals,
                          dictionary=HashDictionary()))
    t_hi, t_lo, t_vals, n = engine.top_k(8)
    assert n == 1
    k64 = join_u64(t_hi, t_lo)
    live = k64 != np.uint64(SENTINEL64)
    assert int(np.sum(live)) == 1
    assert k64[live][0] == 777
    assert np.asarray(t_vals)[live][0] == np.iinfo(np.int32).min
    # and the live row is ranked first
    assert bool(live[0])
