"""The examples must actually run — a user-defined workload plugged into the
framework engines (the pluggable boundary the north star names)."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))


def _write_readings(path, rng, n=2000):
    cities = [b"Oslo", b"Nairobi", b"Quito", b"Perth", b"Ulan-Bator"]
    truth = {}
    with open(path, "wb") as f:
        for _ in range(n):
            c = cities[int(rng.integers(0, len(cities)))]
            t = int(rng.integers(-40, 45))
            f.write(c + b"," + str(t).encode() + b"\n")
            truth[c] = min(truth.get(c, 99), t)
        f.write(b"malformed line no comma\n")   # skipped, like main.rs:160
        f.write(b"Oslo,notanumber\n")
    return truth


@pytest.mark.parametrize("num_shards", [1, 8])
def test_min_temperature_by_city(tmp_path, rng, num_shards):
    from custom_workload import run

    path = tmp_path / "readings.txt"
    truth = _write_readings(path, rng)
    got = run(str(path), num_shards=num_shards)
    assert got == truth
