"""Dataflow workloads (ISSUE-14 tentpole): total-order sort, hash
equi-join, and sessionize — oracle-exact on the single chip AND the
8-virtual-device mesh, through the shuffle transports (forced-spill
sort included), with the range partitioner property-tested on
adversarial inputs and every workload allowlist pinned to the single
source of truth in ``config.py``.
"""

import json

import numpy as np
import pytest

from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.runtime import run_job
from map_oxidize_tpu.workloads.join import (
    join_model,
    read_join_records,
)
from map_oxidize_tpu.workloads.sessionize import sessionize_model
from map_oxidize_tpu.workloads.sort import (
    RESERVED_KEY,
    compute_splitters,
    range_partition,
    read_sorted_records,
    sort_model,
)


def _cfg(tmp_path, inp, out, shards, **kw):
    kw.setdefault("chunk_bytes", 16 * 512)
    kw.setdefault("batch_size", 1 << 12)
    return JobConfig(input_path=str(inp),
                     output_path=str(tmp_path / out) if out else "",
                     backend="cpu", num_shards=shards, metrics=False,
                     **kw)


def _records(tmp_path, name, keys, payloads=None):
    path = tmp_path / name
    if payloads is None:
        np.save(path, keys)
    else:
        np.save(path, np.stack([keys, payloads], axis=1))
    return str(path) + ".npy" if not str(path).endswith(".npy") else str(path)


# --- the range partitioner: adversarial property suite ----------------------


#: adversarial key distributions: uniform, zipf-skewed, duplicate
#: floods, constants, near-sentinel, tiny, empty
def _adversarial_samples():
    rng = np.random.default_rng(42)
    yield "uniform", rng.integers(0, 1 << 64, 5000, dtype=np.uint64)
    z = np.minimum(rng.zipf(1.3, 5000), 1 << 20).astype(np.uint64)
    yield "zipf_skew", z
    d = rng.integers(0, 8, 5000, dtype=np.uint64)  # 8 distinct values
    yield "duplicate_flood", d
    yield "constant", np.full(1000, 7, np.uint64)
    yield "near_max", np.full(64, (1 << 64) - 2, np.uint64)
    yield "single", np.array([123], np.uint64)
    yield "empty", np.empty(0, np.uint64)


@pytest.mark.parametrize("num_shards", [2, 3, 8])
def test_splitters_cover_disjoint_monotone(num_shards):
    """On EVERY adversarial sample: splitters are (S-1,) nondecreasing;
    the induced partition covers every probe key exactly once (dest in
    [0, S)); the shard index is monotone in the key (so per-shard runs
    concatenate in key order); and ties at a splitter break
    deterministically to the right shard."""
    rng = np.random.default_rng(7)
    probes = np.concatenate([
        rng.integers(0, 1 << 64, 4000, dtype=np.uint64),
        np.array([0, 1, (1 << 64) - 1, (1 << 64) - 2], np.uint64),
    ])
    for name, sample in _adversarial_samples():
        sp = compute_splitters(sample, num_shards)
        assert sp.shape == (num_shards - 1,), name
        assert sp.dtype == np.uint64, name
        # nondecreasing (duplicates allowed: empty shards are valid)
        assert np.all(sp[1:] >= sp[:-1]), name
        dest = range_partition(probes, sp)
        # covering + disjoint: every key maps to exactly one shard in range
        assert dest.shape == probes.shape, name
        assert int(dest.min()) >= 0 and int(dest.max()) < num_shards, name
        # order-preserving: sorted keys -> nondecreasing shard ids
        order = np.argsort(probes, kind="stable")
        sdest = dest[order]
        assert np.all(sdest[1:] >= sdest[:-1]), name
        # deterministic ties: a key EQUAL to splitter j goes right of it
        for j, s in enumerate(sp.tolist()):
            assert int(range_partition(
                np.array([s], np.uint64), sp)[0]) >= j + 1, (name, j)
        # sample keys themselves must be covered too
        if sample.size:
            sd = range_partition(sample, sp)
            assert int(sd.min()) >= 0 and int(sd.max()) < num_shards, name


def test_splitters_empty_sample_still_covers():
    """An empty sample yields evenly spaced u64-space splitters — the
    partition still covers (no crash, no degenerate all-to-one-shard)."""
    sp = compute_splitters(np.empty(0, np.uint64), 4)
    assert sp.shape == (3,)
    dest = range_partition(
        np.array([0, 1 << 62, 2 << 62, 3 << 62, (1 << 64) - 1],
                 np.uint64), sp)
    assert dest.tolist() == [0, 1, 2, 3, 3]


def test_device_range_dest_matches_host_partitioner():
    """The in-trace router (:func:`parallel.shuffle.range_dest`) and the
    host partitioner must agree bit for bit — including at splitter
    ties — or the distributed partition writes would disagree with the
    routing."""
    import jax

    from map_oxidize_tpu.ops.hashing import split_u64
    from map_oxidize_tpu.parallel.shuffle import range_dest

    rng = np.random.default_rng(11)
    for _name, sample in _adversarial_samples():
        for S in (2, 8):
            sp = compute_splitters(sample, S)
            keys = np.concatenate([
                rng.integers(0, 1 << 64, 1000, dtype=np.uint64),
                sp,  # the tie cases
                np.array([0, (1 << 64) - 1], np.uint64),
            ])
            hi, lo = split_u64(keys)
            sp_hi, sp_lo = split_u64(sp)
            got = np.asarray(jax.jit(range_dest)(hi, lo, sp_hi, sp_lo))
            want = range_partition(keys, sp)
            assert np.array_equal(got, want), (_name, S)


# --- total-order sort -------------------------------------------------------


@pytest.mark.parametrize("shards", [1, 8])
def test_sort_oracle_exact(tmp_path, shards):
    rng = np.random.default_rng(1)
    n = 5000
    keys = rng.integers(0, 1 << 62, n, dtype=np.uint64)
    keys[:500] = keys[0]  # duplicate-heavy head: payload order matters
    pay = rng.integers(0, 1 << 64, n, dtype=np.uint64)
    inp = _records(tmp_path, "recs.npy", keys, pay)
    r = run_job(_cfg(tmp_path, inp, f"s{shards}.bin", shards), "sort")
    gk, gp = read_sorted_records(tmp_path / f"s{shards}.bin")
    wk, wp = sort_model(keys, pay)
    assert np.array_equal(gk, wk)
    assert np.array_equal(gp, wp)
    assert r.n_rows == n and r.spilled_rows == 0


def test_sort_keys_only_payload_is_row_index(tmp_path):
    """A (n,) keys-only input sorts with the global row index as the
    payload — i.e. a STABLE sort, verifiable per duplicate."""
    rng = np.random.default_rng(2)
    keys = rng.integers(0, 50, 3000, dtype=np.uint64)  # heavy duplicates
    inp = _records(tmp_path, "keys.npy", keys)
    run_job(_cfg(tmp_path, inp, "sk.bin", 8), "sort")
    gk, gp = read_sorted_records(tmp_path / "sk.bin")
    wk, wp = sort_model(keys, np.arange(keys.shape[0], dtype=np.uint64))
    assert np.array_equal(gk, wk)
    assert np.array_equal(gp, wp)


@pytest.mark.parametrize("shards,transport", [(1, "disk"), (8, "hybrid")])
def test_sort_forced_spill_total_order(tmp_path, shards, transport):
    """The acceptance scenario: a sort forced past --collect-max-rows
    COMPLETES via disk buckets with oracle-exact, globally sorted
    output and nonzero spill/rows — on the single chip (disk from row
    0) and through the mesh engine's mid-job demotion (hybrid)."""
    rng = np.random.default_rng(3)
    n = 6000
    keys = rng.integers(0, 1 << 64, n, dtype=np.uint64)
    keys[keys == RESERVED_KEY] -= np.uint64(1)
    pay = rng.integers(0, 1 << 64, n, dtype=np.uint64)
    inp = _records(tmp_path, "recs.npy", keys, pay)
    r = run_job(_cfg(tmp_path, inp, f"sp{shards}.bin", shards,
                     collect_max_rows=1000, shuffle_transport=transport),
                "sort")
    gk, gp = read_sorted_records(tmp_path / f"sp{shards}.bin")
    wk, wp = sort_model(keys, pay)
    assert np.array_equal(gk, wk)
    assert np.array_equal(gp, wp)
    assert r.spilled_rows == n
    assert r.metrics.get("spill/rows", 0) > 0


def test_sort_reserved_key_refused(tmp_path):
    keys = np.array([1, RESERVED_KEY, 2], np.uint64)
    inp = _records(tmp_path, "bad.npy", keys)
    with pytest.raises(Exception, match="reserved key"):
        run_job(_cfg(tmp_path, inp, "x.bin", 1), "sort")


def test_sort_attribution_covers_the_wall(tmp_path):
    """The satellite bar: ``obs where`` attributes >= 90% of a sort
    job's wall — the shuffle route + per-shard sort + host drains must
    land in named buckets, not ``unattributed_pct`` — and the bucket
    sum never exceeds the wall (disjointness)."""
    rng = np.random.default_rng(4)
    n = 200_000
    keys = rng.integers(0, 1 << 62, n, dtype=np.uint64)
    pay = rng.integers(0, 1 << 63, n, dtype=np.uint64)
    inp = _records(tmp_path, "recs.npy", keys, pay)
    cfg = _cfg(tmp_path, inp, "att.bin", 8,
               chunk_bytes=16 * 65536, batch_size=1 << 16,
               metrics_out=str(tmp_path / "m.json"))
    run_job(cfg, "sort")
    doc = json.load(open(tmp_path / "m.json"))
    att = doc["attrib"]
    assert att["unattributed_pct"] <= 10.0, att
    assert att["attributed_ms"] <= att["wall_ms"] + 1.0, att
    assert "host_sort" in att["buckets"]
    # the spilled variant's host drains are attributed too (bigger
    # corpus: the wall must be dominated by measured work, not the
    # fixed per-job framework overhead a 100ms job is mostly made of)
    n2 = 1_000_000
    inp2 = _records(tmp_path, "recs2.npy",
                    rng.integers(0, 1 << 62, n2, dtype=np.uint64),
                    rng.integers(0, 1 << 63, n2, dtype=np.uint64))
    cfg2 = _cfg(tmp_path, inp2, "att2.bin", 1,
                chunk_bytes=16 * 65536, batch_size=1 << 16,
                collect_max_rows=100_000,
                metrics_out=str(tmp_path / "m2.json"))
    run_job(cfg2, "sort")
    att2 = json.load(open(tmp_path / "m2.json"))["attrib"]
    assert att2["unattributed_pct"] <= 10.0, att2
    assert att2["buckets"]["host_sort"]["ms"] > 0.0


# --- hash equi-join ---------------------------------------------------------


@pytest.mark.parametrize("shards", [1, 8])
def test_join_oracle_exact(tmp_path, shards):
    rng = np.random.default_rng(5)
    na, nb = 3000, 2500
    ka = rng.integers(0, 500, na, dtype=np.uint64)
    pa = rng.integers(0, 1 << 40, na, dtype=np.uint64)
    kb = rng.integers(0, 500, nb, dtype=np.uint64)
    pb = rng.integers(0, 1 << 40, nb, dtype=np.uint64)
    a = _records(tmp_path, "a.npy", ka, pa)
    b = _records(tmp_path, "b.npy", kb, pb)
    r = run_job(_cfg(tmp_path, a, f"j{shards}.bin", shards,
                     join_input_path=b), "join")
    gk, ga, gb = read_join_records(tmp_path / f"j{shards}.bin")
    wk, wa, wb = join_model(ka, pa, kb, pb)
    assert np.array_equal(gk, wk)
    assert np.array_equal(ga, wa)
    assert np.array_equal(gb, wb)
    assert r.n_matches == wk.shape[0]
    assert (r.n_left, r.n_right) == (na, nb)


def test_join_disjoint_keys_no_matches(tmp_path):
    ka = np.arange(0, 100, dtype=np.uint64)
    kb = np.arange(1000, 1100, dtype=np.uint64)
    a = _records(tmp_path, "a.npy", ka, ka)
    b = _records(tmp_path, "b.npy", kb, kb)
    r = run_job(_cfg(tmp_path, a, "j0.bin", 8, join_input_path=b),
                "join")
    assert r.n_matches == 0
    gk, _ga, _gb = read_join_records(tmp_path / "j0.bin")
    assert gk.shape == (0,)


def test_join_payload_side_bit_refused(tmp_path):
    ka = np.array([1], np.uint64)
    pa = np.array([1 << 63], np.uint64)  # steals the side bit
    a = _records(tmp_path, "a.npy", ka, pa)
    b = _records(tmp_path, "b.npy", ka, ka)
    with pytest.raises(Exception, match="2\\*\\*63"):
        run_job(_cfg(tmp_path, a, "", 1, join_input_path=b), "join")


def test_join_requires_right_corpus(tmp_path):
    a = _records(tmp_path, "a.npy", np.array([1], np.uint64))
    with pytest.raises(ValueError, match="join-input"):
        run_job(_cfg(tmp_path, a, "", 1), "join")


# --- sessionize -------------------------------------------------------------


@pytest.mark.parametrize("shards", [1, 8])
def test_sessionize_oracle_exact(tmp_path, shards):
    rng = np.random.default_rng(6)
    ne = 4000
    ek = rng.integers(0, 200, ne, dtype=np.uint64)
    ts = rng.integers(0, 100_000, ne, dtype=np.uint64)
    inp = _records(tmp_path, "ev.npy", ek, ts)
    gap = 500
    r = run_job(_cfg(tmp_path, inp, f"se{shards}.txt", shards,
                     session_gap=gap), "sessionize")
    rows = [tuple(int(x) for x in line.split("\t")) for line in
            open(tmp_path / f"se{shards}.txt").read().splitlines()]
    mk, ms, me, mc = sessionize_model(ek, ts, gap)
    want = list(zip(mk.tolist(), ms.tolist(), me.tolist(), mc.tolist()))
    assert rows == want
    assert r.n_sessions == len(want)
    assert r.n_events == ne  # conservation rides the driver check too


def test_sessionize_gap_boundary_semantics(tmp_path):
    """A gap EXACTLY equal to session_gap stays one session; one unit
    more cuts — pinned on both the model and the engine path."""
    ek = np.zeros(4, np.uint64)
    ts = np.array([0, 500, 1001, 1501], np.uint64)
    inp = _records(tmp_path, "ev.npy", ek, ts)
    r = run_job(_cfg(tmp_path, inp, "gb.txt", 1, session_gap=500),
                "sessionize")
    rows = [tuple(int(x) for x in line.split("\t")) for line in
            open(tmp_path / "gb.txt").read().splitlines()]
    # 0->500 within gap; 500->1001 cuts (501 > 500); 1001->1501 within
    assert rows == [(0, 0, 500, 2), (0, 1001, 1501, 2)]
    assert r.n_sessions == 2


def test_cli_tolerates_downstream_pipe_closure(tmp_path):
    """``python -m map_oxidize_tpu obs where doc.json | head`` is the
    documented way to skim the reports (check.sh drives them exactly so
    under pipefail): a consumer that closes the pipe early must read as
    success, not a BrokenPipeError traceback.  The reader end is closed
    BEFORE the child spawns, so the first flush hits EPIPE
    deterministically."""
    import os as _os
    import subprocess
    import sys as _sys

    from map_oxidize_tpu.obs import attrib

    doc = {"attrib": {
        "schema": attrib.ATTRIB_SCHEMA, "wall_ms": 1000.0,
        "attributed_ms": 990.0, "unattributed_ms": 10.0,
        "unattributed_pct": 1.0,
        "buckets": {b: {"ms": 90.0, "pct": 9.0} for b in attrib.BUCKETS},
    }, "meta": {"workload": "sort"}}
    path = tmp_path / "m.json"
    path.write_text(json.dumps(doc))
    r, w = _os.pipe()
    _os.close(r)  # the reader is already gone
    try:
        proc = subprocess.run(
            [_sys.executable, "-m", "map_oxidize_tpu", "obs", "where",
             str(path)],
            stdout=w, stderr=subprocess.PIPE,
            cwd=_os.path.dirname(_os.path.dirname(
                _os.path.abspath(__file__))))
    finally:
        _os.close(w)
    assert proc.returncode == 0, proc.stderr.decode()
    assert b"Traceback" not in proc.stderr


# --- allowlists: one source of truth ---------------------------------------


def test_workload_allowlists_agree():
    """The one-shot CLI, the serve scheduler, and the submit CLI all
    derive their workload choices from ``config.WORKLOADS`` — no
    hand-maintained list can drift, and the three new dataflow
    workloads appear everywhere at once."""
    import argparse

    from map_oxidize_tpu.cli import build_parser
    from map_oxidize_tpu.config import SERVE_WORKLOADS, WORKLOADS
    from map_oxidize_tpu.serve.cli import build_submit_parser

    for w in ("sort", "join", "sessionize"):
        assert w in WORKLOADS
    assert tuple(SERVE_WORKLOADS) == tuple(WORKLOADS)

    def _choices(parser, dest):
        for action in parser._actions:
            if action.dest == dest and not isinstance(
                    action, argparse._VersionAction):
                return tuple(action.choices)
        raise AssertionError(f"no {dest} positional")

    assert _choices(build_parser(), "workload") == tuple(WORKLOADS)
    assert _choices(build_submit_parser(), "workload") == tuple(
        SERVE_WORKLOADS)
