"""Pluggable shuffle transports (map_oxidize_tpu.shuffle).

The transport is a swappable placement policy behind one driver flag
(--shuffle-transport), so the load-bearing claims are parity claims:

* the same 8-virtual-device job under ``hbm`` and ``disk`` produces
  byte-identical output, and the hbm run's comms accounting still obeys
  the exchange-payload identity (the refactor changed nothing resident);
* ``hybrid`` demotes mid-job with the shared ``shuffle/demote`` span and
  ``spill/*`` counters, and its output still matches;
* a 2-process Gloo inverted index with a tiny ``--collect-max-rows``
  COMPLETES (the old "per-process spill is not yet implemented" abort is
  gone) with oracle-exact postings, disjoint per-process spill volumes
  that sum to the global pair count, and bounded host staging.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.runtime import run_job

import test_distributed as td


def _corpus(tmp_path, lines=1200):
    path = tmp_path / "c.txt"
    td._write_corpus(path, lines=lines)
    return path


# --- routing + spelling ----------------------------------------------------


def test_resolve_transport_routes_on_corpus_size(tmp_path):
    from map_oxidize_tpu.shuffle import AUTO_BYTES_PER_ROW, resolve_transport

    path = tmp_path / "r.txt"
    path.write_bytes(b"x" * 4096)
    cfg = JobConfig(input_path=str(path))
    est = 4096 // AUTO_BYTES_PER_ROW
    # estimated rows past the cap -> disk (skip the demotion drain)
    assert resolve_transport(cfg, est - 1) == "disk"
    # resident regime -> hybrid (today's engine behavior, named)
    assert resolve_transport(cfg, est + 1) == "hybrid"
    # explicit pins win regardless of size
    for name in ("hbm", "disk", "hybrid"):
        cfg2 = JobConfig(input_path=str(path), shuffle_transport=name)
        assert resolve_transport(cfg2, 1) == name
    # unreadable input (serve jobs validate later): safe hybrid default
    assert resolve_transport(JobConfig(input_path="/no/such"), 1) == "hybrid"


def test_config_and_cli_spelling(tmp_path):
    with pytest.raises(ValueError, match="shuffle_transport"):
        JobConfig(shuffle_transport="ssd").validate()
    # disk + device sort is rejected by the SINGLE-CHIP engine (the only
    # path where the combination is genuinely impossible), not by config
    # validation — on a sharded mesh collect_sort applies to the
    # single-chip engine only and the pinned disk transport is valid
    from map_oxidize_tpu.runtime.collect import CollectEngine

    cfg = JobConfig(shuffle_transport="disk", collect_sort="device")
    cfg.validate()
    with pytest.raises(ValueError, match="disk buckets"):
        CollectEngine(cfg)
    from map_oxidize_tpu.cli import build_parser, config_from_args

    path = tmp_path / "c.txt"
    path.write_bytes(b"a b c\n")
    args = build_parser().parse_args(
        ["invertedindex", str(path), "--shuffle-transport", "disk"])
    assert config_from_args(args).shuffle_transport == "disk"
    # serve --set rides the same JobConfig field (string passthrough)
    from map_oxidize_tpu.serve.client import coerce_overrides

    assert coerce_overrides(["shuffle_transport=hybrid"]) == {
        "shuffle_transport": "hybrid"}


def test_push_transport_spelling(tmp_path):
    """The pipelined/remote transports ride every existing spelling
    surface: config validation, the CLI flag, and serve --set."""
    for name in ("pipelined", "remote"):
        JobConfig(shuffle_transport=name).validate()
    with pytest.raises(ValueError, match="push_combine"):
        JobConfig(push_combine="sideways").validate()
    with pytest.raises(ValueError, match="remote_stage_timeout_s"):
        JobConfig(remote_stage_timeout_s=0).validate()
    from map_oxidize_tpu.cli import build_parser, config_from_args

    path = tmp_path / "c.txt"
    path.write_bytes(b"a b c\n")
    args = build_parser().parse_args(
        ["wordcount", str(path), "--shuffle-transport", "pipelined",
         "--push-combine", "on", "--remote-stage-dir", str(tmp_path)])
    cfg = config_from_args(args)
    assert cfg.shuffle_transport == "pipelined"
    assert cfg.push_combine == "on"
    assert cfg.remote_stage_dir == str(tmp_path)
    from map_oxidize_tpu.serve.client import coerce_overrides

    assert coerce_overrides(["shuffle_transport=pipelined"]) == {
        "shuffle_transport": "pipelined"}


def test_transport_state_machines():
    from map_oxidize_tpu.shuffle import make_transport

    hbm = make_transport("hbm")
    assert hbm.admit(10, 100, "t") == "resident"
    with pytest.raises(RuntimeError, match="--shuffle-transport disk"):
        hbm.admit(101, 100, "t")
    disk = make_transport("disk")
    assert disk.admit(1, 100, "t") == "spill"
    hybrid = make_transport("hybrid")
    assert hybrid.admit(10, 100, "t") == "resident"
    assert hybrid.admit(101, 100, "t") == "demote"   # the one-way trip
    assert hybrid.admit(102, 100, "t") == "spill"    # never demotes twice
    with pytest.raises(ValueError, match="unknown shuffle transport"):
        make_transport("ssd")


# --- single-controller parity (the 8-virtual-device mesh) ------------------


def _run_ii(corpus, out, transport, max_rows=0, shards=0, trace=False):
    cfg = JobConfig(input_path=str(corpus), output_path=str(out),
                    backend="cpu", num_shards=shards, metrics=False,
                    chunk_bytes=4096, batch_size=1 << 12,
                    shuffle_transport=transport,
                    collect_max_rows=max_rows,
                    trace_out="-" if trace else None)
    return run_job(cfg, "invertedindex")


def test_sharded_hbm_vs_disk_byte_identical(tmp_path):
    """Transport swap parity on the 8-device mesh: identical output
    bytes, identical postings facts — and the hbm run's comms accounting
    still satisfies the exchange-payload identity while the disk run
    moves ZERO collective bytes (it never stages in HBM)."""
    corpus = _corpus(tmp_path)
    r_hbm = _run_ii(corpus, tmp_path / "hbm.txt", "hbm")
    r_disk = _run_ii(corpus, tmp_path / "disk.txt", "disk")
    assert ((tmp_path / "hbm.txt").read_bytes()
            == (tmp_path / "disk.txt").read_bytes())
    for key in ("pairs", "distinct_terms"):
        assert r_hbm.metrics[key] == r_disk.metrics[key]
    assert r_hbm.metrics["shuffle/transport"] == "hbm"
    assert r_disk.metrics["shuffle/transport"] == "disk"
    # hbm: resident path untouched — comms identity intact, no spill
    from map_oxidize_tpu.parallel.shuffle import exchange_payload_bytes

    exchanges = r_hbm.metrics["shuffle/exchanges"]
    S = r_hbm.metrics["comms/all_to_all/collect/route_append/calls"]
    assert S == exchanges
    per = r_hbm.metrics["shuffle/all_to_all_bytes"] / exchanges
    # the per-exchange payload is the accounting identity for SOME
    # (S, cap): reconstruct from the engine's default sizing on 8 shards
    cap = (1 << 12) // 8
    assert per == exchange_payload_bytes(8, cap, 8)
    assert (r_hbm.metrics["comms/all_to_all/collect/route_append/bytes"]
            == r_hbm.metrics["shuffle/all_to_all_bytes"])
    assert "spill/rows" not in r_hbm.metrics
    # disk: every pair spilled, nothing exchanged
    assert r_disk.metrics["spill/rows"] == r_disk.metrics["pairs"]
    assert r_disk.metrics["spill/buckets"] >= 1
    assert r_disk.metrics["spilled_pairs"] == r_disk.metrics["pairs"]
    assert not any(k.startswith("comms/all_to_all/")
                   for k in r_disk.metrics)


def test_hybrid_demotes_with_shared_span(tmp_path):
    """The mid-job RESIDENT->SPILLED trip on the sharded engine records
    the shared evidence — one shuffle/demote span, demote/* and spill/*
    counters — and the output still matches the resident run."""
    corpus = _corpus(tmp_path)
    r_big = _run_ii(corpus, tmp_path / "big.txt", "hybrid")
    r = _run_ii(corpus, tmp_path / "hyb.txt", "hybrid", max_rows=2000,
                trace=True)
    assert ((tmp_path / "big.txt").read_bytes()
            == (tmp_path / "hyb.txt").read_bytes())
    assert r.metrics["demote/events"] == 1
    assert r.metrics["demote/rows"] > 0
    assert r.metrics["spill/rows"] > 0
    assert r.metrics["spill/buckets"] >= 1
    spans = [e for e in r.trace
             if e.get("ph") == "X" and e.get("name") == "shuffle/demote"]
    assert len(spans) == 1, "expected exactly one shuffle/demote span"
    assert spans[0]["args"]["rows"] > 0


def test_single_chip_disk_bounds_staging(tmp_path):
    """num_shards=1 (plain CollectEngine): the disk transport spills
    from the FIRST row, so peak host staging stays at one feed block
    while the resident run stages every pair."""
    from map_oxidize_tpu.runtime.collect import CollectEngine

    corpus = _corpus(tmp_path)
    engines = {}
    orig = CollectEngine.feed

    def spy(self, out):
        engines[self.transport] = self
        return orig(self, out)

    CollectEngine.feed = spy
    try:
        r_disk = _run_ii(corpus, tmp_path / "d1.txt", "disk", shards=1)
        r_res = _run_ii(corpus, tmp_path / "r1.txt", "hybrid", shards=1)
    finally:
        CollectEngine.feed = orig
    assert ((tmp_path / "d1.txt").read_bytes()
            == (tmp_path / "r1.txt").read_bytes())
    pairs = r_res.metrics["pairs"]
    assert engines["hybrid"].peak_staged_rows == pairs
    assert 0 < engines["disk"].peak_staged_rows < pairs
    assert r_disk.metrics["spill/rows"] == pairs


def test_sharded_auto_disk_survives_device_sort_config(tmp_path):
    """collect_sort='device' applies to the single-chip engine only; on
    the sharded path an auto-routed disk transport must still stage on
    disk from row 0 (review finding: the nested host engine used to
    silently degrade to hybrid before its sort_mode was forced to
    host — demoting mid-job while the gauge claimed 'disk')."""
    corpus = _corpus(tmp_path)
    for transport in ("auto", "disk"):  # auto: est rows >> 100 -> disk
        cfg = JobConfig(input_path=str(corpus),
                        output_path=str(tmp_path / f"o_{transport}.txt"),
                        backend="cpu", num_shards=0, metrics=False,
                        chunk_bytes=4096, batch_size=1 << 12,
                        collect_sort="device", collect_max_rows=100,
                        shuffle_transport=transport)
        r = run_job(cfg, "invertedindex")
        assert r.metrics["shuffle/transport"] == "disk"
        assert r.metrics["spill/rows"] == r.metrics["pairs"]
        assert "demote/events" not in r.metrics  # from row 0, no demotion


def test_hbm_cap_message_names_the_transports(tmp_path):
    corpus = _corpus(tmp_path)
    with pytest.raises(RuntimeError,
                       match=r"--shuffle-transport disk.*hybrid"):
        _run_ii(corpus, tmp_path / "x.txt", "hbm", max_rows=500)
    with pytest.raises(RuntimeError, match="disk"):
        _run_ii(corpus, tmp_path / "y.txt", "hbm", max_rows=500, shards=1)


# --- multi-process: the old cap-abort is dead ------------------------------

_CHILD = r"""
import json, sys
pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
corpus = sys.argv[4]; out_path = sys.argv[5]
transport = sys.argv[6]; cap = int(sys.argv[7]); final = sys.argv[8]
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.parallel.distributed import (
    init_distributed, run_distributed_job)
init_distributed(f"127.0.0.1:{port}", num_processes=nproc, process_id=pid)
cfg = JobConfig(input_path=corpus, output_path=final, chunk_bytes=4096,
                batch_size=1 << 12, key_capacity=1 << 12, top_k=5,
                metrics=False, collect_max_rows=cap,
                shuffle_transport=transport)
r = run_distributed_job(cfg, "invertedindex")
m = r.metrics or {}
json.dump({
    "n_keys": r.n_keys, "n_pairs": r.n_pairs, "records": r.records,
    "top": [[f"{h:#018x}", None if w is None else w.decode(), c]
            for h, w, c in r.top],
    "spill_rows": m.get("spill/rows", 0),
    "demotes": m.get("demote/events", 0),
    "peak_staged": m.get("shuffle/peak_staged_rows", 0),
    "transport": m.get("shuffle/transport"),
}, open(out_path, "w"), sort_keys=True)
print("child", pid, "ok")
"""


def _launch_spill(tmp_path, corpus, transport, cap, tag):
    env = td._env(4)
    final = str(tmp_path / f"ii_{tag}.txt")
    outs = [str(tmp_path / f"out_{tag}_{i}.json") for i in range(2)]
    for attempt in range(2):
        port = td._free_port()
        procs = [subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(i), "2", str(port),
             str(corpus), outs[i], transport, str(cap), final],
            env=env, cwd=td.REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True) for i in range(2)]
        logs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=420)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                out = "(timeout)"
            logs.append(out)
        if all(p.returncode == 0 for p in procs):
            break
        if attempt == 1:
            for i, p in enumerate(procs):
                assert p.returncode == 0, f"process {i} failed:\n{logs[i]}"
    results = [json.load(open(p)) for p in outs]
    parts = sorted(tmp_path.glob(f"ii_{tag}.txt.part*"))
    assert len(parts) == 2
    rows = []
    for p in parts:
        rows.extend(p.read_bytes().splitlines(keepends=True))
    return results, b"".join(sorted(rows))


def test_two_process_spilled_invertedindex_oracle(tmp_path):
    """The acceptance scenario: a 2-process inverted index with a tiny
    --collect-max-rows COMPLETES under both beyond-RAM transports with
    oracle-exact postings, byte-identical concatenated partition output
    vs the single-process artifact, disjoint per-process spill summing
    to the global pair count, and bounded host staging."""
    # 1500 lines -> ~5.9k pairs: more than one lockstep exchange round at
    # batch_size 4096, so per-round staging is a strict fraction of each
    # process's partition (the bounded-staging assertion below)
    corpus = _corpus(tmp_path, lines=1500)
    from map_oxidize_tpu.workloads.inverted_index import (
        inverted_index_model,
    )

    model = inverted_index_model(str(corpus))
    n_pairs = sum(len(d) for d in model.values())
    want_dfs = sorted((len(d) for d in model.values()), reverse=True)[:5]

    run_job(JobConfig(input_path=str(corpus),
                      output_path=str(tmp_path / "single.txt"),
                      backend="cpu", num_shards=1, metrics=False,
                      chunk_bytes=4096), "invertedindex")
    single = b"".join(sorted(
        (tmp_path / "single.txt").read_bytes().splitlines(keepends=True)))

    for transport, cap in (("disk", 1500), ("hybrid", 1500)):
        results, merged = _launch_spill(tmp_path, corpus, transport, cap,
                                        transport)
        assert merged == single, f"{transport}: output parity failed"
        spill = [r.pop("spill_rows") for r in results]
        peaks = [r.pop("peak_staged") for r in results]
        records = [r.pop("records") for r in results]
        demotes = [r.pop("demotes") for r in results]
        assert results[0] == results[1]
        r = results[0]
        assert r["transport"] == transport
        assert r["n_keys"] == len(model)
        assert r["n_pairs"] == n_pairs
        assert [c for _h, _w, c in r["top"]] == want_dfs
        for _h, w, c in r["top"]:
            assert w is not None and len(model[w.encode()]) == c
        # per-process partitions are disjoint and cover every pair
        assert all(s > 0 for s in spill)
        assert sum(spill) == n_pairs
        assert sum(records) == sum(
            1 for _ in open(corpus, "rb").read().split())
        # bounded staging: no process ever held its partition whole
        assert all(0 < p < s for p, s in zip(peaks, spill))
        if transport == "hybrid":
            assert demotes == [1, 1]   # one synchronized trip each
        else:
            assert demotes == [0, 0]   # disk never demotes
