"""Resident job service (ISSUE-7 tentpole): HBM admission control
(admit/defer/reject with named reasons), cancel/deadline through the
flight recorder, warm-compile multi-job serving with zero compile deltas
after job 1, concurrent jobs with disjoint per-job obs/ledger state, the
bounded queue, graceful drain, and the /jobs HTTP plane.

Scheduler-level tests inject HELD runners (a threading.Event gates the
job body) so admission and cancellation windows are deterministic; the
HTTP/server tests drive real wordcount jobs through real drivers.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from map_oxidize_tpu.config import JobConfig, ServeConfig
from map_oxidize_tpu.obs import Obs
from map_oxidize_tpu.serve.admission import AdmissionController
from map_oxidize_tpu.serve.corpus import CorpusCache
from map_oxidize_tpu.serve.scheduler import Scheduler


def _write_corpus(path, lines=200, words=None):
    words = words or [b"alpha", b"beta", b"gamma", b"delta"]
    rng = np.random.default_rng(11)
    with open(path, "wb") as f:
        for _ in range(lines):
            f.write(b" ".join(words[int(i)]
                              for i in rng.integers(0, len(words), 8))
                    + b"\n")
    return str(path)


def _serve_cfg(tmp_path, **kw) -> ServeConfig:
    kw.setdefault("port", 0)
    kw.setdefault("spool_dir", str(tmp_path / "spool"))
    kw.setdefault("job_sample_s", 0.05)
    kw.setdefault("drain_timeout_s", 5.0)
    return ServeConfig(**kw).validate()


def _held_runner(release: threading.Event):
    """A runner whose job body blocks on ``release`` inside a real
    ``Obs.recording`` envelope, polling the cancellation point — the
    deterministic stand-in for a long-running driver."""

    def run(config, workload, on_obs):
        obs = Obs.from_config(config)
        on_obs(obs)
        with obs.recording(config, workload):
            obs.registry.count("held/progress", 1)
            while not release.wait(0.01):
                obs.poll_cancel()
        obs.finish(config, workload)

        class _R:
            metrics = {"records_in": 1}

        return _R()

    return run


# --- config + admission units ----------------------------------------------


def test_serve_config_validates():
    with pytest.raises(ValueError):
        ServeConfig(workers=0).validate()
    with pytest.raises(ValueError):
        ServeConfig(max_queue=0).validate()
    with pytest.raises(ValueError):
        ServeConfig(port=70000).validate()
    with pytest.raises(ValueError):
        ServeConfig(hbm_budget_bytes=-1).validate()
    with pytest.raises(ValueError):   # 0 would 404 every finished job
        ServeConfig(max_history=0).validate()
    assert ServeConfig().validate().workers >= 1


def test_admission_decisions():
    adm = AdmissionController(budget_bytes=1000)
    assert adm.decide(400) == ("admit", "")
    decision, reason = adm.decide(2000)
    assert decision == "reject"
    assert "working_set_exceeds_hbm_budget" in reason
    adm.reserve(700)
    decision, reason = adm.decide(400)
    assert decision == "defer"
    assert "hbm_budget_busy" in reason
    adm.release(700)
    assert adm.decide(400)[0] == "admit"
    # zero budget (unprobeable backend) leaves admission open
    assert AdmissionController(0).decide(1 << 50)[0] == "admit"


def test_corpus_cache_idle_eviction(tmp_path):
    clock = [0.0]
    cache = CorpusCache(idle_evict_s=10.0, clock=lambda: clock[0])
    path = _write_corpus(tmp_path / "c.txt", lines=5)
    size = cache.open(path)
    assert size == os.path.getsize(path) and path in cache
    with pytest.raises(OSError):
        cache.open(str(tmp_path / "missing.txt"))
    clock[0] = 9.0
    assert cache.evict_idle() == 0 and len(cache) == 1
    cache.touch(path)            # a job touch resets the idle clock
    clock[0] = 18.0
    assert cache.evict_idle() == 0
    clock[0] = 30.0
    assert cache.evict_idle() == 1 and len(cache) == 0
    assert cache.evictions == 1


# --- scheduler: admission, queue bound, cancel/deadline, drain --------------


def test_oversized_job_rejected_named(tmp_path):
    corpus = _write_corpus(tmp_path / "c.txt")
    sched = Scheduler(_serve_cfg(tmp_path, hbm_budget_bytes=1 << 20),
                      runner=_held_runner(threading.Event()))
    sched.start()
    try:
        job = sched.submit("wordcount", corpus, est_hbm_bytes=2 << 20)
        assert job.state == "rejected"
        assert "working_set_exceeds_hbm_budget" in job.reason
        # a rejection is a named refusal, not a capacity abort: no crash
        # bundle, no job dir
        assert not os.path.isdir(os.path.join(sched.cfg.spool_dir, job.id))
    finally:
        sched.shutdown()


def test_deferred_job_runs_after_hbm_frees(tmp_path):
    corpus = _write_corpus(tmp_path / "c.txt")
    release = threading.Event()
    sched = Scheduler(_serve_cfg(tmp_path, hbm_budget_bytes=1000,
                                 workers=2),
                      runner=_held_runner(release))
    sched.start()
    try:
        a = sched.submit("wordcount", corpus, est_hbm_bytes=700)
        deadline = time.monotonic() + 30
        while a.state != "running" and time.monotonic() < deadline:
            time.sleep(0.01)
        assert a.state == "running"
        b = sched.submit("wordcount", corpus, est_hbm_bytes=600)
        # b cannot fit next to a: deferred (still queued), reason named
        deadline = time.monotonic() + 30
        while b.defer_reason is None and time.monotonic() < deadline:
            time.sleep(0.01)
        assert b.state == "queued"
        assert "hbm_budget_busy" in b.defer_reason
        assert sched.job_doc(b.id)["reason"] == b.defer_reason
        release.set()            # a finishes -> HBM frees -> b admitted
        assert sched.wait(a.id, timeout=30).state == "done"
        assert sched.wait(b.id, timeout=30).state == "done"
    finally:
        release.set()
        sched.shutdown()


def test_queue_bound_rejects_named(tmp_path):
    corpus = _write_corpus(tmp_path / "c.txt")
    release = threading.Event()
    sched = Scheduler(_serve_cfg(tmp_path, workers=1, max_queue=1),
                      runner=_held_runner(release))
    sched.start()
    try:
        a = sched.submit("wordcount", corpus)
        deadline = time.monotonic() + 30
        while a.state != "running" and time.monotonic() < deadline:
            time.sleep(0.01)
        b = sched.submit("wordcount", corpus)   # fills the queue
        c = sched.submit("wordcount", corpus)   # past the bound
        assert b.state == "queued"
        assert c.state == "rejected" and "queue_full" in c.reason
        release.set()
        assert sched.wait(b.id, timeout=30).state == "done"
    finally:
        release.set()
        sched.shutdown()


def test_submit_validation_errors(tmp_path):
    sched = Scheduler(_serve_cfg(tmp_path),
                      runner=_held_runner(threading.Event()))
    corpus = _write_corpus(tmp_path / "c.txt")
    try:
        with pytest.raises(ValueError, match="unknown workload"):
            sched.submit("terasort", corpus)
        with pytest.raises(ValueError, match="reserved"):
            sched.submit("wordcount", corpus,
                         overrides={"metrics_out": "/tmp/x"})
        with pytest.raises(ValueError, match="unknown config"):
            sched.submit("wordcount", corpus, overrides={"nope": 1})
        with pytest.raises(ValueError):     # JobConfig.validate refuses
            sched.submit("wordcount", corpus,
                         overrides={"batch_size": -1})
        missing = sched.submit("wordcount", str(tmp_path / "missing.txt"))
        assert missing.state == "rejected"
        assert "input_not_found" in missing.reason
    finally:
        sched.shutdown()


def test_rejected_history_stays_bounded(tmp_path):
    """A retry storm of rejections while nothing completes must not grow
    the job history unboundedly — rejections are terminal and prune."""
    sched = Scheduler(_serve_cfg(tmp_path, max_history=5),
                      runner=_held_runner(threading.Event()))
    try:
        for _ in range(25):   # every one rejects: input does not exist
            job = sched.submit("wordcount", str(tmp_path / "missing.txt"))
            assert job.state == "rejected"
        assert len(sched.job_ids()) <= 6   # cap + the newest rejection
    finally:
        sched.shutdown()


def test_wait_unknown_job_raises_named_keyerror(tmp_path):
    sched = Scheduler(_serve_cfg(tmp_path),
                      runner=_held_runner(threading.Event()))
    try:
        with pytest.raises(KeyError, match="job-9999"):
            sched.wait("job-9999", timeout=1)
    finally:
        sched.shutdown()


def test_worker_slot_survives_base_exception(tmp_path):
    """A job body raising a BaseException (SystemExit here — the shape a
    pipeline kill-resume re-raise takes) fails THAT job but must not
    kill the worker slot: the next job still runs."""
    corpus = _write_corpus(tmp_path / "c.txt")
    boom = {"armed": True}
    release = threading.Event()
    release.set()                     # the healthy job finishes at once

    def runner(config, workload, on_obs):
        if boom.pop("armed", False):
            raise SystemExit("job body bailed")
        return _held_runner(release)(config, workload, on_obs)

    sched = Scheduler(_serve_cfg(tmp_path, workers=1), runner=runner)
    sched.start()
    try:
        bad = sched.submit("wordcount", corpus)
        assert sched.wait(bad.id, timeout=30).state == "failed"
        assert "SystemExit" in bad.reason
        ok = sched.submit("wordcount", corpus)
        assert sched.wait(ok.id, timeout=30).state == "done"
    finally:
        sched.shutdown()


def test_submit_cli_choices_track_served_workloads():
    """The submit CLI's workload choices come from the same allowlist
    the scheduler enforces — one source of truth in config.py."""
    from map_oxidize_tpu.config import SERVE_WORKLOADS
    from map_oxidize_tpu.serve.cli import build_submit_parser

    action = next(a for a in build_submit_parser()._actions
                  if a.dest == "workload")
    assert tuple(action.choices) == SERVE_WORKLOADS


def test_cancel_running_job_flight_recorded(tmp_path):
    corpus = _write_corpus(tmp_path / "c.txt")
    release = threading.Event()
    sched = Scheduler(_serve_cfg(tmp_path), runner=_held_runner(release))
    sched.start()
    try:
        job = sched.submit("wordcount", corpus)
        deadline = time.monotonic() + 30
        while job.state != "running" and time.monotonic() < deadline:
            time.sleep(0.01)
        sched.cancel(job.id, reason="cancelled_by_client")
        done = sched.wait(job.id, timeout=30)
        assert done.state == "cancelled"
        assert done.reason == "cancelled_by_client"
        # the cancel took the flight path: partial obs flushed as a
        # crash bundle AND the partial metrics doc, with the work so far
        crash = done.config.crash_dir
        bundles = os.listdir(crash)
        assert len(bundles) == 1
        doc = json.loads(open(os.path.join(
            crash, bundles[0], "metrics.json")).read())
        assert doc["counters"]["held/progress"] == 1
        assert doc["gauges"]["aborted"] is True
        err = json.loads(open(os.path.join(
            crash, bundles[0], "error.json")).read())
        assert "JobCancelled" in err["error"]
    finally:
        release.set()
        sched.shutdown()


def test_deadline_cancels_running_job(tmp_path):
    corpus = _write_corpus(tmp_path / "c.txt")
    release = threading.Event()
    sched = Scheduler(_serve_cfg(tmp_path), runner=_held_runner(release))
    sched.start()
    try:
        job = sched.submit("wordcount", corpus, deadline_s=0.3)
        done = sched.wait(job.id, timeout=30)
        assert done.state == "cancelled"
        assert done.reason == "deadline_exceeded"
        assert os.listdir(done.config.crash_dir)  # flight bundle flushed
    finally:
        release.set()
        sched.shutdown()


def test_cancel_queued_job_immediate(tmp_path):
    corpus = _write_corpus(tmp_path / "c.txt")
    release = threading.Event()
    sched = Scheduler(_serve_cfg(tmp_path, workers=1),
                      runner=_held_runner(release))
    sched.start()
    try:
        a = sched.submit("wordcount", corpus)
        b = sched.submit("wordcount", corpus)
        sched.cancel(b.id)
        assert b.state == "cancelled"       # never ran: no bundle dir
        assert not os.path.isdir(os.path.join(b.config.crash_dir))
        release.set()
        assert sched.wait(a.id, timeout=30).state == "done"
    finally:
        release.set()
        sched.shutdown()


def test_drain_finishes_running_rejects_new(tmp_path):
    corpus = _write_corpus(tmp_path / "c.txt")
    release = threading.Event()
    sched = Scheduler(_serve_cfg(tmp_path, workers=1),
                      runner=_held_runner(release))
    sched.start()
    job = sched.submit("wordcount", corpus)
    deadline = time.monotonic() + 30
    while job.state != "running" and time.monotonic() < deadline:
        time.sleep(0.01)
    sched.request_shutdown(drain=True)
    late = sched.submit("wordcount", corpus)
    assert late.state == "rejected" and "server_draining" in late.reason
    release.set()
    sched.shutdown()                 # drains: the running job FINISHES
    assert job.state == "done"
    doc = sched.jobs_doc()
    assert doc["draining"] is True
    assert doc["counts"] == {"done": 1, "rejected": 1}


# --- real jobs through the resident server (HTTP plane) ---------------------


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    from map_oxidize_tpu.serve.server import ResidentServer

    tmp = tmp_path_factory.mktemp("serve")
    cfg = ServeConfig(port=0, workers=2, spool_dir=str(tmp / "spool"),
                      job_sample_s=0.05, drain_timeout_s=10.0).validate()
    srv = ResidentServer(cfg).start()
    yield srv, tmp
    srv.shutdown()


@pytest.fixture(scope="module")
def client(server):
    from map_oxidize_tpu.serve.client import ServeClient

    srv, _tmp = server
    return ServeClient(srv.url)


def _job_overrides():
    # python mapper + pinned single shard: no native dep, no mesh init,
    # and the prefetch pipeline stays on (depth default 2)
    return {"num_chunks": 6, "batch_size": 1 << 12,
            "key_capacity": 1 << 12, "num_map_workers": 1,
            "mapper": "python", "use_native": False, "num_shards": 1}


def test_warm_jobs_zero_compile_delta(server, client, tmp_path):
    """N back-to-back same-shape jobs through the server: every job after
    the first shows a ZERO per-job compile delta (the warm-cache story,
    per-job accounting via the compile-ledger overlay)."""
    _srv, tmp = server
    corpus = _write_corpus(tmp / "warm.txt", lines=300)
    docs = []
    for _ in range(3):
        doc = client.submit("wordcount", corpus, config=_job_overrides())
        docs.append(client.wait(doc["id"], timeout_s=120))
    assert [d["state"] for d in docs] == ["done"] * 3
    assert all(d["records_in"] == docs[0]["records_in"] for d in docs)
    # job 1 may or may not compile (this pytest process may be warm
    # already); jobs 2..N must not compile ANYTHING
    assert docs[1]["compiles"] == 0
    assert docs[2]["compiles"] == 0
    # the full per-program evidence rides the job's metrics doc
    m = json.loads(open(docs[1]["artifacts"]["metrics_out"]).read())
    assert m["gauges"]["compile/total_compiles"] == 0


def test_concurrent_jobs_oracle_exact_disjoint(server, client):
    """Two jobs at once through the 2-worker server: oracle-exact
    outputs, disjoint per-job metrics docs and ledger entries."""
    from map_oxidize_tpu.obs import ledger
    from map_oxidize_tpu.workloads.reference_model import wordcount_model

    srv, tmp = server
    ca = _write_corpus(tmp / "ca.txt", lines=150,
                       words=[b"aa", b"bb", b"cc"])
    cb = _write_corpus(tmp / "cb.txt", lines=250,
                       words=[b"xx", b"yy", b"zz", b"ww"])
    out_a = str(tmp / "out_a.txt")
    out_b = str(tmp / "out_b.txt")
    da = client.submit("wordcount", ca, config=_job_overrides(),
                       output=out_a)
    db = client.submit("wordcount", cb, config=_job_overrides(),
                       output=out_b)
    da = client.wait(da["id"], timeout_s=120)
    db = client.wait(db["id"], timeout_s=120)
    assert da["state"] == "done" and db["state"] == "done"
    for corpus, out in ((ca, out_a), (cb, out_b)):
        with open(corpus, "rb") as f:
            oracle = wordcount_model([f.read()])
        got = {}
        with open(out, "rb") as f:
            for line in f:
                w, _, n = line.rstrip(b"\n").rpartition(b" ")
                got[w] = int(n)
        assert got == dict(oracle), f"output mismatch for {corpus}"
    # disjoint metrics docs: each job's doc counts ITS corpus only
    ma = json.loads(open(da["artifacts"]["metrics_out"]).read())
    mb = json.loads(open(db["artifacts"]["metrics_out"]).read())
    assert ma["gauges"]["records_in"] == da["records_in"]
    assert mb["gauges"]["records_in"] == db["records_in"]
    assert da["records_in"] != db["records_in"]
    # ...and each job appended its own ledger entry
    entries = ledger.read(srv.scheduler.ledger_dir)
    by_rec = {e["metrics"]["records_in"] for e in entries}
    assert {da["records_in"], db["records_in"]} <= by_rec


def test_jobs_table_and_render(server, client):
    from map_oxidize_tpu.obs.cli import render_jobs

    doc = client.jobs()
    assert doc["schema"] == "moxt-jobs-v1"
    assert doc["queue"]["max"] == 16
    assert doc["counts"].get("done", 0) >= 2
    assert {"budget_bytes", "reserved_bytes",
            "measured_live_bytes"} <= set(doc["hbm"])
    assert any(c["hits"] >= 1 for c in doc["corpora"])
    frame = render_jobs(doc)
    assert "jobs (" in frame
    assert doc["jobs"][0]["id"] in frame
    # the index advertises the job plane
    idx = client._request("/")
    assert "/jobs" in idx["endpoints"]


def test_http_submit_validation(server, client):
    from map_oxidize_tpu.serve.client import ServeError

    _srv, tmp = server
    with pytest.raises(ServeError, match="unknown workload"):
        client.submit("terasort", str(tmp / "warm.txt"))
    with pytest.raises(ServeError, match="reserved"):
        client.submit("wordcount", str(tmp / "warm.txt"),
                      config={"obs_port": 5})
    with pytest.raises(ServeError, match="unknown job"):
        client.cancel("job-9999")
    rejected = client.submit("wordcount", str(tmp / "nope.txt"))
    assert rejected["state"] == "rejected"
    assert "input_not_found" in rejected["reason"]


def test_http_shutdown_requests_drain(tmp_path):
    """POST /shutdown flips the scheduler to draining and wakes
    serve_forever, which drains and stops the plane."""
    from map_oxidize_tpu.serve.client import ServeClient
    from map_oxidize_tpu.serve.server import ResidentServer

    release = threading.Event()
    release.set()
    srv = ResidentServer(_serve_cfg(tmp_path, workers=1),
                         runner=_held_runner(release)).start()
    c = ServeClient(srv.url)
    corpus = _write_corpus(tmp_path / "c.txt", lines=5)
    done = c.wait(c.submit("wordcount", corpus)["id"], timeout_s=30)
    assert done["state"] == "done"
    assert c.shutdown(drain=True)["draining"] is True
    t = threading.Thread(target=srv.serve_forever)
    t.start()
    t.join(timeout=30)
    assert not t.is_alive()
    import urllib.error
    import urllib.request

    with pytest.raises((urllib.error.URLError, OSError)):
        urllib.request.urlopen(srv.url + "/jobs", timeout=2)
