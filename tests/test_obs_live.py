"""Live telemetry plane (ISSUE-6 tentpole): the time-series ring, the
/metrics + /status + /series server, the comms observatory, per-job
ObsContext isolation, and the comms/stall ledger gates.

The single-controller tests drive REAL jobs (a deliberately slowed
mapper keeps the scrape window open deterministically); the 2-process
Gloo test launches real processes and scrapes both per-process servers
mid-run, port-discovered through ``MOXT_OBS_PORT_FILE``.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from map_oxidize_tpu.config import JobConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _get(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def _get_json(url: str) -> dict:
    return json.loads(_get(url))


def _write_corpus(path, lines: int = 400) -> int:
    words = [b"alpha", b"beta", b"gamma", b"delta", b"epsilon", b"zeta"]
    rng = np.random.default_rng(7)
    with open(path, "wb") as f:
        for _ in range(lines):
            f.write(b" ".join(words[int(i)]
                              for i in rng.integers(0, 6, 8)) + b"\n")
    return os.path.getsize(path)


class _SlowMapper:
    """Delegating mapper that sleeps per chunk: holds a real job open so
    mid-run scrapes are deterministic, output identical to the inner
    mapper's."""

    def __init__(self, inner, delay_s: float):
        self._inner = inner
        self._delay = delay_s

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def map_chunk(self, chunk):
        time.sleep(self._delay)
        return self._inner.map_chunk(chunk)


# --- single-controller: endpoints during a real job ------------------------


@pytest.fixture(scope="module")
def live_job(tmp_path_factory):
    """One slowed wordcount run with the live plane on: scraped /status,
    /metrics, and /series documents captured MID-run, plus the job's
    result and final metrics document."""
    from map_oxidize_tpu.runtime.driver import run_wordcount_job
    from map_oxidize_tpu.workloads.wordcount import make_wordcount

    tmp = tmp_path_factory.mktemp("live")
    corpus = tmp / "c.txt"
    _write_corpus(corpus)
    mapper, reducer = make_wordcount("ascii", use_native=False)
    cfg = JobConfig(
        input_path=str(corpus), output_path="", metrics=False,
        num_chunks=10, batch_size=1 << 12, key_capacity=1 << 12,
        num_map_workers=1,  # serialize the slowed chunks: a ~1.5s window
        mapper="python", use_native=False,
        obs_port=0, obs_sample_s=0.02, trace_out="-",
        metrics_out=str(tmp / "metrics.json"),
    )
    portfile = tmp / "ports.txt"
    os.environ["MOXT_OBS_PORT_FILE"] = str(portfile)
    box: dict = {}

    def _run():
        try:
            box["result"] = run_wordcount_job(
                cfg, _SlowMapper(mapper, 0.15), reducer)
        except BaseException as e:  # pragma: no cover - surfaced below
            box["error"] = e

    t = threading.Thread(target=_run)
    t.start()
    try:
        deadline = time.monotonic() + 60
        while not portfile.exists() and time.monotonic() < deadline:
            time.sleep(0.005)
        port = int(portfile.read_text().split()[1])
        url = f"http://127.0.0.1:{port}"
        # poll until the job is demonstrably mid-run (a phase is open)
        status = None
        while time.monotonic() < deadline:
            status = _get_json(url + "/status")
            if status.get("phase") == "map+reduce":
                break
            time.sleep(0.01)
        scrapes = {
            "status": status,
            "metrics": _get(url + "/metrics").decode(),
            "series": _get_json(url + "/series"),
            "index": _get_json(url + "/"),
        }
        # a second status a few chunks later must show progress moved
        time.sleep(0.4)
        scrapes["status2"] = _get_json(url + "/status")
    finally:
        t.join(timeout=120)
        os.environ.pop("MOXT_OBS_PORT_FILE", None)
    if "error" in box:
        raise box["error"]
    assert not t.is_alive()
    return cfg, box["result"], scrapes, url, tmp


def test_status_schema_mid_run(live_job):
    _cfg, _result, scrapes, _url, _tmp = live_job
    s = scrapes["status"]
    assert s["schema"] == "moxt-status-v1"
    assert s["phase"] == "map+reduce"
    assert s["meta"]["workload"] == "wordcount"
    assert s["meta"]["version"] and s["meta"]["config_hash"]
    assert s["elapsed_s"] > 0
    assert isinstance(s["comms"], list)  # single shard: present, empty
    assert "open_spans" in s  # tracing was on
    assert "xprof" in s  # live compile/MFU table
    # progress comes from the silent heartbeat (no --progress flag!)
    assert s["progress"]["rows"] >= 0
    assert "fraction" in s["progress"]


def test_status_updates_mid_run(live_job):
    _cfg, result, scrapes, _url, _tmp = live_job
    s1, s2 = scrapes["status"], scrapes["status2"]
    assert s2["t_unix_s"] > s1["t_unix_s"]
    assert s2["progress"]["rows"] >= s1["progress"]["rows"]
    # by the later scrape some chunks were mapped
    assert s2["progress"]["rows"] > 0
    assert s2["progress"]["rows"] <= sum(result.counts.values())


def test_prometheus_text_mid_run(live_job):
    _cfg, _result, scrapes, _url, _tmp = live_job
    text = scrapes["metrics"]
    assert "# TYPE" in text
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name = line.split("{")[0].split(" ")[0]
        assert name.startswith("moxt_")
        # the Prometheus charset, post-sanitization
        assert all(c.isalnum() or c in "_:" for c in name), name
        float(line.rsplit(" ", 1)[1])  # every sample parses


def test_series_schema_and_final_doc(live_job):
    cfg, _result, scrapes, _url, tmp = live_job
    live = scrapes["series"]
    assert live["schema"] == "moxt-series-v1"
    assert live["interval_s"] == pytest.approx(0.02)
    # final metrics document carries the full series section
    doc = json.loads((tmp / "metrics.json").read_text())
    series = doc["series"]
    assert series["schema"] == "moxt-series-v1"
    t = series["t_unix_s"]
    assert len(t) >= 2 and t == sorted(t)
    assert series["samples_taken"] >= len(t)
    # every series aligns with the timestamp axis
    for name, vals in series["series"].items():
        assert len(vals) == len(t), name
    # the ring saw the feed-loop histograms and the heartbeat progress
    assert any(k.startswith("feed_block_ms") for k in series["series"])
    assert "progress/rows" in series["series"]
    assert doc["meta"]["version"]  # stamped like everything else


def test_server_down_after_finish(live_job):
    _cfg, _result, _scrapes, url, _tmp = live_job
    with pytest.raises((urllib.error.URLError, OSError)):
        _get(url + "/status", timeout=2)


def test_zero_compile_delta_from_live_plane(live_job):
    """The telemetry plane must not change what compiles: the slowed
    live-plane run compiles exactly what an identical dark run does."""
    cfg, result, _scrapes, _url, tmp = live_job
    from map_oxidize_tpu.runtime.driver import run_wordcount_job
    from map_oxidize_tpu.workloads.wordcount import make_wordcount

    mapper, reducer = make_wordcount("ascii", use_native=False)
    import dataclasses

    dark = dataclasses.replace(
        cfg, obs_port=-1, obs_sample_s=0.0, trace_out=None,
        metrics_out=None)
    r2 = run_wordcount_job(dark, mapper, reducer)
    live_compiles = {k: v for k, v in result.metrics.items()
                     if k.startswith("compile/") and k.endswith("/compiles")}
    dark_compiles = {k: v for k, v in r2.metrics.items()
                     if k.startswith("compile/") and k.endswith("/compiles")}
    # same program set; the dark run (second in the process) may compile
    # FEWER (jit caches are warm) but never different programs, and the
    # live run must not add any program the dark run doesn't know
    assert set(live_compiles) == set(dark_compiles)
    assert dict(r2.counts) == dict(result.counts)


# --- concurrent scrape safety ----------------------------------------------


def test_concurrent_scrape_safety(tmp_path):
    """Hammer all three endpoints from threads while counters/histograms
    churn: every response parses, none 500s, the server survives."""
    from map_oxidize_tpu.obs import Obs

    cfg = JobConfig(input_path=str(tmp_path / "x"), obs_port=0,
                    obs_sample_s=0.01).validate()
    obs = Obs.from_config(cfg)
    stop = threading.Event()

    def _churn():
        i = 0
        while not stop.is_set():
            obs.registry.count("churn/counter", 1)
            obs.registry.observe("churn/hist_ms", i % 17)
            obs.registry.comm("psum", "churn/prog", 1024, shape=(8,),
                              latency_ms=0.5)
            i += 1

    churner = threading.Thread(target=_churn, daemon=True)
    churner.start()
    errors: list = []
    url = obs.server.url

    def _scrape(ep):
        try:
            for _ in range(50):
                body = _get(url + ep)
                if ep != "/metrics":
                    doc = json.loads(body)
                    assert "error" not in doc
        except Exception as e:
            errors.append((ep, e))

    threads = [threading.Thread(target=_scrape, args=(ep,))
               for ep in ("/metrics", "/status", "/series") for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    stop.set()
    churner.join(timeout=10)
    obs.stop_live()
    obs.finish_xprof()
    assert not errors, errors


# --- ring-buffer bounds ----------------------------------------------------


def test_ring_buffer_bounds():
    from map_oxidize_tpu.obs.metrics import MetricsRegistry
    from map_oxidize_tpu.obs.timeseries import TimeSeriesRecorder

    reg = MetricsRegistry()
    ticks = iter(range(1000))
    tsr = TimeSeriesRecorder(reg, interval_s=1.0, capacity=8,
                             clock=lambda: float(next(ticks)))
    for i in range(20):
        reg.count("c", 1)
        tsr.sample_once()
    out = tsr.export()
    assert out["samples_taken"] == 20
    assert len(out["t_unix_s"]) == 8  # bounded: ring, not append
    # the ring holds the LAST 8 samples, oldest first
    assert out["t_unix_s"] == [float(i) for i in range(12, 20)]
    assert out["series"]["c"] == [float(i) for i in range(13, 21)]


# --- flight-recorder path --------------------------------------------------


def test_live_plane_shutdown_on_abort(tmp_path):
    """An aborting job stops the sampler thread AND the server (flight
    path), and the crash bundle carries the series ring."""
    from map_oxidize_tpu.obs import Obs

    cfg = JobConfig(input_path=str(tmp_path / "x"), obs_port=0,
                    obs_sample_s=0.01,
                    crash_dir=str(tmp_path / "crash")).validate()
    obs = Obs.from_config(cfg)
    url = obs.server.url
    assert _get_json(url + "/status")["schema"] == "moxt-status-v1"
    with pytest.raises(RuntimeError, match="boom"):
        with obs.recording(cfg, "wordcount"):
            obs.registry.count("did_work", 3)
            raise RuntimeError("boom")
    # server refused, sampler thread dead — clean shutdown on the abort
    with pytest.raises((urllib.error.URLError, OSError)):
        _get(url + "/status", timeout=2)
    obs.series._thread.join(timeout=10)
    assert not obs.series._thread.is_alive()
    bundles = list((tmp_path / "crash").iterdir())
    assert len(bundles) == 1
    doc = json.loads((bundles[0] / "metrics.json").read_text())
    assert doc["series"]["schema"] == "moxt-series-v1"
    assert doc["counters"]["did_work"] == 3
    # satellite: the bundle dir feeds obs xprof directly (no extraction)
    from map_oxidize_tpu.cli import main

    assert main(["obs", "xprof", str(bundles[0])]) == 0
    assert main(["obs", "xprof", str(tmp_path / "crash")]) == 0


# --- comms observatory -----------------------------------------------------


def test_comms_oracle_sharded_merge(tmp_path):
    """The comms table's all_to_all bytes equal the exchange-payload
    oracle for the shapes actually exchanged, and the flat gate counters
    agree with the table."""
    import jax

    from map_oxidize_tpu.api import MapOutput, SumReducer
    from map_oxidize_tpu.obs import Obs
    from map_oxidize_tpu.parallel.engine import ShardedReduceEngine
    from map_oxidize_tpu.parallel.shuffle import exchange_payload_bytes

    if len(jax.devices()) < 2:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    cfg = JobConfig(input_path=str(tmp_path / "x"), batch_size=1 << 10,
                    key_capacity=1 << 12).validate()
    obs = Obs.from_config(cfg)
    eng = ShardedReduceEngine(cfg, SumReducer())
    eng.obs = obs
    rng = np.random.default_rng(3)
    n_feeds = 3
    for _ in range(n_feeds):
        keys = rng.integers(0, 1 << 32, 512, dtype=np.uint64)
        out = MapOutput(hi=(keys >> 32).astype(np.uint32),
                        lo=keys.astype(np.uint32),
                        values=np.ones(512, np.int32), records_in=512)
        eng.feed(out)
    eng.flush()
    table = obs.registry.comms_table()
    a2a = [r for r in table if r["collective"] == "all_to_all"
           and r["program"] == "shuffle/merge"]
    assert len(a2a) == 1
    row = a2a[0]
    exchanges = obs.registry.counters["shuffle/exchanges"]
    oracle = exchanges * exchange_payload_bytes(eng.S, eng.bucket_cap, 4)
    assert row["count"] == exchanges
    assert row["bytes"] == oracle
    assert row["shape"] == f"{eng.S}x{eng.bucket_cap}"
    # sampled latency: the first exchange is always sampled
    assert row["latency_ms"] and row["latency_ms"]["count"] >= 1
    # flat gate counters mirror the table
    c = obs.registry.counters
    assert c["comms/all_to_all/shuffle/merge/bytes"] == oracle
    assert c["comms/all_to_all/shuffle/merge/calls"] == exchanges
    assert c["shuffle/all_to_all_bytes"] == oracle  # legacy counter agrees
    # the psum rider is tabled too
    assert any(r["collective"] == "psum" and r["program"] == "shuffle/merge"
               for r in table)
    obs.finish_xprof()


def test_comms_in_metrics_doc_and_ledger(tmp_path):
    """End-to-end: a sharded inverted-index run exports the comms table
    in the metrics doc AND the ledger entry, with flat comms counters in
    the entry's metrics."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    from map_oxidize_tpu.runtime.driver import run_inverted_index_job

    corpus = tmp_path / "docs.txt"
    _write_corpus(corpus, lines=60)
    cfg = JobConfig(input_path=str(corpus), output_path="", metrics=False,
                    batch_size=1 << 10,
                    metrics_out=str(tmp_path / "m.json"),
                    ledger_dir=str(tmp_path / "ledger"))
    run_inverted_index_job(cfg)
    doc = json.loads((tmp_path / "m.json").read_text())
    assert any(r["program"] == "collect/route_append"
               for r in doc["comms"])
    from map_oxidize_tpu.obs import ledger

    (entry,) = ledger.read(str(tmp_path / "ledger"))
    assert any(r["program"] == "collect/route_append"
               for r in entry["comms"])
    assert any(k.startswith("comms/all_to_all/collect/route_append")
               for k in entry["metrics"])


def test_comms_gate_catches_injected_regression():
    """The ledger gate flags unexplained comms-bytes growth (and stall
    episodes), and passes identical comms."""
    from map_oxidize_tpu.obs import ledger

    base = {"ts_unix_s": 1.0, "version": "x", "config_hash": "h",
            "workload": "wordcount", "corpus_bytes": 100, "n_processes": 1,
            "phases_s": {}, "metrics": {
                "comms/all_to_all/shuffle/merge/bytes": 1 << 20,
                "comms/all_to_all/shuffle/merge/calls": 4,
            }}
    same = dict(base, ts_unix_s=2.0)
    diff = ledger.diff_entries(base, same, threshold_pct=10.0)
    assert diff["regressions"] == []
    worse = dict(base, ts_unix_s=3.0, metrics=dict(
        base["metrics"], **{
            "comms/all_to_all/shuffle/merge/bytes": 2 << 20}))
    diff = ledger.diff_entries(base, worse, threshold_pct=10.0)
    assert any("unexplained comms growth" in r for r in diff["regressions"])
    # a collective appearing from nothing flags too
    appeared = dict(base, ts_unix_s=4.0, metrics=dict(
        base["metrics"], **{"comms/psum/new_site/bytes": 4096}))
    diff = ledger.diff_entries(base, appeared, threshold_pct=10.0)
    assert any("comms/psum/new_site/bytes" in r
               for r in diff["regressions"])
    # stall satellite: any stall increase is a regression
    stalled = dict(base, ts_unix_s=5.0, metrics=dict(
        base["metrics"], **{"heartbeat/stalls": 2}))
    diff = ledger.diff_entries(base, stalled, threshold_pct=10.0)
    assert any("stall episodes" in r for r in diff["regressions"])


def test_obs_diff_crash_dir(tmp_path, capsys):
    """Satellite: ``obs diff --crash-dir`` compares a flight bundle
    against the ledger with no hand extraction."""
    from map_oxidize_tpu.cli import main
    from map_oxidize_tpu.obs import Obs, ledger

    cfg = JobConfig(input_path=str(tmp_path / "x"),
                    ledger_dir=str(tmp_path / "ledger"),
                    crash_dir=str(tmp_path / "crash")).validate()
    # a completed run appends the ledger entry
    obs = Obs.from_config(cfg)
    with obs.recording(cfg, "wordcount"):
        obs.registry.count("comms/psum/p/bytes", 1024)
    obs.finish(cfg, "wordcount")
    # then the same job crashes with doubled comms bytes
    obs2 = Obs.from_config(cfg)
    try:
        with obs2.recording(cfg, "wordcount"):
            obs2.registry.count("comms/psum/p/bytes", 4096)
            raise RuntimeError("injected")
    except RuntimeError:
        pass
    assert len(ledger.read(str(tmp_path / "ledger"))) == 1
    rc = main(["obs", "diff", "--ledger-dir", str(tmp_path / "ledger"),
               "--crash-dir", str(tmp_path / "crash"), "--gate"])
    out = capsys.readouterr().out
    assert "crash bundle" in out
    assert "comms/psum/p/bytes" in out
    assert rc == 3  # the injected comms growth gates


# --- ObsContext isolation --------------------------------------------------


def test_two_obs_context_isolation(tmp_path):
    """Two concurrent jobs in one process keep disjoint metrics state:
    dispatches made under each context land in that job's registry
    only (the resident-server groundwork)."""
    import jax
    import jax.numpy as jnp

    from map_oxidize_tpu.obs import Obs
    from map_oxidize_tpu.obs.compile import observed_jit
    from map_oxidize_tpu.obs.context import current_obs, use_obs

    cfg = JobConfig(input_path=str(tmp_path / "x")).validate()
    obs_a = Obs.from_config(cfg)
    obs_b = Obs.from_config(cfg)
    prog = observed_jit("ctx/test_prog", jax.jit(lambda x: x + 1))
    barrier = threading.Barrier(2)

    def _job(obs, n, arr):
        with use_obs(obs):
            assert current_obs() is obs
            barrier.wait(timeout=30)
            for _ in range(n):
                np.asarray(prog(arr))

    x = jnp.arange(8)
    ta = threading.Thread(target=_job, args=(obs_a, 5, x))
    tb = threading.Thread(target=_job, args=(obs_b, 9, x))
    ta.start()
    tb.start()
    ta.join(timeout=120)
    tb.join(timeout=120)
    ha = obs_a.registry.histograms.get("device/dispatch_gap_ms")
    hb = obs_b.registry.histograms.get("device/dispatch_gap_ms")
    # the compiling call is excluded from the gap histogram; whichever
    # thread compiled lost one observation
    assert ha is not None and hb is not None
    assert ha.count + hb.count == 5 + 9 - 1
    assert {ha.count, hb.count} in ({4, 9}, {5, 8})
    # per-job xprof deltas see each job's own dispatches
    da = obs_a.finish_xprof()
    db = obs_b.finish_xprof()
    assert (da["programs"]["ctx/test_prog"]["dispatches"]
            + db["programs"]["ctx/test_prog"]["dispatches"]) == 14
    # registries never shared a counter
    assert obs_a.registry is not obs_b.registry


def test_obs_context_reaches_prefetch_threads(tmp_path):
    """Regression (ISSUE-7 satellite): the ContextVar bound by
    ``Obs.recording`` does NOT inherit into spawned threads, so the
    pipeline's producer thread used to observe under whatever job
    activated last.  With the bind-on-spawn fix, dispatches made while
    mapping IN THE PREFETCH THREAD route to the spawning job — two
    concurrent jobs keep disjoint dispatch histograms."""
    import jax
    import jax.numpy as jnp

    from map_oxidize_tpu.obs.compile import observed_jit
    from map_oxidize_tpu.runtime.driver import run_wordcount_job
    from map_oxidize_tpu.workloads.wordcount import make_wordcount

    prog = observed_jit("ctx/prefetch_prog", jax.jit(lambda x: x * 2))
    barrier = threading.Barrier(2)

    class _DispatchingMapper:
        """Delegates to the python mapper but dispatches a jitted
        program per chunk — with ``num_map_workers=1`` and
        ``pipeline_depth>1`` the inline map (and so the dispatch) runs
        in the PREFETCH thread, not the driver thread."""

        def __init__(self, inner):
            self._inner = inner
            self._first = True

        def __getattr__(self, item):
            return getattr(self._inner, item)

        def map_chunk(self, chunk):
            if self._first:
                self._first = False
                barrier.wait(timeout=60)  # both jobs demonstrably live
            np.asarray(prog(jnp.arange(8)))
            return self._inner.map_chunk(chunk)

    chunks = {"a": 6, "b": 10}
    grabbed: dict = {}
    results: dict = {}

    def _job(name):
        corpus = tmp_path / f"{name}.txt"
        _write_corpus(corpus, lines=40)
        mapper, reducer = make_wordcount("ascii", use_native=False)
        cfg = JobConfig(
            input_path=str(corpus), output_path="", metrics=False,
            num_chunks=chunks[name], num_map_workers=1,
            pipeline_depth=3, batch_size=1 << 12,
            key_capacity=1 << 12, mapper="python", use_native=False,
        )
        try:
            results[name] = run_wordcount_job(
                cfg, _DispatchingMapper(mapper), reducer,
                on_obs=lambda obs: grabbed.__setitem__(name, obs))
        except BaseException as e:  # pragma: no cover - surfaced below
            results[name] = e

    threads = [threading.Thread(target=_job, args=(n,)) for n in chunks]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    for name, r in results.items():
        assert not isinstance(r, BaseException), (name, r)
    ha = grabbed["a"].registry.histograms.get("device/dispatch_gap_ms")
    hb = grabbed["b"].registry.histograms.get("device/dispatch_gap_ms")
    assert ha is not None and hb is not None, \
        "prefetch-thread dispatches did not reach the jobs' registries"
    # ctx/prefetch_prog dispatches once per chunk; all but a possible
    # compiling first call land in the job's OWN gap histogram — under
    # the pre-fix fallback one registry would absorb both jobs'
    # observations while the other starved
    assert ha.count >= chunks["a"] - 1, (ha.count, hb.count)
    assert hb.count >= chunks["b"] - 1, (ha.count, hb.count)
    # decisive: the per-job xprof deltas (the overlay routed by
    # ObsContext) attribute each job EXACTLY its own chunk count of
    # prefetch-thread dispatches
    na = results["a"].metrics.get("xprof/ctx/prefetch_prog/dispatches", 0)
    nb = results["b"].metrics.get("xprof/ctx/prefetch_prog/dispatches", 0)
    assert (na, nb) == (chunks["a"], chunks["b"])


# --- 2-process Gloo: per-proc ports + proc-0 aggregate ---------------------

_CHILD = r"""
import json, logging, sys
pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
corpus = sys.argv[4]; art = sys.argv[5]
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.utils.logging import configure
from map_oxidize_tpu.parallel.distributed import (
    init_distributed, run_distributed_job)
configure(logging.INFO)
init_distributed(f"127.0.0.1:{port}", num_processes=nproc, process_id=pid)
cfg = JobConfig(input_path=corpus, output_path="", chunk_bytes=2048,
                batch_size=1 << 12, key_capacity=1 << 12, top_k=5,
                metrics=False, obs_port=0, obs_sample_s=0.05,
                dist_coordinator=f"127.0.0.1:{port}",
                dist_num_processes=nproc, dist_process_id=pid,
                metrics_out=f"{art}/m.json")
r = run_distributed_job(cfg, "wordcount")
print("RESULT", json.dumps({"records": r.records}))
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _dist_env(portfile: str):
    env = dict(os.environ)
    for k in ("PALLAS_AXON_POOL_IPS", "PJRT_LIBRARY_PATH",
              "TPU_LIBRARY_PATH", "PJRT_DEVICE", "TPU_ACCELERATOR_TYPE",
              "TPU_TOPOLOGY", "TPU_WORKER_HOSTNAMES"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MOXT_OBS_PORT_FILE"] = portfile
    return env


def test_distributed_per_proc_ports_and_aggregate(tmp_path):
    """2 Gloo processes with --obs-port 0: each serves its OWN port,
    both /status docs carry their process slot, proc 0's carries the
    skew-aware aggregate — scraped live, mid-run."""
    corpus = tmp_path / "c.txt"
    _write_corpus(corpus, lines=4000)
    portfile = tmp_path / "ports.txt"
    env = _dist_env(str(portfile))
    port = _free_port()
    procs = [subprocess.Popen(
        [sys.executable, "-c", _CHILD, str(i), "2", str(port),
         str(corpus), str(tmp_path)],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True) for i in range(2)]
    scraped: dict = {}
    err = None
    try:
        deadline = time.monotonic() + 300
        ports: dict = {}
        while time.monotonic() < deadline and len(ports) < 2:
            if portfile.exists():
                for line in portfile.read_text().splitlines():
                    p, prt = line.split()
                    ports[int(p)] = int(prt)
            if any(p.poll() is not None for p in procs):
                break
            time.sleep(0.02)
        assert len(ports) == 2, f"port discovery failed: {ports}"
        assert ports[0] != ports[1]
        # scrape BOTH processes mid-run (retry: the doc must show an
        # open phase to count as mid-run evidence)
        while time.monotonic() < deadline and len(scraped) < 2:
            for slot, prt in ports.items():
                if slot in scraped:
                    continue
                try:
                    doc = _get_json(f"http://127.0.0.1:{prt}/status")
                except (urllib.error.URLError, OSError):
                    continue
                if doc.get("phase"):
                    scraped[slot] = doc
            time.sleep(0.02)
    except BaseException as e:
        err = e
    logs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            out = "(timeout)"
        logs.append(out)
    if err is not None:
        raise AssertionError(f"scrape failed: {err}\n--- proc0:\n"
                             f"{logs[0]}\n--- proc1:\n{logs[1]}")
    for i, p in enumerate(procs):
        assert p.returncode == 0, f"process {i} failed:\n{logs[i]}"
    assert len(scraped) == 2, f"mid-run scrape incomplete:\n{logs[0]}"
    for slot, doc in scraped.items():
        assert doc["schema"] == "moxt-status-v1"
        assert doc["process"] == slot
        assert doc["n_processes"] == 2
        assert doc["meta"]["workload"] == "wordcount"
    agg = scraped[0].get("aggregate")
    assert agg is not None, "proc 0 /status lacks the aggregate"
    assert agg["n_processes"] == 2
    assert "collective_wait_frac" in agg
    assert "est_rows_per_sec" in agg
    assert "aggregate" not in scraped[1]
    # per-process metrics docs carry the distributed comms observatory
    md0 = json.loads((tmp_path / "m.json.proc0").read_text())
    comms_progs = {r["program"] for r in md0["comms"]}
    assert "dist/flag_psum" in comms_progs
    assert "shuffle/merge" in comms_progs
    assert "dist/gather_strings" in comms_progs
    assert md0["series"]["schema"] == "moxt-series-v1"
