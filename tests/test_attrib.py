"""Deep profiling plane (ISSUE-11 tentpole): the wall-clock attribution
ledger, on-demand /profile captures, the host sampling profiler, and the
persistent cross-run calibration store.

The attribution tests drive a REAL slowed CPU job (the same harness
tests/test_obs_live.py uses) so the buckets carry live wall, then pin
the decomposition identity: buckets + unattributed == wall, nothing
negative, the remainder honest.  The store tests exercise the
round-trip, the cross-run merge, and BOTH refusal modes (schema version
and a row whose identity disagrees with its key).
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.obs import attrib, calib, profiler


def _write_corpus(path, lines: int = 400) -> None:
    rng = np.random.default_rng(7)
    with open(path, "w") as f:
        for _ in range(lines):
            f.write(" ".join(f"w{i}" for i in
                             rng.integers(0, 60, 8)) + "\n")


class _SlowMapper:
    """Delegating mapper that sleeps per chunk (in the prefetch thread,
    so the consumer's stall is REAL feed-wait)."""

    def __init__(self, inner, delay_s: float):
        self._inner = inner
        self._delay = delay_s

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def map_chunk(self, chunk):
        time.sleep(self._delay)
        return self._inner.map_chunk(chunk)


@pytest.fixture(scope="module")
def slowed_job(tmp_path_factory):
    """One slowed wordcount with the live plane + /profile server on:
    returns the final metrics document, the obs URL scrapes captured
    mid-run, and the /profile outcomes."""
    from map_oxidize_tpu.runtime.driver import run_wordcount_job
    from map_oxidize_tpu.workloads.wordcount import make_wordcount

    tmp = tmp_path_factory.mktemp("attrib")
    corpus = tmp / "c.txt"
    _write_corpus(corpus, lines=2000)
    mapper, reducer = make_wordcount("ascii", use_native=False)
    cfg = JobConfig(
        input_path=str(corpus), output_path="", metrics=False,
        num_chunks=10, batch_size=1 << 12, num_map_workers=1,
        mapper="python", use_native=False,
        obs_port=0, obs_sample_s=0.03,
        profile_dir=str(tmp / "profiles"),
        metrics_out=str(tmp / "metrics.json"),
    )
    portfile = tmp / "ports.txt"
    os.environ["MOXT_OBS_PORT_FILE"] = str(portfile)
    box: dict = {}

    def _run():
        try:
            box["result"] = run_wordcount_job(
                cfg, _SlowMapper(mapper, 0.2), reducer)
        except BaseException as e:  # pragma: no cover - surfaced below
            box["error"] = e

    t = threading.Thread(target=_run)
    t.start()
    try:
        deadline = time.monotonic() + 60
        while not portfile.exists() and time.monotonic() < deadline:
            time.sleep(0.005)
        port = int(portfile.read_text().split()[1])
        url = f"http://127.0.0.1:{port}"
        status = None
        while time.monotonic() < deadline:
            with urllib.request.urlopen(url + "/status", timeout=5) as r:
                status = json.loads(r.read())
            if (status.get("phase") == "map+reduce"
                    and status.get("attrib")):
                break
            time.sleep(0.01)
        box["mid_status"] = status
        # concurrent /profile: exactly one capture runs, the loser 409s
        body = json.dumps({"duration_s": 0.5, "host_sample_hz": 60,
                           "device": False}).encode()
        codes: list = []
        docs: list = []

        def _post():
            req = urllib.request.Request(url + "/profile", data=body,
                                         method="POST")
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    codes.append(resp.getcode())
                    docs.append(json.loads(resp.read()))
            except urllib.error.HTTPError as e:
                codes.append(e.code)

        t1 = threading.Thread(target=_post)
        t2 = threading.Thread(target=_post)
        t1.start()
        time.sleep(0.1)
        t2.start()
        t1.join()
        t2.join()
        box["profile_codes"] = sorted(codes)
        box["profile_docs"] = docs
    finally:
        t.join(timeout=120)
        os.environ.pop("MOXT_OBS_PORT_FILE", None)
    if "error" in box:
        raise box["error"]
    with open(tmp / "metrics.json") as f:
        box["metrics"] = json.load(f)
    box["tmp"] = tmp
    return box


# --- the attribution ledger -------------------------------------------------


def test_buckets_sum_to_wall_within_tolerance(slowed_job):
    """The decomposition identity on a real job: every bucket >= 0,
    buckets + unattributed == wall (to rounding), and on this slowed
    pipelined run the buckets cover >= 80% of the wall with feed_wait
    the dominant bucket (the injected sleep runs in the prefetch
    thread — its visible residue IS the consumer stall)."""
    doc = slowed_job["metrics"]["attrib"]
    assert doc["schema"] == "moxt-attrib-v1"
    total = 0.0
    for name, row in doc["buckets"].items():
        assert row["ms"] >= 0.0, f"negative bucket {name}: {row}"
        total += row["ms"]
    assert total == pytest.approx(doc["attributed_ms"], abs=1.0)
    # buckets are measured on independent clocks (perf_counter sums vs
    # the unix wall), so the identity holds to a small relative bound,
    # not exactly — the remainder clamps at zero when sums run slightly
    # hot
    assert (doc["attributed_ms"] + doc["unattributed_ms"]
            == pytest.approx(doc["wall_ms"], rel=0.03))
    assert doc["unattributed_pct"] <= 20.0, doc
    # the ~0.2s x 10 chunks of injected producer sleep is visible wall,
    # and it dominates every bucket except the cold-process ones
    # (compile/setup depend on whether an earlier test in this process
    # already warmed the jit caches — not this test's business)
    assert doc["buckets"]["feed_wait"]["ms"] > 1000.0
    steady = {k: v["ms"] for k, v in doc["buckets"].items()
              if k not in ("compile", "setup")}
    assert max(steady, key=steady.get) == "feed_wait", doc["buckets"]


def test_attrib_flat_gauges_and_live_status(slowed_job):
    """The flat attrib/* gauges ride the metrics doc (ledger/BENCH
    evidence), and the MID-RUN /status carried a live decomposition."""
    gauges = slowed_job["metrics"]["gauges"]
    assert "attrib/unattributed_pct" in gauges
    assert gauges["attrib/feed_wait_ms"] > 0
    live = slowed_job["mid_status"]["attrib"]
    assert live["schema"] == "moxt-attrib-v1"
    assert live["wall_ms"] < slowed_job["metrics"]["attrib"]["wall_ms"]


def test_where_token_and_heartbeat_line():
    """where_token picks the dominant bucket; a heartbeat with .where
    set appends it to the line."""
    from map_oxidize_tpu.obs.heartbeat import Heartbeat

    doc = {"unattributed_pct": 5.0,
           "buckets": {"device_compute": {"ms": 610.0, "pct": 61.0},
                       "feed_wait": {"ms": 340.0, "pct": 34.0}}}
    assert attrib.where_token(doc) == "compute 61%"
    lines = []
    hb = Heartbeat(interval_s=1.0, clock=lambda: 0.0,
                   emit=lines.append)
    hb.where = "compute 61%"
    hb.final_beat()
    assert "where=compute 61%" in lines[0]


def test_unattributed_gate_fires_on_injected_hole():
    """obs diff --gate flags an unattributed-fraction regression: a
    +10-point hole flags, jitter below the floor does not."""
    from map_oxidize_tpu.obs import ledger

    def entry(pct):
        return {"workload": "wc", "config_hash": "x", "version": "1",
                "corpus_bytes": 10, "phases_s": {},
                "metrics": {"attrib/unattributed_pct": pct}}

    diff = ledger.diff_entries(entry(4.0), entry(40.0), force=True)
    assert any("unattributed" in r for r in diff["regressions"]), diff
    diff = ledger.diff_entries(entry(4.0), entry(9.0), force=True)
    assert not diff["regressions"], diff


def test_where_cli_renders(slowed_job, capsys):
    from map_oxidize_tpu.obs.cli import obs_main

    rc = obs_main(["where", str(slowed_job["tmp"] / "metrics.json")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "feed_wait" in out and "unattributed" in out


# --- on-demand deep profiling ----------------------------------------------


def test_profile_concurrent_capture_409(slowed_job):
    """Exactly one of two concurrent POST /profile requests captures;
    the other gets 409 (single-capture mutex)."""
    assert slowed_job["profile_codes"] == [200, 409]
    doc = slowed_job["profile_docs"][0]
    assert doc["schema"] == "moxt-profile-v1"
    assert doc["host_samples"] > 0
    assert os.path.isfile(doc["host_stacks"])
    # the capture counted into the job's registry
    assert slowed_job["metrics"]["counters"]["profile/captures"] == 1
    # and carried a live attribution snapshot
    assert doc["attrib"]["schema"] == "moxt-attrib-v1"


def test_host_sampler_sees_known_hot_thread():
    """The sampling profiler produces stacks naming a function we KNOW
    is hot (a spinning thread)."""
    stop = threading.Event()

    def _known_hot_spin():
        while not stop.is_set():
            sum(i * i for i in range(500))

    t = threading.Thread(target=_known_hot_spin, name="hot-spin")
    t.start()
    try:
        sampler = profiler.HostSampler(hz=200)
        sampler.start()
        time.sleep(0.4)
        sampler.stop()
    finally:
        stop.set()
        t.join()
    text = sampler.collapsed()
    assert sampler.samples > 10
    assert "_known_hot_spin" in text, text[:500]
    # flame report parses and classifies it
    report = profiler.flame_report(text, top=5)
    assert "_known_hot_spin" in report or "hot-spin" in report


def test_capture_duration_bounds(tmp_path):
    with pytest.raises(ValueError):
        profiler.capture(str(tmp_path), duration_s=0.0, device=False)
    with pytest.raises(ValueError):
        profiler.capture(str(tmp_path),
                         duration_s=profiler.MAX_CAPTURE_S + 1,
                         device=False)


def test_jax_trace_alias_is_profiler_device_trace():
    """Satellite: utils.profiling.jax_trace is the profiler's
    device_trace — one implementation."""
    from map_oxidize_tpu.utils import profiling

    assert profiling.jax_trace is profiler.device_trace


# --- the calibration store --------------------------------------------------


def _fake_comms_rows():
    return [
        {"collective": "all_to_all", "program": "shuffle/merge",
         "shape": "8x1024", "count": 10, "bytes": 10 * (1 << 20),
         "latency_ms": {"count": 4, "mean": 2.5, "p50": 2.4,
                        "p95": 3.0, "max": 3.2}},
        {"collective": "psum", "program": "kmeans/stream_step",
         "shape": "4x9", "count": 20, "bytes": 20 * 144,
         "latency_ms": None},
    ]


def _fake_xprof():
    return {"programs": {
        "kmeans/stream_step": {"dispatches": 8, "dispatch_ms": 12.0,
                               "sampled_device_ms": 30.0,
                               "device_samples": 2, "compiles": 1,
                               "compile_ms": 400.0}}}


def test_calib_round_trip_and_two_run_merge(tmp_path):
    """Two runs merge into ONE store: counts accumulate, the bandwidth
    table shows a nonzero per-collective GB/s row keyed by
    (collective, program, shape-bucket)."""
    path = str(tmp_path / "calib.json")
    ident = {"platform": "cpu", "device_count": 8, "topology": "1x8"}
    for _run in range(2):
        store = calib.CalibStore(path=path)
        assert store.accumulate_run(ident, _fake_comms_rows(),
                                    _fake_xprof()) == 3
        store.save_merged()
    merged = calib.CalibStore.load(path)
    assert merged.doc["runs"] == 2
    key = "cpu|8|1x8|all_to_all|shuffle/merge|1MB|job"
    row = merged.doc["comms"][key]
    assert row["calls"] == 20 and row["runs"] == 2
    assert row["bytes"] == 20 * (1 << 20)
    bw = [r for r in merged.bandwidth_table()
          if r["collective"] == "all_to_all"]
    assert bw and bw[0]["gbytes_per_s"] > 0
    assert bw[0]["shape_bucket"] == "1MB"
    prog = merged.doc["programs"]["cpu|8|1x8|kmeans/stream_step"]
    assert prog["dispatches"] == 16 and prog["compiles"] == 2
    # render is non-empty and names the collective
    text = calib.render(merged)
    assert "all_to_all" in text and "1MB" in text


def test_calib_refuses_version_mismatch(tmp_path):
    path = str(tmp_path / "calib.json")
    store = calib.CalibStore(path=path)
    store.accumulate_run({"platform": "cpu", "device_count": 1,
                          "topology": "1x1"}, _fake_comms_rows(), None)
    store.save_merged()
    doc = json.load(open(path))
    doc["version"] = 99
    json.dump(doc, open(path, "w"))
    with pytest.raises(calib.CalibMismatch, match="version"):
        calib.CalibStore.load(path)
    # a new run's merge refuses too (and leaves the file intact)
    run = calib.CalibStore(path=path)
    run.accumulate_run({"platform": "cpu", "device_count": 1,
                        "topology": "1x1"}, _fake_comms_rows(), None)
    with pytest.raises(calib.CalibMismatch):
        run.save_merged()
    assert json.load(open(path))["version"] == 99  # untouched


def test_calib_refuses_topology_identity_mismatch(tmp_path):
    """A row whose stored identity disagrees with its key (a doctored/
    torn store) refuses the merge."""
    path = str(tmp_path / "calib.json")
    store = calib.CalibStore(path=path)
    store.accumulate_run({"platform": "cpu", "device_count": 2,
                          "topology": "1x2"}, _fake_comms_rows(), None)
    store.save_merged()
    doc = json.load(open(path))
    key = next(iter(doc["comms"]))
    doc["comms"][key]["topology"] = "2x8"  # disagrees with the key
    json.dump(doc, open(path, "w"))
    with pytest.raises(calib.CalibMismatch, match="topology"):
        calib.CalibStore.load(path)


def test_calib_shape_buckets():
    assert calib.shape_bucket(0) == "0B"
    assert calib.shape_bucket(100) == "64B"
    assert calib.shape_bucket(1 << 20) == "1MB"
    assert calib.shape_bucket((1 << 20) + 5) == "1MB"
    assert calib.shape_bucket((1 << 21) - 1) == "1MB"
    assert calib.shape_bucket(1 << 21) == "2MB"


def test_calib_obs_wiring_end_to_end(tmp_path):
    """Two real CPU jobs with --calib-dir produce one merged store whose
    program rows accumulate across the runs."""
    from map_oxidize_tpu.runtime import run_job

    corpus = tmp_path / "c.txt"
    _write_corpus(corpus, lines=100)
    for i in range(2):
        cfg = JobConfig(
            input_path=str(corpus), output_path="", metrics=False,
            num_chunks=4, batch_size=1 << 12, num_shards=1,
            calib_dir=str(tmp_path / "calib"),
        ).validate()
        r = run_job(cfg, "wordcount")
        assert r.metrics.get("calib/runs") == i + 1
    store = calib.CalibStore.load(str(tmp_path / "calib"))
    assert store.doc["runs"] == 2
    rows = [v for v in store.doc["programs"].values()
            if v["program"] == "engine/merge_packed"]
    assert rows and rows[0]["runs"] == 2
    # the jit cache is process-global (an earlier test in the same
    # process may already have warmed this program), so compiles only
    # bound above; dispatches from BOTH runs accumulate either way
    assert rows[0]["compiles"] <= 1
    assert rows[0]["dispatches"] >= 2


# --- trend satellite: MULTICHIP rounds --------------------------------------


def test_trend_loads_multichip_rounds(tmp_path):
    """obs trend --bench accepts MULTICHIP_r*.json beside BENCH_r*.json;
    the two families trend as separate groups and an ok 1 -> 0 flip
    ranks as a regression mover."""
    from map_oxidize_tpu.obs import trend

    paths = []
    for i, ok in enumerate([True, True, False], 1):
        p = tmp_path / f"MULTICHIP_r{i:02d}.json"
        p.write_text(json.dumps({"n_devices": 8, "rc": 0 if ok else 1,
                                 "ok": ok, "skipped": False,
                                 "tail": "dryrun"}))
        paths.append(str(p))
    b = tmp_path / "BENCH_r01.json"
    b.write_text(json.dumps({"parsed": {"value": 1.0, "vs_baseline": 5.0,
                                        "workloads": {"wc": 5.0}}}))
    paths.append(str(b))
    entries = trend.bench_rounds(paths)
    kinds = {e["workload"] for e in entries}
    assert kinds == {"multichip-rounds", "bench-rounds"}
    multi = [e for e in entries if e["workload"] == "multichip-rounds"]
    assert len(multi) == 3
    assert multi[0]["metrics"] == {"n_devices": 8, "rc": 0, "ok": 1,
                                   "skipped": 0}
    movers = trend.movers(multi)
    ok_mv = [m for m in movers if m["name"] == "ok"]
    assert ok_mv and ok_mv[0]["direction"] == "regressed"
    rc_mv = [m for m in movers if m["name"] == "rc"]
    assert rc_mv and rc_mv[0]["direction"] == "new"


def test_trend_cli_multichip_groups(tmp_path, capsys):
    from map_oxidize_tpu.obs.cli import obs_main

    for i, ok in enumerate([True, False], 1):
        (tmp_path / f"MULTICHIP_r{i:02d}.json").write_text(
            json.dumps({"n_devices": 8, "rc": 0 if ok else 1, "ok": ok,
                        "skipped": False}))
    rc = obs_main(["trend", "--bench",
                   str(tmp_path / "MULTICHIP_r*.json")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "multichip-rounds" in out
    assert "ok" in out
