"""Native unicode tokenizer mode: parity with the Python unicode fallback.

The reference's ``split_whitespace()`` + ``to_lowercase()`` are Unicode
(``/root/reference/src/main.rs:96-97``); round 1 shipped unicode only on the
Python path.  The native mode transforms UTF-8 (Unicode whitespace -> ' ',
full lowercase incl. CPython's Final_Sigma context rule) and must match
``chunk.decode('utf-8').lower().split()`` bit for bit — tables are generated
FROM Python's own str.lower()/str.isspace(), so these tests are the proof the
transform applies them correctly.
"""

from collections import Counter

import numpy as np
import pytest

from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.native.bindings import load_or_none
from map_oxidize_tpu.ops.hashing import join_u64
from map_oxidize_tpu.runtime import run_job
from map_oxidize_tpu.workloads.wordcount import tokenize

native = load_or_none()
pytestmark = pytest.mark.skipif(native is None, reason="native build unavailable")


def _counts(out):
    k = join_u64(out.hi, out.lo)
    return {out.dictionary.lookup(int(h)): int(c)
            for h, c in zip(k.tolist(), out.values.tolist())}


CASES = [
    b"",
    "Füchse ÜBER den Zaun über FÜCHSE".encode(),
    "İstanbul STRASSE weiß ÅNGSTRÖM DŽungla".encode(),      # expansions
    "ΣΟΦΟΣ ΟΔΥΣΣΕΥΣ Σ ΑΣ' Α̇Σ ΑΣ̇Β".encode(),               # final sigma
    "ideographic　space en quad nbsp".encode(),
    "seps\x1cand\x1dmore\x1e\x1f done".encode(),            # str.split extras
    "日本語 中文 mixed ASCII Text 123".encode(),
    ("x" * 5000 + " Ü " + "y" * 3).encode(),
    "İİİ oİo".encode(),                 # İ -> i + U+0307
]


@pytest.mark.parametrize("case", CASES, ids=range(len(CASES)))
def test_unicode_wordcount_parity(case):
    from map_oxidize_tpu.native.build import NativeStream

    out = NativeStream(1, "unicode").map_chunk(case)
    want = dict(Counter(tokenize(case, "unicode")))
    assert _counts(out) == want
    assert out.records_in == sum(want.values())


@pytest.mark.parametrize("case", CASES, ids=range(len(CASES)))
def test_unicode_bigram_parity(case):
    from map_oxidize_tpu.native.build import NativeStream

    out = NativeStream(2, "unicode").map_chunk(case)
    toks = tokenize(case, "unicode")
    want = dict(Counter(toks[i] + b" " + toks[i + 1]
                        for i in range(len(toks) - 1)))
    assert _counts(out) == want


def test_unicode_random_fuzz(rng):
    """Random mixed-script corpora: native == Python on every draw."""
    from map_oxidize_tpu.native.build import NativeStream

    pool = ("abc ÄÖÜ ß ς Σ σ İ ı 中 文     . , ' ̇ "
            "Q W ΤΕΛΟΣ λόγος").split(" ")
    pool += [" ", "\t", "　", "\n"]
    for _ in range(20):
        parts = rng.choice(pool, size=rng.integers(0, 200))
        case = " ".join(parts).encode()
        out = NativeStream(1, "unicode").map_chunk(case)
        want = dict(Counter(tokenize(case, "unicode")))
        assert _counts(out) == want, case


def test_invalid_utf8_raises_like_python():
    from map_oxidize_tpu.native.build import NativeStream

    for bad in (b"ok \xff bad", b"trunc \xc3", b"overlong \xc0\xaf",
                b"surrogate \xed\xa0\x80", b"stray \x80"):
        with pytest.raises(UnicodeDecodeError):
            NativeStream(1, "unicode").map_chunk(bad)
        with pytest.raises(UnicodeDecodeError):
            tokenize(bad, "unicode")


def test_invalid_utf8_mmap_path_raises_decode_error(tmp_path):
    """The mmap fast path must raise the same exception TYPE as map_chunk
    and the Python fallback for invalid UTF-8 (not a generic RuntimeError)."""
    from map_oxidize_tpu.native.build import NativeStream

    p = tmp_path / "bad.txt"
    p.write_bytes(b"fine words here \xff broken")
    it = NativeStream(1, "unicode").iter_file(str(p), 4096)
    with pytest.raises(UnicodeDecodeError):
        list(it)


def test_hard_cut_backs_off_to_codepoint_boundary(tmp_path):
    """A whitespace-free window of multi-byte codepoints (CJK joined by
    U+3000 only) used to hard-cut mid-sequence and abort valid input; the
    cut must back off to a codepoint boundary and the job must agree with
    the whole-file Python tokenization (wordcount is chunking-independent)."""
    from map_oxidize_tpu.native.build import NativeStream

    word = "語言文字處理系統測試"        # 30 UTF-8 bytes, no ASCII at all
    text = "　".join([word] * 40).encode()  # U+3000 separators only
    p = tmp_path / "cjk.txt"
    p.write_bytes(text)
    def file_counts(chunk_bytes):
        """Union the per-chunk delta dictionaries, then resolve hashes."""
        from map_oxidize_tpu.ops.hashing import HashDictionary, join_u64

        d = HashDictionary()
        by_hash = Counter()
        for o, _ in NativeStream(1, "unicode").iter_file(str(p), chunk_bytes):
            d.update(o.dictionary)
            for h, c in zip(join_u64(o.hi, o.lo).tolist(),
                            o.values.tolist()):
                by_hash[h] += c
        return Counter({d.lookup(h): c for h, c in by_hash.items()})

    want = Counter(tokenize(text, "unicode"))
    # chunk window smaller than one word forces repeated hard cuts; cuts
    # split tokens (documented, same as ascii mode), but every piece must be
    # a VALID utf-8 fragment and the byte mass must conserve
    got = file_counts(17)
    assert sum(len(t) * c for t, c in got.items()) == \
        sum(len(t) * c for t, c in want.items())
    for tok in got:
        tok.decode("utf-8")  # no mojibake fragments
    # with a window bigger than one word, counts match exactly
    assert file_counts(4096) == want


def test_unicode_job_end_to_end(tmp_path, rng):
    """run_job with tokenizer=unicode rides the native mmap path and matches
    the pure-Python run exactly (counts and output bytes)."""
    words = ["Füchse", "ÜBER", "weiß", "ΟΔΥΣΣΕΥΣ", "İzmir", "dog", "the,"]
    corpus = tmp_path / "u.txt"
    corpus.write_bytes("\n".join(
        " ".join(rng.choice(words, size=7)) for _ in range(500)).encode())

    def cfg(**kw):
        return JobConfig(input_path=str(corpus), tokenizer="unicode",
                         backend="cpu", num_shards=1, chunk_bytes=4096,
                         metrics=False, **kw)

    res_native = run_job(cfg(output_path=str(tmp_path / "n.txt"),
                             mapper="native"), "wordcount")
    res_python = run_job(cfg(output_path=str(tmp_path / "p.txt"),
                             mapper="python", use_native=False), "wordcount")
    assert res_native.counts == res_python.counts
    assert (tmp_path / "n.txt").read_bytes() == (tmp_path / "p.txt").read_bytes()
