"""Pipelined push shuffle + remote-staged transport (PR: push shuffle).

The claims under test mirror the transports' two legs:

* **pipelined** — a placement twin of hybrid with an eager-push cadence:
  byte-parity against ``hbm``/``disk`` on the 8-virtual-device mesh, the
  map-side combiner changes row counts but never results (conservation
  checksums are sum-combine-invariant), and a 2-process Gloo run keeps
  the lockstep flag sequence consistent while pushing per-block rounds.
* **remote** — shuffle partitions that outlive a worker: a 2-process
  shared-filesystem job completes with clean-run parity after one
  process is SIGKILLed mid-shuffle (manifest prefix + claim + re-map).
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.runtime import run_job

import test_distributed as td


def _corpus(tmp_path, lines=1200):
    path = tmp_path / "c.txt"
    td._write_corpus(path, lines=lines)
    return path


# --- admit() state machines (the PUSHING state) ----------------------------


def test_pipelined_admit_state_machine():
    from map_oxidize_tpu.shuffle import make_transport

    t = make_transport("pipelined")
    assert t.name == "pipelined"
    # PUSHING while under the cap: resident placement, eager cadence
    assert t.admit(10, 100, "t") == "push"
    assert t.admit(100, 100, "t") == "push"    # cap is inclusive
    assert t.admit(101, 100, "t") == "demote"  # the one-way trip
    assert t.admit(5, 100, "t") == "spill"     # never pushes again
    assert t.spilled_state


def test_remote_admit_state_machine():
    from map_oxidize_tpu.shuffle import make_transport

    t = make_transport("remote")
    assert t.name == "remote"
    assert t.spilled_state  # SPILLED from the first row, like disk
    assert t.admit(0, 1 << 30, "t") == "spill"


# --- map-side combiner units ------------------------------------------------


def _raw_output(keys, vals=None, planes=True):
    from map_oxidize_tpu.api import MapOutput
    from map_oxidize_tpu.ops.hashing import HashDictionary

    out = MapOutput(hi=None, lo=None,
                    values=None if vals is None
                    else np.asarray(vals, np.int32),
                    dictionary=HashDictionary(),
                    records_in=len(keys),
                    keys64=np.asarray(keys, np.uint64))
    if planes:  # ensure_planes materializes implicit-ones values too
        out.ensure_planes()
    return out


def test_combine_map_output_sum():
    from map_oxidize_tpu.ops.hashing import join_u64
    from map_oxidize_tpu.shuffle import combine_map_output

    out = _raw_output([7, 3, 7, 7, 3, 9], [1, 2, 3, 4, 5, 6])
    combined, n_in, n_out = combine_map_output(out, "sum")
    assert (n_in, n_out) == (6, 3)
    k64 = join_u64(combined.hi, combined.lo)
    got = dict(zip(k64.tolist(), np.asarray(combined.values).tolist()))
    assert got == {3: 7, 7: 8, 9: 6}
    # record accounting is untouched: combining changes rows, not records
    assert combined.records_in == out.records_in


def test_combine_map_output_implicit_ones_and_minmax():
    from map_oxidize_tpu.ops.hashing import join_u64
    from map_oxidize_tpu.shuffle import combine_map_output

    out = _raw_output([5, 5, 5, 2])  # values=None -> implicit ones
    combined, n_in, n_out = combine_map_output(out, "sum")
    assert (n_in, n_out) == (4, 2)
    k64 = join_u64(combined.hi, combined.lo)
    got = dict(zip(k64.tolist(), np.asarray(combined.values).tolist()))
    assert got == {5: 3, 2: 1}
    with pytest.raises(ValueError, match="sum"):
        combine_map_output(_raw_output([1, 1], planes=False), "min")
    cm, _, _ = combine_map_output(
        _raw_output([4, 4, 8], [9, 2, 5]), "min")
    k64 = join_u64(cm.hi, cm.lo)
    assert dict(zip(k64.tolist(),
                    np.asarray(cm.values).tolist())) == {4: 2, 8: 5}
    with pytest.raises(ValueError, match="combiner supports"):
        combine_map_output(_raw_output([1], [1]), "mean")


def test_combine_identity_window_passes_through():
    from map_oxidize_tpu.shuffle import combine_map_output

    out = _raw_output([1, 2, 3], [4, 5, 6])
    combined, n_in, n_out = combine_map_output(out, "sum")
    assert combined is out and n_in == n_out == 3


def test_combine_preserves_weighted_checksum():
    """The PR 16 conservation identity is sum-combine-invariant by
    construction: sum(mix64(k) * v) mod 2^64 is unchanged when duplicate
    keys collapse into summed partials — the reason audits stay green
    with the combiner on."""
    from map_oxidize_tpu.obs.dataplane import mix64
    from map_oxidize_tpu.ops.hashing import join_u64
    from map_oxidize_tpu.shuffle import combine_map_output

    rng = np.random.default_rng(3)
    keys = rng.integers(0, 50, 4000).astype(np.uint64)
    vals = rng.integers(1, 9, 4000).astype(np.int64)

    def wsum(k, v):
        return int((mix64(np.asarray(k, np.uint64))
                    * np.asarray(v, np.int64).view(np.uint64))
                   .sum(dtype=np.uint64))

    out = _raw_output(keys, vals)
    combined, n_in, n_out = combine_map_output(out, "sum")
    assert n_out < n_in
    k64 = join_u64(combined.hi, combined.lo)
    assert wsum(keys, vals) == wsum(k64, np.asarray(combined.values))


# --- single-controller parity on the 8-virtual-device mesh ------------------


def _run_wc(corpus, out, transport, push_combine="auto"):
    cfg = JobConfig(input_path=str(corpus), output_path=str(out),
                    backend="cpu", metrics=False, chunk_bytes=4096,
                    batch_size=1 << 12, key_capacity=1 << 12,
                    shuffle_transport=transport,
                    push_combine=push_combine)
    return run_job(cfg, "wordcount")


def test_pipelined_byte_parity_vs_hbm_and_disk(tmp_path):
    """Transport swap parity: the push cadence + combiner change WHEN
    rows travel and how many, never what they add up to."""
    corpus = _corpus(tmp_path)
    r_hbm = _run_wc(corpus, tmp_path / "hbm.txt", "hbm")
    r_pipe = _run_wc(corpus, tmp_path / "pipe.txt", "pipelined")
    r_off = _run_wc(corpus, tmp_path / "off.txt", "pipelined",
                    push_combine="off")
    assert ((tmp_path / "hbm.txt").read_bytes()
            == (tmp_path / "pipe.txt").read_bytes()
            == (tmp_path / "off.txt").read_bytes())
    assert r_pipe.metrics["shuffle/transport"] == "pipelined"
    assert r_pipe.metrics["plan/shuffle_transport"] == "pipelined"
    assert r_pipe.metrics["plan/shuffle_transport_provenance"] == "pinned"
    # the push pipeline ran and published its overlap gauge
    assert "pipeline/shuffle_overlap_ratio" in r_pipe.metrics
    assert r_pipe.metrics["pipeline/shuffle_overlap_ratio"] >= 0.0
    # combiner off: no combine evidence
    assert "shuffle/push_combined_in" not in r_off.metrics
    assert dict(r_hbm.counts) == dict(r_pipe.counts)


def test_pipelined_invertedindex_parity(tmp_path):
    """Pair mode (no combiner) under the pipelined transport: placement
    is hybrid's, output is byte-identical to hbm on the 8-device mesh."""
    cfgkw = dict(backend="cpu", metrics=False, chunk_bytes=4096,
                 batch_size=1 << 12)
    corpus = _corpus(tmp_path)
    run_job(JobConfig(input_path=str(corpus),
                      output_path=str(tmp_path / "hbm.txt"),
                      shuffle_transport="hbm", **cfgkw), "invertedindex")
    r = run_job(JobConfig(input_path=str(corpus),
                          output_path=str(tmp_path / "pipe.txt"),
                          shuffle_transport="pipelined", **cfgkw),
                "invertedindex")
    assert ((tmp_path / "hbm.txt").read_bytes()
            == (tmp_path / "pipe.txt").read_bytes())
    assert r.metrics["shuffle/transport"] == "pipelined"


def test_remote_single_controller_behaves_like_disk(tmp_path):
    """Placement-wise remote IS disk on a single controller (the staged
    object layout only exists multi-process): byte parity, spill path."""
    cfgkw = dict(backend="cpu", metrics=False, chunk_bytes=4096,
                 batch_size=1 << 12)
    corpus = _corpus(tmp_path)
    run_job(JobConfig(input_path=str(corpus),
                      output_path=str(tmp_path / "disk.txt"),
                      shuffle_transport="disk", **cfgkw), "invertedindex")
    r = run_job(JobConfig(input_path=str(corpus),
                          output_path=str(tmp_path / "rem.txt"),
                          shuffle_transport="remote", **cfgkw),
                "invertedindex")
    assert ((tmp_path / "disk.txt").read_bytes()
            == (tmp_path / "rem.txt").read_bytes())
    assert r.metrics["shuffle/transport"] == "remote"


def test_combiner_conservation_audit_green(tmp_path):
    """run_job raises ConservationError on any audit violation, so a
    clean return with the combiner forced ON and a reduced feed is the
    end-to-end invariance claim."""
    corpus = _corpus(tmp_path)
    r_on = _run_wc(corpus, tmp_path / "on.txt", "pipelined",
                   push_combine="on")
    r_off = _run_wc(corpus, tmp_path / "off.txt", "pipelined",
                    push_combine="off")
    assert dict(r_on.counts) == dict(r_off.counts)
    assert ((tmp_path / "on.txt").read_bytes()
            == (tmp_path / "off.txt").read_bytes())


# --- 2-process Gloo: push-round lockstep + remote-staged recovery ----------


_PUSH_CHILD = textwrap.dedent("""
    import json, sys
    pid, port, transport, corpus, out_path = (
        int(sys.argv[1]), sys.argv[2], sys.argv[3], sys.argv[4],
        sys.argv[5])
    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.parallel.distributed import (
        init_distributed, run_distributed_job)
    init_distributed(f"127.0.0.1:{port}", num_processes=2, process_id=pid)
    cfg = JobConfig(input_path=corpus, chunk_bytes=1024,
                    batch_size=1 << 12, key_capacity=1 << 12, top_k=5,
                    metrics=False, shuffle_transport=transport)
    r = run_distributed_job(cfg, "wordcount")
    payload = {
        "counts": {str(k): v for k, v in r.counts.items()},
        "flag_rounds": r.flag_rounds,
        "metrics": {k: v for k, v in (r.metrics or {}).items()
                    if str(k).startswith(("shuffle/", "pipeline/"))},
    }
    json.dump(payload, open(out_path, "w"), sort_keys=True)
    print("child", pid, "ok")
""")


def _launch_push(tmp_path, corpus, transport):
    env = td._env(4)
    outs = [str(tmp_path / f"push_{transport}_{i}.json") for i in range(2)]
    port = td._free_port()
    procs = [subprocess.Popen(
        [sys.executable, "-c", _PUSH_CHILD, str(i), str(port), transport,
         str(corpus), outs[i]],
        env=env, cwd=td.REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True) for i in range(2)]
    logs = [p.communicate(timeout=420)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), "\n".join(logs)
    return [json.load(open(o)) for o in outs]


def test_push_round_lockstep_consistency_2proc(tmp_path):
    """Both processes run the same flag-round sequence under the push
    cadence, agree on the replicated counts, and match the barrier
    transport's results exactly — while the push evidence (rounds, rows,
    window-combine reduction, overlap gauge) is live."""
    corpus = tmp_path / "c.txt"
    td._write_corpus(corpus, lines=600)
    base = _launch_push(tmp_path, corpus, "hbm")
    push = _launch_push(tmp_path, corpus, "pipelined")
    assert push[0]["counts"] == push[1]["counts"] == base[0]["counts"]
    assert push[0]["flag_rounds"] == push[1]["flag_rounds"]
    for doc in push:
        m = doc["metrics"]
        assert m["shuffle/transport"] == "pipelined"
        assert m["shuffle/push_rounds"] >= 1
        assert m["shuffle/push_rows"] >= 1
        # the window combiner collapsed duplicate keys before the push
        assert m["shuffle/push_combined_out"] < m["shuffle/push_combined_in"]
        assert m["pipeline/shuffle_overlap_ratio"] >= 0.0


_REMOTE_CHILD = textwrap.dedent("""
    import json, os, signal, sys
    pid, corpus, outdir, die = (int(sys.argv[1]), sys.argv[2],
                                sys.argv[3], int(sys.argv[4]))
    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.parallel.distributed import run_distributed_job
    from map_oxidize_tpu.shuffle import remote as rmod
    if die and pid == 1:
        # a REAL SIGKILL mid-shuffle, deterministically placed: after
        # the second committed chunk, between commit and the next append
        orig = rmod.RemoteStage.append_chunk
        n = [0]
        def bomb(self, *a, **kw):
            orig(self, *a, **kw)
            n[0] += 1
            if n[0] >= 2:
                os.kill(os.getpid(), signal.SIGKILL)
        rmod.RemoteStage.append_chunk = bomb
    cfg = JobConfig(input_path=corpus,
                    output_path=os.path.join(outdir, "out.txt"),
                    chunk_bytes=512, shuffle_transport="remote",
                    remote_stage_dir=os.path.join(outdir, "stage"),
                    remote_stage_timeout_s=8.0,
                    dist_num_processes=2, dist_process_id=pid,
                    metrics=False)
    r = run_distributed_job(cfg, "wordcount")
    json.dump({"counts": {str(k): v for k, v in r.counts.items()},
               "records": r.records},
              open(os.path.join(outdir, f"counts{pid}.json"), "w"),
              sort_keys=True)
    print("child", pid, "ok")
""")


def _launch_remote(tmp_path, corpus, sub, die):
    outdir = tmp_path / sub
    outdir.mkdir()
    env = td._env(1)  # no jax.distributed: FS-only coordination
    procs = [subprocess.Popen(
        [sys.executable, "-c", _REMOTE_CHILD, str(i), str(corpus),
         str(outdir), str(int(die))],
        env=env, cwd=td.REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True) for i in range(2)]
    logs = [p.communicate(timeout=420)[0] for p in procs]
    return outdir, [p.returncode for p in procs], logs


def test_remote_staged_2proc_clean(tmp_path):
    corpus = tmp_path / "c.txt"
    td._write_corpus(corpus, lines=400)
    outdir, codes, logs = _launch_remote(tmp_path, corpus, "clean", False)
    assert codes == [0, 0], "\n".join(logs)
    c0 = json.load(open(outdir / "counts0.json"))
    c1 = json.load(open(outdir / "counts1.json"))
    # the drain is replicated: both processes report the GLOBAL counts
    assert c0["counts"] == c1["counts"]
    # partitioned output covers the key space disjointly
    parts = sorted(p for p in os.listdir(outdir)
                   if p.startswith("out.txt.part"))
    assert len(parts) == 2
    # a stage manifest committed per process, schema-tagged
    m = json.load(open(outdir / "stage" / "manifest.proc0.json"))
    assert m["schema"] == "moxt-shuffle-stage-v1" and m["final"]


def test_remote_staged_sigkill_recovery(tmp_path):
    """Kill process 1 with SIGKILL two chunks into its stage: process 0
    must claim it, re-map only the un-committed chunks, drain every
    partition with the manifest checksums intact, and write output
    byte-identical to an unharmed run."""
    corpus = tmp_path / "c.txt"
    td._write_corpus(corpus, lines=400)
    clean, codes, logs = _launch_remote(tmp_path, corpus, "clean", False)
    assert codes == [0, 0], "\n".join(logs)
    killed, codes, logs = _launch_remote(tmp_path, corpus, "killed", True)
    assert codes[0] == 0, "\n".join(logs)
    assert codes[1] == -9  # genuinely SIGKILLed
    assert (json.load(open(killed / "counts0.json"))["counts"]
            == json.load(open(clean / "counts0.json"))["counts"])
    for part in ("out.txt.part0of2", "out.txt.part1of2"):
        assert ((killed / part).read_bytes()
                == (clean / part).read_bytes())
    # takeover evidence: exactly-one-survivor claim + recovery manifest
    assert (killed / "stage" / "claim.proc1").exists()
    rec = json.load(open(killed / "stage" / "manifest.proc1.rec.json"))
    assert rec["final"] and rec["staged_by"] == 0 and rec["proc"] == 1
