"""Distinct-count workload (HyperLogLog): estimator accuracy vs the exact
oracle, register math, python/native mapper parity, 1-vs-8-shard register
identity, and checkpoint/resume."""

import numpy as np
import pytest

from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.runtime import run_job
from map_oxidize_tpu.workloads.distinct import (
    DistinctMapper,
    distinct_model,
    hll_estimate,
    hll_registers,
)


def _corpus(tmp_path, n_lines=3000, vocab=5000, seed=0, name="c.txt"):
    rng = np.random.default_rng(seed)
    words = [b"w%05d" % i for i in range(vocab)]
    p = tmp_path / name
    with open(p, "wb") as f:
        for _ in range(n_lines):
            f.write(b" ".join(words[int(i)]
                              for i in rng.integers(0, vocab, 8)) + b"\n")
    return p


def _cfg(corpus, **kw):
    base = dict(input_path=str(corpus), output_path="", backend="cpu",
                num_shards=1, metrics=False, chunk_bytes=16 * 1024)
    base.update(kw)
    return JobConfig(**base)


def test_registers_match_reference_definition(rng):
    """hll_registers == a per-hash Python model of bucket/rank."""
    hashes = rng.integers(0, 2**64, size=20_000, dtype=np.uint64)
    p = 11
    regs = hll_registers(hashes, p)
    want = np.zeros(1 << p, np.int32)
    for h in hashes.tolist():
        b = h >> (64 - p)
        w = h & ((1 << (64 - p)) - 1)
        rank = (64 - p) + 1 if w == 0 else (64 - p) - w.bit_length() + 1
        want[b] = max(want[b], rank)
    np.testing.assert_array_equal(regs, want)


def test_estimate_accuracy_synthetic(rng):
    """~100k uniform hashes: estimate within 4 sigma of exact (rse
    1.04/sqrt(2^14) ~ 0.81%)."""
    n = 100_000
    hashes = rng.integers(0, 2**64, size=n, dtype=np.uint64)
    est = hll_estimate(hll_registers(hashes, 14))
    assert abs(est - n) / n < 0.033


def test_small_range_linear_counting(rng):
    """Cardinalities far below m use the zero-register correction and are
    near-exact."""
    hashes = rng.integers(0, 2**64, size=200, dtype=np.uint64)
    est = hll_estimate(hll_registers(hashes, 14))
    assert abs(est - 200) < 6


def test_job_estimate_matches_oracle(tmp_path):
    corpus = _corpus(tmp_path)
    res = run_job(_cfg(corpus), "distinct")
    with open(corpus, "rb") as f:
        exact = distinct_model([f.read()])
    assert 4000 < exact <= 5000  # most of the vocabulary gets drawn
    assert abs(res.estimate - exact) / exact < 0.033


def test_python_native_registers_identical(tmp_path):
    corpus = _corpus(tmp_path, n_lines=500)
    nat = DistinctMapper("ascii", use_native=True, p=12)
    if nat._native is None:
        pytest.skip("native build unavailable")
    py = DistinctMapper("ascii", use_native=False, p=12)
    chunk = open(corpus, "rb").read()
    a, b = nat.map_chunk(chunk), py.map_chunk(chunk)
    np.testing.assert_array_equal(a.lo, b.lo)
    np.testing.assert_array_equal(a.values, b.values)
    assert a.records_in == b.records_in


def test_sharded_registers_equal_single(tmp_path):
    """Max is associative/commutative: the 8-shard mesh run must produce
    bit-identical registers (and therefore the identical estimate)."""
    corpus = _corpus(tmp_path, n_lines=1500)
    r1 = run_job(_cfg(corpus), "distinct")
    r8 = run_job(_cfg(corpus, num_shards=8), "distinct")
    np.testing.assert_array_equal(r1.registers, r8.registers)
    assert r1.estimate == r8.estimate


def test_distinct_checkpoint_resume(tmp_path):
    """Standard per-chunk spill/replay: a full spilled run replayed into a
    fresh engine reproduces the identical registers."""
    import os

    corpus = _corpus(tmp_path, n_lines=1200)
    ck = str(tmp_path / "ck")
    want = run_job(_cfg(corpus), "distinct")
    got1 = run_job(_cfg(corpus, checkpoint_dir=ck, keep_intermediates=True),
                   "distinct")
    assert os.path.isdir(ck)
    got2 = run_job(_cfg(corpus, checkpoint_dir=ck), "distinct")  # pure replay
    np.testing.assert_array_equal(got1.registers, want.registers)
    np.testing.assert_array_equal(got2.registers, want.registers)
    assert not os.path.isdir(ck)  # success removes the spill by default


def test_unions_merge_by_max(tmp_path):
    """Registers from two disjoint corpora merged with np.maximum estimate
    the union — the HLL mergeability property the sharded path relies on."""
    c1 = _corpus(tmp_path, vocab=3000, seed=1, name="a.txt")
    rng = np.random.default_rng(2)
    words = [b"x%05d" % i for i in range(3000)]  # disjoint vocabulary
    c2 = tmp_path / "b.txt"
    with open(c2, "wb") as f:
        for _ in range(3000):
            f.write(b" ".join(words[int(i)]
                              for i in rng.integers(0, 3000, 8)) + b"\n")
    r1 = run_job(_cfg(c1), "distinct")
    r2 = run_job(_cfg(c2), "distinct")
    est = hll_estimate(np.maximum(r1.registers, r2.registers))
    assert abs(est - 6000) / 6000 < 0.04


def test_output_files(tmp_path):
    """distinct writes its result: a text summary by default, the raw
    (mergeable) registers for a .npy output path."""
    corpus = _corpus(tmp_path, n_lines=300)
    res = run_job(_cfg(corpus, output_path=str(tmp_path / "est.txt")),
                  "distinct")
    lines = dict(ln.split("\t") for ln in
                 (tmp_path / "est.txt").read_text().splitlines())
    assert float(lines["estimate"]) == pytest.approx(res.estimate, abs=0.1)
    assert int(lines["precision"]) == 14
    res2 = run_job(_cfg(corpus, output_path=str(tmp_path / "regs.npy")),
                   "distinct")
    np.testing.assert_array_equal(np.load(tmp_path / "regs.npy"),
                                  res2.registers)


def test_registers_high_precision_branch(rng):
    """p > 16 uses the bounded-scratch maximum.at fold: same registers as
    the bincount formulation computed at the same p via the model."""
    hashes = rng.integers(0, 2**64, size=30_000, dtype=np.uint64)
    p = 17
    regs = hll_registers(hashes, p)
    want = np.zeros(1 << p, np.int32)
    for h in hashes.tolist():
        b = h >> (64 - p)
        w = h & ((1 << (64 - p)) - 1)
        rank = (64 - p) + 1 if w == 0 else (64 - p) - w.bit_length() + 1
        want[b] = max(want[b], rank)
    np.testing.assert_array_equal(regs, want)


def test_native_hll_fold_matches_hash_extraction(tmp_path, rng):
    """The in-scan C++ register fold must equal hll_registers() applied to
    the raw hash stream of the same chunks — for several p values, both
    tokenizers, and the mmap file iterator (same cut offsets as the
    hash-only scan)."""
    from map_oxidize_tpu.native import bindings

    if bindings.load_or_none() is None:
        pytest.skip("native build unavailable")
    from map_oxidize_tpu.native.build import NativeStream

    blob = b"\n".join(
        b" ".join(b"t%04x" % int(v) for v in rng.integers(0, 1 << 16, 12))
        for _ in range(400)) + b"\n \n\tmixed  WS\r\n"
    for tokenizer in ("ascii", "unicode"):
        s = NativeStream(1, tokenizer)
        try:
            for p in (11, 14, 18):
                regs, nt = s.map_chunk_hll(blob, p)
                out = s.map_chunk_hashes(blob)
                assert nt == out.records_in
                np.testing.assert_array_equal(
                    regs.astype(np.int32), hll_registers(out.keys64, p))
        finally:
            s.close()
    path = tmp_path / "hll.txt"
    path.write_bytes(blob * 8)
    s = NativeStream(1, "ascii")
    try:
        folded = list(s.iter_file_hll(str(path), 4096, 12))
        raw = list(s.iter_file_hashes(str(path), 4096))
        assert [off for _, _, off in folded] == [off for _, off in raw]
        acc = np.zeros(1 << 12, np.int32)
        for regs, _, _ in folded:
            acc = np.maximum(acc, regs.astype(np.int32))
        want = hll_registers(
            np.concatenate([o.keys64 for o, _ in raw]), 12)
        np.testing.assert_array_equal(acc, want)
    finally:
        s.close()
