"""Dataflow workloads over the DCN path: 2 real OS processes, Gloo
collectives, one global 8-device CPU mesh (the same harness
tests/test_distributed.py uses).

Pins the ISSUE-14 acceptance bar: sort + join + sessionize oracle-exact
in 2-process Gloo, including a sort forced past ``--collect-max-rows``
that completes through per-process disk buckets with globally sorted
concatenated output and nonzero spill on every process.
"""

import json
import os
import subprocess
import sys

import numpy as np

import tests.test_distributed as td

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, sys
import numpy as np
pid = int(sys.argv[1]); port = sys.argv[2]; workload = sys.argv[3]
tmp = sys.argv[4]; cap = int(sys.argv[5])
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.parallel.distributed import (
    init_distributed, run_distributed_job)
init_distributed(f"127.0.0.1:{port}", num_processes=2, process_id=pid)
cfg = JobConfig(
    input_path=f"{tmp}/a.npy" if workload == "join" else f"{tmp}/recs.npy",
    join_input_path=f"{tmp}/b.npy",
    output_path=f"{tmp}/out_{workload}",
    chunk_bytes=16 * 512, batch_size=1 << 12, metrics=False,
    collect_max_rows=cap, session_gap=400)
r = run_distributed_job(cfg, workload)
m = r.metrics or {}
doc = {"spill_rows": m.get("spill/rows", 0),
       "demotes": m.get("demote/events", 0),
       "transport": m.get("shuffle/transport")}
if workload == "sort":
    doc.update(n_rows=r.n_rows, spilled=r.spilled_rows)
elif workload == "join":
    doc.update(matches=r.n_matches, left=r.n_left, right=r.n_right,
               keys=r.n_keys)
else:
    doc.update(sessions=r.n_sessions, events=r.n_events, keys=r.n_keys)
json.dump(doc, open(f"{tmp}/res_{workload}_{pid}.json", "w"),
          sort_keys=True)
print("child", pid, "ok")
"""


def _launch(tmp_path, workload, cap=0):
    env = td._env(4)
    for attempt in range(2):
        port = td._free_port()
        procs = [subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(i), str(port), workload,
             str(tmp_path), str(cap)],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True) for i in range(2)]
        logs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=420)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                out = "(timeout)"
            logs.append(out)
        if all(p.returncode == 0 for p in procs):
            break
        if attempt == 1:
            for i, p in enumerate(procs):
                assert p.returncode == 0, f"process {i} failed:\n{logs[i]}"
    results = [json.load(open(tmp_path / f"res_{workload}_{i}.json"))
               for i in range(2)]
    return results


def _sort_input(tmp_path, n=6000, seed=3):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 1 << 64, n, dtype=np.uint64)
    keys[keys == np.uint64((1 << 64) - 1)] -= np.uint64(1)
    keys[: n // 10] = keys[5]  # duplicate block: payload order matters
    pay = rng.integers(0, 1 << 64, n, dtype=np.uint64)
    np.save(tmp_path / "recs.npy", np.stack([keys, pay], axis=1))
    return keys, pay


def _read_parts(tmp_path, workload, reader):
    parts = [reader(str(tmp_path / f"out_{workload}.part{i}of2"))
             for i in range(2)]
    return parts


def test_two_process_sort_matches_oracle(tmp_path):
    """Resident 2-process sort: replicated totals agree, each process
    writes its contiguous key range, and the parts concatenate
    PROCESS-MAJOR into the exact oracle order — no post-hoc sort."""
    from map_oxidize_tpu.workloads.sort import (
        read_sorted_records,
        sort_model,
    )

    keys, pay = _sort_input(tmp_path)
    results = _launch(tmp_path, "sort")
    assert results[0] == results[1]
    assert results[0]["n_rows"] == keys.shape[0]
    assert results[0]["spilled"] == 0
    parts = _read_parts(tmp_path, "sort", read_sorted_records)
    gk = np.concatenate([p[0] for p in parts])
    gp = np.concatenate([p[1] for p in parts])
    wk, wp = sort_model(keys, pay)
    assert np.array_equal(gk, wk)
    assert np.array_equal(gp, wp)


def test_two_process_forced_spill_sort_globally_sorted(tmp_path):
    """The acceptance scenario: a 2-process sort forced far past the
    resident cap COMPLETES via per-process disk buckets; the
    concatenated parts are the exact total order, spill/rows is nonzero
    on BOTH processes, and the disjoint spills sum to the global row
    count."""
    from map_oxidize_tpu.workloads.sort import (
        read_sorted_records,
        sort_model,
    )

    keys, pay = _sort_input(tmp_path, seed=4)
    n = keys.shape[0]
    results = _launch(tmp_path, "sort", cap=1000)
    assert results[0]["n_rows"] == n
    assert results[0]["spilled"] == n  # replicated global figure
    spills = [r["spill_rows"] for r in results]
    assert all(s > 0 for s in spills)
    assert sum(spills) == n  # disjoint partitions cover every row
    parts = _read_parts(tmp_path, "sort", read_sorted_records)
    gk = np.concatenate([p[0] for p in parts])
    gp = np.concatenate([p[1] for p in parts])
    wk, wp = sort_model(keys, pay)
    assert np.array_equal(gk, wk)
    assert np.array_equal(gp, wp)


def test_two_process_join_matches_oracle(tmp_path):
    from map_oxidize_tpu.workloads.join import (
        join_model,
        read_join_records,
    )

    rng = np.random.default_rng(5)
    na, nb = 3000, 2500
    ka = rng.integers(0, 400, na, dtype=np.uint64)
    pa = rng.integers(0, 1 << 40, na, dtype=np.uint64)
    kb = rng.integers(0, 400, nb, dtype=np.uint64)
    pb = rng.integers(0, 1 << 40, nb, dtype=np.uint64)
    np.save(tmp_path / "a.npy", np.stack([ka, pa], axis=1))
    np.save(tmp_path / "b.npy", np.stack([kb, pb], axis=1))
    results = _launch(tmp_path, "join")
    wk, wa, wb = join_model(ka, pa, kb, pb)
    assert results[0] == results[1]
    assert results[0]["matches"] == wk.shape[0]
    assert (results[0]["left"], results[0]["right"]) == (na, nb)
    assert results[0]["keys"] == np.unique(
        np.concatenate([ka, kb])).shape[0]
    parts = _read_parts(tmp_path, "join", read_join_records)
    gk = np.concatenate([p[0] for p in parts])
    ga = np.concatenate([p[1] for p in parts])
    gb = np.concatenate([p[2] for p in parts])
    # parts cover disjoint hash partitions; global order is recovered
    # by one lexsort for the oracle compare
    order = np.lexsort((gb, ga, gk))
    assert np.array_equal(gk[order], wk)
    assert np.array_equal(ga[order], wa)
    assert np.array_equal(gb[order], wb)


def test_two_process_sessionize_matches_oracle(tmp_path):
    from map_oxidize_tpu.workloads.sessionize import sessionize_model

    rng = np.random.default_rng(6)
    ne = 4000
    ek = rng.integers(0, 150, ne, dtype=np.uint64)
    ts = rng.integers(0, 90_000, ne, dtype=np.uint64)
    np.save(tmp_path / "recs.npy", np.stack([ek, ts], axis=1))
    results = _launch(tmp_path, "sessionize")
    mk, ms, me, mc = sessionize_model(ek, ts, 400)
    assert results[0] == results[1]
    assert results[0]["sessions"] == mk.shape[0]
    assert results[0]["events"] == ne
    assert results[0]["keys"] == np.unique(ek).shape[0]
    rows = []
    for i in range(2):
        path = tmp_path / f"out_sessionize.part{i}of2"
        rows += [tuple(int(x) for x in line.split("\t"))
                 for line in open(path).read().splitlines()]
    rows.sort(key=lambda r: (r[0], r[1]))
    want = list(zip(mk.tolist(), ms.tolist(), me.tolist(), mc.tolist()))
    assert rows == want
