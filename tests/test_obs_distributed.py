"""Distributed observability (ISSUE-3 tentpole): 2 real Gloo processes
run wordcount with --trace-out/--metrics-out/--ledger-dir/--progress, and
the artifacts must reconstruct the job — per-process shards with the
documented schema, one merged Chrome trace (pid = process slot, tids
preserved), a skew report whose per-process row counts sum to the
single-process oracle, stamped per-process metrics documents, a ledger
entry from process 0, and prefixed heartbeat lines.

One subprocess launch covers all of it (the coordination-service spin-up
dominates the cost; asserting eight facts on one run is cheap).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, logging, sys
pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
corpus = sys.argv[4]; art = sys.argv[5]
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.utils.logging import configure
from map_oxidize_tpu.parallel.distributed import (
    init_distributed, run_distributed_job)
configure(logging.INFO)
init_distributed(f"127.0.0.1:{port}", num_processes=nproc, process_id=pid)
cfg = JobConfig(input_path=corpus, output_path="", chunk_bytes=4096,
                batch_size=1 << 12, key_capacity=1 << 12, top_k=5,
                metrics=False,
                # the real CLI sets the per-process dist_* fields; they
                # differ per participant, so the shard identity check
                # must ignore them (regression: hashes used to differ)
                dist_coordinator=f"127.0.0.1:{port}",
                dist_num_processes=nproc, dist_process_id=pid,
                trace_out=f"{art}/t.json", metrics_out=f"{art}/m.json",
                ledger_dir=f"{art}/ledger",
                progress=True, progress_interval_s=0.001)
r = run_distributed_job(cfg, "wordcount")
print("RESULT", json.dumps({"records": r.records, "n_keys": r.n_keys,
                            "metrics_records": r.metrics["records_in"]}))
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env():
    env = dict(os.environ)
    for k in ("PALLAS_AXON_POOL_IPS", "PJRT_LIBRARY_PATH",
              "TPU_LIBRARY_PATH", "PJRT_DEVICE", "TPU_ACCELERATOR_TYPE",
              "TPU_TOPOLOGY", "TPU_WORKER_HOSTNAMES"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.fixture(scope="module")
def dist_obs_run(tmp_path_factory):
    """One 2-process Gloo wordcount run with every obs flag on; returns
    (artifact dir, per-process stdout logs, corpus path)."""
    tmp = tmp_path_factory.mktemp("dist_obs")
    corpus = tmp / "c.txt"
    rng = np.random.default_rng(11)
    words = [b"Alpha", b"beta,", b"Gamma.", b"delta", b"eps;", b"zeta"]
    with open(corpus, "wb") as f:
        for _ in range(3000):
            f.write(b" ".join(words[int(i)]
                              for i in rng.integers(0, 6, 6)) + b"\n")
    env = _env()
    logs = None
    for attempt in range(2):  # free-port probe is inherently racy
        port = _free_port()
        procs = [subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(i), "2", str(port),
             str(corpus), str(tmp)],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True) for i in range(2)]
        logs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=420)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                out = "(timeout)"
            logs.append(out)
        if all(p.returncode == 0 for p in procs):
            break
        if attempt == 1:
            for i, p in enumerate(procs):
                assert p.returncode == 0, f"process {i} failed:\n{logs[i]}"
    return tmp, logs, corpus


def _oracle_records(corpus) -> int:
    from map_oxidize_tpu.workloads.reference_model import wordcount_model

    with open(corpus, "rb") as f:
        return sum(wordcount_model([f.read()]).values())


def test_shard_schema_and_stamp(dist_obs_run):
    tmp, _logs, _corpus = dist_obs_run
    from map_oxidize_tpu.obs.merge import SHARD_SCHEMA, read_shard

    shards = [read_shard(str(tmp / f"t.json.proc{p}")) for p in (0, 1)]
    hashes = set()
    for p, s in enumerate(shards):
        assert s["schema"] == SHARD_SCHEMA
        assert s["meta"]["process"] == p
        assert s["meta"]["n_processes"] == 2
        assert s["meta"]["workload"] == "wordcount"
        assert s["meta"]["wall_start_unix_s"] > 0
        hashes.add(s["meta"]["config_hash"])
        assert isinstance(s["events"], list) and s["events"]
        assert {"phases_s", "counters", "gauges",
                "histograms"} <= set(s["metrics"])
    # identical identity across processes (same job)
    assert len(hashes) == 1


def test_merged_trace_pid_tid_mapping(dist_obs_run):
    tmp, _logs, _corpus = dist_obs_run
    merged = json.loads((tmp / "t.json").read_text())
    xs = [e for e in merged if e["ph"] == "X"]
    assert {e["pid"] for e in xs} == {0, 1}  # one pid per process
    for e in merged:
        assert e["ph"] in ("X", "i", "M")
        if e["ph"] == "X":
            assert isinstance(e["tid"], int)
            assert e["dur"] >= 0
            assert e["ts"] >= 0
    names = {e["name"] for e in xs}
    # both the distributed driver's spans and the engine's inner ones
    assert "dist/map_chunk" in names
    assert "dist/lockstep_flag" in names
    assert "dist/merge_local" in names
    assert "phase/map+reduce" in names
    # slot-keyed process names, not the per-shard OS pids
    proc_names = {e["pid"]: e["args"]["name"] for e in merged
                  if e.get("name") == "process_name"}
    assert proc_names == {0: "proc 0", 1: "proc 1"}


def test_skew_report_rows_sum_to_oracle(dist_obs_run):
    tmp, _logs, corpus = dist_obs_run
    skew = json.loads((tmp / "t.json.skew.json").read_text())
    assert skew["n_processes"] == 2
    per_proc = {r["process"]: r for r in skew["processes"]}
    assert set(per_proc) == {0, 1}
    # per-process mapped records sum to the single-process oracle total
    assert skew["records_total"] == _oracle_records(corpus)
    assert (per_proc[0]["records_in"] + per_proc[1]["records_in"]
            == skew["records_total"])
    # both processes paid the same lockstep rounds, and rows_fed tallies
    assert per_proc[0]["flag_rounds"] == per_proc[1]["flag_rounds"] >= 1
    assert skew["rows_fed_total"] == sum(
        r["rows_fed"] for r in skew["processes"])
    assert len(skew["straggler_ranking"]) == 2
    for r in skew["straggler_ranking"]:
        assert r["work_s"] >= 0 and r["collective_wait_s"] >= 0


def test_per_process_metrics_documents(dist_obs_run):
    tmp, logs, _corpus = dist_obs_run
    results = [json.loads(l.split("RESULT ", 1)[1].splitlines()[0])
               for l in logs]
    total = 0
    for p in (0, 1):
        md = json.loads((tmp / f"m.json.proc{p}").read_text())
        assert md["meta"]["process"] == p
        assert md["gauges"]["records_in"] == results[p]["metrics_records"]
        assert md["gauges"]["flag_rounds"] >= 1
        assert md["counters"]["shuffle/all_to_all_bytes"] > 0
        total += md["gauges"]["records_in"]
    assert total == sum(r["records"] for r in results)


def test_ledger_entry_from_process_zero(dist_obs_run):
    tmp, _logs, corpus = dist_obs_run
    from map_oxidize_tpu.obs import ledger

    entries = ledger.read(str(tmp / "ledger"))
    assert len(entries) == 1  # process 0 only — no double append
    e = entries[0]
    assert e["workload"] == "wordcount"
    assert e["n_processes"] == 2
    assert e["records_total"] == _oracle_records(corpus)
    assert "map+reduce" in e["phases_s"]
    assert e["config_hash"] and e["version"]


def test_heartbeat_prefixed_and_process_zero_only(dist_obs_run):
    _tmp, logs, _corpus = dist_obs_run
    assert "[proc 0] progress: phase=map+reduce" in logs[0]
    # the old "not wired for multi-process" warning is gone
    for log in logs:
        assert "not wired for" not in log
    # process 1 stays silent by default (lockstep: its lines are noise)
    assert "progress:" not in logs[1]


def test_obs_merge_cli_re_merges_real_shards(dist_obs_run, tmp_path,
                                             capsys):
    tmp, _logs, _corpus = dist_obs_run
    from map_oxidize_tpu.cli import main

    out = tmp_path / "re_merged.json"
    rc = main(["obs", "merge", str(tmp / "t.json"), "--out", str(out)])
    assert rc == 0
    assert "merged 2 shards" in capsys.readouterr().out
    re_merged = json.loads(out.read_text())
    original = json.loads((tmp / "t.json").read_text())
    assert ({e["pid"] for e in re_merged if e["ph"] == "X"}
            == {e["pid"] for e in original if e["ph"] == "X"} == {0, 1})
