"""Differential fuzz: every execution route must agree exactly.

For a set of seeded adversarial corpora (empty lines, CRLF, punctuation
stuck to tokens, tokens longer than the 16-byte inline compare, token
pairs that only differ after byte 16, a single enormous line, no trailing
newline), run word count and bigram through every route — python mapper +
device fold, native mapper + device fold, native + host collect
(hash-only, winners rescan), and the 8-shard all_to_all mesh — and assert
byte-exact agreement with the reference-semantics model
(``workloads/reference_model.py``: tokenize per
``/root/reference/src/main.rs:96-97``, merge per main.rs:131-134).

This is the consolidated version of the per-path parity tests: one
corpus-generation bug surface, every route, many seeds.
"""

import numpy as np
import pytest

from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.native.bindings import load_or_none
from map_oxidize_tpu.runtime import run_job
from map_oxidize_tpu.workloads.reference_model import top_k_model, wordcount_model

native = load_or_none()


def _adversarial_corpus(seed: int) -> bytes:
    rng = np.random.default_rng(seed)
    vocab = [
        b"the", b"The", b"THE",                      # case-folding collisions
        b"cat,", b"cat", b"cat.",                    # punctuation kept
        b"x" * 15, b"x" * 16, b"x" * 17,             # inline-compare boundary
        b"longtoken_prefix_" + b"a" * 16,            # differ after byte 16...
        b"longtoken_prefix_" + b"b" * 16,            # ...same first 16 bytes
        b"\xc3\xa9t\xc3\xa9",                        # multibyte UTF-8 (ascii
        b"z",                                        #  mode treats as bytes)
    ]
    # varied separators: single space (the zero-copy contiguous n-gram
    # window), plus multi-byte runs and tabs/vertical-tabs (the scratch
    # join fallback) — both joins must hash identically
    seps = [b" ", b" ", b" ", b"  ", b"\t", b" \t ", b"\x0b"]
    lines = []
    for _ in range(int(rng.integers(100, 300))):
        k = int(rng.integers(0, 9))
        toks = [vocab[int(i)] for i in rng.integers(0, len(vocab), k)]
        line = b""
        for j, t in enumerate(toks):
            if j:
                line += seps[int(rng.integers(0, len(seps)))]
            line += t
        if rng.random() < 0.1:
            line += b"\r"          # CRLF: \r is whitespace per the reference
        lines.append(line)
    blob = b"\n".join(lines)
    if seed % 2:
        blob += b"\n"              # half the corpora lack a trailing newline
    if seed % 3 == 0:              # one enormous single line
        blob += b"\n" + b" ".join(
            vocab[int(i)] for i in rng.integers(0, len(vocab), 2000))
    return blob


def _routes():
    """(name, config-overrides) for every wordcount execution route that
    runs without special hardware."""
    routes = [
        ("python-fold", dict(mapper="python", use_native=False)),
        ("sharded-8", dict(num_shards=8)),
    ]
    if native is not None:
        routes += [
            ("native-fold", dict(mapper="native")),
            ("native-collect", dict(mapper="native", reduce_mode="collect")),
        ]
    return routes


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_wordcount_all_routes_agree(tmp_path, seed):
    blob = _adversarial_corpus(seed)
    p = tmp_path / "c.txt"
    p.write_bytes(blob)
    want = wordcount_model([blob])
    want_top = top_k_model(want, 10)
    for name, kw in _routes():
        base = dict(input_path=str(p), output_path="", backend="cpu",
                    metrics=False, chunk_bytes=1024, batch_size=4096,
                    key_capacity=1 << 14, num_shards=1)
        base.update(kw)
        res = run_job(JobConfig(**base), "wordcount")
        assert dict(res.counts) == dict(want), f"route {name} seed {seed}"
        assert res.top[:10] == want_top, f"route {name} seed {seed} top-k"


@pytest.mark.parametrize("seed", [1, 2])
def test_bigram_all_routes_agree(tmp_path, seed):
    """Bigram pairs span lines within a chunk, so the model must see the
    same chunking: use one chunk (chunk_bytes > corpus)."""
    from collections import Counter

    from map_oxidize_tpu.workloads.wordcount import tokenize

    blob = _adversarial_corpus(seed)
    p = tmp_path / "c.txt"
    p.write_bytes(blob)
    toks = tokenize(blob)
    want = Counter(toks[i] + b" " + toks[i + 1]
                   for i in range(len(toks) - 1))
    want_top = top_k_model(want, 10)
    for name, kw in _routes():
        base = dict(input_path=str(p), output_path="", backend="cpu",
                    metrics=False, chunk_bytes=1 << 22, batch_size=4096,
                    key_capacity=1 << 16, num_shards=1)
        base.update(kw)
        res = run_job(JobConfig(**base), "bigram")
        assert dict(res.counts) == dict(want), f"route {name} seed {seed}"
        assert res.top[:10] == want_top, f"route {name} seed {seed} top-k"
