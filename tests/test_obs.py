"""Observability subsystem (moxt.obs): spans, metrics, heartbeat, CLI
round-trip, and the demotion accounting it makes observable.

Covers the ISSUE-1 acceptance surface: span nesting/exception safety,
Chrome trace-event schema validity, histogram quantiles, the
``--metrics-out`` / ``--trace-out`` CLI round trip on a tiny corpus, and
heartbeat emission under a fake clock — all on the CPU test mesh.

ISSUE-3 additions: the run ledger + regression diff (``obs diff``,
``--gate``), trace-shard merging with skew accounting (``obs merge``),
provenance stamping (version + config hash on every export), and the
failure flight recorder — including the regression test that a job
raising mid-phase still flushes partial metrics/trace with its open
spans closed.
"""

import json
import threading

import numpy as np
import pytest

from map_oxidize_tpu.obs import Obs
from map_oxidize_tpu.obs.heartbeat import Heartbeat
from map_oxidize_tpu.obs.metrics import Histogram, MetricsRegistry
from map_oxidize_tpu.obs.trace import NULL_SPAN, Tracer


# --- tracer ---------------------------------------------------------------


def test_span_nesting_records_depth_and_containment():
    t = Tracer(enabled=True)
    with t.span("outer", rows=2):
        with t.span("inner"):
            pass
    events = {e["name"]: e for e in t.chrome_trace() if e["ph"] == "X"}
    outer, inner = events["outer"], events["inner"]
    # child starts after parent and ends before it (time containment is
    # what gives Perfetto the nesting)
    assert inner["ts"] >= outer["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-6
    assert outer["args"]["rows"] == 2


def test_span_exception_safety_records_end_and_error():
    t = Tracer(enabled=True)
    with pytest.raises(ValueError):
        with t.span("boom"):
            raise ValueError("broken")
    (ev,) = [e for e in t.chrome_trace() if e["ph"] == "X"]
    assert ev["name"] == "boom"
    assert ev["dur"] >= 0
    assert "ValueError" in ev["args"]["error"]


def test_leaked_child_span_does_not_corrupt_parent_stack():
    t = Tracer(enabled=True)
    outer = t.span("outer")
    inner = t.span("inner")
    outer.__enter__()
    inner.__enter__()
    # outer exits while inner never did (a lower-level crash path):
    # the stack must pop through cleanly and later spans get depth 0
    outer.__exit__(None, None, None)
    with t.span("later"):
        pass
    by_name = {e["name"]: e for e in t._events}
    assert by_name["later"]["depth"] == 0


def test_disabled_tracer_is_noop_and_shared():
    t = Tracer(enabled=False)
    s = t.span("x", rows=1)
    assert s is NULL_SPAN
    with s:
        s.set(more=2)
    t.instant("marker")
    assert t.chrome_trace()[0]["name"] == "process_name"
    assert [e for e in t.chrome_trace() if e["ph"] in ("X", "i")] == []


def test_chrome_trace_schema_and_json_round_trip():
    t = Tracer(enabled=True)
    with t.span("a", bytes=np.int64(7), dev=np.int32(0)):
        t.instant("mark", gen=1)
    blob = json.dumps(t.chrome_trace())  # numpy attrs must serialize
    events = json.loads(blob)
    assert isinstance(events, list) and events
    for e in events:
        assert e["ph"] in ("X", "i", "M")
        assert isinstance(e["name"], str)
        if e["ph"] != "M":
            assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] == "X":
            assert e["dur"] >= 0
    assert any(e["ph"] == "X" and e["args"]["bytes"] == 7 for e in events)


def test_tracer_thread_safety_spans_from_workers():
    t = Tracer(enabled=True)

    def work(i):
        with t.span(f"w{i}"):
            with t.span(f"w{i}/child"):
                pass

    threads = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    xs = [e for e in t.chrome_trace() if e["ph"] == "X"]
    assert len(xs) == 16
    # each worker thread got its own tid; children share their parent's
    tids = {e["name"]: e["tid"] for e in xs}
    for i in range(8):
        assert tids[f"w{i}"] == tids[f"w{i}/child"]


def test_jsonl_export(tmp_path):
    t = Tracer(enabled=True)
    with t.span("outer"):
        with t.span("inner"):
            pass
    p = tmp_path / "events.jsonl"
    t.write_jsonl(str(p))
    rows = [json.loads(line) for line in p.read_text().splitlines()]
    depths = {r["name"]: r["depth"] for r in rows}
    assert depths == {"outer": 0, "inner": 1}


# --- histograms / registry ------------------------------------------------


def test_histogram_quantiles_exact_path():
    h = Histogram()
    for v in range(1, 101):
        h.observe(v)
    s = h.summary()
    assert s["count"] == 100
    assert s["max"] == 100
    assert abs(s["mean"] - 50.5) < 1e-9
    assert 45 <= s["p50"] <= 56
    assert 90 <= s["p95"] <= 100


def test_histogram_decimation_bounds_memory_keeps_quantiles():
    h = Histogram(max_samples=256)
    n = 100_000
    for v in range(n):
        h.observe(v)
    assert len(h._samples) < 256
    assert h.count == n
    assert h.max == n - 1 and h.min == 0
    # stride-sampled quantiles stay in the right decile
    assert 0.35 * n <= h.quantile(0.5) <= 0.65 * n
    assert h.quantile(0.95) >= 0.85 * n


def test_registry_summary_is_seed_compatible():
    r = MetricsRegistry()
    with r.phase("map+reduce"):
        pass
    r.count("chunks", 3)
    r.set("records_in", 1000)
    r.observe("feed_block_ms", 2.0)
    r.observe("feed_block_ms", 4.0)
    s = r.summary()
    assert "time/map+reduce_s" in s
    assert s["chunks"] == 3
    assert s["records_in"] == 1000
    assert "records_per_sec" in s  # derived, as the seed Metrics did
    assert s["feed_block_ms/count"] == 2
    assert s["feed_block_ms/max"] == 4.0
    d = r.to_dict()
    assert set(d) == {"phases_s", "counters", "gauges", "histograms"}
    json.dumps(d)  # the --metrics-out document must be valid JSON


def test_registry_gauge_max_watermark():
    r = MetricsRegistry()
    r.gauge_max("peak", 10)
    r.gauge_max("peak", 5)
    r.gauge_max("peak", 20)
    assert r.gauges["peak"] == 20


def test_profiling_shim_still_importable():
    # the seed import path must keep working (drivers outside the repo)
    from map_oxidize_tpu.utils.profiling import Metrics

    m = Metrics()
    with m.phase("x"):
        pass
    assert "time/x_s" in m.summary()


# --- heartbeat (fake clock) ----------------------------------------------


def test_heartbeat_emits_on_interval_with_fake_clock():
    now = [0.0]
    lines = []
    hb = Heartbeat(total_bytes=1000, interval_s=10.0,
                   clock=lambda: now[0], emit=lines.append)
    hb.set_phase("map+reduce")
    hb.update(rows=100, bytes_done=100)   # t=0: within interval, no beat
    assert lines == []
    now[0] = 5.0
    hb.update(rows=100, bytes_done=200)   # t=5: still within
    assert lines == []
    now[0] = 10.0
    hb.update(rows=100, bytes_done=300)   # t=10: beat
    assert len(lines) == 1
    assert "phase=map+reduce" in lines[0]
    assert "rows=300" in lines[0]
    assert "30.0%" in lines[0]
    assert "eta=" in lines[0]
    now[0] = 15.0
    hb.update(rows=100, bytes_done=400)   # within the next interval
    assert len(lines) == 1
    now[0] = 20.0
    hb.update(rows=100, bytes_done=1000)  # next beat, now 100%: no eta
    assert len(lines) == 2
    assert "100.0%" in lines[1]
    assert "eta=" not in lines[1]
    assert hb.beats == 2


def test_heartbeat_fraction_override_and_final_beat():
    now = [0.0]
    lines = []
    hb = Heartbeat(total_bytes=None, interval_s=60.0,
                   clock=lambda: now[0], emit=lines.append)
    hb.set_phase("iterate")
    now[0] = 1.0
    hb.update(rows=50, fraction=0.5)
    assert lines == []          # interval not elapsed
    hb.final_beat()             # jobs shorter than one interval still report
    assert len(lines) == 1
    assert "50.0%" in lines[0]


def test_heartbeat_rejects_bad_interval():
    with pytest.raises(ValueError):
        Heartbeat(interval_s=0)


# --- CLI round trip (tiny corpus, CPU) ------------------------------------


@pytest.fixture
def tiny_corpus(tmp_path):
    p = tmp_path / "tiny.txt"
    p.write_bytes(b"the quick brown fox jumps over the lazy dog\n" * 50)
    return p


def test_cli_metrics_and_trace_round_trip(tmp_path, tiny_corpus, capsys):
    from map_oxidize_tpu.cli import main

    m = tmp_path / "m.json"
    t = tmp_path / "t.json"
    rc = main(["wordcount", str(tiny_corpus),
               "--output", str(tmp_path / "out.txt"),
               "--metrics-out", str(m), "--trace-out", str(t),
               "--progress", "--progress-interval", "0.001",
               "--num-shards", "1", "--quiet"])
    assert rc == 0
    assert "Top 10 words:" in capsys.readouterr().out

    md = json.loads(m.read_text())
    # phase timings, counters, and at least one histogram (acceptance)
    assert "map+reduce" in md["phases_s"]
    assert md["phases_s"]["map+reduce"] > 0
    assert md["counters"]  # engine flush/put counters at minimum
    assert "feed_block_ms" in md["histograms"]
    assert md["histograms"]["feed_block_ms"]["count"] >= 1
    assert md["gauges"]["records_in"] == 450
    assert md["gauges"]["mem/host_rss_peak_bytes"] > 0

    td = json.loads(t.read_text())
    names = [e["name"] for e in td if e["ph"] == "X"]
    # spans cover map, reduce (the fused streaming phase), and finalize
    assert "phase/map+reduce" in names
    assert "phase/finalize" in names
    assert "engine/feed_block" in names
    # nesting: the feed span sits inside the map+reduce phase span
    by = {e["name"]: e for e in td if e["ph"] == "X"}
    ph, feed = by["phase/map+reduce"], by["engine/feed_block"]
    assert ph["ts"] <= feed["ts"]
    assert feed["ts"] + feed["dur"] <= ph["ts"] + ph["dur"] + 1e-6


def test_cli_invertedindex_trace_covers_collect(tmp_path, tiny_corpus):
    from map_oxidize_tpu.cli import main

    t = tmp_path / "t.json"
    rc = main(["invertedindex", str(tiny_corpus),
               "--output", str(tmp_path / "out.txt"),
               "--trace-out", str(t), "--num-shards", "1", "--quiet"])
    assert rc == 0
    names = [e["name"] for e in json.loads(t.read_text())
             if e["ph"] == "X"]
    assert "phase/map+collect" in names
    assert "phase/sort+postings" in names


def test_result_trace_without_file(tiny_corpus):
    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.runtime import run_job

    cfg = JobConfig(input_path=str(tiny_corpus), output_path="",
                    num_shards=1, metrics=False, trace_out="-")
    r = run_job(cfg, "wordcount")
    assert isinstance(r.trace, list)
    assert any(e.get("name") == "phase/finalize" for e in r.trace)
    # tracing off -> None, and metrics stay populated
    r2 = run_job(JobConfig(input_path=str(tiny_corpus), output_path="",
                           num_shards=1, metrics=False), "wordcount")
    assert r2.trace is None
    assert r2.metrics["records_in"] == 450


# --- sharded demotion accounting (ADVICE r5 regression) -------------------


def test_sharded_collect_demotion_rows_fed_parity(rng):
    """The demotion-triggering feed must not double-count its own block:
    after the handoff the host engine's rows_fed equals the sharded
    engine's, and the spill counters the new registry records stay
    consistent with the rows actually fed."""
    from map_oxidize_tpu.api import MapOutput
    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.parallel.collect import ShardedCollectEngine

    cfg = JobConfig(input_path="unused", backend="cpu", num_shards=8,
                    batch_size=512)
    eng = ShardedCollectEngine(cfg, max_rows=600)
    obs = Obs.from_config(cfg)
    eng.obs = obs

    def block(n):
        hi = rng.integers(0, 1 << 31, n).astype(np.uint32)
        lo = rng.integers(0, 1 << 31, n).astype(np.uint32)
        vals = np.zeros((n, 2), np.uint32)
        vals[:, 1] = np.arange(n, dtype=np.uint32)
        return MapOutput(hi=hi, lo=lo, values=vals, records_in=n)

    eng.feed(block(500))          # under max_rows: stays on device
    assert eng._host is None
    eng.feed(block(200))          # crosses 600: demotes, then feeds
    assert eng._host is not None
    assert eng.rows_fed == 700
    assert eng._host.rows_fed == 700   # parity — was 900 pre-fix
    eng.feed(block(100))          # already-demoted branch keeps parity
    assert eng.rows_fed == 800
    assert eng._host.rows_fed == 800
    assert obs.registry.counters["demote/events"] == 1
    # past max_rows the demoted host engine spills to disk buckets; every
    # fed pair must come back through the spilled CSR — an off-by-a-block
    # rows_fed skew would have started the spill one block early and the
    # spill/rows counter makes the volume observable
    terms, offsets, docs, holder = eng.finalize_spilled_csr()
    assert int(offsets[-1]) == 800
    assert obs.registry.counters["spill/rows"] == 800


# --- provenance stamping (ISSUE-3 satellite) -------------------------------


def test_exports_carry_version_and_config_hash(tmp_path, tiny_corpus):
    from map_oxidize_tpu import __version__
    from map_oxidize_tpu.cli import build_parser, config_from_args, main
    from map_oxidize_tpu.obs.ledger import config_hash

    m = tmp_path / "m.json"
    t = tmp_path / "t.json"
    rc = main(["wordcount", str(tiny_corpus), "--output", "",
               "--metrics-out", str(m), "--trace-out", str(t),
               "--num-shards", "1", "--quiet"])
    assert rc == 0
    md = json.loads(m.read_text())
    # the hash is a function of the ENGINE-relevant fields only: the same
    # run minus its artifact flags hashes identically
    want_hash = config_hash(config_from_args(build_parser().parse_args(
        ["wordcount", str(tiny_corpus), "--output", "other.txt",
         "--num-shards", "1"])))
    assert md["meta"]["version"] == __version__
    assert md["meta"]["config_hash"] == want_hash
    assert md["meta"]["workload"] == "wordcount"
    td = json.loads(t.read_text())
    meta = [e for e in td if e.get("name") == "moxt_meta"]
    assert len(meta) == 1
    assert meta[0]["args"]["config_hash"] == want_hash


def test_config_hash_ignores_artifact_paths_only():
    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.obs.ledger import config_hash

    base = JobConfig()
    assert config_hash(base) == config_hash(
        JobConfig(output_path="elsewhere.txt", metrics_out="m.json",
                  trace_out="t.json", ledger_dir="L", crash_dir="C",
                  progress=True))
    # engine-relevant fields DO change the hash
    assert config_hash(base) != config_hash(JobConfig(num_shards=4))
    assert config_hash(base) != config_hash(JobConfig(tokenizer="unicode"))
    # per-process addressing must NOT: the CLI sets a different
    # dist_process_id on every participant of ONE job, and shard merging
    # refuses mixed config hashes — slot/coordinator are not identity
    assert config_hash(
        JobConfig(dist_coordinator="a:1", dist_num_processes=2,
                  dist_process_id=0)) == config_hash(
        JobConfig(dist_coordinator="b:2", dist_num_processes=2,
                  dist_process_id=1))
    # ...but the process COUNT is (it changes the collective topology)
    assert config_hash(
        JobConfig(dist_coordinator="a:1", dist_num_processes=2,
                  dist_process_id=0)) != config_hash(
        JobConfig(dist_coordinator="a:1", dist_num_processes=4,
                  dist_process_id=0))


# --- run ledger + regression diff ------------------------------------------


def _entry(ledger, workload="wordcount", rate=1000.0, phases=None, ts=1.0):
    from map_oxidize_tpu import __version__

    return {"ts_unix_s": ts, "version": __version__,
            "config_hash": "cafe0123cafe0123", "workload": workload,
            "corpus_bytes": 1 << 20, "n_processes": 1,
            "phases_s": dict(phases or {"map+reduce": 1.0}),
            "metrics": {"records_per_sec": rate, "records_in": 1000}}


def test_ledger_append_read_and_zero_delta_diff(tmp_path):
    from map_oxidize_tpu.obs import ledger

    d = str(tmp_path / "led")
    e = _entry(ledger)
    ledger.append(d, e)
    ledger.append(d, dict(e, ts_unix_s=2.0))
    got = ledger.read(d)
    assert len(got) == 2
    diff = ledger.diff_entries(got[0], got[1])
    assert diff["regressions"] == []
    assert diff["warnings"] == []
    # a self-diff prints and flags nothing (the check.sh smoke contract)
    self_diff = ledger.diff_entries(got[1], got[1])
    assert self_diff["regressions"] == []


def test_ledger_diff_flags_slow_phase_and_throughput_drop(tmp_path):
    from map_oxidize_tpu.obs import ledger

    a = _entry(ledger, phases={"map+reduce": 1.0}, rate=1000.0)
    b = _entry(ledger, phases={"map+reduce": 1.5}, rate=700.0, ts=2.0)
    diff = ledger.diff_entries(a, b, threshold_pct=10.0)
    joined = "\n".join(diff["regressions"])
    assert "map+reduce" in joined
    assert "records_per_sec" in joined
    # below threshold: quiet
    c = _entry(ledger, phases={"map+reduce": 1.05}, rate=980.0, ts=3.0)
    assert ledger.diff_entries(a, c, threshold_pct=10.0)["regressions"] == []


def test_ledger_diff_refuses_apples_to_oranges(tmp_path):
    from map_oxidize_tpu.obs import ledger

    a = _entry(ledger)
    b = dict(_entry(ledger, ts=2.0), config_hash="beef4567beef4567")
    with pytest.raises(ledger.LedgerMismatch):
        ledger.diff_entries(a, b)
    # force downgrades the refusal to a warning
    diff = ledger.diff_entries(a, b, force=True)
    assert any("config_hash" in w for w in diff["warnings"])
    with pytest.raises(ledger.LedgerMismatch):
        ledger.diff_entries(a, dict(_entry(ledger, ts=2.0),
                                    workload="bigram"))
    # corpus size is identity too: the config hash excludes input paths,
    # so a 64MB run must not diff/gate against a 10GB run
    with pytest.raises(ledger.LedgerMismatch):
        ledger.diff_entries(a, dict(_entry(ledger, ts=2.0),
                                    corpus_bytes=10 << 30))


def test_ledger_gate_skips_different_corpus_size(tmp_path):
    from map_oxidize_tpu.obs import ledger

    d = str(tmp_path / "led")
    ledger.append(d, _entry(ledger, rate=1000.0, ts=1.0))
    other = dict(_entry(ledger, rate=100.0, ts=2.0), corpus_bytes=10 << 30)
    assert ledger.gate_against_previous(d, other, 10.0) == []


def test_ledger_gate_against_previous(tmp_path):
    from map_oxidize_tpu.obs import ledger

    d = str(tmp_path / "led")
    ledger.append(d, _entry(ledger, rate=1000.0, ts=1.0))
    ok = _entry(ledger, rate=990.0, ts=2.0)
    assert ledger.gate_against_previous(d, ok, 10.0) == []
    bad = _entry(ledger, rate=500.0, ts=3.0)
    regs = ledger.gate_against_previous(d, bad, 10.0)
    assert regs and "records_per_sec" in regs[0]
    # no prior comparable entry -> nothing to gate
    other = _entry(ledger, workload="bigram", ts=4.0)
    assert ledger.gate_against_previous(d, other, 10.0) == []


def test_cli_ledger_roundtrip_and_diff(tmp_path, tiny_corpus, capsys):
    """End-to-end: two CLI runs append ledger entries; `obs diff` on them
    prints per-phase deltas, and a gated self-diff is all-zero.  The
    prev-vs-last diff deliberately runs WITHOUT --gate: two sub-second
    runs on a loaded test host jitter past any sane threshold, and the
    gate's regression behavior is pinned by the injected-slowdown test
    below, not by wall-clock luck here."""
    from map_oxidize_tpu.cli import main

    led = str(tmp_path / "led")
    for _ in range(2):
        rc = main(["wordcount", str(tiny_corpus), "--output", "",
                   "--ledger-dir", led, "--num-shards", "1", "--quiet"])
        assert rc == 0
    rc = main(["obs", "diff", "--ledger-dir", led])
    out = capsys.readouterr().out
    assert rc == 0
    assert "ledger diff: wordcount" in out
    assert "phase/map+reduce_s" in out
    rc = main(["obs", "diff", "--ledger-dir", led, "--gate", "--",
               "-1", "-1"])
    assert rc == 0
    assert "no regressions" in capsys.readouterr().out


def test_cli_diff_gate_exits_nonzero_on_injected_slowdown(tmp_path,
                                                          capsys):
    from map_oxidize_tpu.cli import main
    from map_oxidize_tpu.obs import ledger

    led = str(tmp_path / "led")
    ledger.append(led, _entry(ledger, phases={"map+reduce": 1.0},
                              rate=1000.0, ts=1.0))
    ledger.append(led, _entry(ledger, phases={"map+reduce": 2.0},
                              rate=400.0, ts=2.0))
    rc = main(["obs", "diff", "--ledger-dir", led, "--gate"])
    out = capsys.readouterr().out
    assert rc == 3
    assert "regressions beyond threshold" in out
    assert "phase map+reduce" in out


# --- shard merge + skew ----------------------------------------------------


def _fake_shard(process, wall_start, records, work_ms):
    """A minimal but schema-true shard: one map span + one flag span."""
    t = Tracer(enabled=True)
    t.wall_start = wall_start
    with t.span("dist/map_chunk", index=0):
        pass
    with t.span("dist/lockstep_flag"):
        pass
    events = t.chrome_trace()
    # give the map span a known duration (fake work)
    for e in events:
        if e.get("name") == "dist/map_chunk":
            e["dur"] = work_ms * 1000.0
    r = MetricsRegistry()
    r.set("records_in", records)
    r.set("device_rows_fed", records // 2)
    r.count("shuffle/all_to_all_bytes", 1024)
    meta = {"version": "x", "config_hash": "h", "workload": "wordcount",
            "process": process, "n_processes": 2,
            "wall_start_unix_s": wall_start}
    return {"schema": "moxt-obs-shard-v1", "meta": meta,
            "events": events, "metrics": dict(r.to_dict(), meta=meta)}


def test_merge_shards_pids_time_alignment_and_skew():
    from map_oxidize_tpu.obs.merge import merge_shards

    s0 = _fake_shard(0, wall_start=100.0, records=600, work_ms=50.0)
    s1 = _fake_shard(1, wall_start=100.5, records=400, work_ms=10.0)
    events, skew = merge_shards([s0, s1])
    xs = [e for e in events if e["ph"] == "X"]
    assert {e["pid"] for e in xs} == {0, 1}
    # proc 1 started 0.5s later: its events shift +5e5 us onto the shared
    # axis
    p1_ts = min(e["ts"] for e in xs if e["pid"] == 1)
    assert p1_ts >= 5e5
    # per-process process_name metadata rows, slot-keyed
    names = {e["pid"]: e["args"]["name"] for e in events
             if e.get("name") == "process_name"}
    assert names == {0: "proc 0", 1: "proc 1"}
    assert skew["records_total"] == 1000
    assert skew["rows_fed_total"] == 500
    assert [r["process"] for r in skew["straggler_ranking"]] == [0, 1]
    assert skew["skew"]["records_in"]["max_over_mean"] == pytest.approx(1.2)


def test_merge_refuses_mixed_identity_shards():
    from map_oxidize_tpu.obs.merge import merge_shards

    s0 = _fake_shard(0, 100.0, 1, 1.0)
    s1 = _fake_shard(1, 100.0, 1, 1.0)
    s1["meta"] = dict(s1["meta"], config_hash="other")
    with pytest.raises(ValueError):
        merge_shards([s0, s1])
    dup = _fake_shard(0, 100.0, 1, 1.0)
    with pytest.raises(ValueError):
        merge_shards([s0, dup])


def test_obs_merge_cli(tmp_path, capsys):
    from map_oxidize_tpu.cli import main
    from map_oxidize_tpu.obs import write_json_atomic

    base = str(tmp_path / "trace.json")
    for p, rec in ((0, 30), (1, 70)):
        write_json_atomic(f"{base}.proc{p}",
                          _fake_shard(p, 100.0 + p, rec, 1.0), indent=None)
    rc = main(["obs", "merge", base])
    out = capsys.readouterr().out
    assert rc == 0
    assert "merged 2 shards" in out
    merged = json.loads((tmp_path / "trace.json").read_text())
    assert {e["pid"] for e in merged if e["ph"] == "X"} == {0, 1}
    skew = json.loads((tmp_path / "trace.json.skew.json").read_text())
    assert skew["records_total"] == 100
    # missing shards -> clean error exit
    assert main(["obs", "merge", str(tmp_path / "nope.json")]) == 2


# --- failure flight recorder (ISSUE-3 satellite regression test) -----------


class _BoomMapper:
    """Raises after one good chunk — mid-map+reduce, spans open."""

    value_shape = ()
    value_dtype = np.int32
    keys_have_dictionary = True
    wide_keys = False
    conserves_counts = True

    def __init__(self):
        self.calls = 0

    def map_chunk(self, chunk):
        from map_oxidize_tpu.workloads.wordcount import WordCountMapper

        self.calls += 1
        if self.calls > 1:
            raise RuntimeError("boom mid-phase")
        return WordCountMapper("ascii", use_native=False).map_chunk(chunk)


def test_job_raise_mid_phase_still_flushes_partial_obs(tmp_path):
    """The ISSUE-3 regression: Obs.finish used to be skipped entirely
    when the job raised, losing trace and metrics.  Now the flight
    recorder closes open spans and flushes partial artifacts to the
    configured paths, plus a crash bundle when --crash-dir is set."""
    from map_oxidize_tpu.api import SumReducer
    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.runtime.driver import run_wordcount_job

    corpus = tmp_path / "c.txt"
    corpus.write_bytes(b"aa bb cc\n" * 400)
    t = tmp_path / "t.json"
    m = tmp_path / "m.json"
    crash = tmp_path / "crash"
    cfg = JobConfig(input_path=str(corpus), output_path="", num_shards=1,
                    metrics=False, chunk_bytes=1024, num_map_workers=1,
                    max_retries=0, mapper="python", use_native=False,
                    trace_out=str(t), metrics_out=str(m),
                    crash_dir=str(crash))
    with pytest.raises(RuntimeError, match="boom mid-phase"):
        run_wordcount_job(cfg, _BoomMapper(), SumReducer())

    # partial artifacts flushed to the configured paths; the interrupted
    # phase span is closed (its own __exit__ ran during unwinding) and
    # carries the error — genuinely leaked spans get `unfinished=True`
    # via close_open_spans (covered below, across threads)
    td = json.loads(t.read_text())
    phases = [e for e in td if e["ph"] == "X"
              and e["name"] == "phase/map+reduce"]
    assert len(phases) == 1
    assert "boom mid-phase" in phases[0]["args"]["error"]
    md = json.loads(m.read_text())
    assert md["gauges"]["aborted"] is True
    assert md["meta"]["workload"] == "wordcount"

    # crash bundle: config + metrics + well-formed trace + traceback
    bundles = list(crash.iterdir())
    assert len(bundles) == 1
    err = json.loads((bundles[0] / "error.json").read_text())
    assert "boom mid-phase" in err["error"]
    assert err["config"]["input_path"] == str(corpus)
    assert "Traceback" in err["traceback"]
    bm = json.loads((bundles[0] / "metrics.json").read_text())
    assert "map+reduce" in bm["phases_s"]
    bt = json.loads((bundles[0] / "trace.json").read_text())
    for e in bt:  # well-formed trace-event JSON
        assert e["ph"] in ("X", "i", "M")
        if e["ph"] == "X":
            assert e["dur"] >= 0


def test_conservation_failure_leaves_flight_bundle(tmp_path, monkeypatch):
    """The acceptance-named abort path: an injected conservation-check
    failure (driver invariant, not a mapper error) leaves a bundle."""
    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.runtime import driver, run_job

    corpus = tmp_path / "c.txt"
    corpus.write_bytes(b"aa bb cc dd\n" * 100)
    orig = driver.LazyCounts.total
    monkeypatch.setattr(driver.LazyCounts, "total",
                        lambda self: orig(self) + 7)
    crash = tmp_path / "crash"
    cfg = JobConfig(input_path=str(corpus), output_path="", num_shards=1,
                    metrics=False, crash_dir=str(crash))
    with pytest.raises(RuntimeError, match="conservation violated"):
        run_job(cfg, "wordcount")
    (bundle,) = list(crash.iterdir())
    err = json.loads((bundle / "error.json").read_text())
    assert "conservation violated" in err["error"]
    bm = json.loads((bundle / "metrics.json").read_text())
    # evidence of the run so far: phase clocks + engine counters survive
    assert bm["phases_s"]["map+reduce"] > 0
    # no trace.json: the run did not ask for tracing
    assert not (bundle / "trace.json").exists()


def test_record_failure_never_masks_original_error(tmp_path):
    """A broken crash_dir (a FILE in the way) must not raise out of the
    recorder — the job's own exception is the one the caller sees."""
    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.obs import flight

    blocker = tmp_path / "crash"
    blocker.write_text("not a directory")
    cfg = JobConfig(input_path="missing", crash_dir=str(blocker))
    obs = Obs.from_config(cfg)
    assert flight.record_failure(obs, cfg, RuntimeError("orig")) is None


def test_close_open_spans_across_threads():
    t = Tracer(enabled=True)
    started = threading.Event()
    release = threading.Event()
    worker_span = []

    def work():
        s = t.span("worker/outer")
        s.__enter__()
        worker_span.append(s)
        started.set()
        release.wait(5)
        s.__exit__(None, None, None)  # unwinds AFTER the force-close

    th = threading.Thread(target=work)
    th.start()
    started.wait(5)
    t.span("driver/phase").__enter__()
    closed = t.close_open_spans(error="sim")
    release.set()
    th.join()
    assert closed == 2
    xs = [e for e in t.chrome_trace() if e["ph"] == "X"]
    # the worker's late __exit__ must NOT record a duplicate
    assert len(xs) == 2
    by = {e["name"]: e for e in xs}
    assert by["worker/outer"]["args"]["unfinished"] is True
    assert by["driver/phase"]["args"]["error"] == "sim"
    # each leaked span is attributed to its OWNING thread's track
    assert by["worker/outer"]["tid"] != by["driver/phase"]["tid"]
