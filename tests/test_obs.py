"""Observability subsystem (moxt.obs): spans, metrics, heartbeat, CLI
round-trip, and the demotion accounting it makes observable.

Covers the ISSUE-1 acceptance surface: span nesting/exception safety,
Chrome trace-event schema validity, histogram quantiles, the
``--metrics-out`` / ``--trace-out`` CLI round trip on a tiny corpus, and
heartbeat emission under a fake clock — all on the CPU test mesh.
"""

import json
import threading

import numpy as np
import pytest

from map_oxidize_tpu.obs import Obs
from map_oxidize_tpu.obs.heartbeat import Heartbeat
from map_oxidize_tpu.obs.metrics import Histogram, MetricsRegistry
from map_oxidize_tpu.obs.trace import NULL_SPAN, Tracer


# --- tracer ---------------------------------------------------------------


def test_span_nesting_records_depth_and_containment():
    t = Tracer(enabled=True)
    with t.span("outer", rows=2):
        with t.span("inner"):
            pass
    events = {e["name"]: e for e in t.chrome_trace() if e["ph"] == "X"}
    outer, inner = events["outer"], events["inner"]
    # child starts after parent and ends before it (time containment is
    # what gives Perfetto the nesting)
    assert inner["ts"] >= outer["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-6
    assert outer["args"]["rows"] == 2


def test_span_exception_safety_records_end_and_error():
    t = Tracer(enabled=True)
    with pytest.raises(ValueError):
        with t.span("boom"):
            raise ValueError("broken")
    (ev,) = [e for e in t.chrome_trace() if e["ph"] == "X"]
    assert ev["name"] == "boom"
    assert ev["dur"] >= 0
    assert "ValueError" in ev["args"]["error"]


def test_leaked_child_span_does_not_corrupt_parent_stack():
    t = Tracer(enabled=True)
    outer = t.span("outer")
    inner = t.span("inner")
    outer.__enter__()
    inner.__enter__()
    # outer exits while inner never did (a lower-level crash path):
    # the stack must pop through cleanly and later spans get depth 0
    outer.__exit__(None, None, None)
    with t.span("later"):
        pass
    by_name = {e["name"]: e for e in t._events}
    assert by_name["later"]["depth"] == 0


def test_disabled_tracer_is_noop_and_shared():
    t = Tracer(enabled=False)
    s = t.span("x", rows=1)
    assert s is NULL_SPAN
    with s:
        s.set(more=2)
    t.instant("marker")
    assert t.chrome_trace()[0]["name"] == "process_name"
    assert [e for e in t.chrome_trace() if e["ph"] in ("X", "i")] == []


def test_chrome_trace_schema_and_json_round_trip():
    t = Tracer(enabled=True)
    with t.span("a", bytes=np.int64(7), dev=np.int32(0)):
        t.instant("mark", gen=1)
    blob = json.dumps(t.chrome_trace())  # numpy attrs must serialize
    events = json.loads(blob)
    assert isinstance(events, list) and events
    for e in events:
        assert e["ph"] in ("X", "i", "M")
        assert isinstance(e["name"], str)
        if e["ph"] != "M":
            assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] == "X":
            assert e["dur"] >= 0
    assert any(e["ph"] == "X" and e["args"]["bytes"] == 7 for e in events)


def test_tracer_thread_safety_spans_from_workers():
    t = Tracer(enabled=True)

    def work(i):
        with t.span(f"w{i}"):
            with t.span(f"w{i}/child"):
                pass

    threads = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    xs = [e for e in t.chrome_trace() if e["ph"] == "X"]
    assert len(xs) == 16
    # each worker thread got its own tid; children share their parent's
    tids = {e["name"]: e["tid"] for e in xs}
    for i in range(8):
        assert tids[f"w{i}"] == tids[f"w{i}/child"]


def test_jsonl_export(tmp_path):
    t = Tracer(enabled=True)
    with t.span("outer"):
        with t.span("inner"):
            pass
    p = tmp_path / "events.jsonl"
    t.write_jsonl(str(p))
    rows = [json.loads(line) for line in p.read_text().splitlines()]
    depths = {r["name"]: r["depth"] for r in rows}
    assert depths == {"outer": 0, "inner": 1}


# --- histograms / registry ------------------------------------------------


def test_histogram_quantiles_exact_path():
    h = Histogram()
    for v in range(1, 101):
        h.observe(v)
    s = h.summary()
    assert s["count"] == 100
    assert s["max"] == 100
    assert abs(s["mean"] - 50.5) < 1e-9
    assert 45 <= s["p50"] <= 56
    assert 90 <= s["p95"] <= 100


def test_histogram_decimation_bounds_memory_keeps_quantiles():
    h = Histogram(max_samples=256)
    n = 100_000
    for v in range(n):
        h.observe(v)
    assert len(h._samples) < 256
    assert h.count == n
    assert h.max == n - 1 and h.min == 0
    # stride-sampled quantiles stay in the right decile
    assert 0.35 * n <= h.quantile(0.5) <= 0.65 * n
    assert h.quantile(0.95) >= 0.85 * n


def test_registry_summary_is_seed_compatible():
    r = MetricsRegistry()
    with r.phase("map+reduce"):
        pass
    r.count("chunks", 3)
    r.set("records_in", 1000)
    r.observe("feed_block_ms", 2.0)
    r.observe("feed_block_ms", 4.0)
    s = r.summary()
    assert "time/map+reduce_s" in s
    assert s["chunks"] == 3
    assert s["records_in"] == 1000
    assert "records_per_sec" in s  # derived, as the seed Metrics did
    assert s["feed_block_ms/count"] == 2
    assert s["feed_block_ms/max"] == 4.0
    d = r.to_dict()
    assert set(d) == {"phases_s", "counters", "gauges", "histograms"}
    json.dumps(d)  # the --metrics-out document must be valid JSON


def test_registry_gauge_max_watermark():
    r = MetricsRegistry()
    r.gauge_max("peak", 10)
    r.gauge_max("peak", 5)
    r.gauge_max("peak", 20)
    assert r.gauges["peak"] == 20


def test_profiling_shim_still_importable():
    # the seed import path must keep working (drivers outside the repo)
    from map_oxidize_tpu.utils.profiling import Metrics

    m = Metrics()
    with m.phase("x"):
        pass
    assert "time/x_s" in m.summary()


# --- heartbeat (fake clock) ----------------------------------------------


def test_heartbeat_emits_on_interval_with_fake_clock():
    now = [0.0]
    lines = []
    hb = Heartbeat(total_bytes=1000, interval_s=10.0,
                   clock=lambda: now[0], emit=lines.append)
    hb.set_phase("map+reduce")
    hb.update(rows=100, bytes_done=100)   # t=0: within interval, no beat
    assert lines == []
    now[0] = 5.0
    hb.update(rows=100, bytes_done=200)   # t=5: still within
    assert lines == []
    now[0] = 10.0
    hb.update(rows=100, bytes_done=300)   # t=10: beat
    assert len(lines) == 1
    assert "phase=map+reduce" in lines[0]
    assert "rows=300" in lines[0]
    assert "30.0%" in lines[0]
    assert "eta=" in lines[0]
    now[0] = 15.0
    hb.update(rows=100, bytes_done=400)   # within the next interval
    assert len(lines) == 1
    now[0] = 20.0
    hb.update(rows=100, bytes_done=1000)  # next beat, now 100%: no eta
    assert len(lines) == 2
    assert "100.0%" in lines[1]
    assert "eta=" not in lines[1]
    assert hb.beats == 2


def test_heartbeat_fraction_override_and_final_beat():
    now = [0.0]
    lines = []
    hb = Heartbeat(total_bytes=None, interval_s=60.0,
                   clock=lambda: now[0], emit=lines.append)
    hb.set_phase("iterate")
    now[0] = 1.0
    hb.update(rows=50, fraction=0.5)
    assert lines == []          # interval not elapsed
    hb.final_beat()             # jobs shorter than one interval still report
    assert len(lines) == 1
    assert "50.0%" in lines[0]


def test_heartbeat_rejects_bad_interval():
    with pytest.raises(ValueError):
        Heartbeat(interval_s=0)


# --- CLI round trip (tiny corpus, CPU) ------------------------------------


@pytest.fixture
def tiny_corpus(tmp_path):
    p = tmp_path / "tiny.txt"
    p.write_bytes(b"the quick brown fox jumps over the lazy dog\n" * 50)
    return p


def test_cli_metrics_and_trace_round_trip(tmp_path, tiny_corpus, capsys):
    from map_oxidize_tpu.cli import main

    m = tmp_path / "m.json"
    t = tmp_path / "t.json"
    rc = main(["wordcount", str(tiny_corpus),
               "--output", str(tmp_path / "out.txt"),
               "--metrics-out", str(m), "--trace-out", str(t),
               "--progress", "--progress-interval", "0.001",
               "--num-shards", "1", "--quiet"])
    assert rc == 0
    assert "Top 10 words:" in capsys.readouterr().out

    md = json.loads(m.read_text())
    # phase timings, counters, and at least one histogram (acceptance)
    assert "map+reduce" in md["phases_s"]
    assert md["phases_s"]["map+reduce"] > 0
    assert md["counters"]  # engine flush/put counters at minimum
    assert "feed_block_ms" in md["histograms"]
    assert md["histograms"]["feed_block_ms"]["count"] >= 1
    assert md["gauges"]["records_in"] == 450
    assert md["gauges"]["mem/host_rss_peak_bytes"] > 0

    td = json.loads(t.read_text())
    names = [e["name"] for e in td if e["ph"] == "X"]
    # spans cover map, reduce (the fused streaming phase), and finalize
    assert "phase/map+reduce" in names
    assert "phase/finalize" in names
    assert "engine/feed_block" in names
    # nesting: the feed span sits inside the map+reduce phase span
    by = {e["name"]: e for e in td if e["ph"] == "X"}
    ph, feed = by["phase/map+reduce"], by["engine/feed_block"]
    assert ph["ts"] <= feed["ts"]
    assert feed["ts"] + feed["dur"] <= ph["ts"] + ph["dur"] + 1e-6


def test_cli_invertedindex_trace_covers_collect(tmp_path, tiny_corpus):
    from map_oxidize_tpu.cli import main

    t = tmp_path / "t.json"
    rc = main(["invertedindex", str(tiny_corpus),
               "--output", str(tmp_path / "out.txt"),
               "--trace-out", str(t), "--num-shards", "1", "--quiet"])
    assert rc == 0
    names = [e["name"] for e in json.loads(t.read_text())
             if e["ph"] == "X"]
    assert "phase/map+collect" in names
    assert "phase/sort+postings" in names


def test_result_trace_without_file(tiny_corpus):
    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.runtime import run_job

    cfg = JobConfig(input_path=str(tiny_corpus), output_path="",
                    num_shards=1, metrics=False, trace_out="-")
    r = run_job(cfg, "wordcount")
    assert isinstance(r.trace, list)
    assert any(e.get("name") == "phase/finalize" for e in r.trace)
    # tracing off -> None, and metrics stay populated
    r2 = run_job(JobConfig(input_path=str(tiny_corpus), output_path="",
                           num_shards=1, metrics=False), "wordcount")
    assert r2.trace is None
    assert r2.metrics["records_in"] == 450


# --- sharded demotion accounting (ADVICE r5 regression) -------------------


def test_sharded_collect_demotion_rows_fed_parity(rng):
    """The demotion-triggering feed must not double-count its own block:
    after the handoff the host engine's rows_fed equals the sharded
    engine's, and the spill counters the new registry records stay
    consistent with the rows actually fed."""
    from map_oxidize_tpu.api import MapOutput
    from map_oxidize_tpu.config import JobConfig
    from map_oxidize_tpu.parallel.collect import ShardedCollectEngine

    cfg = JobConfig(input_path="unused", backend="cpu", num_shards=8,
                    batch_size=512)
    eng = ShardedCollectEngine(cfg, max_rows=600)
    obs = Obs.from_config(cfg)
    eng.obs = obs

    def block(n):
        hi = rng.integers(0, 1 << 31, n).astype(np.uint32)
        lo = rng.integers(0, 1 << 31, n).astype(np.uint32)
        vals = np.zeros((n, 2), np.uint32)
        vals[:, 1] = np.arange(n, dtype=np.uint32)
        return MapOutput(hi=hi, lo=lo, values=vals, records_in=n)

    eng.feed(block(500))          # under max_rows: stays on device
    assert eng._host is None
    eng.feed(block(200))          # crosses 600: demotes, then feeds
    assert eng._host is not None
    assert eng.rows_fed == 700
    assert eng._host.rows_fed == 700   # parity — was 900 pre-fix
    eng.feed(block(100))          # already-demoted branch keeps parity
    assert eng.rows_fed == 800
    assert eng._host.rows_fed == 800
    assert obs.registry.counters["demote/events"] == 1
    # past max_rows the demoted host engine spills to disk buckets; every
    # fed pair must come back through the spilled CSR — an off-by-a-block
    # rows_fed skew would have started the spill one block early and the
    # spill/rows counter makes the volume observable
    terms, offsets, docs, holder = eng.finalize_spilled_csr()
    assert int(offsets[-1]) == 800
    assert obs.registry.counters["spill/rows"] == 800
