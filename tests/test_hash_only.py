"""Hash-only bigram map + rescan resolver: winners-only resolve, full
materialization, output file parity with the classic string-draining path."""

import numpy as np
import pytest

from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.native.bindings import load_or_none
from map_oxidize_tpu.runtime.driver import run_wordcount_job
from map_oxidize_tpu.workloads.bigram import RescanDictionary, make_bigram

native = load_or_none()
pytestmark = pytest.mark.skipif(native is None,
                                reason="native library unavailable")

CORPUS = b"the cat sat\nthe cat ran far\nsat the cat sat\n" * 100


def _run(tmp_path, out_name="", **kw):
    p = tmp_path / "c.txt"
    p.write_bytes(CORPUS)
    cfg = JobConfig(input_path=str(p), backend="cpu", num_shards=1,
                    metrics=False,
                    output_path=str(tmp_path / out_name) if out_name else "",
                    **kw)
    mapper, reducer = make_bigram()
    res = run_wordcount_job(cfg, mapper, reducer, workload="bigram")
    return res, mapper


def test_hash_only_activates_and_top_k_resolves(tmp_path):
    res, mapper = _run(tmp_path)
    assert mapper.hash_only, "collect engine + native should enable hash-only"
    # winners carry real strings via the winners-only rescan ("the cat"
    # appears 3x per repetition — pairs span lines inside a chunk)
    top = dict(res.top)
    assert top[b"the cat"] == 300
    assert top[b"cat sat"] == 200


def test_hash_only_matches_string_path_output(tmp_path):
    res_h, mapper = _run(tmp_path, out_name="hash.txt")
    assert mapper.hash_only
    res_s, mapper_s = _run(tmp_path, out_name="str.txt", reduce_mode="fold")
    assert not mapper_s.hash_only
    assert (tmp_path / "hash.txt").read_bytes() == \
        (tmp_path / "str.txt").read_bytes()
    assert res_h.top == res_s.top


def test_rescan_dictionary_lookup_miss_raises(tmp_path):
    p = tmp_path / "c.txt"
    p.write_bytes(CORPUS)
    from map_oxidize_tpu.native.bindings import stream_or_none

    d = RescanDictionary(stream_or_none(ngram=2), str(p), 1 << 20)
    with pytest.raises(KeyError):
        d.lookup(12345)  # hash of nothing in the corpus


def test_early_stop_resolve_matches_full_scan(tmp_path):
    """The early-exit rescan (stop once every queried hash is seen) must
    return exactly the strings the full-corpus scan returns — for frequent
    winners AND for a key whose only occurrence is the corpus's last pair,
    where the "early" stop is the natural end of file."""
    p = tmp_path / "c.txt"
    p.write_bytes(CORPUS + b"unique1 unique2\n")
    from map_oxidize_tpu.native.bindings import stream_or_none
    from map_oxidize_tpu.ops.hashing import moxt64_bytes

    queries = np.array([moxt64_bytes(b"the cat"),
                        moxt64_bytes(b"unique1 unique2")], np.uint64)
    stream = stream_or_none(ngram=2)
    # small chunks so early exit has somewhere to stop between chunks
    full = stream.resolve_file(str(p), 1 << 10, queries, early_stop=False)
    early = stream.resolve_file(str(p), 1 << 10, queries, early_stop=True)
    as_dict = lambda r: {int(h): bytes(r[2][sum(r[1][:i]):sum(r[1][:i + 1])])
                         for i, h in enumerate(r[0].tolist())}
    assert as_dict(full) == as_dict(early)
    assert set(as_dict(full)) == {int(q) for q in queries}


def test_early_stop_quits_before_eof(tmp_path):
    """Observable proof the early stop really skips the tail: under the
    unicode tokenizer a full scan of a corpus with an invalid-UTF-8 tail
    raises, but with every queried key found in the first chunks the
    early-stop scan never reaches the bad bytes."""
    p = tmp_path / "c.txt"
    p.write_bytes(CORPUS + b"\xff\xfe broken tail \xff\n")
    from map_oxidize_tpu.native.bindings import stream_or_none
    from map_oxidize_tpu.ops.hashing import moxt64_bytes

    stream = stream_or_none(ngram=2, tokenizer="unicode")
    q = np.array([moxt64_bytes(b"the cat")], np.uint64)
    h, lens, blob = stream.resolve_file(str(p), 1 << 10, q, early_stop=True)
    assert h.tolist() == [int(q[0])] and blob == b"the cat"
    with pytest.raises(Exception):
        stream.resolve_file(str(p), 1 << 10, q, early_stop=False)


def test_round_robin_mode_keeps_string_path(tmp_path):
    # round-robin chunking has no byte cuts to replay: hash-only must stay off
    res, mapper = _run(tmp_path, num_chunks=4)
    assert not mapper.hash_only
    assert dict(res.top)[b"the cat"] == 300
