"""Streaming pipeline (runtime/pipeline.py): bounded prefetch must change
WHEN host work runs, never WHAT comes out.

The contract under test: with ``--pipeline-depth > 1`` every workload's
output is byte-identical to the serial (depth 1) schedule — including a
checkpoint kill-resume — because the prefetch queue preserves chunk
order and with it every reduction's accumulation order; and the overlap
is *measured* into the obs registry (``pipeline/overlap_ratio``), not
asserted."""

import os
import threading
import time

import numpy as np
import pytest

from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.runtime import run_job
from map_oxidize_tpu.runtime.pipeline import ChunkPrefetcher, pipelined


def _make_corpus(path, n_lines=3000, seed=0):
    rng = np.random.default_rng(seed)
    words = [b"alpha", b"beta", b"Gamma,", b"delta.", b"epsilon", b"zeta"]
    with open(path, "wb") as f:
        for _ in range(n_lines):
            k = int(rng.integers(3, 9))
            f.write(b" ".join(words[int(i)] for i in rng.integers(0, 6, k)))
            f.write(b"\n")


# --- prefetcher unit contract ------------------------------------------


def test_prefetcher_preserves_order_and_counts():
    pf = ChunkPrefetcher(iter(range(100)), depth=3)
    assert list(pf) == list(range(100))
    assert pf.items == 100
    assert pf.produce_s >= 0.0 and pf.wait_s >= 0.0


def test_prefetcher_bounds_inflight():
    """The producer may run at most depth items ahead of the consumer."""
    produced = []

    def gen():
        for i in range(50):
            produced.append(i)
            yield i

    pf = ChunkPrefetcher(gen(), depth=2)
    it = iter(pf)
    consumed = []
    for i in range(50):
        consumed.append(next(it))
        # depth-2 queue + 1 item the producer may hold mid-put: the
        # producer can never be more than depth + 1 ahead
        assert len(produced) - len(consumed) <= 3, \
            (len(produced), len(consumed))
    assert consumed == list(range(50))


@pytest.mark.parametrize("exc", [ValueError, KeyboardInterrupt])
def test_prefetcher_propagates_errors_after_prefix(exc):
    """An error surfaces in the consumer AFTER the items produced before
    it — the serial semantics the checkpoint kill-resume contract needs
    (KeyboardInterrupt included: a mid-map kill is a BaseException)."""

    def gen():
        yield 1
        yield 2
        raise exc("boom")

    pf = ChunkPrefetcher(gen(), depth=4)
    got = []
    with pytest.raises(exc):
        for x in pf:
            got.append(x)
    assert got == [1, 2]


def test_prefetcher_abandon_stops_producer():
    """A consumer that walks away (driver abort) must release a producer
    blocked on the full queue instead of pinning chunks forever."""
    started = threading.Event()

    def gen():
        for i in range(1000):
            started.set()
            yield i

    pf = ChunkPrefetcher(gen(), depth=1)
    it = iter(pf)
    next(it)
    started.wait(timeout=5)
    it.close()  # generator close = abandon
    deadline = time.time() + 5
    while pf._thread.is_alive() and time.time() < deadline:
        time.sleep(0.01)
    assert not pf._thread.is_alive(), "producer thread leaked after abandon"


def test_pipelined_depth1_is_identity():
    it = iter([1, 2, 3])
    assert pipelined(it, 1) is it


# --- end-to-end parity: depth > 1 output == depth 1 output -------------


def _cfg(corpus, out, depth, **kw):
    base = dict(
        input_path=str(corpus), output_path=str(out), backend="cpu",
        num_shards=1, metrics=True, chunk_bytes=16 * 1024,
        num_map_workers=1, pipeline_depth=depth,
    )
    base.update(kw)
    return JobConfig(**base)


@pytest.mark.parametrize("workload,mapper", [
    ("wordcount", "python"),
    ("wordcount", "native"),
    ("bigram", "python"),
    ("invertedindex", "native"),
    ("distinct", "native"),
])
def test_depth_parity_byte_identical(tmp_path, workload, mapper):
    corpus = tmp_path / "corpus.txt"
    _make_corpus(corpus)
    outs = {}
    results = {}
    for depth in (1, 4):
        out = tmp_path / f"out_{depth}.txt"
        cfg = _cfg(corpus, out, depth, mapper=mapper,
                   use_native=(mapper == "native"))
        results[depth] = run_job(cfg, workload)
        outs[depth] = out.read_bytes()
    assert outs[1] == outs[4], \
        f"{workload}/{mapper}: pipelined output differs from serial"
    # the conservation checks inside run_job passed for both depths (they
    # raise otherwise); the overlap evidence must exist only for depth>1
    assert "pipeline/overlap_ratio" in results[4].metrics
    assert 0.0 <= results[4].metrics["pipeline/overlap_ratio"] <= 1.0
    assert results[4].metrics["pipeline/feed_wait_ms"] >= 0.0
    assert "pipeline/overlap_ratio" not in results[1].metrics


def test_kmeans_stream_depth_parity(tmp_path, rng):
    """The host-assign streamed k-means path: pipelined assign must give
    bit-identical centroids (same chunk order -> same float order)."""
    pts = rng.normal(0, 5, (4000, 6)).astype(np.float32)
    inp = tmp_path / "p.npy"
    np.save(inp, pts)

    def run(depth):
        cfg = JobConfig(input_path=str(inp), output_path="", backend="cpu",
                        num_shards=1, kmeans_k=4, kmeans_iters=3,
                        mapper="native", chunk_bytes=8 * 1024,
                        metrics=True, pipeline_depth=depth)
        return run_job(cfg, "kmeans")

    r1, r4 = run(1), run(4)
    assert r1.centroids.tobytes() == r4.centroids.tobytes()
    assert "pipeline/overlap_ratio" in r4.metrics


def test_kill_resume_byte_identical_with_pipeline(tmp_path):
    """The checkpoint contract survives pipelining: a run killed mid-map
    at depth 4 spills exactly the chunks mapped before the kill (order
    preserved), and the resume — also pipelined — produces output
    byte-identical to an uncheckpointed serial run."""
    from map_oxidize_tpu.api import SumReducer
    from map_oxidize_tpu.runtime.driver import run_wordcount_job
    from map_oxidize_tpu.workloads.wordcount import WordCountMapper

    class _DyingMapper(WordCountMapper):
        def __init__(self, die_after, **kw):
            super().__init__(**kw)
            self.mapped = 0
            self.die_after = die_after

        def map_chunk(self, chunk):
            if self.mapped >= self.die_after:
                raise KeyboardInterrupt("simulated kill")
            self.mapped += 1
            return super().map_chunk(chunk)

    corpus = tmp_path / "corpus.txt"
    _make_corpus(corpus)
    ckdir = str(tmp_path / "ck")

    want_out = tmp_path / "want.txt"
    run_job(_cfg(corpus, want_out, 1, mapper="python", use_native=False,
                 max_retries=0), "wordcount")

    got_out = tmp_path / "got.txt"
    dying = _DyingMapper(die_after=3, use_native=False)
    with pytest.raises(KeyboardInterrupt):
        run_wordcount_job(
            _cfg(corpus, got_out, 4, mapper="python", use_native=False,
                 max_retries=0, checkpoint_dir=ckdir),
            dying, SumReducer())
    saved = [n for n in os.listdir(ckdir) if n.endswith(".npz")]
    assert len(saved) == 3, saved  # exactly the pre-kill prefix, in order

    run_wordcount_job(
        _cfg(corpus, got_out, 4, mapper="python", use_native=False,
             max_retries=0, checkpoint_dir=ckdir),
        WordCountMapper(use_native=False), SumReducer())
    assert got_out.read_bytes() == want_out.read_bytes()
    assert not os.path.isdir(ckdir)  # cleaned up on success


def test_cli_pipeline_depth_flag():
    from map_oxidize_tpu.cli import build_parser, config_from_args

    args = build_parser().parse_args(
        ["wordcount", "x.txt", "--pipeline-depth", "5",
         "--kmeans-fit-bytes", "123"])
    cfg = config_from_args(args)
    assert cfg.pipeline_depth == 5
    assert cfg.kmeans_device_fit_bytes == 123
    with pytest.raises(ValueError, match="pipeline_depth"):
        JobConfig(input_path="x", pipeline_depth=0).validate()
    with pytest.raises(ValueError, match="kmeans_device_fit_bytes"):
        JobConfig(input_path="x", kmeans_device_fit_bytes=-1).validate()
