#!/usr/bin/env bash
# One-command verification: the tier-1 test suite plus an observability
# smoke that exercises the whole artifact surface — a tiny wordcount with
# --trace-out/--metrics-out/--ledger-dir (twice, so the ledger has a
# previous entry), artifact well-formedness checks, an informational
# previous-vs-last `obs diff`, and a gated self-diff that must report
# zero deltas.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== tier-1 pytest =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log

echo "== obs smoke =="
smoke=$(mktemp -d)
trap 'rm -rf "$smoke"' EXIT
python - "$smoke" <<'EOF'
import sys
with open(f"{sys.argv[1]}/corpus.txt", "wb") as f:
    f.write(b"the quick brown fox jumps over the lazy dog\n" * 200)
EOF
# --num-chunks 16 --batch-size 64: several same-shape merges, so the run
# has steady-state (non-compiling) dispatches and the dispatch-gap
# histogram populates alongside the exact compile counts
for _ in 1 2; do
    JAX_PLATFORMS=cpu python -m map_oxidize_tpu wordcount \
        "$smoke/corpus.txt" --output "$smoke/out.txt" --num-shards 1 \
        --num-chunks 16 --batch-size 64 \
        --quiet --trace-out "$smoke/trace.json" \
        --metrics-out "$smoke/metrics.json" --ledger-dir "$smoke/ledger" \
        > /dev/null
done
python - "$smoke" <<'EOF'
import json, sys
d = sys.argv[1]
trace = json.load(open(f"{d}/trace.json"))
assert isinstance(trace, list) and trace, "trace.json malformed"
assert all(e["ph"] in ("X", "i", "M") for e in trace)
m = json.load(open(f"{d}/metrics.json"))
assert m["meta"]["config_hash"] and m["meta"]["version"], "stamp missing"
assert m["phases_s"]["map+reduce"] > 0
led = [json.loads(l) for l in open(f"{d}/ledger/ledger.jsonl")]
assert len(led) == 2, f"expected 2 ledger entries, got {len(led)}"
# xprof smoke: the observatory saw the fold engine's programs with EXACT
# compile counts (one shape set each on a one-flush corpus), the cost
# join has FLOPs/bytes, and both ledger entries carry the gate fields
x = m.get("xprof") or {}
progs = x.get("programs") or {}
for prog in ("engine/merge_packed", "engine/pack_finalize"):
    assert progs.get(prog, {}).get("compiles") == 1, (
        f"xprof: expected exactly 1 compile of {prog}, got "
        f"{progs.get(prog)}")
    assert progs[prog].get("bytes_per_dispatch"), f"no cost join for {prog}"
for e in led:
    assert e["metrics"].get("compile/engine/merge_packed/compiles") == 1, \
        "ledger entry lacks exact compile counts"
assert "device/dispatch_gap_ms" in m.get("histograms", {}), \
    "dispatch-gap histogram missing"
print("obs artifacts OK (xprof: "
      f"{x.get('total_compiles')} compiles / "
      f"{x.get('total_dispatches')} dispatches)")
EOF
# the observatory report must render from the metrics document
python -m map_oxidize_tpu obs xprof "$smoke/metrics.json" | head -5
# previous vs last (informational: same config, tiny run — deltas are
# jitter), then a gated self-diff that MUST come back all-zero
python -m map_oxidize_tpu obs diff --ledger-dir "$smoke/ledger"
python -m map_oxidize_tpu obs diff --ledger-dir "$smoke/ledger" \
    --gate -- -1 -1
# cross-run forensics render from the same two entries (the movers
# report is what a gate failure gets attributed with)
python -m map_oxidize_tpu obs trend --ledger-dir "$smoke/ledger" | head -8

echo "== spilled shuffle smoke =="
# a 2-process inverted index forced far past --collect-max-rows: the old
# "per-process spill is not yet implemented" abort is gone — the job
# must COMPLETE (auto routes the transport to per-process disk buckets
# at this corpus/cap ratio), its concatenated partition files must match
# the single-process artifact, and spill/rows must be nonzero on every
# process; the default resident path on the same corpus must spill
# NOTHING (the zero-spill assertion)
python - "$smoke" <<'EOF'
import sys
import numpy as np
rng = np.random.default_rng(7)
words = [b"alpha", b"beta", b"gamma", b"delta", b"eps", b"zeta",
         b"eta", b"theta", b"iota", b"kappa"]
with open(f"{sys.argv[1]}/corpus_spill.txt", "wb") as f:
    for _ in range(40000):
        f.write(b" ".join(words[int(i)]
                          for i in rng.integers(0, 10, 8)) + b"\n")
EOF
JAX_PLATFORMS=cpu python -m map_oxidize_tpu invertedindex \
    "$smoke/corpus_spill.txt" --output "$smoke/spill_single.txt" \
    --num-shards 1 --quiet \
    --metrics-out "$smoke/spill_default_metrics.json" > /dev/null
spill_port=$(python - <<'EOF'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0))
print(s.getsockname()[1]); s.close()
EOF
)
spill_pids=()
for p in 0 1; do
    # timeout guard: a lockstep wedge must kill BOTH spinning collective
    # loops, not hang the whole check (same guard bench.py's twin uses)
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        timeout -k 10 600 \
        python -m map_oxidize_tpu invertedindex "$smoke/corpus_spill.txt" \
        --output "$smoke/spill_2proc.txt" --batch-size 65536 \
        --collect-max-rows 4096 --quiet \
        --dist-coordinator "127.0.0.1:$spill_port" --dist-processes 2 \
        --dist-process-id "$p" \
        --metrics-out "$smoke/spill_metrics.json" > /dev/null &
    spill_pids+=($!)
done
spill_rc=0
for pid in "${spill_pids[@]}"; do wait "$pid" || spill_rc=$?; done
if [ "$spill_rc" -ne 0 ]; then
    # both children are reaped (the loop waits on every pid before this
    # check), so a failure cannot orphan the sibling inside a collective
    echo "spilled shuffle smoke: a 2-proc child failed (rc=$spill_rc)"
    exit "$spill_rc"
fi
python - "$smoke" <<'EOF'
import json, sys
d = sys.argv[1]
# parity: concatenated partition files == the single-process artifact
rows = []
for i in range(2):
    rows.extend(open(f"{d}/spill_2proc.txt.part{i}of2",
                     "rb").read().splitlines(keepends=True))
single = b"".join(sorted(open(f"{d}/spill_single.txt",
                              "rb").read().splitlines(keepends=True)))
assert b"".join(sorted(rows)) == single, "spilled 2-proc output != single"
spilled = 0
for i in range(2):
    m = json.load(open(f"{d}/spill_metrics.json.proc{i}"))
    assert m["gauges"]["shuffle/transport"] == "disk", \
        f"auto should route this corpus/cap ratio to disk: {m['gauges']}"
    r = m["counters"].get("spill/rows", 0)
    assert r > 0, f"process {i} never spilled"
    assert m["counters"].get("spill/buckets", 0) >= 1
    spilled += r
# the default resident path on the same corpus must spill NOTHING
dm = json.load(open(f"{d}/spill_default_metrics.json"))
assert dm["gauges"]["shuffle/transport"] == "hybrid"
assert "spill/rows" not in dm["counters"], dm["counters"]
assert "demote/events" not in dm["counters"], dm["counters"]
print(f"spilled shuffle OK: 2-proc completed past the cap "
      f"({spilled} rows through per-process disk buckets), "
      "parity exact, default path zero-spill")
EOF

echo "== push shuffle smoke =="
# ISSUE-19: (a) a 2-process wordcount under --shuffle-transport pipelined
# must match the barrier (hbm) transport's partition files byte for byte,
# with nonzero push rounds and a nonzero pipeline/shuffle_overlap_ratio
# on at least one process (chunks round-robin, so one side can hold fewer
# rounds); the conservation audit is ON (default), so a clean exit IS the
# audit's green verdict.  (b) a 2-process remote-staged job must complete
# with clean-run parity after one process is SIGKILLed mid-shuffle,
# finishing from the staged partitions via the .rec takeover.
python - "$smoke" <<'EOF'
import sys
import numpy as np
rng = np.random.default_rng(19)
words = [f"tok{i:04d}".encode() for i in range(3000)]
with open(f"{sys.argv[1]}/corpus_push.txt", "wb") as f:
    for _ in range(100000):
        f.write(b" ".join(words[int(i)]
                          for i in rng.integers(0, 3000, 8)) + b"\n")
EOF
for transport in hbm pipelined; do
    push_port=$(python - <<'EOF'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0))
print(s.getsockname()[1]); s.close()
EOF
)
    push_pids=()
    for p in 0 1; do
        JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
            timeout -k 10 600 \
            python -m map_oxidize_tpu wordcount "$smoke/corpus_push.txt" \
            --output "$smoke/push_$transport.txt" --batch-size 2048 \
            --chunk-mb 1 --push-combine off --quiet \
            --shuffle-transport "$transport" \
            --dist-coordinator "127.0.0.1:$push_port" --dist-processes 2 \
            --dist-process-id "$p" \
            --metrics-out "$smoke/push_${transport}_metrics.json" \
            > /dev/null &
        push_pids+=($!)
    done
    push_rc=0
    for pid in "${push_pids[@]}"; do wait "$pid" || push_rc=$?; done
    if [ "$push_rc" -ne 0 ]; then
        echo "push shuffle smoke: a 2-proc $transport child failed" \
             "(rc=$push_rc)"
        exit "$push_rc"
    fi
done
python - "$smoke" <<'EOF'
import json, sys
d = sys.argv[1]
for i in range(2):
    a = open(f"{d}/push_hbm.txt.part{i}of2", "rb").read()
    b = open(f"{d}/push_pipelined.txt.part{i}of2", "rb").read()
    assert a == b, f"pipelined partition {i} != barrier transport"
rounds, ratios = 0, []
for i in range(2):
    m = json.load(open(f"{d}/push_pipelined_metrics.json.proc{i}"))
    assert m["gauges"]["shuffle/transport"] == "pipelined", m["gauges"]
    rounds += m["counters"].get("shuffle/push_rounds", 0)
    assert m["counters"].get("pipeline/produce_ms", 0) > 0, \
        f"process {i} never produced through the push pipeline"
    ratios.append(m["gauges"].get("pipeline/shuffle_overlap_ratio", 0.0))
assert rounds > 0, "no push rounds recorded"
assert max(ratios) > 0.0, f"push pipeline never overlapped: {ratios}"
print(f"push shuffle OK: pipelined == barrier byte-for-byte, "
      f"{rounds} push rounds, overlap ratios {ratios}, audit green")
EOF

# (b) remote-staged SIGKILL recovery: process 1 kills itself (real
# SIGKILL) after its second committed chunk; process 0 must claim the
# dead peer's remainder and finish with clean-run parity
cat > "$smoke/remote_child.py" <<'EOF'
import json, os, signal, sys
pid, corpus, outdir, die = (int(sys.argv[1]), sys.argv[2],
                            sys.argv[3], int(sys.argv[4]))
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.parallel.distributed import run_distributed_job
from map_oxidize_tpu.shuffle import remote as rmod
if die and pid == 1:
    orig = rmod.RemoteStage.append_chunk
    n = [0]
    def bomb(self, *a, **kw):
        orig(self, *a, **kw)
        n[0] += 1
        if n[0] >= 2:
            os.kill(os.getpid(), signal.SIGKILL)
    rmod.RemoteStage.append_chunk = bomb
cfg = JobConfig(input_path=corpus,
                output_path=os.path.join(outdir, "out.txt"),
                chunk_bytes=512, shuffle_transport="remote",
                remote_stage_dir=os.path.join(outdir, "stage"),
                remote_stage_timeout_s=10.0,
                dist_num_processes=2, dist_process_id=pid,
                metrics=False)
r = run_distributed_job(cfg, "wordcount")
json.dump({"counts": {str(k): v for k, v in r.counts.items()}},
          open(os.path.join(outdir, f"counts{pid}.json"), "w"),
          sort_keys=True)
EOF
python - "$smoke" <<'EOF'
import sys
lines = [b"pelican heron egret heron stork pelican crane\n",
         b"egret stork stork crane pelican heron ibis\n"]
with open(f"{sys.argv[1]}/corpus_remote.txt", "wb") as f:
    for i in range(400):
        f.write(lines[i % 2])
EOF
for sub in clean killed; do
    mkdir -p "$smoke/remote_$sub"
    die=0; [ "$sub" = killed ] && die=1
    remote_pids=()
    for p in 0 1; do
        JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=1 \
            PYTHONPATH="$PWD" timeout -k 10 420 \
            python "$smoke/remote_child.py" "$p" \
            "$smoke/corpus_remote.txt" "$smoke/remote_$sub" "$die" \
            > /dev/null &
        remote_pids+=($!)
    done
    remote_rcs=()
    for pid in "${remote_pids[@]}"; do
        rc=0; wait "$pid" || rc=$?
        remote_rcs+=("$rc")
    done
    if [ "$sub" = clean ]; then
        [ "${remote_rcs[0]}" -eq 0 ] && [ "${remote_rcs[1]}" -eq 0 ] || {
            echo "remote clean run failed (rc=${remote_rcs[*]})"; exit 1; }
    else
        # child 1 dies by SIGKILL (137 via shell); child 0 must survive
        [ "${remote_rcs[0]}" -eq 0 ] && [ "${remote_rcs[1]}" -eq 137 ] || {
            echo "remote SIGKILL run: want rc 0/137," \
                 "got ${remote_rcs[*]}"; exit 1; }
    fi
done
python - "$smoke" <<'EOF'
import json, os, sys
d = sys.argv[1]
clean = json.load(open(f"{d}/remote_clean/counts0.json"))
survivor = json.load(open(f"{d}/remote_killed/counts0.json"))
assert survivor == clean, "post-SIGKILL counts != clean run"
for i in range(2):
    a = open(f"{d}/remote_clean/out.txt.part{i}of2", "rb").read()
    b = open(f"{d}/remote_killed/out.txt.part{i}of2", "rb").read()
    assert a == b, f"post-SIGKILL partition {i} != clean run"
stage = f"{d}/remote_killed/stage"
assert os.path.exists(f"{stage}/claim.proc1"), "no takeover claim"
rec = json.load(open(f"{stage}/manifest.proc1.rec.json"))
assert rec["final"] and rec["staged_by"] == 0, rec
print("remote SIGKILL OK: survivor claimed proc1, finished from the "
      "staged partitions, byte parity with the clean run")
EOF

echo "== dataplane smoke =="
# ISSUE-16 acceptance: a 2-process Gloo wordcount on a SKEWED corpus
# must report per-partition rows-in/distinct-out, an order-independent
# checksum matching across the exchange, and the imbalance factor; the
# conservation audit must come back green, data/reduction_ratio and
# data/imbalance_factor must ride the ledger entry, and `obs data`
# must render the audit table from the metrics document.  The corpus
# deliberately fits ONE chunk, so process 1 maps NOTHING — the audit's
# payload-shape guard (a zero-work process must ship the same
# allgather payload as its peers) stays regression-tested end to end
python - "$smoke" <<'EOF'
import sys
import numpy as np
rng = np.random.default_rng(23)
with open(f"{sys.argv[1]}/corpus_skew.txt", "wb") as f:
    for _ in range(3000):
        tail = b" ".join(b"w%d" % i for i in rng.integers(0, 50, 4))
        f.write(b"hot hot hot " + tail + b"\n")
EOF
data_port=$(python - <<'EOF'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0))
print(s.getsockname()[1]); s.close()
EOF
)
data_pids=()
for p in 0 1; do
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        timeout -k 10 600 \
        python -m map_oxidize_tpu wordcount "$smoke/corpus_skew.txt" \
        --output "$smoke/skew_out.txt" --batch-size 4096 --quiet \
        --dist-coordinator "127.0.0.1:$data_port" --dist-processes 2 \
        --dist-process-id "$p" \
        --metrics-out "$smoke/data_metrics.json" \
        --ledger-dir "$smoke/data_ledger" > /dev/null &
    data_pids+=($!)
done
data_rc=0
for pid in "${data_pids[@]}"; do wait "$pid" || data_rc=$?; done
if [ "$data_rc" -ne 0 ]; then
    echo "dataplane smoke: a 2-proc child failed (rc=$data_rc)"
    exit "$data_rc"
fi
python - "$smoke" <<'EOF'
import json, sys
d = sys.argv[1]
docs = [json.load(open(f"{d}/data_metrics.json.proc{p}")) for p in (0, 1)]
for m in docs:
    dp = m["data"]
    assert dp["conservation"]["violations"] == [], dp["conservation"]
    st = dp["stages"]
    # the order-independent checksum matches ACROSS the exchange
    assert (st["map_out"]["weighted_checksum"]
            == st["reduce_out"]["weighted_checksum"]), st
    assert sum(st["map_out"]["rows_per_partition"]) == st["map_out"]["rows"]
    assert dp["skew"]["imbalance_factor"] >= 1.0
    # one chunk => the map side is already fully combined (ratio 1.0);
    # the multi-chunk distributed ratio is pinned by tests/test_dataplane
    assert dp["reduction"]["ratio"] >= 1.0
    assert m["gauges"]["data/conservation_violations"] == 0
# the reduced audit is replicated: identical global figures everywhere
assert (docs[0]["data"]["stages"]["map_out"]["weighted_checksum"]
        == docs[1]["data"]["stages"]["map_out"]["weighted_checksum"])
assert docs[0]["data"]["records_in"] == docs[1]["data"]["records_in"]
# ... and the skew gauges ride process 0's ledger entry
e = json.loads(open(f"{d}/data_ledger/ledger.jsonl").readlines()[-1])
assert e["metrics"]["data/imbalance_factor"] >= 1.0
assert e["metrics"]["data/reduction_ratio"] >= 1.0
assert e["data"]["violations"] == []
print("dataplane OK: conservation green across the exchange, "
      f"imbalance {docs[0]['data']['skew']['imbalance_factor']}x, "
      f"reduction {docs[0]['data']['reduction']['ratio']}x")
EOF
# the audit table must render from the per-process metrics document
python -m map_oxidize_tpu obs data "$smoke/data_metrics.json.proc0" \
    | head -8

echo "== sort smoke =="
# ISSUE-14 acceptance: a 2-process Gloo total-order sort forced far past
# --collect-max-rows must COMPLETE via per-process disk buckets with
# globally-sorted, oracle-exact concatenated output and nonzero
# spill/rows on every process — and obs where must attribute >= 90% of
# the job's wall (the shuffle route + per-shard sort + host drains land
# in named buckets, not unattributed_pct)
python - "$smoke" <<'EOF'
import sys
import numpy as np
rng = np.random.default_rng(17)
n = 300_000
keys = rng.integers(0, 1 << 64, n, dtype=np.uint64)
keys[keys == np.uint64((1 << 64) - 1)] -= np.uint64(1)
pay = rng.integers(0, 1 << 64, n, dtype=np.uint64)
np.save(f"{sys.argv[1]}/sort_recs.npy", np.stack([keys, pay], axis=1))
EOF
sort_port=$(python - <<'EOF'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0))
print(s.getsockname()[1]); s.close()
EOF
)
sort_pids=()
for p in 0 1; do
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        timeout -k 10 600 \
        python -m map_oxidize_tpu sort "$smoke/sort_recs.npy" \
        --output "$smoke/sorted.bin" --batch-size 65536 --chunk-mb 1 \
        --collect-max-rows 32768 --quiet \
        --dist-coordinator "127.0.0.1:$sort_port" --dist-processes 2 \
        --dist-process-id "$p" \
        --metrics-out "$smoke/sort_metrics.json" > /dev/null &
    sort_pids+=($!)
done
sort_rc=0
for pid in "${sort_pids[@]}"; do wait "$pid" || sort_rc=$?; done
if [ "$sort_rc" -ne 0 ]; then
    echo "sort smoke: a 2-proc child failed (rc=$sort_rc)"
    exit "$sort_rc"
fi
python - "$smoke" <<'EOF'
import json, sys
import numpy as np
d = sys.argv[1]
from map_oxidize_tpu.workloads.sort import read_sorted_records, sort_model
recs = np.load(f"{d}/sort_recs.npy").view(np.uint64)
want_k, want_p = sort_model(recs[:, 0], recs[:, 1])
parts = [read_sorted_records(f"{d}/sorted.bin.part{i}of2")
         for i in range(2)]
got_k = np.concatenate([p[0] for p in parts])
got_p = np.concatenate([p[1] for p in parts])
# the parts concatenate PROCESS-MAJOR into the exact total order — no
# post-hoc sort here, the artifact itself must already be ordered
assert np.array_equal(got_k, want_k), "sort output not oracle-ordered"
assert np.array_equal(got_p, want_p), "sort payload order mismatch"
spilled = 0
for i in range(2):
    m = json.load(open(f"{d}/sort_metrics.json.proc{i}"))
    assert m["gauges"]["shuffle/transport"] == "disk", \
        f"auto should route this corpus/cap ratio to disk: {m['gauges']}"
    r = m["counters"].get("spill/rows", 0)
    assert r > 0, f"process {i} never spilled"
    spilled += r
    att = m.get("attrib") or {}
    pct = att.get("unattributed_pct")
    assert pct is not None and pct <= 10.0, \
        f"process {i}: obs where attributes only " \
        f"{100 - (pct or 100):.1f}% of the sort wall ({att})"
assert spilled == recs.shape[0], (spilled, recs.shape[0])
print(f"sort smoke OK: 2-proc spilled sort globally ordered "
      f"({spilled} rows through per-process disk buckets, "
      f">=90% of wall attributed)")
EOF
# obs where renders the sort decomposition from the metrics doc
python -m map_oxidize_tpu obs where "$smoke/sort_metrics.json.proc0"

echo "== critpath smoke =="
# ISSUE-15: the causal critical-path observatory end to end — a 2-proc
# wordcount with trace + ledger + live obs servers publishing into a
# private well-known spool while a fleet collector archives in the
# background.  Afterwards: `obs critpath` renders from the trace base,
# blame shares sum to ~100%, the path covers >= 90% of the traced wall,
# the ledger entry carries the critpath/* gate fields, process 0's
# metrics doc carries the full section, and the archived fleet
# post-mortem renders via --archive after every process exited.
cp_spool="$smoke/cp_spool"; cp_archive="$smoke/cp_archive"
mkdir -p "$cp_spool"
cp_port=$(python - <<'EOF'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0))
print(s.getsockname()[1]); s.close()
EOF
)
MOXT_OBS_SPOOL="$cp_spool" python -m map_oxidize_tpu obs fleet \
    --discover-dir "$cp_spool" --interval 0.2 --iterations 200 \
    --archive-dir "$cp_archive" > "$smoke/cp_fleet.log" 2>&1 &
cp_fleet_pid=$!
cp_pids=()
for p in 0 1; do
    MOXT_OBS_SPOOL="$cp_spool" JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        timeout -k 10 600 \
        python -m map_oxidize_tpu wordcount "$smoke/corpus_spill.txt" \
        --output "$smoke/cp_out.txt" \
        --batch-size 65536 --quiet --obs-port 0 \
        --dist-coordinator "127.0.0.1:$cp_port" --dist-processes 2 \
        --dist-process-id "$p" \
        --trace-out "$smoke/cp_trace.json" \
        --metrics-out "$smoke/cp_metrics.json" \
        --ledger-dir "$smoke/cp_ledger" > /dev/null &
    cp_pids+=($!)
done
cp_rc=0
for pid in "${cp_pids[@]}"; do wait "$pid" || cp_rc=$?; done
if [ "$cp_rc" -ne 0 ]; then
    echo "critpath smoke: a 2-proc child failed (rc=$cp_rc)"
    kill "$cp_fleet_pid" 2>/dev/null || true
    exit "$cp_rc"
fi
sleep 1   # one more collector sweep archives the post-exit state
kill "$cp_fleet_pid" 2>/dev/null || true
wait "$cp_fleet_pid" 2>/dev/null || true
python -m map_oxidize_tpu obs critpath "$smoke/cp_trace.json" | head -12
python - "$smoke" <<'EOF'
import json, sys
d = sys.argv[1]
skew = json.load(open(f"{d}/cp_trace.json.skew.json"))
cp = skew["critpath"]
assert not cp.get("error"), cp
shares = [r["share_pct"] for r in cp["blame"].values()]
assert abs(sum(shares) - 100.0) < 0.5, shares
assert cp["path_over_wall_pct"] >= 90.0, cp["path_over_wall_pct"]
assert cp["what_if"], "no what-if estimates"
led = [json.loads(l) for l in open(f"{d}/cp_ledger/ledger.jsonl")]
m = led[-1]["metrics"]
for k in ("critpath/bound_frac", "critpath/top_blame_share",
          "critpath/top_process_slack_ms",
          "critpath/collective_wait_share_pct",
          "critpath/path_over_wall_pct", "critpath/bound_by"):
    assert k in m, f"ledger entry lacks {k}"
md = json.load(open(f"{d}/cp_metrics.json.proc0"))
assert md.get("critpath", {}).get("blame"), \
    "proc0 metrics doc lacks the critpath section"
print("critpath smoke OK: blame sums to 100%, path covers "
      f"{cp['path_over_wall_pct']:.1f}% of wall, "
      f"bound by {cp['bound_by']}")
EOF
# the archived fleet post-mortem path renders AFTER every producer
# process exited (per-target, degenerating onto the archived attrib)
python -m map_oxidize_tpu obs critpath --archive "$cp_archive" | head -8

echo "== dispatch-floor smoke =="
# scan-batched streamed k-means: a center-seeded corpus streams through
# the device in 5 chunks/iteration at --dispatch-batch 4 (one full block
# + a zero-weight-padded tail = exactly the 2 first/last program
# variants), twice so the ledger has a same-B previous entry; then an
# --dispatch-batch auto run must record its resolved B in the ledger
python - "$smoke" <<'EOF'
import sys
import numpy as np
rng = np.random.default_rng(11)
c = rng.normal(0, 10, (4, 8)).astype(np.float32)
pts = (c[rng.integers(0, 4, 80_000)]
       + rng.normal(0, 0.5, (80_000, 8))).astype(np.float32)
pts[:4] = c  # center-seeded: assignment parity is well-conditioned
np.save(f"{sys.argv[1]}/kpoints.npy", pts)
EOF
for _ in 1 2; do
    JAX_PLATFORMS=cpu python -m map_oxidize_tpu kmeans \
        "$smoke/kpoints.npy" --output "$smoke/kcentroids.npy" \
        --kmeans-k 4 --kmeans-iters 2 --mapper auto --kmeans-fit-bytes 64 \
        --chunk-mb 1 --num-shards 1 --dispatch-batch 4 --quiet \
        --metrics-out "$smoke/kmetrics.json" \
        --ledger-dir "$smoke/kledger" > /dev/null
done
JAX_PLATFORMS=cpu python -m map_oxidize_tpu kmeans \
    "$smoke/kpoints.npy" --output "$smoke/kcentroids_auto.npy" \
    --kmeans-k 4 --kmeans-iters 2 --mapper auto --kmeans-fit-bytes 64 \
    --chunk-mb 1 --num-shards 1 --dispatch-batch auto --quiet \
    --metrics-out "$smoke/kmetrics_auto.json" \
    --ledger-dir "$smoke/kledger" > /dev/null
python - "$smoke" <<'EOF'
import json, sys
import numpy as np
d = sys.argv[1]
m = json.load(open(f"{d}/kmetrics.json"))
row = m["xprof"]["programs"]["kmeans/stream_step"]
# exact compile counts: B=4 over 5 chunks/iter is 2 blocks -> exactly
# the (first) and (padded-tail last) variants, nothing else
assert row["compiles"] == 2, f"expected exactly 2 compiles, got {row}"
# per-chunk attribution counts REAL chunks (the padded tail's dead
# chunks are excluded, same as the comms accounting): the warm
# iteration's 2 dispatches retire 4 + 1 real chunks -> 2.5
assert row["chunks_per_dispatch"] == 2.5, row
assert row["dispatch_gap_per_chunk_ms"] is not None
assert m["gauges"]["dispatch/batch"] == 4
# oracle parity: the scan-batched stream vs plain NumPy k-means
pts = np.load(f"{d}/kpoints.npy")
want = pts[:4].copy()
for _ in range(2):
    dist = ((pts[:, None, :] - want[None, :, :]) ** 2).sum(-1)
    cid = dist.argmin(1)
    for j in range(4):
        sel = pts[cid == j]
        if sel.shape[0]:
            want[j] = sel.mean(0)
got = np.load(f"{d}/kcentroids.npy")
np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)
np.testing.assert_allclose(np.load(f"{d}/kcentroids_auto.npy"), want,
                           rtol=1e-3, atol=1e-3)
led = [json.loads(l) for l in open(f"{d}/kledger/ledger.jsonl")]
assert len(led) == 3
# same-B fresh processes must land identical compile counts (the
# cross-run form of the zero-recompile gate)
k = "compile/kmeans/stream_step/compiles"
assert led[0]["metrics"][k] == led[1]["metrics"][k] == 2, led[0]["metrics"]
# the auto run's ledger entry records the B it resolved (and why)
assert led[2]["metrics"]["dispatch/batch_mode"] == "auto", led[2]["metrics"]
assert led[2]["metrics"]["dispatch/batch"] >= 1
print("dispatch-floor OK: 2 exact compiles at B=4, oracle parity, "
      f"auto resolved to B={led[2]['metrics']['dispatch/batch']}")
EOF

echo "== plan observatory smoke =="
# ISSUE-18 acceptance: --plan auto must (1) record a COLD run's
# platform_default provenance honestly (no pretend prediction), (2)
# predict the wall from the warmed workload curve with the full plan
# document riding the ledger entry and plan/model_error_pct under the
# gate threshold, (3) render predicted-vs-actual via `obs plan`, (4)
# record a user override as pinned provenance, and (5) fail
# `obs diff --gate` with a NAMED reason when the calibration store's
# curves are doctored — leaving the store file itself intact (the merge
# only accumulates; it never rewrites history)
for i in 1 2 3; do
    JAX_PLATFORMS=cpu python -m map_oxidize_tpu wordcount \
        "$smoke/corpus.txt" --output "$smoke/plan_out.txt" \
        --num-shards 1 --plan auto --quiet \
        --calib-dir "$smoke/plan_calib" \
        --ledger-dir "$smoke/plan_ledger" \
        --metrics-out "$smoke/plan_m$i.json" > /dev/null
done
python - "$smoke" <<'EOF'
import json, sys
d = sys.argv[1]
led = [json.loads(l) for l in open(f"{d}/plan_ledger/ledger.jsonl")]
assert len(led) == 3
cold = led[0]["plan"]
assert cold["provenance"] == "platform_default", cold
assert "predicted" not in cold and "model_error_pct" not in cold
assert led[0]["metrics"]["plan/pipeline_depth_provenance"] == "default"
warm = led[2]["plan"]
assert warm["provenance"] == "curve", warm
assert warm["predicted"]["wall_ms"] > 0
assert warm["actual"]["wall_ms"] > 0
# predicted buckets use the SAME names obs where attributes
assert set(warm["predicted"]["buckets"]) <= set(warm["actual"]["buckets"])
err = led[2]["metrics"]["plan/model_error_pct"]
assert err == warm["model_error_pct"] and err < 50.0, \
    f"same-corpus warm prediction should be close, got {err}%"
print(f"plan OK: cold=platform_default, warm predicted "
      f"{warm['predicted']['wall_ms']:.0f}ms vs actual "
      f"{warm['actual']['wall_ms']:.0f}ms ({err}% error)")
EOF
# healthy warm-vs-warm ledger pair passes the gate, and the report renders
python -m map_oxidize_tpu obs diff --ledger-dir "$smoke/plan_ledger" \
    --gate > /dev/null
python -m map_oxidize_tpu obs plan "$smoke/plan_m3.json" | head -7
# a user override must ride the plan as a PIN (metrics-out only: the
# changed config hash makes it a different ledger identity by design)
JAX_PLATFORMS=cpu python -m map_oxidize_tpu wordcount \
    "$smoke/corpus.txt" --output "$smoke/plan_out.txt" --num-shards 1 \
    --plan auto --pipeline-depth 3 --quiet \
    --calib-dir "$smoke/plan_calib" \
    --metrics-out "$smoke/plan_pinned.json" > /dev/null
python - "$smoke" <<'EOF'
import json, sys
d = sys.argv[1]
plan = json.load(open(f"{d}/plan_pinned.json"))["plan"]
assert plan["pins"] == ["pipeline_depth"], plan["pins"]
row = plan["knobs"]["pipeline_depth"]
assert row == {"value": 3, "provenance": "pinned",
               "evidence": {"requested": 3}}, row
print("plan OK: override recorded as pinned provenance")
EOF
# doctor the store's workload curve (x50 wall rates, identity fields
# untouched so it still LOADS — a plausibly-stale store, not a torn one)
python - "$smoke" <<'EOF'
import json, sys
p = f"{sys.argv[1]}/plan_calib/calib.json"
doc = json.load(open(p))
for row in doc["workloads"].values():
    row["wall_ms"] *= 50.0
    for k in [k for k in row
              if k.startswith("bucket_") and k.endswith("_ms")]:
        row[k] *= 50.0
json.dump(doc, open(p, "w"))
EOF
JAX_PLATFORMS=cpu python -m map_oxidize_tpu wordcount \
    "$smoke/corpus.txt" --output "$smoke/plan_out.txt" --num-shards 1 \
    --plan auto --quiet --calib-dir "$smoke/plan_calib" \
    --ledger-dir "$smoke/plan_ledger" \
    --metrics-out "$smoke/plan_m4.json" > /dev/null
if python -m map_oxidize_tpu obs diff --ledger-dir "$smoke/plan_ledger" \
    --gate > "$smoke/plan_gate.txt" 2>&1; then
    echo "doctored-store run should have tripped the plan gate"
    cat "$smoke/plan_gate.txt"
    exit 1
fi
grep -q "plan model drift" "$smoke/plan_gate.txt"
python - "$smoke" <<'EOF'
import json, sys
doc = json.load(open(f"{sys.argv[1]}/plan_calib/calib.json"))
row = next(iter(doc["workloads"].values()))
assert row["wall_ms"] > 1e4, "store must survive the gate run intact"
print("plan OK: doctored store tripped the gate with a named reason; "
      "store file left intact")
EOF

echo "== calibration probe smoke =="
# ISSUE-20 acceptance: one probe on a COLD store gives the very next
# job enough evidence to auto-select the exchange collective — the
# decision rides the plan doc with probe-sourced evidence, and the
# coverage gauges publish on the planned job and its ledger entry.
# Buckets chosen so the follow-up job's derived exchange payload
# (batch 65536 / 8 shards -> cap 2064 -> ~1.5MB -> bucket 1MB) lands
# INSIDE the probed range.
JAX_PLATFORMS=cpu python -m map_oxidize_tpu obs calib probe \
    "$smoke/probe_calib" --num-shards 8 \
    --buckets 256KB 512KB 1MB --reps 3 --json \
    > "$smoke/probe_summary.json"
python - "$smoke" <<'EOF'
import json, sys
s = json.load(open(f"{sys.argv[1]}/probe_summary.json"))
cells = s["cells"]
colls = {c["collective"] for c in cells}
assert {"all_to_all", "all_gather", "psum"} <= colls, colls
for coll in ("all_to_all", "all_gather"):
    buckets = {c["bucket"] for c in cells
               if c["collective"] == coll and c["program"] == "shuffle/merge"}
    assert len(buckets) >= 3, (coll, buckets)
assert s["rows_merged"] >= 8 and s["store_runs"] == 1, s
print(f"probe OK: {s['rows_merged']} rows across {sorted(colls)}")
EOF
JAX_PLATFORMS=cpu python -m map_oxidize_tpu obs calib coverage \
    "$smoke/probe_calib" --num-shards 8 --batch-size 65536 --json \
    > "$smoke/probe_coverage.json"
python - "$smoke" <<'EOF'
import json, sys
cov = json.load(open(f"{sys.argv[1]}/probe_coverage.json"))
assert cov["needed"] >= 2 and cov["coverage_pct"] == 100.0, cov
assert cov["extrapolation_bucket_distance"] == 0, cov
print(f"coverage OK: {cov['covered']}/{cov['needed']} cells after one probe")
EOF
# the source-grouped render must show the probe rows
JAX_PLATFORMS=cpu python -m map_oxidize_tpu obs calib show \
    "$smoke/probe_calib" | grep -q "probe"
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m map_oxidize_tpu wordcount "$smoke/corpus.txt" \
    --output "$smoke/probe_out.txt" --num-shards 8 --batch-size 65536 \
    --plan auto --quiet --calib-dir "$smoke/probe_calib" \
    --ledger-dir "$smoke/probe_ledger" \
    --metrics-out "$smoke/probe_job.json" > /dev/null
python - "$smoke" <<'EOF'
import json, sys
d = sys.argv[1]
m = json.load(open(f"{d}/probe_job.json"))
ex = m["plan"]["exchange"]
# the store curve steered the exchange, on probe-sourced evidence
assert ex["provenance"] == "curve", ex
assert ex["method"] in ("all_to_all", "all_gather"), ex
assert ex["bucket"] == "1MB", ex
ev = ex["evidence"][ex["method"]]
assert ev["by_source"].get("probe", 0) >= 3, ev
assert ev["bucket_distance"] == 0 and ev["predicted_ms"] is not None, ev
# the decision was applied (engine gauge), scored (measured wall), and
# the coverage gauges published
g = m["gauges"]
assert g["plan/exchange_collective"] == ex["method"], g
assert g["plan/exchange_collective_provenance"] == "curve", g
assert g["shuffle/exchange_collective"] == ex["method"], g
assert g["calib/coverage_pct"] == 100.0, g
assert g["calib/extrapolation_bucket_distance"] == 0, g
assert ex.get("actual_ms_per_exchange") is not None, ex
led = [json.loads(l) for l in open(f"{d}/probe_ledger/ledger.jsonl")]
lm = led[-1]["metrics"]
assert lm["calib/coverage_pct"] == 100.0, lm
assert lm["calib/extrapolation_bucket_distance"] == 0, lm
assert led[-1]["plan"]["exchange"]["provenance"] == "curve"
print(f"probe->job OK: {ex['method']} [curve] @ {ex['bucket']}, "
      f"predicted {ev['predicted_ms']}ms vs measured "
      f"{ex['actual_ms_per_exchange']}ms/exchange")
EOF

echo "== live telemetry smoke =="
# a big-enough HIGH-CARDINALITY corpus (the native mapper pre-combines
# per chunk, so a repeated-words corpus stages too few rows to flush
# mid-run) and an 8-virtual-device mesh so the run has real collectives
# to observe while it is still running
python - "$smoke" <<'EOF'
import sys
with open(f"{sys.argv[1]}/corpus_live.txt", "wb") as f:
    for i in range(6000):
        f.write((" ".join(f"w{i * 8 + j}" for j in range(8))
                 + "\n").encode())
EOF
export MOXT_OBS_PORT_FILE="$smoke/ports.txt"
rm -f "$smoke/ports.txt"
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m map_oxidize_tpu wordcount "$smoke/corpus_live.txt" \
    --output "$smoke/out_live.txt" --num-shards 8 --num-chunks 48 \
    --batch-size 512 --quiet --obs-port 0 \
    --calib-dir "$smoke/calib" \
    --metrics-out "$smoke/metrics_live.json" > /dev/null &
live_job=$!
python - "$smoke" <<'EOF'
import json, os, sys, time, urllib.request
d = sys.argv[1]
deadline = time.monotonic() + 180
port = None
while time.monotonic() < deadline and port is None:
    try:
        port = int(open(f"{d}/ports.txt").read().split()[1])
    except (OSError, IndexError, ValueError):
        time.sleep(0.01)
assert port, "obs server port never appeared in MOXT_OBS_PORT_FILE"
url = f"http://127.0.0.1:{port}"

def get(ep):
    return urllib.request.urlopen(url + ep, timeout=5).read()

# /metrics and /series are valid from server start: grab them first,
# then keep polling /status until one scrape shows an open phase AND a
# populated comms table (accumulated across scrapes — the server going
# away means the job ended, and by then the evidence must be in hand)
prom = series = None
phase_seen = comms_seen = None
connected = fails = 0
while time.monotonic() < deadline:
    try:
        if prom is None:
            p = get("/metrics").decode()
            if "# TYPE" in p:  # skip the registry's pre-job empty state
                prom = p
        if series is None:
            series = json.loads(get("/series"))
        # default SLO rules must stay SILENT on this healthy run
        a = json.loads(get("/alerts"))
        assert a["schema"] == "moxt-alerts-v1", a
        assert not a["firing"], f"default rules fired mid-run: {a['firing']}"
        s = json.loads(get("/status"))
        connected, fails = 1, 0
    except OSError:
        fails += 1
        if connected and fails > 200:
            break  # server gone for ~2s = job done; stop polling
        time.sleep(0.01)
        continue
    assert s["schema"] == "moxt-status-v1"
    assert s["meta"]["workload"] == "wordcount"
    if s.get("phase"):
        phase_seen = s["phase"]
    if s.get("comms"):
        comms_seen = s["comms"]
    if phase_seen and comms_seen:
        break
    time.sleep(0.01)
assert phase_seen, "never scraped a mid-run /status with an open phase"
assert comms_seen, "never scraped a /status with a comms table"
assert any(r["collective"] == "all_to_all" for r in comms_seen)
assert prom and "# TYPE" in prom and "moxt_" in prom, "bad /metrics"
assert series and series["schema"] == "moxt-series-v1"
print(f"live scrape OK mid-run: phase={phase_seen} "
      f"comms_rows={len(comms_seen)}")
EOF
wait "$live_job"
unset MOXT_OBS_PORT_FILE
python - "$smoke" <<'EOF'
import json, sys
m = json.load(open(f"{sys.argv[1]}/metrics_live.json"))
assert m["series"]["schema"] == "moxt-series-v1", "series section missing"
assert any(r["program"] == "shuffle/merge" for r in m["comms"]), \
    "comms table missing from the metrics document"
# the default-rules evaluator ran for the whole job and fired NOTHING
al = m.get("alerts") or {}
assert al.get("schema") == "moxt-alerts-v1", "alerts section missing"
assert al["counts"]["fired"] == 0, \
    f"default SLO rules fired on a clean run: {al['timeline']}"
print("final metrics doc carries series + comms + silent alerts")
EOF

echo "== SLO alert smoke =="
# an injected rule that must FIRE mid-run (rows below a floor the job
# eventually passes) and RESOLVE when the condition clears — visible
# live at /alerts, as an incident bundle, and in the exported timeline
cat > "$smoke/slo_rules.json" <<'JSON'
{"defaults": false, "rules": [
 {"name": "smoke-rows-floor", "metric": "progress/rows",
  "op": "<", "threshold": 20000, "kind": "value"}]}
JSON
export MOXT_OBS_PORT_FILE="$smoke/alert_port.txt"
rm -f "$smoke/alert_port.txt"
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m map_oxidize_tpu wordcount "$smoke/corpus_live.txt" \
    --output "$smoke/out_alert.txt" --num-shards 8 --num-chunks 48 \
    --batch-size 512 --quiet --obs-port 0 --obs-sample-interval 0.05 \
    --slo-rules "$smoke/slo_rules.json" \
    --incident-dir "$smoke/incidents" \
    --calib-dir "$smoke/calib" \
    --metrics-out "$smoke/metrics_alert.json" > /dev/null &
alert_job=$!
trap 'kill "$alert_job" 2>/dev/null; rm -rf "$smoke"' EXIT
python - "$smoke" <<'EOF'
import json, sys, time, urllib.request
d = sys.argv[1]
deadline = time.monotonic() + 180
port = None
while time.monotonic() < deadline and port is None:
    try:
        port = int(open(f"{d}/alert_port.txt").read().split()[1])
    except (OSError, IndexError, ValueError):
        time.sleep(0.01)
assert port, "obs server port never appeared for the alert smoke"
url = f"http://127.0.0.1:{port}/alerts"
fired_seen = resolved_seen = False
connected = fails = 0
while time.monotonic() < deadline:
    try:
        a = json.loads(urllib.request.urlopen(url, timeout=5).read())
        connected, fails = 1, 0
    except OSError:
        fails += 1
        if connected and fails > 200:
            break  # server gone ~2s = job finished
        time.sleep(0.01)
        continue
    assert a["schema"] == "moxt-alerts-v1"
    if a["firing"]:
        assert a["firing"][0]["rule"] == "smoke-rows-floor"
        fired_seen = True
    if a["counts"]["resolved"] >= 1:
        resolved_seen = True
    if fired_seen and resolved_seen:
        break
    time.sleep(0.01)
assert fired_seen, "injected rule never seen firing at /alerts"
print(f"live /alerts OK: firing seen, resolved live={resolved_seen}")
EOF
wait "$alert_job"
trap 'rm -rf "$smoke"' EXIT
unset MOXT_OBS_PORT_FILE
python - "$smoke" <<'EOF'
import glob, json, sys
d = sys.argv[1]
m = json.load(open(f"{d}/metrics_alert.json"))
events = [e["event"] for e in m["alerts"]["timeline"]]
assert events == ["fired", "resolved"], \
    f"expected the rule to fire then resolve, got {events}"
assert m["counters"]["alerts/fired"] == 1
bundles = glob.glob(f"{d}/incidents/incident_*/incident.json")
assert len(bundles) == 1, f"expected 1 incident bundle, got {bundles}"
inc = json.load(open(bundles[0]))
assert inc["schema"] == "moxt-incident-v1"
assert inc["rule"]["name"] == "smoke-rows-floor"
assert inc["status"]["schema"] == "moxt-status-v1"
print("alert smoke OK: fired -> resolved, incident bundle landed")
EOF

echo "== attribution + calibration smoke =="
# (1) the wall-clock attribution ledger must decompose BOTH acceptance
# smokes — the 8-shard wordcount and the scan-batched streamed k-means
# — to >= 90% of measured wall (remainder reported, never hidden);
# (2) the two --calib-dir wordcount runs above (live + alert smokes)
# must have merged into ONE calibration store with nonzero
# per-collective bandwidth rows keyed (collective, program, shape-bucket)
python - "$smoke" <<'EOF'
import json, sys
d = sys.argv[1]
for name, path in (("wordcount", f"{d}/metrics_live.json"),
                   ("kmeans", f"{d}/kmetrics.json")):
    a = json.load(open(path))["attrib"]
    assert a["schema"] == "moxt-attrib-v1", a
    total = sum(b["ms"] for b in a["buckets"].values())
    assert abs(total + a["unattributed_ms"] - a["wall_ms"]) \
        <= 0.03 * a["wall_ms"], a
    assert a["unattributed_pct"] < 10.0, (
        f"{name}: {a['unattributed_pct']}% of wall unattributed "
        f"(buckets must cover >= 90%): {a['buckets']}")
    print(f"attrib OK ({name}): {100 - a['unattributed_pct']:.1f}% of "
          f"{a['wall_ms'] / 1e3:.2f}s wall attributed")
store = json.load(open(f"{d}/calib/calib.json"))
assert store["schema"] == "moxt-calib-v1" and store["runs"] >= 2, store
from map_oxidize_tpu.obs.calib import CalibStore
bw = [r for r in CalibStore(doc=store).bandwidth_table()
      if r["collective"] == "all_to_all" and r.get("gbytes_per_s")]
assert bw, "no nonzero all_to_all bandwidth row in the merged store"
r = bw[0]
assert r["runs"] >= 2, r   # BOTH runs' samples merged into the row
print(f"calib OK: {store['runs']} runs merged; {r['collective']}/"
      f"{r['program']} @ {r['shape_bucket']}: {r['gbytes_per_s']} GB/s "
      f"over {r['calls']} calls")
EOF
# the CLI reports must render from the same artifacts (sed drains the
# pipe, so the renderer never dies on EPIPE under pipefail)
python -m map_oxidize_tpu obs where "$smoke/metrics_live.json" \
    | sed -n '1,6p'
python -m map_oxidize_tpu obs calib "$smoke/calib" | sed -n '1,6p'

echo "== serve smoke =="
# resident job server on an ephemeral port: 3 identical small wordcounts
# back to back must show compile/* deltas of ZERO after job 1 (the warm-
# cache story, per-job compile-ledger accounting), /jobs must scrape
# mid-run, and a client-requested drain must exit the server cleanly
export MOXT_OBS_PORT_FILE="$smoke/serve_port.txt"
rm -f "$smoke/serve_port.txt"
JAX_PLATFORMS=cpu python -m map_oxidize_tpu serve --port 0 --workers 1 \
    --spool-dir "$smoke/serve_spool" --quiet &
serve_job=$!
# a failed assertion below must not leak a resident server running
# forever on the CI host (nor delete its live spool out from under it)
trap 'kill "$serve_job" 2>/dev/null; rm -rf "$smoke"' EXIT
python - "$smoke" <<'EOF'
import sys, time
d = sys.argv[1]
deadline = time.monotonic() + 180
port = None
while time.monotonic() < deadline and port is None:
    try:
        port = int(open(f"{d}/serve_port.txt").read().split()[1])
    except (OSError, IndexError, ValueError):
        time.sleep(0.01)
assert port, "serve port never appeared in MOXT_OBS_PORT_FILE"
from map_oxidize_tpu.serve.client import ServeClient
c = ServeClient(f"http://127.0.0.1:{port}")
cfg = {"num_chunks": 16, "batch_size": 64, "num_shards": 1}
ids = [c.submit("wordcount", f"{d}/corpus.txt", config=cfg,
                output=f"{d}/serve_out.txt")["id"] for _ in range(3)]
# mid-run /jobs scrape: all three submissions visible while the single
# worker is still working the queue
tbl = c.jobs()
assert tbl["schema"] == "moxt-jobs-v1", tbl
assert len(tbl["jobs"]) == 3 and tbl["queue"]["max"] == 16
# mid-run deep capture on the LIVE resident server: a host-sampling
# POST /profile while the worker is still chewing the queue — it must
# produce stacks WITHOUT aborting the jobs.  The device leg is taken
# separately below, after the queue drains: jax.profiler's stop_trace
# serializes every event since start, and capturing THROUGH a
# concurrent cold compile costs minutes on this backend (measured) —
# the host sampler is the right mid-run tool, the device trace the
# right warm-server one
import json, os, urllib.request
def profile(body, timeout):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/profile",
        data=json.dumps(body).encode(), method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())
prof = profile({"duration_s": 1.0, "host_sample_hz": 60,
                "device": False, "label": "mid-run"}, 60)
assert prof["schema"] == "moxt-profile-v1", prof
assert prof["host_samples"] > 0, prof
assert os.path.isfile(prof["host_stacks"]), prof
assert prof["dir"].startswith(f"{d}/serve_spool/profiles"), prof
assert prof["meta"]["running_jobs"], "capture saw no running jobs"
docs = [c.wait(i, timeout_s=120) for i in ids]
# device+host capture on the still-live warm server (first jax.profiler
# start/stop pays ~10s of init+serialization here — timeout generous)
prof2 = profile({"duration_s": 0.5, "host_sample_hz": 60}, 240)
assert prof2["device"].get("dir") and os.listdir(prof2["device"]["dir"]), \
    f"device trace artifacts missing: {prof2['device']}"
assert os.path.isfile(prof2["host_stacks"]), prof2
assert [x["state"] for x in docs] == ["done"] * 3, docs
assert docs[0]["compiles"] >= 1, docs[0]      # cold job compiled
assert docs[1]["compiles"] == 0, docs[1]      # warm: zero deltas
assert docs[2]["compiles"] == 0, docs[2]
assert docs[0]["records_in"] == docs[2]["records_in"] == 1800
print(f"serve OK: cold job compiled {docs[0]['compiles']}x, "
      "warm compile deltas zero")
c.shutdown(drain=True)
EOF
wait "$serve_job"   # exit 0 = clean drain on the client's shutdown
trap 'rm -rf "$smoke"' EXIT
unset MOXT_OBS_PORT_FILE
# the flame report renders from the capture the smoke just took
python -m map_oxidize_tpu obs flame "$smoke/serve_spool/profiles" \
    | sed -n '1,8p'

echo "== fleet observatory smoke =="
# two resident servers on ephemeral ports + one fleet collector watching
# both spools: submitted jobs must surface as per-target labels AND a
# nonzero fleet-aggregate row rate on the collector's /metrics; killing
# one server (-9, so its spool record survives) must fire the staleness
# alert in the fleet /alerts timeline; and after EVERY process is gone,
# obs trend/top must reconstruct the run purely from --archive-dir
export MOXT_OBS_PORT_FILE="$smoke/fleet_port.txt"
rm -f "$smoke/fleet_port.txt"
for s in A B; do
    JAX_PLATFORMS=cpu MOXT_OBS_PORT_FILE= python -m map_oxidize_tpu \
        serve --port 0 --workers 1 \
        --spool-dir "$smoke/fleet_spool_$s" --quiet &
    eval "fleet_srv_$s=\$!"
    eval "echo \$fleet_srv_$s > '$smoke/fleet_srv_$s.pid'"
done
JAX_PLATFORMS=cpu python -m map_oxidize_tpu obs fleet \
    --spool "$smoke/fleet_spool_A" "$smoke/fleet_spool_B" \
    --discover-dir none --interval 0.2 --stale-after 2 \
    --archive-dir "$smoke/fleet_archive" > "$smoke/fleet.log" &
fleet_col=$!
trap 'kill -9 "$fleet_col" "$fleet_srv_A" "$fleet_srv_B" 2>/dev/null; rm -rf "$smoke"' EXIT
python - "$smoke" <<'EOF'
import json, os, sys, time, urllib.request
d = sys.argv[1]
deadline = time.monotonic() + 180

def wait_port(path, key=None):
    while time.monotonic() < deadline:
        try:
            if key is None:
                return int(open(path).read().split()[1])
            return int(json.loads(open(path).read())[key])
        except (OSError, IndexError, ValueError, KeyError):
            time.sleep(0.02)
    raise AssertionError(f"port never appeared at {path}")

ports = {s: wait_port(f"{d}/fleet_spool_{s}/obs_port.json", "port")
         for s in "AB"}
fleet_port = wait_port(f"{d}/fleet_port.txt")
fleet = f"http://127.0.0.1:{fleet_port}"

def get(base, ep):
    return urllib.request.urlopen(base + ep, timeout=5).read()

# both targets must come up in the fleet model before work is submitted
labels = {s: f"127.0.0.1:{ports[s]}" for s in "AB"}
while time.monotonic() < deadline:
    st = json.loads(get(fleet, "/status"))
    assert st["schema"] == "moxt-fleet-status-v1", st
    if st["counts"]["up"] == 2:
        break
    time.sleep(0.05)
assert st["counts"]["up"] == 2, f"fleet never saw both servers: {st}"

# submit one small wordcount to EACH server
from map_oxidize_tpu.serve.client import ServeClient
cfg = {"num_chunks": 8, "batch_size": 64, "num_shards": 1}
for s in "AB":
    c = ServeClient(f"http://127.0.0.1:{ports[s]}")
    doc = c.submit("wordcount", f"{d}/corpus.txt", config=cfg,
                   output=f"{d}/fleet_out_{s}.txt")
    c.wait(doc["id"], timeout_s=120)

# the fleet /metrics must carry BOTH targets' labels and a nonzero
# aggregate row rate (recently-finished jobs count toward the load
# index for a bounded window)
rate = 0.0
while time.monotonic() < deadline:
    prom = get(fleet, "/metrics").decode()
    have_labels = all(f'{{target="{labels[s]}"}}' in prom for s in "AB")
    for line in prom.splitlines():
        if line.startswith("moxt_fleet_rows_per_sec "):
            rate = float(line.rsplit(" ", 1)[1])
    if have_labels and rate > 0:
        break
    time.sleep(0.1)
assert have_labels, "fleet /metrics lacks a target label"
assert rate > 0, "fleet-aggregate row rate never went nonzero"
print(f"fleet scrape OK: both targets labeled, fleet rate {rate} rows/s")

# kill server A hard: its spool record survives, so the fleet must mark
# it STALE and fire the staleness alert into the /alerts timeline
os.kill(int(open(f"{d}/fleet_srv_A.pid").read()), 9)
fired = False
while time.monotonic() < deadline and not fired:
    al = json.loads(get(fleet, "/alerts"))
    assert al["schema"] == "moxt-fleet-alerts-v1", al
    fired = any(e["event"] == "fired"
                and e["rule"] == "fleet-target-stale"
                and labels["A"] in e["series"]
                for e in al["fleet"]["timeline"])
    time.sleep(0.1)
assert fired, "staleness alert never fired after killing server A"
inc = [i for i in al["incidents"] if i["rule"] == "fleet-target-stale"]
assert inc and labels["A"] in inc[0]["targets"], al["incidents"]
st = json.loads(get(fleet, "/status"))
row = [t for t in st["targets"] if t["target"] == labels["A"]][0]
assert row["state"] == "stale", row
print("fleet staleness OK: kill -> stale row + fired alert + incident")

# drain server B cleanly, then the post-mortem readers take over
ServeClient(f"http://127.0.0.1:{ports['B']}").shutdown(drain=True)
EOF
wait "$fleet_srv_A" 2>/dev/null || true   # reap the killed server
wait "$fleet_srv_B"   # exit 0 = clean drain
kill "$fleet_col" 2>/dev/null || true
wait "$fleet_col" 2>/dev/null || true
trap 'rm -rf "$smoke"' EXIT
unset MOXT_OBS_PORT_FILE
# every producer AND the collector are gone: the archive alone must
# reconstruct the run — trajectories and the final fleet frame
python -m map_oxidize_tpu obs trend --archive "$smoke/fleet_archive" \
    | sed -n '1,8p'
python -m map_oxidize_tpu obs top --archive "$smoke/fleet_archive" \
    | sed -n '1,6p'
python - "$smoke" <<'EOF'
import sys
from map_oxidize_tpu.obs.fleet import SeriesArchive
d = sys.argv[1]
export = SeriesArchive.export(f"{d}/fleet_archive")
rates = [v for v in export["series"].get("fleet/rows_per_sec", []) if v]
assert rates, "archive never recorded a nonzero fleet rate"
stale = export["series"].get("fleet/targets_stale") or []
assert any(v == 1 for v in stale), "archive never recorded the staleness"
st = SeriesArchive.latest(f"{d}/fleet_archive", "status")
assert st and st["schema"] == "moxt-fleet-status-v1"
print(f"fleet archive OK: {len(export['t_unix_s'])} samples, "
      f"peak rate {max(rates)} rows/s, staleness recorded")
EOF
echo "check.sh: ALL OK"
