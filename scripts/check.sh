#!/usr/bin/env bash
# One-command verification: the tier-1 test suite plus an observability
# smoke that exercises the whole artifact surface — a tiny wordcount with
# --trace-out/--metrics-out/--ledger-dir (twice, so the ledger has a
# previous entry), artifact well-formedness checks, an informational
# previous-vs-last `obs diff`, and a gated self-diff that must report
# zero deltas.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== tier-1 pytest =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log

echo "== obs smoke =="
smoke=$(mktemp -d)
trap 'rm -rf "$smoke"' EXIT
python - "$smoke" <<'EOF'
import sys
with open(f"{sys.argv[1]}/corpus.txt", "wb") as f:
    f.write(b"the quick brown fox jumps over the lazy dog\n" * 200)
EOF
# --num-chunks 16 --batch-size 64: several same-shape merges, so the run
# has steady-state (non-compiling) dispatches and the dispatch-gap
# histogram populates alongside the exact compile counts
for _ in 1 2; do
    JAX_PLATFORMS=cpu python -m map_oxidize_tpu wordcount \
        "$smoke/corpus.txt" --output "$smoke/out.txt" --num-shards 1 \
        --num-chunks 16 --batch-size 64 \
        --quiet --trace-out "$smoke/trace.json" \
        --metrics-out "$smoke/metrics.json" --ledger-dir "$smoke/ledger" \
        > /dev/null
done
python - "$smoke" <<'EOF'
import json, sys
d = sys.argv[1]
trace = json.load(open(f"{d}/trace.json"))
assert isinstance(trace, list) and trace, "trace.json malformed"
assert all(e["ph"] in ("X", "i", "M") for e in trace)
m = json.load(open(f"{d}/metrics.json"))
assert m["meta"]["config_hash"] and m["meta"]["version"], "stamp missing"
assert m["phases_s"]["map+reduce"] > 0
led = [json.loads(l) for l in open(f"{d}/ledger/ledger.jsonl")]
assert len(led) == 2, f"expected 2 ledger entries, got {len(led)}"
# xprof smoke: the observatory saw the fold engine's programs with EXACT
# compile counts (one shape set each on a one-flush corpus), the cost
# join has FLOPs/bytes, and both ledger entries carry the gate fields
x = m.get("xprof") or {}
progs = x.get("programs") or {}
for prog in ("engine/merge_packed", "engine/pack_finalize"):
    assert progs.get(prog, {}).get("compiles") == 1, (
        f"xprof: expected exactly 1 compile of {prog}, got "
        f"{progs.get(prog)}")
    assert progs[prog].get("bytes_per_dispatch"), f"no cost join for {prog}"
for e in led:
    assert e["metrics"].get("compile/engine/merge_packed/compiles") == 1, \
        "ledger entry lacks exact compile counts"
assert "device/dispatch_gap_ms" in m.get("histograms", {}), \
    "dispatch-gap histogram missing"
print("obs artifacts OK (xprof: "
      f"{x.get('total_compiles')} compiles / "
      f"{x.get('total_dispatches')} dispatches)")
EOF
# the observatory report must render from the metrics document
python -m map_oxidize_tpu obs xprof "$smoke/metrics.json" | head -5
# previous vs last (informational: same config, tiny run — deltas are
# jitter), then a gated self-diff that MUST come back all-zero
python -m map_oxidize_tpu obs diff --ledger-dir "$smoke/ledger"
python -m map_oxidize_tpu obs diff --ledger-dir "$smoke/ledger" \
    --gate -- -1 -1
echo "check.sh: ALL OK"
