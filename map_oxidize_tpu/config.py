"""Job configuration.

Replaces the reference's four hardcoded locals
(``/root/reference/src/main.rs:10-13``: ``file_path``, ``num_map_workers=8``,
``num_reduce_workers=4``, ``num_chunks=8``) and the call-site-hardcoded
``n=10`` top-k (main.rs:28) with a real config object, fed by the CLI
(``map_oxidize_tpu.cli``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path

#: every built-in workload ``run_job`` dispatches — THE single source of
#: truth every allowlist derives from: the one-shot CLI's ``choices``,
#: the resident scheduler's submit-time allowlist, and the submit CLI's
#: ``choices`` all import it from here (the one module each already
#: depends on without pulling in jax), so the lists cannot drift
#: (tests/test_dataflow.py asserts they agree)
WORKLOADS = ("wordcount", "bigram", "invertedindex", "kmeans",
             "distinct", "sort", "join", "sessionize")

#: workloads the resident job service serves — every built-in runs
#: through the same drivers the scheduler multiplexes, so the serve
#: allowlist IS the workload list (kept as its own name because the
#: scheduler/submit surfaces bind to serve semantics, and a future
#: serve-incompatible workload would subset here, in one place)
SERVE_WORKLOADS = WORKLOADS


@dataclass
class JobConfig:
    #: input corpus path (reference: "shakes.txt", main.rs:10)
    input_path: str = "shakes.txt"
    #: host map worker threads (reference: 8 tokio tasks, main.rs:11)
    num_map_workers: int = 8
    #: input chunks; 0 = derive from file size / chunk_bytes (reference: 8
    #: round-robin line chunks, main.rs:13 — ours are byte-range shards)
    num_chunks: int = 0
    #: target bytes per streamed chunk (whole corpus is never host-resident,
    #: unlike main.rs:36-51)
    chunk_bytes: int = 32 * 1024 * 1024
    #: max rows per device feed batch; short batches are padded only to the
    #: next power of two, so tiny chunks don't pay full-batch sort cost
    batch_size: int = 1 << 18
    #: bounded-prefetch pipeline depth: how many chunks of host work
    #: (read+tokenize) may run ahead of the device feed, overlapping host
    #: map with device dispatch (runtime/pipeline.py).  1 = the strictly
    #: serial schedule (outputs are byte-identical either way — the
    #: pipeline preserves chunk order); each extra unit of depth holds at
    #: most one more chunk's MapOutput in host memory
    pipeline_depth: int = 2
    #: dispatch batching on streamed paths: logical chunks retired per
    #: device launch.  The streamed k-means step wraps its per-chunk body
    #: in a ``lax.scan`` over a stacked ``(B, chunk_rows, d)`` block, and
    #: the packed fold-engine merge scans B staged feed batches per
    #: dispatch — amortizing the measured ~150-250 ms/launch floor by B.
    #: 0 = auto: picked at job start from the measured dispatch floor,
    #: host-produce and device-compute per chunk (xprof roofline data),
    #: capped by the HBM budget; the chosen B and its inputs are recorded
    #: in metrics and the run ledger.  1 = the unbatched schedule; N > 1
    #: pins the batch.  Outputs are bit-identical at any B (tail chunks
    #: are zero-weight-masked; accumulation order is preserved), and B is
    #: deliberately NOT checkpoint or ledger identity — a job may resume
    #: or gate across different B.  The fold engine batches only under an
    #: explicit N > 1 (auto targets the streamed k-means dispatch).
    dispatch_batch: int = 0
    #: hard upper bound on distinct keys on device (accumulator max size)
    key_capacity: int = 1 << 22
    #: starting accumulator capacity; grows by sentinel-padding (4x steps)
    #: toward key_capacity as distinct keys accumulate
    initial_key_capacity: int = 1 << 16
    #: top-k to report (reference: n=10 at main.rs:28)
    top_k: int = 10
    #: 'tpu' | 'cpu' | 'auto' — auto uses whatever jax.devices() offers
    backend: str = "auto"
    #: number of mesh shards for the device engine; 0 = all local devices
    num_shards: int = 0
    #: tokenizer mode: 'ascii' (byte path) or 'unicode' (exact Rust
    #: split_whitespace/to_lowercase semantics, main.rs:96-97); both are
    #: C++-accelerated, the device mapper is ascii-only
    tokenizer: str = "ascii"
    #: map-phase placement: 'device' tokenizes+hashes on the TPU itself,
    #: 'native' uses the C++ host loop, 'python' the pure fallback; 'auto'
    #: picks device on an accelerator, native on cpu
    mapper: str = "auto"
    #: per-chunk unique-key slots for the device mapper output
    device_chunk_keys: int = 1 << 19
    #: reduce engine choice: 'fold' = streaming device accumulator (narrow
    #: key spaces), 'collect' = host collect + one vectorized sort/reduce
    #: (wide key spaces — see runtime/host_reduce.py for the measured
    #: rationale); 'auto' picks by the mapper's wide_keys declaration
    reduce_mode: str = "auto"
    #: inverted-index pair sort: 'host' = np.lexsort (zero link traffic,
    #: the measured winner on a remote-attached chip), 'device' = XLA sort
    #: in HBM (wins on local attach); 'auto' = host
    collect_sort: str = "auto"
    #: output file (reference: "final_result.txt", main.rs:174)
    output_path: str = "final_result.txt"
    #: directory for spill/checkpoint artifacts; None disables checkpointing
    checkpoint_dir: str | None = None
    #: keep intermediate artifacts instead of deleting (reference always
    #: cleans up, main.rs:194-202)
    keep_intermediates: bool = False
    #: per-chunk map retry budget (reference: abort on first error,
    #: main.rs:88 `handle.await??`)
    max_retries: int = 2
    #: jax.profiler trace output directory; None disables trace capture
    trace_dir: str | None = None
    #: use the C++ native tokenizer when available
    use_native: bool = True
    #: emit per-phase timing/throughput metrics
    metrics: bool = True
    #: write the structured metrics document (phases, counters, gauges,
    #: histograms — obs.MetricsRegistry.to_dict) here as JSON; None skips
    metrics_out: str | None = None
    #: capture framework spans and write Chrome trace-event JSON here
    #: (chrome://tracing / Perfetto); "-" collects the trace onto
    #: ``result.trace`` without writing a file; None disables tracing
    trace_out: str | None = None
    #: append every finished job's summary (metrics, phase times, config
    #: hash, version, workload, corpus size) to ``<dir>/ledger.jsonl`` —
    #: the regression-diff history ``obs diff`` / ``bench.py --gate``
    #: read; None disables
    ledger_dir: str | None = None
    #: failure flight recorder: on an abort (conservation/overflow/
    #: capacity/any exception) dump a post-mortem bundle (config,
    #: metrics-so-far, open-span-closed trace, traceback) under this
    #: directory before propagating; None disables
    crash_dir: str | None = None
    #: emit periodic progress lines (rows/sec, percent, ETA, phase) for
    #: long streamed jobs
    progress: bool = False
    #: minimum seconds between progress lines
    progress_interval_s: float = 10.0
    #: live HBM sampler: seconds between ``device.memory_stats()`` reads
    #: on a background thread (``hbm/live_bytes_device<i>`` watermark
    #: gauges, heartbeat hbm= field, crash-bundle evidence).  0 disables
    #: (the default: phase-boundary sampling still runs)
    hbm_sample_s: float = 0.0
    #: stall detector: warn when no chunk completes within this multiple
    #: of the median inter-chunk interval, naming the open spans.  0
    #: disables (the default — tests and short jobs stay silent)
    stall_warn_factor: float = 0.0
    #: live telemetry HTTP server (obs/serve.py): the port this job's
    #: /metrics + /status + /series endpoints bind on 127.0.0.1.
    #: 0 = ephemeral (the bound port is logged); -1 disables (default).
    #: Distributed runs: every process serves its own port — ephemeral
    #: stays ephemeral, a fixed port offsets by the process slot.
    obs_port: int = -1
    #: time-series recorder (obs/timeseries.py): seconds between ring-
    #: buffer snapshots of every counter/gauge/histogram-quantile (the
    #: metrics doc's ``series`` section + the live /series endpoint).
    #: 0 = off, unless --obs-port is set (serving implies sampling, 1s)
    obs_sample_s: float = 0.0
    #: fleet-discovery spool: where this job's live obs server publishes
    #: its ``moxt-obs-port-v1`` record (pid, process slot, bound port) so
    #: ``obs fleet`` finds it without flags — every process of a
    #: distributed run publishes its own slot.  None = $MOXT_OBS_SPOOL or
    #: the well-known per-user tempdir spool; "none" disables publishing
    obs_spool: str | None = None
    #: SLO/alerting plane (obs/slo.py): rule set for the alert evaluator
    #: that watches the time-series ring whenever it runs.  None = the
    #: built-in defaults; else a JSON file path or inline JSON — a list
    #: EXTENDS the defaults, {"defaults": false, "rules": [...]}
    #: replaces them.  Firing/resolved transitions emit [alert]
    #: heartbeat lines, serve at /alerts, count into alerts/* (ledger-
    #: gated), and write incident bundles
    slo_rules: str | None = None
    #: where alert incident bundles land (series window + /status
    #: snapshot per firing); None = the --crash-dir, if any
    incident_dir: str | None = None
    #: data-plane observatory (obs/dataplane.py): per-partition row-
    #: conservation audits (order-independent checksums across the
    #: shuffle — a violation is a named hard failure), key-skew
    #: telemetry (``data/imbalance_factor``, hot keys, HLL distinct
    #: estimates), and reduction-ratio gauges.  Pure host-side
    #: accounting; does not change any computed result (excluded from
    #: the ledger config identity)
    data_audit: bool = True
    #: deep-profiling plane (obs/profiler.py): where on-demand
    #: ``POST /profile`` captures land (device trace + host sampling
    #: stacks + profile.json).  None = next to the crash bundles /
    #: metrics document, else ./moxt-profiles
    profile_dir: str | None = None
    #: host sampling profiler rate for ``POST /profile`` captures:
    #: Python thread stacks snapshotted this many times per second
    #: (sys._current_frames; overhead is one frame walk per thread per
    #: tick, only WHILE a capture runs)
    host_sample_hz: float = 50.0
    #: persistent calibration store (obs/calib.py): directory whose
    #: ``calib.json`` accumulates measured per-(platform, devices,
    #: topology, collective, program, shape-bucket) bytes/latency and
    #: per-program dispatch/compute figures ACROSS runs — loaded at job
    #: start, merged atomically at finish, rendered by ``obs calib``.
    #: None disables
    calib_dir: str | None = None
    #: multi-host: coordination-service address ("host:port"); empty = the
    #: single-process path.  With it set, dist_num_processes and
    #: dist_process_id select this process's slot; jax.distributed is
    #: initialized before any backend use and the mesh spans every
    #: process's devices (ICI within a host, DCN across hosts).
    dist_coordinator: str = ""
    dist_num_processes: int = 0
    dist_process_id: int = -1
    #: hash-only rescan: scan the whole corpus when resolving winner
    #: strings instead of stopping once every queried hash is found.  The
    #: full scan extends the collision byte-check from the scanned prefix to
    #: every occurrence in the corpus, at the cost of a corpus-length pass.
    rescan_full: bool = False
    #: distinct (HyperLogLog): register-count precision p (2^p registers;
    #: relative standard error ~1.04/sqrt(2^p) — ~0.8% at the default)
    hll_precision: int = 14
    #: k-means mapper='auto' device-fit budget in bytes (the whole working
    #: set — points plus the (n, k) distance/one-hot intermediates — must
    #: fit under it for the HBM-resident path; past it the job streams
    #: through the device).  0 = probe the device's reported memory (half
    #: of it), falling back to the conservative 8GB constant.  Exposed so
    #: tests can pin the beyond-fit routing without a multi-GB corpus and
    #: operators can override a misreporting runtime.
    kmeans_device_fit_bytes: int = 0
    #: k-means: cluster count (init = first k points of the input)
    kmeans_k: int = 16
    #: k-means: iterations to run
    kmeans_iters: int = 1
    #: k-means device-path matmul precision: "highest" (f32 oracle-parity,
    #: the MXU emulates f32 with multiple bf16 passes) or "bf16" (native
    #: single-pass MXU matmuls with f32 accumulation — the chip's design
    #: rate; assignment boundaries can shift within bf16 rounding).  The
    #: streamed (host-assign) path is NumPy f32 and ignores this.
    kmeans_precision: str = "highest"
    #: collect engines: resident-row cap before the disk-bucket spill —
    #: hash-only counts, explicit (key, value) rows, and (key, doc) pairs
    #: all spill; the sharded device engine first demotes its HBM buffers
    #: to the host engine.  0 = engine defaults (host collect 2^28, pair
    #: collect 2^27).  What happens AT the cap is the shuffle transport's
    #: call (``shuffle_transport``): hybrid demotes to disk buckets, disk
    #: never stages residently in the first place, hbm aborts loudly.
    collect_max_rows: int = 0
    #: join (hash equi-join): the RIGHT/probe record corpus
    #: (``input_path`` is the left/build side).  Record model: a ``.npy``
    #: of (u64 key, u64 payload) rows, payloads < 2^63 (the top bit tags
    #: the side inside the shared engine) — see workloads/join.py
    join_input_path: str = ""
    #: sessionize: the gap (in the timestamp column's own units) above
    #: which consecutive same-key events split into separate sessions
    session_gap: int = 3600
    #: sort: target key-sample size for the range splitters (an
    #: every-kth-row strided sample of the whole input — deterministic,
    #: so distributed processes derive identical splitters with no
    #: collective).  Larger samples balance skewed inputs better at the
    #: cost of one longer strided read
    sort_sample: int = 4096
    #: shuffle transport for the collect engines (map_oxidize_tpu.shuffle):
    #: where shuffled rows stage and what happens at the resident-row cap.
    #: 'hbm' = strictly resident (device buffers / host RAM; the cap is a
    #: hard error), 'disk' = per-process top-bits disk buckets from the
    #: first row (bounded residency at any corpus size), 'hybrid' =
    #: resident until the cap, then a one-way demotion to disk mid-job.
    #: 'auto' routes on corpus size vs the cap: estimated rows
    #: (corpus_bytes // 16) past collect_max_rows pick disk, else hybrid.
    #: Applies to single-controller AND multi-process pair collect (each
    #: distributed process spills its disjoint hash partition locally —
    #: the old at-cap abort is gone); the fold engines bound DISTINCT
    #: keys, not staged rows, and are unaffected.  'pipelined' = hybrid's
    #: placement plus the push cadence: each fed block is hash-partitioned
    #: and eagerly merged into its owner WHILE map still produces (the
    #: prefetcher overlaps map with the exchange rounds; see
    #: ``push_combine`` for the map-side combiner riding it).  'remote' =
    #: staged from the first row like disk, but multi-process fold runs
    #: stage in a shared-filesystem object layout (moxt-shuffle-stage-v1
    #: manifests, ``remote_stage_dir``) from which a surviving peer can
    #: finish the job after a process dies mid-shuffle.
    shuffle_transport: str = "auto"
    #: map-side combiner for the pipelined push shuffle: 'auto' combines
    #: each push window's partial fold states when the transport resolves
    #: to pipelined/remote and the reducer's combine is an associative
    #: scalar monoid (sum/min/max — wordcount pushes ~27k combined
    #: partials instead of millions of raw pairs), 'on' forces it for any
    #: eligible reducer regardless of transport, 'off' disables it.  The
    #: conservation checksums are sum-combine-invariant, so audits stay
    #: green either way; outputs are byte-identical.
    push_combine: str = "auto"
    #: remote transport: the shared-filesystem stage directory every
    #: process of the job can reach.  Empty = derived as
    #: ``<output_path>.stage``.
    remote_stage_dir: str = ""
    #: remote transport: how long a process waits for its peers' final
    #: stage manifests before declaring them dead and taking over their
    #: partitions from the staged objects.
    remote_stage_timeout_s: float = 60.0
    #: job planner (runtime/planner.py + obs/plan.py): 'auto' solves the
    #: tunable knobs from the calibration store's measured curves before
    #: the run and emits the plan document — per-knob value + provenance
    #: (curve/memo/default/pinned) + the predicted wall scored against
    #: the measured wall at finish (``plan/model_error_pct``, a gated
    #: gauge).  Explicit per-knob overrides are honored verbatim and
    #: recorded as ``pinned``.  'off' skips planning entirely (no plan
    #: doc, no ``plan/*`` gauges beyond the dispatch aliases)
    plan: str = "auto"
    #: the shuffle exchange's wire program: 'auto' lets the planner's
    #: chooser (parallel.shuffle.choose_collective) pick from the
    #: calibration store's measured curves — monolithic 'all_to_all' vs
    #: the decomposed 'all_gather' + dynamic-slice resharding
    #: (arXiv:2112.01075) — falling back to all_to_all with a named
    #: reason on a cold/out-of-range/thin store.  Explicit values pin.
    exchange_collective: str = "auto"
    #: chooser evidence floor: sampled latencies required in the exact
    #: payload bucket before a store curve may steer the exchange (below
    #: it the decision falls back with reason 'below min-samples floor')
    calib_min_samples: int = 3

    def validate(self) -> "JobConfig":
        if self.plan not in ("auto", "off"):
            raise ValueError(
                f"plan must be auto|off, got {self.plan!r}")
        if self.tokenizer not in ("ascii", "unicode"):
            raise ValueError(f"tokenizer must be ascii|unicode, got {self.tokenizer!r}")
        if self.backend not in ("auto", "cpu", "tpu"):
            raise ValueError(f"backend must be auto|cpu|tpu, got {self.backend!r}")
        if self.batch_size <= 0 or self.key_capacity <= 0:
            raise ValueError("batch_size and key_capacity must be positive")
        if self.initial_key_capacity <= 0:
            raise ValueError("initial_key_capacity must be positive")
        if self.mapper not in ("auto", "device", "native", "python"):
            raise ValueError(
                f"mapper must be auto|device|native|python, got {self.mapper!r}")
        if self.reduce_mode not in ("auto", "fold", "collect"):
            raise ValueError(
                f"reduce_mode must be auto|fold|collect, got {self.reduce_mode!r}")
        if self.collect_sort not in ("auto", "host", "device"):
            raise ValueError(
                f"collect_sort must be auto|host|device, got {self.collect_sort!r}")
        if self.device_chunk_keys <= 0:
            raise ValueError("device_chunk_keys must be positive")
        if self.num_chunks <= 0 and self.chunk_bytes <= 0:
            raise ValueError("chunk_bytes must be positive (or set num_chunks)")
        if self.pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1 (1 = serial)")
        if not 0 <= self.dispatch_batch <= 1024:
            raise ValueError(
                "dispatch_batch must be 0 (auto) or 1..1024 chunks per "
                f"dispatch, got {self.dispatch_batch}")
        if self.kmeans_device_fit_bytes < 0:
            raise ValueError(
                "kmeans_device_fit_bytes must be >= 0 (0 = probe the device)")
        if self.top_k <= 0 or self.num_map_workers <= 0:
            raise ValueError("top_k and num_map_workers must be positive")
        if self.kmeans_k <= 0 or self.kmeans_iters <= 0:
            raise ValueError("kmeans_k and kmeans_iters must be positive")
        if self.kmeans_precision not in ("highest", "bf16"):
            raise ValueError(f"kmeans_precision must be highest|bf16, "
                             f"got {self.kmeans_precision!r}")
        if self.collect_max_rows < 0:
            raise ValueError("collect_max_rows must be >= 0 (0 = default)")
        if self.session_gap < 1:
            raise ValueError("session_gap must be >= 1 (timestamp units)")
        if self.sort_sample < 1:
            raise ValueError("sort_sample must be >= 1 sampled keys")
        from map_oxidize_tpu.shuffle.base import TRANSPORTS

        if self.shuffle_transport not in TRANSPORTS:
            raise ValueError(
                f"shuffle_transport must be one of {'|'.join(TRANSPORTS)}, "
                f"got {self.shuffle_transport!r}")
        if self.push_combine not in ("auto", "on", "off"):
            raise ValueError(
                f"push_combine must be auto|on|off, "
                f"got {self.push_combine!r}")
        # literal mirror of parallel.shuffle.EXCHANGE_COLLECTIVES — that
        # module imports jax at top level, and validate() must stay
        # importable on the jax-free CLI paths (a parity test pins the
        # two tuples)
        if self.exchange_collective not in ("auto", "all_to_all",
                                            "all_gather"):
            raise ValueError(
                "exchange_collective must be one of "
                "auto|all_to_all|all_gather, "
                f"got {self.exchange_collective!r}")
        if self.calib_min_samples < 1:
            raise ValueError(
                "calib_min_samples must be >= 1 sampled latencies")
        if self.remote_stage_timeout_s <= 0:
            raise ValueError(
                "remote_stage_timeout_s must be positive seconds")
        # disk + collect_sort='device' is rejected by the single-chip
        # engine, not here: on a sharded mesh the combination is valid
        # (collect_sort applies to the single-chip engine only) and only
        # the engine knows which path the run resolves to
        if self.progress_interval_s <= 0:
            raise ValueError("progress_interval_s must be positive")
        if self.hbm_sample_s < 0:
            raise ValueError("hbm_sample_s must be >= 0 (0 = off)")
        if self.stall_warn_factor < 0:
            raise ValueError("stall_warn_factor must be >= 0 (0 = off)")
        if self.obs_port < -1 or self.obs_port > 65535:
            raise ValueError(
                "obs_port must be -1 (off), 0 (ephemeral), or a port")
        if (self.obs_port > 0 and self.dist_num_processes > 1
                and self.obs_port + self.dist_num_processes - 1 > 65535):
            raise ValueError(
                f"obs_port {self.obs_port} + the per-process offset for "
                f"{self.dist_num_processes} processes exceeds 65535; "
                "use a lower port or 0 (ephemeral)")
        if self.obs_sample_s < 0:
            raise ValueError("obs_sample_s must be >= 0 (0 = off)")
        if not 0 < self.host_sample_hz <= 1000:
            raise ValueError(
                "host_sample_hz must be in (0, 1000] samples/sec, got "
                f"{self.host_sample_hz}")
        if self.slo_rules:
            from map_oxidize_tpu.obs.slo import load_rules

            try:
                load_rules(self.slo_rules)
            except (OSError, ValueError) as e:
                raise ValueError(f"invalid slo_rules: {e}") from e
        from map_oxidize_tpu.workloads.distinct import HLL_P_MIN, HLL_P_MAX

        if not HLL_P_MIN <= self.hll_precision <= HLL_P_MAX:
            raise ValueError(
                f"hll_precision must be in [{HLL_P_MIN}, {HLL_P_MAX}], "
                f"got {self.hll_precision}")
        if self.dist_coordinator and (
                self.dist_num_processes < 2 or self.dist_process_id < 0
                or self.dist_process_id >= self.dist_num_processes):
            raise ValueError(
                "distributed mode needs dist_num_processes >= 2 and "
                "0 <= dist_process_id < dist_num_processes")
        return self


@dataclass
class FleetConfig:
    """Fleet observatory configuration (``python -m map_oxidize_tpu obs
    fleet``): the collector daemon that polls any number of obs
    endpoints (one-shot jobs, distributed-run processes, resident
    servers), merges their telemetry into one fleet model, serves the
    fleet HTTP plane, and optionally archives the fleet series to disk
    (:mod:`map_oxidize_tpu.obs.fleet`)."""

    #: explicit endpoints to watch ("http://host:port" or "host:port");
    #: explicit targets never depart the model
    targets: list[str] = field(default_factory=list)
    #: a MOXT_OBS_PORT_FILE-format file ("<process> <port>" lines) to
    #: derive 127.0.0.1 targets from (the existing discovery hook)
    port_file: str = ""
    #: resident-server spool directories: each one's ``obs_port.json``
    #: (written by the server at start) names a target
    spool_dirs: list[str] = field(default_factory=list)
    #: well-known port-record spool to scan for live processes
    #: (``moxt-obs-port-v1`` records published by every serving obs
    #: server): "" = $MOXT_OBS_SPOOL / the per-user tempdir default,
    #: "none" disables scanning
    discover_dir: str = ""
    #: the collector's own HTTP bind (fleet /metrics /status /alerts
    #: /series /healthz); 0 = ephemeral (logged, and written to
    #: MOXT_OBS_PORT_FILE as "fleet <port>")
    host: str = "127.0.0.1"
    port: int = 0
    #: seconds between scrape sweeps over the target set
    poll_interval_s: float = 1.0
    #: a target unreachable (or refusing payloads) for this long is
    #: marked stale — a fleet alert, never a crash
    stale_after_s: float = 30.0
    #: persistent fleet series archive (``moxt-archive-v1``): a bounded
    #: ring of JSONL segments under this directory, plus the latest
    #: fleet status/alerts/target snapshots for post-mortem reads
    #: (``obs trend/top/where --archive``).  None disables
    archive_dir: str | None = None
    #: archive bounds: records per segment file, and segments kept —
    #: the ring overwrites oldest-first, so the archive never grows
    #: past segment_records * max_segments samples
    archive_segment_records: int = 512
    archive_max_segments: int = 16
    #: fleet SLO rule set (same spelling as JobConfig.slo_rules); the
    #: built-in defaults are obs.fleet.FLEET_RULES (target staleness,
    #: per-target HBM watermark fraction, scrape refusals)
    slo_rules: str | None = None

    def validate(self) -> "FleetConfig":
        if not 0 <= self.port <= 65535:
            raise ValueError("fleet port must be 0 (ephemeral) or a port")
        if self.poll_interval_s <= 0:
            raise ValueError("poll_interval_s must be positive")
        if self.stale_after_s <= 0:
            raise ValueError("stale_after_s must be positive")
        if self.archive_segment_records < 1 or self.archive_max_segments < 2:
            raise ValueError("archive needs >= 1 record per segment and "
                             ">= 2 segments (the ring rotates into the "
                             "next segment before pruning the oldest)")
        if self.slo_rules:
            from map_oxidize_tpu.obs.fleet import FLEET_RULES
            from map_oxidize_tpu.obs.slo import load_rules

            try:
                load_rules(self.slo_rules, defaults=FLEET_RULES)
            except (OSError, ValueError) as e:
                raise ValueError(f"invalid fleet slo_rules: {e}") from e
        return self


@dataclass
class ServeConfig:
    """Resident job service configuration (``python -m map_oxidize_tpu
    serve``): the long-lived server that holds the mesh, warm jit caches,
    and opened corpora across jobs, and multiplexes submitted jobs over
    the existing drivers.  Per-JOB knobs stay on :class:`JobConfig` —
    clients send overrides with each submission; this object configures
    the server process itself."""

    #: HTTP bind: the obs telemetry plane (/metrics /status /series)
    #: plus the job endpoints (/jobs, submit, cancel, shutdown).
    #: 0 = ephemeral (logged, and written to ``MOXT_OBS_PORT_FILE``)
    host: str = "127.0.0.1"
    port: int = 0
    #: concurrent job slots: worker threads multiplexing admitted jobs
    #: over the pipeline (each runs a full driver under its own Obs)
    workers: int = 2
    #: bounded submission queue: submissions past it are REJECTED with a
    #: named reason (``queue_full``), never silently dropped
    max_queue: int = 16
    #: HBM admission budget in bytes: jobs whose estimated device working
    #: set cannot ever fit are rejected, jobs that do not fit NEXT TO the
    #: currently running set are deferred until HBM frees.  0 = probe the
    #: visible devices' reported memory (sum of bytes_limit); devices
    #: without memory stats (CPU) leave admission open
    hbm_budget_bytes: int = 0
    #: server working directory: per-job artifact spool
    #: (``<spool>/<job_id>/`` holds the metrics doc, output, and crash
    #: bundles) plus the default ledger location
    spool_dir: str = "moxt-serve-spool"
    #: run ledger shared by every job the server finishes (per-job
    #: entries — the same ledger ``obs diff`` reads); empty = ``<spool>/
    #: ledger``; "none" disables
    ledger_dir: str = ""
    #: cached-corpus idle eviction: an opened corpus unused by any job
    #: for this long is closed (page-cache warmth and the fd are
    #: released); 0 disables eviction
    idle_evict_s: float = 300.0
    #: graceful-drain budget: on shutdown, running + already-admitted
    #: jobs get this long to finish before remaining ones are cancelled
    drain_timeout_s: float = 60.0
    #: server-level telemetry cadence (the time-series ring + HBM
    #: sampler on the server's own obs bundle)
    obs_sample_s: float = 1.0
    #: SLO rule set for the SERVER's alert evaluator (serve-scoped
    #: rules see the server-lifetime registry: queue-wait p95, warm
    #: recompiles, HBM watermark); same spelling as JobConfig.slo_rules
    #: ("" = built-in defaults).  Per-job rules ride job submissions as
    #: a config override instead
    slo_rules: str = ""
    #: per-job silent-heartbeat/series cadence (gives every job's /jobs
    #: row live rows/sec without --progress); 0 disables
    job_sample_s: float = 0.5
    #: persistent calibration store shared by every job the server runs
    #: (measured collective bytes/latency + program dispatch/compute
    #: accumulated across jobs AND server restarts — the warm-figures
    #: substrate); empty = ``<spool>/calib``; "none" disables
    calib_dir: str = ""
    #: terminal-job retention: /jobs lists at most this many finished/
    #: rejected jobs; older ones are dropped from memory (their spool
    #: artifacts remain on disk) so a resident process stays bounded
    max_history: int = 512

    def validate(self) -> "ServeConfig":
        if not 0 <= self.port <= 65535:
            raise ValueError("serve port must be 0 (ephemeral) or a port")
        if self.workers < 1:
            raise ValueError("serve workers must be >= 1")
        if self.max_queue < 1:
            raise ValueError("serve max_queue must be >= 1")
        if self.hbm_budget_bytes < 0:
            raise ValueError("hbm_budget_bytes must be >= 0 (0 = probe)")
        if self.idle_evict_s < 0 or self.drain_timeout_s < 0:
            raise ValueError("idle_evict_s and drain_timeout_s must be "
                             ">= 0")
        if self.obs_sample_s < 0 or self.job_sample_s < 0:
            raise ValueError("obs_sample_s and job_sample_s must be >= 0")
        if self.max_history < 1:
            raise ValueError("max_history must be >= 1 (a finished job "
                             "must stay visible to its waiting client)")
        if self.slo_rules:
            from map_oxidize_tpu.obs.slo import load_rules

            try:
                load_rules(self.slo_rules)
            except (OSError, ValueError) as e:
                raise ValueError(f"invalid slo_rules: {e}") from e
        if not self.spool_dir:
            raise ValueError("spool_dir must be set")
        return self
