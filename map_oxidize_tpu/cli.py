"""Command-line driver.

The reference has no CLI at all — path and worker counts are hardcoded locals
(``/root/reference/src/main.rs:10-13``) and the binary must be run in a
directory containing ``shakes.txt``.  Usage here:

    python -m map_oxidize_tpu wordcount shakes.txt --top-k 10
    python -m map_oxidize_tpu bigram corpus.txt --backend tpu
    python -m map_oxidize_tpu obs merge trace.json     # shard merge
    python -m map_oxidize_tpu obs diff --ledger-dir runs/  # regression diff
    python -m map_oxidize_tpu obs fleet --spool spool/ # fleet observatory
    python -m map_oxidize_tpu serve --port 8321        # resident job server
    python -m map_oxidize_tpu submit --url http://127.0.0.1:8321 \\
        wordcount corpus.txt --wait                    # enqueue a job
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.utils.logging import configure, get_logger

_log = get_logger(__name__)


def _dispatch_batch_arg(v: str) -> int:
    """``--dispatch-batch {auto,N}``: 'auto' -> 0 (the config sentinel
    for measured auto-pick), else a positive chunk count."""
    if v == "auto":
        return 0
    try:
        n = int(v)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"--dispatch-batch takes 'auto' or a positive integer, got {v!r}")
    if n < 1:
        raise argparse.ArgumentTypeError(
            "--dispatch-batch must be >= 1 (or 'auto')")
    return n


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="map_oxidize_tpu",
        description="TPU-native MapReduce (capabilities of map-oxidize, rebuilt for JAX/XLA)",
    )
    # the RUNNING package's version (a dist-info lookup would report a
    # stale installed copy when a newer checkout shadows it on sys.path)
    from map_oxidize_tpu import __version__

    p.add_argument("--version", action="version",
                   version=f"%(prog)s {__version__}")
    # single source of truth: the same tuple the serve scheduler and the
    # submit CLI consume — a workload added to config.WORKLOADS appears
    # in every allowlist at once (tests assert they agree)
    from map_oxidize_tpu.config import WORKLOADS

    p.add_argument("workload", choices=list(WORKLOADS),
                   help="built-in workload to run")
    p.add_argument("input", help="input path: text corpus (reference: "
                                 "shakes.txt), a .npy points file for "
                                 "kmeans, or a .npy (u64 key, u64 "
                                 "payload) records file for "
                                 "sort/join/sessionize")
    p.add_argument("--output", default="final_result.txt",
                   help="final result path (reference: final_result.txt)")
    p.add_argument("--top-k", type=int, default=10,
                   help="top-k words to report (reference: 10)")
    p.add_argument("--map-workers", type=int, default=8,
                   help="host map threads (reference: 8)")
    p.add_argument("--num-chunks", type=int, default=0,
                   help="fixed chunk count with round-robin line chunking "
                        "(reference compat mode); 0 = streaming byte ranges")
    p.add_argument("--chunk-mb", type=int, default=32, help="streamed chunk size")
    p.add_argument("--batch-size", type=int, default=1 << 20,
                   help="device feed batch rows")
    p.add_argument("--pipeline-depth", type=int, default=2,
                   help="bounded-prefetch pipeline depth: chunks of host "
                        "read+tokenize allowed to run ahead of the device "
                        "feed (1 = strictly serial; outputs are "
                        "byte-identical at any depth)")
    p.add_argument("--dispatch-batch", type=_dispatch_batch_arg, default=0,
                   metavar="{auto,N}",
                   help="logical chunks retired per device launch on "
                        "streamed paths (lax.scan-batched dispatch, "
                        "amortizing the ~150-250ms/launch floor). 'auto' "
                        "(default) picks B at job start from the measured "
                        "dispatch floor and per-chunk produce/compute "
                        "times, capped by the HBM budget; the chosen B is "
                        "recorded in metrics and the run ledger. Outputs "
                        "are identical at any B")
    p.add_argument("--plan", choices=["auto", "off"], default="auto",
                   help="job planner: auto (default) solves the tunable "
                        "knobs (dispatch batch, pipeline depth, chunk "
                        "size, shuffle transport, sort sample) from the "
                        "calibration store's measured curves before the "
                        "run and records the plan — per-knob provenance "
                        "(curve/memo/default/pinned) plus a predicted "
                        "wall scored against the measured wall "
                        "(plan/model_error_pct, gated by obs diff). "
                        "Explicit knob flags stay authoritative and are "
                        "recorded as pinned. off skips planning")
    p.add_argument("--key-capacity", type=int, default=1 << 22,
                   help="max distinct keys on device")
    p.add_argument("--backend", choices=["auto", "cpu", "tpu"], default="auto")
    p.add_argument("--num-shards", type=int, default=0,
                   help="device mesh shards (0 = all local devices, 1 = single)")
    p.add_argument("--tokenizer", choices=["ascii", "unicode"], default="ascii")
    p.add_argument("--mapper", choices=["auto", "device", "native", "python"],
                   default="auto",
                   help="map-phase placement: TPU kernel (single or sharded), "
                        "C++ host loop, or pure Python (auto: native — the "
                        "measured winner on a remote-attached chip)")
    p.add_argument("--no-native", action="store_true",
                   help="disable the C++ tokenizer hot loop")
    p.add_argument("--reduce-mode", choices=["auto", "fold", "collect"],
                   default="auto",
                   help="reduce engine: streaming device fold vs host "
                        "collect+one-sort (auto: by the workload's key-space "
                        "width — collect for bigram, fold otherwise)")
    p.add_argument("--collect-sort", choices=["auto", "host", "device"],
                   default="auto",
                   help="inverted-index pair sort placement (auto: host — "
                        "the measured winner on a remote-attached chip)")
    p.add_argument("--collect-max-rows", type=int, default=0,
                   help="resident-row cap for the collect engines before "
                        "the disk-bucket spill (counts, values, and "
                        "(key,doc) pairs all spill; the sharded device "
                        "engine demotes to the host engine first); "
                        "0 = engine defaults")
    from map_oxidize_tpu.shuffle.base import TRANSPORTS

    p.add_argument("--shuffle-transport",
                   choices=list(TRANSPORTS), default="auto",
                   help="where collect-engine shuffle rows stage: hbm = "
                        "strictly resident (the row cap is a hard error), "
                        "disk = per-process top-bits disk buckets from the "
                        "first row (bounded residency; distributed "
                        "processes spill their disjoint hash partitions "
                        "locally), hybrid = resident until the cap then "
                        "demote to disk mid-job, pipelined = hybrid's "
                        "placement plus an eager push cadence (each "
                        "mapped block is partitioned and merged while "
                        "map still produces; see --push-combine), "
                        "remote = stage in a shared-filesystem object "
                        "layout a surviving peer can finish the job "
                        "from after a process dies mid-shuffle. auto "
                        "routes on corpus size vs --collect-max-rows "
                        "(estimated rows past the cap pick disk, else "
                        "hybrid)")
    p.add_argument("--push-combine", choices=["auto", "on", "off"],
                   default="auto",
                   help="map-side combiner for the pipelined push "
                        "shuffle: combine each push window's partial "
                        "fold states (sum/min/max reducers) before the "
                        "exchange, so aggregation workloads push "
                        "combined partials instead of raw pairs. auto = "
                        "on when the transport resolves to pipelined/"
                        "remote; outputs are byte-identical either way")
    p.add_argument("--remote-stage-dir", default="",
                   help="remote transport: shared-filesystem stage "
                        "directory every process can reach (default: "
                        "<output>.stage)")
    p.add_argument("--remote-stage-timeout", type=float, default=60.0,
                   help="remote transport: seconds to wait for peers' "
                        "final stage manifests before declaring them "
                        "dead and taking over their partitions")
    p.add_argument("--join-input", default="",
                   help="join: the RIGHT/probe record corpus (.npy of "
                        "(u64 key, u64 payload) rows, payloads < 2^63; "
                        "the positional input is the left/build side)")
    p.add_argument("--session-gap", type=int, default=3600,
                   help="sessionize: consecutive same-key events more "
                        "than this far apart (timestamp units) start a "
                        "new session")
    p.add_argument("--sort-sample", type=int, default=4096,
                   help="sort: target key-sample size for the range "
                        "splitters (deterministic strided sample; "
                        "larger balances skew better)")
    p.add_argument("--rescan-full", action="store_true",
                   help="hash-only mode: rescan the whole corpus when "
                        "resolving winner strings (extends the collision "
                        "byte-check to every occurrence) instead of "
                        "stopping once all queried keys are found")
    p.add_argument("--hll-precision", type=int, default=14,
                   help="distinct: HyperLogLog precision p (2^p registers; "
                        "rse ~1.04/sqrt(2^p))")
    p.add_argument("--kmeans-k", type=int, default=16,
                   help="k-means cluster count (init: first k points)")
    p.add_argument("--kmeans-iters", type=int, default=1,
                   help="k-means iterations")
    p.add_argument("--kmeans-precision", choices=["highest", "bf16"],
                   default="highest",
                   help="device-path matmul precision: f32-emulating "
                        "HIGHEST (oracle parity) or native single-pass "
                        "bf16 MXU matmuls with f32 accumulation")
    p.add_argument("--kmeans-fit-bytes", type=int, default=0,
                   help="kmeans mapper=auto device-fit budget in bytes; "
                        "past it the job streams through the device "
                        "(0 = probe the device's memory)")
    p.add_argument("--dist-coordinator", default="",
                   help="multi-host: coordination address host:port (same "
                        "on every process); enables jax.distributed")
    p.add_argument("--dist-processes", type=int, default=0,
                   help="multi-host: total process count")
    p.add_argument("--dist-process-id", type=int, default=-1,
                   help="multi-host: this process's id (0-based)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="directory for resumable map-output checkpoints "
                        "(kmeans: per-iteration snapshots; a SUCCESSFUL "
                        "run deletes its snapshot, so continuing training "
                        "past a completed run needs --keep-intermediates "
                        "on the earlier run)")
    p.add_argument("--trace-dir", default=None,
                   help="capture a jax.profiler trace of the run into this "
                        "directory (TensorBoard-compatible)")
    p.add_argument("--trace-out", default=None,
                   help="capture framework spans (phases, per-block feeds, "
                        "spills, demotions) and write Chrome trace-event "
                        "JSON here — load in chrome://tracing or Perfetto")
    p.add_argument("--metrics-out", default=None,
                   help="write the structured metrics document (phase "
                        "timings, counters, gauges, histograms) here as "
                        "JSON")
    p.add_argument("--ledger-dir", default=None,
                   help="append this job's summary (metrics, phase times, "
                        "config hash, version) to <dir>/ledger.jsonl — the "
                        "history `obs diff` and `bench.py --gate` compare "
                        "against")
    p.add_argument("--crash-dir", default=None,
                   help="failure flight recorder: on an abort, dump a "
                        "post-mortem bundle (config, metrics-so-far, "
                        "open-span-closed trace, traceback) under this "
                        "directory before the error propagates")
    p.add_argument("--progress", action="store_true",
                   help="log periodic progress lines (rows/sec, percent "
                        "done, ETA, phase) for long streamed jobs")
    p.add_argument("--progress-interval", type=float, default=10.0,
                   help="minimum seconds between --progress lines")
    p.add_argument("--hbm-sample-interval", type=float, default=0.0,
                   help="live HBM sampler: seconds between background "
                        "device.memory_stats() reads (hbm/live_bytes "
                        "watermark gauges, heartbeat hbm= field, crash "
                        "bundles); 0 = off")
    p.add_argument("--stall-factor", type=float, default=0.0,
                   help="stall detector: warn with the open span names "
                        "when no chunk completes within this multiple of "
                        "the median chunk time; 0 = off")
    p.add_argument("--obs-port", type=int, default=-1,
                   help="live telemetry: serve /metrics (Prometheus), "
                        "/status (JSON), and /series on this 127.0.0.1 "
                        "port while the job runs (0 = ephemeral, port "
                        "logged; distributed runs serve one port per "
                        "process); -1 = off.  Watch with "
                        "`python -m map_oxidize_tpu obs top --url ...`")
    p.add_argument("--obs-sample-interval", type=float, default=0.0,
                   help="time-series recorder: seconds between ring-"
                        "buffer snapshots of every counter/gauge/"
                        "histogram quantile (metrics doc `series` "
                        "section + /series endpoint); 0 = off unless "
                        "--obs-port is set (then 1s)")
    p.add_argument("--obs-spool", default=None,
                   help="fleet-discovery spool: where the live obs "
                        "server publishes its port record so `obs "
                        "fleet` finds this job without flags (default: "
                        "$MOXT_OBS_SPOOL or a well-known per-user "
                        "tempdir; 'none' disables publishing)")
    p.add_argument("--slo-rules", default=None,
                   help="SLO/alerting rule set for the live plane: a "
                        "JSON file path or inline JSON (a list extends "
                        "the built-in defaults; {\"defaults\": false, "
                        "\"rules\": [...]} replaces them).  Evaluated "
                        "whenever the time-series recorder runs; firing "
                        "rules emit [alert] lines, serve at /alerts, "
                        "and write incident bundles")
    p.add_argument("--incident-dir", default=None,
                   help="where SLO incident bundles land (series window "
                        "+ status snapshot per alert firing); default: "
                        "the --crash-dir, if any")
    p.add_argument("--no-data-audit", action="store_true",
                   help="disable the data-plane observatory (per-"
                        "partition row-conservation audits, key-skew "
                        "telemetry, data/* gauges — obs/dataplane.py); "
                        "on by default, pure host-side accounting")
    p.add_argument("--profile-dir", default=None,
                   help="where on-demand POST /profile deep captures "
                        "land (jax.profiler device trace + host "
                        "sampling stacks); default: next to the crash "
                        "bundles / metrics document")
    p.add_argument("--host-sample-hz", type=float, default=50.0,
                   help="host sampling profiler rate during a /profile "
                        "capture (Python stacks per second)")
    p.add_argument("--calib-dir", default=None,
                   help="persistent calibration store: accumulate this "
                        "run's measured collective bytes/latency and "
                        "per-program dispatch/compute into "
                        "<dir>/calib.json (merged atomically across "
                        "runs; render with `obs calib`)")
    p.add_argument("--exchange-collective",
                   choices=["auto", "all_to_all", "all_gather"],
                   default="auto",
                   help="shuffle exchange wire program: auto (default) "
                        "lets the planner pick from the calibration "
                        "store's measured curves (monolithic all_to_all "
                        "vs the decomposed all_gather+slice resharding; "
                        "falls back to all_to_all with a named reason on "
                        "a cold store); explicit values pin.  Outputs "
                        "are byte-identical either way")
    p.add_argument("--calib-min-samples", type=int, default=3,
                   help="chooser evidence floor: sampled latencies "
                        "required in the exact payload bucket before a "
                        "store curve may steer the exchange collective")
    p.add_argument("--keep-intermediates", action="store_true")
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("-q", "--quiet", action="store_true")
    return p


def config_from_args(args: argparse.Namespace) -> JobConfig:
    return JobConfig(
        input_path=args.input,
        output_path=args.output,
        top_k=args.top_k,
        num_map_workers=args.map_workers,
        num_chunks=args.num_chunks,
        chunk_bytes=args.chunk_mb * 1024 * 1024,
        batch_size=args.batch_size,
        pipeline_depth=args.pipeline_depth,
        dispatch_batch=args.dispatch_batch,
        key_capacity=args.key_capacity,
        backend=args.backend,
        num_shards=args.num_shards,
        tokenizer=args.tokenizer,
        mapper="python" if args.no_native and args.mapper == "auto"
               else args.mapper,
        use_native=not args.no_native,
        reduce_mode=args.reduce_mode,
        collect_sort=args.collect_sort,
        dist_coordinator=args.dist_coordinator,
        dist_num_processes=args.dist_processes,
        dist_process_id=args.dist_process_id,
        checkpoint_dir=args.checkpoint_dir,
        keep_intermediates=args.keep_intermediates,
        trace_dir=args.trace_dir,
        trace_out=args.trace_out,
        metrics_out=args.metrics_out,
        ledger_dir=args.ledger_dir,
        crash_dir=args.crash_dir,
        progress=args.progress,
        progress_interval_s=args.progress_interval,
        hbm_sample_s=args.hbm_sample_interval,
        stall_warn_factor=args.stall_factor,
        obs_port=args.obs_port,
        obs_sample_s=args.obs_sample_interval,
        obs_spool=args.obs_spool,
        slo_rules=args.slo_rules,
        incident_dir=args.incident_dir,
        data_audit=not args.no_data_audit,
        profile_dir=args.profile_dir,
        host_sample_hz=args.host_sample_hz,
        calib_dir=args.calib_dir,
        rescan_full=args.rescan_full,
        join_input_path=args.join_input,
        session_gap=args.session_gap,
        sort_sample=args.sort_sample,
        collect_max_rows=args.collect_max_rows,
        shuffle_transport=args.shuffle_transport,
        push_combine=args.push_combine,
        remote_stage_dir=args.remote_stage_dir,
        remote_stage_timeout_s=args.remote_stage_timeout,
        plan=args.plan,
        exchange_collective=args.exchange_collective,
        calib_min_samples=args.calib_min_samples,
        hll_precision=args.hll_precision,
        kmeans_k=args.kmeans_k,
        kmeans_iters=args.kmeans_iters,
        kmeans_precision=args.kmeans_precision,
        kmeans_device_fit_bytes=args.kmeans_fit_bytes,
    ).validate()


def main(argv: list[str] | None = None) -> int:
    try:
        return _main(argv)
    except BrokenPipeError:
        # the downstream consumer closed the pipe early (`... | head` —
        # exactly how the obs report commands are meant to be used, and
        # how check.sh drives them): the reader got everything it
        # wanted, so this is success, not an error.  Point stdout at
        # devnull so the interpreter-exit flush cannot raise again.
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
        return 0


def _main(argv: list[str] | None = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "obs":
        # artifact tools (shard merge, ledger diff): pure host-side file
        # work — no input corpus, no jax, no backend init
        from map_oxidize_tpu.obs.cli import obs_main

        return obs_main(argv[1:])
    if argv and argv[0] == "serve":
        # resident job server (serve/): long-lived process, jobs arrive
        # over HTTP — none of the one-shot workload flags below apply
        from map_oxidize_tpu.serve.cli import serve_main

        return serve_main(argv[1:])
    if argv and argv[0] == "submit":
        # client side: pure HTTP, no jax, no backend init
        from map_oxidize_tpu.serve.cli import submit_main

        return submit_main(argv[1:])
    args = build_parser().parse_args(argv)
    configure(logging.DEBUG if args.verbose
              else logging.WARNING if args.quiet else logging.INFO)
    try:
        config = config_from_args(args)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not os.path.isfile(config.input_path):
        print(f"error: cannot open input {config.input_path!r}", file=sys.stderr)
        return 2
    if args.workload == "join" and not os.path.isfile(
            config.join_input_path):
        print(f"error: join needs --join-input; cannot open "
              f"{config.join_input_path!r}", file=sys.stderr)
        return 2
    if config.keep_intermediates and not config.checkpoint_dir:
        _log.warning("--keep-intermediates has no effect without "
                     "--checkpoint-dir (there are no intermediates: map "
                     "outputs stay on device)")
    if config.dist_coordinator:
        from map_oxidize_tpu.parallel.distributed import (
            init_distributed,
            run_distributed_job,
        )

        init_distributed(config.dist_coordinator,
                         config.dist_num_processes, config.dist_process_id)
        r = run_distributed_job(config, args.workload)
        if args.workload in ("sort", "join", "sessionize"):
            print(r.top_report(config.top_k)
                  + f" ({config.dist_num_processes} processes)")
            if config.output_path:
                from map_oxidize_tpu.parallel.distributed import (
                    partition_output_path,
                )

                _log.info(
                    "process %d wrote its partition to %s (the %d parts "
                    "concatenate%s)", config.dist_process_id,
                    partition_output_path(config.output_path,
                                          config.dist_process_id,
                                          config.dist_num_processes),
                    config.dist_num_processes,
                    ", process-major, into the globally sorted artifact"
                    if args.workload == "sort" else " disjointly")
            return 0
        if args.workload == "kmeans":
            c = r.centroids
            print(f"k-means: {c.shape[0]} centroids, dim {c.shape[1]}, "
                  f"{config.kmeans_iters} iterations "
                  f"({config.dist_num_processes} processes)")
            return 0
        if config.output_path and args.workload != "distinct":
            from map_oxidize_tpu.parallel.distributed import (
                partition_output_path,
            )

            _log.info(
                "process %d wrote its hash partition to %s (concatenate "
                "the %d parts and sort for the single-file artifact)",
                config.dist_process_id,
                partition_output_path(config.output_path,
                                      config.dist_process_id,
                                      config.dist_num_processes),
                config.dist_num_processes)
        if args.workload == "distinct":
            print(f"distinct tokens ~ {r.estimate:,.0f} "
                  f"({config.dist_num_processes} processes)")
            return 0
        unit = "docs" if args.workload == "invertedindex" else ""
        print(f"Top {config.top_k} keys ({r.n_keys} distinct):")
        for h, word, c in r.top:
            name = word.decode("utf-8", "replace") if word is not None \
                else f"{h:#018x}"
            print(f"{name}: {c}{' ' + unit if unit else ''}")
        return 0

    from map_oxidize_tpu.runtime import run_job

    result = run_job(config, args.workload)
    print(result.top_report(config.top_k))  # reference stdout, main.rs:188-191
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
