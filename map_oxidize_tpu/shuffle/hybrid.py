"""Hybrid transport: resident speed, disk safety net.

Starts on the HBM/RAM-resident path and makes the one-way
RESIDENT -> SPILLED transition when the resident row count crosses the
cap — the engine drains its resident state into disk buckets (under a
``shuffle/demote`` span, :func:`map_oxidize_tpu.shuffle.base.record_demotion`)
and stages every later block there.  This names the demotion ladder the
single-controller engines already climb (device buffers -> host engine
-> disk buckets) and extends it to the distributed pair collect, whose
old behavior at the cap was a hard abort ("per-process spill is not yet
implemented" — dead as of this transport).

Demotion trips on a count every participant agrees on: the distributed
engine feeds it the lockstep-summed GLOBAL row count (identical on every
process by construction), so all processes demote in the same round and
the collective program sequence stays SPMD-consistent."""

from __future__ import annotations

from map_oxidize_tpu.shuffle.base import ShuffleTransport


class HybridTransport(ShuffleTransport):
    """RESIDENT until the cap trips, then SPILLED for good."""

    name = "hybrid"

    def admit(self, resident_rows: int, max_rows: int, engine: str) -> str:
        if self.spilled_state:
            return "spill"
        if resident_rows > max_rows:
            self.spilled_state = True
            return "demote"
        return "resident"
