"""HBM transport: strictly resident shuffle staging.

This is today's default mechanism given a name: rows live in the device
accumulators / host RAM stage, cross-shard routing is the jitted
``all_to_all`` exchange (:func:`map_oxidize_tpu.parallel.shuffle._exchange`
and the engines' ``route_append`` programs built on it), and the payload
accounting identity is :func:`map_oxidize_tpu.parallel.shuffle.exchange_payload_bytes`
— none of which this class re-implements; the engines keep owning their
compiled programs (zero behavior change on the resident path, and the
``comms/*/bytes`` ledger gate keeps proving it).

What ``hbm`` adds is the *strict* placement contract: the resident row
cap is a hard error, never a silent demotion — the right default for
latency-pinned serving jobs where a surprise disk drain mid-job is worse
than an up-front rejection.  The error names the escape hatches
(``--shuffle-transport disk|hybrid``)."""

from __future__ import annotations

from map_oxidize_tpu.shuffle.base import ShuffleTransport


class HbmTransport(ShuffleTransport):
    """RESIDENT-only: never trips to disk; the cap raises."""

    name = "hbm"

    def admit(self, resident_rows: int, max_rows: int, engine: str) -> str:
        if resident_rows > max_rows:
            raise self.cap_error(resident_rows, max_rows, engine)
        return "resident"
