"""Pipelined push transport: shuffle overlapped behind map.

Exoshuffle's core result (arXiv:2203.05072, PAPERS.md) is that the
barrier between map and shuffle is an artifact of the execution model,
not the dataflow: each mapped block's rows already know their owner
(hash partition), so they can be *pushed* and eagerly merged while map
is still producing the next block.  PR 15's critpath observatory prices
exactly this waste per run — the ``map_shuffle_overlapped`` what-if
replays the schedule with the exchange hidden behind map — and this
transport banks it:

* **placement** — identical to hybrid: resident until the cap, then the
  one-way demotion to disk buckets.  What changes is the *verdict name*:
  ``admit`` answers ``"push"`` instead of ``"resident"`` while under the
  cap (the PUSHING state), which engines treat as resident placement and
  drivers treat as the eager-merge cadence signal.
* **push cadence** — the driver's half: map production runs in the
  bounded prefetcher (``runtime/pipeline.py``, spans named
  ``push/produce`` / ``push/feed_wait``) so block i+1's host map
  overlaps block i's partition+merge; the distributed lockstep loop
  keeps its one flag-psum per round, so push rounds stay SPMD-consistent
  with demotion cadence.
* **map-side combiner** — :func:`combine_map_output` sum-combines the
  partial fold states of one push window before the exchange (wordcount:
  ~27k distinct keys vs millions of raw pairs), so aggregation workloads
  push combined partials instead of raw rows.  The PR 16 conservation
  checksums (``sum(mix64(key) * value) mod 2^64``,
  :mod:`map_oxidize_tpu.obs.dataplane`) are sum-combine-invariant by
  design, so the audits stay green with the combiner on.

Evidence contract (on top of the base transport counters):
``shuffle/push_rounds`` / ``shuffle/push_rows`` (eager merges and the
rows they carried), ``shuffle/push_combined_in`` / ``_out`` /
``shuffle/push_bytes_saved`` (combiner reduction ratio), and the
``pipeline/shuffle_overlap_ratio`` gauge — the fraction of host map
time the push pipeline actually hid, the number that must move the
``map_shuffle_overlapped`` what-if's predicted saving toward zero.
"""

from __future__ import annotations

import numpy as np

from map_oxidize_tpu.shuffle.base import ShuffleTransport

#: reducer combine monoids the map-side combiner can pre-apply: the
#: combine must be associative AND idempotent under regrouping — exactly
#: the host collect-reduce engine's vocabulary (sum of partials, min of
#: partials, max of partials all equal the combine over raw rows)
COMBINABLE = {"sum": np.add, "min": np.minimum, "max": np.maximum}

#: nominal staged bytes per scalar fold row (u64 key + i32 value) — the
#: ``shuffle/push_bytes_saved`` accounting unit
FOLD_ROW_BYTES = 12


class PipelinedTransport(ShuffleTransport):
    """PUSHING until the cap trips, then SPILLED for good (hybrid's
    placement ladder with the eager-push verdict under the cap)."""

    name = "pipelined"

    def admit(self, resident_rows: int, max_rows: int, engine: str) -> str:
        if self.spilled_state:
            return "spill"
        if resident_rows > max_rows:
            self.spilled_state = True
            return "demote"
        return "push"


def combine_map_output(out, combine: str):
    """Sum-combine one push window's partial fold states: collapse
    duplicate keys in a scalar-fold :class:`~map_oxidize_tpu.api.MapOutput`
    with the reducer's combine monoid (``COMBINABLE``), returning
    ``(combined_out, rows_in, rows_out)``.

    ``values=None`` (the hash-only implicit-ones form) combines to
    explicit int32 counts under ``sum``.  The output carries the input's
    dictionary and ``records_in`` unchanged — combining changes the row
    *count*, never the record accounting — and has its key planes
    materialized so plane-bound consumers (device engines, the
    distributed block concatenation) need no special case.  Identity
    blocks (already all-distinct) pass through untouched."""
    from map_oxidize_tpu.api import MapOutput
    from map_oxidize_tpu.ops.hashing import join_u64

    ufunc = COMBINABLE.get(combine)
    if ufunc is None:
        raise ValueError(
            f"map-side combiner supports {sorted(COMBINABLE)} combines, "
            f"got {combine!r}")
    k64 = (out.keys64 if out.keys64 is not None
           else join_u64(out.hi, out.lo))
    n = int(k64.shape[0])
    if n == 0:
        return out, 0, 0
    order = np.argsort(k64, kind="stable")
    ks = k64[order]
    bounds = np.flatnonzero(np.concatenate([[True], ks[1:] != ks[:-1]]))
    uniq = ks[bounds]
    if uniq.shape[0] == n:
        return out, n, n
    if out.values is None:
        if combine != "sum":
            raise ValueError(
                "implicit all-ones values only combine under 'sum', "
                f"got {combine!r}")
        vals = np.diff(np.append(bounds, n)).astype(np.int32)
    else:
        v = np.asarray(out.values)
        if v.ndim != 1:
            # vector fold states (k-means partials) keep their engine-side
            # combine; the map-side window combiner is scalar-only
            return out, n, n
        vals = ufunc.reduceat(v[order], bounds).astype(v.dtype, copy=False)
    combined = MapOutput(hi=None, lo=None, values=vals,
                         dictionary=out.dictionary,
                         records_in=out.records_in, keys64=uniq)
    combined.ensure_planes()
    return combined, n, int(uniq.shape[0])


def record_push_combine(obs, rows_in: int, rows_out: int) -> None:
    """The one combiner-evidence record (``shuffle/push_combined_in`` /
    ``_out`` / ``shuffle/push_bytes_saved``), shared by the
    single-controller and distributed push paths so the bench A-B and
    the ledger gate compare identical counters."""
    if obs is None or rows_in == 0:
        return
    reg = obs.registry
    reg.count("shuffle/push_combined_in", rows_in)
    reg.count("shuffle/push_combined_out", rows_out)
    if rows_in > rows_out:
        reg.count("shuffle/push_bytes_saved",
                  (rows_in - rows_out) * FOLD_ROW_BYTES)
