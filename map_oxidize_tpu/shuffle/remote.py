"""Remote-staged transport: shuffle partitions that outlive a worker.

The Coded-TeraSort line (arXiv:1702.04850, PAPERS.md) motivates staging
shuffle data OFF-process: when intermediate partitions live somewhere a
peer can reach, a multi-host run stops requiring all-resident peers and
a job can finish from staged partitions after a process dies
mid-shuffle.  The cheapest "somewhere" every multi-process test rig and
single-rack deployment already has is a shared filesystem, so that is
the object store here — the same place the output partitions land.

Placement-wise :class:`RemoteTransport` is ``disk`` (SPILLED from the
first row; single-controller engines stage through the same top-bits
bucket machinery).  What this module adds is the multi-process stage:

**Object layout** (``<stage-root>/``)::

    proc<p>/part<q>.rows      append-only 16-byte (u64 key, i64 value)
                              records owned by partition q (key % P == q)
    proc<p>/strings.dat       append-only (u64 hash, u32 len, bytes)
                              records resolving this process's keys
    manifest.proc<p>.json     the commit record (schema below)
    claim.proc<d>             O_CREAT|O_EXCL takeover claim for a dead
                              peer d (exactly one survivor wins)
    proc<d>.rec<p>/...        claimant p's re-map of dead peer d's
                              un-committed chunks (fresh object files —
                              d's committed prefix is never touched)
    manifest.proc<d>.rec.json the recovery commit record

**Manifest** (``moxt-shuffle-stage-v1``): written via write-tmp +
``os.replace`` after every committed chunk, so the visible manifest is
always internally consistent — data files are append-only and the
manifest records the VALID ROW PREFIX per object, which is why a
process SIGKILLed mid-append leaves a readable stage (readers consume
only the recorded prefix; torn tail bytes are dead weight, never data)::

    {"schema": "moxt-shuffle-stage-v1", "proc": p, "n_proc": P,
     "final": false, "chunks_done": [...global chunk indices...],
     "records": n, "strings_rows": s,
     "objects": [{"file": "proc0/part1.rows", "part": 1, "rows": r}],
     "checksums": {"1": wsum}}    # per-partition sum(mix64(k)*v) mod 2^64

The per-partition checksums make conservation provable WITHOUT
collectives: the drain-side weighted checksum of partition q must equal
the u64-wrapping sum of every manifest's ``checksums[q]`` — the PR 16
audit identity, carried by files instead of an allgather (and
sum-combine-invariant, so map-side combining upstream never breaks it).

**Recovery contract**: a peer that never writes its ``final: true``
manifest within the deadline is claimed (:func:`claim_dead_proc`) by
exactly one survivor, which re-maps the dead peer's chunks NOT in its
last committed ``chunks_done`` (chunk ownership is deterministic —
index % P — so no coordination is needed to know what died with it)
and reduces/writes the dead peer's output partition from the staged
objects.  Chunks are deduplicated by global index at reduce time, so a
manifest-committed chunk is never double-counted against a re-map."""

from __future__ import annotations

import json
import os
import time

import numpy as np

from map_oxidize_tpu.shuffle.base import ShuffleTransport
from map_oxidize_tpu.utils.logging import get_logger

_log = get_logger(__name__)

STAGE_SCHEMA = "moxt-shuffle-stage-v1"

#: one staged row: the key and its (possibly pre-combined) partial value
REC = np.dtype([("k", "<u8"), ("v", "<i8")])

#: one strings-table row header: u64 key hash, u32 token byte length
_STR_HDR = np.dtype([("h", "<u8"), ("n", "<u4")])


class RemoteTransport(ShuffleTransport):
    """SPILLED from the start, like disk — but the stage is the shared
    filesystem object layout above, not process-private buckets."""

    name = "remote"

    def __init__(self) -> None:
        super().__init__()
        self.spilled_state = True

    def admit(self, resident_rows: int, max_rows: int, engine: str) -> str:
        return "spill"


def stage_root(config) -> str:
    """The stage directory for one job: ``remote_stage_dir`` when set,
    else derived from the output path (the one location every process
    of a shared-filesystem job can already reach)."""
    root = getattr(config, "remote_stage_dir", "") or ""
    if root:
        return root
    out = getattr(config, "output_path", "") or "moxt_remote"
    return out + ".stage"


def manifest_path(root: str, proc: int) -> str:
    return os.path.join(root, f"manifest.proc{proc}.json")


def recovery_manifest_path(root: str, proc: int) -> str:
    """The claimant-committed manifest covering a dead ``proc``'s
    re-mapped chunks (the dead peer's own last manifest stays in place
    and keeps covering its committed prefix)."""
    return os.path.join(root, f"manifest.proc{proc}.rec.json")


def read_manifest(root: str, proc: int,
                  recovery: bool = False) -> "dict | None":
    """The last atomically committed manifest for ``proc`` (None when
    the process died before its first commit)."""
    path = (recovery_manifest_path(root, proc) if recovery
            else manifest_path(root, proc))
    try:
        with open(path, "rb") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if doc.get("schema") != STAGE_SCHEMA:
        raise ValueError(
            f"stage manifest {path} has schema "
            f"{doc.get('schema')!r}, expected {STAGE_SCHEMA!r}")
    return doc


class RemoteStage:
    """One process's writer half of the stage: partition, append,
    commit.  ``append_chunk`` is the atom — rows land in the per-
    partition object files, then ONE manifest replace commits the chunk
    (a kill between the two leaves the previous manifest authoritative
    and the appended tail invisible)."""

    def __init__(self, root: str, proc: int, n_proc: int, obs=None,
                 owner: "int | None" = None):
        #: ``owner`` is the process whose CHUNKS these rows come from —
        #: a survivor re-mapping a dead peer writes with owner=dead.
        #: Recovery NEVER touches the dead peer's files (its committed
        #: prefix stays authoritative; its torn tail stays dead weight):
        #: it writes a fresh ``proc<d>.rec<p>/`` directory and commits a
        #: separate ``manifest.proc<d>.rec.json``, and readers simply sum
        #: over every committed manifest.
        self.root = root
        self.proc = proc
        self.owner = proc if owner is None else owner
        self.n_proc = n_proc
        self.obs = obs
        self.dir_name = (f"proc{self.owner}" if self.owner == proc
                         else f"proc{self.owner}.rec{proc}")
        self.dir = os.path.join(root, self.dir_name)
        os.makedirs(self.dir, exist_ok=True)
        self.chunks_done: list[int] = []
        self.records = 0
        self.strings_rows = 0
        self._rows = np.zeros(n_proc, np.int64)
        self._wsum = np.zeros(n_proc, np.uint64)
        self._files: dict[int, object] = {}

    def _part_file(self, q: int):
        f = self._files.get(q)
        if f is None:
            f = open(os.path.join(self.dir, f"part{q}.rows"), "ab")
            self._files[q] = f
        return f

    def append_chunk(self, chunk_index: int, keys: np.ndarray,
                     vals: np.ndarray, records: int = 0) -> None:
        """Partition one mapped (and usually pre-combined) chunk by
        ``key % P``, append each partition's records, fsync, and commit
        the chunk with a manifest replace."""
        from map_oxidize_tpu.obs.dataplane import mix64

        keys = np.ascontiguousarray(keys, np.uint64)
        vals = np.ascontiguousarray(vals, np.int64)
        part = (keys % np.uint64(self.n_proc)).astype(np.int64)
        w = mix64(keys) * vals.view(np.uint64)
        nbytes = 0
        for q in np.unique(part).tolist():
            sel = part == q
            rec = np.empty(int(sel.sum()), REC)
            rec["k"] = keys[sel]
            rec["v"] = vals[sel]
            f = self._part_file(q)
            f.write(rec.tobytes())
            f.flush()
            os.fsync(f.fileno())
            self._rows[q] += rec.shape[0]
            with np.errstate(over="ignore"):  # mod-2^64 by design
                self._wsum[q] += w[sel].sum(dtype=np.uint64)
            nbytes += rec.nbytes
        self.chunks_done.append(int(chunk_index))
        self.records += int(records)
        if self.obs is not None:
            reg = self.obs.registry
            reg.count("shuffle/remote_rows", int(keys.shape[0]))
            reg.count("shuffle/remote_bytes", nbytes)
            reg.count("shuffle/remote_chunks")
        self._commit(final=False)

    def stage_strings(self, dictionary) -> None:
        """Append this process's hash -> token-bytes resolutions (every
        key it mapped), so ANY survivor can render winners for ANY
        partition without a gather collective."""
        items = list(dictionary.items())
        if not items:
            return
        with open(os.path.join(self.dir, "strings.dat"), "ab") as f:
            for h, tok in items:
                hdr = np.zeros(1, _STR_HDR)
                hdr["h"] = np.uint64(h)
                hdr["n"] = np.uint32(len(tok))
                f.write(hdr.tobytes())
                f.write(tok)
            f.flush()
            os.fsync(f.fileno())
        self.strings_rows += len(items)

    def finish(self) -> None:
        """The final commit: ``final: true`` tells waiting peers this
        process staged everything it owns."""
        for f in self._files.values():
            f.close()
        self._files.clear()
        self._commit(final=True)

    def _commit(self, final: bool) -> None:
        doc = {
            "schema": STAGE_SCHEMA,
            "proc": self.owner,
            "staged_by": self.proc,
            "n_proc": self.n_proc,
            "final": final,
            "chunks_done": self.chunks_done,
            "records": self.records,
            "strings_rows": self.strings_rows,
            "objects": [
                {"file": f"{self.dir_name}/part{q}.rows", "part": q,
                 "rows": int(self._rows[q])}
                for q in range(self.n_proc) if self._rows[q]
            ],
            "checksums": {str(q): int(self._wsum[q])
                          for q in range(self.n_proc) if self._rows[q]},
        }
        target = (manifest_path(self.root, self.owner)
                  if self.owner == self.proc
                  else recovery_manifest_path(self.root, self.owner))
        tmp = target + f".tmp{self.proc}"
        with open(tmp, "w") as f:
            json.dump(doc, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, target)


def wait_for_finals(root: str, n_proc: int, self_proc: int,
                    timeout_s: float, poll_s: float = 0.25,
                    ) -> "tuple[dict, list[int]]":
    """Poll peers' manifests until every one is ``final`` or the
    deadline passes.  Returns ``(manifests_by_proc, dead_procs)`` —
    ``dead`` lists peers with no final manifest at the deadline (their
    LAST committed manifest, possibly None, still rides in the dict)."""
    deadline = time.monotonic() + max(timeout_s, 0.0)
    manifests: dict = {}
    while True:
        pending = []
        for p in range(n_proc):
            if p == self_proc:
                continue
            m = read_manifest(root, p)
            if m is not None:
                manifests[p] = m
            if m is None or not m.get("final"):
                pending.append(p)
        if not pending:
            return manifests, []
        if time.monotonic() >= deadline:
            _log.warning(
                "remote stage: peers %s never went final within %.1fs; "
                "declaring them dead and taking over from the manifest",
                pending, timeout_s)
            return manifests, pending
        time.sleep(poll_s)


def claim_dead_proc(root: str, dead: int, claimant: int) -> bool:
    """Exactly-one-survivor takeover: O_CREAT|O_EXCL on the claim file.
    The winner re-maps the dead peer's un-staged chunks and writes its
    output partition; losers treat the partition as handled."""
    try:
        fd = os.open(os.path.join(root, f"claim.proc{dead}"),
                     os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        return False
    os.write(fd, f"{claimant}\n".encode())
    os.close(fd)
    return True


def read_partition(root: str, manifests: "dict[int, dict]", part: int,
                   ) -> "tuple[np.ndarray, np.ndarray, int]":
    """Drain partition ``part`` across every committed manifest: the
    valid row prefix of each owning object file, concatenated, plus the
    manifest-summed expected checksum (u64 wrap) for the conservation
    audit.  Chunk dedup is the manifests' job (an owner's committed
    chunks are excluded from its claimant's re-map), so a plain
    concatenation here is exact."""
    keys: list[np.ndarray] = []
    vals: list[np.ndarray] = []
    want = np.uint64(0)
    for m in manifests.values():
        if m is None:
            continue
        for ob in m.get("objects", ()):
            if ob["part"] != part or not ob["rows"]:
                continue
            path = os.path.join(root, ob["file"])
            rec = np.fromfile(path, REC, count=int(ob["rows"]))
            if rec.shape[0] != ob["rows"]:
                raise ValueError(
                    f"stage object {path} holds {rec.shape[0]} rows but "
                    f"its manifest committed {ob['rows']}")
            keys.append(rec["k"].copy())
            vals.append(rec["v"].copy())
        with np.errstate(over="ignore"):  # mod-2^64 by design
            want += np.uint64(int(m.get("checksums", {}).get(str(part), 0)))
    if not keys:
        return (np.empty(0, np.uint64), np.empty(0, np.int64), int(want))
    return np.concatenate(keys), np.concatenate(vals), int(want)


def read_strings(root: str) -> "dict[int, bytes]":
    """Merge every staged strings table — live peers' AND recovery
    directories' — into one hash -> bytes dict (collisions impossible:
    same 64-bit hash discipline as
    :class:`~map_oxidize_tpu.ops.hashing.HashDictionary`)."""
    import glob as _glob

    words: dict[int, bytes] = {}
    for path in sorted(
            _glob.glob(os.path.join(root, "proc*", "strings.dat"))):
        try:
            blob = open(path, "rb").read()
        except OSError:
            continue
        off = 0
        while off + _STR_HDR.itemsize <= len(blob):
            hdr = np.frombuffer(blob, _STR_HDR, count=1, offset=off)
            n = int(hdr["n"][0])
            off += _STR_HDR.itemsize
            if off + n > len(blob):
                break  # torn tail from a mid-append kill: dead weight
            words[int(hdr["h"][0])] = blob[off:off + n]
            off += n
    return words
