"""Disk transport: per-process beyond-RAM shuffle staging.

Rows stage in the shared top-bits disk-bucket partition
(:mod:`map_oxidize_tpu.runtime.spill`) from the FIRST row: resident
memory stays bounded by one fed block plus OS write buffers at any
corpus size, and the bucket-by-bucket drain at finalize yields the
globally key-ascending order downstream consumers expect (buckets are
top-bit key ranges).  Each distributed process spills only rows it OWNS
— the hash partitions are disjoint, which is exactly why per-process
spill is sound (ROADMAP open item 1).

:class:`DiskPairStage` is the concrete (key, doc) pair stage shared by
the single-controller pair collect (its beyond-RAM path now stages
through it) and the distributed per-process spill — one code path, one
record format, one obs contract."""

from __future__ import annotations

import time

import numpy as np

from map_oxidize_tpu.shuffle.base import ShuffleTransport


class DiskTransport(ShuffleTransport):
    """SPILLED from the start: every block goes to disk buckets."""

    name = "disk"

    def __init__(self) -> None:
        super().__init__()
        self.spilled_state = True

    def admit(self, resident_rows: int, max_rows: int, engine: str) -> str:
        return "spill"


def record_spill(obs, opened: set, counts: np.ndarray, rows: int,
                 nbytes: int) -> None:
    """The one spill-counter record — ``spill/rows``, ``spill/bytes``,
    and ``spill/buckets`` (distinct bucket files opened, tracked through
    the caller's ``opened`` set, which this mutates) — shared by every
    bucket-staging engine so the ledger's spill gate always compares
    like with like.  ``counts`` is the per-bucket row count of the block
    just partitioned (``partition_top_bits``)."""
    new = set(np.flatnonzero(counts).tolist()) - opened
    opened |= new
    if obs is not None:
        reg = obs.registry
        reg.count("spill/rows", rows)
        reg.count("spill/bytes", nbytes)
        if new:
            reg.count("spill/buckets", len(new))


class DiskPairStage:
    """Top-bits disk-bucket staging of 16-byte (u64 key, i64 doc)
    records — the one on-disk pair format.  Wraps
    :class:`~map_oxidize_tpu.runtime.spill.BucketFiles` with the obs
    contract (``spill/rows``, ``spill/bytes``, ``spill/buckets``) and
    the record codec, so every spilling engine shares both.

    The stable partition preserves feed order within a bucket; drain
    callers choose the final intra-bucket sort (stable-by-key when feed
    order already implies ascending docs, full (key, doc) lexsort when
    rows interleave across processes)."""

    #: on-disk record: the joined u64 key + i64 doc id
    REC = np.dtype([("k", "<u8"), ("d", "<i8")])

    def __init__(self, bits: int | None = None,
                 prefix: str = "moxt_pair_spill_", obs=None):
        from map_oxidize_tpu.runtime.spill import DEFAULT_BITS, BucketFiles

        self.bits = DEFAULT_BITS if bits is None else bits
        self.files = BucketFiles(prefix, self.bits)
        self.obs = obs
        self.rows = 0
        self.bytes = 0
        self._buckets_opened: set[int] = set()
        # spill round-trip conservation: (rows, xor, sum) pair digests
        # of everything staged vs everything drained — the full-drain
        # paths compare them and raise ConservationError on mismatch
        # (obs.dataplane_enabled=False switches the digesting off)
        self._dig_in = [0, 0, 0]
        self._dig_out = [0, 0, 0]
        self._bucket_rows = np.zeros(1 << self.bits, np.int64)

    def _audit_on(self) -> bool:
        return (self.obs is None
                or getattr(self.obs, "dataplane_enabled", True))

    @property
    def n_buckets(self) -> int:
        return 1 << self.bits

    @property
    def path(self) -> str:
        return self.files.path

    def add(self, keys: np.ndarray, docs: np.ndarray) -> None:
        """Partition one (u64 keys, i64 docs) block by top key bits and
        append to the bucket files, recording the spill counters."""
        from map_oxidize_tpu.runtime.spill import partition_top_bits

        n = int(keys.shape[0])
        if n == 0:
            return
        order, counts, offs = partition_top_bits(
            np.asarray(keys, np.uint64), self.bits)
        rec = np.empty(n, self.REC)
        rec["k"] = keys[order]
        rec["d"] = docs[order]
        t0 = time.perf_counter()
        self.files.write_partitioned("kd", rec, counts, offs)
        self._count_io_ms(t0)
        self.rows += n
        self.bytes += int(rec.nbytes)
        self._bucket_rows += counts
        if self._audit_on():
            from map_oxidize_tpu.obs.dataplane import pair_digest

            x, s = pair_digest(keys, docs)
            self._dig_in[0] += n
            self._dig_in[1] ^= x
            self._dig_in[2] = (self._dig_in[2] + s) & 0xFFFFFFFFFFFFFFFF
        record_spill(self.obs, self._buckets_opened, counts, n,
                     int(rec.nbytes))

    def _count_io_ms(self, t0: float) -> None:
        """Feed the attribution ledger's ``spill_io`` bucket: wall spent
        in bucket-file writes/drains (``spill/io_ms``), measured at the
        call sites so partition/sort compute stays out of it."""
        if self.obs is not None:
            self.obs.registry.count(
                "spill/io_ms", (time.perf_counter() - t0) * 1e3)

    def take(self, i: int) -> "np.ndarray | None":
        """Drain bucket ``i`` (read + unlink); None if never written."""
        t0 = time.perf_counter()
        try:
            rec = self.files.take("kd", i, self.REC)
        finally:
            self._count_io_ms(t0)
        if rec is not None and self._audit_on():
            from map_oxidize_tpu.obs.dataplane import pair_digest

            x, s = pair_digest(rec["k"], rec["d"])
            self._dig_out[0] += int(rec.shape[0])
            self._dig_out[1] ^= x
            self._dig_out[2] = (self._dig_out[2] + s) & 0xFFFFFFFFFFFFFFFF
        return rec

    def check_roundtrip(self) -> None:
        """Spill conservation: after a FULL drain, the drained pair
        multiset must digest identically to what was staged.  A mismatch
        means the disk round-trip dropped, duplicated, or corrupted
        records — a named hard failure (:class:`ConservationError`),
        recorded on the run's data-plane audit when one is live."""
        if not self._audit_on():
            return
        dp = (getattr(self.obs, "dataplane", None)
              if self.obs is not None else None)
        if dp is not None:
            dp.checks += 1
        if self._dig_in == self._dig_out:
            return
        from map_oxidize_tpu.obs.dataplane import ConservationError

        msg = (f"spill conservation violated: staged {self._dig_in[0]} "
               f"pair rows (xor {self._dig_in[1]:#018x}, sum "
               f"{self._dig_in[2]:#018x}) but drained {self._dig_out[0]} "
               f"(xor {self._dig_out[1]:#018x}, sum "
               f"{self._dig_out[2]:#018x}) — the disk round-trip lost or "
               f"corrupted records")
        if dp is not None:
            dp.violations.append(msg)
        raise ConservationError(msg)

    def _publish_bucket_skew(self) -> None:
        """Post-drain disk-bucket skew: max/mean rows over the non-empty
        top-bit buckets (``data/spill_bucket_imbalance``) — the
        disk-spill twin of the audit's hash-partition imbalance."""
        if self.obs is None:
            return
        live = self._bucket_rows[self._bucket_rows > 0]
        if live.shape[0]:
            self.obs.registry.set(
                "data/spill_bucket_imbalance",
                round(float(live.max() / live.mean()), 4))

    def drain_csr(self, sort_pairs):
        """Bucket-by-bucket CSR finalize — THE shared drain (the
        single-controller and distributed spilled finalizes differ only
        in ``sort_pairs``, the intra-bucket ``(keys, docs) -> (keys,
        docs)`` sort: stable-by-key where feed order already implies
        ascending docs, full (key, doc) lexsort where rows interleave
        across processes).  Each bucket loads, sorts, appends its doc
        segment to ONE on-disk column, and accumulates distinct
        terms/offsets; buckets are top-bit ranges, so terms come out
        globally hash-ascending.  Returns ``(terms, offsets,
        docs_memmap, holder, peak_rows)`` — ``holder`` keeps the doc
        column alive, ``peak_rows`` is the largest bucket drained
        (bounded-residency evidence).  Consumes the stage."""
        import os

        terms_parts: list = []
        df_parts: list = []
        doc_path = os.path.join(self.path, "docs.i64")
        peak = 0
        dp = (getattr(self.obs, "dataplane", None)
              if self.obs is not None else None)
        with open(doc_path, "wb") as out:
            for i in range(self.n_buckets):
                rec = self.take(i)
                if rec is None:
                    continue
                keys = np.ascontiguousarray(rec["k"])
                docs = np.ascontiguousarray(rec["d"])
                del rec
                peak = max(peak, int(keys.shape[0]))
                keys, docs = sort_pairs(keys, docs)
                if dp is not None:
                    # buckets are disjoint key ranges, so per-bucket
                    # records sum to the exact out-side audit
                    dp.record_pairs_out(keys, docs)
                bounds = (np.flatnonzero(np.concatenate(
                    [[True], keys[1:] != keys[:-1]])) if keys.shape[0]
                    else np.empty(0, np.int64))
                terms_parts.append(keys[bounds])
                df_parts.append(np.diff(np.append(bounds, keys.shape[0])))
                t0 = time.perf_counter()
                out.write(docs.tobytes())
                self._count_io_ms(t0)
        self.check_roundtrip()
        self._publish_bucket_skew()
        holder = self.release()  # caller keeps the doc file alive
        if not terms_parts:
            return (np.empty(0, np.uint64), np.zeros(1, np.int64),
                    np.empty(0, np.int64), holder, peak)
        terms = np.concatenate(terms_parts)
        offsets = np.concatenate(
            [[0], np.cumsum(np.concatenate(df_parts))]).astype(np.int64)
        docs = np.memmap(doc_path, np.int64, mode="r")
        return terms, offsets, docs, holder, peak

    def drain_sorted(self, sort_pairs):
        """Bucket-by-bucket sorted-RUN drain (the total-order sort's
        finalize, CSR-free): yields ``(keys, docs)`` per non-empty
        bucket, each block sorted by ``sort_pairs`` — buckets are
        top-bit key RANGES, so the yielded blocks concatenate into the
        globally key-ascending stream, and a full (key, doc) lexsort
        per bucket makes that concatenation the exact total order.
        Resident memory: one bucket at a time.  Consumes the stage
        (bucket files unlink as they drain; the temp dir is removed
        when the generator finishes)."""
        try:
            for i in range(self.n_buckets):
                rec = self.take(i)
                if rec is None:
                    continue
                keys = np.ascontiguousarray(rec["k"])
                docs = np.ascontiguousarray(rec["d"])
                del rec
                yield sort_pairs(keys, docs)
            # only a COMPLETED drain proves conservation (an abandoned
            # generator legitimately leaves staged rows behind)
            self.check_roundtrip()
            self._publish_bucket_skew()
        finally:
            self.cleanup()

    def release(self):
        """Hand the temp directory to the caller (keeps on-disk finalize
        artifacts like the CSR doc column alive)."""
        return self.files.release()

    def cleanup(self) -> None:
        self.files.cleanup()
