"""Shuffle transport interface: partition -> exchange -> drain.

A transport answers one question per fed block — *where do shuffled rows
stage?* — through a tiny three-state machine:

    PUSHING --(trip)--> SPILLED <--(trip: resident rows cross the cap)-- RESIDENT

``hbm`` never leaves RESIDENT (the trip is a hard error), ``disk`` starts
in SPILLED, ``hybrid`` makes the one-way demotion transition mid-job.
``pipelined`` starts in PUSHING — every fed block is hash-partitioned
and pushed to its owner while map is still producing (the ``"push"``
verdict: resident placement plus an eager per-block merge, no terminal
barrier), optionally sum-combining partial fold states per push window;
at the cap it takes the same one-way demotion to SPILLED as hybrid.
``remote`` starts in SPILLED like disk, but the stage is a SHARED
filesystem object layout under a ``moxt-shuffle-stage-v1`` manifest
(:mod:`map_oxidize_tpu.shuffle.remote`) so multi-host runs stop
requiring all-resident peers: a job can finish from staged partitions
after a process dies mid-shuffle.
The engines own the mechanisms on each side of the seam — the jitted
``all_to_all`` exchange programs (:mod:`map_oxidize_tpu.parallel.shuffle`)
for RESIDENT, the top-bits disk buckets (:mod:`map_oxidize_tpu.runtime.spill`)
for SPILLED — and consult the transport via :meth:`ShuffleTransport.admit`
before acting on a block.

Obs-counter contract (every transport/engine pair must honor it, so the
ledger gate and BENCH_DETAIL compare spill behavior across engines):

* ``spill/rows`` / ``spill/bytes`` — rows/bytes written to disk buckets
  (:class:`~map_oxidize_tpu.shuffle.disk.DiskPairStage` records them).
* ``spill/buckets`` — distinct bucket files opened.
* ``demote/events`` / ``demote/rows`` and a ``shuffle/demote`` tracer
  span — one per RESIDENT->SPILLED transition, identical on the
  single-controller and distributed paths (:func:`record_demotion`).
* ``shuffle/transport`` gauge — the transport actually driving the job
  (drivers set it; ``/status`` surfaces it live).
* data-plane audit hooks — a spilling stage digests every pair it
  stages and drains (order-independent multiset checksums) and raises
  :class:`~map_oxidize_tpu.obs.dataplane.ConservationError` if a FULL
  drain returns a different multiset; with a live ``obs.dataplane`` it
  also records drained pairs into the run's conservation/skew audit
  and publishes ``data/spill_bucket_imbalance``.

Drain-order invariant (inherited from :mod:`map_oxidize_tpu.runtime.spill`):
buckets are top-bit key RANGES, so a bucket-by-bucket drain concatenates
into globally key-ascending output — the segment-contiguous layout every
downstream postings/reduce consumer already expects.
"""

from __future__ import annotations

import abc
import os

#: the ``--shuffle-transport`` vocabulary (config + CLI + serve ``--set``)
TRANSPORTS = ("auto", "hbm", "disk", "hybrid", "pipelined", "remote")

#: auto-routing density assumption: one shuffled row per this many corpus
#: bytes.  Deliberately conservative (short-token text emits a pair per
#: ~6-10 bytes): when even this UNDERestimate of the row count exceeds
#: the resident cap, the job is certainly beyond-RAM and should stage on
#: disk from the first row instead of paying a mid-job demotion drain.
AUTO_BYTES_PER_ROW = 16


def resolve_transport(config, max_rows: int, name: str | None = None) -> str:
    """Resolve ``config.shuffle_transport`` to a concrete transport name.

    ``auto`` routes on corpus size vs the resident-row cap: estimated
    rows (``corpus_bytes // AUTO_BYTES_PER_ROW``) past ``max_rows``
    pick ``disk`` (the job will certainly spill — skip the demotion
    drain and bound residency from row 0), anything else picks
    ``hybrid`` (resident speed, disk safety net) — today's engine
    behavior, now a named policy.  An unreadable input (serve jobs
    validate paths later) falls back to ``hybrid``.

    ``name`` overrides the config's spelling — the planner's
    ``Obs.knob("shuffle_transport")`` seam resolves the PLANNED name
    through the same router, so a curve-chosen ``pipelined`` and a
    pinned one take an identical path."""
    if name is None:
        name = getattr(config, "shuffle_transport", "auto")
    if name != "auto":
        return name
    try:
        size = os.path.getsize(config.input_path)
    except (OSError, TypeError):
        size = 0
    return "disk" if size // AUTO_BYTES_PER_ROW > max_rows else "hybrid"


class ShuffleTransport(abc.ABC):
    """The placement policy state machine.  Engines call :meth:`admit`
    with the prospective resident row count before acting on a block and
    act on the verdict:

    * ``"resident"`` — keep the block on the resident path (device
      buffers / host RAM staging).
    * ``"push"`` — resident placement PLUS an eager per-block push: the
      engine partitions and merges the block into its owner immediately
      instead of accumulating toward a terminal barrier (the PUSHING
      state; placement-wise engines treat it exactly like
      ``"resident"``, the push cadence is the driver's half).
    * ``"spill"`` — stage the block in disk buckets.
    * ``"demote"`` — drain the resident state to disk buckets first
      (record it via :func:`record_demotion`), then spill this block and
      every later one; returned exactly once, at the trip.
    """

    name: str = "?"

    def __init__(self) -> None:
        self.spilled_state = False

    @abc.abstractmethod
    def admit(self, resident_rows: int, max_rows: int, engine: str) -> str:
        """Verdict for a block that brings the resident row count to
        ``resident_rows`` against the ``max_rows`` cap.  ``engine`` names
        the caller for error messages (e.g. ``"pair collect"``)."""

    def cap_error(self, resident_rows: int, max_rows: int,
                  engine: str) -> RuntimeError:
        """The actionable strict-mode abort (``hbm`` only)."""
        return RuntimeError(
            f"{engine} exceeded max_rows={max_rows} with "
            "--shuffle-transport hbm (strictly resident, no spill); "
            "re-run with --shuffle-transport disk (disk buckets from the "
            "first row) or hybrid (resident until the cap, then demote "
            "to disk), or raise --collect-max-rows if the rows genuinely "
            "fit")


def make_transport(name: str) -> ShuffleTransport:
    """Concrete transport instance for a resolved (non-``auto``) name."""
    from map_oxidize_tpu.shuffle.disk import DiskTransport
    from map_oxidize_tpu.shuffle.hbm import HbmTransport
    from map_oxidize_tpu.shuffle.hybrid import HybridTransport
    from map_oxidize_tpu.shuffle.pipelined import PipelinedTransport
    from map_oxidize_tpu.shuffle.remote import RemoteTransport

    try:
        cls = {"hbm": HbmTransport, "disk": DiskTransport,
               "hybrid": HybridTransport,
               "pipelined": PipelinedTransport,
               "remote": RemoteTransport}[name]
    except KeyError:
        raise ValueError(
            f"unknown shuffle transport {name!r}; expected one of "
            f"{TRANSPORTS}") from None
    return cls()


def record_demotion(obs, rows: int, frm: str, to: str, **attrs):
    """The one demotion record, shared by every engine so the
    single-controller and distributed paths emit IDENTICAL evidence: a
    ``shuffle/demote`` span wrapping the drain (use as a context
    manager) plus the ``demote/events`` / ``demote/rows`` counters.
    ``rows`` is the resident row count being drained."""
    import contextlib

    if obs is None:
        return contextlib.nullcontext()
    obs.registry.count("demote/events")
    obs.registry.count("demote/rows", rows)
    return obs.tracer.span("shuffle/demote", rows=rows, **{"from": frm,
                                                           "to": to},
                           **attrs)
