"""Pluggable shuffle transport layer.

Exoshuffle's thesis (arXiv:2203.05072, PAPERS.md) is that shuffle belongs
in an application-level library over swappable transports, not as a policy
hard-wired into each engine.  This package is that seam for the collect
engines: the *mechanisms* (the jitted ``all_to_all`` exchange programs,
the top-bits disk-bucket partition) stay where they always lived
(:mod:`map_oxidize_tpu.parallel.shuffle`, :mod:`map_oxidize_tpu.runtime.spill`);
what moves here is the *policy* — where shuffled rows stage, when staging
demotes to disk, and the observability contract every placement must
honor — so the driver picks the transport (``--shuffle-transport``)
instead of each engine hard-coding one.

Five concrete transports behind one small interface:

* :class:`~map_oxidize_tpu.shuffle.hbm.HbmTransport` — strictly
  device/RAM-resident (today's ``all_to_all``/accumulator paths,
  unchanged); crossing the resident-row cap is a hard, actionable error.
* :class:`~map_oxidize_tpu.shuffle.disk.DiskTransport` — rows stage in
  per-process top-bits disk buckets from the first row; bounded resident
  memory at any corpus size.
* :class:`~map_oxidize_tpu.shuffle.hybrid.HybridTransport` — resident
  until the cap trips, then a one-way demotion to disk buckets mid-job.
* :class:`~map_oxidize_tpu.shuffle.pipelined.PipelinedTransport` —
  hybrid's placement with an eager push cadence: each fed block is
  hash-partitioned and merged into its owner WHILE map still produces
  (no terminal barrier), optionally pre-combined map-side.
* :class:`~map_oxidize_tpu.shuffle.remote.RemoteTransport` — staged
  from the first row like disk, but in a shared-filesystem object
  layout (``moxt-shuffle-stage-v1`` manifests) a surviving peer can
  finish the job from after a process dies mid-shuffle.

``auto`` routes on corpus size vs the cap (:func:`resolve_transport`).
"""

from map_oxidize_tpu.shuffle.base import (
    AUTO_BYTES_PER_ROW,
    ShuffleTransport,
    TRANSPORTS,
    make_transport,
    record_demotion,
    resolve_transport,
)
from map_oxidize_tpu.shuffle.disk import DiskPairStage, DiskTransport
from map_oxidize_tpu.shuffle.hbm import HbmTransport
from map_oxidize_tpu.shuffle.hybrid import HybridTransport
from map_oxidize_tpu.shuffle.pipelined import (
    PipelinedTransport,
    combine_map_output,
    record_push_combine,
)
from map_oxidize_tpu.shuffle.remote import RemoteStage, RemoteTransport

__all__ = [
    "AUTO_BYTES_PER_ROW",
    "DiskPairStage",
    "DiskTransport",
    "HbmTransport",
    "HybridTransport",
    "PipelinedTransport",
    "RemoteStage",
    "RemoteTransport",
    "ShuffleTransport",
    "TRANSPORTS",
    "combine_map_output",
    "make_transport",
    "record_demotion",
    "record_push_combine",
    "resolve_transport",
]
