"""map_oxidize_tpu — a TPU-native MapReduce framework.

A from-scratch JAX/XLA re-design of the capabilities of
``AnarchistHoneybun/map-oxidize`` (crate ``meduce``, a single-file Rust/tokio
word-count MapReduce — see ``/root/reference/src/main.rs``).  Nothing here is a
translation: the reference's text-file spill + global-mutex reduce
(main.rs:103-150) becomes device-resident ``(hash(key), value)`` arrays reduced
with ``jax.lax.sort`` + segment combines, and its in-process task pools
(main.rs:53-92, 111-150) become a host-side map executor feeding a sharded
device engine whose cross-shard shuffle rides XLA ``all_to_all`` / ``psum``
collectives over the ICI mesh.

Layer map (mirrors SURVEY.md §1, redrawn TPU-first):

* ``runtime.driver``   — phase orchestration (reference L5, main.rs:8-34)
* ``runtime.executor`` — host map worker pool w/ retries (L4, main.rs:53-92)
* ``runtime.engine``   — streaming device reduce engine (L4, main.rs:111-150)
* ``api``              — Mapper/Reducer trait boundary (L3; the reference
  hardcodes these, main.rs:94-101 + 131-134)
* ``ops``              — device kernels: hashing, sort+segment reduce, top-k
* ``parallel``         — mesh, shard_map shuffle, collectives (reference: none)
* ``io``               — splitter / spill / writer (L2, main.rs:36-51, 103-109,
  152-182)
* ``native``           — C++ tokenize/hash hot loop (the reference's "native"
  tier is the whole Rust binary; ours is the one loop that deserves it)
"""

__version__ = "0.5.0"  # keep in sync with pyproject.toml

from map_oxidize_tpu.api import (
    Mapper,
    MapOutput,
    MaxReducer,
    MinReducer,
    Reducer,
    SumReducer,
)
from map_oxidize_tpu.config import JobConfig
from map_oxidize_tpu.runtime import run_job

__all__ = [
    "Mapper",
    "MapOutput",
    "Reducer",
    "SumReducer",
    "MinReducer",
    "MaxReducer",
    "JobConfig",
    "run_job",
    "__version__",
]
