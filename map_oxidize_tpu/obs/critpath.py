"""Causal critical-path observatory: cross-process blame, slack, what-if.

The obs stack can say *where* each process spent its wall (the
attribution ledger, the skew report) but not *what causally bounded the
job*: which chain of spans, feed waits, and lockstep collective rounds
across ALL processes set the end-to-end wall, and how much each
off-path process could slow down for free.  This module answers that —
the evidence plane ROADMAP items 2 (Exoshuffle-style pipelined shuffle,
arXiv:2203.05072: "shuffle wall hidden behind map wall") and 3
(straggler mitigation, arXiv:1802.03049: only pays when the straggler
is ON the critical path) both gate on.

The happens-before span DAG is built from the merged distributed trace:

* **program edges** — intra-process, per-thread span ordering (time is
  serial within a thread);
* **handoff edges** — producer->consumer queue handoffs across the
  prefetcher/stager threads, joined on the ``seq=`` tags both halves
  record at trace time (:class:`~map_oxidize_tpu.runtime.pipeline.
  ChunkPrefetcher`);
* **barrier edges** — cross-process rendezvous at every lockstep
  collective round, joined on the ``round=`` tags the ``parallel/``
  drivers stamp on their ``dist/lockstep_flag`` (flag psum) and
  ``dist/merge_local`` (exchange) spans: no process exits round *k*
  before the LAST process enters it, so round *k*'s flag spans across
  processes are one barrier node.

The critical path walks back from the last-finishing process through
the barrier chain: at each barrier the path jumps to the process that
arrived LAST (the round's binding process), so the path tiles the whole
traced wall into segments — per-process work intervals (sub-attributed
onto the existing attrib bucket names by span overlap), on-path
collective latency, and startup skew.  From the same round model:

* **blame shares** — each process's share of the on-path work (sums to
  100%);
* **slack** — per process, how much it could slow down for free: every
  barrier resynchronizes the fleet, so each round's wait independently
  absorbs slowdown of the interval feeding it — the total is the sum
  of the process's barrier waits (a straggler that binds every round
  has none);
* **what-if estimators** — a deterministic replay of the round model
  under counterfactual inputs: "process *i* at peer-median speed"
  (the straggler-mitigation payoff), "map/shuffle perfectly overlapped"
  (the pipelined-transport payoff item 2 must later realize: each
  interval's exchange time hides behind its map time), and
  "collectives free" (the interconnect bill).

Surfaces: ``obs critpath`` (CLI), the ``critpath`` section of the
merged-trace skew report and the metrics document, headline
``critpath/*`` gauges in ledger entries (``obs diff --gate`` /
``obs trend`` watch them), the ``obs top`` one-line "bound by" panel,
and the ``critpath-process-blame`` SLO rule.  A single-process job has
no cross-process DAG: its path degenerates to the attribution timeline
(:func:`degenerate_from_attrib`), same document shape.

See docs/OBSERVABILITY.md "Critical path & what-if".
"""

from __future__ import annotations

from dataclasses import dataclass, field

CRITPATH_SCHEMA = "moxt-critpath-v1"

#: clock-alignment refusal bound (seconds): after wall-clock alignment,
#: every process is INSIDE a lockstep barrier round simultaneously at
#: some instant — a round whose last arrival lands after another
#: process's exit by more than this is wall-clock skew, and merging it
#: would silently mis-order every cross-process edge
CLOCK_SKEW_BOUND_S = 2.0

#: span-name -> attrib-bucket classification for on-path work segments,
#: checked in order (first match wins; specific names before phase
#: containers).  Buckets reuse the attribution ledger's names where the
#: meaning matches (docs/OBSERVABILITY.md "Where did the time go");
#: ``exchange`` is new — the lockstep all_to_all exchange rounds, the
#: time the "map/shuffle overlapped" what-if hides behind map work.
_SPAN_BUCKETS: tuple[tuple[str, str], ...] = (
    ("dist/merge_local", "exchange"),
    ("dist/map_chunk", "host_produce"),
    # push-edge handoffs (the pipelined shuffle transport): map runs on
    # the prefetcher thread as push/produce while the lockstep exchange
    # occupies the driver, and push/feed_wait is the residue the overlap
    # did NOT hide.  Once a run is pushed, the map_shuffle_overlapped
    # what-if prices only that residue — its predicted saving
    # approaching zero is the banked-overlap signal, not a regression.
    ("push/produce", "host_produce"),
    ("push/feed_wait", "feed_wait"),
    ("shuffle/remote_stage", "spill_io"),
    ("shuffle/demote", "spill_io"),
    ("engine/flush", "host_stage"),
    ("engine/feed_block", "host_stage"),
    ("phase/sample", "host_produce"),
    ("phase/split", "host_produce"),
    ("phase/write", "host_write"),
    ("phase/finalize", "finalize"),
    ("phase/merge", "finalize"),
)
#: suffix-matched handoff spans (the prefetcher names are
#: ``<pipeline-name>/produce`` / ``<pipeline-name>/feed_wait``)
_SPAN_SUFFIX_BUCKETS: tuple[tuple[str, str], ...] = (
    ("/feed_wait", "feed_wait"),
    ("/produce", "host_produce"),
)

#: the what-if names (stable identifiers tests and docs reference)
WHATIF_PROC_MEDIAN = "proc_{p}_at_peer_median_speed"
WHATIF_OVERLAP = "map_shuffle_overlapped"
WHATIF_FREE_COLLECTIVES = "collectives_free"


class ClockSkewError(ValueError):
    """Shard wall clocks disagree beyond :data:`CLOCK_SKEW_BOUND_S`:
    after alignment, a lockstep barrier round's spans do not overlap
    across processes.  Merging/critpathing would silently mis-order
    every cross-process edge, so the caller must refuse (or re-align
    with trusted clocks)."""


@dataclass
class ProcTimeline:
    """One process's aligned trace view: complete (``ph="X"``) spans on
    a shared global time axis (microseconds since the earliest shard's
    wall start), the lockstep barrier rounds extracted from the
    ``round=`` tags, and the shard's attribution document when the
    shard carried one."""

    process: int
    spans: list = field(default_factory=list)   # (name, t0, t1, tid, args)
    rounds: dict = field(default_factory=dict)  # round -> (enter, exit) us
    attrib: dict | None = None
    wall_start_unix_s: float = 0.0

    @property
    def start_us(self) -> float:
        return min((s[1] for s in self.spans), default=0.0)

    @property
    def end_us(self) -> float:
        return max((s[2] for s in self.spans), default=0.0)


# --- timeline construction -------------------------------------------------


def _push_span(tl: ProcTimeline, name: str, t0: float, dur: float,
               tid, args: dict) -> None:
    t1 = t0 + max(dur, 0.0)
    tl.spans.append((name, t0, t1, tid, args))
    if name == "dist/lockstep_flag":
        r = args.get("round")
        if isinstance(r, int) and r not in tl.rounds:
            tl.rounds[r] = (t0, t1)


def timelines_from_shards(shards: list[dict]) -> list[ProcTimeline]:
    """Per-process timelines from shard documents (``moxt-obs-shard-v1``),
    aligned exactly the way :func:`map_oxidize_tpu.obs.merge.merge_shards`
    aligns the merged Chrome trace: each shard's events shift by its
    wall-clock anchor relative to the earliest shard.  Refuses (named
    ``ValueError``) a shard whose wall anchor is missing or non-positive
    — an un-anchorable shard cannot join a shared time axis."""
    tls: list[ProcTimeline] = []
    anchors = []
    for s in shards:
        meta = s.get("meta", {})
        ws = meta.get("wall_start_unix_s")
        if not isinstance(ws, (int, float)) or not ws > 0:
            raise ValueError(
                f"shard for process {meta.get('process')!r} has no usable "
                f"wall_start_unix_s anchor ({ws!r}): cannot align it onto "
                "the shared time axis")
        anchors.append(float(ws))
    anchor = min(anchors)
    for s, ws in zip(shards, anchors):
        meta = s.get("meta", {})
        tl = ProcTimeline(process=int(meta.get("process", 0)),
                          attrib=(s.get("metrics") or {}).get("attrib"),
                          wall_start_unix_s=ws)
        shift = (ws - anchor) * 1e6
        for e in s.get("events", []):
            if e.get("ph") != "X":
                continue
            _push_span(tl, e.get("name", ""), float(e.get("ts", 0.0))
                       + shift, float(e.get("dur", 0.0)), e.get("tid"),
                       e.get("args") or {})
        tls.append(tl)
    tls.sort(key=lambda t: t.process)
    return tls


def timelines_from_merged_events(events: list[dict]) -> list[ProcTimeline]:
    """Per-process timelines from an already-merged Chrome trace (the
    ``obs merge`` artifact: ``pid`` = process slot, timestamps already
    on the shared axis)."""
    by_pid: dict[int, ProcTimeline] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        p = int(e.get("pid", 0))
        tl = by_pid.get(p)
        if tl is None:
            tl = by_pid[p] = ProcTimeline(process=p)
        _push_span(tl, e.get("name", ""), float(e.get("ts", 0.0)),
                   float(e.get("dur", 0.0)), e.get("tid"),
                   e.get("args") or {})
    return [by_pid[p] for p in sorted(by_pid)]


def common_rounds(timelines: list[ProcTimeline]) -> list[int]:
    """Barrier rounds every covered process recorded (a killed process's
    partial shard truncates the common set — the coverage gap the report
    names)."""
    if not timelines:
        return []
    rounds = set(timelines[0].rounds)
    for tl in timelines[1:]:
        rounds &= set(tl.rounds)
    return sorted(rounds)


def check_clock_alignment(timelines: list[ProcTimeline],
                          bound_s: float = CLOCK_SKEW_BOUND_S) -> None:
    """Causal clock-skew check over the barrier rounds: for every common
    round, the last arrival must not land after any process's exit by
    more than ``bound_s`` (barrier semantics — everyone is inside the
    round simultaneously; only wall-clock skew can violate that).
    Raises :class:`ClockSkewError` naming the worst round."""
    worst = (0.0, None)
    for r in common_rounds(timelines):
        max_enter = max(tl.rounds[r][0] for tl in timelines)
        min_exit = min(tl.rounds[r][1] for tl in timelines)
        skew = (max_enter - min_exit) / 1e6
        if skew > worst[0]:
            worst = (skew, r)
    if worst[1] is not None and worst[0] > bound_s:
        raise ClockSkewError(
            f"wall-clock skew {worst[0]:.3f}s at lockstep round "
            f"{worst[1]} exceeds the {bound_s:g}s alignment bound: after "
            "wall-clock alignment a barrier round's spans must overlap "
            "across processes; refusing to mis-order cross-process edges "
            "(fix the hosts' clocks, or re-export with aligned anchors)")


# --- interval classification -----------------------------------------------


def _bucket_of(name: str) -> str | None:
    for prefix, bucket in _SPAN_BUCKETS:
        if name.startswith(prefix):
            return bucket
    for suffix, bucket in _SPAN_SUFFIX_BUCKETS:
        if name.endswith(suffix):
            return bucket
    return None


def _classify_interval(tl: ProcTimeline, t0: float, t1: float) -> dict:
    """Sub-attribute one work interval ``[t0, t1]`` on ``tl`` onto the
    attrib bucket names by span overlap.  Buckets claim time in
    :data:`_SPAN_BUCKETS` priority order over a covered-interval list,
    so nested spans (a ``dist/map_chunk`` inside ``phase/map+reduce``)
    never double-count; the unclaimed remainder is ``other``.  Returns
    ``{bucket: ms}``."""
    if t1 <= t0:
        return {}
    by_bucket: dict[str, list] = {}
    for name, s0, s1, _tid, _args in tl.spans:
        b = _bucket_of(name)
        if b is None:
            continue
        lo, hi = max(s0, t0), min(s1, t1)
        if hi > lo:
            by_bucket.setdefault(b, []).append((lo, hi))
    covered: list[tuple[float, float]] = []
    out: dict[str, float] = {}
    order = [b for _p, b in _SPAN_BUCKETS] + [b for _s, b
                                              in _SPAN_SUFFIX_BUCKETS]
    seen = set()
    for bucket in order:
        if bucket in seen or bucket not in by_bucket:
            seen.add(bucket)
            continue
        seen.add(bucket)
        got = 0.0
        for lo, hi in _merge_intervals(by_bucket[bucket]):
            got += _uncovered(lo, hi, covered)
            covered = _merge_intervals(covered + [(lo, hi)])
        if got > 0:
            out[bucket] = out.get(bucket, 0.0) + got / 1e3
    claimed = sum(hi - lo for lo, hi in covered)
    other = (t1 - t0) - claimed
    if other > 0:
        out["other"] = other / 1e3
    return out


def _merge_intervals(ivs: list) -> list:
    if not ivs:
        return []
    ivs = sorted(ivs)
    out = [ivs[0]]
    for lo, hi in ivs[1:]:
        if lo <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], hi))
        else:
            out.append((lo, hi))
    return out


def _uncovered(lo: float, hi: float, covered: list) -> float:
    """Length of [lo, hi] not already claimed by ``covered`` (a sorted,
    disjoint interval list)."""
    got = hi - lo
    for c0, c1 in covered:
        o_lo, o_hi = max(lo, c0), min(hi, c1)
        if o_hi > o_lo:
            got -= o_hi - o_lo
    return max(got, 0.0)


# --- the round model + replay ----------------------------------------------


@dataclass
class _RoundModel:
    """The barrier-structured execution model extracted from the
    timelines — the inputs the deterministic what-if replay runs on."""

    procs: list[int]
    rounds: list[int]
    start_s: dict        # proc -> start offset from job start (s)
    work_s: dict         # proc -> [interval duration per round] (s)
    coll_s: list         # per-round collective latency (s)
    tail_s: dict         # proc -> after-last-round tail (s)
    #: per-(proc, round) bucket decomposition of the interval, {bkt: ms}
    buckets: dict


def _extract_model(timelines: list[ProcTimeline],
                   rounds: list[int]) -> _RoundModel:
    job_start = min(tl.start_us for tl in timelines)
    procs = [tl.process for tl in timelines]
    start_s, work_s, tail_s, buckets = {}, {}, {}, {}
    coll_s = []
    for tl in timelines:
        p = tl.process
        start_s[p] = (tl.start_us - job_start) / 1e6
        prev_exit = tl.start_us
        ws = []
        for i, r in enumerate(rounds):
            enter, exit_ = tl.rounds[r]
            ws.append(max(enter - prev_exit, 0.0) / 1e6)
            buckets[(p, i)] = _classify_interval(tl, prev_exit, enter)
            prev_exit = exit_
        work_s[p] = ws
        tail_s[p] = max(tl.end_us - prev_exit, 0.0) / 1e6
        buckets[(p, len(rounds))] = _classify_interval(tl, prev_exit,
                                                       tl.end_us)
    for r in rounds:
        arrive = max(tl.rounds[r][0] for tl in timelines)
        mean_exit = (sum(tl.rounds[r][1] for tl in timelines)
                     / len(timelines))
        coll_s.append(max(mean_exit - arrive, 0.0) / 1e6)
    return _RoundModel(procs=procs, rounds=rounds, start_s=start_s,
                       work_s=work_s, coll_s=coll_s, tail_s=tail_s,
                       buckets=buckets)


def _replay(model: _RoundModel, start_s=None, work_s=None, coll_s=None,
            tail_s=None) -> float:
    """Deterministic barrier-schedule replay: wall (seconds) of the
    round model under (possibly counterfactual) inputs.  Every process
    runs its interval work, the round completes when the LAST arrives
    plus the collective latency, everyone leaves together — the same
    lockstep semantics the real drivers implement."""
    start_s = model.start_s if start_s is None else start_s
    work_s = model.work_s if work_s is None else work_s
    coll_s = model.coll_s if coll_s is None else coll_s
    tail_s = model.tail_s if tail_s is None else tail_s
    avail = dict(start_s)
    for i in range(len(model.rounds)):
        arrive = max(avail[p] + work_s[p][i] for p in model.procs)
        done = arrive + coll_s[i]
        avail = {p: done for p in model.procs}
    return max(avail[p] + tail_s[p] for p in model.procs)


def _median(vals: list[float]) -> float:
    vals = sorted(vals)
    n = len(vals)
    if not n:
        return 0.0
    mid = n // 2
    return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])


# --- the report ------------------------------------------------------------


def compute(timelines: list[ProcTimeline],
            coverage: dict | None = None) -> dict:
    """The critical-path document from >= 2 aligned process timelines.
    Raises ``ValueError`` when no common lockstep rounds exist (nothing
    to anchor cross-process edges on)."""
    if len(timelines) < 2:
        raise ValueError(
            "critical-path extraction needs >= 2 process timelines; a "
            "single-process job degenerates to the attribution timeline "
            "(degenerate_from_attrib)")
    rounds = common_rounds(timelines)
    if not rounds:
        raise ValueError(
            "no common lockstep rounds across process timelines — the "
            "trace predates round tagging, or the shards are not one "
            "job's")
    model = _extract_model(timelines, rounds)
    by_proc = {tl.process: tl for tl in timelines}
    job_start = min(tl.start_us for tl in timelines)
    job_end = max(tl.end_us for tl in timelines)
    wall_ms = (job_end - job_start) / 1e3

    # --- DAG bookkeeping (counts; the path extraction below IS the
    # longest-path walk over these edges)
    n_program_edges = 0
    n_handoff = 0
    for tl in timelines:
        per_thread: dict = {}
        produce_seqs: dict = {}
        wait_seqs = set()
        for name, _t0, _t1, tid, args in tl.spans:
            per_thread[tid] = per_thread.get(tid, 0) + 1
            seq = args.get("seq")
            if isinstance(seq, int):
                if name.endswith("/produce"):
                    produce_seqs[seq] = True
                elif name.endswith("/feed_wait"):
                    wait_seqs.add(seq)
        n_program_edges += sum(max(n - 1, 0) for n in per_thread.values())
        n_handoff += len(wait_seqs & set(produce_seqs))
    n_barrier_edges = len(rounds) * len(timelines) * 2  # in + out per proc

    # --- critical path: walk back from the last-finishing process
    # through the barrier chain (the binding process of round r is the
    # LAST arrival — the longest-path predecessor through the barrier)
    segments: list[dict] = []
    cur = max(timelines, key=lambda t: t.end_us).process
    T = job_end

    def _work_seg(p: int, t0: float, t1: float, interval: int,
                  kind: str = "work"):
        if t1 - t0 <= 0:
            return
        segments.append({
            "kind": kind, "process": p,
            "round": (rounds[interval] if interval < len(rounds)
                      else None),
            "ms": round((t1 - t0) / 1e3, 3),
            "t0_ms": round((t0 - job_start) / 1e3, 3),
            "buckets": {k: round(v, 3) for k, v in sorted(
                model.buckets.get((p, interval), {}).items())},
        })

    # tail: after the last common round on the path-ending process
    _work_seg(cur, by_proc[cur].rounds[rounds[-1]][1], T, len(rounds),
              kind="tail")
    for i in range(len(rounds) - 1, -1, -1):
        r = rounds[i]
        binder = max(timelines, key=lambda t: t.rounds[r][0]).process
        arrive = by_proc[binder].rounds[r][0]
        exit_cur = by_proc[cur].rounds[r][1]
        if exit_cur > arrive:
            segments.append({
                "kind": "collective", "process": None, "round": r,
                "ms": round((exit_cur - arrive) / 1e3, 3),
                "t0_ms": round((arrive - job_start) / 1e3, 3),
                "binder": binder,
            })
        cur = binder
        t0 = (by_proc[cur].rounds[rounds[i - 1]][1] if i > 0
              else by_proc[cur].start_us)
        _work_seg(cur, t0, arrive, i)
    if by_proc[cur].start_us > job_start:
        segments.append({
            "kind": "startup", "process": cur, "round": None,
            "ms": round((by_proc[cur].start_us - job_start) / 1e3, 3),
            "t0_ms": 0.0,
        })
    segments.reverse()
    path_ms = sum(s["ms"] for s in segments)

    # --- blame: each process's share of the on-path work
    blame_ms: dict[int, float] = {tl.process: 0.0 for tl in timelines}
    for s in segments:
        if s["kind"] in ("work", "tail", "startup"):
            blame_ms[s["process"]] += s["ms"]
    work_total = sum(blame_ms.values())
    blame = {
        str(p): {"on_path_ms": round(ms, 3),
                 "share_pct": round(100.0 * ms / work_total, 2)
                 if work_total else 0.0}
        for p, ms in sorted(blame_ms.items())}

    # --- slack: how much this process could slow down for free.  Every
    # barrier RESYNCHRONIZES the fleet, so each round's wait absorbs
    # slowdown of the interval feeding that round independently — the
    # process's total free slowdown is the SUM of its barrier waits
    # (distributed as those waits; a straggler that binds every round
    # has none).  ``binding_round`` names the first round whose wait is
    # ~zero (where more slowdown would start moving the wall), and
    # ``end_gap_ms`` is the separate tail headroom (how much its
    # post-barrier tail could stretch before setting the job end).
    slack = {}
    for tl in timelines:
        waits = [max((max(t2.rounds[r][0] for t2 in timelines)
                      - tl.rounds[r][0]) / 1e3, 0.0) for r in rounds]
        binding = rounds[waits.index(min(waits))]
        slack[str(tl.process)] = {
            "slack_ms": round(sum(waits), 3),
            "binding_round": binding,
            "end_gap_ms": round(max((job_end - tl.end_us) / 1e3, 0.0),
                                3)}

    coll_on_path = sum(s["ms"] for s in segments
                       if s["kind"] == "collective")

    # --- what-if estimators: deterministic replay of the round model
    base_wall_s = _replay(model)
    what_if = []
    for tl in timelines:
        p = tl.process
        others = [q for q in model.procs if q != p]
        w2 = dict(model.work_s)
        w2[p] = [_median([model.work_s[q][i] for q in others])
                 for i in range(len(rounds))]
        t2 = dict(model.tail_s)
        t2[p] = _median([model.tail_s[q] for q in others])
        s2 = dict(model.start_s)
        s2[p] = _median([model.start_s[q] for q in others])
        est = _replay(model, start_s=s2, work_s=w2, tail_s=t2)
        what_if.append(_whatif_row(
            WHATIF_PROC_MEDIAN.format(p=p), base_wall_s, est,
            f"process {p} at the peer-median speed per round"))
    # map/shuffle overlapped: each interval's exchange time hides
    # behind its map/produce time (the pipelined-transport bound)
    w_ov = {}
    for p in model.procs:
        ws = []
        for i, w in enumerate(model.work_s[p]):
            b = model.buckets.get((p, i), {})
            hidden = min(b.get("exchange", 0.0),
                         b.get("host_produce", 0.0)) / 1e3
            ws.append(max(w - hidden, 0.0))
        w_ov[p] = ws
    what_if.append(_whatif_row(
        WHATIF_OVERLAP, base_wall_s, _replay(model, work_s=w_ov),
        "per-round exchange wall hidden behind map production "
        "(pipelined shuffle upper bound)"))
    what_if.append(_whatif_row(
        WHATIF_FREE_COLLECTIVES, base_wall_s,
        _replay(model, coll_s=[0.0] * len(rounds)),
        "lockstep collective latency taken to zero"))
    what_if.sort(key=lambda w: -w["est_delta_ms"])

    top_p, top_row = max(blame.items(),
                         key=lambda kv: kv[1]["share_pct"])
    top_buckets: dict[str, float] = {}
    for s in segments:
        if s["kind"] in ("work", "tail") and str(s["process"]) == top_p:
            for k, v in (s.get("buckets") or {}).items():
                top_buckets[k] = top_buckets.get(k, 0.0) + v
    top_bucket = max(top_buckets, key=top_buckets.get) \
        if top_buckets else "work"
    doc = {
        "schema": CRITPATH_SCHEMA,
        "n_processes": len(timelines),
        "rounds": len(rounds),
        "wall_ms": round(wall_ms, 3),
        "path_ms": round(path_ms, 3),
        "path_over_wall_pct": round(100.0 * path_ms
                                    / max(wall_ms, 1e-9), 2),
        "model_wall_ms": round(base_wall_s * 1e3, 3),
        "model_error_pct": round(
            100.0 * abs(base_wall_s * 1e3 - wall_ms)
            / max(wall_ms, 1e-9), 2),
        "dag": {"nodes": sum(len(tl.spans) for tl in timelines),
                "edges": {"program": n_program_edges,
                          "barrier": n_barrier_edges,
                          "handoff": n_handoff}},
        "segments": segments,
        "blame": blame,
        "slack": slack,
        "collective_wait": {
            "on_path_ms": round(coll_on_path, 3),
            "share_pct": round(100.0 * coll_on_path
                               / max(path_ms, 1e-9), 2)},
        "what_if": what_if,
        "bound_by": f"proc {top_p} {top_bucket} "
                    f"({top_row['share_pct']:.0f}% blame)",
    }
    if coverage:
        doc["coverage"] = coverage
    return doc


def _whatif_row(name: str, base_s: float, est_s: float,
                description: str) -> dict:
    delta = max(base_s - est_s, 0.0)
    return {
        "name": name,
        "est_wall_ms": round(est_s * 1e3, 3),
        "est_delta_ms": round(delta * 1e3, 3),
        "est_delta_pct": round(100.0 * delta / max(base_s, 1e-9), 2),
        "description": description,
    }


def check_shard_identity(shards: list[dict]) -> None:
    """Mixed-identity or duplicate-slot shards are not one job: blending
    them (stale ``.proc2``/``.proc3`` next to a fresh 2-proc rerun, two
    copies of one slot) would produce a silently cross-job causal
    report.  Same refusal semantics as ``obs merge``."""
    metas = [s.get("meta", {}) for s in shards]
    ident = {(m.get("config_hash"), m.get("workload")) for m in metas}
    if len(ident) > 1:
        raise ValueError(
            f"shards disagree on (config_hash, workload): {sorted(ident)}"
            " — they are not shards of one job (remove stale .proc<i> "
            "files from an earlier run)")
    seen = [m.get("process") for m in metas]
    if len(set(seen)) != len(seen):
        raise ValueError(f"duplicate process slots in shards: {seen}")


def compute_from_shards(shards: list[dict], coverage: dict | None = None,
                        check_clock: bool = True) -> dict:
    """Critical path from shard documents: identity-check, align,
    clock-check, extract.  A single available shard degenerates to its
    attribution timeline (the named coverage gap rides the document)."""
    check_shard_identity(shards)
    tls = timelines_from_shards(shards)
    if check_clock:
        check_clock_alignment(tls)
    if len(tls) == 1:
        doc = degenerate_from_attrib(
            tls[0].attrib, process=tls[0].process)
        if coverage:
            doc["coverage"] = coverage
        return doc
    return compute(tls, coverage=coverage)


def compute_from_merged_events(events: list[dict]) -> dict:
    """Critical path from an already-merged Chrome trace (``obs merge``
    output; clock alignment already applied and checked at merge
    time)."""
    return compute(timelines_from_merged_events(events))


def degenerate_from_attrib(attrib_doc: dict | None,
                           process: int = 0) -> dict:
    """The single-process (single-chip) form: no cross-process DAG
    exists, so the path IS the attribution timeline — one segment per
    attrib bucket, blame 100% on the one process, no slack, and the
    feed-wait bucket as the overlap what-if (the part of host produce
    the pipeline did not hide)."""
    if not attrib_doc:
        raise ValueError(
            "no attribution document to degenerate onto (run with "
            "metrics enabled, or give a merged multi-process trace)")
    wall_ms = float(attrib_doc.get("wall_ms", 0.0))
    buckets = {name: float(row.get("ms", 0.0))
               for name, row in (attrib_doc.get("buckets") or {}).items()
               if row.get("ms")}
    attributed = sum(buckets.values())
    segments = [{"kind": "work", "process": process, "round": None,
                 "ms": round(ms, 3), "buckets": {name: round(ms, 3)}}
                for name, ms in sorted(buckets.items(),
                                       key=lambda kv: -kv[1])]
    top = max(buckets, key=buckets.get) if buckets else "unattributed"
    coll_ms = buckets.get("collective_wait", 0.0)
    feed_wait = buckets.get("feed_wait", 0.0)
    base_s = wall_ms / 1e3
    what_if = [_whatif_row(
        WHATIF_OVERLAP, base_s, (wall_ms - feed_wait) / 1e3,
        "pipeline feed waits fully hidden behind consumer work")]
    return {
        "schema": CRITPATH_SCHEMA,
        "n_processes": 1,
        "rounds": 0,
        "wall_ms": round(wall_ms, 3),
        "path_ms": round(attributed, 3),
        "path_over_wall_pct": round(100.0 * attributed
                                    / max(wall_ms, 1e-9), 2),
        "degenerate": "attrib-timeline",
        "segments": segments,
        "blame": {str(process): {"on_path_ms": round(attributed, 3),
                                 "share_pct": 100.0}},
        "slack": {},
        "collective_wait": {
            "on_path_ms": round(coll_ms, 3),
            "share_pct": round(100.0 * coll_ms
                               / max(attributed, 1e-9), 2)},
        "what_if": what_if,
        "bound_by": f"{top} "
                    f"({100.0 * buckets.get(top, 0.0) / max(wall_ms, 1e-9):.0f}% of wall)"
                    if buckets else "unattributed",
    }


# --- headline gauges + publication -----------------------------------------


def headline(doc: dict) -> dict:
    """The flat ``critpath/*`` gauges ledger entries carry (what
    ``obs diff --gate`` and ``obs trend`` watch, and what the
    ``critpath-process-blame`` SLO rule fires on)."""
    blame = doc.get("blame") or {}
    top_share = max((row.get("share_pct", 0.0) for row in blame.values()),
                    default=0.0)
    slack = doc.get("slack") or {}
    top_slack = max((row.get("slack_ms", 0.0) for row in slack.values()),
                    default=0.0)
    if doc.get("degenerate"):
        # single process: every path is 100% "this process", so the
        # bound fraction is the DOMINANT COST's share of wall instead
        # (the largest attrib bucket — what bound_by names)
        wall = float(doc.get("wall_ms") or 0.0)
        top_ms = max((s.get("ms", 0.0)
                      for s in doc.get("segments") or []), default=0.0)
        bound_frac = top_ms / wall if wall else 0.0
    else:
        bound_frac = top_share / 100.0
    out = {
        "critpath/bound_frac": round(bound_frac, 4),
        "critpath/top_process_slack_ms": round(top_slack, 3),
        "critpath/collective_wait_share_pct":
            (doc.get("collective_wait") or {}).get("share_pct", 0.0),
        "critpath/path_over_wall_pct": doc.get("path_over_wall_pct", 0.0),
        "critpath/bound_by": doc.get("bound_by", "?"),
    }
    if isinstance(doc.get("model_error_pct"), (int, float)):
        # the what-if replay's fidelity number, ledger-visible so
        # replay-model error and the planner's plan/model_error_pct
        # trend side by side (obs trend ranks both up-is-bad).  The
        # degenerate single-process doc never replays, so it carries
        # no error to publish
        out["critpath/model_error_pct"] = doc["model_error_pct"]
    if doc.get("n_processes", 1) > 1:
        # the process-blame share only exists where processes exist —
        # the degenerate single-chip form must NOT publish either gauge
        out["critpath/top_blame_share"] = round(top_share / 100.0, 4)
        # the robust straggler signal the SLO rule watches: the largest
        # "this process at peer-median speed" saving, as a fraction of
        # the wall.  Raw path ownership concentrates on the marginal
        # binder even when arrivals near-tie (a healthy 2-proc compile
        # round reads 99% blame on a coin-flip binder); the replay
        # saving is ~0 on a tie and large only when fixing ONE process
        # would actually move the wall — which is what "straggler on
        # the critical path" means
        save = max((w.get("est_delta_pct", 0.0)
                    for w in doc.get("what_if") or []
                    if w.get("name", "").startswith("proc_")),
                   default=0.0)
        out["critpath/straggler_save_frac"] = round(save / 100.0, 4)
    return out


def publish(registry, doc: dict) -> dict:
    """Set the headline gauges on a job registry (they ride the summary
    into the ledger entry, ``/metrics``, BENCH_DETAIL, and — after a
    final series sample — the SLO evaluator).  Returns the gauge map."""
    gauges = headline(doc)
    for k, v in gauges.items():
        registry.set(k, v)
    return gauges


# --- rendering -------------------------------------------------------------


def render(doc: dict, title: str = "critical path") -> str:
    """Human-readable report (the ``obs critpath`` stdout).  Pure, so
    tests pin it without artifacts."""
    wall_s = doc.get("wall_ms", 0.0) / 1e3
    lines = [f"{title}: wall {wall_s:.3f}s, path covers "
             f"{doc.get('path_over_wall_pct', 0.0):.1f}% "
             f"({doc.get('n_processes', 1)} process(es), "
             f"{doc.get('rounds', 0)} lockstep rounds)"]
    lines.append(f"bound by: {doc.get('bound_by', '?')}")
    cov = doc.get("coverage")
    if cov:
        missing = cov.get("missing_processes") or []
        torn = cov.get("torn_shards") or []
        if missing or torn:
            lines.append(
                "!! coverage gap: "
                + (f"missing shard(s) for process(es) {missing}"
                   if missing else "")
                + (" and " if missing and torn else "")
                + (f"torn shard(s) {torn}" if torn else "")
                + " — path computed from the surviving processes")
    blame = doc.get("blame") or {}
    if blame:
        lines.append("blame (share of on-path work):")
        for p, row in sorted(blame.items(),
                             key=lambda kv: -kv[1]["share_pct"]):
            bar = "#" * min(int(round(row["share_pct"] / 2.5)), 40)
            lines.append(f"  proc {p:<3} {row['on_path_ms'] / 1e3:>9.3f}s "
                         f"{row['share_pct']:>5.1f}%  {bar}")
    cw = doc.get("collective_wait") or {}
    if cw:
        lines.append(f"on-path collective wait: "
                     f"{cw.get('on_path_ms', 0.0) / 1e3:.3f}s "
                     f"({cw.get('share_pct', 0.0):.1f}% of path)")
    slack = doc.get("slack") or {}
    if slack:
        lines.append("slack (how much each process could slow for free, "
                     "distributed as its barrier waits):")
        for p, row in sorted(slack.items()):
            b = row.get("binding_round")
            lines.append(
                f"  proc {p:<3} {row['slack_ms'] / 1e3:>9.3f}s  "
                f"(tightest at round {b}; tail headroom "
                f"{row.get('end_gap_ms', 0.0) / 1e3:.3f}s)")
    what_if = doc.get("what_if") or []
    if what_if:
        lines.append("what-if (deterministic replay of the round model):")
        for w in what_if[:6]:
            lines.append(
                f"  {w['name']:<34} wall -{w['est_delta_pct']:>5.1f}% "
                f"(-{w['est_delta_ms'] / 1e3:.3f}s) — {w['description']}")
    segs = doc.get("segments") or []
    if segs and not doc.get("degenerate"):
        lines.append(f"path segments ({len(segs)}):")
        for s in segs[:24]:
            who = ("collective" if s["kind"] == "collective"
                   else f"proc {s['process']} {s['kind']}")
            b = s.get("buckets") or {}
            top = (" [" + ", ".join(
                f"{k} {v / 1e3:.2f}s" for k, v in sorted(
                    b.items(), key=lambda kv: -kv[1])[:3]) + "]"
                if b else "")
            r = f" r{s['round']}" if s.get("round") is not None else ""
            lines.append(f"  {s['ms'] / 1e3:>8.3f}s  {who}{r}{top}")
        if len(segs) > 24:
            lines.append(f"  ... {len(segs) - 24} more")
    elif doc.get("degenerate"):
        lines.append("(single process: path degenerates to the "
                     "attribution timeline)")
        for s in segs[:12]:
            name = next(iter(s.get("buckets") or {"work": 0}))
            lines.append(f"  {s['ms'] / 1e3:>8.3f}s  {name}")
    return "\n".join(lines)
