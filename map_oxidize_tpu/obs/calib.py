"""Persistent cross-run calibration store: measured collective and
program costs that SURVIVE the process.

ROADMAP item 3 (portable collectives, arXiv:2112.01075) picks collective
decompositions from *measured* per-(collective, program, shape)
bytes/latency tables, and the auto dispatch-batch roofline wants warm
per-program dispatch/compute figures on a COLD process — but until this
module every measurement died with the job: the comms observatory and
the compile ledger are in-memory.  The calibration store accumulates
them across runs:

* one versioned JSON document (``<calib_dir>/calib.json``,
  ``moxt-calib-v1``) holding **comms rows** keyed
  ``(platform, device-count, topology, collective, program,
  shape-bucket)`` — calls, payload bytes, sampled latency mass —
  **program rows** keyed ``(platform, device-count, topology, program)``
  — dispatches, dispatch wall, sampled device compute, compiles — and
  **workload rows** keyed ``(platform, device-count, topology,
  workload)`` — corpus bytes, wall, and per-attribution-bucket wall
  mass, the shape the job planner's wall prediction is read from
  (``runtime/planner.py``);
* shape-bucket is the power-of-two floor of the per-call payload
  (``"1MB"`` covers [1MB, 2MB)): close payloads share a row, so curves
  accumulate density instead of exploding per exact shape;
* loaded at ``Obs.from_config`` (``obs.calib``), accumulated from the
  job's comms table + xprof report at ``Obs.finish``, and **merged
  atomically** into the store file: the merge re-reads the file under an
  ``flock`` and writes temp+rename, so concurrent finishing processes
  (a 2-process job, a resident server's workers) interleave safely;
* merges REFUSE mismatches instead of corrupting evidence: an unknown
  schema/version refuses wholesale, and a row whose key disagrees with
  its stored identity fields (a doctored or torn store) refuses too —
  ``calib/merge_refused`` lands as a gauge either way.

``obs calib`` renders the store as per-collective bandwidth curves —
the measurement substrate ROADMAP items 2 and 3 consume.  The
**read side** of those curves lives here too: :func:`program_curve`,
:func:`workload_curve` and :func:`interpolate_latency_ms` turn the
accumulated mass back into per-call / per-MB rates the planner and the
collective chooser consume.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time

from map_oxidize_tpu.utils.logging import get_logger

_log = get_logger(__name__)

CALIB_SCHEMA = "moxt-calib-v1"
CALIB_VERSION = 1
CALIB_FILE = "calib.json"

#: identity fields every row carries (and its key encodes).  ``source``
#: is evidence provenance — ``"job"`` rows accumulated as a side effect
#: of real runs, ``"probe"`` rows written by the deterministic
#: microbenchmark harness (:mod:`map_oxidize_tpu.obs.probe`) — kept IN
#: the identity so the two never merge into one row (never
#: double-trusted), while the read-side curves pool them explicitly.
_COMM_IDENTITY = ("platform", "device_count", "topology", "collective",
                  "program", "shape_bucket", "source")
_PROG_IDENTITY = ("platform", "device_count", "topology", "program")
_WORKLOAD_IDENTITY = ("platform", "device_count", "topology", "workload")

#: legal evidence provenance tags (trailing ``_COMM_IDENTITY`` field)
_SOURCES = ("job", "probe")

#: ``obs diff --gate``: coverage dropping more than this many points
#: against the baseline entry flags (the chooser went from informed to
#: guessing — gate before the guess costs a mispredicted job)
CALIB_COVERAGE_GATE_POINTS = 10.0

#: selection floor: below this many sampled latencies in the exact
#: bucket the chooser refuses to trust a curve (named reason, default
#: kept) — 1–2 samples is an anecdote, not evidence
CALIB_MIN_SAMPLES = 3

#: jax-free mirror of ``parallel.shuffle.EXCHANGE_COLLECTIVES`` (this
#: module must stay importable on jax-free CLI paths; a parity test
#: pins the two tuples)
EXCHANGE_COLLECTIVE_NAMES = ("all_to_all", "all_gather")


def exchange_shape(num_shards: int, batch_size: int,
                   collect: bool = False) -> tuple:
    """The ``(bucket_cap, value_row_bytes)`` the engines will derive for
    a job of this shape — the jax-free mirror of the fold engine's cap
    derivation (``parallel.shuffle.build_sharded_ops``) and the
    pair-collect engines' full-batch cap, shared by the planner's
    chooser call and ``obs calib coverage`` so both price the exchange
    at the same payload bucket the run will record."""
    S = max(int(num_shards), 1)
    bps = max(1, int(batch_size) // S)
    if collect:
        return bps, 8
    return min(bps, 2 * (-(-bps // S)) + 16), 4


class CalibMismatch(ValueError):
    """The store (or a merge source) is not compatible: wrong schema/
    version, or a row's key disagrees with its identity fields."""


def shape_bucket(nbytes_per_call: float) -> str:
    """Power-of-two payload bucket label: ``"64KB"`` = [64KB, 128KB)."""
    n = int(nbytes_per_call)
    if n <= 0:
        return "0B"
    k = n.bit_length() - 1
    floor = 1 << k
    for scale, suffix in ((1 << 40, "TB"), (1 << 30, "GB"),
                          (1 << 20, "MB"), (1 << 10, "KB")):
        if floor >= scale:
            return f"{floor // scale}{suffix}"
    return f"{floor}B"


def run_identity(n_processes: int = 1) -> dict:
    """This run's (platform, device-count, topology) triple.  Reads only
    an ALREADY-initialized jax (never forces backend init); host-only
    jobs calibrate under ``platform="host"``."""
    platform, count = "host", 0
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            devices = jax.devices()
            platform = devices[0].platform
            count = len(devices)
        except Exception:
            pass
    return {
        "platform": platform,
        "device_count": count,
        "topology": f"{max(n_processes, 1)}x{count}",
    }


def _comm_key(ident: dict, collective: str, program: str,
              bucket: str, source: str = "job") -> str:
    return "|".join([ident["platform"], str(ident["device_count"]),
                     ident["topology"], collective, program, bucket,
                     source])


def _normalize_legacy_comms(doc: dict) -> None:
    """Rewrite pre-``source`` comms rows (6-part keys) in place to the
    7-part form, tagging them ``source="job"`` — every legacy row WAS
    organic job evidence.  Runs before :func:`validate_doc` so a store
    written by an older build still loads/merges instead of refusing."""
    if not isinstance(doc, dict):
        return
    comms = doc.get("comms")
    if not isinstance(comms, dict):
        return
    legacy = [k for k in comms
              if isinstance(k, str)
              and len(k.split("|")) == len(_COMM_IDENTITY) - 1]
    for key in legacy:
        row = comms.pop(key)
        if isinstance(row, dict):
            row.setdefault("source", "job")
        comms[key + "|job"] = row


def _prog_key(ident: dict, program: str) -> str:
    return "|".join([ident["platform"], str(ident["device_count"]),
                     ident["topology"], program])


def _workload_key(ident: dict, workload: str) -> str:
    return "|".join([ident["platform"], str(ident["device_count"]),
                     ident["topology"], workload])


class CalibStore:
    """In-memory form of the store document, with accumulate/merge/save.

    ``doc`` is the JSON shape on disk: ``{"schema", "version", "comms":
    {key: row}, "programs": {key: row}, "runs", "updated_unix_s"}``."""

    def __init__(self, path: str | None = None, doc: dict | None = None):
        self.path = path
        self.doc = doc if doc is not None else {
            "schema": CALIB_SCHEMA, "version": CALIB_VERSION,
            "comms": {}, "programs": {}, "runs": 0,
        }

    # --- load / validate --------------------------------------------------

    @classmethod
    def load(cls, path: str) -> "CalibStore":
        """Load ``<path>`` (a calib.json, or a directory holding one).
        A missing file is an empty store; an incompatible one REFUSES
        (:class:`CalibMismatch`) — stale evidence must never silently
        merge with a new schema's."""
        if os.path.isdir(path):
            path = os.path.join(path, CALIB_FILE)
        store = cls(path=path)
        try:
            with open(path) as f:
                doc = json.load(f)
        except FileNotFoundError:
            return store
        except (OSError, ValueError) as e:
            raise CalibMismatch(f"unreadable calibration store {path!r}: "
                                f"{e}") from e
        _normalize_legacy_comms(doc)
        validate_doc(doc, path)
        store.doc = doc
        return store

    # --- accumulation (one run's measurements) ----------------------------

    def accumulate_run(self, ident: dict, comms_rows: list | None,
                       xprof_report: dict | None,
                       source: str = "job") -> int:
        """Fold one finished run's comms table + xprof program rows into
        this store under ``ident``, tagged with evidence ``source``
        (``"job"`` for organic runs, ``"probe"`` for the microbenchmark
        harness).  Returns the number of rows touched."""
        if source not in _SOURCES:
            raise ValueError(f"source must be one of {_SOURCES}, "
                             f"got {source!r}")
        touched = 0
        for r in comms_rows or []:
            calls = int(r.get("count") or 0)
            nbytes = float(r.get("bytes") or 0.0)
            if calls <= 0:
                continue
            bucket = shape_bucket(nbytes / calls)
            key = _comm_key(ident, r["collective"], r["program"], bucket,
                            source)
            row = self.doc["comms"].get(key)
            if row is None:
                row = self.doc["comms"][key] = dict(
                    ident, collective=r["collective"],
                    program=r["program"], shape_bucket=bucket,
                    source=source, calls=0, bytes=0.0, latency_ms=0.0,
                    latency_samples=0, runs=0)
            lat = r.get("latency_ms") or {}
            samples = int(lat.get("count") or 0)
            row["calls"] += calls
            row["bytes"] += nbytes
            row["latency_ms"] += float(lat.get("mean") or 0.0) * samples
            row["latency_samples"] += samples
            row["runs"] += 1
            row["last_shape"] = r.get("shape")
            touched += 1
        for name, p in ((xprof_report or {}).get("programs") or {}).items():
            dispatches = int(p.get("dispatches") or 0)
            compiles = int(p.get("compiles") or 0)
            if dispatches <= 0 and compiles <= 0:
                continue
            key = _prog_key(ident, name)
            row = self.doc["programs"].get(key)
            if row is None:
                row = self.doc["programs"][key] = dict(
                    ident, program=name, dispatches=0, dispatch_ms=0.0,
                    compute_ms=0.0, compute_samples=0, compiles=0,
                    compile_ms=0.0, runs=0)
            row["dispatches"] += dispatches
            row["dispatch_ms"] += float(p.get("dispatch_ms") or 0.0)
            row["compute_ms"] += float(p.get("sampled_device_ms") or 0.0)
            row["compute_samples"] += int(p.get("device_samples") or 0)
            row["compiles"] += compiles
            row["compile_ms"] += float(p.get("compile_ms") or 0.0)
            row["runs"] += 1
            touched += 1
        if touched:
            self.doc["runs"] = int(self.doc.get("runs") or 0) + 1
        return touched

    def accumulate_workload(self, ident: dict, workload: str,
                            corpus_bytes: float,
                            attrib_doc: dict | None) -> int:
        """Fold one finished run's wall attribution into the per-workload
        curve row under ``ident`` — the mass :func:`workload_curve`
        turns back into the planner's per-MB wall prediction.  Bucket
        fields are flat (``bucket_<name>_ms``) so the generic numeric
        merge in :meth:`merge_from` accumulates them like any other
        counter.  Returns rows touched (0/1)."""
        if not workload or not attrib_doc:
            return 0
        wall = float(attrib_doc.get("wall_ms") or 0.0)
        if wall <= 0 or not corpus_bytes or corpus_bytes <= 0:
            return 0
        workloads = self.doc.setdefault("workloads", {})
        key = _workload_key(ident, workload)
        row = workloads.get(key)
        if row is None:
            row = workloads[key] = dict(
                ident, workload=workload, runs=0, corpus_bytes=0.0,
                wall_ms=0.0, unattributed_ms=0.0)
        row["runs"] += 1
        row["corpus_bytes"] += float(corpus_bytes)
        row["wall_ms"] += wall
        row["unattributed_ms"] += float(
            attrib_doc.get("unattributed_ms") or 0.0)
        for name, b in (attrib_doc.get("buckets") or {}).items():
            f = f"bucket_{name}_ms"
            row[f] = float(row.get(f, 0.0)) + float(b.get("ms") or 0.0)
        return 1

    # --- merge / persist --------------------------------------------------

    def merge_from(self, other: dict) -> None:
        """Fold another store DOCUMENT into this one (legacy comms keys
        normalized to the ``source``-tagged form, then validated)."""
        _normalize_legacy_comms(other)
        validate_doc(other)
        for section in ("comms", "programs", "workloads"):
            for key, row in (other.get(section) or {}).items():
                mine = self.doc.setdefault(section, {}).get(key)
                if mine is None:
                    self.doc[section][key] = dict(row)
                    continue
                for field, v in row.items():
                    if isinstance(v, bool) or not isinstance(
                            v, (int, float)):
                        mine.setdefault(field, v)
                    elif field in _COMM_IDENTITY or field == "device_count":
                        pass  # identity fields never accumulate
                    else:
                        mine[field] = mine.get(field, 0) + v
        self.doc["runs"] = (int(self.doc.get("runs") or 0)
                            + int(other.get("runs") or 0))

    def save_merged(self) -> str:
        """Atomic read-merge-write of ``self.path``: under an ``flock``
        on a sidecar lock file, re-read whatever is on disk now (another
        process may have merged since we loaded), fold it in, write
        temp+rename.  Refuses (raises :class:`CalibMismatch`) instead of
        overwriting an incompatible store."""
        if not self.path:
            raise ValueError("store has no path")
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        lock_path = self.path + ".lock"
        lock_fd = os.open(lock_path, os.O_WRONLY | os.O_CREAT, 0o644)
        try:
            try:
                import fcntl

                fcntl.flock(lock_fd, fcntl.LOCK_EX)
            except ImportError:  # pragma: no cover - non-POSIX
                pass
            try:
                with open(self.path) as f:
                    on_disk = json.load(f)
            except FileNotFoundError:
                on_disk = None
            except (OSError, ValueError) as e:
                raise CalibMismatch(
                    f"unreadable calibration store {self.path!r}: {e}"
                ) from e
            if on_disk is not None:
                # self.doc holds ONLY this run's rows (the Obs wiring
                # seeds an empty store for accumulation; the prior
                # history loaded at job start is a separate read-only
                # object), so on-disk + ours never double-counts — even
                # when another process merged between our load and now
                merged = CalibStore(path=self.path)
                merged.merge_from(on_disk)   # validates on_disk
                merged.merge_from(self.doc)
                self.doc = merged.doc
            self.doc["updated_unix_s"] = round(time.time(), 3)
            from map_oxidize_tpu.obs import write_json_atomic

            write_json_atomic(self.path, self.doc)
        finally:
            os.close(lock_fd)
        return self.path

    # --- reporting --------------------------------------------------------

    def bandwidth_table(self) -> list[dict]:
        """Per-(identity, collective, program, shape-bucket) bandwidth
        rows, bytes-heaviest first.  ``gbytes_per_s`` needs sampled
        latency; rows without samples still carry calls/bytes."""
        rows = []
        for row in self.doc.get("comms", {}).values():
            calls = row.get("calls") or 0
            out = dict(row)
            if calls:
                out["bytes_per_call"] = row["bytes"] / calls
            samples = row.get("latency_samples") or 0
            if samples and row.get("latency_ms"):
                mean_ms = row["latency_ms"] / samples
                out["mean_latency_ms"] = round(mean_ms, 4)
                if calls:
                    out["gbytes_per_s"] = round(
                        (row["bytes"] / calls) / (mean_ms / 1e3) / 1e9, 4)
            rows.append(out)
        rows.sort(key=lambda r: -(r.get("bytes") or 0))
        return rows

    def program_table(self) -> list[dict]:
        rows = []
        for row in self.doc.get("programs", {}).values():
            out = dict(row)
            n = row.get("dispatches") or 0
            if n:
                out["dispatch_ms_per_call"] = round(
                    row["dispatch_ms"] / n, 4)
            s = row.get("compute_samples") or 0
            if s:
                out["compute_ms_per_sample"] = round(
                    row["compute_ms"] / s, 4)
            rows.append(out)
        rows.sort(key=lambda r: -(r.get("dispatch_ms") or 0))
        return rows


def validate_doc(doc: dict, path: str = "") -> None:
    """Schema/version/identity-consistency check; raises
    :class:`CalibMismatch` with the named reason."""
    where = f" ({path})" if path else ""
    if not isinstance(doc, dict) or doc.get("schema") != CALIB_SCHEMA:
        raise CalibMismatch(
            f"not a {CALIB_SCHEMA} store{where}: schema="
            f"{doc.get('schema') if isinstance(doc, dict) else type(doc)}")
    if doc.get("version") != CALIB_VERSION:
        raise CalibMismatch(
            f"calibration store version {doc.get('version')!r} != "
            f"supported {CALIB_VERSION}{where}; refusing to merge")
    for section, ident_fields in (("comms", _COMM_IDENTITY),
                                  ("programs", _PROG_IDENTITY),
                                  ("workloads", _WORKLOAD_IDENTITY)):
        for key, row in (doc.get(section) or {}).items():
            parts = key.split("|")
            if len(parts) != len(ident_fields):
                raise CalibMismatch(
                    f"malformed {section} key {key!r}{where}")
            for field, part in zip(ident_fields, parts):
                stored = row.get(field)
                if str(stored) != part:
                    raise CalibMismatch(
                        f"{section} row {key!r}: stored {field}="
                        f"{stored!r} disagrees with its key{where}; "
                        "refusing to merge a torn/doctored store")


# --- read-side curve APIs (the planner's substrate) ------------------------


def program_curve(store: "CalibStore | None", ident: dict,
                  program: str) -> dict | None:
    """The store's warm per-call figures for one program under this
    identity: ``dispatch_ms_per_call`` (the launch floor) and
    ``compute_ms_per_sample`` — the cross-process form of the compile
    ledger's in-memory measurements, what a COLD process plans auto-B
    from.  None when the store has no usable row."""
    if store is None:
        return None
    row = (store.doc.get("programs") or {}).get(_prog_key(ident, program))
    if not row:
        return None
    out: dict = {"runs": int(row.get("runs") or 0)}
    n = row.get("dispatches") or 0
    if n and row.get("dispatch_ms"):
        out["dispatch_ms_per_call"] = float(row["dispatch_ms"]) / n
    s = row.get("compute_samples") or 0
    if s and row.get("compute_ms"):
        out["compute_ms_per_sample"] = float(row["compute_ms"]) / s
    return out if len(out) > 1 else None


def workload_curve(store: "CalibStore | None", ident: dict,
                   workload: str) -> dict | None:
    """The store's per-MB wall rates for one workload under this
    identity: ``wall_ms_per_mb`` plus ``buckets_ms_per_mb`` in the SAME
    bucket names ``obs where`` attributes — the planner multiplies them
    by the new corpus's size for its predicted wall.  None when the
    store has no row with positive bytes and wall."""
    if store is None:
        return None
    row = (store.doc.get("workloads") or {}).get(
        _workload_key(ident, workload))
    if not row:
        return None
    mb = float(row.get("corpus_bytes") or 0.0) / (1 << 20)
    wall = float(row.get("wall_ms") or 0.0)
    if mb <= 0 or wall <= 0:
        return None
    runs = int(row.get("runs") or 1)
    curve = {
        "runs": runs,
        "wall_ms_per_mb": wall / mb,
        "mean_corpus_bytes": float(row["corpus_bytes"]) / max(runs, 1),
        "buckets_ms_per_mb": {},
    }
    for f, v in row.items():
        if f.startswith("bucket_") and f.endswith("_ms"):
            curve["buckets_ms_per_mb"][f[len("bucket_"):-len("_ms")]] = (
                float(v) / mb)
    return curve


def interpolate_latency_ms(store: "CalibStore | None", ident: dict,
                           collective: str, nbytes: float,
                           program: str | None = None) -> float | None:
    """Read-side interpolation over the per-shape-bucket latency curve:
    the expected one-call latency of ``collective`` at payload
    ``nbytes`` under this identity, log-linear in payload between the
    measured bucket means and clamped at the curve's ends (collective
    cost is near-affine in log-payload across the bucket range — the
    portable-collectives premise).  ``program=None`` pools rows across
    programs.  None when no sampled row matches."""
    if store is None:
        return None
    pts = []
    for row in (store.doc.get("comms") or {}).values():
        if (row.get("platform") != ident["platform"]
                or str(row.get("device_count")) != str(
                    ident["device_count"])
                or row.get("topology") != ident["topology"]
                or row.get("collective") != collective):
            continue
        if program is not None and row.get("program") != program:
            continue
        calls = row.get("calls") or 0
        samples = row.get("latency_samples") or 0
        if calls and samples and row.get("latency_ms"):
            pts.append((float(row["bytes"]) / calls,
                        float(row["latency_ms"]) / samples))
    if not pts:
        return None
    pts.sort()
    x = max(float(nbytes), 1.0)
    if x <= pts[0][0]:
        return pts[0][1]
    if x >= pts[-1][0]:
        return pts[-1][1]
    for (x0, y0), (x1, y1) in zip(pts, pts[1:]):
        if x0 <= x <= x1:
            if x1 <= x0:
                return y1
            t = ((math.log(x) - math.log(x0))
                 / (math.log(x1) - math.log(x0)))
            return y0 + t * (y1 - y0)
    return pts[-1][1]  # pragma: no cover - unreachable past the clamp


# --- the coverage plane (needs vs has) --------------------------------------


def bucket_index(label: str) -> int | None:
    """A shape-bucket label's power-of-two exponent (``"64KB"`` → 16),
    the x-axis the coverage distance is measured on.  None for
    unparsable or zero buckets."""
    if not isinstance(label, str) or not label:
        return None
    for suffix, scale in (("TB", 1 << 40), ("GB", 1 << 30),
                          ("MB", 1 << 20), ("KB", 1 << 10), ("B", 1)):
        if label.endswith(suffix):
            try:
                n = int(label[:-len(suffix)]) * scale
            except ValueError:
                return None
            return n.bit_length() - 1 if n > 0 else None
    return None


def collective_evidence(store: "CalibStore | None", ident: dict,
                        collective: str, bucket: str,
                        program: str | None = None) -> dict:
    """What the store KNOWS about one (collective, bucket) cell under
    this identity: sampled-latency counts in the exact bucket (total and
    split by evidence ``source`` — probe and job rows pool for density
    but stay attributable), plus ``bucket_distance`` — how many pow2
    steps the nearest sampled bucket is from the needed one (0 = exact
    hit; None = no sampled curve for this collective at all, i.e. a
    cold cell where even extrapolation has nothing to extrapolate
    from)."""
    want = bucket_index(bucket)
    samples = 0
    by_source: dict[str, int] = {}
    sampled: dict[str, int] = {}
    for row in ((store.doc.get("comms") or {}).values()
                if store is not None else ()):
        if (row.get("platform") != ident["platform"]
                or str(row.get("device_count")) != str(
                    ident["device_count"])
                or row.get("topology") != ident["topology"]
                or row.get("collective") != collective):
            continue
        if program is not None and row.get("program") != program:
            continue
        s = int(row.get("latency_samples") or 0)
        if s <= 0:
            continue
        b = row.get("shape_bucket")
        sampled[b] = sampled.get(b, 0) + s
        if b == bucket:
            samples += s
            src = row.get("source", "job")
            by_source[src] = by_source.get(src, 0) + s
    distance: int | None = None
    if want is not None:
        idxs = [i for i in (bucket_index(b) for b in sampled)
                if i is not None]
        if idxs:
            distance = min(abs(want - i) for i in idxs)
    return {
        "bucket": bucket, "samples": samples, "by_source": by_source,
        "bucket_distance": distance,
        "sampled_buckets": sorted(sampled, key=lambda b:
                                  bucket_index(b) or 0),
    }


def coverage_report(store: "CalibStore | None", ident: dict,
                    needed_cells: list[dict],
                    min_samples: int = CALIB_MIN_SAMPLES) -> dict:
    """Needs-vs-has over the planner's required (collective, program,
    bucket) cells: a cell is COVERED when the store holds at least
    ``min_samples`` sampled latencies in the exact bucket.
    ``coverage_pct`` is the covered fraction; ``extrapolation_bucket_
    distance`` the worst pow2-step gap the chooser would have to
    extrapolate across (cells with no curve at all are uncovered but
    excluded from the distance — there is nothing to extrapolate
    from)."""
    cells = []
    covered = 0
    distances = []
    for need in needed_cells:
        ev = collective_evidence(store, ident, need["collective"],
                                 need["bucket"],
                                 program=need.get("program"))
        ok = (ev["samples"] >= min_samples
              and ev["bucket_distance"] == 0)
        covered += int(ok)
        if ev["bucket_distance"] is not None:
            distances.append(ev["bucket_distance"])
        cells.append({
            "collective": need["collective"],
            "program": need.get("program"),
            "bucket": need["bucket"], "samples": ev["samples"],
            "by_source": ev["by_source"],
            "bucket_distance": ev["bucket_distance"], "covered": ok,
        })
    needed = len(cells)
    return {
        "schema": "moxt-calib-coverage-v1",
        "identity": dict(ident), "min_samples": int(min_samples),
        "needed": needed, "covered": covered,
        "coverage_pct": round(100.0 * covered / needed, 1) if needed
        else 100.0,
        "extrapolation_bucket_distance": max(distances) if distances
        else 0,
        "cells": cells,
    }


def render_coverage(report: dict) -> str:
    """Human-readable needs-vs-has table (`obs calib coverage`)."""
    ident = report.get("identity") or {}
    lines = [
        f"calibration coverage: {report['covered']}/{report['needed']} "
        f"cells covered ({report['coverage_pct']}%) under "
        f"{ident.get('platform')}/{ident.get('topology')} "
        f"(min {report['min_samples']} samples/cell); worst "
        f"extrapolation distance "
        f"{report['extrapolation_bucket_distance']} bucket(s)",
        f"  {'collective':<11} {'program':<26} {'bucket':>7} "
        f"{'samples':>8} {'dist':>5}  status",
    ]
    for c in report.get("cells") or []:
        srcs = ",".join(f"{k}:{v}" for k, v in
                        sorted((c.get("by_source") or {}).items()))
        dist = c["bucket_distance"]
        status = ("covered" if c["covered"] else
                  "no curve" if dist is None else
                  f"extrapolated ({dist} away)" if dist else
                  "thin evidence")
        lines.append(
            f"  {c['collective']:<11} {c.get('program') or '*':<26} "
            f"{c['bucket']:>7} {c['samples']:>8} "
            f"{'-' if dist is None else dist:>5}  {status}"
            + (f" [{srcs}]" if srcs else ""))
    return "\n".join(lines)


# --- rendering (the `obs calib` table) -------------------------------------


from map_oxidize_tpu.obs.metrics import format_bytes as _fmt_bytes  # noqa: E402 - rendering helper


def render(store: CalibStore) -> str:
    """Human-readable store report: the bandwidth curves (grouped by
    identity + collective + program, one line per shape-bucket) and the
    per-program dispatch/compute table."""
    doc = store.doc
    lines = [f"calibration store: {doc.get('runs', 0)} runs merged"
             + (f", updated {time.strftime('%Y-%m-%dT%H:%M:%S', time.localtime(doc['updated_unix_s']))}"
                if doc.get("updated_unix_s") else "")]
    comms = store.bandwidth_table()
    if comms:
        lines.append("collective bandwidth (per shape bucket; rows with "
                     f"< {CALIB_MIN_SAMPLES} samples marked 'thin' — "
                     "below the selection floor):")
        by_source: dict[str, list] = {}
        for r in comms:
            by_source.setdefault(r.get("source", "job"), []).append(r)
        for src in sorted(by_source):
            lines.append(f" source={src}:")
            lines.append(f"  {'identity':<12} {'collective':<11} "
                         f"{'program':<24} {'bucket':>7} {'calls':>7} "
                         f"{'bytes':>9} {'smpl':>5} {'lat ms':>8} "
                         f"{'GB/s':>7}")
            for r in by_source[src]:
                ident = f"{r['platform']}/{r['topology']}"
                samples = int(r.get("latency_samples") or 0)
                thin = ("  thin" if 0 < samples < CALIB_MIN_SAMPLES
                        else "")
                lines.append(
                    f"  {ident:<12} {r['collective']:<11} "
                    f"{r['program']:<24} "
                    f"{r['shape_bucket']:>7} {r['calls']:>7} "
                    f"{_fmt_bytes(r['bytes']):>9} {samples:>5} "
                    f"{r.get('mean_latency_ms', '-'):>8} "
                    f"{r.get('gbytes_per_s', '-'):>7}{thin}")
    else:
        lines.append("no collective rows yet (runs with a multi-shard "
                     "mesh or multi-process exchange populate them)")
    progs = store.program_table()
    if progs:
        lines.append("program dispatch/compute:")
        lines.append(f"  {'identity':<12} {'program':<28} {'disp':>7} "
                     f"{'ms/disp':>8} {'compute ms':>10} {'compiles':>8}")
        for r in progs[:20]:
            ident = f"{r['platform']}/{r['topology']}"
            lines.append(
                f"  {ident:<12} {r['program']:<28} {r['dispatches']:>7} "
                f"{r.get('dispatch_ms_per_call', '-'):>8} "
                f"{r.get('compute_ms_per_sample', '-'):>10} "
                f"{r['compiles']:>8}")
    return "\n".join(lines)
