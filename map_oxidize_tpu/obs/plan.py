"""Plan observatory: the predicted-vs-actual side of the job planner.

``runtime/planner.py`` solves the knobs and predicts the wall; this
module is where that plan meets the obs stack:

* :func:`publish` flattens the plan document onto the registry as
  ``plan/*`` gauges at job START — chosen knob values, per-knob
  provenance, the predicted wall — so the plan rides ``/status``, the
  time series, and (via the summary) the run ledger and BENCH_DETAIL;
* :func:`finalize` scores the plan at ``Obs.finish``: the measured
  attribution doc becomes the plan's ``actual`` section, and — when
  the plan actually predicted (``curve`` provenance; a cold run
  records ``platform_default`` instead of pretending) —
  ``plan/model_error_pct`` = \\|predicted − actual\\| / actual wall
  lands as the gated gauge;
* :func:`render` is the ``obs plan`` report: per-knob choices with
  provenance, and predicted vs actual per attribution bucket.

The error gauge is watched twice: ``obs diff --gate`` fails when
prediction error DEGRADES by more than :data:`PLAN_ERROR_GATE_POINTS`
percentage points over the previous comparable run (obs/ledger.py),
and the ``plan-model-drift`` default SLO rule (obs/slo.py) fires when
a resident server's median prediction error goes stale.
"""

from __future__ import annotations

PLAN_SCHEMA = "moxt-plan-v1"

#: ``obs diff --gate``: prediction error growing by more than this many
#: percentage points over the previous comparable run flags — the
#: planner's performance model no longer describes the machine (stale
#: or doctored calibration curves, an unmodeled cost change).  Points,
#: not relative percent: 8% -> 20% is model noise on short runs, 8% ->
#: 300% is a broken model
PLAN_ERROR_GATE_POINTS = 50.0

#: the provenance taxonomy (docs/OBSERVABILITY.md "Planner & prediction
#: error"): per-knob ``curve``/``memo``/``default``/``pinned``, plus
#: the plan-level ``platform_default`` a cold run records
PROVENANCES = ("curve", "memo", "default", "pinned", "platform_default")


def publish(registry, doc: dict) -> None:
    """Flatten the plan onto the registry at job start: ``plan/mode``,
    ``plan/provenance``, per-knob ``plan/<knob>`` +
    ``plan/<knob>_provenance``, and ``plan/predicted_wall_ms`` when the
    plan predicted."""
    if registry is None or not doc:
        return
    registry.set("plan/mode", doc.get("mode", "auto"))
    registry.set("plan/provenance",
                 doc.get("provenance", "platform_default"))
    for name, row in (doc.get("knobs") or {}).items():
        v = row.get("value")
        if v is not None:
            registry.set(f"plan/{name}", v)
        registry.set(f"plan/{name}_provenance",
                     row.get("provenance", "?"))
    pred = doc.get("predicted")
    if pred and pred.get("wall_ms") is not None:
        registry.set("plan/predicted_wall_ms", pred["wall_ms"])
    # the coverage plane: needs-vs-has over the chooser's consulted
    # cells, on EVERY planned job (both gauges gate in `obs diff`)
    cov = doc.get("coverage")
    if cov:
        registry.set("calib/coverage_pct", cov.get("coverage_pct"))
        registry.set("calib/extrapolation_bucket_distance",
                     cov.get("extrapolation_bucket_distance"))


def finalize(obs, doc: dict, attrib_doc: dict | None) -> dict:
    """Score the plan against the measured run (``Obs.finish``, after
    the attribution finalize): attach the ``actual`` section and — when
    the plan predicted — compute ``plan/model_error_pct``.  Mutates and
    returns ``doc``."""
    if not attrib_doc:
        return doc
    actual = {
        "wall_ms": attrib_doc.get("wall_ms"),
        "buckets": {name: row.get("ms")
                    for name, row
                    in (attrib_doc.get("buckets") or {}).items()},
        "unattributed_ms": attrib_doc.get("unattributed_ms"),
    }
    doc["actual"] = actual
    pred = doc.get("predicted")
    wall = actual.get("wall_ms")
    if pred and pred.get("wall_ms") and wall:
        err = (100.0 * abs(float(pred["wall_ms"]) - float(wall))
               / max(float(wall), 1e-9))
        doc["model_error_pct"] = round(err, 2)
        obs.registry.set("plan/model_error_pct", doc["model_error_pct"])
        obs.registry.set("plan/actual_wall_ms", wall)
    # score the exchange-collective decision: the chooser predicted a
    # per-exchange latency from the store curve; the run measured the
    # real one (sampled collective walls in the comms table).  Both land
    # in the decision doc so the ledger / `obs plan` can say whether the
    # substitution actually paid.
    ex = doc.get("exchange")
    if ex and ex.get("method"):
        rows = [r for r in obs.registry.comms_table()
                if r.get("collective") == ex["method"]
                and r.get("latency_ms")]
        if rows:
            best = max(rows, key=lambda r: r["latency_ms"]["count"])
            ex["actual_ms_per_exchange"] = round(
                best["latency_ms"]["mean"], 4)
            ev = (ex.get("evidence") or {}).get(ex["method"])
            if isinstance(ev, dict) and ev.get("predicted_ms") is not None:
                ex["predicted_ms_per_exchange"] = ev["predicted_ms"]
    return doc


# --- rendering (the `obs plan` report) -------------------------------------


def render(doc: dict, title: str = "plan vs actual") -> str:
    """Human-readable plan report: the knob table (value + provenance +
    one-line evidence) and, when the plan predicted, the predicted-vs-
    actual wall per attribution bucket.  Pure, so tests pin it."""
    mode = doc.get("mode", "auto")
    prov = doc.get("provenance", "platform_default")
    head = f"{title}: {doc.get('workload', '?')} (--plan {mode}, {prov}"
    if doc.get("model_error_pct") is not None:
        head += f", model error {doc['model_error_pct']:.1f}%"
    lines = [head + ")"]
    knobs = doc.get("knobs") or {}
    if knobs:
        width = max(len(n) for n in knobs)
        for name, row in knobs.items():
            ev = row.get("evidence") or {}
            evs = " ".join(f"{k}={v}" for k, v in ev.items())
            lines.append(
                f"  {name:<{width}} = {row.get('value')!s:<10} "
                f"[{row.get('provenance', '?'):<7}] {evs}".rstrip())
    ex = doc.get("exchange")
    if ex and ex.get("method"):
        line = (f"exchange collective: {ex['method']} "
                f"[{ex.get('provenance', '?')}] @ {ex.get('bucket')} — "
                f"{ex.get('reason', '')}")
        if ex.get("actual_ms_per_exchange") is not None:
            line += f"; measured {ex['actual_ms_per_exchange']}ms/exchange"
            if ex.get("predicted_ms_per_exchange") is not None:
                line += (f" (predicted "
                         f"{ex['predicted_ms_per_exchange']}ms)")
        lines.append(line)
    cov = doc.get("coverage")
    if cov and cov.get("needed"):
        lines.append(
            f"calibration coverage: {cov['covered']}/{cov['needed']} "
            f"cells ({cov['coverage_pct']}%), worst extrapolation "
            f"{cov['extrapolation_bucket_distance']} bucket(s)")
    pred = doc.get("predicted")
    actual = doc.get("actual")
    if pred and pred.get("buckets"):
        lines.append(
            f"predicted wall {pred.get('wall_ms', 0.0) / 1e3:.3f}s "
            f"(curve of {pred.get('curve_runs', '?')} runs)"
            + (f" vs actual {actual['wall_ms'] / 1e3:.3f}s"
               if actual and actual.get("wall_ms") else ""))
        abuckets = (actual or {}).get("buckets") or {}
        names = list(pred["buckets"])
        width = max(len(n) for n in names)
        for name in names:
            p = float(pred["buckets"].get(name) or 0.0)
            a = abuckets.get(name)
            line = f"  {name:<{width}} {p / 1e3:>9.3f}s predicted"
            if a is not None:
                line += f" {float(a) / 1e3:>9.3f}s actual"
                if p > 0 or a:
                    delta = float(a) - p
                    line += f" {delta / 1e3:>+9.3f}s"
            lines.append(line)
    elif actual and actual.get("wall_ms"):
        lines.append(f"no prediction ({prov}); actual wall "
                     f"{actual['wall_ms'] / 1e3:.3f}s")
    return "\n".join(lines)
