"""Nested, thread-safe spans with Chrome trace-event export.

Event model: a span is one timed region (``ph="X"`` complete event in
Chrome trace-event terms) with free-form scalar attributes (rows, bytes,
device, spill generation).  Spans nest per thread — each thread keeps its
own open-span stack, so host map workers, the driver loop, and the
heartbeat interleave without lock contention on the stack — and the flat
event list records parent depth, so the JSONL export preserves nesting
explicitly while the Chrome export gets it for free (Perfetto nests
same-tid events by time containment).

Disabled tracers hand out one shared no-op span object; the per-site cost
of an un-traced run is a single attribute check, which is how the job
keeps its <2% flags-off overhead budget.

Causally-paired spans carry sequence tags in their args — ``round=<k>``
on the distributed drivers' lockstep flag/exchange spans (round *k* is
one cross-process barrier) and ``seq=<n>`` on the pipeline's
producer/consumer queue-handoff spans — so the critical-path analyzer
(:mod:`map_oxidize_tpu.obs.critpath`) joins happens-before edges by tag
equality instead of timestamp heuristics.  The tags are plain loop
counters at the call sites: lockstep rounds advance identically on every
process by construction, which is what makes the cross-process join
sound.

Open the exported file at ``chrome://tracing`` or https://ui.perfetto.dev
(see docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import os
import threading
import time


class _NullSpan:
    """Shared do-nothing span for disabled tracers (and a safe default for
    engines whose driver never attached an ``Obs``)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> "_NullSpan":
        return self


NULL_SPAN = _NullSpan()


class Span:
    """One open timed region.  Use as a context manager; the end time is
    recorded in ``__exit__`` even when the body raises, and an exception
    is annotated on the event (``error`` attribute) rather than losing
    the span."""

    __slots__ = ("_tracer", "name", "attrs", "_t0", "_depth", "_done")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self._done = False

    def set(self, **attrs) -> "Span":
        """Attach/overwrite attributes while the span is open."""
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        self._t0 = self._tracer._clock()
        stack = self._tracer._stack()
        self._depth = len(stack)
        stack.append(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = self._tracer._clock()
        stack = self._tracer._stack()
        # exception safety: pop through to this span even if a child span
        # leaked (its __exit__ never ran because of a lower-level crash)
        while stack and stack[-1] is not self:
            stack.pop()
        if stack:
            stack.pop()
        if self._done:
            # close_open_spans already exported this span (a crash on
            # another thread force-closed it); don't record it twice
            return False
        self._done = True
        if exc_type is not None:
            self.attrs["error"] = f"{exc_type.__name__}: {exc}"
        self._tracer._record(self.name, self._t0, t1, self._depth,
                             self.attrs)
        return False


class Tracer:
    """Collects span/instant events; exports Chrome trace JSON or JSONL.

    Thread-safe: the event list is guarded by a lock, the open-span stack
    is thread-local.  Timestamps are microseconds since tracer creation
    (``perf_counter``-based, so durations are monotonic and immune to
    wall-clock steps).
    """

    def __init__(self, enabled: bool = True, clock=time.perf_counter):
        self.enabled = enabled
        self._clock = clock
        self._epoch = clock()
        #: wall-clock instant of the epoch — the cross-process alignment
        #: anchor (perf_counter epochs are per-process and incomparable;
        #: the shard merger offsets each shard by its wall start)
        self.wall_start = time.time()
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self._local = threading.local()
        #: every thread's open-span stack, for close_open_spans (the
        #: thread-local view alone can only see the CURRENT thread's)
        self._stacks: list[list] = []
        self._pid = os.getpid()

    # --- recording --------------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
            with self._lock:
                # remember the owning thread: close_open_spans runs on
                # the CRASHING thread but must attribute each leaked
                # span to the thread that opened it
                self._stacks.append((threading.get_ident(), stack))
        return stack

    def close_open_spans(self, error: str | None = None) -> int:
        """Record every still-open span (any thread) as ended NOW, tagged
        ``unfinished`` (plus ``error`` when given), under its OWNING
        thread's tid.  The flight recorder calls this when a job dies
        mid-phase so the exported trace is well-formed — Perfetto renders
        a truncated timeline instead of losing the phases the crash
        interrupted.  Spans closed here are marked done, so a thread
        that later unwinds its ``with`` block does not record a
        duplicate."""
        if not self.enabled:
            return 0
        now = self._clock()
        with self._lock:
            stacks = [(tid, list(s)) for tid, s in self._stacks]
            for _tid, s in self._stacks:
                s.clear()
        closed = 0
        for tid, stack in stacks:
            for depth, span in enumerate(stack):
                span._done = True
                attrs = dict(span.attrs, unfinished=True)
                if error is not None:
                    attrs.setdefault("error", error)
                self._record(span.name, span._t0, now, depth, attrs,
                             tid=tid)
                closed += 1
        return closed

    def span(self, name: str, **attrs):
        """Open a named span (context manager).  Returns the shared no-op
        span when disabled."""
        if not self.enabled:
            return NULL_SPAN
        return Span(self, name, attrs)

    def instant(self, name: str, **attrs) -> None:
        """Record a zero-duration marker (demotion, spill begin, snapshot
        cut) — a Chrome ``ph="i"`` instant event."""
        if not self.enabled:
            return
        now = self._clock()
        with self._lock:
            self._events.append({
                "name": name, "ph": "i",
                "ts": (now - self._epoch) * 1e6,
                "tid": threading.get_ident(),
                "depth": len(self._stack()),
                "args": attrs,
            })

    def _record(self, name: str, t0: float, t1: float, depth: int,
                attrs: dict, tid: int | None = None) -> None:
        with self._lock:
            self._events.append({
                "name": name, "ph": "X",
                "ts": (t0 - self._epoch) * 1e6,
                "dur": (t1 - t0) * 1e6,
                "tid": threading.get_ident() if tid is None else tid,
                "depth": depth,
                "args": attrs,
            })

    # --- export -----------------------------------------------------------

    def _tid_map(self) -> dict[int, int]:
        """Compact thread idents to small stable tids (0 = first seen)."""
        tids: dict[int, int] = {}
        for e in self._events:
            tids.setdefault(e["tid"], len(tids))
        return tids

    def chrome_trace(self) -> list[dict]:
        """The event list in Chrome trace-event format (the ``[...]``
        array form both chrome://tracing and Perfetto load)."""
        with self._lock:
            events = list(self._events)
        tids = self._tid_map()
        out = [
            {"name": "process_name", "ph": "M", "pid": self._pid, "tid": 0,
             "args": {"name": "map_oxidize_tpu"}},
        ]
        for raw, tid in tids.items():
            out.append({"name": "thread_name", "ph": "M", "pid": self._pid,
                        "tid": tid,
                        "args": {"name": f"thread-{tid}" if tid else
                                 "driver"}})
        for e in events:
            ev = {
                "name": e["name"], "ph": e["ph"], "cat": "moxt",
                "ts": round(e["ts"], 3), "pid": self._pid,
                "tid": tids[e["tid"]],
                "args": _scalarize(e["args"]),
            }
            if e["ph"] == "X":
                ev["dur"] = round(e["dur"], 3)
            else:
                ev["s"] = "t"  # instant scope: thread
            out.append(ev)
        return out

    def write_chrome(self, path: str) -> None:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.chrome_trace(), f)
        os.replace(tmp, path)

    def write_jsonl(self, path: str) -> None:
        """One event per line, with explicit ``depth`` (nesting level at
        open) — the grep/jq-friendly export."""
        with self._lock:
            events = list(self._events)
        tids = self._tid_map()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            for e in events:
                row = dict(e, tid=tids[e["tid"]], args=_scalarize(e["args"]))
                f.write(json.dumps(row) + "\n")
        os.replace(tmp, path)


def _scalarize(args: dict) -> dict:
    """JSON-safe attribute values (numpy scalars -> Python scalars)."""
    out = {}
    for k, v in args.items():
        item = getattr(v, "item", None)
        if item is not None and getattr(v, "ndim", 1) == 0:
            v = item()
        elif not isinstance(v, (str, int, float, bool, type(None))):
            v = str(v)
        out[k] = v
    return out
