"""XLA program observatory: cost/roofline join, report rendering, and the
live device sampler.

The join half turns :mod:`map_oxidize_tpu.obs.compile`'s raw per-program
record (compiles, causes, FLOPs/bytes from ``cost_analysis``, dispatch
timings) into the per-job accounting the ISSUE's Exoshuffle argument
demands — *where the FLOPs and bytes actually go*:

* achieved FLOP/s and bytes/s per program, from cost-analysis cost x
  dispatch count over the estimated device time (the sampled
  ``block_until_ready`` waits when available, else the dispatch walls);
* MFU against the session-measured peak (bench's matmul probe, exported
  via ``MOXT_PEAK_FLOPS``; defaults to the round-5 sustained
  measurement on TPU) and achieved-bandwidth fraction against
  ``MOXT_PEAK_MEMBW``;
* a memory-bound / compute-bound classification from arithmetic
  intensity vs the machine balance point.

The sampler half is one low-rate daemon thread per job doing two things
the inline instrumentation cannot:

* **live HBM watermarks** — ``hbm/live_bytes_device<i>`` gauges sampled
  from ``device.memory_stats()`` between phase boundaries (the existing
  end-of-phase samples miss mid-phase peaks), surfaced on heartbeat
  lines and in flight-recorder crash bundles;
* **stall detection** — if no chunk completes within a configurable
  multiple of the median inter-chunk interval, one ``[stalled]`` line
  names the currently open span stacks (exactly what a hung feed loop
  or a wedged collective looks like from the outside).
"""

from __future__ import annotations

import os
import sys
import threading
import time

from map_oxidize_tpu.utils.logging import get_logger

_log = get_logger(__name__)

#: fallback peaks when no env override and no probe ran: the round-5
#: session measurements for the deployed part (bf16-sustained matmul
#: ~91 TFLOP/s — about half the v5e nominal 197e12 — and ~60 GB/s
#: achieved HBM read; benchmarks/RESULTS.md).  CPU hosts get no default:
#: MFU is meaningless there, so it is simply omitted.
TPU_PEAK_FLOPS = 91e12
TPU_PEAK_MEMBW = 60e9

#: machine-balance fallback (FLOPs per byte) for the bound classification
#: when no peak pair is known — the TPU ratio above, rounded
DEFAULT_BALANCE = 1500.0


def device_peaks() -> dict:
    """The peak rates MFU is quoted against.  ``MOXT_PEAK_FLOPS`` /
    ``MOXT_PEAK_MEMBW`` env overrides win PER FIELD (bench exports only
    its measured matmul peak — the membw default must survive that);
    whatever the env leaves unset falls back to the round-5 measured
    sustained rates on TPU, and to nothing on CPU."""
    peaks = {"flops": None, "membw": None, "source": "none"}
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            if jax.devices()[0].platform == "tpu":
                peaks.update(flops=TPU_PEAK_FLOPS, membw=TPU_PEAK_MEMBW,
                             source="round5-measured-default")
        except Exception:
            pass
    env_used = False
    for env, key in (("MOXT_PEAK_FLOPS", "flops"),
                     ("MOXT_PEAK_MEMBW", "membw")):
        v = os.environ.get(env)
        if v:
            try:
                peaks[key] = float(v)
                env_used = True
            except ValueError:
                pass
    if env_used:
        peaks["source"] = ("env" if peaks["source"] == "none"
                           else f"env+{peaks['source']}")
    return peaks


def job_report(delta: dict) -> dict:
    """Join one job's compile-ledger delta (``CompileLedger.job_delta``)
    with the session peaks into the per-program observatory rows the
    metrics document carries (``metrics.json["xprof"]``)."""
    peaks = device_peaks()
    balance = (peaks["flops"] / peaks["membw"]
               if peaks["flops"] and peaks["membw"] else DEFAULT_BALANCE)
    programs = {}
    for name, d in sorted(delta.items()):
        row = dict(d)
        n = d["dispatches"]
        flops = d.get("flops_per_dispatch")
        bytes_ = d.get("bytes_per_dispatch")
        # device-time estimate: mean sampled ready-wait x dispatches when
        # samples exist (the honest figure under async dispatch), else
        # the summed dispatch walls (an upper bound: host overhead rides
        # along, so rates and MFU read conservative)
        dev_s = None
        if d["device_samples"] > 0 and d["sampled_device_ms"] > 0:
            dev_s = (d["sampled_device_ms"] / d["device_samples"]) * n / 1e3
            row["device_time_source"] = "sampled_ready_wait"
        elif d["dispatch_ms"] > 0:
            dev_s = d["dispatch_ms"] / 1e3
            row["device_time_source"] = "dispatch_wall"
        row["device_s_est"] = round(dev_s, 6) if dev_s else None
        # per-LOGICAL-chunk dispatch attribution: a scan-batched program
        # retires B chunks per launch, so its per-dispatch gap is not
        # comparable across B — gap / logical chunks is (the number the
        # dispatch-floor trajectory tracks)
        ch = d.get("logical_chunks") or 0
        if ch and d["dispatch_ms"] > 0:
            row["chunks_per_dispatch"] = round(
                ch / max(n - d["compiles"], 1), 2)
            row["dispatch_gap_per_chunk_ms"] = round(
                d["dispatch_ms"] / ch, 4)
        if n and flops and dev_s:
            row["achieved_flops_per_s"] = round(flops * n / dev_s, 1)
            if peaks["flops"]:
                row["mfu_pct"] = round(
                    100.0 * flops * n / dev_s / peaks["flops"], 3)
        if n and bytes_ and dev_s:
            row["achieved_bytes_per_s"] = round(bytes_ * n / dev_s, 1)
            if peaks["membw"]:
                row["membw_pct"] = round(
                    100.0 * bytes_ * n / dev_s / peaks["membw"], 3)
        if flops and bytes_:
            intensity = flops / bytes_
            row["intensity_flops_per_byte"] = round(intensity, 4)
            row["bound"] = ("compute" if intensity >= balance else "memory")
        programs[name] = row
    return {
        "programs": programs,
        "peaks": peaks,
        "balance_flops_per_byte": round(balance, 2),
        "total_compiles": sum(d["compiles"] for d in delta.values()),
        "total_compile_ms": round(
            sum(d["compile_ms"] for d in delta.values()), 3),
        "total_dispatches": sum(d["dispatches"] for d in delta.values()),
    }


def flatten_report(report: dict) -> dict:
    """The scalar projection of :func:`job_report` for the flat metrics
    summary — what rides ``JobResult.metrics``, the run ledger, and the
    ``obs diff --gate`` / ``bench.py --gate`` regression checks."""
    out = {
        "compile/total_compiles": report["total_compiles"],
        "compile/total_ms": report["total_compile_ms"],
    }
    for name, row in report["programs"].items():
        out[f"compile/{name}/compiles"] = row["compiles"]
        out[f"compile/{name}/shape_sets"] = row["shape_sets"]
        if row["recompile_causes"]:
            out[f"compile/{name}/recompile_cause"] = \
                row["recompile_causes"][-1]
        out[f"xprof/{name}/dispatches"] = row["dispatches"]
        for k, dst in (("mfu_pct", "mfu_pct"), ("membw_pct", "membw_pct"),
                       ("bound", "bound")):
            if row.get(k) is not None:
                out[f"xprof/{name}/{dst}"] = row[k]
        # per-logical-chunk attribution (an unbatched program retires 1
        # chunk/dispatch, so for it this equals the mean dispatch gap —
        # the value stays comparable when the same program later batches)
        if row.get("dispatch_gap_per_chunk_ms") is not None:
            out[f"xprof/{name}/logical_chunks"] = row["logical_chunks"]
            out[f"xprof/{name}/dispatch_gap_per_chunk_ms"] = \
                row["dispatch_gap_per_chunk_ms"]
    return out


# --- report rendering (the `obs xprof` table) ------------------------------


def _fmt_rate(v, unit):
    if v is None:
        return "-"
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if v >= scale:
            return f"{v / scale:.2f} {suffix}{unit}"
    return f"{v:.1f} {unit}"


def render_report(report: dict, histograms: dict | None = None) -> str:
    """Human-readable observatory report: the compile table, the
    cost/utilization table, and the dispatch-gap histogram summary."""
    lines = ["XLA program observatory"]
    peaks = report.get("peaks", {})
    lines.append(
        f"  peaks: flops={_fmt_rate(peaks.get('flops'), 'FLOP/s')} "
        f"membw={_fmt_rate(peaks.get('membw'), 'B/s')} "
        f"({peaks.get('source', '?')}); balance "
        f"{report.get('balance_flops_per_byte')} FLOP/byte")
    progs = report.get("programs", {})
    if not progs:
        lines.append("  (no observed programs ran in this job)")
        return "\n".join(lines)
    lines.append(
        f"  {report['total_compiles']} compiles "
        f"({report['total_compile_ms']:.1f} ms) across {len(progs)} "
        f"programs, {report['total_dispatches']} dispatches")
    lines.append("compiles:")
    lines.append(f"  {'program':<28} {'n':>3} {'ms':>9} {'shapes':>6}  cause")
    for name, r in progs.items():
        cause = ", ".join(r["recompile_causes"]) if r["recompile_causes"] \
            else "-"
        lines.append(f"  {name:<28} {r['compiles']:>3} "
                     f"{r['compile_ms']:>9.1f} {r['shape_sets']:>6}  {cause}")
    lines.append("cost / utilization:")
    lines.append(f"  {'program':<28} {'disp':>5} {'flops/disp':>11} "
                 f"{'bytes/disp':>11} {'achieved':>12} {'MFU%':>6} "
                 f"{'bw%':>6}  bound")
    for name, r in progs.items():
        lines.append(
            f"  {name:<28} {r['dispatches']:>5} "
            f"{_fmt_rate(r.get('flops_per_dispatch'), ''):>11} "
            f"{_fmt_rate(r.get('bytes_per_dispatch'), ''):>11} "
            f"{_fmt_rate(r.get('achieved_flops_per_s'), 'F/s'):>12} "
            f"{r.get('mfu_pct', '-'):>6} {r.get('membw_pct', '-'):>6}  "
            f"{r.get('bound', '-')}")
    if histograms:
        for h in ("device/dispatch_gap_ms",
                  "device/dispatch_gap_per_chunk_ms", "device/compute_ms"):
            s = histograms.get(h)
            if s:
                lines.append(
                    f"{h}: n={s.get('count')} p50={s.get('p50')} "
                    f"p95={s.get('p95')} max={s.get('max')} "
                    f"mean={s.get('mean')}")
    return "\n".join(lines)


# --- live device sampler ---------------------------------------------------


class DeviceSampler:
    """Low-rate daemon thread: live HBM watermarks + the stall detector.

    Chunk progress is read from the job's own registry (the
    ``feed_block_ms`` / ``device/dispatch_gap_ms`` histogram counts and
    the ``engine/flushes`` / ``pipeline/chunks`` counters), so the
    detector needs no extra hooks in the drivers and works with or
    without ``--progress``.  Stall warnings fire once per episode (a
    completing chunk re-arms the detector).
    """

    #: registry series whose growth means "a chunk completed"
    PROGRESS_HISTS = ("feed_block_ms", "device/dispatch_gap_ms")
    PROGRESS_COUNTERS = ("engine/flushes", "pipeline/chunks")

    def __init__(self, obs, interval_s: float = 0.0,
                 stall_factor: float = 0.0):
        self.obs = obs
        self.interval_s = interval_s if interval_s > 0 else 0.5
        self.stall_factor = stall_factor
        self.sample_hbm = interval_s > 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="obs-device-sampler")
        self._intervals: list[float] = []
        self._last_signal = 0
        self._last_change = time.monotonic()
        self._warned = False
        self.stall_warnings = 0

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # final watermark read so short jobs still record one sample
        if self.sample_hbm:
            self.sample_once()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            if self.sample_hbm:
                self.sample_once()
            if self.stall_factor > 0:
                self.check_stall()

    # --- HBM --------------------------------------------------------------

    def sample_once(self) -> None:
        """One live-bytes reading per initialized device.  A no-op until
        the job itself has imported jax (never init a backend from the
        sampler) and on backends without memory stats (CPU)."""
        jax = sys.modules.get("jax")
        if jax is None:
            return
        try:
            devices = jax.devices()
        except Exception:
            return
        best = None
        for d in devices:
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            in_use = stats.get("bytes_in_use")
            if in_use is None:
                continue
            self.obs.registry.gauge_max(f"hbm/live_bytes_device{d.id}",
                                        int(in_use))
            best = max(best or 0, int(in_use))
        if best is not None and self.obs.heartbeat is not None:
            self.obs.heartbeat.hbm_bytes = best

    # --- stall detection --------------------------------------------------

    def _progress_signal(self) -> int:
        reg = self.obs.registry
        with reg._lock:
            n = sum(reg.histograms[h].count for h in self.PROGRESS_HISTS
                    if h in reg.histograms)
            n += sum(int(reg.counters.get(c, 0))
                     for c in self.PROGRESS_COUNTERS)
        return n

    def check_stall(self, now: float | None = None) -> bool:
        """One detector tick (public for the fake-clock tests).  Returns
        True when a stall warning was emitted this tick."""
        now = time.monotonic() if now is None else now
        sig = self._progress_signal()
        if sig != self._last_signal:
            if self._last_signal:
                self._intervals.append(now - self._last_change)
                if len(self._intervals) > 64:
                    del self._intervals[0]
            self._last_signal = sig
            self._last_change = now
            self._warned = False
            return False
        if self._warned or len(self._intervals) < 3:
            return False
        med = sorted(self._intervals)[len(self._intervals) // 2]
        elapsed = now - self._last_change
        if med <= 0 or elapsed < self.stall_factor * med:
            return False
        self._warned = True
        self.stall_warnings += 1
        tracer = self.obs.tracer
        spans = []
        if tracer.enabled:
            with tracer._lock:
                for _tid, stack in tracer._stacks:
                    if stack:
                        spans.append(" > ".join(s.name for s in stack))
        open_s = "; ".join(spans) if spans else "(no trace: run with " \
                                                "--trace-out for span names)"
        line = (f"[stalled] no chunk completed in {elapsed:.1f}s "
                f"(median {med:.2f}s, factor {self.stall_factor:g}); "
                f"open spans: {open_s}")
        hb = self.obs.heartbeat
        if hb is not None and not getattr(hb, "silent", False):
            hb._emit(line)
        else:
            # no heartbeat, or a silent tracking-only one (the live
            # plane's /status feed): the warning must still hit the log
            _log.warning("%s", line)
        # the counter the ledger gate and /status read: a stall episode
        # is evidence, not just a log line (any increase vs the previous
        # comparable run flags in `obs diff --gate`)
        self.obs.registry.count("heartbeat/stalls")
        return True
