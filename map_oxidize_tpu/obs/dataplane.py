"""Data-plane observatory: row-conservation audits, key-skew telemetry,
and reduction-ratio gauges across the shuffle.

The rest of the obs stack answers *where time went* (attribution,
critical path, fleet load); this module answers *what the data did*:

* **conservation audits** — rows/bytes counted at each phase boundary
  (map -> exchange -> reduce -> write) with order-independent checksums
  over the (key, value) pairs, so a run *proves* end-to-end row
  conservation per hash partition instead of asserting one global sum.
  Two checksum families, chosen per engine:

  - fold engines (``combine == "sum"``): the **weighted checksum**
    ``sum(mix64(key) * value) mod 2^64``.  Order-independent AND
    invariant under sum-combining — pre-combined map rows and the final
    reduced counts produce the SAME digest, so it matches across the
    exchange even though the row count legitimately shrinks.
  - pair engines (collect paths): the **pair digest** — XOR and
    wrapping-sum of ``mix64(key ^ mix64(doc))`` — an exact multiset
    identity over (key, doc) rows; any dropped, duplicated, or
    corrupted row flips it.

* **key-skew telemetry** — per-partition row histograms, distinct-key
  estimates via the existing HLL machinery
  (:mod:`map_oxidize_tpu.workloads.distinct`), a bounded hot-key top-k,
  and the imbalance factor (max/mean partition rows) — the evidence
  ROADMAP item 2's straggler tolerance and item 5's planner consume.

* **reduction-ratio gauges** — rows-in vs distinct-keys-out per
  partition: the exact number ROADMAP item 1's map-side combiner must
  beat (Exoshuffle prices the combining win from this ratio).

Everything is host-side numpy (no jax import): digests fold in as the
engines feed, partitioned by the SAME hash the device shuffle routes by
(:func:`partition_of` mirrors ``parallel.shuffle.bucket_of``; a test
pins them together).  Violations raise :class:`ConservationError` — a
named, gated failure — and every run's audit lands in the metrics
document (``doc["data"]``), the ledger entry (``data/*`` gauges + a
compact ``data`` section), ``/status``, and the ``obs data`` CLI.
"""

from __future__ import annotations

import numpy as np

#: metrics-document section schema (``doc["data"]``)
DATA_SCHEMA = "moxt-data-v1"

#: single-shard runs still want skew/reduction telemetry: the audit
#: then partitions by hash into this many VIRTUAL partitions (the
#: conservation identities hold under any deterministic key partition)
VIRTUAL_PARTITIONS = 8

#: per-partition HLL precision (2^p int32 registers per partition —
#: ~16KB at p=12; the global estimate is the union/max of the rows)
HLL_P = 12

#: hot-key tracker bounds: keep the top ``HOT_KEYS`` for the doc,
#: tracked through a dict pruned back to ``_HOT_KEEP`` candidates
#: whenever it grows past ``_HOT_CAP`` (space-bounded heavy hitters;
#: counts for keys that never leave the candidate set are exact)
HOT_KEYS = 10
_HOT_KEEP = 1024
_HOT_CAP = 8192

_U64 = np.uint64
_M1 = _U64(0xBF58476D1CE4E5B9)
_M2 = _U64(0x94D049BB133111EB)


class ConservationError(RuntimeError):
    """A row-conservation audit failed: rows (or their checksum) at one
    phase boundary do not match the other side.  Data was dropped,
    duplicated, or corrupted in between — never a tolerable condition,
    so this is a named hard failure (and ``data/conservation_violations``
    records it for the ledger gate even when the run aborts through the
    flight recorder)."""


def mix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer: a cheap, well-mixed u64 -> u64
    bijection.  Checksums digest ``mix64(key)`` rather than the raw key
    so adjacent key values cannot cancel in the wrapping sum."""
    x = np.asarray(x, _U64).copy()
    x ^= x >> _U64(30)
    x *= _M1
    x ^= x >> _U64(27)
    x *= _M2
    x ^= x >> _U64(31)
    return x


def join_planes(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    """(hi, lo) u32 planes -> u64 keys (host twin of the device join)."""
    return ((np.asarray(hi, _U64) << _U64(32))
            | np.asarray(lo, _U64))


def partition_of(keys: np.ndarray, n_partitions: int) -> np.ndarray:
    """Owner partition per key — the host-side twin of
    ``parallel.shuffle.bucket_of`` (``(hi ^ lo) % S`` on the u32
    planes), so the audit's partitions ARE the device shuffle's hash
    partitions.  A parity test pins the two implementations together."""
    keys = np.asarray(keys, _U64)
    hi = (keys >> _U64(32)).astype(np.uint32)
    lo = keys.astype(np.uint32)
    return ((hi ^ lo) % np.uint32(n_partitions)).astype(np.int64)


def map_output_rows(out, pairs: bool) -> "tuple | None":
    """Host ``(keys_u64, values | docs_i64)`` view of a ``MapOutput`` in
    either the plane or the compact 64-bit form (compact fold outputs
    carry implicit all-ones counts — the hash-only contract).  ``None``
    for vector-valued fold rows, which have no scalar conservation
    identity (k-means centroids)."""
    if getattr(out, "keys64", None) is not None:
        k64 = np.asarray(out.keys64, _U64)
    else:
        k64 = join_planes(out.hi, out.lo)
    if pairs:
        if getattr(out, "docs64", None) is not None:
            return k64, np.asarray(out.docs64, np.int64)
        va = np.asarray(out.values)
        return k64, join_planes(va[:, 0], va[:, 1]).view(np.int64)
    if out.values is None:
        return k64, np.ones(k64.shape[0], np.int64)
    va = np.asarray(out.values)
    if va.ndim != 1:
        return None
    return k64, va


def weighted_checksum(keys: np.ndarray, values: np.ndarray) -> int:
    """``sum(mix64(key) * value) mod 2^64`` over the whole block —
    order-independent and invariant under sum-combining (module
    docstring).  The scalar spelling of the per-partition fold-stage
    digest, exposed for tests and ad-hoc tooling."""
    if np.asarray(keys).shape[0] == 0:
        return 0
    v = np.asarray(values, np.int64).astype(_U64)
    return int((mix64(keys) * v).sum(dtype=_U64))


def pair_digest(keys: np.ndarray, docs: np.ndarray) -> "tuple[int, int]":
    """(XOR, wrapping-sum) of ``mix64(key ^ mix64(doc))`` — an exact
    order-independent multiset identity over (key, doc) rows."""
    if np.asarray(keys).shape[0] == 0:
        return 0, 0
    h = mix64(np.asarray(keys, _U64)
              ^ mix64(np.ascontiguousarray(docs, np.int64).view(_U64)))
    return (int(np.bitwise_xor.reduce(h)), int(h.sum(dtype=_U64)))


def _hll_ranks(hashes: np.ndarray, p: int) -> "tuple[np.ndarray, np.ndarray]":
    """(bucket, rank) per hash — the register-update pair of the
    standard HLL sketch (same frexp trick as
    ``workloads.distinct.hll_registers``, which owns the exactness
    argument for p >= 11)."""
    buckets = (hashes >> _U64(64 - p)).astype(np.int64)
    w = (hashes & _U64((1 << (64 - p)) - 1)).astype(np.float64)
    _, exp = np.frexp(w)
    ranks = np.where(w == 0, 64 - p + 1, 64 - p + 1 - exp)
    return buckets, ranks.astype(np.int32)


class _Stage:
    """One phase boundary's per-partition ledger: row/byte counts plus
    the order-independent digests (both families; the checks read the
    one that applies).  ``scope`` drives the cross-process reduction:
    ``local`` vectors sum across processes, ``disjoint`` ones too (a
    partition is owned by exactly one process, everyone else holds
    zeros — XOR folds the same way), ``replicated`` ones are already
    global on every process and must NOT be reduced again."""

    __slots__ = ("rows", "bytes", "vsum", "wsum", "xor", "sum",
                 "uniq", "scope")

    def __init__(self, S: int, scope: str):
        self.rows = np.zeros(S, _U64)
        self.bytes = np.zeros(S, _U64)
        self.vsum = np.zeros(S, _U64)
        self.wsum = np.zeros(S, _U64)
        self.xor = np.zeros(S, _U64)
        self.sum = np.zeros(S, _U64)
        self.uniq = np.zeros(S, _U64)
        self.scope = scope

    def vectors(self) -> "list[tuple[str, np.ndarray, str]]":
        """(name, vector, reduce-op) triples for the cross-process
        allgather; op is ``add`` or ``xor``."""
        return [("rows", self.rows, "add"), ("bytes", self.bytes, "add"),
                ("vsum", self.vsum, "add"), ("wsum", self.wsum, "add"),
                ("xor", self.xor, "xor"), ("sum", self.sum, "add"),
                ("uniq", self.uniq, "add")]


class DataPlaneAudit:
    """The per-job data-plane ledger the engines feed (reachable as
    ``obs.dataplane``; drivers create it through
    ``Obs.ensure_dataplane``).  Thread-compat with the host map pool is
    the caller's concern: every record call happens on the driver's
    ingest thread (the same serialization the engines already rely on).
    """

    def __init__(self, n_partitions: int, conserves: bool = True,
                 hll_p: int = HLL_P, top_k: int = HOT_KEYS):
        self.virtual = n_partitions <= 1
        self.S = VIRTUAL_PARTITIONS if self.virtual else int(n_partitions)
        self.conserves = bool(conserves)
        self.p = hll_p
        self.top_k = top_k
        self.stages: "dict[str, _Stage]" = {}
        self.records_in: "int | None" = None
        #: in-side skew state (fed by map-out records)
        self._regs = np.zeros(self.S << hll_p, np.int32)
        self._hot: "dict[int, int]" = {}
        self._hot_resolved: "dict[int, bytes]" = {}
        self.observed_rows: "np.ndarray | None" = None
        self.checks = 0
        self.violations: "list[str]" = []
        self._reduced = False

    # --- recording --------------------------------------------------------

    def _stage(self, name: str, scope: str) -> _Stage:
        st = self.stages.get(name)
        if st is None:
            st = self.stages[name] = _Stage(self.S, scope)
        elif st.scope != scope:
            raise ValueError(f"stage {name!r} recorded with scope "
                             f"{scope!r} after {st.scope!r}")
        return st

    def _skew(self, keys: np.ndarray, part: np.ndarray,
              weights: "np.ndarray | None") -> None:
        h = mix64(keys)
        b, r = _hll_ranks(h, self.p)
        np.maximum.at(self._regs, (part << self.p) + b, r)
        uk, inv = np.unique(keys, return_inverse=True)
        cnt = np.bincount(inv, weights=None if weights is None
                          else np.asarray(weights, np.float64))
        hot = self._hot
        for k, c in zip(uk.tolist(), cnt.tolist()):
            hot[k] = hot.get(k, 0) + int(c)
        if len(hot) > _HOT_CAP:
            keep = sorted(hot.items(), key=lambda kv: -kv[1])[:_HOT_KEEP]
            self._hot = dict(keep)

    def _fold(self, name: str, scope: str, keys: np.ndarray,
              values: np.ndarray, skew: bool) -> None:
        keys = np.asarray(keys, _U64)
        n = int(keys.shape[0])
        if n == 0:
            return
        part = partition_of(keys, self.S)
        st = self._stage(name, scope)
        rows = np.bincount(part, minlength=self.S).astype(_U64)
        st.rows += rows
        row_b = _U64((keys.nbytes + np.asarray(values).nbytes) // n)
        st.bytes += rows * row_b
        v = np.asarray(values, np.int64).astype(_U64)
        np.add.at(st.vsum, part, v)
        np.add.at(st.wsum, part, mix64(keys) * v)
        if skew:
            self._skew(keys, part, values)

    def _pairs(self, name: str, scope: str, keys: np.ndarray,
               docs: np.ndarray, skew: bool, uniq: bool) -> None:
        keys = np.asarray(keys, _U64)
        n = int(keys.shape[0])
        if n == 0:
            return
        part = partition_of(keys, self.S)
        st = self._stage(name, scope)
        rows = np.bincount(part, minlength=self.S).astype(_U64)
        st.rows += rows
        st.bytes += rows * _U64(16)  # the one on-disk pair record width
        h = mix64(keys ^ mix64(np.ascontiguousarray(docs, np.int64)
                               .view(_U64)))
        np.bitwise_xor.at(st.xor, part, h)
        np.add.at(st.sum, part, h)
        if uniq:
            uk = np.unique(keys)
            st.uniq += np.bincount(partition_of(uk, self.S),
                                   minlength=self.S).astype(_U64)
        if skew:
            self._skew(keys, part, None)

    def record_fold_in(self, keys, values) -> None:
        """Map output entering the fold shuffle (pre-exchange, possibly
        chunk-pre-combined — the weighted checksum absorbs that)."""
        self._fold("map_out", "local", keys, values, skew=True)

    def record_fold_out(self, keys, values) -> None:
        """The final reduced readback (one distinct key per row).  In a
        distributed run the readback is replicated on every process."""
        self._fold("reduce_out", "replicated", keys, values, skew=False)
        self._stage("reduce_out", "replicated").uniq += np.bincount(
            partition_of(np.asarray(keys, _U64), self.S),
            minlength=self.S).astype(_U64)

    def record_pairs_in(self, keys, docs) -> None:
        """(key, doc) pairs entering the collect shuffle."""
        self._pairs("map_out", "local", keys, docs, skew=True, uniq=False)

    def record_pairs_out(self, keys, docs) -> None:
        """(key, doc) pairs leaving finalize toward the writer.  Called
        once on the resident path, per disjoint bucket on the spilled
        path (bucket key ranges are disjoint, so per-call distinct
        counts sum exactly)."""
        self._pairs("reduce_out", "disjoint", keys, docs, skew=False,
                    uniq=True)

    def record_observed_rows(self, rows) -> None:
        """Post-exchange rows per shard actually observed by the device
        transport (the sharded engine's cursor) — the measured twin of
        the in-side hash histogram, cross-checkable when the shuffle
        partitions by hash."""
        rows = np.asarray(rows, np.int64)
        if rows.shape[0] == self.S:
            prev = self.observed_rows
            self.observed_rows = (rows if prev is None else prev + rows)

    def set_records_in(self, records: int) -> None:
        self.records_in = int(records)

    # --- cross-process reduction -----------------------------------------

    def reduce_distributed(self, allgather,
                           expect=(("map_out", "local"),)) -> None:
        """Fold every process's local vectors into the global audit:
        ``allgather`` maps a u64 vector to its ``(P, k)`` gather (the
        distributed runner passes ``_allgather_u64``).  One collective
        carries everything; each section then reduces with its own op
        (sum for counts, XOR for the pair digest, max for HLL
        registers).  Every process ends up with the same global state,
        so the subsequent checks abort SPMD-consistently.

        ``expect`` names the (stage, scope) pairs the workload feeds
        PRE-reduce; they are materialized (as zeros) before the payload
        is built so a process that happened to record nothing — e.g. it
        owned zero chunks of a small corpus, or drained zero spill
        buckets — still ships the same payload shape as its peers (an
        allgather with diverging lengths wedges the transport)."""
        for name, scope in expect:
            self._stage(name, scope)
        sections: "list[tuple[str, str, np.ndarray, str]]" = []
        for name in sorted(self.stages):
            st = self.stages[name]
            if st.scope == "replicated":
                continue
            for field, vec, op in st.vectors():
                sections.append((name, field, vec, op))
        hot = sorted(self._hot.items(), key=lambda kv: -kv[1])
        hot = hot[:_HOT_KEEP]
        hot_k = np.zeros(_HOT_KEEP, _U64)
        hot_c = np.zeros(_HOT_KEEP, _U64)
        if hot:
            hot_k[:len(hot)] = np.array([k for k, _ in hot], _U64)
            hot_c[:len(hot)] = np.array([c for _, c in hot], _U64)
        parts = ([vec for _, _, vec, _ in sections]
                 + [self._regs.astype(_U64), hot_k, hot_c,
                    np.array([self.records_in or 0], _U64)])
        flat = np.concatenate(parts)
        g = np.asarray(allgather(flat), _U64)  # (P, k)
        off = 0
        for name, field, vec, op in sections:
            blk = g[:, off:off + vec.shape[0]]
            off += vec.shape[0]
            folded = (np.bitwise_xor.reduce(blk, axis=0) if op == "xor"
                      else blk.sum(axis=0, dtype=_U64))
            setattr(self.stages[name], field, folded)
        regs = g[:, off:off + self._regs.shape[0]]
        off += self._regs.shape[0]
        self._regs = regs.max(axis=0).astype(np.int32)
        P = g.shape[0]
        merged: "dict[int, int]" = {}
        for p_ in range(P):
            ks = g[p_, off:off + _HOT_KEEP]
            cs = g[p_, off + _HOT_KEEP:off + 2 * _HOT_KEEP]
            for k, c in zip(ks.tolist(), cs.tolist()):
                if c:
                    merged[k] = merged.get(k, 0) + c
        self._hot = merged
        off += 2 * _HOT_KEEP
        self.records_in = int(g[:, off].sum(dtype=_U64))
        self._reduced = True

    # --- checks -----------------------------------------------------------

    def _violate(self, msg: str) -> None:
        self.violations.append(msg)
        raise ConservationError(msg)

    def check_fold(self) -> None:
        """Per-partition fold conservation: the weighted checksum and
        the value sum at ``map_out`` must equal ``reduce_out`` exactly
        (both are invariant under the sum-combine), and the total value
        sum must equal the mapped record count when the mapper conserves
        counts — the generalized, per-partition spelling of the old
        global driver assertion."""
        a = self.stages.get("map_out")
        b = self.stages.get("reduce_out")
        if a is None or b is None or not self.conserves:
            return
        self.checks += 1
        for p_ in range(self.S):
            if int(a.vsum[p_]) != int(b.vsum[p_]):
                self._violate(
                    f"row conservation violated at map->reduce: partition "
                    f"{p_}: value sum in {int(a.vsum[p_])} != out "
                    f"{int(b.vsum[p_])} (rows in {int(a.rows[p_])}, "
                    f"out {int(b.rows[p_])})")
            if int(a.wsum[p_]) != int(b.wsum[p_]):
                self._violate(
                    f"row conservation violated at map->reduce: partition "
                    f"{p_}: weighted checksum in {int(a.wsum[p_]):#018x} "
                    f"!= out {int(b.wsum[p_]):#018x} with matching value "
                    f"sums — keys were remapped or counts were swapped "
                    f"across keys")
        self.checks += 1
        if self.records_in is not None and self.records_in > 0:
            total = int(a.vsum.sum(dtype=_U64))
            if total != self.records_in:
                self._violate(
                    f"count conservation violated: mapped "
                    f"{self.records_in} records but map output values "
                    f"sum to {total}")

    def check_total(self, total) -> None:
        """The consumer-facing readback container must tell the same
        story as the audited arrays: Σ counts (as a consumer will read
        them) == records mapped — the old global driver assertion,
        kept as a named audit check so a corrupted counts container
        aborts through the same flight-recorder path."""
        if not self.conserves or not self.records_in:
            return
        self.checks += 1
        if int(total) != self.records_in:
            self._violate(
                f"count conservation violated: mapped {self.records_in} "
                f"records but reduced counts sum to {int(total)}")

    def check_pairs(self) -> None:
        """Per-partition pair-multiset conservation: rows, XOR, and
        wrapping-sum digests at ``map_out`` must equal ``reduce_out``
        exactly — pairs cross the exchange (and any spill round-trip)
        unchanged."""
        a = self.stages.get("map_out")
        b = self.stages.get("reduce_out")
        if a is None or b is None:
            return
        self.checks += 1
        for p_ in range(self.S):
            if int(a.rows[p_]) != int(b.rows[p_]):
                self._violate(
                    f"pair conservation violated at map->reduce: "
                    f"partition {p_}: {int(a.rows[p_])} rows in, "
                    f"{int(b.rows[p_])} out")
            if (int(a.xor[p_]) != int(b.xor[p_])
                    or int(a.sum[p_]) != int(b.sum[p_])):
                self._violate(
                    f"pair conservation violated at map->reduce: "
                    f"partition {p_}: digest in "
                    f"(xor {int(a.xor[p_]):#018x}, sum "
                    f"{int(a.sum[p_]):#018x}) != out "
                    f"(xor {int(b.xor[p_]):#018x}, sum "
                    f"{int(b.sum[p_]):#018x}) with matching row counts "
                    f"— pair contents changed in flight")

    # --- export -----------------------------------------------------------

    def _skew_figures(self) -> "tuple[np.ndarray, float, np.ndarray]":
        a = self.stages.get("map_out")
        rows = (a.rows.astype(np.float64) if a is not None
                else np.zeros(self.S))
        mean = rows.mean()
        imb = float(rows.max() / mean) if mean > 0 else 1.0
        m = 1 << self.p
        from map_oxidize_tpu.workloads.distinct import hll_estimate
        est = np.array([hll_estimate(self._regs[p_ * m:(p_ + 1) * m])
                        if rows[p_] > 0 else 0.0
                        for p_ in range(self.S)])
        return rows, imb, est

    def hot_hashes(self) -> "list[int]":
        """The top-k hot-key hashes (descending rows) — the list a
        distributed caller feeds ``gather_strings`` (identical on every
        process after ``reduce_distributed``, so the collective is
        well-formed)."""
        return sorted(self._hot, key=self._hot.get, reverse=True)[
            :self.top_k]

    def resolve_hot_keys(self, lookup) -> None:
        """Best-effort hash -> key-bytes resolution for the hot-key
        table (``lookup(hash) -> bytes | None``, e.g. the run's
        ``HashDictionary``)."""
        for k in self.hot_hashes():
            try:
                b = lookup(k)
            except Exception:
                b = None
            if b is not None:
                self._hot_resolved[k] = b

    def doc(self) -> dict:
        """The structured audit section (``moxt-data-v1``): the
        per-stage conservation table, the per-partition skew/reduction
        figures, and the hot-key top-k."""
        rows, imb, est = self._skew_figures()
        a = self.stages.get("map_out")
        b = self.stages.get("reduce_out")
        stages = {}
        for name in sorted(self.stages):
            st = self.stages[name]
            stages[name] = {
                "scope": st.scope,
                "rows": int(st.rows.sum(dtype=_U64)),
                "bytes": int(st.bytes.sum(dtype=_U64)),
                "rows_per_partition": st.rows.astype(np.int64).tolist(),
                "value_sum": int(st.vsum.sum(dtype=_U64)),
                "weighted_checksum": f"{int(st.wsum.sum(dtype=_U64)):#018x}",
                "pair_xor":
                    f"{int(np.bitwise_xor.reduce(st.xor)):#018x}",
                "pair_sum": f"{int(st.sum.sum(dtype=_U64)):#018x}",
            }
        distinct_out = (int(b.uniq.sum(dtype=_U64)) if b is not None
                        else 0)
        rows_in = int(a.rows.sum(dtype=_U64)) if a is not None else 0
        ratio_pp = []
        if a is not None and b is not None:
            for p_ in range(self.S):
                u = int(b.uniq[p_])
                ratio_pp.append(
                    round(int(a.rows[p_]) / u, 3) if u else 0.0)
        hot = []
        for k in sorted(self._hot, key=self._hot.get, reverse=True)[
                :self.top_k]:
            word = self._hot_resolved.get(k)
            if isinstance(word, bytes):
                word = word.decode("utf-8", "replace")
            hot.append({"hash": f"{int(k):#018x}", "key": word,
                        "rows": int(self._hot[k])})
        total_rows = float(rows.sum())
        m = 1 << self.p
        doc = {
            "schema": DATA_SCHEMA,
            "partitions": self.S,
            "virtual_partitions": self.virtual,
            "conserves": self.conserves,
            "records_in": self.records_in,
            "stages": stages,
            "conservation": {"checks": self.checks,
                             "violations": list(self.violations)},
            "skew": {
                "rows_per_partition": rows.astype(np.int64).tolist(),
                "distinct_est_per_partition":
                    [round(float(e), 1) for e in est],
                "distinct_est":
                    round(hll_union_estimate(self._regs, self.S, m), 1),
                "imbalance_factor": round(imb, 4),
                "hot_keys": hot,
                "top_share": (round(hot[0]["rows"] / total_rows, 4)
                              if hot and total_rows else 0.0),
            },
            "reduction": {
                "rows_in": rows_in,
                "distinct_out": distinct_out,
                "ratio": (round(rows_in / distinct_out, 3)
                          if distinct_out else 0.0),
                "ratio_per_partition": ratio_pp,
            },
        }
        if self.observed_rows is not None:
            doc["skew"]["observed_rows_per_partition"] = [
                int(r) for r in self.observed_rows]
        return doc

    def publish(self, registry) -> None:
        """The flat ``data/*`` gauges — the ledger entry, ``/status``,
        the series ring, and the ``data-partition-skew`` SLO rule all
        read these."""
        rows, imb, est = self._skew_figures()
        a = self.stages.get("map_out")
        b = self.stages.get("reduce_out")
        rows_in = int(a.rows.sum(dtype=_U64)) if a is not None else 0
        distinct = int(b.uniq.sum(dtype=_U64)) if b is not None else 0
        registry.set("data/partitions", self.S)
        registry.set("data/rows_in", rows_in)
        registry.set("data/distinct_out", distinct)
        registry.set("data/distinct_est",
                     round(hll_union_estimate(self._regs, self.S,
                                              1 << self.p), 1))
        registry.set("data/imbalance_factor", round(imb, 4))
        if distinct:
            registry.set("data/reduction_ratio",
                         round(rows_in / distinct, 3))
        registry.set("data/conservation_checks", self.checks)
        registry.set("data/conservation_violations",
                     len(self.violations))
        if self._hot and rows.sum() > 0:
            top = max(self._hot.values())
            registry.set("data/hot_key_share",
                         round(top / float(rows.sum()), 4))


def hll_union_estimate(regs_flat: np.ndarray, S: int, m: int) -> float:
    """Global distinct estimate: the element-wise max of the S
    per-partition register rows is the HLL union sketch."""
    from map_oxidize_tpu.workloads.distinct import hll_estimate

    return hll_estimate(
        np.asarray(regs_flat).reshape(S, m).max(axis=0))


def ledger_section(doc: dict) -> dict:
    """The compact ``data`` section a ledger entry carries (full
    per-stage digests stay in the metrics document)."""
    skew = doc.get("skew") or {}
    red = doc.get("reduction") or {}
    return {
        "partitions": doc.get("partitions"),
        "rows_per_partition": skew.get("rows_per_partition"),
        "imbalance_factor": skew.get("imbalance_factor"),
        "reduction_ratio": red.get("ratio"),
        "distinct_out": red.get("distinct_out"),
        "violations": (doc.get("conservation") or {}).get("violations"),
    }


_BLOCKS = " ▁▂▃▄▅▆▇█"


def _bar(frac: float, width: int = 12) -> str:
    """A unicode block bar: ``frac`` of ``width`` cells filled."""
    cells = frac * width
    full = int(cells)
    rem = cells - full
    bar = "█" * full
    if rem > 0 and full < width:
        bar += _BLOCKS[max(1, int(rem * 8))]
    return bar.ljust(width)


def render(doc: dict) -> str:
    """Human rendering of the audit section: the conservation table,
    the per-partition skew heatmap, and the reduction-ratio gauges
    (the ``obs data`` CLI body)."""
    out = []
    S = doc.get("partitions", 0)
    virt = " (virtual)" if doc.get("virtual_partitions") else ""
    out.append(f"data plane: {S} hash partitions{virt}")
    cons = doc.get("conservation") or {}
    nviol = len(cons.get("violations") or [])
    verdict = "FAIL" if nviol else "OK"
    out.append(f"conservation: {cons.get('checks', 0)} checks, "
               f"{nviol} violations  [{verdict}]")
    for v in cons.get("violations") or []:
        out.append(f"  ! {v}")
    stages = doc.get("stages") or {}
    if stages:
        out.append(f"  {'stage':<12} {'rows':>12} {'bytes':>14} "
                   f"{'value sum':>14}  checksum")
        order = sorted(stages, key=lambda n: (n != "map_out", n))
        for name in order:
            st = stages[name]
            ck = (st["weighted_checksum"]
                  if int(st.get("value_sum") or 0) else st["pair_xor"])
            out.append(f"  {name:<12} {st['rows']:>12,} "
                       f"{st['bytes']:>14,} {st['value_sum']:>14,}  {ck}")
    skew = doc.get("skew") or {}
    rows = skew.get("rows_per_partition") or []
    red = doc.get("reduction") or {}
    ratio_pp = red.get("ratio_per_partition") or []
    est = skew.get("distinct_est_per_partition") or []
    if rows:
        peak = max(max(rows), 1)
        total = max(sum(rows), 1)
        out.append("")
        out.append(f"  {'part':>4} {'rows_in':>12} {'distinct~':>10} "
                   f"{'ratio':>8}  {'heat':<12} share")
        for p_ in range(len(rows)):
            e = est[p_] if p_ < len(est) else 0.0
            r = ratio_pp[p_] if p_ < len(ratio_pp) else 0.0
            out.append(
                f"  {p_:>4} {rows[p_]:>12,} {e:>10,.0f} "
                f"{r:>7.2f}x  {_bar(rows[p_] / peak)} "
                f"{100.0 * rows[p_] / total:>5.1f}%")
        out.append(f"imbalance factor {skew.get('imbalance_factor')} "
                   f"(max/mean partition rows)")
    if red.get("distinct_out"):
        out.append(f"reduction ratio {red.get('ratio')}x "
                   f"({red.get('rows_in'):,} rows in -> "
                   f"{red.get('distinct_out'):,} distinct keys out — "
                   f"the map-side combining budget)")
    hot = skew.get("hot_keys") or []
    if hot:
        out.append("hot keys: " + ", ".join(
            (f"{h['key']!r}" if h.get("key") else h["hash"])
            + f" ({h['rows']:,})" for h in hot[:5]))
    return "\n".join(out)
