"""``python -m map_oxidize_tpu obs ...`` — observability artifact tools.

Three subcommands, all pure host-side file work (no jax, no backend
init):

* ``obs merge`` — combine a distributed run's per-process trace shards
  (``<trace_out>.proc<i>``) into one Chrome trace (pid = process slot)
  plus a skew/straggler report.  Process 0 does this automatically at
  job end when the shards share a filesystem; this command covers the
  copied-from-isolated-hosts case and re-merges.
* ``obs diff`` — compare two entries of a run ledger
  (``--ledger-dir``'s ``ledger.jsonl``): per-phase and per-counter
  deltas, identity-checked (workload, config hash, version) so
  apples-to-oranges comparisons refuse by default; ``--gate`` exits
  nonzero when a regression exceeds the threshold.
* ``obs xprof`` — render the XLA program observatory report from a run's
  ``--metrics-out`` document (or an obs shard): per-program compile
  counts with recompile causes, FLOPs/bytes from ``cost_analysis``,
  achieved-vs-peak utilization, and the dispatch-gap histogram summary.
"""

from __future__ import annotations

import argparse
import sys


def build_obs_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="map_oxidize_tpu obs",
        description="observability artifact tools (merge shards, diff "
                    "ledger runs)")
    sub = p.add_subparsers(dest="cmd", required=True)

    m = sub.add_parser(
        "merge", help="merge per-process trace shards into one Chrome "
                      "trace + skew report")
    m.add_argument("base", help="the run's --trace-out path: shards are "
                                "<base>.proc<i>, the merged trace is "
                                "written to <base> (or --out)")
    m.add_argument("--out", default=None,
                   help="merged Chrome trace path (default: the base path)")
    m.add_argument("--skew-out", default=None,
                   help="skew report path (default: <out>.skew.json)")

    d = sub.add_parser(
        "diff", help="diff two ledger entries (per-phase/per-counter "
                     "deltas; --gate for a nonzero regression exit)")
    d.add_argument("--ledger-dir", required=True,
                   help="directory holding ledger.jsonl")
    d.add_argument("runs", nargs="*", default=[],
                   help="two entry indices into the (filtered) ledger, "
                        "python-style (default: -2 -1 — previous vs last)")
    d.add_argument("--workload", default=None,
                   help="filter the ledger to one workload first")
    d.add_argument("--threshold-pct", type=float, default=10.0,
                   help="regression threshold: a phase slower / throughput "
                        "lower by more than this percent flags (default 10)")
    d.add_argument("--gate", action="store_true",
                   help="exit 3 when any regression exceeds the threshold")
    d.add_argument("--force", action="store_true",
                   help="diff even when workload/config-hash/version "
                        "differ (mismatches print as warnings)")

    x = sub.add_parser(
        "xprof", help="render the XLA program observatory report (compile "
                      "ledger, cost/MFU join, dispatch-gap histograms) "
                      "from a --metrics-out document")
    x.add_argument("metrics", help="a run's --metrics-out JSON (or a "
                                   "<metrics_out>.proc<i> shard document)")
    x.add_argument("--json", action="store_true",
                   help="emit the structured report as JSON instead of "
                        "the rendered tables")
    return p


def obs_main(argv: list[str]) -> int:
    args = build_obs_parser().parse_args(argv)
    if args.cmd == "merge":
        return _merge(args)
    if args.cmd == "xprof":
        return _xprof(args)
    return _diff(args)


def _xprof(args) -> int:
    import json

    from map_oxidize_tpu.obs.xprof import render_report

    try:
        with open(args.metrics) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"error: cannot read metrics document {args.metrics!r}: {e}",
              file=sys.stderr)
        return 2
    if doc.get("schema"):  # an obs shard: the metrics doc nests inside
        doc = doc.get("metrics", {})
    report = doc.get("xprof")
    if not report:
        print("error: no xprof section in this metrics document (produced "
              "by a pre-observatory version, or the job ran no jitted "
              "programs)", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
        return 0
    print(render_report(report, histograms=doc.get("histograms")))
    return 0


def _merge(args) -> int:
    from map_oxidize_tpu.obs.merge import find_shards, merge_to_files

    shards = find_shards(args.base)
    if not shards:
        print(f"error: no shards found at {args.base}.proc*",
              file=sys.stderr)
        return 2
    out = args.out if args.out else args.base
    try:
        skew = merge_to_files(shards, out, args.skew_out)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    skew_path = args.skew_out if args.skew_out else out + ".skew.json"
    print(f"merged {len(shards)} shards -> {out}")
    print(f"skew report -> {skew_path}")
    for r in skew["straggler_ranking"]:
        print(f"  proc {r['process']}: work {r['work_s']:.3f}s, "
              f"collective wait {r['collective_wait_s']:.3f}s")
    return 0


def _diff(args) -> int:
    from map_oxidize_tpu.obs import ledger

    entries = ledger.read(args.ledger_dir, args.workload)
    if not entries:
        print(f"error: no ledger entries under {args.ledger_dir}"
              + (f" for workload {args.workload!r}" if args.workload
                 else ""), file=sys.stderr)
        return 2
    specs = args.runs if args.runs else ["-2", "-1"]
    if len(specs) != 2:
        print("error: diff takes exactly two entry indices",
              file=sys.stderr)
        return 2
    try:
        idx = [int(s) for s in specs]
    except ValueError:
        print(f"error: run specs must be integer indices, got {specs}",
              file=sys.stderr)
        return 2
    try:
        a, b = entries[idx[0]], entries[idx[1]]
    except IndexError:
        print(f"error: ledger has {len(entries)} entries; indices {idx} "
              "out of range", file=sys.stderr)
        return 2
    try:
        diff = ledger.diff_entries(a, b, args.threshold_pct, args.force)
    except ledger.LedgerMismatch as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(ledger.format_diff(a, b, diff))
    if args.gate and diff["regressions"]:
        return 3
    return 0
