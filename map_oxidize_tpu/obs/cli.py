"""``python -m map_oxidize_tpu obs ...`` — observability artifact tools.

Twelve subcommands, all pure host-side work (no jax, no backend init):

* ``obs merge`` — combine a distributed run's per-process trace shards
  (``<trace_out>.proc<i>``) into one Chrome trace (pid = process slot)
  plus a skew/straggler report (now carrying ``coverage`` and
  ``critpath`` sections).  Process 0 does this automatically at job end
  when the shards share a filesystem; this command covers the
  copied-from-isolated-hosts case and re-merges.  Torn/missing shards
  yield a post-mortem merge with a NAMED coverage gap; wall-clock skew
  past the alignment bound refuses (``--allow-clock-skew`` overrides).
* ``obs critpath`` — the causal critical-path report
  (:mod:`map_oxidize_tpu.obs.critpath`): which chain of spans, feed
  waits, and lockstep collective rounds across ALL processes set
  end-to-end wall — per-process blame shares, slack (how much each
  off-path process could slow for free), and what-if estimators
  ("proc 1 at peer-median speed => wall −31%").  Reads a trace base
  with shards, a merged trace, a skew report, a metrics document, a
  crash bundle, or ``--archive`` fleet post-mortems.
* ``obs diff`` — compare two entries of a run ledger
  (``--ledger-dir``'s ``ledger.jsonl``): per-phase and per-counter
  deltas, identity-checked (workload, config hash, version) so
  apples-to-oranges comparisons refuse by default; ``--gate`` exits
  nonzero when a regression exceeds the threshold.  ``--crash-dir``
  diffs a flight-recorder bundle against the ledger directly — no
  hand-extracting the metrics document from the bundle.
* ``obs xprof`` — render the XLA program observatory report from a run's
  ``--metrics-out`` document, an obs shard, or a ``--crash-dir`` bundle
  directory: per-program compile counts with recompile causes,
  FLOPs/bytes from ``cost_analysis``, achieved-vs-peak utilization, and
  the dispatch-gap histogram summary.
* ``obs data`` — render the data-plane observatory section
  (:mod:`map_oxidize_tpu.obs.dataplane`) from a ``--metrics-out``
  document, an obs shard, or a crash bundle: the row-conservation audit
  table (rows/bytes/checksums per phase boundary), the per-partition
  key-skew heatmap with the imbalance factor and hot keys, and the
  reduction-ratio gauges (rows in vs distinct keys out).
* ``obs trend`` — cross-run regression forensics over a run ledger (or
  ``BENCH_r*.json`` rounds): per-counter/per-phase trajectories, step-
  change detection against the median of prior entries, and a ranked
  movers report — when a gate trips, the table that says WHICH counter
  moved and when (``--json`` for the structured form).
* ``obs plan`` — the plan observatory report
  (:mod:`map_oxidize_tpu.obs.plan`): the knob values the planner chose
  before the job ran, each with its evidence provenance
  (curve/memo/default/pinned), and — when the calibration store held a
  workload curve — the predicted wall decomposition next to what
  actually happened, bucket by bucket, with the headline
  ``plan/model_error_pct``.
* ``obs where`` — the wall-clock attribution report
  (:mod:`map_oxidize_tpu.obs.attrib`): where every millisecond of a
  job's wall went — named buckets plus the unattributed remainder —
  from a metrics document, a crash bundle, or a live ``--url``.
* ``obs flame`` — renders a deep-profile capture's host sampling
  stacks (collapsed-stack format): hottest stacks and frames, joined
  against the attribution buckets.
* ``obs calib`` — the calibration-store tools.  ``show`` (also the
  bare legacy form) renders the store: per-collective bandwidth curves
  keyed (platform, devices, topology, collective, program,
  shape-bucket, source) plus the per-program dispatch/compute table
  accumulated across runs.  ``probe`` fills the curves with the
  deterministic microbenchmark harness
  (:mod:`map_oxidize_tpu.obs.probe`) — the ONE obs subcommand that
  initializes jax.  ``coverage`` reports needs-vs-has for a job shape:
  which (collective, bucket) cells the exchange chooser would consult
  and whether the store can answer them.
* ``obs fleet`` — the fleet observatory
  (:mod:`map_oxidize_tpu.obs.fleet`): a collector daemon polling any
  number of obs endpoints (``--targets``, a port file, resident-server
  spool dirs, and the well-known port-record spool), merging them into
  one fleet model, serving fleet ``/metrics`` (per-target labels +
  aggregates) / ``/status`` / ``/alerts`` (cross-target incident
  correlation), and optionally archiving the fleet series to a bounded
  on-disk ring (``--archive-dir``).
* ``obs top`` — live terminal view of a running job: polls the
  ``--obs-port`` server's ``/status`` and redraws phase, rows/sec, ETA,
  the compile/MFU table, HBM, the attribution panel, and the comms
  table.  Curses-free (plain
  ANSI redraw), so it works in any terminal and over ssh.  Renders the
  SLO plane's ``/alerts`` panel (firing + recently-resolved) when the
  evaluator is running, and — pointed at a RESIDENT job server
  (``python -m map_oxidize_tpu serve``) — the ``/jobs`` table next to
  the single-job view.  Pointed at a FLEET collector it renders the
  per-target table + incident panel instead; ``--archive`` renders the
  last archived fleet frame post-mortem.

``obs trend --archive`` and ``obs where --archive`` read the fleet
archive the same way — trajectories and per-target attribution survive
every producer process exiting.
"""

from __future__ import annotations

import argparse
import glob
import os
import sys


def build_obs_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="map_oxidize_tpu obs",
        description="observability artifact tools (merge shards, diff "
                    "ledger runs)")
    sub = p.add_subparsers(dest="cmd", required=True)

    m = sub.add_parser(
        "merge", help="merge per-process trace shards into one Chrome "
                      "trace + skew report")
    m.add_argument("base", help="the run's --trace-out path: shards are "
                                "<base>.proc<i>, the merged trace is "
                                "written to <base> (or --out)")
    m.add_argument("--out", default=None,
                   help="merged Chrome trace path (default: the base path)")
    m.add_argument("--skew-out", default=None,
                   help="skew report path (default: <out>.skew.json)")
    m.add_argument("--allow-clock-skew", action="store_true",
                   help="merge even when the lockstep rounds do not "
                        "overlap after wall-clock alignment (forensics "
                        "on hosts with known-bad clocks; cross-process "
                        "ordering may be wrong)")

    cp = sub.add_parser(
        "critpath", help="causal critical-path report: which chain of "
                         "spans, feed waits, and lockstep collective "
                         "rounds across ALL processes set end-to-end "
                         "wall — per-process blame shares, slack, and "
                         "what-if estimators")
    cp.add_argument("source", nargs="?", default=None,
                    help="a run's --trace-out base (its .proc<i> shards "
                         "are used), a merged Chrome trace, a skew "
                         "report, a --metrics-out document, or a "
                         "flight-recorder crash bundle directory (omit "
                         "with --archive)")
    cp.add_argument("--archive", default=None, metavar="DIR",
                    help="a fleet series archive (obs fleet "
                         "--archive-dir): render each archived "
                         "target's critical path post-mortem — works "
                         "after every producer process exited")
    cp.add_argument("--target", default=None,
                    help="with --archive: only this target label "
                         "(host:port)")
    cp.add_argument("--allow-clock-skew", action="store_true",
                    help="compute even when shard wall clocks disagree "
                         "past the alignment bound")
    cp.add_argument("--json", action="store_true",
                    help="emit the structured critpath document")

    d = sub.add_parser(
        "diff", help="diff two ledger entries (per-phase/per-counter "
                     "deltas; --gate for a nonzero regression exit)")
    d.add_argument("--ledger-dir", required=True,
                   help="directory holding ledger.jsonl")
    d.add_argument("runs", nargs="*", default=[],
                   help="two entry indices into the (filtered) ledger, "
                        "python-style (default: -2 -1 — previous vs last)")
    d.add_argument("--workload", default=None,
                   help="filter the ledger to one workload first")
    d.add_argument("--threshold-pct", type=float, default=10.0,
                   help="regression threshold: a phase slower / throughput "
                        "lower by more than this percent flags (default 10)")
    d.add_argument("--gate", action="store_true",
                   help="exit 3 when any regression exceeds the threshold")
    d.add_argument("--force", action="store_true",
                   help="diff even when workload/config-hash/version "
                        "differ (mismatches print as warnings)")
    d.add_argument("--crash-dir", default=None,
                   help="diff a flight-recorder crash bundle (the bundle "
                        "directory, or a --crash-dir root — the newest "
                        "bundle is picked) against the most recent "
                        "comparable ledger entry")

    x = sub.add_parser(
        "xprof", help="render the XLA program observatory report (compile "
                      "ledger, cost/MFU join, dispatch-gap histograms) "
                      "from a --metrics-out document or a crash bundle")
    x.add_argument("metrics", help="a run's --metrics-out JSON, a "
                                   "<metrics_out>.proc<i> shard document, "
                                   "or a flight-recorder --crash-dir "
                                   "bundle directory (its metrics.json "
                                   "is used; a crash-dir root resolves "
                                   "to the newest bundle)")
    x.add_argument("--json", action="store_true",
                   help="emit the structured report as JSON instead of "
                        "the rendered tables")

    da = sub.add_parser(
        "data", help="render the data-plane audit (row-conservation "
                     "table, per-partition skew heatmap, reduction-ratio "
                     "gauges) from a --metrics-out document or a crash "
                     "bundle")
    da.add_argument("metrics", help="a run's --metrics-out JSON, a "
                                    "<metrics_out>.proc<i> shard document, "
                                    "or a flight-recorder --crash-dir "
                                    "bundle directory (its metrics.json "
                                    "is used; a crash-dir root resolves "
                                    "to the newest bundle)")
    da.add_argument("--json", action="store_true",
                    help="emit the structured audit section as JSON "
                         "instead of the rendered tables")

    tr = sub.add_parser(
        "trend", help="cross-run regression forensics: per-counter/per-"
                      "phase trajectories over N ledger entries (or "
                      "BENCH_r*.json rounds), step-change detection, and "
                      "a ranked movers report attributing a gate failure "
                      "to the counters that moved")
    tr.add_argument("--ledger-dir", default=None,
                    help="directory holding ledger.jsonl (omit when "
                         "--bench files are given)")
    tr.add_argument("--workload", default=None,
                    help="filter the ledger to one workload (default: "
                         "the workload with the most entries)")
    tr.add_argument("--last", type=int, default=0,
                    help="use only the newest N entries (0 = all)")
    tr.add_argument("--bench", nargs="*", default=[], metavar="JSON",
                    help="BENCH_r*.json round artifacts to trend instead "
                         "of (or besides) a ledger")
    tr.add_argument("--archive", default=None, metavar="DIR",
                    help="a fleet series archive (obs fleet "
                         "--archive-dir): trend the archived fleet "
                         "samples — the history that survives every "
                         "producer process exiting")
    tr.add_argument("--threshold-pct", type=float, default=25.0,
                    help="step-change detection threshold (default 25)")
    tr.add_argument("--top", type=int, default=10,
                    help="movers to rank (default 10; 0 = all)")
    tr.add_argument("--all-series", action="store_true",
                    help="print every series' trajectory, not just "
                         "phases + steps + movers")
    tr.add_argument("--json", action="store_true",
                    help="emit the structured analysis as JSON")

    w = sub.add_parser(
        "where", help="wall-clock attribution report: where every "
                      "millisecond of a job's wall went (buckets + the "
                      "unattributed remainder), from a --metrics-out "
                      "document, a crash bundle, or a live /status URL")
    w.add_argument("metrics", nargs="?", default=None,
                   help="a run's --metrics-out JSON, an obs shard, or a "
                        "flight-recorder bundle directory (omit with "
                        "--url)")
    w.add_argument("--url", default=None,
                   help="a LIVE job/server obs URL (e.g. "
                        "http://127.0.0.1:8321): render the current "
                        "/status attribution instead of a document")
    w.add_argument("--archive", default=None, metavar="DIR",
                   help="a fleet series archive: render the attribution "
                        "of the last archived per-target /status "
                        "snapshots (post-mortem — works after every "
                        "target process exited)")
    w.add_argument("--target", default=None,
                   help="with --archive: only this target label "
                        "(host:port); default: every target that "
                        "carried an attribution")
    w.add_argument("--json", action="store_true",
                   help="emit the structured attribution document")

    pl = sub.add_parser(
        "plan", help="render the plan observatory: the knob values the "
                     "planner chose before the job ran (with per-knob "
                     "provenance — curve/memo/default/pinned) and the "
                     "predicted-vs-actual wall decomposition, from a "
                     "--metrics-out document, an obs shard, or a crash "
                     "bundle")
    pl.add_argument("metrics", help="a run's --metrics-out JSON, a "
                                    "<metrics_out>.proc<i> shard document, "
                                    "or a flight-recorder --crash-dir "
                                    "bundle directory (its metrics.json "
                                    "is used; a crash-dir root resolves "
                                    "to the newest bundle)")
    pl.add_argument("--json", action="store_true",
                    help="emit the structured plan document instead of "
                         "the rendered tables")

    fl = sub.add_parser(
        "flame", help="render a deep-profile capture's host sampling "
                      "stacks (collapsed-stack format): hottest stacks "
                      "and frames, joined against the wall-attribution "
                      "buckets")
    fl.add_argument("profile", help="a capture bundle directory "
                                    "(profile_<stamp>/), a --profile-dir "
                                    "root (newest capture wins), or a "
                                    "host_stacks.collapsed file")
    fl.add_argument("--top", type=int, default=15,
                    help="stacks/frames to list (default 15)")

    cb = sub.add_parser(
        "calib", help="the calibration store tools: 'show' renders the "
                      "per-collective bandwidth curves, 'probe' fills "
                      "them with deterministic microbenchmarks (source: "
                      "probe), 'coverage' reports needs-vs-has for a "
                      "job shape (bare 'obs calib <store>' still shows)")
    cbs = cb.add_subparsers(dest="calib_cmd", required=True)
    cbw = cbs.add_parser(
        "show", help="render the store: per-collective bandwidth "
                     "curves keyed (platform, devices, topology, "
                     "collective, program, shape-bucket, source) plus "
                     "per-program dispatch/compute figures")
    cbw.add_argument("store", help="the --calib-dir directory (or its "
                                   "calib.json)")
    cbw.add_argument("--json", action="store_true",
                     help="emit the raw store document")
    cbp = cbs.add_parser(
        "probe", help="deterministic collective microbenchmarks: sweep "
                      "the framework's exchange/psum/top-k programs "
                      "across pow2 payload buckets on the current mesh "
                      "and merge the rows in with source=probe (the ONE "
                      "obs subcommand that initializes jax)")
    cbp.add_argument("store", help="the --calib-dir directory to merge "
                                   "into (created if missing)")
    cbp.add_argument("--num-shards", type=int, default=8,
                     help="mesh width; on a CPU-only host this many "
                          "virtual devices are forced (default 8)")
    cbp.add_argument("--buckets", nargs="*", default=None,
                     metavar="BUCKET",
                     help="payload buckets to sweep (pow2 labels, e.g. "
                          "64KB 1MB; default 16KB..4MB)")
    cbp.add_argument("--reps", type=int, default=None,
                     help="timed repetitions per cell (default 5 — "
                          "above the chooser's min-samples floor)")
    cbp.add_argument("--backend", default="auto",
                     help="device pool to probe ('cpu'/'tpu'; default "
                          "auto)")
    cbp.add_argument("--json", action="store_true",
                     help="emit the probe summary document")
    cbc = cbs.add_parser(
        "coverage", help="needs-vs-has over the exchange chooser's "
                         "cells for a job shape: which (collective, "
                         "bucket) curves the planner would consult, "
                         "and whether the store can answer")
    cbc.add_argument("store", help="the --calib-dir directory (or its "
                                   "calib.json)")
    cbc.add_argument("--num-shards", type=int, default=8,
                     help="job mesh width (default 8)")
    cbc.add_argument("--batch-size", type=int, default=None,
                     help="job batch size (default: JobConfig default)")
    cbc.add_argument("--collect", action="store_true",
                     help="price the pair-collect engines' exchange "
                          "shape instead of the fold engine's")
    cbc.add_argument("--min-samples", type=int, default=None,
                     help="selection floor (default: the chooser's "
                          "CALIB_MIN_SAMPLES)")
    cbc.add_argument("--platform", default=None,
                     help="identity platform (default: the store's "
                          "sole identity, else required)")
    cbc.add_argument("--topology", default=None,
                     help="identity topology, e.g. 1x8 (default: the "
                          "store's sole identity)")
    cbc.add_argument("--device-count", type=int, default=None,
                     help="identity device count (default: the store's "
                          "sole identity)")
    cbc.add_argument("--json", action="store_true",
                     help="emit the coverage report document")

    fle = sub.add_parser(
        "fleet", help="fleet observatory: poll N obs endpoints, merge "
                      "them into one fleet model, serve fleet /metrics "
                      "(per-target labels + aggregates) /status /alerts "
                      "(cross-target incidents), and archive the fleet "
                      "series to a bounded on-disk ring")
    fle.add_argument("--targets", nargs="*", default=[], metavar="URL",
                     help="explicit endpoints (http://host:port or "
                          "host:port); explicit targets never depart "
                          "the model")
    fle.add_argument("--port-file", default="",
                     help="a MOXT_OBS_PORT_FILE-format file "
                          "('<process> <port>' lines) to derive "
                          "127.0.0.1 targets from")
    fle.add_argument("--spool", nargs="*", default=[], metavar="DIR",
                     dest="spool_dirs",
                     help="resident-server spool dirs: each one's "
                          "obs_port.json names a target")
    fle.add_argument("--discover-dir", default="",
                     help="well-known port-record spool to scan for "
                          "live processes (default: $MOXT_OBS_SPOOL or "
                          "the per-user tempdir spool; 'none' disables "
                          "auto-discovery)")
    fle.add_argument("--port", type=int, default=0,
                     help="the collector's own HTTP port (0 = "
                          "ephemeral, logged and written to "
                          "MOXT_OBS_PORT_FILE as 'fleet <port>')")
    fle.add_argument("--host", default="127.0.0.1")
    fle.add_argument("--interval", type=float, default=1.0,
                     help="seconds between scrape sweeps (default 1)")
    fle.add_argument("--stale-after", type=float, default=30.0,
                     help="a target unreachable/refusing this long is "
                          "marked stale and fires the fleet staleness "
                          "alert (default 30s)")
    fle.add_argument("--archive-dir", default=None,
                     help="persistent fleet series archive "
                          "(moxt-archive-v1 ring-of-segments; read "
                          "post-mortem with obs trend/top/where "
                          "--archive)")
    fle.add_argument("--archive-segment-records", type=int, default=512,
                     help="archive ring: samples per segment file")
    fle.add_argument("--archive-max-segments", type=int, default=16,
                     help="archive ring: segments kept (oldest pruned)")
    fle.add_argument("--slo-rules", default=None,
                     help="fleet SLO rule set (JSON file path or inline "
                          "JSON; defaults: target staleness, per-target "
                          "HBM watermark fraction, scrape refusals)")
    fle.add_argument("--iterations", type=int, default=0,
                     help="stop after N scrape sweeps (0 = run until "
                          "SIGTERM/Ctrl-C — the normal daemon mode)")

    t = sub.add_parser(
        "top", help="live terminal view of a running job: poll the "
                    "--obs-port server's /status and redraw")
    t.add_argument("--url", default=None,
                   help="the job's obs server, e.g. http://127.0.0.1:8321 "
                        "(the [obs] serving log line prints it)")
    t.add_argument("--archive", default=None, metavar="DIR",
                   help="render the last archived fleet frame from an "
                        "obs fleet --archive-dir instead of polling a "
                        "live server (post-mortem view)")
    t.add_argument("--interval", type=float, default=2.0,
                   help="seconds between polls (default 2)")
    t.add_argument("--iterations", type=int, default=0,
                   help="stop after N polls (0 = until the job's server "
                        "goes away or Ctrl-C)")
    t.add_argument("--no-clear", action="store_true",
                   help="append refreshes instead of redrawing in place "
                        "(log-friendly)")
    return p


def obs_main(argv: list[str]) -> int:
    # back-compat: the pre-subcommand form `obs calib <store> [--json]`
    # keeps working — insert the implicit 'show'
    if (argv and argv[0] == "calib"
            and (len(argv) == 1
                 or argv[1] not in ("show", "probe", "coverage"))):
        argv = [argv[0], "show", *argv[1:]]
    args = build_obs_parser().parse_args(argv)
    if args.cmd == "merge":
        return _merge(args)
    if args.cmd == "xprof":
        return _xprof(args)
    if args.cmd == "data":
        return _data(args)
    if args.cmd == "top":
        return _top(args)
    if args.cmd == "trend":
        return _trend(args)
    if args.cmd == "where":
        return _where(args)
    if args.cmd == "plan":
        return _plan(args)
    if args.cmd == "flame":
        return _flame(args)
    if args.cmd == "calib":
        return _calib(args)
    if args.cmd == "fleet":
        return _fleet(args)
    if args.cmd == "critpath":
        return _critpath(args)
    return _diff(args)


def _critpath_doc_from_source(source: str, allow_clock_skew: bool):
    """Resolve an ``obs critpath`` source argument to a critpath
    document.  Accepts, in probe order: a trace base with ``.proc<i>``
    shards next to it (fresh extraction, torn shards tolerated), a
    single shard document, a merged Chrome trace (event list), a skew
    report carrying a ``critpath`` section, and a metrics document /
    crash bundle (its stored section, else the attribution timeline).
    Returns ``(doc, title)`` or raises ``ValueError``."""
    import json

    from map_oxidize_tpu.obs import critpath, merge

    shard_paths = merge.find_shards(source)
    if shard_paths:
        shards, torn = merge.read_shards_tolerant(shard_paths)
        if not shards:
            raise ValueError(
                f"no readable shards at {source}.proc* "
                f"(torn: {[t['path'] for t in torn]})")
        cov = merge.coverage_report(shards, torn)
        doc = critpath.compute_from_shards(
            shards, coverage=cov, check_clock=not allow_clock_skew)
        wl = shards[0].get("meta", {}).get("workload")
        return doc, f"critical path — {wl or '?'} ({len(shards)} shards)"
    path = resolve_metrics_path(source)
    with open(path) as f:
        loaded = json.load(f)
    if isinstance(loaded, list):
        # a merged Chrome trace: pid = process slot, already aligned
        return (critpath.compute_from_merged_events(loaded),
                "critical path — merged trace")
    if not isinstance(loaded, dict):
        raise ValueError(f"{path!r} is not a critpath source")
    if loaded.get("schema") == merge.SHARD_SCHEMA:
        meta = loaded.get("meta", {})
        return (critpath.compute_from_shards([loaded]),
                f"critical path — proc {meta.get('process')} shard only")
    stored = loaded.get("critpath")
    if stored and not stored.get("error"):
        wl = (loaded.get("meta") or {}).get("workload")
        return stored, f"critical path — {wl or '?'} (stored)"
    attrib_doc = loaded.get("attrib")
    if attrib_doc:
        wl = (loaded.get("meta") or {}).get("workload")
        return (critpath.degenerate_from_attrib(attrib_doc),
                f"critical path — {wl or '?'} (attrib timeline)")
    raise ValueError(
        f"{path!r} carries neither trace shards, a merged trace, a "
        "critpath section, nor an attrib section")


def _critpath(args) -> int:
    import json

    from map_oxidize_tpu.obs import critpath

    if args.archive:
        # post-mortem: archived per-target /status snapshots carry the
        # critpath headline and the attribution each path degenerates
        # onto — readable after every producer process exited
        from map_oxidize_tpu.obs.fleet import ArchiveMismatch, SeriesArchive

        try:
            snap = SeriesArchive.latest(args.archive, "targets")
        except ArchiveMismatch as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        targets = (snap or {}).get("targets") or {}
        if args.target is not None:
            targets = {k: v for k, v in targets.items()
                       if k == args.target}
        docs = {}
        for label, st in sorted(targets.items()):
            if not isinstance(st, dict):
                continue
            try:
                docs[label] = critpath.degenerate_from_attrib(
                    st.get("attrib"))
                cp = st.get("critpath") or {}
                if cp.get("bound_by"):
                    docs[label]["bound_by"] = cp["bound_by"]
            except ValueError:
                continue
        if not docs:
            print("error: no archived target attribution"
                  + (f" for {args.target!r}" if args.target else "")
                  + f" under {args.archive!r}", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(docs, indent=1, sort_keys=True))
            return 0
        for label, doc in docs.items():
            print(critpath.render(
                doc, title=f"critical path — {label} (archived)"))
        return 0
    if not args.source:
        print("error: obs critpath needs a source (trace base, merged "
              "trace, metrics document, crash bundle) or --archive",
              file=sys.stderr)
        return 2
    try:
        doc, title = _critpath_doc_from_source(args.source,
                                               args.allow_clock_skew)
    except critpath.ClockSkewError as e:
        print(f"error: {e}", file=sys.stderr)
        return 3
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(doc, indent=1, sort_keys=True))
        return 0
    print(critpath.render(doc, title=title))
    return 0


def _fleet(args) -> int:
    from map_oxidize_tpu.config import FleetConfig
    from map_oxidize_tpu.obs.fleet import FleetCollector, FleetServer

    try:
        cfg = FleetConfig(
            targets=list(args.targets), port_file=args.port_file,
            spool_dirs=list(args.spool_dirs),
            discover_dir=args.discover_dir,
            host=args.host, port=args.port,
            poll_interval_s=args.interval,
            stale_after_s=args.stale_after,
            archive_dir=args.archive_dir,
            archive_segment_records=args.archive_segment_records,
            archive_max_segments=args.archive_max_segments,
            slo_rules=args.slo_rules,
        ).validate()
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    collector = FleetCollector(cfg)
    server = FleetServer(collector, cfg.port, host=cfg.host).start()
    print(f"[fleet] collector on {server.url} "
          f"(/metrics /status /alerts /series; watch with "
          f"obs top --url {server.url})", flush=True)
    try:
        if args.iterations:
            for _ in range(args.iterations):
                collector.poll_once()
                import time as _time

                _time.sleep(cfg.poll_interval_s)
        else:
            collector.start()
            collector._thread.join()
    except KeyboardInterrupt:
        pass
    finally:
        collector.stop()
        server.stop()
    return 0


def _where(args) -> int:
    import json

    from map_oxidize_tpu.obs.attrib import render

    if args.archive:
        # post-mortem: the archived per-target /status snapshots carry
        # each target's last live attribution — readable after every
        # producer process exited
        from map_oxidize_tpu.obs.fleet import ArchiveMismatch, SeriesArchive

        try:
            snap = SeriesArchive.latest(args.archive, "targets")
        except ArchiveMismatch as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        targets = (snap or {}).get("targets") or {}
        if args.target is not None:
            targets = {k: v for k, v in targets.items()
                       if k == args.target}
        with_attrib = {label: st for label, st in sorted(targets.items())
                       if isinstance(st, dict) and st.get("attrib")}
        if not with_attrib:
            print("error: no archived target attribution"
                  + (f" for {args.target!r}" if args.target else "")
                  + f" under {args.archive!r}", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps({label: st["attrib"]
                              for label, st in with_attrib.items()},
                             indent=1, sort_keys=True))
            return 0
        for label, st in with_attrib.items():
            wl = (st.get("meta") or {}).get("workload")
            print(render(st["attrib"],
                         title=f"where did the time go — {label} "
                               f"({wl or '?'}, archived)"))
        return 0
    if args.url:
        import urllib.request

        url = args.url.rstrip("/") + "/status"
        try:
            with urllib.request.urlopen(url, timeout=5) as resp:
                status = json.loads(resp.read())
        except (OSError, ValueError) as e:
            print(f"error: cannot reach {url}: {e}", file=sys.stderr)
            return 2
        doc = status.get("attrib")
        title = (f"where did the time go — {status.get('phase') or '?'} "
                 f"(live)")
    elif args.metrics:
        path = resolve_metrics_path(args.metrics)
        try:
            with open(path) as f:
                mdoc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"error: cannot read metrics document {path!r}: {e}",
                  file=sys.stderr)
            return 2
        if mdoc.get("schema"):  # an obs shard nests the metrics doc
            mdoc = mdoc.get("metrics", {})
        doc = mdoc.get("attrib")
        wl = (mdoc.get("meta") or {}).get("workload")
        title = f"where did the time go — {wl or '?'}"
    else:
        print("error: obs where needs a metrics document, --url, or "
              "--archive", file=sys.stderr)
        return 2
    if not doc:
        print("error: no attrib section (produced by a pre-attribution "
              "version?)", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(doc, indent=1, sort_keys=True))
        return 0
    print(render(doc, title=title))
    return 0


def resolve_profile_stacks(path: str) -> "tuple[str, str | None]":
    """Resolve an ``obs flame`` argument to ``(collapsed_path,
    profile_json_path)``: a collapsed file directly, a capture bundle
    directory, or a --profile-dir root (newest capture)."""
    if os.path.isfile(path):
        side = os.path.join(os.path.dirname(path), "profile.json")
        return path, side if os.path.isfile(side) else None
    direct = os.path.join(path, "host_stacks.collapsed")
    if os.path.isfile(direct):
        return direct, (os.path.join(path, "profile.json")
                        if os.path.isfile(os.path.join(path,
                                                       "profile.json"))
                        else None)
    bundles = sorted(glob.glob(os.path.join(path, "profile_*",
                                            "host_stacks.collapsed")))
    if bundles:
        newest = bundles[-1]
        side = os.path.join(os.path.dirname(newest), "profile.json")
        return newest, side if os.path.isfile(side) else None
    return path, None


def _flame(args) -> int:
    import json

    from map_oxidize_tpu.obs.profiler import flame_report

    stacks_path, profile_path = resolve_profile_stacks(args.profile)
    try:
        with open(stacks_path) as f:
            text = f.read()
    except OSError as e:
        print(f"error: cannot read collapsed stacks {stacks_path!r}: {e}",
              file=sys.stderr)
        return 2
    attrib_doc = None
    if profile_path:
        try:
            with open(profile_path) as f:
                attrib_doc = json.load(f).get("attrib")
        except (OSError, ValueError):
            pass
    print(flame_report(text, attrib_doc=attrib_doc, top=args.top))
    return 0


def _calib(args) -> int:
    if args.calib_cmd == "probe":
        return _calib_probe(args)
    if args.calib_cmd == "coverage":
        return _calib_coverage(args)
    import json

    from map_oxidize_tpu.obs.calib import CalibMismatch, CalibStore, render

    try:
        store = CalibStore.load(args.store)
    except CalibMismatch as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not store.doc.get("comms") and not store.doc.get("programs"):
        print(f"error: no calibration store at {args.store!r} (runs "
              "merge into it via --calib-dir)", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(store.doc, indent=1, sort_keys=True))
        return 0
    print(render(store))
    return 0


def _calib_probe(args) -> int:
    import json

    # the ONE obs subcommand that needs a backend: force the virtual CPU
    # pool BEFORE jax initializes when the host has fewer real devices
    flags = os.environ.get("XLA_FLAGS", "")
    if (args.num_shards > 0
            and "xla_force_host_platform_device_count" not in flags):
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.num_shards}").strip()
    from map_oxidize_tpu.obs import probe as _probe
    from map_oxidize_tpu.obs.calib import CalibMismatch

    kw = {}
    if args.buckets:
        kw["buckets"] = tuple(args.buckets)
    if args.reps:
        kw["reps"] = int(args.reps)
    try:
        summary = _probe.run_probe(args.store,
                                   num_shards=args.num_shards,
                                   backend=args.backend, **kw)
    except CalibMismatch as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(summary, indent=1, sort_keys=True))
        return 0
    print(_probe.render_probe(summary))
    return 0


def _calib_coverage(args) -> int:
    import json

    from map_oxidize_tpu.obs import calib as _calib_mod

    try:
        store = _calib_mod.CalibStore.load(args.store)
    except _calib_mod.CalibMismatch as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    # identity: explicit flags win; otherwise the store's sole identity
    idents = {(r["platform"], str(r["device_count"]), r["topology"])
              for r in (store.doc.get("comms") or {}).values()}
    if args.platform and args.topology and args.device_count is not None:
        ident = {"platform": args.platform,
                 "device_count": args.device_count,
                 "topology": args.topology}
    elif len(idents) == 1:
        p, dc, topo = next(iter(idents))
        ident = {"platform": p, "device_count": int(dc),
                 "topology": topo}
    else:
        print("error: store holds "
              f"{len(idents)} identities; name one with --platform "
              "--device-count --topology", file=sys.stderr)
        return 2
    if args.batch_size is None:
        from map_oxidize_tpu.config import JobConfig

        batch = dataclasses_field_default(JobConfig, "batch_size")
    else:
        batch = args.batch_size
    cap, row_bytes = _calib_mod.exchange_shape(args.num_shards, batch,
                                               collect=args.collect)
    payload = (args.num_shards * args.num_shards * cap * (8 + row_bytes))
    bucket = _calib_mod.shape_bucket(payload)
    cells = [{"collective": c, "bucket": bucket}
             for c in _calib_mod.EXCHANGE_COLLECTIVE_NAMES]
    report = _calib_mod.coverage_report(
        store, ident, cells,
        min_samples=args.min_samples or _calib_mod.CALIB_MIN_SAMPLES)
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
        return 0
    print(_calib_mod.render_coverage(report))
    return 0


def dataclasses_field_default(cls, name: str):
    """A dataclass field's default value (jax-free JobConfig peek)."""
    import dataclasses

    for f in dataclasses.fields(cls):
        if f.name == name:
            return f.default
    raise AttributeError(name)


def resolve_metrics_path(path: str) -> str:
    """A metrics-document argument may be the JSON itself, a flight-
    recorder BUNDLE directory (its ``metrics.json``), or a ``--crash-dir``
    root (the newest ``crash_*`` bundle inside — the stamp prefix sorts
    chronologically)."""
    if not os.path.isdir(path):
        return path
    direct = os.path.join(path, "metrics.json")
    if os.path.isfile(direct):
        return direct
    bundles = sorted(glob.glob(os.path.join(path, "crash_*",
                                            "metrics.json")))
    if bundles:
        return bundles[-1]
    return path


def _xprof(args) -> int:
    import json

    from map_oxidize_tpu.obs.xprof import render_report

    path = resolve_metrics_path(args.metrics)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"error: cannot read metrics document {path!r}: {e}",
              file=sys.stderr)
        return 2
    if doc.get("schema"):  # an obs shard: the metrics doc nests inside
        doc = doc.get("metrics", {})
    report = doc.get("xprof")
    if not report:
        print("error: no xprof section in this metrics document (produced "
              "by a pre-observatory version, or the job ran no jitted "
              "programs)", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
        return 0
    print(render_report(report, histograms=doc.get("histograms")))
    return 0


def _data(args) -> int:
    import json

    from map_oxidize_tpu.obs.dataplane import render

    path = resolve_metrics_path(args.metrics)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"error: cannot read metrics document {path!r}: {e}",
              file=sys.stderr)
        return 2
    if doc.get("schema"):  # an obs shard: the metrics doc nests inside
        doc = doc.get("metrics", {})
    section = doc.get("data")
    if not section:
        print("error: no data section in this metrics document (produced "
              "by a pre-audit version, or the run disabled it with "
              "--no-data-audit)", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(section, indent=1, sort_keys=True))
        return 0
    print(render(section))
    return 0


def _plan(args) -> int:
    import json

    from map_oxidize_tpu.obs.plan import render

    path = resolve_metrics_path(args.metrics)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"error: cannot read metrics document {path!r}: {e}",
              file=sys.stderr)
        return 2
    if doc.get("schema"):  # an obs shard: the metrics doc nests inside
        doc = doc.get("metrics", {})
    section = doc.get("plan")
    if not section:
        print("error: no plan section in this metrics document (produced "
              "by a pre-planner version, the job ran with --plan off, or "
              "this is a resident server's own bundle — each job plans "
              "itself)", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(section, indent=1, sort_keys=True))
        return 0
    print(render(section))
    return 0


def _merge(args) -> int:
    from map_oxidize_tpu.obs.merge import find_shards, merge_to_files

    shards = find_shards(args.base)
    if not shards:
        print(f"error: no shards found at {args.base}.proc*",
              file=sys.stderr)
        return 2
    out = args.out if args.out else args.base
    try:
        skew = merge_to_files(shards, out, args.skew_out,
                              allow_clock_skew=args.allow_clock_skew)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    skew_path = args.skew_out if args.skew_out else out + ".skew.json"
    cov = skew.get("coverage") or {}
    n_merged = len(cov.get("present_processes") or []) or len(shards)
    print(f"merged {n_merged} shards -> {out}")
    print(f"skew report -> {skew_path}")
    if cov.get("missing_processes") or cov.get("torn_shards"):
        print(f"  !! coverage gap: missing process(es) "
              f"{cov.get('missing_processes')}, torn shard(s) "
              f"{cov.get('torn_shards')} — post-mortem merge over the "
              "survivors")
    for r in skew["straggler_ranking"]:
        print(f"  proc {r['process']}: work {r['work_s']:.3f}s, "
              f"collective wait {r['collective_wait_s']:.3f}s")
    cp = skew.get("critpath") or {}
    if cp.get("bound_by"):
        print(f"  critical path: bound by {cp['bound_by']} "
              f"(obs critpath {args.base} for the full report)")
    return 0


def _diff(args) -> int:
    import json

    from map_oxidize_tpu.obs import ledger

    crash_entry = None
    workload = args.workload
    if args.crash_dir:
        path = resolve_metrics_path(args.crash_dir)
        try:
            with open(path) as f:
                crash_entry = ledger.entry_from_metrics_doc(json.load(f))
        except (OSError, ValueError) as e:
            print(f"error: cannot read crash bundle metrics {path!r}: {e}",
                  file=sys.stderr)
            return 2
        if workload is None:
            workload = crash_entry.get("workload")
    entries = ledger.read(args.ledger_dir, workload)
    if not entries:
        print(f"error: no ledger entries under {args.ledger_dir}"
              + (f" for workload {workload!r}" if workload
                 else ""), file=sys.stderr)
        return 2
    if crash_entry is not None:
        # before = a chosen (default: last) ledger entry, after = the
        # crashed run's partial metrics — "what changed before it died"
        specs = args.runs if args.runs else ["-1"]
        if len(specs) != 1:
            print("error: --crash-dir takes at most one ledger index "
                  "(the entry to compare the bundle against)",
                  file=sys.stderr)
            return 2
        try:
            a = entries[int(specs[0])]
        except (ValueError, IndexError):
            print(f"error: bad ledger index {specs[0]!r} "
                  f"({len(entries)} entries)", file=sys.stderr)
            return 2
        b = crash_entry
    else:
        specs = args.runs if args.runs else ["-2", "-1"]
        if len(specs) != 2:
            print("error: diff takes exactly two entry indices",
                  file=sys.stderr)
            return 2
        try:
            idx = [int(s) for s in specs]
        except ValueError:
            print(f"error: run specs must be integer indices, got {specs}",
                  file=sys.stderr)
            return 2
        try:
            a, b = entries[idx[0]], entries[idx[1]]
        except IndexError:
            print(f"error: ledger has {len(entries)} entries; indices "
                  f"{idx} out of range", file=sys.stderr)
            return 2
    try:
        diff = ledger.diff_entries(a, b, args.threshold_pct, args.force)
    except ledger.LedgerMismatch as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if crash_entry is not None and crash_entry.get("aborted"):
        print("NOTE: comparing against a crash bundle (partial metrics "
              "as of the abort); phase times and totals read low")
    print(ledger.format_diff(a, b, diff))
    if args.gate and diff["regressions"]:
        return 3
    return 0


# --- obs trend -------------------------------------------------------------


def _trend(args) -> int:
    import json

    from map_oxidize_tpu.obs import ledger, trend

    groups: list[tuple[str, list]] = []
    if args.archive:
        from map_oxidize_tpu.obs.fleet import ArchiveMismatch

        try:
            entries = trend.archive_entries(args.archive, last=args.last)
        except (ArchiveMismatch, OSError) as e:
            print(f"error: cannot read fleet archive: {e}",
                  file=sys.stderr)
            return 2
        if len(entries) >= 2:
            groups.append(("fleet-archive", entries))
        else:
            print(f"(fleet archive: only {len(entries)} sample — need "
                  ">= 2 to trend)")
    if args.bench:
        paths: list[str] = []
        for spec in args.bench:
            hits = sorted(glob.glob(spec))
            paths += hits if hits else [spec]
        try:
            entries = trend.bench_rounds(paths)
        except (OSError, ValueError) as e:
            print(f"error: cannot read bench round: {e}", file=sys.stderr)
            return 2
        # BENCH and MULTICHIP rounds load side by side but trend as
        # separate groups — a scoreboard ratio and a dryrun pass flag
        # share no axis
        by_kind: dict[str, list] = {}
        for e in entries:
            by_kind.setdefault(e["workload"], []).append(e)
        ok = False
        for kind, es in sorted(by_kind.items()):
            if len(es) >= 2:
                groups.append((kind, es))
                ok = True
            else:
                print(f"({kind}: only {len(es)} round — need >= 2 to "
                      "trend)")
        if not ok:
            print(f"error: need >= 2 rounds of a kind, got "
                  f"{ {k: len(v) for k, v in by_kind.items()} }",
                  file=sys.stderr)
            return 2
    if args.ledger_dir:
        entries = ledger.read(args.ledger_dir, args.workload)
        if not entries and not groups:
            print(f"error: no ledger entries under {args.ledger_dir}"
                  + (f" for workload {args.workload!r}" if args.workload
                     else ""), file=sys.stderr)
            return 2
        by_wl: dict[str, list] = {}
        for e in entries:
            by_wl.setdefault(e.get("workload") or "?", []).append(e)
        if args.workload is None and len(by_wl) > 1:
            # default to the richest history; name the rest so the
            # operator knows what to ask for
            names = sorted(by_wl, key=lambda w: -len(by_wl[w]))
            print(f"(ledger holds {len(by_wl)} workloads; trending "
                  f"{names[0]!r} — pass --workload for "
                  f"{', '.join(repr(n) for n in names[1:6])})")
            by_wl = {names[0]: by_wl[names[0]]}
        for wl, es in sorted(by_wl.items()):
            if args.last and args.last > 1:
                es = es[-args.last:]
            if len(es) >= 2:
                groups.append((wl, es))
            else:
                print(f"(workload {wl!r}: only {len(es)} entry — need "
                      ">= 2 to trend)")
    if not groups and not args.bench and not args.ledger_dir \
            and not args.archive:
        print("error: obs trend needs --ledger-dir, --bench files, "
              "and/or --archive", file=sys.stderr)
        return 2
    if not groups:
        return 2
    analyses = [trend.analyze(es, args.threshold_pct, args.top)
                for _wl, es in groups]
    if args.json:
        print(json.dumps(analyses if len(analyses) > 1 else analyses[0],
                         indent=1, sort_keys=True))
        return 0
    for a in analyses:
        print(trend.render(a, show_series=1 if args.all_series else 0))
    return 0


# --- obs top ---------------------------------------------------------------


from map_oxidize_tpu.obs.metrics import format_bytes as _fmt_bytes


def render_status(doc: dict) -> str:
    """One ``obs top`` frame from a ``/status`` document.  Pure, so tests
    pin the rendering without a server."""
    meta = doc.get("meta", {})
    head = (f"moxt obs top — {meta.get('workload') or '?'} "
            f"v{meta.get('version', '?')} cfg {meta.get('config_hash')}")
    if doc.get("n_processes", 1) > 1:
        head += f"  [proc {doc.get('process')}/{doc.get('n_processes')}]"
    lines = [head]
    line = (f"phase={doc.get('phase') or '?'} "
            f"elapsed={doc.get('elapsed_s', 0):.1f}s")
    prog = doc.get("progress") or {}
    if prog:
        line += (f" rows={prog.get('rows', 0):,} "
                 f"({prog.get('rows_per_sec', 0):,.0f} rows/s)")
        if prog.get("fraction") is not None:
            line += f" {100 * prog['fraction']:.1f}%"
        if prog.get("eta_s") is not None:
            line += f" eta={prog['eta_s']:.0f}s"
        if prog.get("hbm_bytes") is not None:
            line += f" hbm={_fmt_bytes(prog['hbm_bytes'])}"
    lines.append(line)
    stalls = (doc.get("counters") or {}).get("heartbeat/stalls")
    if stalls:
        lines.append(f"!! {stalls:g} stall episode(s)")
    xprof = doc.get("xprof") or {}
    progs = xprof.get("programs") or {}
    if progs:
        lines.append(
            f"programs ({xprof.get('total_compiles', 0)} compiles, "
            f"{xprof.get('total_dispatches', 0)} dispatches):")
        lines.append(f"  {'program':<28} {'n':>3} {'disp':>6} {'MFU%':>6} "
                     f" bound")
        ranked = sorted(progs.items(),
                        key=lambda kv: -kv[1].get("dispatches", 0))
        for name, r in ranked[:8]:
            lines.append(
                f"  {name:<28} {r.get('compiles', 0):>3} "
                f"{r.get('dispatches', 0):>6} "
                f"{r.get('mfu_pct', '-'):>6}  {r.get('bound', '-')}")
    comms = doc.get("comms") or []
    if comms:
        lines.append("comms:")
        lines.append(f"  {'collective':<11} {'program':<24} {'shape':<12} "
                     f"{'calls':>6} {'bytes':>9} {'p50 ms':>7}")
        for c in comms[:8]:
            lat = c.get("latency_ms") or {}
            p50 = lat.get("p50")
            lines.append(
                f"  {c['collective']:<11} {c['program']:<24} "
                f"{c['shape']:<12} {c['count']:>6} "
                f"{_fmt_bytes(c['bytes']):>9} "
                f"{p50 if p50 is not None else '-':>7}")
    at = doc.get("attrib")
    if at:
        from map_oxidize_tpu.obs.attrib import render as render_attrib

        lines.append(render_attrib(at, title="where"))
    cp = doc.get("critpath")
    if cp and cp.get("bound_by"):
        # the causal one-liner: what bounded the job, end to end
        line = f"bound by: {cp['bound_by']}"
        slack_ms = cp.get("top_process_slack_ms")
        if isinstance(slack_ms, (int, float)) and slack_ms > 0:
            line += f"  (top process slack {slack_ms / 1e3:.2f}s)"
        cw = cp.get("collective_wait_share_pct")
        if isinstance(cw, (int, float)) and cw > 0:
            line += f"  collective-wait {cw:.1f}% of path"
        lines.append(line)
    agg = doc.get("aggregate")
    if agg:
        lines.append(
            f"aggregate (x{agg.get('n_processes')}): "
            f"~{agg.get('est_rows_per_sec', 0):,.0f} rows/s global, "
            f"collective wait {agg.get('collective_wait_s', 0):.2f}s "
            f"({100 * agg.get('collective_wait_frac', 0):.1f}% of wall)")
    spans = doc.get("open_spans")
    if spans:
        lines.append("open spans: " + "; ".join(spans[:4]))
    return "\n".join(lines)


def render_alerts(doc: dict) -> str:
    """The SLO plane's ``/alerts`` document as an ``obs top`` panel:
    firing alerts (rule, series, observed value) plus the recently
    resolved tail.  Pure, so tests pin the rendering without a server."""
    counts = doc.get("counts") or {}
    firing = doc.get("firing") or []
    resolved = doc.get("resolved") or []
    head = (f"alerts: {len(firing)} firing "
            f"(lifetime {counts.get('fired', 0)} fired / "
            f"{counts.get('resolved', 0)} resolved)")
    lines = [head]
    def _g(v):
        return f"{v:g}" if isinstance(v, (int, float)) else "?"

    for a in firing[:8]:
        lines.append(
            f"  !! {a.get('severity', '?').upper():<8} {a['rule']}: "
            f"{a['series']}={_g(a.get('value'))} "
            f"({a.get('op', '?')} {_g(a.get('threshold'))})")
    for e in resolved[-4:]:
        lines.append(
            f"  ok resolved {e['rule']}: {e['series']} "
            f"(was {_g(e.get('value'))})")
    return "\n".join(lines)


def render_jobs(doc: dict) -> str:
    """The resident server's ``/jobs`` table as an ``obs top`` section.
    Pure, so tests pin the rendering without a server."""
    counts = doc.get("counts") or {}
    q = doc.get("queue") or {}
    summary = ", ".join(f"{n} {s}" for s, n in sorted(counts.items()))
    head = (f"jobs ({summary or 'none yet'};"
            f" queue {q.get('depth', 0)}/{q.get('max', '?')}")
    hbm = doc.get("hbm") or {}
    if hbm.get("budget_bytes"):
        in_use = max(hbm.get("reserved_bytes", 0),
                     hbm.get("measured_live_bytes", 0))
        head += (f", hbm {_fmt_bytes(in_use)}"
                 f"/{_fmt_bytes(hbm['budget_bytes'])}")
    if doc.get("draining"):
        head += ", DRAINING"
    lines = [head + "):"]
    lines.append(f"  {'id':<10} {'state':<9} {'workload':<13} {'phase':<12} "
                 f"{'rows/s':>9} {'compiles':>8}  reason")
    for r in (doc.get("jobs") or [])[:12]:
        rate = r.get("rows_per_sec")
        if rate is None and r.get("records_in") and r.get("duration_s"):
            rate = round(r["records_in"] / max(r["duration_s"], 1e-9), 1)
        compiles = r.get("compiles")
        lines.append(
            f"  {r['id']:<10} {r['state']:<9} {r['workload']:<13} "
            f"{(r.get('phase') or '-'):<12} "
            f"{(f'{rate:,.0f}' if rate is not None else '-'):>9} "
            f"{(compiles if compiles is not None else '-'):>8}  "
            f"{r.get('reason') or '-'}")
    return "\n".join(lines)


def render_fleet(doc: dict) -> str:
    """A ``moxt-fleet-status-v1`` document as an ``obs top`` frame: the
    per-target table (state, phase, rows/sec, HBM, queue, firing alerts,
    staleness) plus the fleet aggregates.  Pure, so tests pin the
    rendering without a collector."""
    counts = doc.get("counts") or {}
    agg = doc.get("aggregates") or {}
    head = (f"moxt obs fleet — {counts.get('targets', 0)} targets "
            f"({counts.get('up', 0)} up, {counts.get('stale', 0)} stale"
            + (f", {counts['departed']} departed"
               if counts.get("departed") else "")
            + f")  uptime={doc.get('uptime_s', 0):.0f}s")
    lines = [head]
    lines.append(
        f"fleet: {agg.get('rows_per_sec', 0):,.0f} rows/s, "
        f"hbm max {_fmt_bytes(int(agg.get('hbm_max_bytes', 0) or 0))}, "
        f"queue {agg.get('queue_depth', 0):g}, "
        f"{agg.get('jobs_running', 0):g} running, "
        f"{agg.get('target_alerts_firing', 0):g} target alerts firing")
    targets = doc.get("targets") or []
    if targets:
        lines.append(
            f"  {'target':<21} {'state':<8} {'kind':<6} {'phase':<14} "
            f"{'rows/s':>9} {'hbm':>9} {'queue':>5} {'alerts':>6} "
            f"{'stale s':>7}")
        for t in targets[:16]:
            stale_s = t.get("staleness_s") or 0
            lines.append(
                f"  {t['target']:<21} {t['state']:<8} "
                f"{t.get('kind', '?'):<6} "
                f"{(t.get('phase') or '-'):<14} "
                f"{t.get('rows_per_sec', 0):>9,.0f} "
                f"{_fmt_bytes(int(t.get('hbm_bytes') or 0)):>9} "
                f"{t.get('queue_depth', 0):>5g} "
                f"{t.get('alerts_firing', 0):>6g} "
                f"{(f'{stale_s:.0f}' if stale_s else '-'):>7}")
    arch = doc.get("archive")
    if arch:
        lines.append(f"archive: {arch['dir']} "
                     f"({arch['segments']} segments, cap "
                     f"{arch['max_records']} samples)")
    return "\n".join(lines)


def render_fleet_alerts(doc: dict) -> str:
    """A ``moxt-fleet-alerts-v1`` document as an ``obs top`` panel: the
    correlated incidents (one row per rule, naming every target) plus
    the collector's own firing set."""
    incidents = doc.get("incidents") or []
    fleet = doc.get("fleet") or {}
    counts = fleet.get("counts") or {}
    lines = [f"fleet alerts: {len([i for i in incidents if i['active']])}"
             f" active incidents (collector lifetime "
             f"{counts.get('fired', 0)} fired / "
             f"{counts.get('resolved', 0)} resolved)"]
    for inc in incidents[:8]:
        mark = "!!" if inc.get("active") else "ok"
        lines.append(
            f"  {mark} {inc.get('severity', '?').upper():<8} "
            f"{inc['rule']}: {inc['k']} target(s) — "
            f"{', '.join(inc['targets'][:6])}"
            + ("" if inc.get("active") else " (resolved)"))
    return "\n".join(lines)


def _top_archive(args) -> int:
    """``obs top --archive``: the last archived fleet frame, rendered
    once — the post-mortem view after every process exited."""
    from map_oxidize_tpu.obs.fleet import ArchiveMismatch, SeriesArchive

    try:
        status = SeriesArchive.latest(args.archive, "status")
        alerts = SeriesArchive.latest(args.archive, "alerts")
    except ArchiveMismatch as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not status:
        print(f"error: no archived fleet status under {args.archive!r}",
              file=sys.stderr)
        return 2
    frame = render_fleet(status)
    if alerts and alerts.get("schema") == "moxt-fleet-alerts-v1":
        frame += "\n" + render_fleet_alerts(alerts)
    print(frame)
    print(f"(archived frame as of t={status.get('t_unix_s')})")
    return 0


def _top(args) -> int:
    import json
    import time
    import urllib.error
    import urllib.request

    if args.archive:
        return _top_archive(args)
    if not args.url:
        print("error: obs top needs --url (live) or --archive "
              "(post-mortem)", file=sys.stderr)
        return 2
    base = args.url.rstrip("/")
    url = base + "/status"
    polls = 0
    seen_one = False
    try:
        while True:
            try:
                with urllib.request.urlopen(url, timeout=5) as resp:
                    doc = json.loads(resp.read())
            except (urllib.error.URLError, OSError, ValueError) as e:
                if seen_one:
                    # the server going away after healthy polls means
                    # the job finished — a clean exit, not an error
                    print("job's obs server went away (job finished?)")
                    return 0
                print(f"error: cannot reach {url}: {e}", file=sys.stderr)
                return 2
            seen_one = True
            fleet_schema = doc.get("schema") == "moxt-fleet-status-v1"
            frame = render_fleet(doc) if fleet_schema \
                else render_status(doc)
            # the SLO plane's panel rides beside the job view (servers
            # without an evaluator 404 here — skip silently); a fleet
            # collector serves the correlated-incident form instead
            try:
                with urllib.request.urlopen(base + "/alerts",
                                            timeout=5) as resp:
                    alerts_doc = json.loads(resp.read())
                if alerts_doc.get("schema") == "moxt-fleet-alerts-v1":
                    frame += "\n" + render_fleet_alerts(alerts_doc)
                elif alerts_doc.get("schema") == "moxt-alerts-v1":
                    frame += "\n" + render_alerts(alerts_doc)
            except (urllib.error.URLError, OSError, ValueError):
                pass
            # a resident job server carries /jobs too: render the table
            # (plain per-job telemetry servers 404 here — skip silently)
            if not fleet_schema:
                try:
                    with urllib.request.urlopen(base + "/jobs",
                                                timeout=5) as resp:
                        jobs_doc = json.loads(resp.read())
                    if jobs_doc.get("schema") == "moxt-jobs-v1":
                        frame += "\n" + render_jobs(jobs_doc)
                except (urllib.error.URLError, OSError, ValueError):
                    pass
            if args.no_clear:
                print(frame)
                print("-" * 40)
            else:
                # ANSI clear + home: curses-free redraw-in-place
                sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
                sys.stdout.flush()
            polls += 1
            if args.iterations and polls >= args.iterations:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        # Ctrl-C anywhere in the poll cycle (a blocked fetch included)
        # is "stop watching", never a traceback
        return 0
