"""SLO & alerting plane: declarative rules watched continuously in-process.

The serve mode (PR 7) made the framework a long-lived service and the
live telemetry plane (PR 6) exposes every signal as a point-in-time
scrape — but nothing *watched* those signals: a warm server that starts
recompiling (the DrJAX flat-program-count invariant, arXiv:2403.07128),
a straggling process, or a creeping dispatch-gap regression was only
caught if a human stared at ``/status``.  This module is the watcher:

* :class:`SloRule` — one declarative rule over the time-series ring
  (:mod:`map_oxidize_tpu.obs.timeseries`): a glob over series names, a
  ``kind`` (``value`` — latest reading, optionally as a fraction of a
  ``denominator`` series; ``delta`` — change over ``window_s``;
  ``rate`` — that change per second), a comparison op + threshold, a
  ``for_s`` debounce (the condition must HOLD that long before the
  alert fires), an ``after_s`` arm delay (cold-start warmup — compiles
  at job start are normal, compiles at minute five are not), and a
  ``scope`` (``job`` / ``serve`` / ``any``) so serve-plane rules don't
  evaluate against one-shot jobs and vice versa.
* :class:`SloEvaluator` — a daemon thread (same cadence as the series
  sampler) running every armed rule against the ring each tick, with a
  firing -> resolved state machine per (rule, matched series).  Ring
  wraparound is handled by construction: evaluation reads the ring's
  ordered export, and a ``delta``/``rate`` window that reaches past the
  oldest surviving sample clamps to it (the rate divides by the ACTUAL
  time spanned, so a wrapped ring never fabricates a burst).
* **incident bundles** — each firing transition writes a non-fatal
  flight-recorder-style bundle (``incident.json``: the rule, the
  observed value, the matched series' recent window, and a ``/status``
  snapshot) under ``--incident-dir`` (default: the run's
  ``--crash-dir``), bounded per run so an alert storm can't fill a disk.

Rules come from built-in :data:`DEFAULT_RULES` plus ``--slo-rules``
(a JSON file path or inline JSON: a list EXTENDS the defaults, an object
``{"defaults": false, "rules": [...]}`` replaces them).  The evaluator
runs whenever the time-series recorder runs (the live plane implies it);
every transition is announced as a ``[alert]`` heartbeat line, counted
into ``alerts/fired`` / ``alerts/resolved`` (ledger-gated like any other
counter), exported live at ``/alerts`` (``moxt-alerts-v1``), rendered by
``obs top``, and carried by the metrics document, ledger entries, and
crash bundles as a bounded event timeline.
"""

from __future__ import annotations

import fnmatch
import json
import os
import threading
import time
from dataclasses import asdict, dataclass, field

from map_oxidize_tpu.utils.logging import get_logger

_log = get_logger(__name__)

ALERTS_SCHEMA = "moxt-alerts-v1"
INCIDENT_SCHEMA = "moxt-incident-v1"

#: per-run ceiling on incident bundles: an alert storm (a rule matching
#: a hundred series, all firing) must not fill the disk — past it the
#: timeline/counters still record every transition, bundles stop
MAX_INCIDENTS = 16

#: bounded event history carried by exports (metrics doc, ledger entry,
#: crash bundle) and served at /alerts
TIMELINE_CAP = 128

_KINDS = ("value", "delta", "rate")
_OPS = (">", ">=", "<", "<=")
#: "info" is visibility without urgency (e.g. the fleet's cold-
#: calibration-store rule): it fires, correlates, and lands in exports
#: like any alert, but readers may render it below warnings
_SEVERITIES = ("info", "warning", "critical")
#: "fleet" arms only on a fleet collector's evaluator
#: (:mod:`map_oxidize_tpu.obs.fleet`), whose merged cross-target series
#: no single job or server ever records
_SCOPES = ("any", "job", "serve", "fleet")

_RULE_FIELDS = frozenset({
    "name", "metric", "kind", "op", "threshold", "window_s", "for_s",
    "after_s", "scope", "severity", "denominator", "description",
    "evidence",
})


@dataclass
class SloRule:
    """One declarative SLO rule (see the module docstring for the
    evaluation model).  ``metric`` is an fnmatch glob over the series
    names the ring records — counters and gauges by name, histograms as
    ``<name>/p50``/``p95``/``count``."""

    name: str
    metric: str
    kind: str = "value"
    op: str = ">"
    threshold: float = 0.0
    #: delta/rate lookback; clamped to the ring's surviving span
    window_s: float = 60.0
    #: debounce: the condition must hold this long before firing
    for_s: float = 0.0
    #: arm delay from job start (cold-start warmup exclusion)
    after_s: float = 0.0
    scope: str = "any"
    severity: str = "warning"
    #: value rules only: evaluate metric / denominator (skipped while
    #: the denominator series is absent or zero) — HBM watermark as a
    #: fraction of the admission budget, and friends
    denominator: str | None = None
    #: cross-link: metric name(s) whose figures corroborate a firing —
    #: rendered in incident bundles and /alerts so the responder reads
    #: the corroborating gauge next to the trigger (e.g. the data-plane
    #: skew rule cross-links the critpath straggler-save fraction:
    #: a skewed partition should show up as a blamed process)
    evidence: str = ""
    description: str = ""

    def validate(self) -> "SloRule":
        if not isinstance(self.name, str) or not isinstance(
                self.metric, str) or not self.name or not self.metric:
            raise ValueError("SLO rule needs a name and a metric glob")
        for fld in ("threshold", "window_s", "for_s", "after_s"):
            v = getattr(self, fld)
            # the config-time validation promise: a string threshold
            # must fail HERE, not TypeError out of every evaluator tick
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(f"rule {self.name!r}: {fld} must be a "
                                 f"number, got {v!r}")
        if self.kind not in _KINDS:
            raise ValueError(f"rule {self.name!r}: kind must be one of "
                             f"{_KINDS}, got {self.kind!r}")
        if self.op not in _OPS:
            raise ValueError(f"rule {self.name!r}: op must be one of "
                             f"{_OPS}, got {self.op!r}")
        if self.severity not in _SEVERITIES:
            raise ValueError(f"rule {self.name!r}: severity must be one "
                             f"of {_SEVERITIES}, got {self.severity!r}")
        if self.scope not in _SCOPES:
            raise ValueError(f"rule {self.name!r}: scope must be one of "
                             f"{_SCOPES}, got {self.scope!r}")
        if self.window_s <= 0:
            raise ValueError(f"rule {self.name!r}: window_s must be "
                             "positive")
        if self.for_s < 0 or self.after_s < 0:
            raise ValueError(f"rule {self.name!r}: for_s and after_s "
                             "must be >= 0")
        if self.denominator is not None and self.kind != "value":
            raise ValueError(f"rule {self.name!r}: denominator only "
                             "applies to value rules")
        if not isinstance(self.evidence, str):
            raise ValueError(f"rule {self.name!r}: evidence must be a "
                             f"metric-name string, got {self.evidence!r}")
        return self

    def holds(self, observed: float) -> bool:
        t = self.threshold
        if self.op == ">":
            return observed > t
        if self.op == ">=":
            return observed >= t
        if self.op == "<":
            return observed < t
        return observed <= t


#: built-in rules.  Calibrated to stay SILENT on a healthy run (the
#: check.sh smokes gate exactly that): warmup exclusions where a cold
#: start legitimately trips the signal, ceilings far above measured
#: healthy values, and serve-scoped rules that only see the resident
#: server's registry.  Override or extend via --slo-rules.
DEFAULT_RULES: tuple[dict, ...] = (
    # stall episodes are evidence of a wedged feed loop or a straggler-
    # gated collective — any increase alerts (mirrors the ledger gate)
    {"name": "stall-episodes", "metric": "heartbeat/stalls",
     "kind": "delta", "op": ">", "threshold": 0, "window_s": 120,
     "severity": "critical",
     "description": "heartbeat stall episodes increased"},
    # DrJAX's flat-program-count invariant, live: compiles during the
    # first five minutes are warmup; compiles after that are an
    # input-shape-set leak recompiling mid-stream
    {"name": "recompile-after-warmup", "metric": "compile/*/compiles",
     "kind": "delta", "op": ">", "threshold": 0, "window_s": 120,
     "after_s": 300, "scope": "job", "severity": "critical",
     "description": "XLA recompile on a warmed-up job "
                    "(flat-program-count invariant)"},
    # the serve-plane form: the scheduler counts compile deltas from
    # job 2 on into serve/warm_compiles — a warm server must never
    # move it (the zero-compile-delta story, continuously enforced)
    {"name": "warm-serve-recompile", "metric": "serve/warm_compiles",
     "kind": "delta", "op": ">", "threshold": 0, "window_s": 300,
     "scope": "serve", "severity": "critical",
     "description": "a warm resident server recompiled on a "
                    "repeat-shape job"},
    # dispatch-gap p95 ceiling: the measured healthy floor is
    # ~150-250 ms/launch; sustained seconds-long gaps mean the host is
    # starving the device (GIL storm, swap, a wedged producer)
    {"name": "dispatch-gap-p95", "metric": "device/dispatch_gap_ms/p95",
     "kind": "value", "op": ">", "threshold": 5000, "for_s": 10,
     "scope": "job", "severity": "warning",
     "description": "per-dispatch gap p95 above 5s — host starving "
                    "the device"},
    # serve queue-wait p95 ceiling: waiting a minute for a slot is an
    # under-provisioned server (or a deferred-job pileup)
    {"name": "serve-queue-wait-p95", "metric": "serve/queue_wait_ms/p95",
     "kind": "value", "op": ">", "threshold": 60_000, "for_s": 10,
     "scope": "serve", "severity": "warning",
     "description": "p95 queue wait above 60s — server "
                    "under-provisioned for its load"},
    # HBM watermark as a fraction of the admission budget (the
    # denominator gauge exists only where a budget was probed/configured,
    # so CPU smokes skip this rule by construction)
    {"name": "hbm-watermark", "metric": "hbm/live_bytes_*",
     "kind": "value", "op": ">", "threshold": 0.95,
     "denominator": "hbm/budget_bytes", "for_s": 5,
     "severity": "critical",
     "description": "live HBM above 95% of the admission budget"},
    # MFU floor: shipped armed-but-at-zero because a universal floor
    # does not exist (CPU smoke MFU is legitimately ~0%); override the
    # threshold via --slo-rules with the fleet's measured baseline
    {"name": "mfu-floor", "metric": "xprof/*/mfu_pct",
     "kind": "value", "op": "<", "threshold": 0.0, "scope": "job",
     "description": "program MFU below the configured floor (default "
                    "floor 0 never fires — set your fleet's baseline "
                    "via --slo-rules)"},
    # comms burst: a sustained >20 GB/s accounted collective payload
    # rate for the same job is redistribution gone circular
    {"name": "comms-burst", "metric": "comms/*/bytes", "kind": "rate",
     "op": ">", "threshold": 20e9, "window_s": 30, "for_s": 10,
     "severity": "warning",
     "description": "sustained collective payload rate above 20 GB/s"},
    # causal straggler alarm: fixing ONE process (the what-if "at
    # peer-median speed" replay) would cut the wall by more than 30% —
    # the process's blame share of the wall, measured causally.  Raw
    # path ownership is deliberately NOT the trigger: near-tied
    # arrivals put ~100% ownership on a coin-flip binder even on
    # healthy runs, while the replay saving is ~0 on a tie and large
    # only when a straggler is genuinely ON the critical path.  The
    # gauge is published ONLY for multi-process runs (post-merge, which
    # takes one final series sample + evaluator tick), so a single-chip
    # job can never trip this; a firing lands in the ledger's
    # alerts/fired gate counter + an incident bundle.
    {"name": "critpath-process-blame",
     "metric": "critpath/straggler_save_frac", "kind": "value",
     "op": ">", "threshold": 0.30, "scope": "job",
     "severity": "warning",
     "description": "one process's blame share of the wall exceeds 30% "
                    "(straggler on the critical path — see obs "
                    "critpath for blame/slack/what-if)"},
    # data-plane skew alarm: max/mean partition rows above 6x means the
    # key distribution concentrates the shuffle onto a few partitions —
    # the precondition for the straggler pattern the critpath plane
    # blames, so the incident cross-links its save fraction as
    # corroborating evidence (skewed partition <-> blamed process).
    # 6.0 stays silent on healthy hash-partitioned corpora (measured
    # smoke imbalance ~1-3x even on tiny vocabularies); an adversarial
    # Zipf corpus trips it.  The gauge is published at audit finish
    # (post-merge on distributed runs, like the critpath gauges).
    {"name": "data-partition-skew", "metric": "data/imbalance_factor",
     "kind": "value", "op": ">", "threshold": 6.0, "scope": "job",
     "severity": "warning",
     "evidence": "critpath/straggler_save_frac",
     "description": "partition rows max/mean above 6x — key skew "
                    "concentrating the shuffle on few partitions (see "
                    "obs data for the heatmap; corroborate with the "
                    "critpath straggler save fraction)"},
    # plan observatory drift: a resident server re-plans every
    # submission from its own calibration history, and the scheduler
    # publishes the MEDIAN prediction error of its recently finished
    # jobs onto the server registry (median-of-recent so one noisy
    # micro-job cannot trip it; a cold server publishes nothing and
    # stays silent by construction, like a cold CLI run's
    # platform_default provenance).  Sustained error above 150% means
    # the stored curves no longer describe the machine (stale store
    # after a topology/attach change, doctored evidence) — recalibrate
    # or clear the store.  The one-shot form of the same signal is the
    # plan/model_error_pct ledger gate (obs diff --gate).
    {"name": "plan-model-drift", "metric": "plan/model_error_pct",
     "kind": "value", "op": ">", "threshold": 150, "for_s": 5,
     "scope": "serve", "severity": "warning",
     "evidence": "plan/predicted_wall_ms",
     "description": "resident server's plan predictions went stale — "
                    "median predicted-vs-actual wall error above 150% "
                    "(see obs plan; recalibrate or clear the store)"},
)


def load_rules(spec: str | None,
               defaults: tuple[dict, ...] = DEFAULT_RULES
               ) -> list[SloRule]:
    """Resolve ``--slo-rules`` into the rule set.  ``spec`` may be None/
    empty (defaults only), a path to a JSON file, or inline JSON.  A
    JSON list EXTENDS the defaults; ``{"defaults": false,
    "rules": [...]}`` replaces them.  A later rule with an existing name
    overrides the earlier one (so defaults are tunable by name).
    ``defaults`` is the built-in set ``{"defaults": true}`` refers to —
    :data:`DEFAULT_RULES` for jobs/servers, the fleet collector passes
    its own :data:`~map_oxidize_tpu.obs.fleet.FLEET_RULES`."""
    parsed = None
    if spec:
        text = spec.strip()
        if text.startswith(("[", "{")):
            parsed = json.loads(text)
        else:
            with open(spec) as f:
                parsed = json.load(f)
    use_defaults = True
    extra: list = []
    if isinstance(parsed, list):
        extra = parsed
    elif isinstance(parsed, dict):
        use_defaults = bool(parsed.get("defaults", True))
        extra = parsed.get("rules", [])
        if not isinstance(extra, list):
            raise ValueError('"rules" must be a list of rule objects')
    elif parsed is not None:
        raise ValueError("--slo-rules JSON must be a list of rules or "
                         'an object with a "rules" list')
    raw = (list(defaults) if use_defaults else []) + extra
    by_name: dict[str, SloRule] = {}
    for d in raw:
        if not isinstance(d, dict):
            raise ValueError(f"each rule must be a JSON object, got {d!r}")
        unknown = set(d) - _RULE_FIELDS
        if unknown:
            raise ValueError(
                f"unknown SLO rule field(s) {sorted(unknown)} in "
                f"{d.get('name', d)!r}")
        try:
            rule = SloRule(**d)
        except TypeError as e:  # a missing required field must surface
            # as the config-time ValueError every caller catches
            raise ValueError(f"bad SLO rule {d!r}: {e}") from e
        rule.validate()
        by_name[rule.name] = rule      # later wins: defaults are tunable
    return list(by_name.values())


@dataclass
class _AlertState:
    """Per-(rule, series) state machine cell."""

    state: str = "ok"              # ok | pending | firing
    since_unix_s: float = 0.0      # pending/firing start
    value: float | None = None     # last observed


class SloEvaluator:
    """Evaluates the rule set against one job's time-series ring on a
    daemon thread (``interval_s`` — the series sampler's cadence by
    default).  ``clock`` is injectable and :meth:`evaluate_once` is the
    whole tick, so tests drive it deterministically without the thread.
    """

    def __init__(self, obs, rules: list[SloRule], config=None,
                 interval_s: float = 1.0, incident_dir: str | None = None,
                 clock=time.time):
        if interval_s <= 0:
            raise ValueError("interval_s must be positive")
        self.obs = obs
        self.rules = list(rules)
        self.config = config
        self.interval_s = interval_s
        self.incident_dir = incident_dir
        self._clock = clock
        #: (rule.name, series name) -> state cell
        self._states: dict[tuple[str, str], _AlertState] = {}
        #: bounded fired/resolved event history, oldest first
        self.timeline: list[dict] = []
        self.fired_total = 0
        self.resolved_total = 0
        self.incidents_written = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="obs-slo")

    # --- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        """Stop the thread and run one final evaluation (against the
        series recorder's final sample), so a condition that cleared at
        the very end still resolves in the exported timeline."""
        if not self._stop.is_set():
            self._stop.set()
            self.evaluate_once()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate_once()
            except Exception as e:  # the watcher must never kill the job
                _log.warning("SLO evaluation error (skipping tick): %s", e)

    # --- evaluation -------------------------------------------------------

    @property
    def _scope(self) -> str:
        """This evaluator's plane: the resident server's own bundle
        (workload 'serve') evaluates serve-scoped rules, a fleet
        collector's (workload 'fleet') the fleet-scoped ones; everything
        else is a job."""
        wl = getattr(self.obs, "workload", None)
        return wl if wl in ("serve", "fleet") else "job"

    def evaluate_once(self, now: float | None = None) -> list[dict]:
        """One tick: run every armed rule against the ring, advance the
        state machines, announce/record transitions.  Returns the
        transition events of this tick (tests assert on them)."""
        series_rec = getattr(self.obs, "series", None)
        if series_rec is None:
            return []
        now = self._clock() if now is None else now
        job_age = now - self.obs.tracer.wall_start
        scope = self._scope
        armed = [r for r in self.rules
                 if (r.scope == "any" or r.scope == scope)
                 and job_age >= r.after_s]
        if not armed:
            return []
        # glob against the cheap name list first, then pull a TARGETED
        # export — the per-tick read must not materialize the whole ring
        all_names = series_rec.latest_names()
        if not all_names:
            return []
        matched = {r.name: fnmatch.filter(all_names, r.metric)
                   for r in armed}
        needed: set[str] = set()
        for r in armed:
            needed.update(matched[r.name])
            if r.denominator is not None:
                needed.add(r.denominator)
        if not needed:
            return []
        export = series_rec.export(only=needed)
        t = export["t_unix_s"]
        if not t:
            return []
        series = export["series"]
        events: list[dict] = []
        for rule in armed:
            for name in matched[rule.name]:
                if name not in series:
                    continue
                observed = self._observe(rule, name, t, series, now)
                if observed is None:
                    continue
                ev = self._advance(rule, name, observed, now)
                if ev is not None:
                    events.append(ev)
        with self._lock:
            firing = sum(1 for s in self._states.values()
                         if s.state == "firing")
        self.obs.registry.set("alerts/firing", firing)
        return events

    def _observe(self, rule: SloRule, name: str, t: list,
                 series: dict, now: float) -> float | None:
        """The rule's observed value for one matched series, or None
        when the series has no usable reading yet (rule skipped, state
        untouched)."""
        vals = series[name]
        latest = _latest(vals)
        if latest is None:
            return None
        v_now, i_now = latest
        if rule.kind == "value":
            if rule.denominator is None:
                return v_now
            dvals = series.get(rule.denominator)
            if dvals is None:
                return None
            dlatest = _latest(dvals)
            if dlatest is None or not dlatest[0]:
                return None
            return v_now / dlatest[0]
        # delta/rate: reference = the newest sample at or before the
        # window start; a window reaching past the ring's oldest
        # surviving sample clamps to that oldest sample (wrap-safe:
        # rate divides by the ACTUAL span, never the nominal window)
        target = now - rule.window_s
        ref = _at_or_before(t, vals, target)
        if ref is None:
            return None
        v_ref, i_ref = ref
        ref_t = t[i_ref]
        if ref_t > target and i_ref > 0:
            # the series APPEARED mid-ring: the tick before its first
            # sample proves it did not exist, so the baseline is 0 at
            # that tick — counters are created lazily on their first
            # increment (heartbeat/stalls, serve/warm_compiles), and
            # that FIRST increment must fire, not only the second.  A
            # wrapped ring whose oldest surviving sample already holds
            # the series (i_ref == 0) keeps the clamp baseline instead
            v_ref, ref_t = 0.0, t[i_ref - 1]
        elif i_ref >= i_now:
            return None                 # no span to difference over
        delta = v_now - v_ref
        if rule.kind == "delta":
            return delta
        dt = t[i_now] - ref_t
        if dt <= 0:
            return None
        return delta / dt

    def _advance(self, rule: SloRule, name: str, observed: float,
                 now: float) -> dict | None:
        """One state-machine step; returns a fired/resolved event on a
        transition."""
        key = (rule.name, name)
        with self._lock:
            cell = self._states.get(key)
            if cell is None:
                cell = self._states[key] = _AlertState()
            cell.value = observed
            holds = rule.holds(observed)
            if cell.state == "firing":
                if holds:
                    return None
                cell.state = "ok"
                return self._record_locked("resolved", rule, name,
                                           observed, now)
            if not holds:
                cell.state = "ok"
                return None
            if cell.state == "ok":
                cell.state = "pending"
                cell.since_unix_s = now
            if now - cell.since_unix_s < rule.for_s:
                return None             # still debouncing
            cell.state = "firing"
            cell.since_unix_s = now
            event = self._record_locked("fired", rule, name, observed, now)
        # incident bundle OUTSIDE the state lock (filesystem I/O)
        self._write_incident(rule, name, observed, now)
        return event

    def _record_locked(self, what: str, rule: SloRule, name: str,
                       observed: float, now: float) -> dict:
        event = {
            "event": what,
            "rule": rule.name,
            "series": name,
            "value": round(float(observed), 6),
            "threshold": rule.threshold,
            "op": rule.op,
            "severity": rule.severity,
            "t_unix_s": round(now, 3),
        }
        self.timeline.append(event)
        del self.timeline[:-TIMELINE_CAP]
        if what == "fired":
            self.fired_total += 1
        else:
            self.resolved_total += 1
        # counters ride the registry: summary -> ledger entry -> gate
        self.obs.registry.count(f"alerts/{what}", 1)
        self._announce(
            f"[alert] {'FIRING' if what == 'fired' else 'resolved'} "
            f"{rule.name}: {name}={observed:g} "
            f"({rule.op} {rule.threshold:g}, {rule.severity})")
        return event

    def _announce(self, line: str) -> None:
        """Transition lines ride the heartbeat when one is printing;
        silent heartbeats (the live plane's tracking-only mode) fall
        back to the logger so the operator still sees the alert."""
        hb = getattr(self.obs, "heartbeat", None)
        if hb is not None and not getattr(hb, "silent", False):
            hb.announce(line)
        else:
            _log.warning("%s", line)

    # --- incident bundles -------------------------------------------------

    def _write_incident(self, rule: SloRule, name: str, observed: float,
                        now: float) -> str | None:
        """Flight-recorder-style evidence for one firing: the rule, the
        matched series' surviving window, and a /status snapshot.  Best
        effort and bounded — an incident writer error must never reach
        the job, and an alert storm stops at :data:`MAX_INCIDENTS`."""
        if not self.incident_dir:
            return None
        with self._lock:
            if self.incidents_written >= MAX_INCIDENTS:
                if self.incidents_written == MAX_INCIDENTS:
                    self.incidents_written += 1
                    _log.warning("[alert] incident-bundle cap (%d) "
                                 "reached; further firings record to the "
                                 "timeline only", MAX_INCIDENTS)
                return None
            self.incidents_written += 1
            seq = self.incidents_written
        try:
            from map_oxidize_tpu.obs import write_json_atomic

            stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(now))
            safe_rule = rule.name.replace("/", "_")
            bundle = os.path.join(
                self.incident_dir,
                f"incident_{stamp}_{safe_rule}_{seq:02d}_{os.getpid()}")
            os.makedirs(bundle, exist_ok=True)
            doc = {
                "schema": INCIDENT_SCHEMA,
                "rule": asdict(rule),
                "series": name,
                "value": float(observed),
                "t_unix_s": round(now, 3),
            }
            if rule.evidence:
                # the cross-linked corroborating metric, read at firing
                # time (gauge first, series ring as fallback) — the
                # responder sees e.g. the critpath straggler-save
                # fraction right next to the skew trigger
                ev_val = None
                reg = getattr(self.obs, "registry", None)
                if reg is not None:
                    ev_val = reg.gauges.get(rule.evidence)
                doc["evidence"] = {"metric": rule.evidence,
                                   "value": ev_val}
            series_rec = getattr(self.obs, "series", None)
            if series_rec is not None:
                export = series_rec.export()
                doc["window"] = {
                    "interval_s": export["interval_s"],
                    "t_unix_s": export["t_unix_s"][-120:],
                    "values": (export["series"].get(name) or [])[-120:],
                }
            if self.config is not None:
                from map_oxidize_tpu.obs.serve import build_status

                doc["status"] = build_status(self.obs, self.config)
            write_json_atomic(os.path.join(bundle, "incident.json"), doc)
            _log.warning("[alert] incident bundle: %s", bundle)
            return bundle
        except Exception as e:  # pragma: no cover - defensive
            _log.warning("incident bundle write failed: %s", e)
            return None

    # --- export -----------------------------------------------------------

    def export(self) -> dict:
        """The ``/alerts`` document (``moxt-alerts-v1``): every rule with
        its per-series states, the currently-firing set, recently
        resolved events, and the bounded timeline.  Snapshot-read under
        the state lock — safe against concurrent ticks and scrapes."""
        now = self._clock()
        with self._lock:
            firing = []
            per_rule: dict[str, list] = {}
            for (rname, series), cell in sorted(self._states.items()):
                row = {"series": series, "state": cell.state,
                       "value": cell.value}
                if cell.state == "firing":
                    row["since_unix_s"] = round(cell.since_unix_s, 3)
                    rule = next((r for r in self.rules
                                 if r.name == rname), None)
                    firing.append({
                        "rule": rname, "series": series,
                        "value": cell.value,
                        "threshold": rule.threshold if rule else None,
                        "op": rule.op if rule else None,
                        "severity": rule.severity if rule else None,
                        "evidence": (rule.evidence or None) if rule
                                    else None,
                        "since_unix_s": round(cell.since_unix_s, 3),
                    })
                per_rule.setdefault(rname, []).append(row)
            resolved = [e for e in self.timeline
                        if e["event"] == "resolved"][-32:]
            timeline = list(self.timeline)
            counts = {"fired": self.fired_total,
                      "resolved": self.resolved_total,
                      "incidents": min(self.incidents_written,
                                       MAX_INCIDENTS)}
        return {
            "schema": ALERTS_SCHEMA,
            "t_unix_s": round(now, 3),
            "interval_s": self.interval_s,
            "counts": counts,
            "firing": firing,
            "resolved": resolved,
            "rules": [dict(asdict(r), states=per_rule.get(r.name, []))
                      for r in self.rules],
            "timeline": timeline,
        }

    def timeline_doc(self) -> dict:
        """The compact form ledger entries carry."""
        with self._lock:
            return {"fired": self.fired_total,
                    "resolved": self.resolved_total,
                    "timeline": list(self.timeline)[-64:]}


def _latest(vals: list) -> tuple[float, int] | None:
    """Newest non-None reading and its index."""
    for i in range(len(vals) - 1, -1, -1):
        if vals[i] is not None:
            return vals[i], i
    return None


def _at_or_before(t: list, vals: list, target: float
                  ) -> tuple[float, int] | None:
    """Newest non-None reading at or before ``target``; falls back to
    the OLDEST surviving reading when the whole ring is younger (the
    wrap-clamp described in the module docstring)."""
    best = None
    for i, ts in enumerate(t):
        if vals[i] is None:
            continue
        if ts <= target:
            best = (vals[i], i)
        else:
            break
    if best is not None:
        return best
    for i, v in enumerate(vals):        # ring younger than the window:
        if v is not None:               # clamp to the oldest sample
            return v, i
    return None
