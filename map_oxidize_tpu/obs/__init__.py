"""Unified observability: spans, metrics, and a progress heartbeat.

The reference's entire observability surface is a ``println!`` of the
top-10 (``/root/reference/src/main.rs:188-191``); the seed's was a flat
61-line phase-timer dict.  This package is the instrumentation discipline
the ROADMAP's scale targets require (the same discipline Exoshuffle,
arXiv:2203.05072, credits for making shuffle regressions debuggable):

* :class:`~map_oxidize_tpu.obs.trace.Tracer` — nested, thread-safe spans
  with attributes (rows, bytes, device, spill generation), exportable as
  Chrome trace-event JSON (``chrome://tracing`` / Perfetto) or JSONL.
* :class:`~map_oxidize_tpu.obs.metrics.MetricsRegistry` — counters,
  gauges, and lightweight histograms (p50/p95/max) behind the seed
  ``Metrics`` surface (``phase``/``count``/``set``/``summary``), so every
  existing consumer keeps working.
* :class:`~map_oxidize_tpu.obs.heartbeat.Heartbeat` — opt-in periodic
  progress lines (rows/sec, percent done, ETA, phase) for long streamed
  jobs.

:class:`Obs` bundles the three per job and owns the config wiring
(``--metrics-out`` / ``--trace-out`` / ``--progress``).  One ``Obs`` is
created per job run and *injected* into engines and checkpoint stores —
replacing the ad-hoc per-driver ``Metrics()`` instantiations — so every
layer (driver, engine, collect, shuffle, spill, checkpoint) records into
one coherent event model.

The job-level legs on top of the per-process bundle:

* :mod:`~map_oxidize_tpu.obs.merge` — multi-process trace/metrics
  shards, the merged cross-process Chrome trace (pid = process), and
  the straggler/skew report;
* :mod:`~map_oxidize_tpu.obs.ledger` — the append-only run ledger
  (``--ledger-dir``) with regression diffing (``obs diff``,
  ``bench.py --gate``) behind a version + config-hash identity check;
* :mod:`~map_oxidize_tpu.obs.flight` — the failure flight recorder
  (``--crash-dir``): aborts dump config/metrics/open-span-closed trace
  before propagating, and ``Obs.recording`` is the crash-safe envelope
  every driver wraps its body in.

The live telemetry plane on top of all of it (ISSUE-6):

* :mod:`~map_oxidize_tpu.obs.timeseries` — the ring-buffer time-series
  recorder (``--obs-sample-interval``): bounded timestamped series of
  every counter/gauge/quantile, exported as the metrics document's
  ``series`` section;
* :mod:`~map_oxidize_tpu.obs.serve` — the per-process HTTP server
  (``--obs-port``): ``/metrics`` (Prometheus), ``/status`` (live phase/
  progress/compile/MFU/comms; skew-aware aggregate on process 0),
  ``/series`` — shut down by ``finish`` AND the flight recorder;
* the **comms observatory**: every collective site records payload
  bytes + sampled latency (``MetricsRegistry.comm``) into per-
  (collective, program, shape) tables the ledger gate checks;
* :mod:`~map_oxidize_tpu.obs.context` — per-job routing so concurrent
  jobs in one process keep disjoint obs state.

The watcher on top of the live plane (ISSUE-9):

* :mod:`~map_oxidize_tpu.obs.slo` — declarative SLO rules evaluated
  continuously against the series ring (``--slo-rules``): firing/
  resolved state machines, ``[alert]`` heartbeat lines, the ``/alerts``
  endpoint, incident bundles, and ``alerts/*`` gate counters;
* :mod:`~map_oxidize_tpu.obs.trend` — cross-run regression forensics
  over the ledger history and BENCH rounds (``obs trend``): per-series
  trajectories, step-change detection, and the ranked movers report
  that attributes a gate failure to the counters that moved.

The layer above any one process (ISSUE-13):

* :mod:`~map_oxidize_tpu.obs.fleet` — the fleet observatory
  (``obs fleet``): a collector polling N obs endpoints (explicit
  targets, port files, serve spools, and the well-known port-record
  spool every serving process publishes into), merging them into one
  fleet model with staleness tracking, per-target labeled ``/metrics``
  + fleet aggregates (the multi-server load index), cross-target
  incident correlation at ``/alerts``, fleet-scope SLO rules through
  the same ``SloEvaluator``, and a bounded on-disk series archive that
  ``obs trend/top/where --archive`` read after every producer process
  has exited.

See ``docs/OBSERVABILITY.md`` for the event model and flag reference.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
from dataclasses import dataclass, field

from map_oxidize_tpu.obs.heartbeat import Heartbeat
from map_oxidize_tpu.obs.metrics import (
    Histogram,
    MetricsRegistry,
    sample_device_memory,
    sample_host_memory,
)
from map_oxidize_tpu.obs.trace import NULL_SPAN, Span, Tracer

__all__ = [
    "Heartbeat",
    "Histogram",
    "JobCancelled",
    "MetricsRegistry",
    "NULL_SPAN",
    "Obs",
    "Span",
    "Tracer",
    "sample_device_memory",
    "sample_host_memory",
]


class JobCancelled(RuntimeError):
    """Cooperative cancellation: a client cancel or an expired deadline.

    Raised by :meth:`Obs.poll_cancel` from inside the job body — i.e.
    inside ``Obs.recording`` — so the abort takes the flight-recorder
    path like any other: open spans close, partial metrics/trace flush,
    and the crash bundle lands before the exception reaches the caller
    (the resident server's scheduler, which maps it to the job's
    ``cancelled`` state instead of ``failed``)."""


@dataclass
class Obs:
    """Per-job observability bundle: one registry, one tracer, and an
    optional heartbeat, threaded through driver -> engine -> spill layers.

    Always constructed (metrics were always-on in the seed too); the
    tracer is enabled only when the job asked for a trace, and its
    disabled spans are a shared no-op object, so the hot-path cost of an
    un-traced run is one attribute check per span site.
    """

    registry: MetricsRegistry
    tracer: Tracer
    heartbeat: Heartbeat | None = None
    #: this process's slot and the job's process count (multi-process
    #: runs; 0/1 for the single-controller drivers)
    process: int = 0
    n_processes: int = 1
    #: live device sampler (HBM watermarks + stall detector), when the
    #: config asked for either; stopped by finish/flight
    sampler: "object | None" = None
    #: compile-ledger snapshot taken at job start — finish deltas the
    #: process-global ledger against it for per-job xprof numbers
    xprof_base: "dict | None" = None
    #: live telemetry plane (``--obs-port`` / ``--obs-sample-interval``):
    #: the HTTP status server and the ring-buffer time-series recorder —
    #: both stopped by finish AND the flight recorder
    server: "object | None" = None
    series: "object | None" = None
    #: SLO plane (obs/slo.py): the alert evaluator watching the series
    #: ring — runs whenever the recorder runs, stopped with the live
    #: plane (its final tick sees the recorder's final sample)
    alerts: "object | None" = None
    #: the phase currently open (obs.phase) and the workload under
    #: recording — what /status reports while the job runs
    current_phase: "str | None" = None
    workload: "str | None" = None
    #: cooperative cancellation (the resident job service's cancel and
    #: deadline paths): set via :meth:`request_cancel` from ANY thread,
    #: observed at phase boundaries and per-block feeds by
    #: :meth:`poll_cancel`, which raises :class:`JobCancelled` inside the
    #: job body so the flight recorder runs
    cancel_event: threading.Event = field(default_factory=threading.Event)
    cancel_reason: "str | None" = None
    #: calibration store hookup (obs/calib.py): ``calib`` accumulates
    #: THIS run's measurements (seeded empty; merged into the store
    #: file at finish), ``calib_prior`` is the loaded cross-run history
    #: consumers read (collective chooser, auto-B).  Both None without
    #: ``--calib-dir`` — or when the store on disk refused to load
    calib: "object | None" = None
    calib_prior: "object | None" = None
    #: the job plan (runtime/planner.py + obs/plan.py): solved in
    #: ``recording`` before the body runs (knob choices + provenance +
    #: predicted wall), scored against the measured attribution in
    #: ``finish`` (``plan/model_error_pct``).  None with ``--plan off``
    #: or outside a workload body (the resident server's own bundle)
    plan: "dict | None" = None
    #: data-plane observatory (obs/dataplane.py): the per-partition
    #: row-conservation/skew audit the engines feed, created lazily by
    #: the driver through :meth:`ensure_dataplane` (the partition count
    #: is an engine fact the config alone doesn't know); None until
    #: then, and always None when ``config.data_audit`` is off
    dataplane: "object | None" = None
    dataplane_enabled: bool = True
    #: first-phase latch: Obs.phase stamps ``attrib/setup_ms`` (wall
    #: from Obs creation to the first phase span) exactly once
    _setup_stamped: bool = False

    @classmethod
    def from_config(cls, config, process: int = 0,
                    n_processes: int = 1) -> "Obs":
        """Build the bundle a job's config asks for.  ``trace_out='-'``
        collects the trace for ``result.trace`` without writing a file.

        Multi-process jobs pass their slot: heartbeat lines are prefixed
        with the process id and emitted from process 0 only (every
        process advances in lockstep, so P copies of the same line are
        noise; ``MOXT_PROGRESS_ALL_PROCS=1`` un-silences the rest for
        per-process debugging)."""
        tracer = Tracer(enabled=bool(config.trace_out))
        obs_port = getattr(config, "obs_port", -1)
        sample_s = getattr(config, "obs_sample_s", 0.0)
        live = obs_port >= 0 or sample_s > 0
        if live and sample_s <= 0:
            sample_s = 1.0  # serving implies sampling: /series must work
        hb = None
        if getattr(config, "progress", False) or live:
            total = None
            try:
                total = os.path.getsize(config.input_path)
            except OSError:
                pass
            emit = None
            # silent heartbeat: the live plane needs the row/phase/ETA
            # accumulation for /status even when --progress printing is
            # off — emit becomes a no-op, the tracking is identical
            silent = not getattr(config, "progress", False)
            if n_processes > 1 and not silent:
                if process != 0 and not os.environ.get(
                        "MOXT_PROGRESS_ALL_PROCS"):
                    silent = True  # lockstep: P copies of a line are noise
                else:
                    from map_oxidize_tpu.utils.logging import get_logger

                    plog = get_logger(__name__)
                    emit = (lambda line, _p=process:
                            plog.info("[proc %d] %s", _p, line))
            if silent and not live:
                pass  # progress off, no live plane: no heartbeat at all
            else:
                if silent:
                    emit = lambda line: None
                hb = Heartbeat(total_bytes=total,
                               interval_s=config.progress_interval_s,
                               emit=emit)
                hb.silent = silent
        obs = cls(registry=MetricsRegistry(), tracer=tracer, heartbeat=hb,
                  process=process, n_processes=n_processes,
                  dataplane_enabled=bool(
                      getattr(config, "data_audit", True)))
        # the XLA program observatory is always-on: compile counts, costs
        # and dispatch gaps accrue in the process-global ledger; the job
        # deltas against this baseline at finish (obs/compile.py)
        from map_oxidize_tpu.obs import compile as _compile

        obs.xprof_base = _compile.LEDGER.activate(obs)
        hbm_s = getattr(config, "hbm_sample_s", 0.0)
        stall = getattr(config, "stall_warn_factor", 0.0)
        if live and hbm_s <= 0:
            # the live plane implies the HBM sampler: /status and the
            # time series carry hbm/live_bytes at the sample cadence
            hbm_s = sample_s
        if hbm_s > 0 or stall > 0:
            from map_oxidize_tpu.obs.xprof import DeviceSampler

            obs.sampler = DeviceSampler(obs, interval_s=hbm_s,
                                        stall_factor=stall)
            obs.sampler.start()
        if sample_s > 0:
            from map_oxidize_tpu.obs.timeseries import (
                DEFAULT_CAPACITY,
                TimeSeriesRecorder,
            )

            # MOXT_SERIES_CAPACITY: test hook for ring-wraparound
            # coverage — a tiny ring wraps in seconds instead of a
            # 17-minute soak (tests/test_slo.py)
            try:
                cap = int(os.environ.get("MOXT_SERIES_CAPACITY", "")
                          or DEFAULT_CAPACITY)
            except ValueError:
                cap = DEFAULT_CAPACITY
            obs.series = TimeSeriesRecorder(obs.registry,
                                            interval_s=sample_s,
                                            capacity=cap,
                                            heartbeat=obs.heartbeat,
                                            obs=obs)
            obs.series.start()
            # the SLO plane rides the series ring: default rules plus
            # --slo-rules, evaluated at the sampling cadence; incident
            # bundles land under --incident-dir (default: --crash-dir)
            from map_oxidize_tpu.obs.slo import SloEvaluator, load_rules

            obs.alerts = SloEvaluator(
                obs, load_rules(getattr(config, "slo_rules", None)),
                config=config, interval_s=sample_s,
                incident_dir=(getattr(config, "incident_dir", None)
                              or getattr(config, "crash_dir", None)))
            obs.alerts.start()
        if obs_port >= 0:
            from map_oxidize_tpu.obs.serve import (
                ObsServer,
                serve_port_for_process,
            )

            obs.server = ObsServer(
                obs, config, serve_port_for_process(obs_port, process))
            obs.server.start()
        calib_dir = getattr(config, "calib_dir", None)
        if calib_dir:
            from map_oxidize_tpu.obs import calib as _calib

            path = os.path.join(calib_dir, _calib.CALIB_FILE)
            try:
                # prior history loads for consumers (collective chooser,
                # auto-B warm figures); the RUN accumulator is a fresh
                # empty store so the finish-time merge never double-
                # counts the history already on disk
                obs.calib_prior = _calib.CalibStore.load(path)
                obs.calib = _calib.CalibStore(path=path)
                # cold-store visibility: 0 runs = a restarted server
                # with an empty store (the fleet-calib-cold SLO rule
                # and the fleet rollup read this)
                obs.registry.set(
                    "calib/store_runs",
                    obs.calib_prior.doc.get("runs", 0))
            except _calib.CalibMismatch as e:
                # refusal is the contract: stale/torn evidence must not
                # merge — the run proceeds uncalibrated, loudly
                obs.registry.set("calib/load_refused", 1)
                from map_oxidize_tpu.utils.logging import get_logger

                get_logger(__name__).warning(
                    "calibration store refused to load: %s", e)
        return obs

    def ensure_dataplane(self, n_partitions: int, conserves: bool = True):
        """Create (once) and return the data-plane audit
        (:class:`~map_oxidize_tpu.obs.dataplane.DataPlaneAudit`), or
        None when ``config.data_audit`` disabled it.  Drivers call this
        as soon as they know the effective partition count; engines and
        transports then feed ``obs.dataplane`` directly."""
        if not self.dataplane_enabled:
            return None
        if self.dataplane is None:
            from map_oxidize_tpu.obs.dataplane import DataPlaneAudit

            self.dataplane = DataPlaneAudit(n_partitions,
                                            conserves=conserves)
        return self.dataplane

    def finish_dataplane(self) -> "dict | None":
        """Publish the ``data/*`` gauges and return the structured audit
        section (``doc["data"]``) — called by ``finish`` and its
        distributed twin BEFORE the registry summary is taken, so the
        ledger entry carries the gauges.  None when no audit ran."""
        if self.dataplane is None:
            return None
        self.dataplane.publish(self.registry)
        return self.dataplane.doc()

    def knob(self, name: str, fallback):
        """The planner-effective value of a tunable knob: the plan's
        chosen value when a plan exists, else the caller's config value.
        Drivers consult this instead of the raw config so a curve-driven
        choice applies WITHOUT mutating the config (the ledger's
        config-hash identity must not depend on what the planner
        chose)."""
        p = self.plan
        if p:
            row = (p.get("knobs") or {}).get(name)
            if row is not None and row.get("value") is not None:
                return row["value"]
        return fallback

    def request_cancel(self, reason: str = "cancelled") -> None:
        """Ask the job to stop at its next cancellation point (phase
        boundary or per-block feed).  Thread-safe; the first reason
        wins.  A job that never reaches another cancellation point (a
        wedged collective) is the stall detector's department — this is
        the cooperative path."""
        if not self.cancel_event.is_set():
            self.cancel_reason = reason
            self.cancel_event.set()

    def poll_cancel(self) -> None:
        """Raise :class:`JobCancelled` if a cancel was requested.  Called
        at every phase start and per-block feed; one ``Event.is_set``
        check on the not-cancelled path."""
        if self.cancel_event.is_set():
            raise JobCancelled(self.cancel_reason or "cancelled")

    @contextlib.contextmanager
    def phase(self, name: str, **attrs):
        """One job phase: wall-clocked in the registry, a top-level span in
        the trace, the heartbeat's current phase label, and a host-RSS
        watermark sample on exit (phase boundaries are where residency
        peaks: finalize fetches, sort buffers, write staging).  Also a
        cancellation point (:meth:`poll_cancel`)."""
        self.poll_cancel()
        if not self._setup_stamped:
            # the attribution ledger's ``setup`` bucket source: Obs
            # creation to the first phase span (config/engine/backend
            # bring-up).  Deliberately NOT named attrib/setup_ms — the
            # published bucket gauge owns that name, and a shared name
            # would feed the published value back into the next compute
            self._setup_stamped = True
            import time as _time

            self.registry.set(
                "attrib/pre_phase_ms",
                round(max(_time.time() - self.tracer.wall_start, 0.0)
                      * 1e3, 3))
        if self.heartbeat is not None:
            self.heartbeat.set_phase(name)
        prev, self.current_phase = self.current_phase, name
        with self.tracer.span(f"phase/{name}", **attrs):
            with self.registry.phase(name):
                try:
                    yield
                finally:
                    self.current_phase = prev
                    sample_host_memory(self.registry)

    def feed_span(self, **attrs) -> "Span":
        """Span for one mapped block's engine feed (the per-block latency
        site every driver instruments) — and the job's fine-grained
        cancellation point: a cancel/deadline lands between blocks, never
        mid-feed."""
        self.poll_cancel()
        return self.tracer.span("engine/feed_block", **attrs)

    def stamp(self, config, workload: str | None = None) -> dict:
        """Provenance stamp carried by every exported document (metrics,
        trace, shard, ledger entry, crash bundle): the package version
        plus the identity config hash — what ``obs diff``/``obs merge``
        check before comparing or combining — and the process slot."""
        from map_oxidize_tpu import __version__
        from map_oxidize_tpu.obs.ledger import config_hash

        return {
            "version": __version__,
            "config_hash": config_hash(config),
            "workload": workload,
            "process": self.process,
            "n_processes": self.n_processes,
            "wall_start_unix_s": round(self.tracer.wall_start, 6),
        }

    def stop_live(self) -> None:
        """Quiesce the live telemetry plane: stop the HTTP server (no
        scrape may observe a half-finished export) and the time-series
        recorder (which takes its final sample).  Idempotent; called by
        ``finish`` AND the flight recorder."""
        if self.server is not None:
            self.server.stop()
        if self.series is not None:
            self.series.stop()
        if self.alerts is not None:
            # after the recorder's final sample, so a condition that
            # cleared at the very end still resolves in the timeline
            self.alerts.stop()

    def finish_xprof(self) -> dict | None:
        """Close the job's XLA observatory window: stop the sampler,
        release the compile-ledger hookup, and fold the per-job delta
        (compile counts, per-program MFU, bound classification) into the
        registry as flat ``compile/*`` / ``xprof/*`` gauges — the fields
        the run ledger and ``obs diff --gate`` compare.  Returns the
        structured report for the metrics document (None on a second
        call or when the observatory never opened)."""
        from map_oxidize_tpu.obs import compile as _compile
        from map_oxidize_tpu.obs import xprof

        if self.sampler is not None:
            self.sampler.stop()
            self.sampler = None
        local = _compile.LEDGER.deactivate(self)
        base, self.xprof_base = self.xprof_base, None
        if base is None:
            return None
        report = xprof.job_report(_compile.LEDGER.job_delta(base, local))
        for k, v in xprof.flatten_report(report).items():
            self.registry.set(k, v)
        return report

    def _merge_calibration(self, xprof_report: dict | None,
                           workload: str | None = None,
                           corpus_bytes: float = 0.0,
                           attrib_doc: dict | None = None) -> None:
        """Fold this run's comms table + xprof program rows — plus the
        per-workload wall-attribution curve row the planner's wall
        prediction reads (obs/calib.py ``workloads`` section) — into
        the persistent calibration store and merge it atomically into
        the store file.  A refusal (schema/identity mismatch on disk)
        records ``calib/merge_refused`` and moves on — the job's own
        result is never hostage to the store."""
        if self.calib is None:
            return
        from map_oxidize_tpu.obs import calib as _calib
        from map_oxidize_tpu.utils.logging import get_logger

        try:
            ident = _calib.run_identity(self.n_processes)
            touched = self.calib.accumulate_run(
                ident, self.registry.comms_table(), xprof_report,
                source="job")
            if workload and workload != "serve":
                touched += self.calib.accumulate_workload(
                    ident, workload, corpus_bytes, attrib_doc)
            if touched:
                self.calib.save_merged()
                self.registry.set("calib/rows_merged", touched)
                self.registry.set(
                    "calib/runs", self.calib.doc.get("runs", 0))
        except _calib.CalibMismatch as e:
            self.registry.set("calib/merge_refused", 1)
            get_logger(__name__).warning(
                "calibration store refused the merge: %s", e)
        except Exception as e:  # pragma: no cover - the store is
            # evidence, never a reason to fail a finished job
            get_logger(__name__).warning("calibration merge failed: %s", e)

    def finish(self, config, workload: str | None = None
               ) -> tuple[dict, list | None]:
        """End-of-job hook: final memory watermarks, the xprof export,
        flag-driven file exports (version/config-hash stamped), the
        optional ledger append, and the ``(summary, trace_events)`` pair
        the result carries.  ``trace_events`` is None when tracing was
        off."""
        self.stop_live()
        xprof_report = self.finish_xprof()
        # the end-of-job wall attribution: buckets + unattributed
        # remainder as attrib/* gauges (ledger/gate/BENCH_DETAIL) and
        # the structured section the metrics document carries
        import time as _time

        from map_oxidize_tpu.obs import attrib as _attrib

        attrib_doc = _attrib.finalize(
            self, xprof_report,
            max(_time.time() - self.tracer.wall_start, 1e-9))
        # the causal layer's single-process form: the critical path
        # degenerates to the attribution timeline, but the SAME headline
        # gauges (critpath/bound_frac, path coverage, bound_by) land in
        # the summary -> ledger entry, so trend/gate watch one axis
        # across single- and multi-process runs.  The resident server's
        # own bundle idles between jobs — no job wall to decompose.
        critpath_doc = None
        if workload != "serve":
            from map_oxidize_tpu.obs import critpath as _critpath

            try:
                critpath_doc = _critpath.degenerate_from_attrib(
                    attrib_doc, process=self.process)
                _critpath.publish(self.registry, critpath_doc)
            except ValueError:
                pass
        # score the plan against the measured attribution (predicted
        # vs actual wall per bucket; plan/model_error_pct when the plan
        # actually predicted) BEFORE the summary below, so the ledger
        # entry and the gate carry the error gauge
        if self.plan is not None:
            from map_oxidize_tpu.obs import plan as _plan

            try:
                _plan.finalize(self, self.plan, attrib_doc)
            except Exception:  # pragma: no cover - scoring is evidence,
                pass           # never a reason to fail a finished job
        corpus_bytes = 0.0
        try:
            corpus_bytes = float(os.path.getsize(config.input_path))
        except (OSError, TypeError, AttributeError):
            pass
        self._merge_calibration(xprof_report, workload=workload,
                                corpus_bytes=corpus_bytes,
                                attrib_doc=attrib_doc)
        # the data-plane audit lands before the summary below, so the
        # ledger entry (and obs diff --gate) carries the data/* gauges
        data_doc = self.finish_dataplane()
        sample_host_memory(self.registry)
        sample_device_memory(self.registry)
        if self.heartbeat is not None:
            self.heartbeat.final_beat()
        meta = self.stamp(config, workload)
        if config.metrics_out:
            doc = dict(self.registry.to_dict(), meta=meta)
            doc["attrib"] = attrib_doc
            if self.plan is not None:
                doc["plan"] = self.plan
            if critpath_doc is not None:
                doc["critpath"] = critpath_doc
            if data_doc is not None:
                doc["data"] = data_doc
            if xprof_report is not None:
                doc["xprof"] = xprof_report
            if self.series is not None:
                doc["series"] = self.series.export()
            if self.alerts is not None:
                doc["alerts"] = self.alerts.export()
            write_json_atomic(config.metrics_out, doc)
        trace = self.tracer.chrome_trace() if self.tracer.enabled else None
        if trace is not None:
            trace.insert(0, {"name": "moxt_meta", "ph": "M",
                             "pid": self.tracer._pid, "tid": 0,
                             "args": meta})
            if config.trace_out != "-":
                # dump the list just built — rebuilding it via
                # write_chrome would pay the tid-compaction pass twice
                write_json_atomic(config.trace_out, trace, indent=None)
        summary = self.registry.summary()
        if getattr(config, "ledger_dir", None):
            from map_oxidize_tpu.obs import ledger

            extra: dict = {}
            if self.plan is not None:
                # the full plan doc rides the entry (knobs + provenance
                # + predicted wall per bucket) — `obs plan` renders it
                # straight from ledger history, and the flat plan/*
                # gauges are already in the summary the gate compares
                extra["plan"] = self.plan
            comms = self.registry.comms_table()
            if comms:
                extra["comms"] = comms
            if data_doc is not None:
                from map_oxidize_tpu.obs.dataplane import ledger_section

                extra["data"] = ledger_section(data_doc)
            if self.alerts is not None and (self.alerts.fired_total
                                            or self.alerts.resolved_total):
                # the alert timeline rides the entry (the flat
                # alerts/fired counter is already in the summary the
                # gate compares)
                extra["alerts"] = self.alerts.timeline_doc()
            ledger.append(config.ledger_dir, ledger.build_entry(
                config, workload or "?", summary,
                n_processes=self.n_processes,
                extra=extra or None))
        return summary, trace

    @contextlib.contextmanager
    def recording(self, config, workload: str | None = None):
        """Crash-safe envelope for a job body: on ANY exception the
        flight recorder closes open spans, flushes the partial metrics/
        trace to their configured paths, and dumps a post-mortem bundle
        under ``config.crash_dir`` — then the exception propagates
        unchanged.  Zero cost on the success path.

        Also binds this bundle as the context's current job
        (:mod:`map_oxidize_tpu.obs.context`), so per-dispatch
        observations from concurrent jobs in one process route to their
        own registries."""
        from map_oxidize_tpu.obs.context import use_obs

        self.workload = workload
        if (self.plan is None and workload and workload != "serve"
                and getattr(config, "plan", "auto") != "off"):
            # the job plan: solve the knobs + predict the wall BEFORE
            # the body runs, from the calibration store's curves; the
            # plan/* gauges land now so /status and the time series
            # carry the plan while the job runs (obs/plan.py scores it
            # at finish).  Planning is evidence — never a reason to
            # fail the job it describes.
            from map_oxidize_tpu.obs import plan as _plan
            from map_oxidize_tpu.runtime import planner as _planner

            try:
                self.plan = _planner.build_plan(
                    config, workload, calib_prior=self.calib_prior,
                    n_processes=self.n_processes)
                _plan.publish(self.registry, self.plan)
            except Exception as e:
                from map_oxidize_tpu.utils.logging import get_logger

                get_logger(__name__).warning("job planning failed: %s", e)
        try:
            with use_obs(self):
                yield self
        except BaseException as exc:
            from map_oxidize_tpu.obs import flight

            flight.record_failure(self, config, exc, workload=workload)
            raise


def write_json_atomic(path: str, payload, indent: int | None = 1) -> None:
    """Write ``payload`` as JSON via temp-file + rename (same atomicity
    contract as every other artifact writer in the repo).  ``indent=None``
    for bulk documents (trace event lists) where compactness wins."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=indent, default=_json_default)
    os.replace(tmp, path)


def _json_default(o):
    """Numpy scalars leak into counters from engine code; make them JSON."""
    item = getattr(o, "item", None)
    if item is not None:
        return item()
    raise TypeError(f"not JSON serializable: {type(o)!r}")
