"""Unified observability: spans, metrics, and a progress heartbeat.

The reference's entire observability surface is a ``println!`` of the
top-10 (``/root/reference/src/main.rs:188-191``); the seed's was a flat
61-line phase-timer dict.  This package is the instrumentation discipline
the ROADMAP's scale targets require (the same discipline Exoshuffle,
arXiv:2203.05072, credits for making shuffle regressions debuggable):

* :class:`~map_oxidize_tpu.obs.trace.Tracer` — nested, thread-safe spans
  with attributes (rows, bytes, device, spill generation), exportable as
  Chrome trace-event JSON (``chrome://tracing`` / Perfetto) or JSONL.
* :class:`~map_oxidize_tpu.obs.metrics.MetricsRegistry` — counters,
  gauges, and lightweight histograms (p50/p95/max) behind the seed
  ``Metrics`` surface (``phase``/``count``/``set``/``summary``), so every
  existing consumer keeps working.
* :class:`~map_oxidize_tpu.obs.heartbeat.Heartbeat` — opt-in periodic
  progress lines (rows/sec, percent done, ETA, phase) for long streamed
  jobs.

:class:`Obs` bundles the three per job and owns the config wiring
(``--metrics-out`` / ``--trace-out`` / ``--progress``).  One ``Obs`` is
created per job run and *injected* into engines and checkpoint stores —
replacing the ad-hoc per-driver ``Metrics()`` instantiations — so every
layer (driver, engine, collect, shuffle, spill, checkpoint) records into
one coherent event model.

See ``docs/OBSERVABILITY.md`` for the event model and flag reference.
"""

from __future__ import annotations

import contextlib
import json
import os
from dataclasses import dataclass

from map_oxidize_tpu.obs.heartbeat import Heartbeat
from map_oxidize_tpu.obs.metrics import (
    Histogram,
    MetricsRegistry,
    sample_device_memory,
    sample_host_memory,
)
from map_oxidize_tpu.obs.trace import NULL_SPAN, Span, Tracer

__all__ = [
    "Heartbeat",
    "Histogram",
    "MetricsRegistry",
    "NULL_SPAN",
    "Obs",
    "Span",
    "Tracer",
    "sample_device_memory",
    "sample_host_memory",
]


@dataclass
class Obs:
    """Per-job observability bundle: one registry, one tracer, and an
    optional heartbeat, threaded through driver -> engine -> spill layers.

    Always constructed (metrics were always-on in the seed too); the
    tracer is enabled only when the job asked for a trace, and its
    disabled spans are a shared no-op object, so the hot-path cost of an
    un-traced run is one attribute check per span site.
    """

    registry: MetricsRegistry
    tracer: Tracer
    heartbeat: Heartbeat | None = None

    @classmethod
    def from_config(cls, config) -> "Obs":
        """Build the bundle a job's config asks for.  ``trace_out='-'``
        collects the trace for ``result.trace`` without writing a file."""
        tracer = Tracer(enabled=bool(config.trace_out))
        hb = None
        if getattr(config, "progress", False):
            total = None
            try:
                total = os.path.getsize(config.input_path)
            except OSError:
                pass
            hb = Heartbeat(total_bytes=total,
                           interval_s=config.progress_interval_s)
        return cls(registry=MetricsRegistry(), tracer=tracer, heartbeat=hb)

    @contextlib.contextmanager
    def phase(self, name: str, **attrs):
        """One job phase: wall-clocked in the registry, a top-level span in
        the trace, the heartbeat's current phase label, and a host-RSS
        watermark sample on exit (phase boundaries are where residency
        peaks: finalize fetches, sort buffers, write staging)."""
        if self.heartbeat is not None:
            self.heartbeat.set_phase(name)
        with self.tracer.span(f"phase/{name}", **attrs):
            with self.registry.phase(name):
                try:
                    yield
                finally:
                    sample_host_memory(self.registry)

    def feed_span(self, **attrs) -> "Span":
        """Span for one mapped block's engine feed (the per-block latency
        site every driver instruments)."""
        return self.tracer.span("engine/feed_block", **attrs)

    def finish(self, config) -> tuple[dict, list | None]:
        """End-of-job hook: final memory watermarks, flag-driven file
        exports, and the ``(summary, trace_events)`` pair the result
        carries.  ``trace_events`` is None when tracing was off."""
        sample_host_memory(self.registry)
        sample_device_memory(self.registry)
        if self.heartbeat is not None:
            self.heartbeat.final_beat()
        if config.metrics_out:
            write_json_atomic(config.metrics_out, self.registry.to_dict())
        trace = self.tracer.chrome_trace() if self.tracer.enabled else None
        if trace is not None and config.trace_out != "-":
            # dump the list just built — rebuilding it via write_chrome
            # would pay the tid-compaction/scalarize pass twice
            write_json_atomic(config.trace_out, trace, indent=None)
        return self.registry.summary(), trace


def write_json_atomic(path: str, payload, indent: int | None = 1) -> None:
    """Write ``payload`` as JSON via temp-file + rename (same atomicity
    contract as every other artifact writer in the repo).  ``indent=None``
    for bulk documents (trace event lists) where compactness wins."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=indent, default=_json_default)
    os.replace(tmp, path)


def _json_default(o):
    """Numpy scalars leak into counters from engine code; make them JSON."""
    item = getattr(o, "item", None)
    if item is not None:
        return item()
    raise TypeError(f"not JSON serializable: {type(o)!r}")
